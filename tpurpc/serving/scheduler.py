"""tpurpc-cadence: the continuous-batching decode scheduler.

The FanInBatcher batches ONCE: gather, dispatch, split, done — the right
shape for one-shot inference, and exactly wrong for autoregressive
generation, where a "request" is hundreds of device steps and a
flush-once batcher would hold every new request hostage until the whole
batch drains (the convoy the serving-loop studies in PAPERS.md measure:
small-payload overheads, not bandwidth, dominate the decode regime).

:class:`DecodeScheduler` generalizes flush-once to **iterative
re-batching**. One loop thread owns the running batch and walks a strict
two-phase cycle:

``boundary`` (membership changes happen HERE and only here)
    retire finished sequences, drop sequences whose client left, preempt
    batch-class sequences when interactive work is waiting and the batch
    is full, then admit waiting prefills under a per-step token budget —
    a new request JOINs the running batch without the batch draining,
    and its first token (the prefill's sample) streams immediately.
``step``
    one batched ``model.step`` over every running sequence: row ``i`` of
    the stacked state/token arrays is sequence ``i``. Each emitted token
    is pushed to its sequence's stream queue; the RPC handler threads
    parked there forward them over the streaming response, where PR 3's
    cross-stream coalescing folds many streams' tokens into one writev.

Locking: the running batch is **loop-private** — only the loop thread
touches it, so the decode hot path takes no lock at all. The one shared
edge is the waiting queue (``submit`` appends, the boundary pops), guarded
by ``_lock``; the loop only ever takes it with bounded waits (the `block`
lint rule enforces this: a timeout-less acquire in the step loop would
stall every running stream behind one wedged submit).

Overload: shedding is class-aware and trips BEFORE collapse. Batch-class
work sheds at half the queue bar or as soon as the step-time EWMA exceeds
its SLO; interactive work sheds only at the full bar. Rejections carry a
pushback hint (the PR 6 admission contract), and every shed leaves a
flight event + counter so /healthz can say "shedding" while it is true.

Failure isolation: a batched ``prefill``/``step`` that raises is retried
row-by-row, so a poisoned sequence fails ALONE — the PR 3 batch / PR 7
merge-boundary poison discipline, lifted to the decode loop.

tpurpc-keystone (ISSUE 11): constructed with ``kv=KvBlockManager`` and a
model implementing the explicit-KV contract (``prefill_paged`` /
``step_paged``, :mod:`tpurpc.jaxshim.generate`), the scheduler runs
PAGED: sequence state lives in per-sequence block tables, prefill
consults the prefix cache (a hit skips the shared span), preemption
SWAPS the victim's blocks to host (``kv.swap_out`` — the arena is
actually freed, unlike PR 10's keep-in-HBM parking) and the sequence
parks in ``_swapped`` until a boundary has room to swap it back.
``load_depth()`` — waiting + swapped — is the fleet load signal:
``queue_depth`` alone made a server holding swapped work look idle to
least_loaded picking (the ISSUE 11 satellite fix). :meth:`detach` and
:meth:`submit_adopted` are the migration plane's two halves: remove a
live sequence with its KV intact / graft a shipped one in.

tpurpc-odyssey (ISSUE 15): every sequence carries its originating RPC's
trace context and an accounting identity (``trace=``/``account=`` on
submit/submit_adopted — the transport face reads the ambient context and
the ``tpurpc-account`` metadata key), and the loop feeds the
:mod:`tpurpc.obs.odyssey` hooks at lifecycle edges: ledger at submit,
journey spans at join/preempt/swap/retire, per-token ITL at the stream
edge, per-step cost shares at step end. All of it behind the ONE
``_odyssey.ACTIVE`` gate (``TPURPC_ODYSSEY=0`` drops everything but the
always-on SEQ_* flight edges).
"""

from __future__ import annotations

import itertools
import queue as _qmod
import threading
import time
import weakref
from collections import deque
from typing import Callable, Dict, List, Optional

import numpy as np

from tpurpc.analysis.locks import make_condition, make_event, make_lock
from tpurpc.obs import flight as _flight
from tpurpc.obs import metrics as _metrics
from tpurpc.obs import odyssey as _odyssey
from tpurpc.obs import profiler as _profiler

__all__ = ["DecodeScheduler", "TokenStream", "ShedError", "DrainingError",
           "SLO_INTERACTIVE", "SLO_BATCH", "health_lines"]

#: tpurpc-lens: everything the loop thread does — stacking, the batched
#: model call, membership bookkeeping — is the `decode_step` stage
_LENS_STAGES = {
    "_step_loop": "decode_step",
    "_boundary": "decode_step",
    "_admit": "decode_step",
    "_run_step": "decode_step",
    "_prefill_batch": "decode_step",
}
_profiler.register_stages(__file__, _LENS_STAGES)

SLO_INTERACTIVE = "interactive"
SLO_BATCH = "batch"
_SLO_CODE = {SLO_INTERACTIVE: 0, SLO_BATCH: 1}

#: tpurpc-cadence observability: one counter bump / histogram record per
#: DEVICE STEP (amortized over the whole batch, the BATCH_FLUSH economy),
#: per-sequence records only at the joins/retires edges
_STEPS = _metrics.counter("decode_steps")
_TOKENS = _metrics.counter("gen_tokens")
_STEP_US = _metrics.histogram("decode_step_us", kind="latency")
_STEP_BATCH = _metrics.histogram("decode_batch")
_TTFT_US = _metrics.histogram("gen_ttft_us", kind="latency")
_SHED = _metrics.labeled_counter("gen_shed", ("slo",))
_PREEMPTS = _metrics.counter("gen_preempted")
_SEQ_FAILED = _metrics.counter("gen_seq_failed")
#: scrape-time truth for the watchdog + /healthz: live batch occupancy
#: and queue depth, weakref'd like every fleet gauge
_RUNNING_G = _metrics.fleet("decode_running", lambda s: s.running_depth())
_WAITING_G = _metrics.fleet("decode_waiting", lambda s: s.queue_depth())

#: live schedulers for /healthz's "shed/queue states visible" line
_LIVE: "weakref.WeakSet[DecodeScheduler]" = weakref.WeakSet()


class ShedError(RuntimeError):
    """Request shed at submit: the scheduler is protecting its SLOs.
    ``pushback_ms`` is the retry floor the transport layer forwards
    (the PR 6 ``tpurpc-pushback-ms`` contract)."""

    def __init__(self, reason: str, pushback_ms: int, slo: str):
        super().__init__(reason)
        self.pushback_ms = int(pushback_ms)
        self.slo = slo


class DrainingError(RuntimeError):
    """Request refused because the scheduler (or its server) is draining:
    in-flight sequences finish, new prefills do not start."""


_DONE = object()


class _Seq:
    """One generation request inside the scheduler. ``q`` is the only
    egress: the loop thread puts tokens / _DONE / an Exception; the
    handler thread gets. ``cancelled`` is the leave flag — set by any
    thread, honored by the loop at the NEXT step boundary. ``kv`` is the
    paged-mode block table (None until prefill allocates it, or grafted
    whole by :meth:`DecodeScheduler.submit_adopted`)."""

    __slots__ = ("sid", "prompt", "prompt_len", "max_tokens", "slo",
                 "slo_code", "state", "last_token", "emitted", "q",
                 "cancelled", "t_submit_ns", "t_first_ns", "preempted",
                 "kv", "adopted", "trace", "account", "led")

    def __init__(self, sid: int, prompt: np.ndarray, max_tokens: int,
                 slo: str):
        self.sid = sid
        self.prompt = prompt
        self.prompt_len = int(prompt.shape[0])
        self.max_tokens = max_tokens
        self.slo = slo
        self.slo_code = _SLO_CODE[slo]
        self.state = None           # set by prefill; survives preemption
        self.last_token = 0
        self.emitted = 0
        self.q: "_qmod.Queue" = _qmod.Queue()
        self.cancelled = False
        self.t_submit_ns = time.monotonic_ns()
        self.t_first_ns = 0
        self.preempted = False
        self.kv = None              # paged mode: the sequence's block table
        self.adopted = False        # arrived via handoff/migration
        self.trace = None           # odyssey: the originating RPC's context
        self.account = _odyssey.DEFAULT_ACCOUNT
        self.led = None             # odyssey: the sequence's cost ledger

    def resumable(self) -> bool:
        """Prefilled already — admission is free (no prefill cost)."""
        return self.state is not None or self.kv is not None


class TokenStream:
    """Caller-facing handle for one sequence: iterate tokens, or drive it
    manually with :meth:`next` (bounded waits — the RPC handler's shape,
    interleaving client-liveness checks). :meth:`cancel` is the LEAVE
    signal: the sequence is retired at the next step boundary without
    stalling its batch siblings."""

    #: safety net for blocking iteration in tests: a stream nobody feeds
    #: for this long raises instead of hanging the suite
    MAX_IDLE_S = 60.0

    def __init__(self, seq: _Seq, sched: "DecodeScheduler"):
        self._seq = seq
        self._sched = sched

    @property
    def sid(self) -> int:
        return self._seq.sid

    @property
    def emitted(self) -> int:
        return self._seq.emitted

    def next(self, timeout: Optional[float] = None):
        """The next token (int), ``None`` on timeout, or raise
        ``StopIteration`` when the sequence is done / the sequence's own
        error when it failed."""
        try:
            item = self._seq.q.get(timeout=timeout)
        except _qmod.Empty:
            return None
        if item is _DONE:
            raise StopIteration
        if isinstance(item, BaseException):
            raise item
        return item

    def cancel(self) -> None:
        """Leave: flag the sequence; the loop retires it at the next step
        boundary (and a waiting sequence is dropped at admission time).
        Idempotent, callable from any thread."""
        seq = self._seq
        if not seq.cancelled:
            seq.cancelled = True
            self._sched._wake()

    def __iter__(self):
        return self

    def __next__(self):
        tok = self.next(timeout=self.MAX_IDLE_S)
        if tok is None:
            raise TimeoutError(
                f"sequence {self._seq.sid}: no token in "
                f"{self.MAX_IDLE_S}s")
        return tok


class DecodeScheduler:
    """Continuous-batching scheduler around a step model (see the
    module docstring for the state machine and
    :mod:`tpurpc.jaxshim.generate` for the model contract).

    Knobs:

    * ``max_batch`` — running-batch bound (rows per device step).
    * ``prefill_budget`` — prompt tokens admitted per step boundary: new
      joins cost their prompt length, resumed (preempted) sequences cost
      nothing. At least one prefill is always admitted into a non-full
      batch, so a prompt longer than the whole budget still runs.
    * ``max_waiting`` — the interactive shed bar; batch-class work sheds
      at ``batch_shed_depth`` (default half) and additionally as soon as
      the step-time EWMA exceeds ``step_slo_ms`` — the trip-BEFORE-
      collapse signal: rising step time at partial queue depth.
    * ``draining_fn`` — usually ``lambda: server.draining``: when true,
      submit refuses new work (:class:`DrainingError`) while in-flight
      sequences finish.
    """

    #: lock map (lint rule `lock`): the waiting queue, lifecycle flags and
    #: the detach-request registry are the ONLY cross-thread state; the
    #: running batch and the swapped list are loop-private
    _GUARDED_BY = {"_waiting": "_lock", "_closed": "_lock",
                   "_draining": "_lock", "_detach_req": "_lock"}

    def __init__(self, model, *, max_batch: int = 8,
                 prefill_budget: int = 128, max_waiting: int = 32,
                 batch_shed_depth: Optional[int] = None,
                 step_slo_ms: Optional[float] = None,
                 base_pushback_ms: int = 25, max_pushback_ms: int = 1000,
                 idle_wait_s: float = 0.05,
                 draining_fn: Optional[Callable[[], bool]] = None,
                 kv=None, name: str = "gen"):
        self.model = model
        self.kv = kv
        self._paged = kv is not None
        if self._paged and not hasattr(model, "prefill_paged"):
            raise ValueError(
                "kv= given but the model implements no explicit-KV "
                "contract (prefill_paged/step_paged; see "
                "tpurpc.jaxshim.generate)")
        self.max_batch = max(1, int(max_batch))
        self.prefill_budget = max(1, int(prefill_budget))
        self.max_waiting = max(1, int(max_waiting))
        self.batch_shed_depth = (int(batch_shed_depth)
                                 if batch_shed_depth is not None
                                 else max(1, self.max_waiting // 2))
        self.step_slo_ms = step_slo_ms
        self.base_pushback_ms = int(base_pushback_ms)
        self.max_pushback_ms = int(max_pushback_ms)
        self.idle_wait_s = idle_wait_s
        self._draining_fn = draining_fn
        self.name = name
        # factory-made (ISSUE 12): TPURPC_DEBUG_LOCKS now covers the
        # decode loop's one shared edge, and the schedule explorer hooks
        # the same seam to make boundary-vs-submit races explorable
        self._lock = make_lock("DecodeScheduler._lock")
        self._kick = make_condition("DecodeScheduler._kick", self._lock)
        self._waiting: "deque[_Seq]" = deque()
        self._closed = False
        self._draining = False
        self._running: List[_Seq] = []   # loop-private (no lock by design)
        #: paged mode: preempted sequences whose KV is swapped to host —
        #: loop-private like _running (only the boundary parks/resumes)
        self._swapped: List[_Seq] = []
        #: sid -> (event, box): migration threads asking the boundary to
        #: hand a live sequence over with its KV intact
        self._detach_req: Dict[int, tuple] = {}
        self._sids = itertools.count(1)
        #: odyssey: arena bytes per block (0 in opaque mode) — the ledger's
        #: KV byte-second integrand
        self._kv_block_bytes = int(getattr(kv, "block_bytes", 0) or 0) \
            if kv is not None else 0
        self._tag = _flight.tag_for(f"decode:{name}")
        self._step_roll: "deque[float]" = deque(maxlen=64)  # step ms
        self._step_ewma_ms = 0.0
        self.steps = 0
        self.tokens_out = 0
        self.shed_total = 0
        self.preempted_total = 0
        self.last_shed_ns = 0
        _RUNNING_G.track(self)
        _WAITING_G.track(self)
        _LIVE.add(self)
        self._thread = threading.Thread(target=self._step_loop, daemon=True,
                                        name=f"tpurpc-decode-{name}")
        self._thread.start()

    # -- submit side ----------------------------------------------------------

    def submit(self, prompt, *, max_tokens: int = 32,
               slo: str = SLO_INTERACTIVE, trace=None,
               account: Optional[str] = None) -> TokenStream:
        """Queue one generation request; returns its :class:`TokenStream`.

        Raises :class:`ShedError` (overload; carries the pushback hint),
        :class:`DrainingError` (server leaving), or ``RuntimeError``
        (closed). The returned stream's first token arrives after the
        next step boundary admits the prefill — joining never waits for
        the running batch to drain.

        ``trace``/``account`` (tpurpc-odyssey): the originating RPC's
        :class:`~tpurpc.obs.tracing.TraceContext` and accounting identity
        — the transport face passes the ambient context and the
        ``tpurpc-account`` metadata key; in-process callers may pass
        their own."""
        if slo not in _SLO_CODE:
            raise ValueError(f"unknown slo class {slo!r} "
                             f"(want {sorted(_SLO_CODE)})")
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        seq = _Seq(next(self._sids), prompt, max(1, int(max_tokens)), slo)
        seq.trace = trace
        seq.account = _odyssey.sanitize_account(account)
        if _odyssey.ACTIVE:
            seq.led = _odyssey.seq_submit(
                self.name, seq.sid, seq.account, slo, trace,
                seq.prompt_len, self._kv_block_bytes)
        try:
            with self._lock:
                if self._closed:
                    raise RuntimeError("scheduler closed")
                if self._draining or (self._draining_fn is not None
                                      and self._draining_fn()):
                    raise DrainingError(
                        "scheduler draining: in-flight sequences finish, "
                        "new prefills are refused")
                reason, pushback = self._shed_decision_locked(slo)
                if reason is not None:
                    self.shed_total += 1
                    self.last_shed_ns = time.monotonic_ns()
                    slo_code = seq.slo_code
                    _flight.emit(_flight.GEN_SHED, self._tag, slo_code,
                                 pushback)
                    _SHED.labels(slo).inc()
                    raise ShedError(reason, pushback, slo)
                sid = seq.sid
                plen = seq.prompt_len
                _flight.emit(_flight.SEQ_SUBMIT, self._tag, sid, plen)
                self._waiting.append(seq)
                self._kick.notify_all()
        except ShedError:
            _odyssey.seq_done(seq.led, "shed")
            raise
        except BaseException:
            _odyssey.seq_done(seq.led, "refused")
            raise
        return TokenStream(seq, self)

    def submit_adopted(self, kv_handle, prompt, *, last_token: int,
                       emitted: int, max_tokens: int,
                       slo: str = SLO_INTERACTIVE, trace=None,
                       account: Optional[str] = None,
                       shipped_bytes: int = 0) -> TokenStream:
        """Graft a sequence whose KV was computed ELSEWHERE — a
        disaggregated prefill handoff or an inbound migration. The block
        table arrives whole (entries present through the last generated
        token); the sequence joins as a free resume at the next boundary
        and its next token continues the stream exactly where the sender
        left it. The caller owns nothing afterwards: retire/leave/failure
        release the table like any local sequence's.

        ``trace``/``account``/``shipped_bytes`` (tpurpc-odyssey): the
        sender's journey context, accounting identity, and the handoff's
        rendezvous bytes — the journey and the ledger continue across the
        process split under the same trace_id / account key."""
        if not self._paged:
            raise RuntimeError("submit_adopted needs a paged scheduler "
                               "(kv=)")
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        seq = _Seq(next(self._sids), prompt, max(1, int(max_tokens)), slo)
        seq.kv = kv_handle
        seq.adopted = True
        seq.last_token = int(last_token)
        seq.emitted = int(emitted)
        seq.trace = trace
        seq.account = _odyssey.sanitize_account(account)
        if _odyssey.ACTIVE:
            seq.led = _odyssey.seq_submit(
                self.name, seq.sid, seq.account, slo, trace,
                seq.prompt_len, self._kv_block_bytes,
                shipped_bytes=int(shipped_bytes), adopted=True)
        try:
            with self._lock:
                if self._closed:
                    raise RuntimeError("scheduler closed")
                # a draining server must not accept NEW residency;
                # migration initiators pick a non-draining peer
                if self._draining or (self._draining_fn is not None
                                      and self._draining_fn()):
                    raise DrainingError(
                        "scheduler draining: adoption refused")
                reason, pushback = self._shed_decision_locked(slo)
                if reason is not None:
                    self.shed_total += 1
                    self.last_shed_ns = time.monotonic_ns()
                    slo_code = seq.slo_code
                    _flight.emit(_flight.GEN_SHED, self._tag, slo_code,
                                 pushback)
                    _SHED.labels(slo).inc()
                    raise ShedError(reason, pushback, slo)
                sid = seq.sid
                plen = seq.prompt_len
                _flight.emit(_flight.SEQ_SUBMIT, self._tag, sid, plen)
                self._waiting.append(seq)
                self._kick.notify_all()
        except ShedError:
            _odyssey.seq_done(seq.led, "shed")
            raise
        except BaseException:
            _odyssey.seq_done(seq.led, "refused")
            raise
        return TokenStream(seq, self)

    def detach(self, sid: int, timeout: float = 5.0):
        """Remove a live sequence (running, waiting-resumable, or
        swapped) from the scheduler WITH its KV intact — the migration
        sender's half. Blocks until the next step boundary hands it over
        (or ``timeout``). Returns the internal sequence object (``kv``,
        ``prompt``, ``emitted``, ``last_token``, ``q`` all live) or None
        when the sid is gone/unknown. The caller now owns the KV table:
        it must ship-and-free, re-adopt, or quarantine it."""
        ev = make_event("DecodeScheduler.detach")
        box: List[_Seq] = []
        with self._lock:
            if self._closed:
                return None
            self._detach_req[sid] = (ev, box)
            self._kick.notify_all()
        ev.wait(timeout)
        with self._lock:
            self._detach_req.pop(sid, None)
        return box[0] if box else None

    # -- load signals ---------------------------------------------------------

    def swapped_depth(self) -> int:
        return len(self._swapped)

    def load_depth(self) -> int:
        """The fleet load signal: waiting AND preempted/swapped work.
        ``queue_depth`` alone omitted preempted rows, so a server holding
        swapped sequences looked idle to least_loaded picking and drew
        MORE traffic exactly when it was oversubscribed (the ISSUE 11
        satellite fix); the server's load report wires this instead."""
        return len(self._waiting) + len(self._swapped)

    def live_sids(self) -> List[int]:
        """Sids currently running / swapped / waiting-resumable — the
        migration initiator's worklist (loop-private lists read
        GIL-atomically; a racing boundary only changes membership, which
        detach re-checks anyway)."""
        out = [s.sid for s in list(self._running)]
        out.extend(s.sid for s in list(self._swapped))
        out.extend(s.sid for s in list(self._waiting) if s.resumable())
        return out

    def _shed_decision_locked(self, slo: str):
        """(reason, pushback_ms) when this submit must shed, else
        (None, 0). Class-aware and deliberately early for batch work:
        the cheap class absorbs the first pressure so interactive TTFT
        holds — the graceful half of the degradation curve."""
        depth = len(self._waiting)
        if depth >= self.max_waiting:
            return ("queue full "
                    f"({depth}/{self.max_waiting} waiting)",
                    self._pushback(depth - self.max_waiting + 1))
        if slo == SLO_BATCH:
            if depth >= self.batch_shed_depth:
                return ("batch-class queue bar "
                        f"({depth}/{self.batch_shed_depth} waiting)",
                        self._pushback(depth - self.batch_shed_depth + 1))
            if (self.step_slo_ms is not None and depth > 0
                    and self._step_ewma_ms > self.step_slo_ms):
                return ("step time over SLO "
                        f"({self._step_ewma_ms:.1f}ms > "
                        f"{self.step_slo_ms}ms)",
                        self._pushback(2))
        return None, 0

    def _pushback(self, excess: int) -> int:
        return min(self.max_pushback_ms,
                   self.base_pushback_ms * max(1, excess))

    def _wake(self) -> None:
        with self._lock:
            self._kick.notify_all()

    # -- lifecycle ------------------------------------------------------------

    def drain(self) -> None:
        """Refuse new submits; in-flight sequences finish. (serve_
        generation wires the server's own draining flag instead, via
        ``draining_fn`` — this is the in-process face.)"""
        with self._lock:
            self._draining = True

    def close(self, timeout: float = 5.0) -> None:
        with self._lock:
            self._closed = True
            self._kick.notify_all()
        self._thread.join(timeout=timeout)
        # deregister from /healthz NOW: the loop-thread target is a
        # reference cycle back to self, so waiting for cyclic GC would
        # leave a dead scheduler's `gen` line on health bodies (and in
        # anything forked meanwhile)
        _LIVE.discard(self)

    # -- shared-state reads (GIL-atomic; gauges + admission signals) ----------

    def queue_depth(self) -> int:
        return len(self._waiting)

    def running_depth(self) -> int:
        return len(self._running)

    def step_time_ms(self) -> float:
        return self._step_ewma_ms

    def step_p99_ms(self) -> Optional[float]:
        """Rolling p99 of recent step times — serve_generation feeds this
        to the AdmissionGate as its latency signal (a decode server's
        pre-collapse signature is a rising step time, not RPC latency)."""
        roll = list(self._step_roll)
        if len(roll) < 8:
            return None
        roll.sort()
        return roll[max(0, int(len(roll) * 0.99) - 1)]

    def state_str(self) -> str:
        if self._draining or (self._draining_fn is not None
                              and self._draining_fn()):
            return "draining"
        if (self.last_shed_ns
                and time.monotonic_ns() - self.last_shed_ns < 5_000_000_000):
            return "shedding"
        return "ok"

    # -- the loop thread ------------------------------------------------------

    def _step_loop(self) -> None:
        while True:
            alive = self._boundary()
            if not alive:
                return
            if self._running:
                self._run_step()

    def _boundary(self) -> bool:
        """One step boundary: retire leaves, preempt, admit (with
        prefill). Returns False when closed (loop exits). Every wait in
        here is bounded — this function is on the step loop's no-block
        path (lint rule `block`)."""
        # leaves: clients that cancelled since the last step — retire
        # them without touching their siblings
        kept: List[_Seq] = []
        for s in self._running:
            if s.cancelled:
                sid = s.sid
                emitted = s.emitted
                _flight.emit(_flight.GEN_LEAVE, self._tag, sid, emitted)
                self._release_kv(s, cache=True)
                _odyssey.seq_done(s.led, "left")
                s.q.put(_DONE)
            else:
                kept.append(s)
        self._running = kept
        # swapped leaves: a preempted sequence whose client went away
        # releases its host image without ever swapping back in
        if self._swapped:
            self._swapped = [s for s in self._swapped
                             if not self._drop_if_cancelled(s)]
        preempt: List[_Seq] = []
        with self._lock:
            if self._closed:
                stranded = (list(self._running) + list(self._waiting)
                            + list(self._swapped))
                self._waiting.clear()
                self._running = []
                self._swapped = []
                for _sid, (ev, _box) in self._detach_req.items():
                    ev.set()
                self._detach_req.clear()
                err = RuntimeError("scheduler closed")
                for s in stranded:
                    self._release_kv(s, cache=False)
                    _odyssey.seq_done(s.led, "failed")
                    s.q.put(err)
                return False
            if self._detach_req:
                self._serve_detach_locked()
            draining = self._draining or (self._draining_fn is not None
                                          and self._draining_fn())
            # decide (pure), then APPLY the queue edit lexically under the
            # lock — the `lock` lint rule proves the guard holds
            admit, keep, drop, preempt = self._admit(draining)
            self._waiting.clear()
            self._waiting.extend(keep)
            if (not self._running and not admit and not drop
                    and not preempt):
                # idle: park (bounded — the block rule's contract) until a
                # submit kicks; the next loop pass re-runs the boundary
                self._kick.wait(timeout=self.idle_wait_s)
                return True
        # paged preemption happens OUTSIDE the lock: swap_out copies block
        # bytes to host, which must not stall a concurrent submit
        for s in preempt:
            t0 = time.monotonic_ns()
            self.kv.swap_out(s.kv)
            if s.led is not None:
                host = s.kv.host
                _odyssey.seq_swap(s.led, 0,
                                  len(host) if host is not None else 0,
                                  time.monotonic_ns() - t0)
            self._swapped.append(s)
        for s, outcome in drop:
            sid = s.sid
            emitted = s.emitted
            if isinstance(outcome, BaseException):
                _flight.emit(_flight.GEN_RETIRE, self._tag, sid, emitted)
                self._release_kv(s, cache=False)
                _odyssey.seq_done(
                    s.led, "refused" if isinstance(outcome, DrainingError)
                    else "failed")
                s.q.put(outcome)
            else:
                _flight.emit(_flight.GEN_LEAVE, self._tag, sid, emitted)
                self._release_kv(s, cache=True)
                _odyssey.seq_done(s.led, "left")
                s.q.put(_DONE)
        if admit:
            self._prefill_batch(admit)
        return True

    def _drop_if_cancelled(self, s: _Seq) -> bool:
        if not s.cancelled:
            return False
        sid = s.sid
        emitted = s.emitted
        _flight.emit(_flight.GEN_LEAVE, self._tag, sid, emitted)
        self._release_kv(s, cache=False)
        _odyssey.seq_done(s.led, "left")
        s.q.put(_DONE)
        return True

    def _serve_detach_locked(self) -> None:
        """Hand requested sequences to waiting migration threads (runs
        under ``_lock`` on the loop thread — the only mutator of the
        loop-private lists, so touching them here is safe)."""
        for sid in list(self._detach_req):
            ev, box = self._detach_req[sid]
            found = None
            for pool in (self._running, self._swapped):
                for s in pool:
                    if s.sid == sid:
                        found = s
                        pool.remove(s)
                        break
                if found is not None:
                    break
            if found is None:
                for s in list(self._waiting):
                    if s.sid == sid and s.resumable():
                        found = s
                        # contract: caller holds _lock (_locked suffix)
                        self._waiting.remove(s)  # tpr: allow(lock)
                        break
            if found is not None:
                kv = found.kv
                entries = kv.length if kv is not None else 0
                _flight.emit(_flight.SEQ_DETACH, self._tag, sid, entries)
                _odyssey.seq_detached(found.led, entries)
                box.append(found)
                ev.set()
                del self._detach_req[sid]  # tpr: allow(lock)

    def _release_kv(self, s: _Seq, cache: bool) -> None:
        """Return a sequence's block table to the arena (no-op in opaque
        mode or when the table moved elsewhere). ``cache=True`` donates
        the prompt-prefix span to the prefix cache (retire/leave after a
        clean prefill)."""
        kv = s.kv
        if kv is None:
            return
        s.kv = None
        try:
            self.kv.free_blocks(kv, cache_prefix=cache)
        except Exception:
            # releasing must never take the loop down; the arena's
            # accounting is best-effort at teardown edges
            pass

    def _admit(self, draining: bool):
        """Decide the boundary's joins (runs under ``_lock``; PURE with
        respect to the waiting queue — the caller applies the edit so the
        guard is lexically provable). Interactive first; preemption makes
        room for it; prefill rides the token budget; resumed sequences
        are free. Returns ``(admit, keep, drop, preempt)``: ``drop``
        pairs a sequence with ``None`` (client left) or an exception
        (refused); ``preempt`` (paged mode only) names victims the caller
        swaps out AFTER releasing the lock."""
        admit: List[_Seq] = []
        drop: List[tuple] = []
        preempt: List[_Seq] = []
        live: List[_Seq] = []
        for s in self._waiting:
            if s.cancelled:
                drop.append((s, None))
            else:
                live.append(s)
        if not live and not self._swapped:
            return admit, live, drop, preempt
        # preemption-at-step-boundary: interactive work waiting, batch
        # full, batch-class rows running -> the cheap class yields. Opaque
        # mode keeps the victim's state array in memory (PR 10); paged
        # mode SWAPS its blocks to host (the caller performs the copy
        # outside the lock) — the arena is actually freed for the
        # incoming prefill's table.
        want_i = sum(1 for s in live if s.slo == SLO_INTERACTIVE)
        if want_i and len(self._running) >= self.max_batch:
            for s in reversed(list(self._running)):
                if want_i <= 0:
                    break
                if s.slo == SLO_BATCH:
                    self._running.remove(s)
                    s.preempted = True
                    sid = s.sid
                    slo_code = s.slo_code
                    _flight.emit(_flight.GEN_PREEMPT, self._tag, sid,
                                 slo_code)
                    _odyssey.seq_preempt(s.led)
                    _PREEMPTS.inc()
                    self.preempted_total += 1
                    if self._paged:
                        preempt.append(s)
                    else:
                        live.insert(0, s)
                    want_i -= 1
        slots = self.max_batch - len(self._running)
        budget = self.prefill_budget
        prefills = 0
        keep: List[_Seq] = []
        # two passes, interactive first; within a class, FIFO
        for klass in (SLO_INTERACTIVE, SLO_BATCH):
            for s in live:
                if s.slo != klass:
                    continue
                if slots <= 0:
                    keep.append(s)
                    continue
                if s.resumable():              # resume: no prefill cost
                    admit.append(s)
                    slots -= 1
                    continue
                if draining:
                    # drain: no NEW prefills (resumes still land); refuse
                    # now rather than park callers behind a server that
                    # will never admit them
                    drop.append((s, DrainingError(
                        "scheduler draining: prefill refused")))
                    continue
                cost = s.prompt_len
                # the budget bounds prefill work per step; the first
                # prefill is exempt so a prompt longer than the whole
                # budget still runs (it just runs alone)
                if cost <= budget or prefills == 0:
                    admit.append(s)
                    slots -= 1
                    budget -= cost
                    prefills += 1
                else:
                    keep.append(s)
        # swapped sequences come back when room remains AFTER the queue
        # had its turn (they already ran once; fresh interactive work is
        # not made to wait behind a swap-in) — unless nothing else wants
        # the slot, in which case they must not starve
        while slots > 0 and self._swapped and not preempt:
            admit.append(self._swapped.pop(0))
            slots -= 1
        # keep lost the cross-class FIFO interleaving; restore arrival
        # order (sid order) so re-examination next boundary stays fair
        keep.sort(key=lambda s: s.sid)
        return admit, keep, drop, preempt

    def _prefill_batch(self, admit: List[_Seq]) -> None:
        """Join the admitted sequences: resumes re-enter directly (a
        swapped table swaps back in first; a full arena re-parks it),
        fresh prompts prefill as ONE batched model call (row-isolated on
        failure) and their first token streams immediately."""
        fresh = [s for s in admit if not s.resumable()]
        for s in admit:
            if not s.resumable():
                continue
            if s.kv is not None and s.kv.swapped:
                t0 = time.monotonic_ns()
                try:
                    self.kv.swap_in(s.kv)
                except Exception:
                    # arena full right now: stay parked, retry at a later
                    # boundary (load_depth keeps reporting the debt)
                    self._swapped.append(s)
                    continue
                if s.led is not None:
                    _odyssey.seq_swap(
                        s.led, 1,
                        len(s.kv.blocks) * self._kv_block_bytes,
                        time.monotonic_ns() - t0)
            sid = s.sid
            _flight.emit(_flight.GEN_JOIN, self._tag, sid, 0)
            _odyssey.seq_join(s.led, resumed=True)
            self._running.append(s)
        if not fresh:
            return
        if self._paged:
            self._prefill_paged(fresh)
            return
        t0_pf = time.monotonic_ns()
        try:
            states, tokens = self.model.prefill([s.prompt for s in fresh])
            results = [(states[i], int(tokens[i]))
                       for i in range(len(fresh))]
        except Exception:
            # batched prefill failed: row-by-row isolation (one bad
            # prompt must not fail its co-admitted siblings)
            results = []
            for s in fresh:
                try:
                    st, tok = self.model.prefill([s.prompt])
                    results.append((st[0], int(tok[0])))
                except Exception as exc:
                    results.append(exc)
        dt_pf = time.monotonic_ns() - t0_pf
        emitted = 0
        for s, res in zip(fresh, results):
            sid = s.sid
            plen = s.prompt_len
            if isinstance(res, Exception):
                _SEQ_FAILED.inc()
                _flight.emit(_flight.GEN_RETIRE, self._tag, sid, 0)
                _odyssey.seq_done(s.led, "failed")
                s.q.put(res)
                continue
            s.state, first = res
            _flight.emit(_flight.GEN_JOIN, self._tag, sid, plen)
            _odyssey.seq_join(s.led)
            _odyssey.seq_prefill(s.led, dt_pf, len(fresh))
            self._emit_token(s, first)
            emitted += 1
            if s.emitted < s.max_tokens and not self._hit_eos(first):
                self._running.append(s)
            else:
                self._retire(s)
        # prefill's sampled token counts like any other emitted token
        self.tokens_out += emitted
        _TOKENS.inc(emitted)

    def _prefill_paged(self, fresh: List[_Seq]) -> None:
        """The explicit-KV prefill: allocate each row's block table
        (prefix cache consulted — a hit means the model folds only the
        uncached tail), one batched ``prefill_paged``, row-isolated
        retry with truncate-undo on failure."""
        ready: List[_Seq] = []
        for s in fresh:
            try:
                # the sequence adopts the table in the same statement;
                # every later path releases via _release_kv
                s.kv, _hit = self.kv.alloc_for_prompt(  # tpr: allow(kv)
                    s.sid, s.prompt)
                ready.append(s)
            except Exception as exc:
                _SEQ_FAILED.inc()
                sid = s.sid
                _flight.emit(_flight.GEN_RETIRE, self._tag, sid, 0)
                _odyssey.seq_done(s.led, "failed")
                s.q.put(exc)
        if not ready:
            return
        lengths = [s.kv.length for s in ready]
        t0_pf = time.monotonic_ns()
        try:
            toks = self.model.prefill_paged([s.prompt for s in ready],
                                            [s.kv for s in ready])
            results = [int(toks[i]) for i in range(len(ready))]
        except Exception:
            # batched prefill failed: undo partial appends, then
            # row-by-row isolation (one bad prompt must not fail its
            # co-admitted siblings)
            results = []
            for s, n0 in zip(ready, lengths):
                s.kv.truncate(n0)
                try:
                    t = self.model.prefill_paged([s.prompt], [s.kv])
                    results.append(int(t[0]))
                except Exception as exc:
                    s.kv.truncate(n0)
                    results.append(exc)
        dt_pf = time.monotonic_ns() - t0_pf
        emitted = 0
        for s, res in zip(ready, results):
            sid = s.sid
            plen = s.prompt_len
            if isinstance(res, Exception):
                _SEQ_FAILED.inc()
                _flight.emit(_flight.GEN_RETIRE, self._tag, sid, 0)
                self._release_kv(s, cache=False)
                _odyssey.seq_done(s.led, "failed")
                s.q.put(res)
                continue
            _flight.emit(_flight.GEN_JOIN, self._tag, sid, plen)
            _odyssey.seq_join(s.led)
            _odyssey.seq_prefill(
                s.led, dt_pf, len(ready),
                kv_bytes=len(s.kv.blocks) * self._kv_block_bytes)
            self._emit_token(s, res)
            emitted += 1
            if s.emitted < s.max_tokens and not self._hit_eos(res):
                self._running.append(s)
            else:
                self._retire(s)
        self.tokens_out += emitted
        _TOKENS.inc(emitted)

    def _run_step(self) -> None:
        """One batched decode step over the running batch; delivery and
        retirement inline (loop-private state, no locks)."""
        running = self._running
        nb = len(running)
        waiting_n = len(self._waiting)
        _flight.emit(_flight.GEN_STEP_BEGIN, self._tag, nb, waiting_n)
        t0 = time.monotonic_ns()
        tokens = np.asarray([s.last_token for s in running],
                            dtype=np.int32)
        if self._paged:
            lengths = [s.kv.length for s in running]
            try:
                toks = self.model.step_paged([s.kv for s in running],
                                             tokens)
                results = [(None, int(toks[i])) for i in range(nb)]
            except Exception:
                # poisoned batch: undo partial appends, retry row-by-row
                # so the bad sequence fails ALONE
                results = []
                for s, n0 in zip(running, lengths):
                    s.kv.truncate(n0)
                    try:
                        t = self.model.step_paged(
                            [s.kv], np.asarray([s.last_token], np.int32))
                        results.append((None, int(t[0])))
                    except Exception as exc:
                        s.kv.truncate(n0)
                        results.append(exc)
        else:
            states = np.stack([s.state for s in running])
            try:
                new_states, new_tokens = self.model.step(states, tokens)
                results = [(new_states[i], int(new_tokens[i]))
                           for i in range(nb)]
            except Exception:
                # poisoned batch: retry row-by-row so the bad sequence
                # fails ALONE (PR 3/7 poison-isolation discipline)
                results = []
                for s in running:
                    try:
                        st, tok = self.model.step(
                            s.state[None],
                            np.asarray([s.last_token], dtype=np.int32))
                        results.append((st[0], int(tok[0])))
                    except Exception as exc:
                        results.append(exc)
        t_end = time.monotonic_ns()
        dt_ns = t_end - t0
        self._note_step_time(dt_ns)
        if _odyssey.ACTIVE:
            # cost attribution: each row owns 1/nb of this device step,
            # and its arena residency integrates against the same clock
            _odyssey.seq_step(running, dt_ns, t_end)
        emitted = 0
        kept: List[_Seq] = []
        for s, res in zip(running, results):
            if isinstance(res, Exception):
                _SEQ_FAILED.inc()
                sid = s.sid
                n = s.emitted
                _flight.emit(_flight.GEN_RETIRE, self._tag, sid, n)
                self._release_kv(s, cache=False)
                _odyssey.seq_done(s.led, "failed")
                s.q.put(res)
                continue
            st, tok = res
            if not self._paged:
                s.state = st
            self._emit_token(s, tok, t_end)
            emitted += 1
            if s.emitted >= s.max_tokens or self._hit_eos(tok):
                self._retire(s)
            else:
                kept.append(s)
        self._running = kept
        self.steps += 1
        self.tokens_out += emitted
        _STEPS.inc()
        _TOKENS.inc(emitted)
        _STEP_BATCH.record(nb)
        _STEP_US.record(dt_ns // 1000)
        _flight.emit(_flight.GEN_STEP_END, self._tag, nb, emitted)

    # -- loop helpers ---------------------------------------------------------

    def _note_step_time(self, dt_ns: int) -> None:
        ms = dt_ns / 1e6
        self._step_roll.append(ms)
        a = 0.2
        self._step_ewma_ms = ms if self._step_ewma_ms == 0.0 else (
            (1 - a) * self._step_ewma_ms + a * ms)

    def _emit_token(self, s: _Seq, tok: int, now_ns: int = 0) -> None:
        s.last_token = tok
        s.emitted += 1
        if s.t_first_ns == 0:
            s.t_first_ns = now_ns or time.monotonic_ns()
            ttft_us = (s.t_first_ns - s.t_submit_ns) // 1000
            _TTFT_US.record(ttft_us)
            sid = s.sid
            _flight.emit(_flight.SEQ_FIRST_TOKEN, self._tag, sid, ttft_us)
            _odyssey.seq_first_token(s.led, ttft_us, s.t_first_ns)
        else:
            # the stream edge: inter-token latency lands here, per token —
            # the one per-token odyssey site (a subtraction + one record;
            # the step's shared end stamp stands in for a clock read)
            _odyssey.seq_token(s.led, now_ns)
        s.q.put(tok)

    def _hit_eos(self, tok: int) -> bool:
        eos = getattr(self.model, "eos", None)
        return eos is not None and tok == eos

    def _retire(self, s: _Seq) -> None:
        sid = s.sid
        n = s.emitted
        _flight.emit(_flight.GEN_RETIRE, self._tag, sid, n)
        # natural finish: the prompt's block-aligned prefix is donated to
        # the prefix cache before the table frees — a repeated prompt
        # skips prefill for the shared span
        self._release_kv(s, cache=True)
        _odyssey.seq_done(s.led, "retire")
        s.q.put(_DONE)

def health_lines() -> List[str]:
    """One ``gen:`` line per live scheduler for /healthz — the shed/queue
    state an operator (or an LB) reads during overload without scraping
    the full metrics plane."""
    out = []
    for s in list(_LIVE):
        try:
            if s._closed:
                continue
            out.append(
                f"gen {s.name}: state={s.state_str()} "
                f"running={s.running_depth()} waiting={s.queue_depth()} "
                f"swapped={s.swapped_depth()} steps={s.steps} "
                f"shed={s.shed_total} preempted={s.preempted_total}")
        except Exception:
            continue
    return sorted(out)
