"""tpurpc-keystone: disaggregated prefill/decode serving over the KV plane.

The "large DL tensors shouldn't ride the framed RPC path" thesis (RPC
Considered Harmful, arXiv:1805.08430), applied to SERVING STATE: a
generation fleet's prefill compute and decode residency scale on different
axes, so this module splits them — PREFILL servers fold prompts into KV
entries and ship the blocks to DECODE servers, where the
:class:`~tpurpc.serving.scheduler.DecodeScheduler` steps them. The blocks
move over the rendezvous plane's block-granular grants
(:class:`~tpurpc.core.rendezvous.BlockGrant`): the framed RPC connection
carries only descriptor control frames, and every KV byte lands
ONE-SIDED in the decode server's arena — zero host landing copies,
ledger-provable (``tools/disagg_smoke.py`` asserts it).

The sequence-handoff protocol (all methods on the decode server, service
``tpurpc.Kv``; control payloads are small tensor trees):

    prefill/source                          decode/target
    --------------                          -------------
    OfferKv(seq_key, prompt, n_tokens) ──►  prefix-cache probe; allocate
                                            block table (shared span +
                                            fresh blocks); register the
                                            PENDING handoff
                       ◄──────────────────  grant(BlockGrant descriptor),
                                            resume_pos/resume_hash (a
                                            prefix HIT: the sender skips
                                            prefill for the shared span)
    one-sided write of each fresh
    block via GrantWriter (RDMA WRITE
    / single memoryview copy)
    CompleteKv(handoff, last_token, …) ──►  entries live; sequence PARKED
                       ◄──────────────────  ok
    … client re-attaches: ResumeSeq(seq_key) streams tokens from the
    scheduler (submit_adopted), continuing the index where prefill left.

The SAME protocol is live **migration**: :func:`migrate` detaches a
running sequence from the source scheduler (KV intact), ships it to a
peer decode server, and ends the source stream with a ``migrated``
re-attach record the client follows — PR 6's zero-failed-RPC drain
extended to stateful generation (``serve_decode(migrate_to=…)`` wires it
to ``Server.drain`` via the new drain hook).

Failure contract (chaos-tested): a peer that dies mid-handoff fails that
sequence ALONE with UNAVAILABLE — never a hang, never a sibling. On the
receiving side, a PENDING handoff whose sender vanished is reaped after
``pending_ttl_s`` and its blocks are QUARANTINED, never reused — a
straggling one-sided write must land in dead memory (the
``reuse_before_quarantine`` mutant in ``analysis/ringcheck.py
check_kv_handoff`` models exactly this rule). A PARKED sequence nobody
resumed is reaped too, but freed: its writer already completed.
"""

from __future__ import annotations

import itertools
import os
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from tpurpc.analysis.locks import make_lock
from tpurpc.core.rendezvous import BlockGrant, GrantWriter
from tpurpc.jaxshim import codec
from tpurpc.obs import flight as _flight
from tpurpc.obs import metrics as _metrics
from tpurpc.obs import odyssey as _odyssey
from tpurpc.obs import tracing as _tracing
from tpurpc.rpc.server import (PUSHBACK_KEY, Server,
                               unary_stream_rpc_method_handler,
                               unary_unary_rpc_method_handler)
from tpurpc.rpc.status import StatusCode
from tpurpc.serving.kv import ENTRY_BYTES, HostKv, KvBlockManager
from tpurpc.serving.scheduler import (SLO_INTERACTIVE, DecodeScheduler,
                                      DrainingError, ShedError, _SLO_CODE)

__all__ = [
    "KV_SERVICE", "DisaggDecode", "DisaggPrefill", "DisaggClient",
    "serve_decode", "serve_prefill", "migrate", "SeqMigrated",
    "MigrationFailed", "TEST_HOOKS",
]

KV_SERVICE = "tpurpc.Kv"

_SLO_BY_CODE = {v: k for k, v in _SLO_CODE.items()}

#: how often the resume bridge re-checks client liveness (api.py's bound)
_POLL_S = 0.05

#: chaos seams (tests/test_disagg.py, the death-mid-migration scenario):
#: `wedge_before_complete` (an Event) parks every shipper between its
#: one-sided block writes and the COMPLETE frame until the event fires —
#: the window where a peer death must quarantine, not reuse
TEST_HOOKS: Dict[str, object] = {}

_HANDOFFS = _metrics.counter("kv_handoffs")
_HANDOFF_BYTES = _metrics.counter("kv_handoff_bytes")
_MIGRATIONS = _metrics.counter("kv_migrations")
_MIG_FAILED = _metrics.counter("kv_migrations_failed")
_REAPED = _metrics.counter("kv_handoffs_reaped")


class SeqMigrated(Exception):
    """Internal stream signal: the sequence now lives at ``address`` under
    ``seq_key``; the client re-attaches with ResumeSeq and continues at
    ``next_index``. The resume bridge converts it into a final
    ``migrated`` record on the token stream (never an RPC error — a
    migrated stream is a SUCCESSFUL stream)."""

    def __init__(self, address: str, seq_key: int, next_index: int):
        super().__init__(f"migrated to {address}")
        self.address = address
        self.seq_key = int(seq_key)
        self.next_index = int(next_index)


class MigrationFailed(RuntimeError):
    """The peer died (or refused) mid-migration: the sequence fails ALONE
    with UNAVAILABLE — its KV was detached from the source scheduler and
    cannot silently resume."""


def _method(name: str) -> str:
    return f"/{KV_SERVICE}/{name}"


def _scalar(x) -> int:
    arr = np.asarray(x)
    return int(arr if arr.ndim == 0 else arr.ravel()[0])


def _b(s: str) -> np.ndarray:
    return np.frombuffer(s.encode(), dtype=np.uint8).copy()


def _s(arr) -> str:
    return bytes(np.asarray(arr, dtype=np.uint8)).decode()


class _Pending:
    """A handoff between CLAIM and COMPLETE: the sender may still write
    these blocks one-sided. Expiry => QUARANTINE (module docstring).
    ``trace``/``account`` (tpurpc-odyssey) are the sender's journey
    context and accounting identity, carried in the OfferKv request —
    the sequence's identity crosses the process split with its KV."""

    __slots__ = ("kv", "seq_key", "prompt", "deadline", "trace",
                 "account", "t0_ns")

    def __init__(self, kv, seq_key: int, prompt: np.ndarray,
                 deadline: float, trace=None, account: str = "anon"):
        self.kv = kv
        self.seq_key = seq_key
        self.prompt = prompt
        self.deadline = deadline
        self.trace = trace
        self.account = account
        self.t0_ns = time.monotonic_ns()


class _Parked:
    """A completed handoff awaiting its client's ResumeSeq. The writer is
    done, so expiry frees (prefix donated — the bytes are good)."""

    __slots__ = ("kv", "prompt", "last_token", "emitted", "deadline",
                 "trace", "account", "nbytes")

    def __init__(self, kv, prompt: np.ndarray, last_token: int,
                 emitted: int, deadline: float, trace=None,
                 account: str = "anon", nbytes: int = 0):
        self.kv = kv
        self.prompt = prompt
        self.last_token = last_token
        self.emitted = emitted
        self.deadline = deadline
        self.trace = trace
        self.account = account
        self.nbytes = nbytes


class _ClosedError(Exception):
    """Internal: a registry insert lost the race against ``close()``."""


# ---------------------------------------------------------------------------
# Decode side: the handoff receiver + resume/park registry.
# ---------------------------------------------------------------------------

class DisaggDecode:
    """The decode server's KV-plane state: pending handoffs, parked
    sequences, and the OfferKv/CompleteKv/ReleaseKv/ResumeSeq handlers.
    One per decode server; :func:`serve_decode` builds the whole stack."""

    _GUARDED_BY = {"_pending": "_lock", "_parked": "_lock"}

    def __init__(self, sched: DecodeScheduler, mgr: KvBlockManager,
                 address: str = "", pending_ttl_s: float = 30.0,
                 parked_ttl_s: float = 60.0):
        self.sched = sched
        self.mgr = mgr
        self.address = address
        self.pending_ttl_s = float(pending_ttl_s)
        self.parked_ttl_s = float(parked_ttl_s)
        self._lock = make_lock("DisaggDecode._lock")
        self._pending: Dict[int, _Pending] = {}
        self._parked: Dict[int, _Parked] = {}
        self._closed = False
        self._handoff_ids = itertools.count(1)
        self._tag = _flight.tag_for(f"disagg:{sched.name}")
        self.handoffs_in = 0
        self.prefix_hits = 0
        self.quarantined_handoffs = 0

    # -- lifecycle sweeps -----------------------------------------------------

    def reap(self, now: Optional[float] = None) -> Tuple[int, int]:
        """Expire overdue registry entries: pending => quarantine (the
        sender may still write), parked => free (the sender finished).
        Called inline on every control op and by tests; returns
        (quarantined, freed)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            dead_p = [h for h, p in self._pending.items()
                      if p.deadline <= now]
            pend = [self._pending.pop(h) for h in dead_p]
            dead_k = [k for k, p in self._parked.items()
                      if p.deadline <= now]
            parked = [self._parked.pop(k) for k in dead_k]
        nq = 0
        for p in pend:
            nq += self.mgr.quarantine(p.kv)
            self.quarantined_handoffs += 1
            _REAPED.inc()
        for p in parked:
            self.mgr.free_blocks(p.kv, cache_prefix=True)
            _REAPED.inc()
        return nq, len(parked)

    def close(self) -> None:
        """Server teardown: pending handoffs quarantine (stragglers),
        parked sequences free. The ``_closed`` flag closes the window an
        in-flight handler would otherwise slip through: ``on_complete``
        drops ``_lock`` between popping its pending entry and parking the
        result (``set_length`` must run unlocked), and an ``on_offer``
        mid-alloc holds no lock at all — either one landing its registry
        insert AFTER this clear would strand live blocks in a closed
        server's registries, neither freed nor quarantined (found by the
        simnet ``close-complete`` scenario, ISSUE 17)."""
        with self._lock:
            self._closed = True
            pend = list(self._pending.values())
            self._pending.clear()
            parked = list(self._parked.values())
            self._parked.clear()
        for p in pend:
            self.mgr.quarantine(p.kv)
        for p in parked:
            self.mgr.free_blocks(p.kv)

    # -- control handlers -----------------------------------------------------

    def on_offer(self, req, ctx):
        self.reap()
        seq_key = _scalar(req["seq_key"])
        prompt = np.asarray(req["prompt"], dtype=np.int32).reshape(-1)
        n_tokens = _scalar(req["n_tokens"])
        if self.sched.state_str() == "draining":
            ctx.abort(StatusCode.UNAVAILABLE,
                      "decode server draining: handoff refused")
        handoff = next(self._handoff_ids)
        try:
            kv, hit = self.mgr.alloc_for_prompt(
                seq_key, prompt, reserve_entries=n_tokens)
        except Exception as exc:
            return {"ok": np.int32(0), "reason": _b(f"arena: {exc}")}
        try:
            bt = self.mgr.block_tokens
            fresh = kv.blocks[hit // bt:]
            grant = BlockGrant(
                handoff, self.mgr.kind, self.mgr.region_handle,
                self.mgr.block_bytes,
                [self.mgr.block_offset(b) for b in fresh],
                self.mgr.window_bytes, self.mgr.nonce, self.mgr.nonce_off)
            resume_hash = resume_flags = 0
            if hit:
                resume_hash, _tok, resume_flags = kv.entry(hit - 1)
                self.prefix_hits += 1
            # tpurpc-odyssey: the sender's journey context + account ride
            # the offer — adopt() opens this process's tail buffer for
            # the trace, so decode-side spans join the same commit
            tr = req.get("trace")
            trace = _tracing.adopt(bytes(np.asarray(tr, np.uint8))) \
                if tr is not None else _tracing.current()
            account = _odyssey.sanitize_account(
                _s(req["account"]) if "account" in req else None)
            with self._lock:
                if self._closed:
                    raise _ClosedError()
                self._pending[handoff] = _Pending(
                    kv, seq_key, prompt,
                    time.monotonic() + self.pending_ttl_s,
                    trace=trace, account=account)
        except _ClosedError:
            # close() already swept the registries; registering now would
            # strand these blocks forever — free and refuse instead
            self.mgr.free_blocks(kv)
            ctx.abort(StatusCode.UNAVAILABLE,
                      "decode server closed: handoff refused")
        except BaseException:
            self.mgr.free_blocks(kv)
            raise
        nbytes = (n_tokens - hit) * ENTRY_BYTES
        _flight.emit(_flight.KV_SHIP_OFFER, self._tag, handoff, nbytes)
        return {
            "ok": np.int32(1),
            "handoff": np.int64(handoff),
            "grant": np.frombuffer(grant.to_wire(), np.uint8).copy(),
            "resume_pos": np.int32(hit),
            "resume_hash": np.uint64(resume_hash),
            "resume_flags": np.int32(resume_flags),
        }

    def on_complete(self, req, ctx):
        handoff = _scalar(req["handoff"])
        n_tokens = _scalar(req["n_tokens"])
        last_token = _scalar(req["last_token"])
        emitted = _scalar(req["emitted"])
        with self._lock:
            pend = self._pending.pop(handoff, None)
        if pend is None:
            ctx.abort(StatusCode.FAILED_PRECONDITION,
                      f"unknown/expired handoff {handoff} (blocks "
                      "quarantined; offer again)")
        try:
            pend.kv.set_length(n_tokens)
        except Exception as exc:
            self.mgr.quarantine(pend.kv)
            ctx.abort(StatusCode.INVALID_ARGUMENT, str(exc))
        nbytes = n_tokens * ENTRY_BYTES
        with self._lock:
            if self._closed:
                parked_ok = False
            else:
                parked_ok = True
                self._parked[pend.seq_key] = _Parked(
                    pend.kv, pend.prompt, last_token, emitted,
                    time.monotonic() + self.parked_ttl_s,
                    trace=pend.trace, account=pend.account, nbytes=nbytes)
        if not parked_ok:
            # close() ran between our pending-pop and this park: its sweep
            # never saw these blocks, so release them here (the writer is
            # done — COMPLETE means the bytes landed — so free, not
            # quarantine) and tell the sender the server is gone
            self.mgr.free_blocks(pend.kv, cache_prefix=True)
            ctx.abort(StatusCode.UNAVAILABLE,
                      "decode server closed: handoff not parked")
        self.handoffs_in += 1
        _HANDOFFS.inc()
        _HANDOFF_BYTES.inc(nbytes)
        _flight.emit(_flight.KV_SHIP_COMPLETE, self._tag, handoff, nbytes)
        # journey: the receive side of the ship, offer -> complete, under
        # the sequence's own trace (the sender records its write side)
        if pend.trace is not None:
            now = time.monotonic_ns()
            _tracing.record("seq-ship", pend.trace, pend.t0_ns,
                            now - pend.t0_ns, handoff=handoff,
                            nbytes=nbytes, account=pend.account)
        return {"ok": np.int32(1)}

    def on_release(self, req, ctx):
        """The sender abandons a claimed handoff CLEANLY (it failed before
        COMPLETE but is alive and done writing): blocks free, no
        quarantine needed."""
        handoff = _scalar(req["handoff"])
        with self._lock:
            pend = self._pending.pop(handoff, None)
        if pend is not None:
            self.mgr.free_blocks(pend.kv)
        return {"ok": np.int32(1)}

    def on_resume(self, req, ctx):
        """Stream re-attach: park -> scheduler -> per-token stream,
        continuing the client-visible index. A mid-stream migration ends
        the stream with a ``migrated`` record instead of tokens."""
        seq_key = _scalar(req["seq_key"])
        max_tokens = _scalar(req.get("max_tokens", 32))
        slo = _SLO_BY_CODE.get(_scalar(req.get("slo", 0)), SLO_INTERACTIVE)
        with self._lock:
            parked = self._parked.pop(seq_key, None)
        if parked is None:
            ctx.abort(StatusCode.NOT_FOUND,
                      f"no parked sequence {seq_key} (expired or already "
                      "resumed)")
        try:
            stream = self.sched.submit_adopted(
                parked.kv, parked.prompt, last_token=parked.last_token,
                emitted=parked.emitted, max_tokens=max_tokens, slo=slo,
                trace=parked.trace, account=parked.account,
                shipped_bytes=parked.nbytes)
        except ShedError as exc:
            self.mgr.free_blocks(parked.kv, cache_prefix=True)
            ctx.set_trailing_metadata([(PUSHBACK_KEY,
                                        str(exc.pushback_ms))])
            ctx.abort(StatusCode.UNAVAILABLE, f"resume shed: {exc}")
        except (DrainingError, Exception) as exc:
            self.mgr.free_blocks(parked.kv, cache_prefix=True)
            code = (StatusCode.UNAVAILABLE
                    if isinstance(exc, DrainingError)
                    else StatusCode.INTERNAL)
            ctx.abort(code, str(exc))
        idx = parked.emitted
        try:
            while True:
                if not ctx.is_active():
                    return
                try:
                    tok = stream.next(timeout=_POLL_S)
                except StopIteration:
                    return
                except SeqMigrated as mig:
                    yield {"migrated": _b(mig.address),
                           "seq_key": np.int64(mig.seq_key),
                           "next_index": np.int32(mig.next_index)}
                    return
                except MigrationFailed as exc:
                    ctx.abort(StatusCode.UNAVAILABLE,
                              f"migration failed: {exc}")
                except (ShedError, DrainingError) as exc:
                    ctx.abort(StatusCode.UNAVAILABLE, str(exc))
                except Exception as exc:
                    ctx.abort(StatusCode.INTERNAL,
                              f"sequence failed: {exc}")
                if tok is None:
                    continue
                yield {"token": np.int32(tok), "index": np.int32(idx)}
                idx += 1
        finally:
            stream.cancel()

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "pending": len(self._pending),
                "parked": len(self._parked),
                "handoffs_in": self.handoffs_in,
                "prefix_hits": self.prefix_hits,
                "quarantined_handoffs": self.quarantined_handoffs,
            }


def add_kv_methods(server: Server, state: DisaggDecode) -> None:
    server.add_method(
        _method("OfferKv"),
        unary_unary_rpc_method_handler(state.on_offer,
                                       codec.tree_deserializer,
                                       codec.tree_serializer))
    server.add_method(
        _method("CompleteKv"),
        unary_unary_rpc_method_handler(state.on_complete,
                                       codec.tree_deserializer,
                                       codec.tree_serializer))
    server.add_method(
        _method("ReleaseKv"),
        unary_unary_rpc_method_handler(state.on_release,
                                       codec.tree_deserializer,
                                       codec.tree_serializer))
    server.add_method(
        _method("ResumeSeq"),
        unary_stream_rpc_method_handler(state.on_resume,
                                        codec.tree_deserializer,
                                        codec.tree_serializer))


# ---------------------------------------------------------------------------
# The shipper: one handoff over the grant plane (prefill AND migration).
# ---------------------------------------------------------------------------

class _KvShipper:
    """Sender-side handoff driver shared by the prefill server and the
    migration path: OfferKv -> one-sided block writes (GrantWriter, the
    standing-window discipline) -> CompleteKv; a clean local failure
    releases the claim so the peer frees instead of quarantining."""

    def __init__(self, channel):
        self._channel = channel
        self._offer = channel.unary_unary(_method("OfferKv"),
                                          codec.tree_serializer,
                                          codec.tree_deserializer)
        self._complete = channel.unary_unary(_method("CompleteKv"),
                                             codec.tree_serializer,
                                             codec.tree_deserializer)
        self._release = channel.unary_unary(_method("ReleaseKv"),
                                            codec.tree_serializer,
                                            codec.tree_deserializer)
        self.writer = GrantWriter()

    def _burst(self, mc, reqs, timeout: float):
        """Issue a BURST of small control RPCs: pipelined (N in flight on
        one connection) with their fused sends coalesced into ONE writev
        (tpurpc-pulse: Channel.batch_calls + FrameWriter.batch) — a drain
        migrating N sequences frames one transport write, not N.  Returns
        one result-or-exception per request, order preserved."""
        import contextlib

        if len(reqs) == 1:
            try:
                return [mc(reqs[0], timeout=timeout)]
            except Exception as exc:
                return [exc]
        pipe = mc.pipeline(depth=max(1, len(reqs)))
        batcher = getattr(self._channel, "batch_calls", None)
        cm = batcher() if batcher is not None else contextlib.nullcontext()
        futs = []
        with cm:
            for r in reqs:
                futs.append(pipe.call_async(r, timeout=timeout))
        out = []
        for fut in futs:
            try:
                out.append(fut.result(timeout=timeout + 1))
            except Exception as exc:
                out.append(exc)
        return out

    def offer(self, seq_key: int, prompt: np.ndarray, n_tokens: int,
              timeout: float, trace=None, account: Optional[str] = None):
        req = {"seq_key": np.int64(seq_key), "prompt": prompt,
               "n_tokens": np.int32(n_tokens)}
        # tpurpc-odyssey: the sequence's journey context + accounting
        # identity cross the split IN the offer (metadata would bind to
        # the RPC; bursts carry a different sequence per request)
        if trace is not None:
            req["trace"] = _b(trace.encode())
        if account:
            req["account"] = _b(account)
        resp = self._offer(req, timeout=timeout)
        if not _scalar(resp["ok"]):
            raise MigrationFailed(
                f"handoff refused: {_s(resp.get('reason', b''))}")
        grant = BlockGrant.from_wire(bytes(
            np.asarray(resp["grant"], np.uint8)))
        return (grant, _scalar(resp["handoff"]),
                _scalar(resp["resume_pos"]),
                int(np.asarray(resp["resume_hash"],
                               np.uint64).ravel()[0]),
                _scalar(resp["resume_flags"]))

    def ship(self, grant: BlockGrant, handoff: int, payload: memoryview,
             n_tokens: int, last_token: int, emitted: int,
             timeout: float) -> None:
        chunks = [payload[o:o + grant.block_bytes]
                  for o in range(0, len(payload), grant.block_bytes)]
        try:
            self.writer.write_blocks(grant, chunks)
        except BaseException:
            # clean local failure: tell the peer to FREE (we are alive
            # and done — no straggler risk, no quarantine needed)
            try:
                self._release({"handoff": np.int64(handoff)}, timeout=2)
            except Exception:
                pass  # peer unreachable: its TTL reap quarantines
            raise
        wedge = TEST_HOOKS.get("wedge_before_complete")
        if wedge is not None:
            wedge.wait(10)  # chaos seam: die-between-write-and-complete
        self._complete({"handoff": np.int64(handoff),
                        "n_tokens": np.int32(n_tokens),
                        "last_token": np.int32(last_token),
                        "emitted": np.int32(emitted)}, timeout=timeout)

    def close(self) -> None:
        self.writer.close()


# ---------------------------------------------------------------------------
# Prefill side.
# ---------------------------------------------------------------------------

class DisaggPrefill:
    """The prefill server's engine: fold prompts into KV entries (host
    scratch — its arena is the DECODE server's), ship over the grant
    plane, answer with the re-attach key + first token."""

    def __init__(self, model, decode_channel, decode_address: str,
                 timeout_s: float = 10.0):
        if not hasattr(model, "prefill_paged"):
            raise ValueError("prefill serving needs the explicit-KV model "
                             "contract (prefill_paged)")
        self.model = model
        self.decode_address = decode_address
        self._shipper = _KvShipper(decode_channel)
        self.timeout_s = float(timeout_s)
        base = int.from_bytes(os.urandom(4), "big") << 20
        self._keys = itertools.count(base + 1)
        self.prefills = 0
        self.shipped_bytes = 0
        self.prefix_skipped_entries = 0

    def on_prefill(self, req, ctx):
        prompt = np.asarray(req["prompt"], dtype=np.int32).reshape(-1)
        if prompt.size == 0:
            ctx.abort(StatusCode.INVALID_ARGUMENT, "empty prompt")
        seq_key = next(self._keys)
        n_tokens = int(prompt.size) + 1  # prompt entries + first sample
        # tpurpc-odyssey: this RPC's ambient trace + the caller's account
        # ride the offer, so the decode side parks the sequence under the
        # same journey/identity the client started
        trace = _tracing.current()
        account = None
        try:
            for key, value in ctx.invocation_metadata():
                if key == _odyssey.ACCOUNT_KEY:
                    account = _odyssey.sanitize_account(value)
                    break
        except Exception:
            pass
        try:
            grant, handoff, pos, rhash, rflags = self._shipper.offer(
                seq_key, prompt, n_tokens, self.timeout_s,
                trace=trace, account=account)
            host = HostKv(base_pos=pos, base_hash=rhash, base_flags=rflags)
            first = int(self.model.prefill_paged([prompt], [host])[0])
            payload = host.payload()
            self._shipper.ship(grant, handoff, payload, n_tokens, first,
                               1, self.timeout_s)
        except MigrationFailed as exc:
            ctx.abort(StatusCode.UNAVAILABLE, str(exc))
        except Exception as exc:
            ctx.abort(StatusCode.UNAVAILABLE,
                      f"handoff to {self.decode_address} failed: {exc}")
        self.prefills += 1
        self.shipped_bytes += len(payload)
        self.prefix_skipped_entries += pos
        return {"seq_key": np.int64(seq_key),
                "first_token": np.int32(first),
                "decode_address": _b(self.decode_address)}

    def on_stats(self, req, ctx):
        from tpurpc.tpu import ledger

        snap = ledger.snapshot()
        return {"prefills": np.int64(self.prefills),
                "shipped_bytes": np.int64(self.shipped_bytes),
                "prefix_skipped_entries":
                    np.int64(self.prefix_skipped_entries),
                "rdma_write": np.int64(snap["rdma_write"]),
                "host_copy": np.int64(snap["host_copy"])}

    def close(self) -> None:
        self._shipper.close()


def add_prefill_methods(server: Server, state: DisaggPrefill) -> None:
    server.add_method(
        _method("Prefill"),
        unary_unary_rpc_method_handler(state.on_prefill,
                                       codec.tree_deserializer,
                                       codec.tree_serializer))
    server.add_method(
        _method("PrefillStats"),
        unary_unary_rpc_method_handler(state.on_stats,
                                       codec.tree_deserializer,
                                       codec.tree_serializer))


# ---------------------------------------------------------------------------
# Live migration (source side).
# ---------------------------------------------------------------------------

def migrate(state: DisaggDecode, peer_channel, peer_address: str,
            sids: Optional[List[int]] = None,
            timeout_s: float = 10.0) -> Tuple[int, int]:
    """Move live sequences (KV + stream) from ``state``'s scheduler to the
    decode server at ``peer_channel``/``peer_address``. Per sequence:
    detach at a step boundary (KV intact), OfferKv/ship/CompleteKv to the
    peer (prefix hits there skip shipped bytes), then end the source
    stream with the re-attach record. On ANY failure the sequence fails
    ALONE with UNAVAILABLE — its siblings and the peer's other work are
    untouched. Returns ``(moved, failed)``."""
    sched = state.sched
    shipper = _KvShipper(peer_channel)
    moved = failed = 0

    def fail_one(sid, s, exc) -> None:
        nonlocal failed
        _flight.emit(_flight.MIG_END, state._tag, sid, 0)
        _MIG_FAILED.inc()
        # the peer may be dead mid-write: OUR blocks saw no foreign
        # writer, so free (not quarantine) locally; the peer's TTL reap
        # quarantines ITS claimed blocks
        state.mgr.free_blocks(s.kv)
        s.kv = None
        _odyssey.seq_done(s.led, "failed")
        s.q.put(MigrationFailed(str(exc)))
        failed += 1

    def _offer_req(s, n, k) -> dict:
        req = {"seq_key": np.int64(k), "prompt": s.prompt,
               "n_tokens": np.int32(n)}
        # odyssey: each migrating sequence carries ITS OWN journey
        # context and account across the hop (bursts span sequences, so
        # per-request fields, not call metadata)
        if s.trace is not None:
            req["trace"] = _b(s.trace.encode())
        if s.account:
            req["account"] = _b(s.account)
        return req

    try:
        live = []
        for sid in (sids if sids is not None else sched.live_sids()):
            s = sched.detach(sid)
            if s is None:
                continue
            if s.kv is None or s.cancelled:
                _odyssey.seq_done(s.led, "failed")
                s.q.put(MigrationFailed("sequence had no shippable KV"))
                failed += 1
                continue
            n_entries = s.kv.length
            _flight.emit(_flight.MIG_BEGIN, state._tag, sid, n_entries)
            seq_key = (int(time.monotonic_ns()) << 8 | (sid & 0xFF)) \
                & 0x7FFFFFFFFFFFFFFF
            live.append((sid, s, n_entries, seq_key, time.monotonic_ns()))
        # Phase 1 — BURST the offers (tpurpc-pulse, ROADMAP item 2's
        # follow-up): a drain migrating N sequences frames ONE gathered
        # writev of OfferKv calls instead of N serialized round trips.
        resps = shipper._burst(
            shipper._offer,
            [_offer_req(s, n, k) for _sid, s, n, k, _t0 in live],
            timeout_s) if live else []
        # Phase 2 — per-sequence one-sided block writes (failures fail
        # that sequence ALONE; its siblings keep going).
        pending = []  # (sid, s, seq_key, t0, shipped, CompleteKv request)
        for (sid, s, n_entries, seq_key, t0), resp in zip(live, resps):
            try:
                if isinstance(resp, Exception):
                    raise resp
                if not _scalar(resp["ok"]):
                    raise MigrationFailed(
                        f"handoff refused: {_s(resp.get('reason', b''))}")
                grant = BlockGrant.from_wire(bytes(
                    np.asarray(resp["grant"], np.uint8)))
                handoff = _scalar(resp["handoff"])
                pos = _scalar(resp["resume_pos"])
                chunks = [v for _bi, v in s.kv.chunks(pos, n_entries)]
                shipper.writer.write_blocks(grant, chunks)
            except Exception as exc:
                fail_one(sid, s, exc)
                continue
            pending.append((sid, s, seq_key, t0,
                            (n_entries - pos) * ENTRY_BYTES,
                            {"handoff": np.int64(handoff),
                             "n_tokens": np.int32(n_entries),
                             "last_token": np.int32(s.last_token),
                             "emitted": np.int32(s.emitted)}))
        wedge = TEST_HOOKS.get("wedge_before_complete")
        if wedge is not None and pending:
            wedge.wait(10)
        # Phase 3 — burst the completes: one writev flushes every pending
        # CompleteKv, the exact shape the ISSUE names.
        cresps = shipper._burst(shipper._complete,
                                [req for *_x, req in pending],
                                timeout_s) if pending else []
        for (sid, s, seq_key, t0, shipped, _req), resp in zip(pending,
                                                              cresps):
            if isinstance(resp, Exception):
                fail_one(sid, s, resp)
                continue
            state.mgr.free_blocks(s.kv, cache_prefix=True)
            s.kv = None
            emitted = s.emitted
            _flight.emit(_flight.MIG_END, state._tag, sid, 1)
            _MIGRATIONS.inc()
            # odyssey: settle the source ledger — migration count, the
            # hop's rendezvous bytes, the seq-migrate journey span; a
            # migrated journey always tail-commits (PR 5 rule)
            _odyssey.seq_migrated(s.led, shipped, t0)
            s.q.put(SeqMigrated(peer_address, seq_key, emitted))
            moved += 1
    finally:
        shipper.close()
    return moved, failed


# ---------------------------------------------------------------------------
# One-liners + the re-attaching client.
# ---------------------------------------------------------------------------

def serve_decode(model, address: str = "127.0.0.1:0", *,
                 kv_blocks: int = 512, block_bytes: int = 2048,
                 kv_kind: str = "shm", name: str = "decode",
                 max_batch: int = 8, max_waiting: int = 32,
                 prefill_budget: int = 128,
                 batch_shed_depth: Optional[int] = None,
                 step_slo_ms: Optional[float] = None,
                 pending_ttl_s: float = 30.0, parked_ttl_s: float = 60.0,
                 migrate_to: Optional[Callable[[], Tuple[object, str]]]
                 = None,
                 max_workers: int = 32,
                 ) -> Tuple[Server, int, DecodeScheduler, DisaggDecode]:
    """A decode server: paged scheduler over a ``kv_kind`` arena, the
    handoff/resume methods, the standard Generate method (for colocated
    traffic and A/B baselines), load reports carrying ``load_depth`` (the
    waiting+swapped satellite fix), and — with ``migrate_to`` returning
    ``(channel, address)`` — a drain hook that migrates every live
    sequence before the server finishes draining (the zero-failed-RPC
    drain, stateful edition)."""
    from tpurpc.serving.api import add_generation_method

    mgr = KvBlockManager(n_blocks=kv_blocks, block_bytes=block_bytes,
                         kind=kv_kind, name=name)
    srv_box: list = []

    def draining() -> bool:
        return bool(srv_box and srv_box[0].draining)

    sched = DecodeScheduler(
        model, kv=mgr, max_batch=max_batch, max_waiting=max_waiting,
        prefill_budget=prefill_budget, batch_shed_depth=batch_shed_depth,
        step_slo_ms=step_slo_ms, draining_fn=draining, name=name)
    srv = Server(max_workers=max_workers)
    srv_box.append(srv)
    state = DisaggDecode(sched, mgr, pending_ttl_s=pending_ttl_s,
                         parked_ttl_s=parked_ttl_s)
    add_kv_methods(srv, state)
    add_generation_method(srv, sched, name="Generate")
    srv.set_load_provider(sched.load_depth)
    if migrate_to is not None:
        def _drain_migrate() -> None:
            try:
                ch, addr = migrate_to()
            except Exception:
                return
            try:
                migrate(state, ch, addr)
            except Exception:
                pass  # drain continues; unmigrated streams finish locally
        srv.add_drain_hook(_drain_migrate)
    srv.start()
    port = srv.add_insecure_port(address)
    state.address = f"127.0.0.1:{port}"
    return srv, port, sched, state


def serve_prefill(model, decode_channel, decode_address: str,
                  address: str = "127.0.0.1:0", *,
                  max_workers: int = 16,
                  ) -> Tuple[Server, int, DisaggPrefill]:
    """A prefill server shipping into ``decode_address``'s arena."""
    state = DisaggPrefill(model, decode_channel, decode_address)
    srv = Server(max_workers=max_workers)
    add_prefill_methods(srv, state)
    srv.start()
    port = srv.add_insecure_port(address)
    return srv, port, state


class DisaggClient:
    """The re-attaching generation client: Prefill on the prefill tier,
    ResumeSeq on the decode tier, transparent follow of ``migrated``
    records — the caller sees one ordered token stream regardless of how
    many decode servers carried it."""

    def __init__(self, prefill_channel, decode_address: str,
                 channel_factory: Optional[Callable[[str], object]]
                 = None, account: Optional[str] = None):
        self._prefill = prefill_channel.unary_unary(
            _method("Prefill"), codec.tree_serializer,
            codec.tree_deserializer)
        self._decode_address = decode_address
        #: tpurpc-odyssey accounting identity, attached to every control
        #: RPC as the ``tpurpc-account`` metadata key
        self._account = account
        if channel_factory is None:
            from tpurpc.rpc.channel import Channel

            channel_factory = Channel
        self._factory = channel_factory
        self._channels: Dict[str, object] = {}

    def _md(self):
        return [(_odyssey.ACCOUNT_KEY, self._account)] \
            if self._account else None

    def _channel(self, address: str):
        ch = self._channels.get(address)
        if ch is None:
            ch = self._channels[address] = self._factory(address)
        return ch

    def generate_with_meta(self, prompt, *, max_tokens: int = 32,
                           slo: str = SLO_INTERACTIVE,
                           timeout: Optional[float] = None):
        """Yield ``(index, token)`` pairs, indices 0..n-1 across prefill,
        decode, and any number of migrations."""
        prompt = np.asarray(prompt, dtype=np.int32).reshape(-1)
        resp = self._prefill({"prompt": prompt}, timeout=timeout,
                             metadata=self._md())
        seq_key = _scalar(resp["seq_key"])
        address = _s(resp["decode_address"]) or self._decode_address
        yield 0, _scalar(resp["first_token"])
        emitted = 1
        while emitted < max_tokens:
            ch = self._channel(address)
            mc = ch.unary_stream(_method("ResumeSeq"),
                                 codec.tree_serializer,
                                 codec.tree_deserializer)
            call = mc({"seq_key": np.int64(seq_key),
                       "max_tokens": np.int32(max_tokens),
                       "slo": np.int32(_SLO_CODE[slo])}, timeout=timeout,
                      metadata=self._md())
            migrated = None
            for item in call:
                if "migrated" in item:
                    migrated = (_s(item["migrated"]),
                                _scalar(item["seq_key"]))
                    break
                yield _scalar(item["index"]), _scalar(item["token"])
                emitted += 1
            if migrated is None:
                return
            address, seq_key = migrated

    def generate(self, prompt, *, max_tokens: int = 32,
                 slo: str = SLO_INTERACTIVE,
                 timeout: Optional[float] = None):
        for _i, tok in self.generate_with_meta(prompt,
                                               max_tokens=max_tokens,
                                               slo=slo, timeout=timeout):
            yield tok

    def close(self) -> None:
        for ch in self._channels.values():
            try:
                ch.close()
            except Exception:
                pass
        self._channels.clear()
