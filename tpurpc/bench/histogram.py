"""Log-bucketed latency histogram (HdrHistogram-lite).

The reference links HdrHistogram_c for its RTT percentiles
(``cmake/modules/FindHdrHistogram.cmake``, ``mb_client.cc`` MPI_Reduce'd
histograms); this is the same idea sized for Python: ~2,048 buckets with
<2% relative error across 1µs..67s, mergeable across threads/processes.
"""

from __future__ import annotations

from typing import Dict


class LatencyHistogram:
    """Values in nanoseconds; buckets are 64 linear steps per power of two."""

    _SUB = 64  # sub-buckets per octave → ≤ 1/64 relative error

    def __init__(self):
        self.counts: Dict[int, int] = {}
        self.total = 0
        self.sum_ns = 0
        self.min_ns = None
        self.max_ns = 0

    def record(self, ns: int) -> None:
        ns = max(1, int(ns))
        octave = ns.bit_length() - 1
        if octave <= 6:
            key = ns  # exact below 64ns
        else:
            sub = ns >> (octave - 6)      # 64..127
            key = (octave << 7) | sub
        self.counts[key] = self.counts.get(key, 0) + 1
        self.total += 1
        self.sum_ns += ns
        self.max_ns = max(self.max_ns, ns)
        self.min_ns = ns if self.min_ns is None else min(self.min_ns, ns)

    @staticmethod
    def _key_value(key: int) -> int:
        if key < 128:
            return key  # exact region
        octave = key >> 7
        sub = key & 0x7F
        return sub << (octave - 6)

    def merge(self, other: "LatencyHistogram") -> None:
        for k, c in other.counts.items():
            self.counts[k] = self.counts.get(k, 0) + c
        self.total += other.total
        self.sum_ns += other.sum_ns
        self.max_ns = max(self.max_ns, other.max_ns)
        if other.min_ns is not None:
            self.min_ns = (other.min_ns if self.min_ns is None
                           else min(self.min_ns, other.min_ns))

    def percentile(self, q: float) -> float:
        """q in [0,100] → value in ns."""
        if not self.total:
            return 0.0
        target = self.total * q / 100.0
        seen = 0
        for key in sorted(self.counts):
            seen += self.counts[key]
            if seen >= target:
                return float(self._key_value(key))
        return float(self.max_ns)

    @property
    def mean_ns(self) -> float:
        return self.sum_ns / self.total if self.total else 0.0

    def to_dict(self) -> Dict:
        return {"counts": self.counts, "total": self.total,
                "sum_ns": self.sum_ns, "min_ns": self.min_ns,
                "max_ns": self.max_ns}

    @classmethod
    def from_dict(cls, d: Dict) -> "LatencyHistogram":
        h = cls()
        h.counts = {int(k): v for k, v in d["counts"].items()}
        h.total = d["total"]
        h.sum_ns = d["sum_ns"]
        h.min_ns = d["min_ns"]
        h.max_ns = d["max_ns"]
        return h
