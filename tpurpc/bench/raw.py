"""Raw transport micro-benchmark: the ring data plane WITHOUT the RPC stack.

Clone of the reference's ``examples/cpp/rdma_microbenchmark`` (``mb.cc`` —
raw ibverbs WRITE ping-pong/bandwidth with no gRPC anywhere), recast for
the tpurpc data plane: drive :class:`tpurpc.core.pair.Pair` directly over
the loopback/shm domain and report raw bandwidth + message rate, giving the
A/B baseline that isolates RPC-stack overhead from transport cost (the
same comparison the reference's README tells its users to run first).

Two workloads, mirroring ``mb.cc``'s modes:

* ``bw``   — one-way bulk: sender streams ``--msgs`` messages of
  ``--size`` bytes; receiver drains. Reports GB/s + msgs/s.
* ``lat``  — ping-pong: 1-byte echo round trips. Reports p50/p99 µs.

CLI:
    python -m tpurpc.bench.raw bw  --size 1048576 --msgs 256
    python -m tpurpc.bench.raw lat --iters 2000

Threads, not processes: the loopback pair shares one address space the way
the reference's single-host A/B test shares one NIC. ``--discipline``
selects the wait mode (busy/event/hybrid) like ``GRPC_PLATFORM_TYPE``
selects it for the RPC stack.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from typing import List

from tpurpc.core.pair import create_loopback_pair
from tpurpc.core.poller import wait_readable


def run_bw(size: int, msgs: int, ring_size: int, discipline: str) -> dict:
    a, b = create_loopback_pair(ring_size=ring_size)
    payload = b"\xab" * size
    total = size * msgs
    recv_done = threading.Event()
    recv_bytes = [0]

    def drain():
        while recv_bytes[0] < total:
            if not wait_readable(b, timeout=30, discipline=discipline):
                break
            chunk = b.recv()
            recv_bytes[0] += len(chunk)
        recv_done.set()

    t = threading.Thread(target=drain, daemon=True)
    try:
        t0 = time.perf_counter()
        t.start()
        for _ in range(msgs):
            sent = 0
            while sent < size:
                n = a.send([payload], byte_idx=sent)
                sent += n
        if not recv_done.wait(timeout=60):
            raise TimeoutError("receiver did not drain")
        if recv_bytes[0] != total:
            # drain() bailed on a wait_readable timeout: reporting a number
            # computed from bytes that never arrived would be silently wrong
            raise TimeoutError(
                f"receiver stalled at {recv_bytes[0]}/{total} bytes")
        dt = time.perf_counter() - t0
    finally:
        a.destroy()
        b.destroy()
    return {
        "metric": "raw_ring_bandwidth",
        "gbps": round(total / dt / 1e9, 3),
        "msgs_per_s": round(msgs / dt, 1),
        "size": size,
        "discipline": discipline,
    }


def run_lat(iters: int, ring_size: int, discipline: str) -> dict:
    a, b = create_loopback_pair(ring_size=ring_size)
    stop = threading.Event()

    def echo():
        while not stop.is_set():
            if not wait_readable(b, timeout=1, discipline=discipline):
                continue
            data = b.recv()
            if data:
                b.send([data])

    t = threading.Thread(target=echo, daemon=True)
    t.start()
    rtts: List[float] = []
    try:
        for _ in range(iters):
            t0 = time.perf_counter()
            a.send([b"x"])
            deadline = t0 + 10.0
            while True:
                if wait_readable(a, timeout=5, discipline=discipline):
                    if a.recv():
                        break
                if time.perf_counter() > deadline:
                    raise TimeoutError("echo reply never arrived")
            rtts.append(time.perf_counter() - t0)
    finally:
        stop.set()
        t.join(timeout=2)
        a.destroy()
        b.destroy()
    rtts.sort()
    return {
        "metric": "raw_ring_latency",
        "p50_us": round(rtts[len(rtts) // 2] * 1e6, 1),
        "p99_us": round(rtts[int(len(rtts) * 0.99)] * 1e6, 1),
        "iters": iters,
        "discipline": discipline,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tpurpc.bench.raw")
    sub = ap.add_subparsers(dest="mode", required=True)
    bw = sub.add_parser("bw")
    bw.add_argument("--size", type=int, default=1 << 20)
    bw.add_argument("--msgs", type=int, default=256)
    lat = sub.add_parser("lat")
    lat.add_argument("--iters", type=int, default=2000)
    for p in (bw, lat):
        p.add_argument("--ring-kb", type=int, default=4096)
        p.add_argument("--discipline", default="hybrid",
                       choices=("busy", "event", "hybrid"))
    args = ap.parse_args(argv)
    if args.mode == "bw":
        out = run_bw(args.size, args.msgs, args.ring_kb * 1024,
                     args.discipline)
    else:
        out = run_lat(args.iters, args.ring_kb * 1024, args.discipline)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
