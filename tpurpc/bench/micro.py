"""Micro-benchmark: closed/open-loop RPC ping-pong with reference log format.

Clone of ``examples/cpp/micro-bench`` (``mb_client.cc``/``mb_server.cc``):
a BenchmarkService echo server; clients issue unary or streaming ping-pongs
of a fixed request size, closed-loop (next request after the reply) or
open-loop (fixed issue rate), recording RTTs in a mergeable histogram and
printing the reference's periodic/aggregate lines so its plot scripts
(``draw/draw_bandwidth.py``-style) parse ours unchanged.

CLI:
    python -m tpurpc.bench.micro server --port 0
    python -m tpurpc.bench.micro client --target HOST:PORT --req-size 64 \
        --streaming --duration 10 --concurrency 1 [--rate 50000]
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from typing import List, Optional

import tpurpc.rpc as rpc
from tpurpc.bench.histogram import LatencyHistogram

SERVICE = "/tpurpc.Benchmark/"


def add_benchmark_service(srv: "rpc.Server") -> None:
    """Echo endpoints mirroring BenchmarkService (benchmark_service.proto)."""

    def unary_call(req, ctx):
        return req

    def streaming_call(req_iter, ctx):
        for req in req_iter:
            yield req

    srv.add_method(SERVICE + "UnaryCall",
                   rpc.unary_unary_rpc_method_handler(unary_call,
                                                      inline=True))
    srv.add_method(SERVICE + "StreamingCall",
                   rpc.stream_stream_rpc_method_handler(streaming_call))


def run_server(port: int = 0, max_workers: int = 32) -> "rpc.Server":
    srv = rpc.Server(max_workers=max_workers)
    add_benchmark_service(srv)
    bound = srv.add_insecure_port(f"0.0.0.0:{port}")
    srv.start()
    srv.bench_port = bound
    return srv


class ClientStats:
    def __init__(self):
        self.hist = LatencyHistogram()
        self.rpcs = 0
        self.bytes_tx = 0
        self.lock = threading.Lock()

    def record(self, rtt_ns: int, nbytes: int) -> None:
        with self.lock:
            self.hist.record(rtt_ns)
            self.rpcs += 1
            self.bytes_tx += nbytes

    def take_interval(self):
        with self.lock:
            r, b = self.rpcs, self.bytes_tx
            self.rpcs = 0
            self.bytes_tx = 0
            return r, b


def _report_line(rpcs: int, nbytes: int, dt: float,
                 hist: LatencyHistogram) -> str:
    rate = rpcs / dt if dt > 0 else 0.0
    mbps = nbytes * 8 / dt / 1e6 if dt > 0 else 0.0
    return (f"Rate {rate:.0f} RPCs/s, TX Bandwidth {mbps:.1f} Mb/s, "
            f"RTT (us) mean {hist.mean_ns / 1e3:.2f} "
            f"P50 {hist.percentile(50) / 1e3:.2f} "
            f"P95 {hist.percentile(95) / 1e3:.2f} "
            f"P99 {hist.percentile(99) / 1e3:.2f}")


def _closed_loop_unary(ch, stats: ClientStats, payload: bytes,
                       stop: threading.Event) -> None:
    mc = ch.unary_unary(SERVICE + "UnaryCall")
    try:
        while not stop.is_set():
            t0 = time.perf_counter_ns()
            mc(payload, timeout=30)
            stats.record(time.perf_counter_ns() - t0, len(payload))
    except rpc.RpcError:
        if not stop.is_set():  # shutdown races are expected, mid-run isn't
            raise


def _closed_loop_streaming(ch, stats: ClientStats, payload: bytes,
                           stop: threading.Event) -> None:
    """Streaming ping-pong: ONE message in flight per loop, matching the
    reference's closed-loop streaming mode (its 7µs p50 logs are
    request→reply round trips, not a free-running flood — an ungated
    generator here measured 1.5s 'RTTs' that were pure queue depth)."""
    mc = ch.stream_stream(SERVICE + "StreamingCall")
    send_times: "List[int]" = []
    window = threading.Semaphore(1)

    def gen():
        while not stop.is_set():
            if not window.acquire(timeout=0.25):
                continue  # reply pending; re-check stop
            if stop.is_set():
                return
            send_times.append(time.perf_counter_ns())
            yield payload
    try:
        for _reply in mc(gen(), timeout=None):
            stats.record(time.perf_counter_ns() - send_times.pop(0),
                         len(payload))
            window.release()
            if stop.is_set():
                break
    except rpc.RpcError:
        if not stop.is_set():
            raise


def _open_loop_unary(ch, stats: ClientStats, payload: bytes,
                     stop: threading.Event, rate: float) -> None:
    """Fixed issue rate; RTT includes queueing (the open-loop honesty the
    reference's mb_client implements with a send schedule)."""
    mc = ch.unary_unary(SERVICE + "UnaryCall")
    period = 1.0 / rate
    next_t = time.perf_counter()
    inflight: "threading.Semaphore" = threading.Semaphore(512)

    def issue():
        t0 = time.perf_counter_ns()
        try:
            mc(payload, timeout=30)
            stats.record(time.perf_counter_ns() - t0, len(payload))
        finally:
            inflight.release()

    while not stop.is_set():
        now = time.perf_counter()
        if now < next_t:
            time.sleep(min(next_t - now, 0.01))
            continue
        next_t += period
        inflight.acquire()
        threading.Thread(target=issue, daemon=True).start()


def run_client(target: str, req_size: int = 64, streaming: bool = False,
               duration: float = 10.0, concurrency: int = 1,
               rate: Optional[float] = None, report_every: float = 1.0,
               out=sys.stdout) -> dict:
    payload = bytes(req_size)
    stats = ClientStats()
    stop = threading.Event()
    channels = [rpc.insecure_channel(target) for _ in range(concurrency)]
    workers = []
    #: per-worker verdict: ran until the stop signal without dying. A
    #: worker that raised mid-run fell out of the offered load — the
    #: ACHIEVED concurrency the result records is what the measurement
    #: really exercised, not what --concurrency asked for.
    worker_ok = [False] * concurrency
    for i, ch in enumerate(channels):
        if rate is not None:
            fn = lambda c=ch: _open_loop_unary(c, stats, payload, stop,
                                               rate / concurrency)
        elif streaming:
            fn = lambda c=ch: _closed_loop_streaming(c, stats, payload, stop)
        else:
            fn = lambda c=ch: _closed_loop_unary(c, stats, payload, stop)

        def run(fn=fn, i=i):
            try:
                fn()
            except BaseException:
                return  # died mid-run: this slot's load stopped early
            worker_ok[i] = stop.is_set()  # clean exit = lasted the run

        t = threading.Thread(target=run, daemon=True)
        t.start()
        workers.append(t)

    t_start = time.perf_counter()
    last = t_start
    agg_rpcs = 0
    agg_bytes = 0
    while time.perf_counter() - t_start < duration:
        time.sleep(min(report_every, duration / 2))
        now = time.perf_counter()
        rpcs, nbytes = stats.take_interval()
        agg_rpcs += rpcs
        agg_bytes += nbytes
        print(_report_line(rpcs, nbytes, now - last, stats.hist), file=out)
        last = now
    stop.set()
    for ch in channels:
        try:
            ch.close()  # unblocks workers parked mid-RPC
        except Exception:
            pass
    for t in workers:
        t.join(timeout=5)
    achieved = sum(1 for i, t in enumerate(workers)
                   if worker_ok[i] and not t.is_alive())
    total_dt = time.perf_counter() - t_start
    rpcs, nbytes = stats.take_interval()
    agg_rpcs += rpcs
    agg_bytes += nbytes
    h = stats.hist
    print("Aggregated " + _report_line(agg_rpcs, agg_bytes, total_dt, h),
          file=out)
    return {
        "rpcs": agg_rpcs, "duration_s": total_dt,
        "concurrency_requested": concurrency,
        "concurrency_achieved": achieved,
        "rate_rps": agg_rpcs / total_dt if total_dt else 0.0,
        "tx_mbps": agg_bytes * 8 / total_dt / 1e6 if total_dt else 0.0,
        "rtt_us": {"mean": h.mean_ns / 1e3, "p50": h.percentile(50) / 1e3,
                   "p95": h.percentile(95) / 1e3,
                   "p99": h.percentile(99) / 1e3},
        "histogram": h.to_dict(),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tpurpc.bench.micro")
    sub = ap.add_subparsers(dest="role", required=True)
    s = sub.add_parser("server")
    s.add_argument("--port", type=int, default=0)
    c = sub.add_parser("client")
    c.add_argument("--target", required=True)
    c.add_argument("--req-size", type=int, default=64)
    c.add_argument("--streaming", action="store_true")
    c.add_argument("--duration", type=float, default=10.0)
    c.add_argument("--concurrency", type=int, default=1)
    c.add_argument("--rate", type=float, default=None,
                   help="open-loop issue rate (RPCs/s); omit for closed loop")
    c.add_argument("--json", action="store_true",
                   help="print the aggregate as one JSON line at the end")
    args = ap.parse_args(argv)
    if args.role == "server":
        srv = run_server(args.port)
        print(f"listening {srv.bench_port}", flush=True)
        srv.wait_for_termination()
        return 0
    result = run_client(args.target, req_size=args.req_size,
                        streaming=args.streaming, duration=args.duration,
                        concurrency=args.concurrency, rate=args.rate)
    if args.json:
        result.pop("histogram")
        print(json.dumps(result))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
