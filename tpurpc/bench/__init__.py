"""Benchmark rig: micro-bench clone + qps-style driver/worker.

Mirrors the reference's two performance harnesses (SURVEY.md §2.6/§4):
``examples/cpp/micro-bench`` (closed/open-loop MPI client with HdrHistogram
RTTs and periodic rate lines) and ``test/cpp/qps`` (driver RPC-controls N
workers). Log lines use the reference's format so plots are comparable:

    Rate <N> RPCs/s, TX Bandwidth <M> Mb/s, RTT (us) mean <..> P50 <..> P99 <..>
    Aggregated ...
"""
