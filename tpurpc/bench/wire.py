"""gRPC-wire-path sweep: stock grpcio clients against the tpurpc h2 server.

VERDICT r3 next-round #4: every committed fast number rides tpurpc's lean
native framing, but the reference's numbers all INCLUDE chttp2+HPACK
(``/root/reference/src/core/ext/transport/chttp2/transport/
chttp2_transport.cc:1624`` sits in its hot path) — so the wire-compat path
(``tpurpc/wire/grpc_h2.py``, from-scratch h2+HPACK in Python) needs its own
measured row, and an honest same-host comparison against grpcio↔grpcio
(grpcio's server is the C core; ours is Python — the gap IS the price of a
pure-Python h2 server).

Cells: {tpurpc-h2-server, grpcio-server} × {unary, streaming} × sizes,
stock grpcio client throughout. One fresh server subprocess per cell.

    python -m tpurpc.bench.wire --sizes 64,65536 --duration 3
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_TPURPC_SERVER = """
import tpurpc.rpc as rpc
srv = rpc.Server(max_workers=8)
srv.add_method("/wire.Bench/Echo",
               rpc.unary_unary_rpc_method_handler(lambda r, c: bytes(r),
                                                  inline=True))
def _echo_stream(req_iter, ctx):
    for m in req_iter:
        yield bytes(m)
srv.add_method("/wire.Bench/EchoStream",
               rpc.stream_stream_rpc_method_handler(_echo_stream))
print("PORT", srv.add_insecure_port("127.0.0.1:0"), flush=True)
srv.start()
srv.wait_for_termination(timeout=600)
"""

_GRPCIO_SERVER = """
import grpc
from concurrent import futures

class H(grpc.GenericRpcHandler):
    def service(self, hcd):
        if hcd.method == "/wire.Bench/Echo":
            return grpc.unary_unary_rpc_method_handler(lambda r, c: bytes(r))
        if hcd.method == "/wire.Bench/EchoStream":
            def es(req_iter, ctx):
                for m in req_iter:
                    yield bytes(m)
            return grpc.stream_stream_rpc_method_handler(es)
        return None

srv = grpc.server(futures.ThreadPoolExecutor(max_workers=8))
srv.add_generic_rpc_handlers((H(),))
port = srv.add_insecure_port("127.0.0.1:0")
print("PORT", port, flush=True)
srv.start()
srv.wait_for_termination(timeout=600)
"""


def _run_client(port: int, size: int, duration: float,
                streaming: bool) -> dict:
    """Closed-loop stock-grpcio client (in-process: grpcio's client is the
    C core; its overhead is part of every reference measurement too)."""
    import grpc

    payload = b"x" * size
    lat = []
    with grpc.insecure_channel(f"127.0.0.1:{port}") as ch:
        if streaming:
            import queue as _q
            import threading as _t

            sendq: "_q.Queue" = _q.Queue(maxsize=1)
            stop = _t.Event()

            def gen():
                while not stop.is_set():
                    item = sendq.get()
                    if item is None:
                        return
                    yield item

            mc = ch.stream_stream("/wire.Bench/EchoStream")
            call = mc(gen())
            # warm
            sendq.put(payload)
            next(iter([next(iter(call))]))
            t_end = time.perf_counter() + duration
            while time.perf_counter() < t_end:
                t0 = time.perf_counter()
                sendq.put(payload)
                next(iter(call))
                lat.append(time.perf_counter() - t0)
            stop.set()
            sendq.put(None)
            call.cancel()
        else:
            mc = ch.unary_unary("/wire.Bench/Echo")
            mc(payload, timeout=30)  # warm
            t_end = time.perf_counter() + duration
            while time.perf_counter() < t_end:
                t0 = time.perf_counter()
                mc(payload, timeout=30)
                lat.append(time.perf_counter() - t0)
    lat.sort()
    n = len(lat)
    total = sum(lat)
    return {
        "rpcs": n,
        "rate_rps": round(n / total, 1) if total else 0.0,
        "rtt_us": {
            "mean": round(total / n * 1e6, 1),
            "p50": round(lat[n // 2] * 1e6, 1),
            "p99": round(lat[min(n - 1, int(n * 0.99))] * 1e6, 1),
        },
    }


def run_cell(server_kind: str, size: int, duration: float,
             streaming: bool) -> dict:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.setdefault("GRPC_PLATFORM_TYPE", "TCP")  # the wire path IS tcp+h2
    code = _TPURPC_SERVER if server_kind == "tpurpc" else _GRPCIO_SERVER
    srv = subprocess.Popen([sys.executable, "-u", "-c", code],
                           stdout=subprocess.PIPE, text=True, env=env)
    try:
        line = srv.stdout.readline()
        if not line.startswith("PORT"):
            raise RuntimeError(f"server failed: {line!r} (rc={srv.poll()})")
        port = int(line.split()[1])
        out = _run_client(port, size, duration, streaming)
        out.update({"server": server_kind, "size": size,
                    "streaming": streaming})
        return out
    finally:
        srv.kill()
        srv.wait()


def main(argv=None) -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", default="64,65536")
    ap.add_argument("--duration", type=float, default=3.0)
    args = ap.parse_args(argv)
    sizes = [int(s) for s in args.sizes.split(",")]

    rows = []
    for server_kind in ("tpurpc", "grpcio"):
        for streaming in (False, True):
            for size in sizes:
                cell = run_cell(server_kind, size, args.duration, streaming)
                print(json.dumps(cell), flush=True)
                rows.append(cell)
    print(f"\n{'server':<8} {'mode':<10} {'size':>7} {'RPC/s':>9} "
          f"{'p50us':>8} {'p99us':>8}")
    for r in rows:
        print(f"{r['server']:<8} "
              f"{'streaming' if r['streaming'] else 'unary':<10} "
              f"{r['size']:>7} {r['rate_rps']:>9.0f} "
              f"{r['rtt_us']['p50']:>8.1f} {r['rtt_us']['p99']:>8.1f}")


if __name__ == "__main__":
    main()
