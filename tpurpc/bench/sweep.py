"""Parameter-sweep driver over the micro-benchmark: the reference's run.sh.

The reference validates with shell sweeps over its micro-bench
(``examples/cpp/helloworld.benchmark/benchmark/run.sh`` parameterizes
platform × size × clients and archives the logs ``draw/draw_bandwidth.py``
plots — SURVEY.md §2.6/§6). This module is that rig as one command: each
cell runs a fresh server subprocess with the cell's ``GRPC_PLATFORM_TYPE``
(config is read once per process — sweeping inside one process would lie),
drives ``tpurpc.bench.micro``'s client in-process, and emits one
JSON line per cell plus a final table.

    python -m tpurpc.bench.sweep --platforms TCP,RDMA_BPEV \\
        --sizes 64,65536 --duration 3

Reference-comparable fields: rate_rps, tx_mbps, rtt p50/p95/p99 (µs).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_SERVER = """
from tpurpc.bench import micro
srv = micro.run_server(port=0)
print("PORT", srv.bound_ports[0], flush=True)
srv.wait_for_termination(timeout=600)
"""


def run_cell(platform: str, size: int, duration: float, concurrency: int,
             streaming: bool) -> dict:
    env = dict(os.environ)
    env["GRPC_PLATFORM_TYPE"] = platform
    env.setdefault("JAX_PLATFORMS", "cpu")
    srv = subprocess.Popen([sys.executable, "-u", "-c", _SERVER],
                           stdout=subprocess.PIPE, text=True, env=env)
    try:
        line = srv.stdout.readline()
        if not line.startswith("PORT"):
            rc = srv.poll()
            raise RuntimeError(
                f"sweep server failed to start (rc={rc}): {line!r}")
        port = int(line.split()[1])
        # the CLIENT must also run under the cell's platform: subprocess it
        code = (
            "import json, sys\n"
            "from tpurpc.bench.micro import run_client\n"
            "import io\n"
            f"r = run_client('127.0.0.1:{port}', req_size={size},"
            f" streaming={streaming}, duration={duration},"
            f" concurrency={concurrency}, out=io.StringIO())\n"
            "r.pop('histogram', None)\n"
            "print(json.dumps(r))\n"
        )
        out = subprocess.run([sys.executable, "-u", "-c", code],
                             capture_output=True, text=True, env=env,
                             timeout=duration + 120)
        if out.returncode != 0:
            raise RuntimeError(f"client failed: {out.stderr[-500:]}")
        cell = json.loads(out.stdout.strip().splitlines()[-1])
    finally:
        srv.kill()
        srv.wait(timeout=10)  # no zombie/fd leak per cell
        srv.stdout.close()
    cell.update({"platform": platform, "size": size,
                 "concurrency": concurrency, "streaming": streaming})
    return cell


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tpurpc.bench.sweep")
    ap.add_argument("--platforms", default="TCP,RDMA_BPEV")
    ap.add_argument("--sizes", default="64,65536")
    ap.add_argument("--duration", type=float, default=3.0)
    ap.add_argument("--concurrency", type=int, default=1)
    ap.add_argument("--streaming", action="store_true")
    args = ap.parse_args(argv)

    cells = []
    for platform in args.platforms.split(","):
        for size in (int(s) for s in args.sizes.split(",")):
            t0 = time.monotonic()
            cell = run_cell(platform.strip(), size, args.duration,
                            args.concurrency, args.streaming)
            cell["wall_s"] = round(time.monotonic() - t0, 1)
            print(json.dumps(cell), flush=True)
            cells.append(cell)

    # reference-log-style closing table
    print(f"\n{'platform':<12}{'size':>8}{'RPC/s':>12}{'Mb/s':>10}"
          f"{'p50us':>8}{'p99us':>8}")
    for c in cells:
        print(f"{c['platform']:<12}{c['size']:>8}{c['rate_rps']:>12.0f}"
              f"{c['tx_mbps']:>10.1f}{c['rtt_us']['p50']:>8.0f}"
              f"{c['rtt_us']['p99']:>8.0f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
