"""qps-style distributed benchmark rig: a driver RPC-controls N workers.

Clone of ``test/cpp/qps`` (SURVEY.md §4.2): ``driver.cc RunScenario`` talks
to ``qps_worker.cc`` WorkerService over gRPC itself; workers then assume
server or client roles for the measured traffic. Here the control plane is
tpurpc, configs/stats are JSON trees, and the whole scenario can run
all-localhost (the reference's ``json_run_localhost`` trick — multi-node
shape without a cluster).

    # every participant:
    python -m tpurpc.bench.qps worker --port 5000x
    # orchestrator:
    python -m tpurpc.bench.qps driver --workers h1:50001,h2:50002 \
        --req-size 64 --duration 10
"""

from __future__ import annotations

import argparse
import json
import threading
from typing import Dict, List

import tpurpc.rpc as rpc
from tpurpc.bench import micro
from tpurpc.bench.histogram import LatencyHistogram

WORKER_SERVICE = "/tpurpc.WorkerService/"


def _jser(obj) -> bytes:
    return json.dumps(obj).encode()


def _jdes(buf) -> dict:
    return json.loads(bytes(buf).decode())


class WorkerServicer:
    """RunServer / RunClient control streams (qps_worker.cc:105-140)."""

    def run_server(self, req_iter, ctx):
        setup = next(req_iter, None)
        if setup is None:
            return
        srv = micro.run_server(port=int(setup.get("port", 0)),
                               max_workers=int(setup.get("threads", 16)))
        try:
            yield {"port": srv.bench_port, "ok": True}
            for _mark in req_iter:   # each mark → interval status
                yield {"port": srv.bench_port, "ok": True}
        finally:
            srv.stop(grace=0)

    def run_client(self, req_iter, ctx):
        setup = next(req_iter, None)
        if setup is None:
            return
        result = micro.run_client(
            setup["target"], req_size=int(setup.get("req_size", 64)),
            streaming=bool(setup.get("streaming", False)),
            duration=float(setup.get("duration", 10.0)),
            concurrency=int(setup.get("concurrency", 1)),
            rate=setup.get("rate"), out=open("/dev/null", "w"))
        yield result

    def attach(self, srv: "rpc.Server") -> None:
        srv.add_method(
            WORKER_SERVICE + "RunServer",
            rpc.stream_stream_rpc_method_handler(self.run_server, _jdes, _jser))
        srv.add_method(
            WORKER_SERVICE + "RunClient",
            rpc.stream_stream_rpc_method_handler(self.run_client, _jdes, _jser))


def run_worker(port: int = 0) -> "rpc.Server":
    srv = rpc.Server(max_workers=16)
    WorkerServicer().attach(srv)
    bound = srv.add_insecure_port(f"0.0.0.0:{port}")
    srv.start()
    srv.worker_port = bound
    return srv


def run_scenario(worker_targets: List[str], req_size: int = 64,
                 streaming: bool = False, duration: float = 10.0,
                 concurrency: int = 1, rate=None,
                 server_host: str = "127.0.0.1") -> Dict:
    """First worker serves; the rest run clients (driver.cc RunScenario)."""
    if len(worker_targets) < 2:
        raise ValueError("need >= 2 workers (1 server + >=1 client)")
    channels = [rpc.insecure_channel(t) for t in worker_targets]
    try:
        # stand up the measured server on worker 0
        srv_mc = channels[0].stream_stream(WORKER_SERVICE + "RunServer",
                                           _jser, _jdes)
        srv_q: "list" = []
        srv_done = threading.Event()
        srv_stream_stop = threading.Event()

        def srv_reqs():
            yield {"port": 0}
            srv_stream_stop.wait()

        srv_call = srv_mc(srv_reqs(), timeout=None)
        it = iter(srv_call)
        status = next(it)
        bench_port = status["port"]

        # fan the clients out
        target = f"{server_host}:{bench_port}"
        results: List[Dict] = [None] * (len(channels) - 1)

        def one(i, ch):
            mc = ch.stream_stream(WORKER_SERVICE + "RunClient", _jser, _jdes)
            out = list(mc(iter([{
                "target": target, "req_size": req_size,
                "streaming": streaming, "duration": duration,
                "concurrency": concurrency, "rate": rate,
            }]), timeout=duration + 60))
            results[i] = out[-1]

        ts = [threading.Thread(target=one, args=(i, ch))
              for i, ch in enumerate(channels[1:])]
        [t.start() for t in ts]
        [t.join() for t in ts]
        srv_stream_stop.set()

        # merge: aggregate rate sums; RTT percentiles from merged histograms
        merged = LatencyHistogram()
        agg = {"rate_rps": 0.0, "tx_mbps": 0.0, "rpcs": 0,
               "concurrency_requested": 0, "concurrency_achieved": 0}
        for r in results:
            if r is None:
                continue
            agg["rate_rps"] += r["rate_rps"]
            agg["tx_mbps"] += r["tx_mbps"]
            agg["rpcs"] += r["rpcs"]
            # achieved vs requested load provenance: workers can fall
            # behind --concurrency (die mid-run); the scenario records
            # what actually ran so rates aren't misattributed
            agg["concurrency_requested"] += r.get("concurrency_requested", 0)
            agg["concurrency_achieved"] += r.get("concurrency_achieved", 0)
            merged.merge(LatencyHistogram.from_dict(r["histogram"]))
        agg["rtt_us"] = {"mean": merged.mean_ns / 1e3,
                         "p50": merged.percentile(50) / 1e3,
                         "p99": merged.percentile(99) / 1e3}
        agg["n_clients"] = len(results)
        return agg
    finally:
        srv_done.set()
        for ch in channels:
            try:
                ch.close()
            except Exception:
                pass


def run_localhost(n_clients: int = 2, **kw) -> Dict:
    """All-localhost scenario: workers in-process (json_run_localhost.cc)."""
    workers = [run_worker(0) for _ in range(n_clients + 1)]
    try:
        targets = [f"127.0.0.1:{w.worker_port}" for w in workers]
        return run_scenario(targets, **kw)
    finally:
        for w in workers:
            w.stop(grace=0)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tpurpc.bench.qps")
    sub = ap.add_subparsers(dest="role", required=True)
    w = sub.add_parser("worker")
    w.add_argument("--port", type=int, default=0)
    d = sub.add_parser("driver")
    d.add_argument("--workers", required=True,
                   help="comma-separated host:port worker list")
    d.add_argument("--req-size", type=int, default=64)
    d.add_argument("--streaming", action="store_true")
    d.add_argument("--duration", type=float, default=10.0)
    d.add_argument("--concurrency", type=int, default=1)
    d.add_argument("--server-host", default="127.0.0.1")
    args = ap.parse_args(argv)
    if args.role == "worker":
        srv = run_worker(args.port)
        print(f"worker listening {srv.worker_port}", flush=True)
        srv.wait_for_termination()
        return 0
    agg = run_scenario(args.workers.split(","), req_size=args.req_size,
                       streaming=args.streaming, duration=args.duration,
                       concurrency=args.concurrency,
                       server_host=args.server_host)
    print(json.dumps(agg))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
