"""tpurpc-odyssey: sequence-lifecycle tracing, token latency, cost ledgers.

Every observability face before this one (spans PR 4, flight PR 5, lens
PR 8, argus PR 14) is RPC- or process-scoped. Since PR 10/11 the unit of
work is a *sequence* whose life spans many RPCs and up to three processes
(prefill -> KV ship -> decode -> preempt/swap -> migrate) — and the
cross-layer attribution gap that opens is exactly the blind spot the RPC-
under-ML studies name (arXiv:1805.08430: where a request's time actually
goes; arXiv:1804.01138: tails only exist under honest methodology). This
module is the sequence-scoped answer, three planes over one per-sequence
record:

* **Journey tracing.** The originating generation RPC's
  :class:`~tpurpc.obs.tracing.TraceContext` rides into the scheduler's
  sequence object and across the disagg control plane (OfferKv /
  CompleteKv / ResumeSeq / ``migrate()`` request metadata), so ONE
  trace_id stitches admission -> prefill -> KV ship -> every decode-step
  membership window -> preempt/swap -> migration -> final token. Spans
  land in the ordinary span ring of whichever process did the work;
  :func:`journey` merges the processes' ``/traces?trace_id=`` exports
  onto one wall-clock axis via the PR 8 clock anchors. The PR 5
  tail-commit rules apply at sequence granularity: a slow, shed,
  preempted, or migrated sequence ALWAYS commits its provisional trace —
  the pathological journey is never the one the sampler skipped.
* **Token-latency plane.** Per-SLO-class inter-token latency
  (``gen_itl_us`` + ``gen_itl_<class>_us``) and time-per-output-token
  (``gen_tpot_us`` + ``gen_tpot_<class>_us``) histograms recorded at the
  stream edge (the sequence's token queue — the last point the scheduler
  can see), plus bounded ROLLING windows whose p99s the tsdb samples as
  ``gen_itl_p99_us{class}`` / ``gen_ttft_p99_us{class}`` — rolling so an
  ITL/TTFT SLO objective (:mod:`tpurpc.obs.slo`'s new track kinds) can
  RESOLVE when the degradation ends, which the cumulative histograms
  never allow (the PR 14 watchdog_p99 move, applied to tokens).
* **Cost accounting.** A per-sequence :class:`SeqLedger`: device-step
  microseconds consumed (each step's duration divided by batch occupancy
  — row i of an N-row step owns 1/N of it), prefill microseconds, KV
  block-byte-seconds held (arena residency) and swap-byte-seconds (host
  residency while preempted — swapped work is not free work), rendezvous
  bytes shipped, preemption/swap/migration counts. Ledgers aggregate by
  the metadata key :data:`ACCOUNT_KEY` (``tpurpc-account`` — the tenant
  stand-in ROADMAP item 4 builds on; default ``anon``), and export at
  ``GET /debug/seq`` (live + recent-completed ring), shard-merged by
  :mod:`tpurpc.obs.shard` and fleet-merged at the collector's
  ``/fleet/seq``.

Cost model: everything here is per-sequence-EDGE or per-DEVICE-STEP
(amortized over the whole batch), except the one per-token ITL record —
a monotonic read, a subtraction, one histogram record, one deque append.
The bench gate ``odyssey_overhead_pct < 3%`` holds the line; the
off-switch ``TPURPC_ODYSSEY=0`` (or :func:`force`) drops even that (the
flight SEQ_* events stay — the always-on postmortem contract).

Account-key grammar: ``[A-Za-z0-9._:-]{1,64}``; anything else is
character-sanitized to ``_`` and truncated; an empty/absent key is
``anon`` (:func:`sanitize_account`).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

from tpurpc.obs import metrics as _metrics
from tpurpc.obs import tracing as _tracing

__all__ = [
    "ACTIVE", "ACCOUNT_KEY", "DEFAULT_ACCOUNT", "SeqLedger",
    "configure", "force", "enabled", "sanitize_account",
    "seq_submit", "seq_join", "seq_prefill", "seq_first_token",
    "seq_token", "seq_step", "seq_swap", "seq_preempt", "seq_detached",
    "seq_migrated", "seq_done",
    "itl_p99_us", "ttft_p99_us", "rolling_series",
    "seq_doc", "accounts_snapshot", "merge_seq_docs", "journey",
    "reset", "postfork_reset",
]

#: metadata key carrying the accounting identity (tenant stand-in)
ACCOUNT_KEY = "tpurpc-account"
DEFAULT_ACCOUNT = "anon"

#: the ONE gate the scheduler's hot sites load (the tracing.ACTIVE shape)
ACTIVE = True
_forced: Optional[bool] = None

#: completed-sequence ring + per-class rolling token-latency windows
_DONE_CAP = 256
_ROLL_CAP = 512

#: terminal outcomes a ledger can settle with
_OUTCOMES = ("retire", "left", "shed", "refused", "failed", "migrated")

# -- token-latency histograms (per SLO class; the hot path records ONE) ------
_ITL = {
    "interactive": _metrics.histogram("gen_itl_interactive_us",
                                      kind="latency"),
    "batch": _metrics.histogram("gen_itl_batch_us", kind="latency"),
}
_TPOT = {
    "interactive": _metrics.histogram("gen_tpot_interactive_us",
                                      kind="latency"),
    "batch": _metrics.histogram("gen_tpot_batch_us", kind="latency"),
}
_SEQS_DONE = _metrics.counter("seq_completed")
_SEQS_MIGRATED = _metrics.counter("seq_migrated")


def _env_on() -> bool:
    import os

    return os.environ.get("TPURPC_ODYSSEY", "1").lower() not in (
        "0", "off", "false")


def configure() -> None:
    """Recompute the gate from ``TPURPC_ODYSSEY`` (honoring :func:`force`)."""
    global ACTIVE
    ACTIVE = _forced if _forced is not None else _env_on()


def force(on: Optional[bool]) -> None:
    """Tests/bench: pin the plane on/off; ``None`` returns to the env."""
    global _forced
    _forced = on
    configure()


def enabled() -> bool:
    return ACTIVE


def sanitize_account(raw) -> str:
    """The account-key grammar (module docstring): ``[A-Za-z0-9._:-]``,
    at most 64 chars; invalid characters become ``_``; empty -> anon."""
    if raw is None:
        return DEFAULT_ACCOUNT
    if isinstance(raw, (bytes, bytearray, memoryview)):
        raw = bytes(raw).decode("utf-8", "replace")
    s = str(raw)[:64]
    if not s:
        return DEFAULT_ACCOUNT
    return "".join(c if (c.isalnum() or c in "._:-") else "_" for c in s)


# -- the ledger ---------------------------------------------------------------

class SeqLedger:
    """One sequence's lifetime record. Mutated by the scheduler loop
    thread (join/step/swap/retire), the submitting thread (creation), and
    a migration thread after detach — phases never overlap, so field
    updates are plain GIL-atomic stores; only the registry (live/done/
    accounts maps) takes the module lock."""

    __slots__ = (
        "sid", "name", "account", "slo", "trace", "prompt_len", "state",
        "tokens", "steps", "step_us", "prefill_us", "kv_byte_s",
        "swap_byte_s", "shipped_bytes", "preempts", "swaps", "migrations",
        "adopted", "t_submit_ns", "t_first_ns", "t_done_ns", "outcome",
        "block_bytes", "_arena_bytes", "_host_bytes", "_mark_ns",
        "_last_tok_ns", "_win_t0_ns", "_itl_hist", "_itl_roll",
        "_itl_pend",
    )

    def __init__(self, name: str, sid: int, account: str, slo: str,
                 trace, prompt_len: int, block_bytes: int,
                 shipped_bytes: int, adopted: bool):
        self.sid = sid
        self.name = name
        self.account = account
        self.slo = slo
        self.trace = trace
        self.prompt_len = prompt_len
        self.state = "waiting"
        self.tokens = 0
        self.steps = 0
        self.step_us = 0.0
        self.prefill_us = 0.0
        self.kv_byte_s = 0.0
        self.swap_byte_s = 0.0
        self.shipped_bytes = shipped_bytes
        self.preempts = 0
        self.swaps = 0
        self.migrations = 0
        self.adopted = adopted
        self.t_submit_ns = time.monotonic_ns()
        self.t_first_ns = 0
        self.t_done_ns = 0
        self.outcome = ""
        self.block_bytes = block_bytes
        self._arena_bytes = 0
        self._host_bytes = 0
        self._mark_ns = self.t_submit_ns
        self._last_tok_ns = 0
        self._win_t0_ns = 0
        # per-token hot-path references resolved ONCE per sequence; ITL
        # samples accumulate in _itl_pend and flush to the histogram in
        # BATCHES (one lock per flush — the registry's amortization rule)
        self._itl_hist = _ITL[slo]
        self._itl_roll = _itl_roll[slo]
        self._itl_pend: List[int] = []

    # -- residency integration ------------------------------------------------

    def _charge(self, now_ns: int) -> None:
        """Integrate byte-seconds held since the last mark. Monotone by
        construction: the mark only moves forward, and each elapsed
        interval is charged exactly once (never at two call sites — every
        transition charges BEFORE flipping the residency fields)."""
        dt = now_ns - self._mark_ns
        if dt <= 0:
            return
        if self._arena_bytes:
            self.kv_byte_s += self._arena_bytes * dt / 1e9
        if self._host_bytes:
            self.swap_byte_s += self._host_bytes * dt / 1e9
        self._mark_ns = now_ns

    def _projected(self, now_ns: int):
        """(kv_byte_s, swap_byte_s) as of ``now_ns`` WITHOUT mutating —
        the live /debug/seq view."""
        dt = max(0, now_ns - self._mark_ns)
        return (self.kv_byte_s + self._arena_bytes * dt / 1e9,
                self.swap_byte_s + self._host_bytes * dt / 1e9)

    def to_dict(self, now_ns: Optional[int] = None) -> dict:
        now = now_ns if now_ns is not None else time.monotonic_ns()
        kv_bs, swap_bs = self._projected(now)
        end = self.t_done_ns or now
        d = {
            "sid": self.sid, "sched": self.name, "account": self.account,
            "slo": self.slo, "state": self.state,
            "prompt_len": self.prompt_len, "tokens": self.tokens,
            "steps": self.steps,
            "step_us": round(self.step_us, 1),
            "prefill_us": round(self.prefill_us, 1),
            "kv_byte_s": round(kv_bs, 3),
            "swap_byte_s": round(swap_bs, 3),
            "shipped_bytes": self.shipped_bytes,
            "preempts": self.preempts, "swaps": self.swaps,
            "migrations": self.migrations,
            "dur_s": round((end - self.t_submit_ns) / 1e9, 3),
        }
        if self.t_first_ns:
            d["ttft_us"] = (self.t_first_ns - self.t_submit_ns) // 1000
        if self.tokens >= 2 and self._last_tok_ns > self.t_first_ns:
            d["tpot_us"] = round((self._last_tok_ns - self.t_first_ns)
                                 / 1e3 / (self.tokens - 1), 1)
        if self.outcome:
            d["outcome"] = self.outcome
        if self.trace is not None:
            d["trace_id"] = f"{self.trace.trace_id:016x}"
        return d


# -- registry -----------------------------------------------------------------

_lock = threading.Lock()
_live: Dict[tuple, SeqLedger] = {}
_done: "deque[SeqLedger]" = deque(maxlen=_DONE_CAP)
#: account -> accumulated totals of COMPLETED sequences (live sequences
#: are folded in at render time)
_accounts: Dict[str, Dict[str, float]] = {}
#: device-step time seen / attributed (the >=95% acceptance instrument)
_step_us_total = [0.0]
_step_us_attrib = [0.0]

_itl_roll: Dict[str, "deque[int]"] = {
    "interactive": deque(maxlen=_ROLL_CAP),
    "batch": deque(maxlen=_ROLL_CAP),
}
_ttft_roll: Dict[str, "deque[int]"] = {
    "interactive": deque(maxlen=_ROLL_CAP),
    "batch": deque(maxlen=_ROLL_CAP),
}

_ACCOUNT_FIELDS = ("seqs", "tokens", "step_us", "prefill_us", "kv_byte_s",
                   "swap_byte_s", "shipped_bytes", "preempts", "swaps",
                   "migrations", "sheds", "failed")


def _account_bucket(account: str) -> Dict[str, float]:
    b = _accounts.get(account)
    if b is None:
        b = _accounts[account] = {k: 0 for k in _ACCOUNT_FIELDS}
    return b


# -- scheduler-facing hooks ---------------------------------------------------
#
# Every hook tolerates ``led is None`` (the off-switch: the scheduler
# skips ledger creation when ACTIVE is false, and every later site passes
# the None through).

def seq_submit(name: str, sid: int, account: str, slo: str, trace,
               prompt_len: int, block_bytes: int = 0,
               shipped_bytes: int = 0,
               adopted: bool = False) -> SeqLedger:
    led = SeqLedger(name, sid, account, slo, trace, prompt_len,
                    block_bytes, shipped_bytes, adopted)
    with _lock:
        _live[(name, sid)] = led
    return led


def seq_join(led: Optional[SeqLedger], resumed: bool = False) -> None:
    """The boundary admitted the sequence into the running batch: close
    the admit/park wait with a journey span, open a decode window."""
    if led is None:
        return
    now = time.monotonic_ns()
    t0 = led._mark_ns \
        if led.state in ("waiting", "swapped", "preempted") else now
    name = "seq-resume" \
        if (resumed or led.state in ("swapped", "preempted")) \
        else "seq-admit"
    _record(led, name, t0, now - t0)
    led._charge(now)
    led.state = "running"
    led._win_t0_ns = now


def seq_prefill(led: Optional[SeqLedger], dur_ns: int, share: int,
                kv_bytes: int = 0) -> None:
    """One batched prefill landed this sequence's entries: charge its
    1/share of the batch's device time, start arena residency."""
    if led is None:
        return
    now = time.monotonic_ns()
    led._charge(now)
    led.prefill_us += dur_ns / 1e3 / max(1, share)
    if kv_bytes:
        led._arena_bytes = kv_bytes
    _record(led, "seq-prefill", now - dur_ns, dur_ns)


def seq_first_token(led: Optional[SeqLedger], ttft_us: int,
                    now_ns: int = 0) -> None:
    if led is None:
        return
    now = now_ns or time.monotonic_ns()
    led.tokens += 1
    led.t_first_ns = now
    led._last_tok_ns = now
    _ttft_roll[led.slo].append(ttft_us)


def seq_token(led: Optional[SeqLedger], now_ns: int = 0) -> None:
    """The per-token record (the one hot-path site): inter-token latency
    at the stream edge — one histogram record (the per-class hist,
    resolved at ledger creation) and one deque append. The scheduler
    passes its step-end stamp as ``now_ns`` so a whole batch's token
    emissions share ONE clock read (the skew inside the delivery loop is
    microseconds against millisecond steps)."""
    if led is None:
        return
    now = now_ns or time.monotonic_ns()
    last = led._last_tok_ns
    led._last_tok_ns = now
    led.tokens += 1
    if last and now > last:
        # ONE list append on the per-token path; the histogram (one lock
        # per flush) and the rolling SLO window both fill in 64-token
        # batches — at worst a few steps of staleness against SLO
        # windows measured in seconds
        pend = led._itl_pend
        pend.append((now - last) // 1000)
        if len(pend) >= 64:
            _itl_flush(led)


def _itl_flush(led: "SeqLedger") -> None:
    pend = led._itl_pend
    led._itl_hist.record_many(pend)
    led._itl_roll.extend(pend)
    del pend[:]


def seq_step(running, dt_ns: int, now_ns: Optional[int] = None) -> None:
    """One device step over ``running`` completed in ``dt_ns``: charge
    each member its occupancy share and integrate its arena residency.
    Duck-typed over the scheduler's sequence objects (``.led``, ``.kv``)
    so this module never imports the scheduler."""
    nb = len(running)
    if nb == 0:
        return
    now = now_ns if now_ns is not None else time.monotonic_ns()
    dt_us = dt_ns / 1e3
    share = dt_us / nb
    _step_us_total[0] += dt_us
    attrib = 0.0
    for s in running:
        led = s.led
        if led is None:
            continue
        led.steps += 1
        led.step_us += share
        attrib += share
        # residency integrates only for rows that HOLD bytes (paged
        # mode); an opaque row pays two attribute loads and moves on
        kv = s.kv
        if kv is not None and led.block_bytes:
            led._arena_bytes = len(kv.blocks) * led.block_bytes
            led._charge(now)
        elif led._host_bytes:
            led._charge(now)
    _step_us_attrib[0] += attrib


def seq_swap(led: Optional[SeqLedger], direction: int, nbytes: int,
             dur_ns: int) -> None:
    """Residency flip: ``direction`` 0 = out-to-host (``nbytes`` = host
    image), 1 = in-from-host (``nbytes`` = arena bytes re-held)."""
    if led is None:
        return
    now = time.monotonic_ns()
    led._charge(now)
    led.swaps += 1
    if direction == 0:
        led._arena_bytes = 0
        led._host_bytes = nbytes
        led.state = "swapped"
        _record(led, "seq-swap-out", now - dur_ns, dur_ns)
    else:
        led._host_bytes = 0
        led._arena_bytes = nbytes
        _record(led, "seq-swap-in", now - dur_ns, dur_ns)


def seq_preempt(led: Optional[SeqLedger]) -> None:
    if led is None:
        return
    now = time.monotonic_ns()
    led.preempts += 1
    if led._win_t0_ns:
        _record(led, "seq-decode", led._win_t0_ns, now - led._win_t0_ns,
                tokens=led.tokens)
        led._win_t0_ns = 0
    led._charge(now)
    led.state = "preempted"


def seq_detached(led: Optional[SeqLedger], entries: int) -> None:
    """The boundary handed the sequence out (migration sender half): the
    ledger stays live — :func:`seq_migrated` / :func:`seq_done` settles
    it once the shipper knows the outcome."""
    if led is None:
        return
    now = time.monotonic_ns()
    if led._win_t0_ns:
        _record(led, "seq-decode", led._win_t0_ns, now - led._win_t0_ns,
                tokens=led.tokens)
        led._win_t0_ns = 0
    led._charge(now)
    led.state = "detached"


def seq_migrated(led: Optional[SeqLedger], shipped_bytes: int,
                 t0_ns: int) -> None:
    """The migration completed on the peer: final settle on the source.
    ``t0_ns`` brackets the ship (detach -> peer CompleteKv ok)."""
    if led is None:
        return
    led.shipped_bytes += shipped_bytes
    led.migrations += 1
    _SEQS_MIGRATED.inc()
    now = time.monotonic_ns()
    _record(led, "seq-migrate", t0_ns, now - t0_ns,
            shipped_bytes=shipped_bytes)
    seq_done(led, "migrated")


def seq_done(led: Optional[SeqLedger], outcome: str) -> None:
    """Terminal settle: integrate, close the decode window, record TPOT,
    fold into the account rollup, move live -> done, and make the journey
    tail-commit decision (a shed/failed/migrated/preempted or slow
    sequence always yields a full journey)."""
    if led is None or led.outcome:
        return
    now = time.monotonic_ns()
    if led._win_t0_ns:
        _record(led, "seq-decode", led._win_t0_ns, now - led._win_t0_ns,
                tokens=led.tokens)
        led._win_t0_ns = 0
    led._charge(now)
    led._arena_bytes = 0
    led._host_bytes = 0
    led.outcome = outcome if outcome in _OUTCOMES else "failed"
    led.t_done_ns = now
    led.state = "done"
    if led._itl_pend:
        _itl_flush(led)
    if led.tokens >= 2 and led._last_tok_ns > led.t_first_ns:
        tpot = int((led._last_tok_ns - led.t_first_ns)
                   / 1e3 / (led.tokens - 1))
        _TPOT[led.slo].record(tpot)
    _SEQS_DONE.inc()
    with _lock:
        _live.pop((led.name, led.sid), None)
        _done.append(led)
        b = _account_bucket(led.account)
        b["seqs"] += 1
        b["tokens"] += led.tokens
        b["step_us"] += led.step_us
        b["prefill_us"] += led.prefill_us
        b["kv_byte_s"] += led.kv_byte_s
        b["swap_byte_s"] += led.swap_byte_s
        b["shipped_bytes"] += led.shipped_bytes
        b["preempts"] += led.preempts
        b["swaps"] += led.swaps
        b["migrations"] += led.migrations
        if led.outcome == "shed":
            b["sheds"] += 1
        elif led.outcome == "failed":
            b["failed"] += 1
    _journey_settle(led)


# -- journey spans ------------------------------------------------------------

def _record(led: SeqLedger, name: str, t0_ns: int, dur_ns: int,
            **attrs) -> None:
    ctx = led.trace
    if ctx is None:
        return
    _tracing.record(name, ctx, t0_ns, dur_ns, sid=led.sid,
                    account=led.account, **attrs)


def _journey_settle(led: SeqLedger) -> None:
    """The PR 5 tail-commit rules at sequence granularity: a provisional
    journey commits when the sequence was shed/refused/failed/migrated,
    was preempted or swapped (the interesting journeys), or was slow by
    the ordinary tail bar; a healthy fast retire ages out untouched."""
    ctx = led.trace
    if ctx is None or not getattr(ctx, "provisional", False):
        return
    if (led.outcome in ("shed", "refused", "failed", "migrated")
            or led.preempts or led.swaps or led.migrations):
        _tracing.tail_commit(ctx.trace_id)
        return
    _tracing.tail_decide(ctx, led.t_done_ns - led.t_submit_ns)


# -- rolling token-latency windows (the SLO substrate) ------------------------

def _roll_p(roll, q: float) -> Optional[float]:
    vals = sorted(roll)
    if not vals:
        return None
    return float(vals[min(len(vals) - 1, max(0, int(len(vals) * q) - 1))])


def itl_p99_us(slo: str = "interactive") -> Optional[float]:
    return _roll_p(list(_itl_roll.get(slo, ())), 0.99)


def ttft_p99_us(slo: str = "interactive") -> Optional[float]:
    return _roll_p(list(_ttft_roll.get(slo, ())), 0.99)


def rolling_series() -> Dict[str, float]:
    """Series for the tsdb sampler: ``gen_itl_p99_us{class}`` /
    ``gen_ttft_p99_us{class}`` from the bounded rolling windows — the
    resolvable latency signal the new SLO track kinds threshold."""
    out: Dict[str, float] = {}
    for klass, roll in _itl_roll.items():
        p = _roll_p(list(roll), 0.99)
        if p is not None:
            out["gen_itl_p99_us{" + klass + "}"] = p
    for klass, roll in _ttft_roll.items():
        p = _roll_p(list(roll), 0.99)
        if p is not None:
            out["gen_ttft_p99_us{" + klass + "}"] = p
    return out


# -- export -------------------------------------------------------------------

def accounts_snapshot() -> Dict[str, Dict[str, float]]:
    """Account rollup with LIVE sequences folded in at read time."""
    now = time.monotonic_ns()
    with _lock:
        out = {a: dict(b) for a, b in _accounts.items()}
        live = list(_live.values())
    for led in live:
        b = out.setdefault(led.account, {k: 0 for k in _ACCOUNT_FIELDS})
        kv_bs, swap_bs = led._projected(now)
        b["seqs"] += 1
        b["tokens"] += led.tokens
        b["step_us"] += led.step_us
        b["prefill_us"] += led.prefill_us
        b["kv_byte_s"] += kv_bs
        b["swap_byte_s"] += swap_bs
        b["shipped_bytes"] += led.shipped_bytes
        b["preempts"] += led.preempts
        b["swaps"] += led.swaps
        b["migrations"] += led.migrations
    for b in out.values():
        for k in ("step_us", "prefill_us", "kv_byte_s", "swap_byte_s"):
            b[k] = round(b[k], 3)
    return out


def _hist_doc(h) -> dict:
    s = h.snapshot()
    return {"p50_us": s["p50"], "p99_us": s["p99"], "count": s["count"]}


def seq_doc(params: Optional[dict] = None) -> dict:
    """The ``GET /debug/seq`` body: live ledgers, the recent-completed
    ring, the account rollup, the step-time attribution check, and the
    token-latency summaries. ``?account=`` filters the sequence lists;
    ``?n=`` bounds them (default 32 live / 32 recent)."""
    if not ACTIVE:
        return {"enabled": False, "reason": "TPURPC_ODYSSEY=0"}
    params = params or {}
    want = params.get("account") or None
    try:
        n = max(1, int(params.get("n") or 32))
    except ValueError:
        n = 32
    now = time.monotonic_ns()
    with _lock:
        live = list(_live.values())
        done = list(_done)
    if want:
        live = [led for led in live if led.account == want]
        done = [led for led in done if led.account == want]
    live.sort(key=lambda led: led.step_us, reverse=True)
    total = _step_us_total[0]
    attrib = _step_us_attrib[0]
    return {
        "enabled": True,
        "live": [led.to_dict(now) for led in live[:n]],
        "live_total": len(live),
        "recent": [led.to_dict(now) for led in done[-n:]][::-1],
        "accounts": accounts_snapshot(),
        "step_us_total": round(total, 1),
        "step_us_attributed": round(attrib, 1),
        "attributed_pct": round(attrib / total * 100, 2) if total else None,
        "itl": {k: _hist_doc(h) for k, h in _ITL.items()},
        "tpot": {k: _hist_doc(h) for k, h in _TPOT.items()},
        "itl_p99_rolling_us": {k: _roll_p(list(r), 0.99)
                               for k, r in _itl_roll.items()},
        "ttft_p99_rolling_us": {k: _roll_p(list(r), 0.99)
                                for k, r in _ttft_roll.items()},
    }


def merge_seq_docs(docs: Dict[str, dict], label: str = "member") -> dict:
    """The pure shard/fleet merge: per-source docs keyed by shard id or
    member target -> one doc with tagged sequence lists and SUMMED
    account/attribution totals (used by ``obs.shard.aggregate_seq`` and
    the collector's ``/fleet/seq``)."""
    live: List[dict] = []
    recent: List[dict] = []
    accounts: Dict[str, Dict[str, float]] = {}
    total = attrib = 0.0
    enabled = False
    for src in sorted(docs):
        doc = docs[src] or {}
        if not doc.get("enabled"):
            continue
        enabled = True
        for row in doc.get("live", ()):
            live.append(dict(row, **{label: src}))
        for row in doc.get("recent", ()):
            recent.append(dict(row, **{label: src}))
        for acct, b in (doc.get("accounts") or {}).items():
            agg = accounts.setdefault(acct, {k: 0 for k in _ACCOUNT_FIELDS})
            for k in _ACCOUNT_FIELDS:
                agg[k] = round(agg[k] + (b.get(k) or 0), 3)
        total += float(doc.get("step_us_total") or 0.0)
        attrib += float(doc.get("step_us_attributed") or 0.0)
    live.sort(key=lambda r: r.get("step_us", 0), reverse=True)
    return {
        "enabled": enabled,
        "sources": sorted(docs),
        "live": live,
        "recent": recent,
        "accounts": accounts,
        "step_us_total": round(total, 1),
        "step_us_attributed": round(attrib, 1),
        "attributed_pct": round(attrib / total * 100, 2) if total else None,
    }


def journey(targets: List[str], trace_id: "int | str") -> dict:
    """One sequence's cross-process journey as a Perfetto chrome-trace:
    fetch ``/traces?trace_id=`` from every named process (serving ports —
    the scrape plane answers) and merge on the shared wall-clock axis via
    the PR 8 clock anchors (:mod:`tpurpc.tools.timeline`'s pure rebase).
    Each process is one named lane; unanchored members are flagged in
    ``otherData.unanchored``, never silently misaligned."""
    import json as _json
    import urllib.request

    from tpurpc.tools import timeline as _timeline

    if isinstance(trace_id, int):
        trace_id = f"{trace_id:016x}"
    collected = []
    for t in targets:
        try:
            with urllib.request.urlopen(
                    f"http://{t}/traces?trace_id={trace_id}",
                    timeout=5) as resp:
                doc = _json.loads(resp.read())
        except Exception:
            continue
        collected.append({"target": t, "traces": doc, "flight": None,
                          "profile": None, "metrics": ""})
    out = _timeline.build_timeline(collected)
    out["trace_id"] = trace_id
    return out


# -- lifecycle ----------------------------------------------------------------

def reset() -> None:
    """Test isolation: forget every ledger, rollup, and rolling window."""
    global _forced
    with _lock:
        _live.clear()
        _done.clear()
        _accounts.clear()
    _step_us_total[0] = 0.0
    _step_us_attrib[0] = 0.0
    for r in _itl_roll.values():
        r.clear()
    for r in _ttft_roll.values():
        r.clear()
    _forced = None
    configure()


def postfork_reset() -> None:
    """Fresh registry in a forked shard worker (the inherited ledgers are
    the supervisor's, not this worker's)."""
    global _lock
    _lock = threading.Lock()
    _live.clear()
    _done.clear()
    _accounts.clear()
    _step_us_total[0] = 0.0
    _step_us_attrib[0] = 0.0
    for r in _itl_roll.values():
        r.clear()
    for r in _ttft_roll.values():
        r.clear()
    configure()


configure()
