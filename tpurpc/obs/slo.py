"""tpurpc-argus SLO burn-rate alerting over the ring tsdb.

An operator does not page on "an error happened" — they page on "the
error *budget* is burning fast enough that the objective will be missed".
This module is that machinery, evaluated entirely in-process over
:mod:`tpurpc.obs.tsdb`'s bounded history:

* **Objectives** are declared per method (or server-wide) with up to
  three budget tracks:

  - ``errors`` — availability: the fraction of RPCs answering a non-OK
    code (``srv_calls{method,code}``), excluding admission sheds, must
    stay under ``1 - target_pct/100``;
  - ``sheds`` — pushback-awareness: admission-shed rejections
    (``srv_admission_rejected``) burn their OWN, deliberately looser
    budget (``shed_target_pct``). A server shedding under overload is
    doing its job — folding sheds into the error budget would page the
    defense mechanism, and ignoring them would hide capacity exhaustion;
  - ``latency`` — a threshold objective over a sampled quantile series
    (default the watchdog's ROLLING p99, per-method or worst-method —
    ``watchdog_p99_us{method}`` / ``watchdog_rolling_p99_us``, µs): the
    fraction of tsdb samples above ``latency_ms`` must stay under
    ``1 - latency_target_pct/100`` (the "bad minutes" formulation —
    per-call latency counters do not exist retroactively, a sampled
    rolling quantile does, and it recovers when the degradation ends).

* **Multi-window multi-burn-rate** (the Google SRE alerting recipe):
  each objective evaluates ``(fast, slow, threshold)`` window pairs —
  default ``(TPURPC_SLO_FAST_S, TPURPC_SLO_SLOW_S, 14.4)`` plus a
  ``(5×fast, 5×slow, 6.0)`` pair — and an alert FIRES only when both the
  fast and the slow window of some pair burn over the threshold: the
  fast window gives detection latency, the slow window immunity to
  blips. Windows are env-tunable so tests and smokes run in seconds.

* **State machine** per (objective, track): ``ok → pending`` when a fast
  window burns hot, ``pending → firing`` when a pair's slow window
  agrees, ``firing → resolved → ok`` when no pair sustains the burn.
  Transitions are exported at ``GET /debug/slo``, appended to
  ``/healthz`` (a firing alert degrades health — see
  :mod:`tpurpc.obs.scrape`), recorded as flight events
  (``slo-firing``/``slo-resolved`` — the ``slo`` protocol machine checks
  the bracket), and bridged into the stall watchdog via
  :func:`tpurpc.obs.watchdog.StallWatchdog.external_trip` so a page
  shows up in ``/debug/stalls`` — and so the watchdog's trip hooks
  (automatic evidence capture, :mod:`tpurpc.obs.bundle`) run.

The evaluator is one daemon thread on ``TPURPC_SLO_EVAL_S`` (default a
quarter of the fast window); it does nothing until an objective is
declared. Everything here is cold-path: the hot path already paid its
one counter bump in the server.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from tpurpc.obs import flight as _flight
from tpurpc.obs import metrics as _metrics

__all__ = [
    "SloObjective", "SloEvaluator", "declare", "objectives", "get",
    "ensure_started", "firing", "health_lines", "slo_doc", "reset",
    "postfork_reset", "TRACK_CODES",
]

#: flight-event a1 values naming the burning track (append-only).
#: tpurpc-odyssey (ISSUE 15) adds the token-latency objectives: ``ttft``
#: and ``itl`` threshold the odyssey plane's ROLLING per-class p99 series
#: (``gen_ttft_p99_us{class}`` / ``gen_itl_p99_us{class}``) exactly like
#: ``latency`` thresholds the watchdog roll — rolling, so they resolve.
TRACK_CODES = {"errors": 0, "sheds": 1, "latency": 2, "ttft": 3, "itl": 4}
TRACK_NAMES = {v: k for k, v in TRACK_CODES.items()}

#: anomaly counters: alert transitions, always-on
_FIRED = _metrics.labeled_counter("slo_alerts_fired", ("objective", "track"))
_RESOLVED = _metrics.counter("slo_alerts_resolved")


def _env_float(name: str, default: float) -> float:
    import os

    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


def default_windows() -> List[Tuple[float, float, float]]:
    """The env-scaled window pairs: ``(fast_s, slow_s, burn_threshold)``.
    Defaults (60 s / 720 s and 300 s / 3600 s) fit inside the tsdb's
    fine/coarse spans; tests scale the envs down to fractions of a
    second."""
    fast = max(0.1, _env_float("TPURPC_SLO_FAST_S", 60.0))
    slow = max(fast, _env_float("TPURPC_SLO_SLOW_S", 720.0))
    return [(fast, slow, 14.4), (5 * fast, 5 * slow, 6.0)]


class _TrackState:
    __slots__ = ("state", "since_ns", "fired", "last_burn", "last_transition")

    def __init__(self):
        self.state = "ok"
        self.since_ns = 0
        self.fired = 0
        self.last_burn = (0.0, 0.0)   # (fast, slow) of the hottest pair
        self.last_transition = ""


class SloObjective:
    """One declared objective. ``method=None`` binds server-wide. Tracks
    exist for whichever targets were given: ``target_pct`` opens the
    ``errors`` + ``sheds`` pair, ``latency_ms`` opens ``latency``, and
    (tpurpc-odyssey) ``ttft_ms`` / ``itl_ms`` open the token-latency
    objectives over the ``slo_class``'s rolling p99 series — "p99 ITL
    over X ms" as a burn-rate page that can resolve."""

    def __init__(self, name: str, method: Optional[str] = None,
                 target_pct: Optional[float] = None,
                 latency_ms: Optional[float] = None,
                 latency_target_pct: float = 99.0,
                 shed_target_pct: float = 95.0,
                 series: Optional[str] = None,
                 ttft_ms: Optional[float] = None,
                 itl_ms: Optional[float] = None,
                 token_target_pct: float = 99.0,
                 slo_class: str = "interactive",
                 windows: Optional[List[Tuple[float, float, float]]] = None):
        self.name = name
        self.method = method
        self.target_pct = target_pct
        self.latency_ms = latency_ms
        self.latency_target_pct = latency_target_pct
        self.shed_target_pct = shed_target_pct
        self.ttft_ms = ttft_ms
        self.itl_ms = itl_ms
        self.token_target_pct = token_target_pct
        self.slo_class = slo_class
        #: the sampled quantile series the latency track thresholds (µs):
        #: by default the watchdog's ROLLING p99 — per-method when the
        #: objective is, the worst-method roll otherwise. Rolling, not the
        #: cumulative histogram: the signal must RECOVER when the
        #: degradation ends or a fired alert could never resolve.
        if series:
            self.series = series
        elif method is not None:
            self.series = "watchdog_p99_us{" + method + "}"
        else:
            self.series = "watchdog_rolling_p99_us"
        self.windows = list(windows) if windows else default_windows()
        self.tag = _flight.tag_for(f"slo:{name}")
        #: threshold tracks share one evaluation shape: (series, µs bar)
        self._threshold_tracks: Dict[str, Tuple[str, float]] = {}
        if latency_ms is not None:
            self._threshold_tracks["latency"] = (self.series,
                                                 latency_ms * 1000.0)
        if ttft_ms is not None:
            self._threshold_tracks["ttft"] = (
                "gen_ttft_p99_us{" + slo_class + "}", ttft_ms * 1000.0)
        if itl_ms is not None:
            self._threshold_tracks["itl"] = (
                "gen_itl_p99_us{" + slo_class + "}", itl_ms * 1000.0)
        self.tracks: Dict[str, _TrackState] = {}
        if target_pct is not None:
            self.tracks["errors"] = _TrackState()
            self.tracks["sheds"] = _TrackState()
        for t in self._threshold_tracks:
            self.tracks[t] = _TrackState()

    # -- budget math ----------------------------------------------------------

    def _budget(self, track: str) -> float:
        if track == "errors":
            return max(1e-9, 1.0 - (self.target_pct or 100.0) / 100.0)
        if track == "sheds":
            return max(1e-9, 1.0 - self.shed_target_pct / 100.0)
        if track in ("ttft", "itl"):
            return max(1e-9, 1.0 - self.token_target_pct / 100.0)
        return max(1e-9, 1.0 - self.latency_target_pct / 100.0)

    def _counts(self, db, window_s: float,
                now_ns: Optional[int]) -> Tuple[float, float, float]:
        """(total, errors, sheds) deltas over the window from the tsdb's
        flattened ``srv_calls{method,code}`` children + the shed counter."""
        total = errors = 0.0
        prefix = "srv_calls{"
        for name in db.series():
            if not name.startswith(prefix):
                continue
            inner = name[len(prefix):-1]
            method, _, code = inner.rpartition(",")
            if self.method is not None and method != self.method:
                continue
            d = db.delta(name, window_s, now_ns=now_ns)
            total += d
            if code not in ("0", "OK"):
                errors += d
        sheds = db.delta("srv_admission_rejected", window_s, now_ns=now_ns)
        return total, errors, sheds

    def bad_ratio(self, db, track: str, window_s: float,
                  now_ns: Optional[int] = None) -> Optional[float]:
        """The fraction of the window that was 'bad' for one track, or
        None when the window holds no evidence yet."""
        thr = self._threshold_tracks.get(track)
        if thr is not None:
            series, bar_us = thr
            return db.over_threshold_fraction(series, bar_us, window_s,
                                              now_ns=now_ns)
        total, errors, sheds = self._counts(db, window_s, now_ns)
        if track == "sheds":
            denom = total + sheds
            return (sheds / denom) if denom > 0 else None
        if total <= 0:
            return None
        # pushback-aware: sheds never reach a handler, so they cannot be
        # in srv_calls — errors here are handler/transport failures only
        return errors / total

    def burns(self, db, track: str, now_ns: Optional[int] = None
              ) -> List[Tuple[float, float, float]]:
        """Per window pair: ``(burn_fast, burn_slow, threshold)`` — burn
        rate is bad_ratio / budget (1.0 = exactly on budget)."""
        budget = self._budget(track)
        out = []
        for fast_s, slow_s, thr in self.windows:
            bf = self.bad_ratio(db, track, fast_s, now_ns=now_ns)
            bs = self.bad_ratio(db, track, slow_s, now_ns=now_ns)
            out.append(((bf or 0.0) / budget, (bs or 0.0) / budget, thr))
        return out


class SloEvaluator:
    """Holds the declared objectives and drives their state machines on a
    cadence. One process-wide instance (:func:`get`); tests build private
    ones and call :meth:`evaluate_once` with a pinned clock."""

    def __init__(self, eval_s: Optional[float] = None, tsdb=None):
        fast = default_windows()[0][0]
        self.eval_s = eval_s if eval_s is not None else max(
            0.05, _env_float("TPURPC_SLO_EVAL_S", fast / 4.0))
        self._tsdb = tsdb
        self._objectives: Dict[str, SloObjective] = {}
        self._lock = threading.Lock()
        self._history: List[dict] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def _db(self):
        if self._tsdb is not None:
            return self._tsdb
        from tpurpc.obs import tsdb as _tsdb_mod

        return _tsdb_mod.get()

    # -- declaration ----------------------------------------------------------

    def declare(self, objective: SloObjective) -> SloObjective:
        with self._lock:
            self._objectives[objective.name] = objective
        return objective

    def objectives(self) -> List[SloObjective]:
        with self._lock:
            return list(self._objectives.values())

    # -- evaluation -----------------------------------------------------------

    def _transition(self, obj: SloObjective, track: str, st: _TrackState,
                    new_state: str, burn: Tuple[float, float],
                    now_ns: int) -> None:
        old = st.state
        st.state = new_state
        st.since_ns = now_ns
        st.last_transition = f"{old}->{new_state}"
        self._history.append({
            "t": time.time(),  # tpr: allow(wallclock)
            "objective": obj.name, "track": track,
            "from": old, "to": new_state,
            "burn_fast": round(burn[0], 2), "burn_slow": round(burn[1], 2),
        })
        del self._history[:-128]
        if new_state == "firing":
            st.fired += 1
            _FIRED.labels(obj.name, track).inc()
            tag = obj.tag
            track_code = TRACK_CODES.get(track, 0)
            burn_pct = int(burn[0] * 100)
            _flight.emit(_flight.SLO_FIRING, tag, track_code, burn_pct)
            self._page(obj, track, burn)
        elif old == "firing":
            _RESOLVED.inc()
            tag = obj.tag
            track_code = TRACK_CODES.get(track, 0)
            burn_pct = int(burn[0] * 100)
            _flight.emit(_flight.SLO_RESOLVED, tag, track_code, burn_pct)

    def _page(self, obj: SloObjective, track: str,
              burn: Tuple[float, float]) -> None:
        """The watchdog bridge: a firing page lands in /debug/stalls with
        stage ``slo`` (and through the watchdog's trip hooks, triggers
        automatic evidence capture)."""
        try:
            from tpurpc.obs import watchdog as _watchdog

            _watchdog.get().external_trip(
                "slo", obj.name,
                f"SLO burn-rate alert firing: track={track} "
                f"burn={burn[0]:.1f}x fast / {burn[1]:.1f}x slow "
                f"(method={obj.method or '*'})")
        except Exception:
            pass  # paging plumbing must never break the evaluator

    def evaluate_once(self, now_ns: Optional[int] = None) -> None:
        now = now_ns if now_ns is not None else time.monotonic_ns()
        db = self._db()
        for obj in self.objectives():
            for track, st in obj.tracks.items():
                try:
                    burns = obj.burns(db, track, now_ns=now)
                except Exception:
                    continue
                # the hottest pair drives the display; conditions scan all
                hot = max(burns, key=lambda b: b[0] / b[2]) if burns else \
                    (0.0, 0.0, 1.0)
                st.last_burn = (round(hot[0], 2), round(hot[1], 2))
                fire = any(bf >= thr and bs >= thr for bf, bs, thr in burns)
                pend = any(bf >= thr for bf, _bs, thr in burns)
                # ok always passes through pending (Prometheus `for:`
                # semantics): the acceptance contract is that a page is
                # OBSERVABLY pending→firing, never a 0-to-paged jump
                if st.state == "ok" and pend:
                    self._transition(obj, track, st, "pending",
                                     st.last_burn, now)
                elif st.state == "pending":
                    if fire:
                        self._transition(obj, track, st, "firing",
                                         st.last_burn, now)
                    elif not pend:
                        self._transition(obj, track, st, "ok",
                                         st.last_burn, now)
                elif st.state == "firing" and not fire:
                    self._transition(obj, track, st, "ok",
                                     st.last_burn, now)

    def _loop(self) -> None:
        while not self._stop.wait(timeout=self.eval_s):
            try:
                self.evaluate_once()
            except Exception:
                pass  # the pager must never take the server down

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop = threading.Event()
        t = threading.Thread(target=self._loop, daemon=True,
                             name="tpurpc-slo")
        self._thread = t
        t.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2)
        self._thread = None

    # -- export ---------------------------------------------------------------

    def firing(self) -> List[dict]:
        out = []
        for obj in self.objectives():
            for track, st in obj.tracks.items():
                if st.state == "firing":
                    out.append({"objective": obj.name, "track": track,
                                "method": obj.method,
                                "burn_fast": st.last_burn[0],
                                "burn_slow": st.last_burn[1],
                                "since_ns": st.since_ns})
        return out

    def doc(self) -> dict:
        objs = []
        for obj in self.objectives():
            tracks = {}
            for track, st in obj.tracks.items():
                tracks[track] = {
                    "state": st.state,
                    "budget": obj._budget(track),
                    "burn_fast": st.last_burn[0],
                    "burn_slow": st.last_burn[1],
                    "since_ns": st.since_ns,
                    "fired": st.fired,
                }
            objs.append({
                "name": obj.name,
                "method": obj.method,
                "target_pct": obj.target_pct,
                "latency_ms": obj.latency_ms,
                "latency_target_pct": obj.latency_target_pct,
                "shed_target_pct": obj.shed_target_pct,
                "ttft_ms": obj.ttft_ms,
                "itl_ms": obj.itl_ms,
                "slo_class": obj.slo_class,
                "series": obj.series,
                "windows": [list(w) for w in obj.windows],
                "tracks": tracks,
            })
        with self._lock:
            history = list(self._history)
        return {"objectives": objs, "history": history,
                "eval_s": self.eval_s,
                "firing": self.firing(),
                "running": self._thread is not None
                and self._thread.is_alive()}


# -- process-wide instance -----------------------------------------------------

_instance: Optional[SloEvaluator] = None
_instance_lock = threading.Lock()


def get() -> SloEvaluator:
    global _instance
    if _instance is None:
        with _instance_lock:
            if _instance is None:
                _instance = SloEvaluator()
    return _instance


def declare(name: str, **kwargs) -> SloObjective:
    """Declare (or replace) one objective and make sure the evaluator and
    its tsdb substrate are running. See :class:`SloObjective`."""
    obj = get().declare(SloObjective(name, **kwargs))
    ensure_started()
    return obj


def objectives() -> List[SloObjective]:
    return get().objectives()


def firing() -> List[dict]:
    ev = _instance
    return ev.firing() if ev is not None else []


def ensure_started() -> Optional[SloEvaluator]:
    """Start the evaluator iff objectives exist (idempotent). Also starts
    the tsdb sampler — burn rates integrate over its history."""
    ev = get()
    if not ev.objectives():
        return None
    from tpurpc.obs import tsdb as _tsdb_mod

    _tsdb_mod.ensure_started()
    ev.start()
    return ev


def slo_doc() -> dict:
    """``GET /debug/slo`` body."""
    return get().doc()


def health_lines() -> List[str]:
    """One ``slo`` line per non-ok (objective, track) for /healthz —
    scrape.py appends these under the same ``sys.modules`` gate the kv
    and gen lines use, so processes without an SLO plane keep their
    exact old bodies."""
    out = []
    ev = _instance
    if ev is None:
        return out
    for obj in ev.objectives():
        for track, st in obj.tracks.items():
            if st.state != "ok":
                out.append(
                    f"slo {obj.name}: state={st.state} track={track} "
                    f"burn={st.last_burn[0]:.1f}x/{st.last_burn[1]:.1f}x")
    return sorted(out)


def reset() -> None:
    """Test isolation: stop the evaluator and forget every objective."""
    global _instance
    ev = _instance
    if ev is not None:
        ev.stop()
    _instance = None


def postfork_reset() -> None:
    """Fresh evaluator in a forked shard worker (the inherited thread did
    not survive the fork; objectives re-declare in the worker's build)."""
    global _instance, _instance_lock
    _instance_lock = threading.Lock()
    _instance = None
