"""tpurpc-lens stage-tagged sampling profiler: where the cycles go, by stage.

A background sampler walks every thread's Python stack
(``sys._current_frames``, default ~50 Hz, ``TPURPC_LENS_HZ``) and maps each
stack to a pipeline *stage* via a declared frame-marker registry: hot
modules register ``(file basename, function name) → stage`` pairs as
module-level constants (the ``stage`` lint rule keeps the registrations
static), and a sample's stage is the FIRST marker hit walking the stack
innermost→outermost — the most specific stage wins, and a thread parked in
stdlib wait primitives is attributed to whichever tpurpc frame parked it.

The stage vocabulary extends the one the PR 5 watchdog already names
(:data:`STAGES`): ring write/read, pair send, h2 framing, codec, hbm
placement, batcher, device dispatch, server dispatch, poller wait, wire,
scrape, idle. A stack that matches no marker but contains tpurpc frames
counts as ``unattributed`` (the acceptance bar keeps it under 20% under
load); a stack with no tpurpc frames at all (interpreter housekeeping,
user threads) counts as ``other`` and is excluded from the attribution
denominator — it is not this framework's CPU time to explain.

Exports:

* per-stage sample shares (``snapshot()``, ``GET /debug/profile``),
  merged across shard workers by the PR 7 fan-out with ``shard`` tags;
* collapsed-stack (flamegraph.pl / speedscope ``collapsed``) text
  (``collapsed_text()``, ``GET /debug/profile?collapsed=1``);
* a bounded ring of recent raw samples ``(t_ns, tid, stage)`` that the
  timeline tool (``python -m tpurpc.tools.timeline``) renders as per-thread
  CPU lanes under the span tree (``?samples=1``).

Cost model: one ``sys._current_frames()`` dict per tick plus a bounded
(≤48-frame) walk per thread — at 50 Hz and a dozen threads this is a few
hundred microseconds per second of wall time; ``lens_overhead_pct`` in
bench.py holds the whole lens plane (profiler at default Hz included)
under the same <3% gate the rest of the always-on telemetry carries.
``TPURPC_LENS=0`` disables the sampler entirely.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

__all__ = [
    "STAGES", "DEFAULT_HZ", "register_stages", "markers", "StageProfiler",
    "get", "ensure_started", "stop", "snapshot", "collapsed_text",
    "postfork_reset",
]

#: canonical stage vocabulary (superset of the watchdog's stall stages on
#: the CPU side). Append-only: names land in scrapes and bench artifacts.
STAGES = (
    "ring-write", "ring-read", "pair-send", "h2-framing", "codec",
    "hbm-place", "batcher", "device-dispatch", "dispatch", "poller-wait",
    "wire", "scrape", "idle",
)

DEFAULT_HZ = 50.0

#: the frame-marker registry: (file basename, function name) -> stage.
#: Mutated only by register_stages at import time; read racily by the
#: sampler (a plain dict read — worst case one sample attributes late).
_MARKERS: Dict[Tuple[str, str], str] = {}

#: markers for stacks that are pure infrastructure parking — registered
#: here because the frames live in the stdlib, not in a tpurpc module
_SELF_STAGES = {
    "_loop": "idle",            # this module's own sampler thread
}

_TPURPC_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def register_stages(filename: str, mapping: Dict[str, str]) -> None:
    """Declare frame markers for one module: ``mapping`` is
    ``{function_name: stage}``, ``filename`` is the module's ``__file__``
    (or a bare basename for stdlib files). Modules call this ONCE at import
    with a module-level constant dict — the ``stage`` lint rule enforces
    the no-dynamic-strings contract."""
    base = os.path.basename(filename)
    for fn, stage in mapping.items():
        _MARKERS[(base, fn)] = stage


def markers() -> Dict[Tuple[str, str], str]:
    return dict(_MARKERS)


register_stages(__file__, _SELF_STAGES)
#: stdlib parking spots for threads this package owns (scrape listener,
#: thread-pool idlers): basename-keyed like every other marker
register_stages("socketserver.py", {"serve_forever": "idle",
                                    "service_actions": "idle"})
register_stages("threading.py", {"_bootstrap": "idle"})


def _default_hz() -> float:
    raw = os.environ.get("TPURPC_LENS_HZ", "")
    try:
        return max(1.0, min(250.0, float(raw))) if raw else DEFAULT_HZ
    except ValueError:
        return DEFAULT_HZ


_MAX_WALK = 48        # frames examined per thread per sample
_MAX_STACKS = 2048    # distinct collapsed stacks kept (overflow -> "(other)")
_RECENT = 4096        # raw (t_ns, tid, stage) samples kept for the timeline


class StageProfiler:
    """The sampler. One instance per process (:func:`get`); tests may build
    private ones and drive :meth:`sample_once` deterministically."""

    def __init__(self, hz: Optional[float] = None):
        self.hz = hz if hz is not None else _default_hz()
        self.samples = 0           # thread-samples taken (threads x ticks)
        self.ticks = 0
        self.stages: Dict[str, int] = {}
        self._stacks: Dict[str, int] = {}
        self.recent: "deque" = deque(maxlen=_RECENT)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._lock = threading.Lock()   # start/stop only; sampling is free
        self._names: Dict[int, str] = {}
        self._names_stamp = 0.0
        self.started_ns = 0

    # -- classification ------------------------------------------------------

    @staticmethod
    def classify(frame) -> Tuple[str, List[str]]:
        """(stage, collapsed-stack leaf-last) for one thread's innermost
        frame. Walks innermost→outermost; first marker wins. The collapsed
        stack keeps tpurpc + marker frames only, outermost first."""
        stage = None
        parts: List[str] = []
        f = frame
        depth = 0
        saw_tpurpc = False
        while f is not None and depth < _MAX_WALK:
            code = f.f_code
            base = os.path.basename(code.co_filename)
            key = (base, code.co_name)
            hit = _MARKERS.get(key)
            if hit is not None and stage is None:
                stage = hit
            in_tree = code.co_filename.startswith(_TPURPC_DIR)
            saw_tpurpc = saw_tpurpc or in_tree
            if in_tree or hit is not None:
                parts.append(f"{base[:-3] if base.endswith('.py') else base}"
                             f":{code.co_name}")
            f = f.f_back
            depth += 1
        if stage is None:
            stage = "unattributed" if saw_tpurpc else "other"
        parts.reverse()
        return stage, parts

    # -- sampling ------------------------------------------------------------

    def sample_once(self, frames: Optional[dict] = None,
                    now_ns: Optional[int] = None) -> None:
        """One tick: classify every live thread. ``frames`` injectable for
        deterministic tests.

        Lifetime discipline: ``sys._current_frames()`` includes THIS
        thread's own frame — i.e. ``sample_once`` itself — and the dict is
        a local of that very frame, a reference cycle only the gc can
        break. Left in place, the cycle keeps every sampled frame (and its
        locals — live memoryview exports over data-plane buffers!) pinned
        until the next collection, which surfaces as BufferError on
        bytearray resizes far away. Popping the self entry breaks the
        cycle, so the whole dict frees by refcount the moment this
        function returns; the ``finally`` clear bounds the hold to one
        walk even if the dict was injected."""
        own = False
        if frames is None:
            frames = sys._current_frames()
            own = True
        me = threading.get_ident()
        frames.pop(me, None)  # break the frame→dict→frame self-cycle
        now = now_ns if now_ns is not None else time.monotonic_ns()
        self.ticks += 1
        try:
            for tid, frame in frames.items():
                stage, parts = self.classify(frame)
                self.samples += 1
                self.stages[stage] = self.stages.get(stage, 0) + 1
                if parts:
                    key = ";".join(parts)
                    if key in self._stacks or len(self._stacks) < _MAX_STACKS:
                        self._stacks[key] = self._stacks.get(key, 0) + 1
                    else:
                        self._stacks["(other)"] = \
                            self._stacks.get("(other)", 0) + 1
                self.recent.append((now, tid, stage))
        finally:
            if own:
                frames.clear()  # drop every sampled-frame ref NOW

    def _refresh_names(self) -> None:
        now = time.monotonic()
        if now - self._names_stamp < 1.0:
            return
        self._names_stamp = now
        try:
            self._names = {t.ident: t.name for t in threading.enumerate()
                           if t.ident is not None}
        except RuntimeError:
            pass

    def _loop(self) -> None:
        period = 1.0 / self.hz
        while not self._stop.wait(period):
            try:
                self.sample_once()
                self._refresh_names()
            except Exception:
                pass  # the profiler must never take anything down

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "StageProfiler":
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            self.started_ns = time.monotonic_ns()
            t = threading.Thread(target=self._loop, daemon=True,
                                 name="tpurpc-lens-sampler")
            self._thread = t
            t.start()
        return self

    def stop(self) -> None:
        with self._lock:
            self._stop.set()
            t = self._thread
            self._thread = None
        if t is not None:
            t.join(timeout=2)

    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def reset(self) -> None:
        self.samples = 0
        self.ticks = 0
        self.stages = {}
        self._stacks = {}
        self.recent.clear()

    # -- export --------------------------------------------------------------

    def snapshot(self, top: int = 20, include_samples: bool = False) -> dict:
        stages = dict(self.stages)
        other = stages.get("other", 0)
        unatt = stages.get("unattributed", 0)
        denom = self.samples - other
        shares = {s: round(n / denom * 100, 1) if denom else 0.0
                  for s, n in stages.items() if s != "other"}
        out = {
            "hz": self.hz,
            "running": self.running(),
            "ticks": self.ticks,
            "samples": self.samples,
            "stages": stages,
            "stage_pct": shares,
            "attributed_pct": (round((denom - unatt) / denom * 100, 1)
                               if denom else 0.0),
            "top_stacks": sorted(self._stacks.items(),
                                 key=lambda kv: -kv[1])[:top],
        }
        from tpurpc.obs import shard as _shard

        if _shard.shard_id() >= 0:
            out["shard"] = _shard.shard_id()
        if include_samples:
            self._refresh_names()
            out["recent"] = [{"t_ns": t, "tid": tid, "stage": s,
                              "thread": self._names.get(tid, "")}
                             for t, tid, s in list(self.recent)]
        return out

    def collapsed_text(self) -> str:
        """flamegraph.pl-compatible collapsed stacks: ``a;b;c count``."""
        lines = [f"{stack} {n}"
                 for stack, n in sorted(self._stacks.items(),
                                        key=lambda kv: -kv[1])]
        return "\n".join(lines) + ("\n" if lines else "")


_instance: Optional[StageProfiler] = None
_instance_lock = threading.Lock()


def get() -> StageProfiler:
    global _instance
    if _instance is None:
        with _instance_lock:
            if _instance is None:
                _instance = StageProfiler()
    return _instance


def ensure_started() -> bool:
    """Start the continuous sampler if the lens plane is enabled; the call
    every entry point makes (Server.start, the /debug/profile route, the
    smoke tools). Idempotent, False when TPURPC_LENS=0."""
    from tpurpc.obs import lens as _lens

    if not _lens.enabled():
        return False
    get().start()
    return True


def stop() -> None:
    if _instance is not None:
        _instance.stop()


def snapshot(top: int = 20, include_samples: bool = False) -> dict:
    return get().snapshot(top=top, include_samples=include_samples)


def collapsed_text() -> str:
    return get().collapsed_text()


def postfork_reset() -> None:
    """Fresh profiler in a forked shard worker: the inherited instance's
    sampler thread did not survive the fork and its aggregates describe the
    supervisor. (Registered markers are import-time constants and carry
    over untouched.)"""
    global _instance, _instance_lock
    _instance_lock = threading.Lock()
    _instance = None
