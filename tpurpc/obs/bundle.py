"""tpurpc-argus automatic evidence capture: the self-contained postmortem.

When an SLO alert fires or the stall watchdog trips, the evidence an
operator needs is ALREADY in this process — the flight ring, the tail
traces, the collapsed profile, the waterfall, the tsdb window bracketing
the event — but it is all volatile: by the time a human looks, the rings
have wrapped and the history has rolled off. A bundle freezes all of it
to disk at the moment of degradation:

    <root>/bundle-<utcstamp>-<trigger>-<pid>/
        flight-<pid>.json   flight dump, TPURPC_FLIGHT_DUMP format — a
                            plain JSON event list, so
                            `python -m tpurpc.analysis protocol --flight
                            <bundle-dir>` replays it UNMODIFIED against
                            the declared machines
        traces.json         chrome-trace export of the span buffer (the
                            tail-captured trees of the pathological calls)
        profile.txt         collapsed stacks (flamegraph.pl input)
        waterfall.json      per-hop byte-flow table
        history.json        tsdb series windows bracketing the event
        slo.json            objective/track states + transition history
        stalls.json         watchdog snapshot (active + history)
        diagnosis.json      tpurpc-oracle ranked causal hypotheses at
                            capture time (the same report
                            `python -m tpurpc.tools.diagnose <dir>`
                            recomputes offline)
        meta.json           trigger, detail, stamps, cap accounting

Every sibling file is a JSON *object* (or plain text), so a directory
walk that treats each ``*.json`` as a flight dump (``analysis.protocol
.check_dump``) sees events only in ``flight-*.json`` — the bundle IS a
valid ``--flight`` argument.

Discipline — a flapping alert must not fill the disk:

* **rate limit**: at most one bundle per ``min_interval_s`` (default
  30 s) per trigger key, and a global floor between any two captures;
* **caps**: at most ``max_bundles`` directories / ``max_total_bytes``
  under the root — oldest bundles are deleted first (the newest evidence
  is the evidence);
* **bounded content**: the flight ring is fixed-size by construction,
  traces/history are tail-bounded here.

Arming: :func:`enable` (or ``TPURPC_BUNDLE_DIR`` via
:func:`maybe_enable_from_env`, which ``Server.start`` calls) registers a
watchdog trip hook — and since a firing SLO routes through
``watchdog.external_trip``, one hook covers both triggers. Rendering:
``python -m tpurpc.tools.bundle <dir>``.
"""

from __future__ import annotations

import json
import os
import shutil
import threading
import time
from typing import Dict, List, Optional

from tpurpc.obs import flight as _flight
from tpurpc.obs import metrics as _metrics

__all__ = [
    "BundleWriter", "enable", "disable", "enabled", "get",
    "maybe_enable_from_env", "capture", "TRIGGER_CODES", "list_bundles",
]

#: flight-event a1 values naming the capture trigger (append-only)
TRIGGER_CODES = {"slo": 0, "watchdog": 1, "manual": 2}

_BUNDLES_WRITTEN = _metrics.counter("bundles_written")
_BUNDLES_RATELIMITED = _metrics.counter("bundles_ratelimited")

#: interned once: the bundle plane's flight entity
_BUNDLE_TAG = _flight.tag_for("bundle")


class BundleWriter:
    def __init__(self, root: str, max_bundles: int = 8,
                 max_total_bytes: int = 64 << 20,
                 min_interval_s: Optional[float] = None):
        self.root = root
        self.max_bundles = max(1, int(max_bundles))
        self.max_total_bytes = int(max_total_bytes)
        if min_interval_s is None:
            raw = os.environ.get("TPURPC_BUNDLE_MIN_INTERVAL_S", "")
            try:
                min_interval_s = float(raw) if raw else 30.0
            except ValueError:
                min_interval_s = 30.0
        self.min_interval_s = min_interval_s
        self._lock = threading.Lock()
        self._last_by_key: Dict[str, float] = {}
        self._last_any = 0.0
        self._seq = 0

    # -- rate limiting --------------------------------------------------------

    def _admit(self, key: str) -> bool:
        now = time.monotonic()
        with self._lock:
            last = self._last_by_key.get(key, 0.0)
            if now - last < self.min_interval_s:
                return False
            # global floor: two DIFFERENT alerts in the same second are
            # one incident — half the per-key interval apart is enough
            if now - self._last_any < self.min_interval_s / 2:
                return False
            self._last_by_key[key] = now
            self._last_any = now
            self._seq += 1
            return True

    # -- capture --------------------------------------------------------------

    def capture(self, trigger: str, detail: str = "",
                key: Optional[str] = None) -> Optional[str]:
        """Write one bundle; returns its directory path, or None when
        rate-limited or on any failure (evidence capture must never take
        down the thing it is documenting)."""
        key = key or trigger
        if not self._admit(key):
            _BUNDLES_RATELIMITED.inc()
            return None
        try:
            return self._write(trigger, detail)
        except Exception:
            return None

    def _write(self, trigger: str, detail: str) -> str:
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
        pid = os.getpid()
        name = f"bundle-{stamp}-{trigger}-{pid}-{self._seq}"
        path = os.path.join(self.root, name)
        os.makedirs(path, exist_ok=True)

        # 1) the flight ring, TPURPC_FLIGHT_DUMP format (a plain list)
        events = _flight.RECORDER.snapshot()
        self._dump(path, f"flight-{pid}.json", events, raw_list=True)

        # 2) tail traces (chrome-trace doc — a JSON object)
        try:
            from tpurpc.obs import tracing as _tracing

            self._dump(path, "traces.json", _tracing.chrome_trace())
        except Exception:
            pass
        # 3) collapsed profile
        try:
            from tpurpc.obs import profiler as _profiler

            with open(os.path.join(path, "profile.txt"), "w",
                      encoding="utf-8") as f:
                f.write(_profiler.collapsed_text())
        except Exception:
            pass
        # 4) the byte-flow waterfall
        try:
            from tpurpc.obs import lens as _lens

            self._dump(path, "waterfall.json", _lens.waterfall())
        except Exception:
            pass
        # 5) the tsdb window bracketing the event: every series' fine
        #    window (bounded: fine slots x series cap, all floats)
        try:
            from tpurpc.obs import tsdb as _tsdb

            db = _tsdb.get()
            span = db.fine_window_s
            kinds = db.series()
            hist = {"window_s": span, "grain_s": db.fine_s, "series": {},
                    # tpurpc-oracle: series kinds ride along so the
                    # offline replay applies the same reset-aware delta
                    # transform the live change-point scan uses
                    "kinds": {}}
            for s in sorted(kinds):
                pts = db.window(s, span)
                if pts:
                    hist["series"][s] = [[t, v] for t, v in pts]
                    hist["kinds"][s] = kinds[s]
            self._dump(path, "history.json", hist)
        except Exception:
            pass
        # 6) SLO + watchdog state
        try:
            from tpurpc.obs import slo as _slo

            self._dump(path, "slo.json", _slo.slo_doc())
        except Exception:
            pass
        try:
            from tpurpc.obs import watchdog as _watchdog

            self._dump(path, "stalls.json", _watchdog.get().snapshot())
        except Exception:
            pass
        # 7) tpurpc-oracle: the diagnosis AT CAPTURE TIME — the ranked
        #    hypotheses for the trip that caused this bundle (a JSON
        #    object, no top-level "events": protocol walks stay clean)
        try:
            from tpurpc.obs import diagnose as _diagnose

            if _diagnose.enabled():
                self._dump(path, "diagnosis.json",
                           _diagnose.diagnose(_diagnose.LivePlanes()))
        except Exception:
            pass
        meta = {
            "trigger": trigger,
            "detail": detail,
            "pid": pid,
            "t_wall": time.time(),  # tpr: allow(wallclock)
            "t_mono_ns": time.monotonic_ns(),
            "seq": self._seq,
            # NB: not "events" — a directory protocol walk reads any
            # top-level "events" key as a flight stream
            "n_events": len(events),
            "tool": "tpurpc.obs.bundle",
        }
        self._dump(path, "meta.json", meta)

        self._enforce_caps(keep=name)
        _BUNDLES_WRITTEN.inc()
        trig = TRIGGER_CODES.get(trigger, 2)
        seq = self._seq
        _flight.emit(_flight.BUNDLE_WRITTEN, _BUNDLE_TAG, trig, seq)
        return path

    @staticmethod
    def _dump(path: str, fname: str, obj, raw_list: bool = False) -> None:
        assert raw_list or isinstance(obj, dict), fname
        with open(os.path.join(path, fname), "w", encoding="utf-8") as f:
            json.dump(obj, f)

    # -- caps -----------------------------------------------------------------

    def _bundles(self) -> List[str]:
        try:
            names = [n for n in os.listdir(self.root)
                     if n.startswith("bundle-")
                     and os.path.isdir(os.path.join(self.root, n))]
        except OSError:
            return []
        return sorted(names)  # utc stamp prefix: lexical == chronological

    def _enforce_caps(self, keep: str) -> None:
        names = self._bundles()
        while len(names) > self.max_bundles:
            victim = names.pop(0)
            if victim == keep and names:
                victim = names.pop(0)
            shutil.rmtree(os.path.join(self.root, victim),
                          ignore_errors=True)
        while len(names) > 1 and self._total_bytes() > self.max_total_bytes:
            victim = names.pop(0)
            if victim == keep:
                continue
            shutil.rmtree(os.path.join(self.root, victim),
                          ignore_errors=True)

    def _total_bytes(self) -> int:
        total = 0
        for dirpath, _dirs, files in os.walk(self.root):
            for fn in files:
                try:
                    total += os.path.getsize(os.path.join(dirpath, fn))
                except OSError:
                    continue
        return total


# -- process-wide arming -------------------------------------------------------

_writer: Optional[BundleWriter] = None
_writer_lock = threading.Lock()


def _on_trip(diag: dict) -> None:
    """The watchdog trip hook: one capture per trip, keyed by stage+method
    so a flapping alert (same page over and over) is one bundle per
    rate-limit interval while a DIFFERENT page still captures."""
    w = _writer
    if w is None:
        return
    trigger = "slo" if diag.get("stage") == "slo" else "watchdog"
    key = f"{diag.get('stage')}:{diag.get('method')}"
    w.capture(trigger,
              detail=f"{diag.get('method')} stage={diag.get('stage')}: "
                     f"{diag.get('detail', '')}",
              key=key)


def enable(root: str, **kwargs) -> BundleWriter:
    """Arm automatic capture into ``root`` (idempotent per path)."""
    global _writer
    from tpurpc.obs import watchdog as _watchdog

    with _writer_lock:
        if _writer is None or _writer.root != root:
            os.makedirs(root, exist_ok=True)
            _writer = BundleWriter(root, **kwargs)
        _watchdog.add_trip_hook(_on_trip)
        return _writer


def disable() -> None:
    global _writer
    from tpurpc.obs import watchdog as _watchdog

    with _writer_lock:
        _watchdog.remove_trip_hook(_on_trip)
        _writer = None


def enabled() -> bool:
    return _writer is not None


def get() -> Optional[BundleWriter]:
    return _writer


def maybe_enable_from_env() -> Optional[BundleWriter]:
    """``TPURPC_BUNDLE_DIR=<dir>`` arms capture; ``Server.start`` calls
    this so any serving process opts in by environment alone."""
    root = os.environ.get("TPURPC_BUNDLE_DIR", "")
    if not root:
        return None
    return enable(root)


def capture(trigger: str = "manual", detail: str = "") -> Optional[str]:
    """Manual capture through the armed writer (None when disarmed)."""
    w = _writer
    return w.capture(trigger, detail=detail) if w is not None else None


def list_bundles(root: str) -> List[str]:
    """Bundle directory names under ``root``, oldest first."""
    return BundleWriter(root)._bundles()
