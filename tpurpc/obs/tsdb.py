"""tpurpc-argus ring time-series store: bounded in-process metric history.

Every telemetry face before this one answers "what is happening right
now": ``/metrics`` is a point-in-time scrape, the flight ring holds the
last N *edges*, the watchdog reacts per call. The questions a fleet-scale
operator actually asks are over TIME — did p99 degrade ten minutes ago,
is this counter's rate trending down, how long has that gauge been
pinned — and arXiv:1804.01138's micro-benchmark critique applies to
telemetry too: point measurements hide trend regressions by
construction. The tsdb is the bounded answer:

* a background **sampler** snapshots the PR-4 registry on a fixed grain —
  counters as their raw cumulative values (``rate()`` differentiates at
  query time, reset-aware), histograms as their p50/p99 quantiles, fleet
  gauges as their scrape-time sum;
* samples land in **preallocated fixed-size rings** (``array('d')`` per
  series per tier), two downsampling tiers: a fine grain
  (``TPURPC_TSDB_FINE_S``, default 1 s) covering the recent window
  (default 5 min) and a coarse grain (``TPURPC_TSDB_COARSE_S``, default
  15 s) covering the long window (default 1 h). Coarse slots take every
  Nth fine sample (decimation — a quantile series' decimated sample is
  still a true observation, which max/mean rollups would not be);
* memory is **bounded by construction**: ``MAX_SERIES`` rings of fixed
  slot counts, preallocated at series registration — the steady-state
  sample path writes floats into existing arrays and allocates nothing
  (registry reads go through each metric's own lock-scoped accessors;
  new series allocate once, at first sight);
* queries — :meth:`Tsdb.window`, :meth:`Tsdb.rate`,
  :meth:`Tsdb.quantile_over_time` — pick the tier by requested span and
  are the substrate the SLO burn-rate evaluator (:mod:`tpurpc.obs.slo`)
  integrates over;
* served at ``GET /debug/history`` on the scrape plane
  (``?series=NAME&window_s=S`` for points, bare for the inventory), and
  reset per shard worker by :func:`postfork_reset` — a fork inherits the
  supervisor's history, which is not this worker's past.

:class:`ResetClamp` also lives here: monotonic-counter reset detection
shared by the shard scrape merge (a killed-and-restarted worker must not
step the merged series backwards) and the fleet collector
(:mod:`tpurpc.obs.collector`) — one definition of "this counter went
backwards, so its process restarted; continue from last-known + delta".
"""

from __future__ import annotations

import threading
import time
from array import array
from typing import Dict, List, Optional, Tuple

from tpurpc.obs import metrics as _metrics
from tpurpc.obs import profiler as _obs_profiler

__all__ = [
    "Tsdb", "ResetClamp", "get", "ensure_started", "enabled",
    "postfork_reset", "history_doc",
]

#: the sampler thread parked between ticks is infrastructure idle time
_LENS_STAGES = {"_loop": "idle", "sample_once": "idle"}
_obs_profiler.register_stages(__file__, _LENS_STAGES)

#: hard cap on tracked series — rings are preallocated per series, so this
#: bounds resident memory no matter how hostile the metric cardinality
MAX_SERIES = 768

#: self-accounting: sample ticks + series the cap refused
_TSDB_SAMPLES = _metrics.counter("tsdb_samples")
_TSDB_SERIES_DROPPED = _metrics.counter("tsdb_series_dropped")


class ResetClamp:
    """Monotonic-counter reset detection across scrapes of a restartable
    source (a shard worker, a fleet member). ``clamp(key, value)`` returns
    a NEVER-DECREASING view of the counter: when a fresh reading drops
    below the last one (the restart signature — counters only reset to
    zero by dying), the last-known value becomes a standing offset and the
    new reading counts as the delta since restart. Multiple restarts
    accumulate. ``resets`` counts detections (the merge paths export it)."""

    def __init__(self):
        self._last: Dict[object, float] = {}
        self._offset: Dict[object, float] = {}
        self.resets = 0

    def clamp(self, key, value: float) -> float:
        last = self._last.get(key)
        if last is not None and value < last:
            self._offset[key] = self._offset.get(key, 0.0) + last
            self.resets += 1
        self._last[key] = value
        return self._offset.get(key, 0.0) + value

    def forget(self, key_prefix=None) -> None:
        """Drop tracked state (all of it, or keys whose first tuple element
        matches ``key_prefix``) — a member deliberately removed from a
        fleet must not pin its offsets forever."""
        if key_prefix is None:
            self._last.clear()
            self._offset.clear()
            return
        for d in (self._last, self._offset):
            for k in [k for k in d
                      if isinstance(k, tuple) and k and k[0] == key_prefix]:
                d.pop(k, None)


class _Tier:
    """One downsampling tier: per-series preallocated value rings plus ONE
    shared stamp ring (every series in a tier is sampled on the same
    tick). Slot ``n % slots`` holds tick ``n``; NaN marks never-written
    slots and series registered after the tier started."""

    __slots__ = ("grain_s", "slots", "stamps", "values", "n")

    def __init__(self, grain_s: float, slots: int):
        self.grain_s = grain_s
        self.slots = max(8, int(slots))
        self.stamps = array("q", [0] * self.slots)
        self.values: Dict[str, array] = {}
        self.n = 0

    def add_series(self, name: str) -> None:
        if name not in self.values:
            self.values[name] = array("d", [float("nan")] * self.slots)

    def record(self, t_ns: int, readings: Dict[str, float]) -> None:
        slot = self.n % self.slots
        self.stamps[slot] = t_ns
        for name, ring in self.values.items():
            v = readings.get(name)
            ring[slot] = v if v is not None else float("nan")
        self.n += 1

    def points(self, name: str, since_ns: int) -> List[Tuple[int, float]]:
        ring = self.values.get(name)
        if ring is None or self.n == 0:
            return []
        out: List[Tuple[int, float]] = []
        first = max(0, self.n - self.slots)
        for i in range(first, self.n):
            slot = i % self.slots
            t = self.stamps[slot]
            v = ring[slot]
            if t >= since_ns and v == v:  # NaN-skip
                out.append((t, v))
        return out

    def resident_bytes(self) -> int:
        per = self.slots * 8
        return per * (1 + len(self.values))


def _env_float(name: str, default: float) -> float:
    import os

    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


class Tsdb:
    """The two-tier store + its sampler. One process-wide instance
    (:func:`get`); tests build private ones and drive
    :meth:`sample_once` deterministically."""

    def __init__(self, fine_s: Optional[float] = None,
                 fine_window_s: Optional[float] = None,
                 coarse_s: Optional[float] = None,
                 coarse_window_s: Optional[float] = None,
                 registry: Optional[_metrics.Registry] = None):
        self.fine_s = fine_s if fine_s is not None else _env_float(
            "TPURPC_TSDB_FINE_S", 1.0)
        fine_window = fine_window_s if fine_window_s is not None else \
            _env_float("TPURPC_TSDB_FINE_WINDOW_S", 300.0)
        self.coarse_s = coarse_s if coarse_s is not None else _env_float(
            "TPURPC_TSDB_COARSE_S", 15.0)
        coarse_window = coarse_window_s if coarse_window_s is not None else \
            _env_float("TPURPC_TSDB_COARSE_WINDOW_S", 3600.0)
        self.fine_s = max(0.01, self.fine_s)
        self.coarse_s = max(self.fine_s, self.coarse_s)
        self._registry = registry or _metrics.registry()
        self._fine = _Tier(self.fine_s, round(fine_window / self.fine_s))
        self._coarse = _Tier(self.coarse_s,
                             round(coarse_window / self.coarse_s))
        #: every Nth fine tick lands in the coarse tier too
        self._decim = max(1, round(self.coarse_s / self.fine_s))
        self._kinds: Dict[str, str] = {}  # series -> counter|gauge|quantile
        self._lock = threading.Lock()
        self._readings: Dict[str, float] = {}  # reused tick scratch
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- sampling -------------------------------------------------------------

    def _register(self, name: str, kind: str) -> bool:
        if name in self._kinds:
            return True
        if len(self._kinds) >= MAX_SERIES:
            _TSDB_SERIES_DROPPED.inc()
            return False
        self._kinds[name] = kind
        self._fine.add_series(name)
        self._coarse.add_series(name)
        return True

    def _read_registry(self) -> Dict[str, float]:
        """One pass over the registry into the reused readings dict.
        Counters/gauges are attribute reads; histograms pay their own
        lock for two quantiles; labeled families flatten to one series
        per child (cardinality already bounded by the family)."""
        readings = self._readings
        readings.clear()
        for name, m in self._registry.metrics().items():
            if isinstance(m, _metrics.Counter):
                if self._register(name, "counter"):
                    readings[name] = float(m.value)
            elif isinstance(m, _metrics.Gauge):
                if self._register(name, "gauge"):
                    readings[name] = float(m.value)
            elif isinstance(m, _metrics.Histogram):
                for q, suffix in ((0.5, ":p50"), (0.99, ":p99")):
                    if self._register(name + suffix, "quantile"):
                        readings[name + suffix] = float(m.percentile(q))
                if self._register(name + ":count", "counter"):
                    readings[name + ":count"] = float(m.snapshot()["count"])
            elif isinstance(m, _metrics.LabeledCounter):
                for key, v in m.snapshot().items():
                    child = name + "{" + ",".join(key) + "}"
                    if self._register(child, "counter"):
                        readings[child] = float(v)
            elif isinstance(m, _metrics.FleetGauge):
                if self._register(name, "gauge"):
                    readings[name] = m.collect()[0]
        # the watchdog's ROLLING per-method p99s (µs): the latency signal
        # SLO burn rates threshold — a rolling window recovers when a
        # degradation ends, which the cumulative histograms never do.
        # (Process-wide stores only: a test's private registry stays pure.)
        if self._registry is not _metrics.registry():
            return readings
        try:
            from tpurpc.obs import watchdog as _watchdog

            wd = _watchdog.get()
            worst = None
            for method, p99 in wd.method_p99s().items():
                sname = "watchdog_p99_us{" + method + "}"
                if self._register(sname, "gauge"):
                    readings[sname] = p99 / 1e3
                if worst is None or p99 > worst:
                    worst = p99
            if worst is not None and self._register(
                    "watchdog_rolling_p99_us", "gauge"):
                readings["watchdog_rolling_p99_us"] = worst / 1e3
        except Exception:
            pass
        # tpurpc-odyssey (ISSUE 15): per-SLO-class ROLLING token-latency
        # p99s (gen_itl_p99_us{class} / gen_ttft_p99_us{class}) — the
        # watchdog_p99 move applied to tokens, so the new ITL/TTFT SLO
        # track kinds can fire AND resolve. sys.modules-gated: processes
        # that never served generation sample nothing new.
        try:
            import sys

            ody = sys.modules.get("tpurpc.obs.odyssey")
            if ody is not None and ody.ACTIVE:
                for sname, v in ody.rolling_series().items():
                    if self._register(sname, "gauge"):
                        readings[sname] = v
        except Exception:
            pass
        return readings

    def sample_once(self, now_ns: Optional[int] = None) -> None:
        """One sampler tick (tests drive this directly with synthetic
        stamps; the daemon loop calls it on the fine grain)."""
        now = now_ns if now_ns is not None else time.monotonic_ns()
        # tpurpc-xray: refresh the native_* mirror series from the C
        # core's shm table before the registry pass, so history picks up
        # native-plane counters at the same grain as everything else.
        # (Process-wide registry only — a test's private registry stays
        # free of ambient native state.)
        if self._registry is _metrics.registry():
            try:
                from tpurpc.obs import native_obs as _nobs

                _nobs.sync_registry()
            except Exception:
                pass
        with self._lock:
            readings = self._read_registry()
            self._fine.record(now, readings)
            if (self._fine.n - 1) % self._decim == 0:
                self._coarse.record(now, readings)
        _TSDB_SAMPLES.inc()

    def _loop(self) -> None:
        while not self._stop.wait(timeout=self.fine_s):
            try:
                self.sample_once()
            except Exception:
                pass  # the historian must never take anything down

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop = threading.Event()
        t = threading.Thread(target=self._loop, daemon=True,
                             name="tpurpc-tsdb")
        self._thread = t
        t.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2)
        self._thread = None

    # -- queries --------------------------------------------------------------

    def series(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._kinds)

    @property
    def fine_window_s(self) -> float:
        return self._fine.grain_s * self._fine.slots

    @property
    def coarse_window_s(self) -> float:
        return self._coarse.grain_s * self._coarse.slots

    def _tier_for(self, window_s: float) -> _Tier:
        fine_span = self._fine.grain_s * self._fine.slots
        return self._fine if window_s <= fine_span else self._coarse

    def window(self, name: str, window_s: float,
               now_ns: Optional[int] = None) -> List[Tuple[int, float]]:
        """Time-ordered ``(t_ns, value)`` points for one series over the
        trailing window, from the tier whose span covers it."""
        now = now_ns if now_ns is not None else time.monotonic_ns()
        since = now - int(window_s * 1e9)
        with self._lock:
            return self._tier_for(window_s).points(name, since)

    def snapshot_windows(self, window_s: Optional[float] = None,
                         now_ns: Optional[int] = None
                         ) -> Dict[str, List[Tuple[int, float]]]:
        """tpurpc-oracle: every series' trailing window in ONE lock
        acquisition — the diagnosis engine's change-point scan needs a
        consistent cross-series view (per-series ``window()`` calls
        could straddle a sampler tick and skew onsets across series).
        Defaults to the fine window; empty series are omitted."""
        span = window_s if window_s is not None else self.fine_window_s
        now = now_ns if now_ns is not None else time.monotonic_ns()
        since = now - int(span * 1e9)
        out: Dict[str, List[Tuple[int, float]]] = {}
        with self._lock:
            tier = self._tier_for(span)
            for name in self._kinds:
                pts = tier.points(name, since)
                if pts:
                    out[name] = pts
        return out

    def rate(self, name: str, window_s: float,
             now_ns: Optional[int] = None) -> float:
        """Per-second rate of a cumulative series over the window: the sum
        of POSITIVE deltas (a negative delta is a counter reset — the
        restarted process re-counts from zero, so the post-reset value IS
        the missing delta) divided by the covered span."""
        pts = self.window(name, window_s, now_ns=now_ns)
        if len(pts) < 2:
            return 0.0
        total = 0.0
        prev = pts[0][1]
        for _t, v in pts[1:]:
            d = v - prev
            total += d if d >= 0 else v
            prev = v
        span_s = (pts[-1][0] - pts[0][0]) / 1e9
        return total / span_s if span_s > 0 else 0.0

    def delta(self, name: str, window_s: float,
              now_ns: Optional[int] = None) -> float:
        """Reset-aware cumulative increase over the window (rate × span,
        without dividing — what a budget integrator wants)."""
        pts = self.window(name, window_s, now_ns=now_ns)
        if len(pts) < 2:
            return 0.0
        total = 0.0
        prev = pts[0][1]
        for _t, v in pts[1:]:
            d = v - prev
            total += d if d >= 0 else v
            prev = v
        return total

    def quantile_over_time(self, name: str, q: float, window_s: float,
                           now_ns: Optional[int] = None) -> Optional[float]:
        """The q-quantile of the SAMPLED values over the window (each
        sample weighs equally — on a fixed grain that is time-weighting)."""
        pts = self.window(name, window_s, now_ns=now_ns)
        if not pts:
            return None
        vals = sorted(v for _t, v in pts)
        idx = min(len(vals) - 1, max(0, int(len(vals) * q)))
        return vals[idx]

    def over_threshold_fraction(self, name: str, threshold: float,
                                window_s: float,
                                now_ns: Optional[int] = None
                                ) -> Optional[float]:
        """Fraction of window samples strictly above ``threshold`` — the
        time-based "bad minutes" ratio latency SLOs burn against."""
        pts = self.window(name, window_s, now_ns=now_ns)
        if not pts:
            return None
        bad = sum(1 for _t, v in pts if v > threshold)
        return bad / len(pts)

    # -- export ---------------------------------------------------------------

    def resident_bytes(self) -> int:
        with self._lock:
            return self._fine.resident_bytes() + self._coarse.resident_bytes()

    def doc(self, series: Optional[str] = None,
            window_s: Optional[float] = None) -> dict:
        """The ``/debug/history`` body: the inventory (bare), or one
        series' points (``?series=``)."""
        out = {
            "fine": {"grain_s": self._fine.grain_s,
                     "slots": self._fine.slots, "samples": self._fine.n},
            "coarse": {"grain_s": self._coarse.grain_s,
                       "slots": self._coarse.slots,
                       "samples": self._coarse.n},
            "resident_bytes": self.resident_bytes(),
            "running": self._thread is not None and self._thread.is_alive(),
        }
        if series is None:
            out["series"] = sorted(self.series())
            return out
        w = window_s if window_s is not None else \
            self._fine.grain_s * self._fine.slots
        pts = self.window(series, w)
        out["series"] = series
        out["kind"] = self.series().get(series)
        out["window_s"] = w
        out["points"] = [[t, v] for t, v in pts]
        if self.series().get(series) == "counter":
            out["rate_per_s"] = round(self.rate(series, w), 3)
        return out


# -- process-wide instance -----------------------------------------------------

_instance: Optional[Tsdb] = None
_instance_lock = threading.Lock()


def enabled() -> bool:
    from tpurpc.utils.config import _env

    return (_env("TPURPC_TSDB") or "1").lower() not in ("0", "off", "false")


def get() -> Tsdb:
    global _instance
    if _instance is None:
        with _instance_lock:
            if _instance is None:
                _instance = Tsdb()
    return _instance


def ensure_started() -> Optional[Tsdb]:
    """Start the process-wide sampler (idempotent; ``TPURPC_TSDB=0``
    no-ops). :class:`tpurpc.rpc.server.Server` calls this at start, like
    the lens profiler."""
    if not enabled():
        return None
    db = get()
    db.start()
    return db


def history_doc(params: dict) -> dict:
    """``GET /debug/history`` rendering (scrape.py route hook)."""
    if not enabled():
        return {"enabled": False, "reason": "TPURPC_TSDB=0"}
    series = params.get("series") or None
    window_s = None
    raw = params.get("window_s")
    if raw:
        try:
            window_s = float(raw)
        except ValueError:
            window_s = None
    out = get().doc(series=series, window_s=window_s)
    out["enabled"] = True
    return out


def postfork_reset() -> None:
    """Fresh store in a forked shard worker: the inherited rings hold the
    supervisor's history (not this worker's past) and the inherited
    sampler thread did not survive the fork."""
    global _instance, _instance_lock
    _instance_lock = threading.Lock()
    _instance = None
