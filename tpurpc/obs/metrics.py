"""Always-on metrics registry: counters, gauges, histograms, fleet gauges.

Design constraints (ISSUE 4 tentpole):

* **Hot-path writes are plain-int, GIL-atomic bumps.** ``Counter.inc`` is a
  single attribute add — no lock, no dict lookup (instrumented modules cache
  the Counter object at import). CPython's GIL makes the read-modify-write
  of one bytecode-visible int effectively atomic for our purposes; a
  vanishingly rare lost increment under free-threading would skew a stat,
  never corrupt state — the trade the reference's ``gpr_atm_no_barrier``
  stats make too.
* **Histograms amortize.** The data-plane histograms record once per BATCH
  (drain, coalesced writev, dispatched fan-in batch), which is exactly the
  amortization the batching exists to buy; one lock per batch is noise.
* **State gauges cost the hot path NOTHING.** Ring head/tail/credits, lease
  occupancy, in-flight windows are attributes live objects already
  maintain; a :class:`FleetGauge` holds weak references to those objects
  and evaluates its function at SCRAPE time only.

This registry subsumes the ad-hoc counter/histogram dicts that grew in
``tpurpc/utils/stats.py`` during PR 1 (``counter_inc`` / ``batch_hist`` now
delegate here — one store, no parallel bookkeeping) and backs the copy
ledger's export. The Prometheus text face lives in
:mod:`tpurpc.obs.scrape`.
"""

from __future__ import annotations

import math
import threading
import weakref
from collections import defaultdict
from typing import Callable, Dict, Optional, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "FleetGauge", "LabeledCounter",
    "Registry", "registry", "counter", "gauge", "histogram", "fleet",
    "labeled_counter", "snapshot", "reset",
]


class Counter:
    """Monotonic counter. ``inc`` is the branch-free hot-path primitive."""

    __slots__ = ("name", "value")
    kind = "counter"

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n

    def snapshot(self) -> int:
        return self.value

    def reset(self) -> None:
        self.value = 0


class Gauge:
    """Last-write-wins instantaneous value (explicitly set, not sampled)."""

    __slots__ = ("name", "value")
    kind = "gauge"

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = v

    def inc(self, n: float = 1.0) -> None:
        self.value += n

    def dec(self, n: float = 1.0) -> None:
        self.value -= n

    def snapshot(self) -> float:
        return self.value

    def reset(self) -> None:
        self.value = 0.0


class Histogram:
    """Thread-safe histogram, two flavors:

    * ``kind="size"`` — EXACT counts for small integers (batch sizes,
      window depths): percentiles are precise below ``_EXACT_MAX``; larger
      values clamp into the top bucket. This is PR 1's ``BatchHist``
      folded into the registry.
    * ``kind="latency"`` — 64 log2 buckets over nanoseconds with
      within-bucket linear interpolation, so p50/p99 don't snap to
      power-of-two bucket bounds (the ``utils/stats._Hist`` defect this PR
      fixes, applied here from the start).
    """

    _EXACT_MAX = 4096

    __slots__ = ("name", "kind", "_lock", "_counts", "_buckets", "_total",
                 "_n", "_max")

    def __init__(self, name: str, kind: str = "size"):
        if kind not in ("size", "latency"):
            raise ValueError(f"unknown histogram kind {kind!r}")
        self.name = name
        self.kind = kind
        self._lock = threading.Lock()
        self._counts: Dict[int, int] = defaultdict(int)  # size flavor
        self._buckets = [0] * 64 if kind == "latency" else None
        self._total = 0
        self._n = 0
        self._max = 0

    def record(self, v: int) -> None:
        if v <= 0:
            return
        v = int(v)
        with self._lock:
            if self._buckets is None:
                self._counts[min(v, self._EXACT_MAX)] += 1
            else:
                self._buckets[min(63, v.bit_length())] += 1
            self._total += v
            self._n += 1
            if v > self._max:
                self._max = v

    def record_many(self, values) -> None:
        """Record a BATCH under one lock — the amortization this registry
        was designed around (PR 4's "histograms amortize"), for callers
        that accumulate per-item samples and flush per batch/lifetime
        (tpurpc-odyssey's per-sequence ITL flush)."""
        with self._lock:
            for v in values:
                if v <= 0:
                    continue
                v = int(v)
                if self._buckets is None:
                    self._counts[min(v, self._EXACT_MAX)] += 1
                else:
                    self._buckets[min(63, v.bit_length())] += 1
                self._total += v
                self._n += 1
                if v > self._max:
                    self._max = v

    # -- percentiles ---------------------------------------------------------

    def _percentile_locked(self, q: float) -> float:
        if self._n == 0:
            return 0.0
        target = math.ceil(self._n * q)
        if self._buckets is None:
            seen = 0
            for size in sorted(self._counts):
                seen += self._counts[size]
                if seen >= target:
                    return size
            return self._max
        seen = 0
        for i, n in enumerate(self._buckets):
            if not n:
                continue
            if seen + n >= target:
                # bucket i holds values with bit_length == i, i.e.
                # [2^(i-1), 2^i); interpolate linearly inside it
                lo = 0 if i == 0 else 1 << (i - 1)
                hi = 1 << i
                frac = (target - seen) / n
                return min(lo + frac * (hi - lo), float(self._max))
            seen += n
        return float(self._max)

    def percentile(self, q: float) -> float:
        with self._lock:
            return self._percentile_locked(q)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            if self._n == 0:
                return {"count": 0, "mean": 0.0, "p50": 0, "p99": 0, "max": 0}
            p50 = self._percentile_locked(0.5)
            p99 = self._percentile_locked(0.99)
            if self._buckets is None:
                p50, p99 = int(p50), int(p99)
            else:
                p50, p99 = round(p50, 1), round(p99, 1)
            return {
                "count": self._n,
                "mean": round(self._total / self._n, 2),
                "p50": p50,
                "p99": p99,
                "max": self._max,
            }

    def sum(self) -> int:
        with self._lock:
            return self._total

    def reset(self) -> None:
        with self._lock:
            self._counts.clear()
            if self._buckets is not None:
                self._buckets = [0] * 64
            self._total = 0
            self._n = 0
            self._max = 0


class LabeledCounter:
    """A counter FAMILY keyed by a fixed label tuple (Prometheus labels):
    ``family.labels("method", "0").inc()``. Children are plain
    :class:`Counter`\\ s — the hot path caches the child and pays the same
    single GIL-atomic bump; ``labels()`` itself is a dict hit after the
    first call per label set. Cardinality is bounded (``_MAX_CHILDREN``):
    overflow collapses into an ``overflow`` child instead of growing the
    registry without bound on hostile method names."""

    kind = "labeled_counter"
    _MAX_CHILDREN = 512

    __slots__ = ("name", "labelnames", "_children", "_lock", "_overflow")

    def __init__(self, name: str, labelnames: Tuple[str, ...]):
        self.name = name
        self.labelnames = tuple(labelnames)
        self._children: Dict[Tuple[str, ...], Counter] = {}
        self._lock = threading.Lock()
        self._overflow: Optional[Counter] = None

    def labels(self, *values) -> Counter:
        key = tuple(str(v) for v in values)
        c = self._children.get(key)
        if c is not None:
            return c
        with self._lock:
            c = self._children.get(key)
            if c is None:
                if len(self._children) >= self._MAX_CHILDREN:
                    if self._overflow is None:
                        self._overflow = Counter(self.name + ":overflow")
                    return self._overflow
                c = self._children[key] = Counter(self.name)
            return c

    def snapshot(self) -> Dict[Tuple[str, ...], int]:
        with self._lock:
            return {k: c.value for k, c in self._children.items()}

    def reset(self) -> None:
        with self._lock:
            self._children.clear()
            self._overflow = None


class FleetGauge:
    """Scrape-time aggregate over live instances (weakly referenced).

    ``track(obj)`` at construction is the ONLY hot-path cost (one WeakSet
    add per object lifetime); ``collect()`` evaluates ``fn(obj)`` for every
    still-live object at scrape time and returns ``(sum, object_count)``.
    A raising ``fn`` skips that object — a half-torn-down ring must not
    break the scrape."""

    kind = "fleet"

    def __init__(self, name: str, fn: Callable[[object], float]):
        self.name = name
        self._fn = fn
        self._refs: "weakref.WeakSet" = weakref.WeakSet()
        self._lock = threading.Lock()

    def track(self, obj) -> None:
        with self._lock:
            self._refs.add(obj)

    def collect(self) -> Tuple[float, int]:
        with self._lock:
            objs = list(self._refs)
        total = 0.0
        n = 0
        for o in objs:
            try:
                total += float(self._fn(o))
                n += 1
            except Exception:
                continue  # dying object: skip, never break the scrape
        return total, n


class Registry:
    """Name → metric. One process-wide instance (:func:`registry`);
    tests may build private ones."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def _get(self, name: str, factory, want_cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = factory()
            elif not isinstance(m, want_cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, lambda: Counter(name), Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, lambda: Gauge(name), Gauge)

    def histogram(self, name: str, kind: str = "size") -> Histogram:
        return self._get(name, lambda: Histogram(name, kind), Histogram)

    def fleet(self, name: str,
              fn: Optional[Callable[[object], float]] = None) -> FleetGauge:
        if fn is None:
            fn = lambda _o: 1.0  # noqa: E731 — membership count gauge
        return self._get(name, lambda: FleetGauge(name, fn), FleetGauge)

    def labeled_counter(self, name: str,
                        labelnames: Tuple[str, ...]) -> LabeledCounter:
        return self._get(name, lambda: LabeledCounter(name, labelnames),
                         LabeledCounter)

    # -- export --------------------------------------------------------------

    def metrics(self) -> Dict[str, object]:
        with self._lock:
            return dict(self._metrics)

    def snapshot(self) -> Dict[str, Dict]:
        """All metrics as plain dicts (tests / JSON export)."""
        out: Dict[str, Dict] = {"counters": {}, "gauges": {},
                                "histograms": {}, "fleet": {},
                                "labeled": {}}
        for name, m in self.metrics().items():
            if isinstance(m, Counter):
                out["counters"][name] = m.snapshot()
            elif isinstance(m, Gauge):
                out["gauges"][name] = m.snapshot()
            elif isinstance(m, Histogram):
                out["histograms"][name] = m.snapshot()
            elif isinstance(m, LabeledCounter):
                out["labeled"][name] = {
                    ",".join(k): v for k, v in m.snapshot().items()}
            elif isinstance(m, FleetGauge):
                total, n = m.collect()
                out["fleet"][name] = {"sum": total, "objects": n}
        return out

    def counters_snapshot(self) -> Dict[str, int]:
        return {n: m.snapshot() for n, m in self.metrics().items()
                if isinstance(m, Counter)}

    def histograms_snapshot(self) -> Dict[str, Dict[str, float]]:
        return {n: m.snapshot() for n, m in self.metrics().items()
                if isinstance(m, Histogram)}

    def reset(self) -> None:
        """Zero counters/gauges/histograms (bench round isolation). Fleet
        gauges keep their membership: they describe live objects."""
        for m in self.metrics().values():
            if not isinstance(m, FleetGauge):
                m.reset()


_REGISTRY = Registry()


def registry() -> Registry:
    return _REGISTRY


def counter(name: str) -> Counter:
    return _REGISTRY.counter(name)


def gauge(name: str) -> Gauge:
    return _REGISTRY.gauge(name)


def histogram(name: str, kind: str = "size") -> Histogram:
    return _REGISTRY.histogram(name, kind)


def fleet(name: str, fn: Optional[Callable[[object], float]] = None
          ) -> FleetGauge:
    return _REGISTRY.fleet(name, fn)


def labeled_counter(name: str, labelnames: Tuple[str, ...]) -> LabeledCounter:
    return _REGISTRY.labeled_counter(name, labelnames)


def snapshot() -> Dict[str, Dict]:
    return _REGISTRY.snapshot()


def reset() -> None:
    _REGISTRY.reset()
