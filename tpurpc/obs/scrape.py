"""The introspection plane: Prometheus text + trace export over plain HTTP.

Two ways in, one rendering core:

* **In-process on every serving port** — :class:`tpurpc.rpc.server.Server`'s
  protocol sniff recognizes an HTTP request line (``GET`` / ``HEAD``) and
  hands the endpoint to :func:`handle_http`, so the SAME port that serves
  RPCs answers ``curl http://host:port/metrics``. No extra listener, no
  extra thread pool — the sniff thread serves the one response and closes
  (scrapes are rare and tiny). Disable with ``TPURPC_SCRAPE=0``.
* **Standalone** — :func:`start_http_server` for processes that are pure
  clients (no Server): a daemon-threaded ``http.server`` with the same
  routes.

Routes::

    /metrics       Prometheus text: registry counters/gauges/histograms/
                   fleet gauges + the copy ledger + channelz counters
    /traces        Chrome trace_event JSON of the span buffer (?trace_id=hex)
    /channelz      channelz snapshot JSON (the live data test_channelz asserts)
    /healthz       "ok"; 503 "degraded: ..." while the stall watchdog has
                   an active diagnosis (tpurpc-blackbox, ISSUE 5); 200
                   "draining" while Server.drain() bleeds connections
                   (tpurpc-fleet, ISSUE 6 — healthy but leaving rotation)
    /debug/flight  flight-recorder replay: JSON event list (?text=1 for the
                   human rendering, ?since_ns=N to bound)
    /debug/stalls  stall-watchdog diagnoses: active + recent history JSON
    /debug/profile tpurpc-lens stage-tagged sampling profiler: per-stage
                   sample shares + top collapsed stacks (?collapsed=1 for
                   flamegraph.pl text, ?samples=1 to include the recent
                   raw samples the timeline tool renders)
    /debug/waterfall  tpurpc-lens byte-flow waterfall: per-hop effective
                   GB/s with the copy ledger folded in (?text=1 table)
    /debug/history tpurpc-argus ring tsdb: bounded two-tier metric history
                   (?series=NAME&window_s=S for points, bare = inventory)
    /debug/slo     tpurpc-argus SLO objectives, burn rates, alert states
    /debug/diagnose  tpurpc-oracle causal diagnosis: ranked hypotheses with
                   cited evidence for the current symptom (?symptom= pins
                   one, ?text=1 for the prose report)

tpurpc-argus (ISSUE 14): ``/healthz?json=1`` answers the STRUCTURED body
(:func:`healthz_doc`) — status plus one ``degraded_reasons`` list where
watchdog stalls, firing SLO alerts, drain, shedding, and KV pressure each
contribute a ``{"reason", "detail"}`` entry; the bare text face keeps
every legacy body byte-for-byte.

tpurpc-lens (ISSUE 8): every ``_route`` dispatch records its own cost into
the ``scrape_us`` latency histogram — the concurrent-scraper test asserts
scrape work shows up THERE, not in serving p99.
"""

from __future__ import annotations

import json
import time as _time
from typing import List, Optional, Tuple

from tpurpc.obs import metrics as _metrics
from tpurpc.obs import profiler as _obs_profiler
from tpurpc.obs import tracing as _tracing

PREFIX = "tpurpc_"

#: HTTP request-line openers the server sniff routes here (8-byte prefixes
#: compared against the sniffed first bytes)
HTTP_METHOD_PREFIXES = (b"GET ", b"HEAD")

#: tpurpc-lens: what one scrape costs, measured where it runs (the sniff /
#: http threads) — so scrape load is attributable without touching serving
#: latency histograms
_SCRAPE_US = _metrics.histogram("scrape_us", kind="latency")

#: sampling-profiler frame markers: scrape rendering is its own stage
_LENS_STAGES = {
    "handle_http": "scrape",
    "render_prometheus": "scrape",
    "_route": "scrape",
    "route_local": "scrape",
}
_obs_profiler.register_stages(__file__, _LENS_STAGES)


def scrape_enabled() -> bool:
    from tpurpc.utils.config import _env

    return (_env("TPURPC_SCRAPE") or "1").lower() not in ("0", "off", "false")


def _san(name: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)


def render_prometheus() -> str:
    """The full Prometheus text exposition: one pass over the registry,
    the copy ledger, and channelz — scrape-time reads only."""
    lines: List[str] = []

    # tpurpc-xray: fold the C core's shm metrics table into the registry
    # as native_* series before the pass (scrape-time read, hot path
    # untouched; a no-op when the native plane is off)
    try:
        from tpurpc.obs import native_obs as _nobs

        _nobs.sync_registry()
    except Exception:
        pass

    snap = _metrics.registry().metrics()
    for name in sorted(snap):
        m = snap[name]
        full = PREFIX + _san(name)
        if isinstance(m, _metrics.Counter):
            lines.append(f"# TYPE {full} counter")
            lines.append(f"{full} {m.snapshot()}")
        elif isinstance(m, _metrics.LabeledCounter):
            lines.append(f"# TYPE {full} counter")
            names = m.labelnames
            for key, value in sorted(m.snapshot().items()):
                labels = ",".join(f'{n}="{v}"' for n, v in zip(names, key))
                lines.append(f"{full}{{{labels}}} {value}")
        elif isinstance(m, _metrics.Gauge):
            lines.append(f"# TYPE {full} gauge")
            lines.append(f"{full} {m.snapshot()}")
        elif isinstance(m, _metrics.Histogram):
            s = m.snapshot()
            lines.append(f"# TYPE {full} summary")
            lines.append(f'{full}{{quantile="0.5"}} {s["p50"]}')
            lines.append(f'{full}{{quantile="0.99"}} {s["p99"]}')
            lines.append(f"{full}_sum {m.sum()}")
            lines.append(f"{full}_count {s['count']}")
            lines.append(f"{full}_max {s['max']}")
        elif isinstance(m, _metrics.FleetGauge):
            total, n = m.collect()
            lines.append(f"# TYPE {full} gauge")
            lines.append(f"{full} {total}")
            lines.append(f"{full}_objects {n}")

    # copy ledger (tpurpc/tpu/ledger.py): byte + op totals per mechanism
    try:
        from tpurpc.tpu import ledger

        led = ledger.snapshot()
        lines.append(f"# TYPE {PREFIX}ledger_bytes counter")
        lines.append(f"# TYPE {PREFIX}ledger_ops counter")
        for k in sorted(led):
            if k.endswith("_ops"):
                lines.append(
                    f'{PREFIX}ledger_ops{{kind="{k[:-4]}"}} {led[k]}')
            else:
                lines.append(f'{PREFIX}ledger_bytes{{kind="{k}"}} {led[k]}')
    except Exception:
        pass

    # channelz: per-entity call counters + stream/connection gauges — the
    # data test_channelz asserts programmatically, live on the scrape
    try:
        from tpurpc.rpc import channelz

        lines.append(f"# TYPE {PREFIX}channelz_calls counter")
        lines.append(f"# TYPE {PREFIX}channelz_streams gauge")
        lines.append(f"# TYPE {PREFIX}channelz_connections gauge")
        for sid, srv in channelz.live_servers():
            info = channelz.server_info(srv)
            ent = f'entity="server",id="{sid}"'
            for key in ("calls_started", "calls_succeeded", "calls_failed"):
                if key in info:
                    lines.append(
                        f'{PREFIX}channelz_calls{{{ent},'
                        f'kind="{key[6:]}"}} {info[key]}')
            lines.append(f'{PREFIX}channelz_streams{{{ent}}} '
                         f'{info["active_streams"]}')
            lines.append(f'{PREFIX}channelz_connections{{{ent}}} '
                         f'{info["connections"]}')
        for cid, ch in channelz.live_channels():
            info = channelz.channel_info(ch)
            ent = f'entity="channel",id="{cid}"'
            counters = getattr(ch, "call_counters", None)
            if counters is not None:
                cd = counters.as_dict()
                for key in ("calls_started", "calls_succeeded",
                            "calls_failed"):
                    lines.append(
                        f'{PREFIX}channelz_calls{{{ent},'
                        f'kind="{key[6:]}"}} {cd[key]}')
            lines.append(f'{PREFIX}channelz_streams{{{ent}}} '
                         f'{info["active_streams"]}')
            lines.append(f'{PREFIX}channelz_connections{{{ent}}} '
                         f'{info["connected"]}')
    except Exception:
        pass

    return "\n".join(lines) + "\n"


# -- request handling (shared by the sniff path and the standalone server) --

def _query_params(query: str) -> dict:
    out = {}
    for part in query.split("&"):
        k, _, v = part.partition("=")
        if k:
            out[k] = v
    return out


def _route(path: str) -> Tuple[int, str, bytes]:
    """(status, content_type, body) for one GET path.

    tpurpc-manycore: in a shard worker, the aggregate-aware routes
    (/metrics, /traces, /debug/flight, /debug/stalls, /debug/profile,
    /debug/waterfall, /healthz) merge EVERY live worker's view — one GET on
    the serving port tells the whole truth no matter which shard the accept
    spread picked. ``?local=1`` serves this worker alone (it is also the
    recursion guard for peer fetches)."""
    t0 = _time.monotonic_ns()
    try:
        route, _, query = path.partition("?")
        params = _query_params(query)
        if not params.get("local"):
            from tpurpc.obs import shard as _shard

            if _shard.sharded():
                agg = _shard.route_aggregate(route, params)
                if agg is not None:
                    return agg
        return route_local(path)
    finally:
        _SCRAPE_US.record((_time.monotonic_ns() - t0) // 1000)


def healthz_doc() -> dict:
    """tpurpc-argus (ISSUE 14): ONE structured health assembly feeding
    both ``/healthz`` faces. Every subsystem that used to compose its own
    ad-hoc text line (watchdog 503, fleet drain, cadence shedding, kv
    pressure, and now a firing SLO) contributes one entry to
    ``degraded_reasons`` — ``[{"reason": <slug>, "detail": <text>}]`` —
    so probes stop regex-ing prose. ``code`` is the HTTP status the text
    face answers (503 iff a watchdog stall or SLO page is live);
    ``lines`` are the legacy per-subsystem body lines, unchanged."""
    reasons: List[dict] = []
    code = 200
    # tpurpc-blackbox: a live stall diagnosis degrades health — LBs and
    # probes see the wedge without scraping /debug/stalls themselves.
    # Ordered FIRST so the legacy degraded text body stays byte-for-byte.
    try:
        from tpurpc.obs import watchdog as _watchdog

        active = _watchdog.get().active()
    except Exception:
        active = []
    if active:
        worst = active[0]
        code = 503
        reasons.append({
            "reason": "watchdog-stall",
            "detail": (f"{len(active)} stalled call(s); "
                       f"{worst['method']} blocked on {worst['stage']} "
                       f"for {worst['age_s']}s")})
    # tpurpc-argus: a FIRING burn-rate alert is a page — degraded, like a
    # stall (sys.modules-gated: processes without an SLO plane keep their
    # exact old behavior)
    import sys

    slo_lines: List[str] = []
    try:
        slo_mod = sys.modules.get("tpurpc.obs.slo")
        if slo_mod:
            fir = slo_mod.firing()
            if fir:
                code = 503
                f0 = fir[0]
                reasons.append({
                    "reason": "slo-firing",
                    "detail": (f"{len(fir)} firing SLO alert(s); "
                               f"{f0['objective']}/{f0['track']} burning "
                               f"{f0['burn_fast']}x fast-window budget")})
            slo_lines = slo_mod.health_lines()
    except Exception:
        pass
    # tpurpc-fleet: a draining server is HEALTHY but leaving — 200 with a
    # distinct body (a 503 would read as failure and page; orchestrators
    # key on the text to stop routing without alarming)
    try:
        from tpurpc.rpc import channelz as _channelz

        draining = any(getattr(srv, "draining", False)
                       for _sid, srv in _channelz.live_servers())
    except Exception:
        draining = False
    if draining:
        reasons.append({"reason": "draining",
                        "detail": "graceful drain in progress (healthy, "
                                  "leaving rotation)"})
    # tpurpc-cadence: live decode schedulers append their shed/queue
    # state — during overload an operator (or probe) reads "shedding"
    # plus the queue numbers right here, without the metrics plane.
    # Still 200: a shedding server is doing its job, not failing.
    try:
        gen_mod = sys.modules.get("tpurpc.serving.scheduler")
        gen_lines = gen_mod.health_lines() if gen_mod else []
    except Exception:
        gen_lines = []
    shedding = [ln for ln in gen_lines if "state=shedding" in ln]
    if shedding:
        reasons.append({"reason": "shedding",
                        "detail": f"{len(shedding)} scheduler(s) shedding "
                                  "batch-class load under pressure"})
    # tpurpc-keystone: live KV arenas append block occupancy / swap
    # pressure / quarantine counts — same sys.modules gate, so
    # processes without a KV plane keep their exact old bodies
    kv_lines: List[str] = []
    try:
        kv_mod = sys.modules.get("tpurpc.serving.kv")
        if kv_mod:
            kv_lines = kv_mod.health_lines()
            pressured = []
            for m in list(getattr(kv_mod, "_LIVE", ()) or ()):
                try:
                    s = m.stats()
                    if s.get("swapped_blocks") or s.get("quarantined"):
                        pressured.append(m.name)
                except Exception:
                    continue
            if pressured:
                reasons.append({
                    "reason": "kv-pressure",
                    "detail": f"KV arena(s) under pressure "
                              f"(swap/quarantine): "
                              f"{', '.join(sorted(pressured))}"})
    except Exception:
        pass
    lines = gen_lines + kv_lines + slo_lines
    status = ("degraded" if code == 503
              else "draining" if draining else "ok")
    return {"status": status, "code": code, "draining": draining,
            "degraded_reasons": reasons, "lines": lines}


def route_local(path: str) -> Tuple[int, str, bytes]:
    """The single-process rendering of one GET path (no shard fan-out)."""
    route, _, query = path.partition("?")
    if route in ("/metrics", "/metrics/"):
        return 200, "text/plain; version=0.0.4", render_prometheus().encode()
    if route in ("/healthz", "/health"):
        params = _query_params(query)
        doc = healthz_doc()
        # tpurpc-argus (ISSUE 14): the STRUCTURED face — one
        # degraded_reasons list instead of N ad-hoc text conventions
        if params.get("json"):
            return (doc["code"], "application/json",
                    json.dumps(doc, indent=1).encode())
        # the text face: every legacy body preserved byte-for-byte (the
        # fleet/shard/cadence tests and smokes key on these exact bytes)
        if doc["code"] == 503:
            worst = doc["degraded_reasons"][0]
            body = (f"degraded: {worst['detail']}\n").encode()
            return 503, "text/plain", body
        head = b"draining" if doc["draining"] else b"ok"
        gen_lines = doc["lines"]
        if gen_lines:
            body = head + b"\n" + "\n".join(gen_lines).encode() + b"\n"
            return 200, "text/plain", body
        if doc["draining"]:
            return 200, "text/plain", b"draining\n"
        return 200, "text/plain", b"ok\n"
    if route in ("/debug/flight", "/debug/flight/"):
        from tpurpc.obs import flight as _flight

        params = _query_params(query)
        try:
            since_ns = int(params.get("since_ns") or 0)
        except ValueError:
            return 400, "text/plain", b"bad since_ns\n"
        if params.get("text"):
            return (200, "text/plain",
                    _flight.dump_text(since_ns=since_ns).encode())
        return (200, "application/json",
                json.dumps({"events": _flight.snapshot(since_ns=since_ns),
                            "capacity": _flight.RECORDER.capacity}).encode())
    if route in ("/debug/stalls", "/debug/stalls/"):
        from tpurpc.obs import watchdog as _watchdog

        return (200, "application/json",
                json.dumps(_watchdog.get().snapshot(), indent=1).encode())
    if route in ("/debug/profile", "/debug/profile/"):
        from tpurpc.obs import lens as _lens

        params = _query_params(query)
        if not _lens.enabled():
            return (200, "application/json",
                    json.dumps({"enabled": False,
                                "reason": "TPURPC_LENS=0"}).encode())
        _obs_profiler.ensure_started()  # client-only processes: first scrape
        if params.get("collapsed"):
            return (200, "text/plain",
                    _obs_profiler.collapsed_text().encode())
        snap = _obs_profiler.snapshot(
            include_samples=bool(params.get("samples")))
        snap["enabled"] = True
        return 200, "application/json", json.dumps(snap).encode()
    if route in ("/debug/waterfall", "/debug/waterfall/"):
        from tpurpc.obs import lens as _lens

        params = _query_params(query)
        if params.get("text"):
            return 200, "text/plain", _lens.render_text().encode()
        return (200, "application/json",
                json.dumps(_lens.waterfall()).encode())
    if route in ("/debug/history", "/debug/history/"):
        # tpurpc-argus (ISSUE 14): the ring tsdb — bounded metric history
        from tpurpc.obs import tsdb as _tsdb

        params = _query_params(query)
        return (200, "application/json",
                json.dumps(_tsdb.history_doc(params)).encode())
    if route in ("/debug/slo", "/debug/slo/"):
        # tpurpc-argus: objectives + burn rates + alert states
        from tpurpc.obs import slo as _slo

        return (200, "application/json",
                json.dumps(_slo.slo_doc(), indent=1).encode())
    if route in ("/debug/seq", "/debug/seq/"):
        # tpurpc-odyssey (ISSUE 15): per-sequence cost ledgers — live +
        # recent-completed, account rollup, step-time attribution check
        # (?account= filters, ?n= bounds the lists)
        from tpurpc.obs import odyssey as _odyssey

        params = _query_params(query)
        return (200, "application/json",
                json.dumps(_odyssey.seq_doc(params), indent=1).encode())
    if route in ("/debug/diagnose", "/debug/diagnose/"):
        # tpurpc-oracle (ISSUE 20): ranked causal hypotheses for the
        # current symptom (?symptom= pins one; ?text=1 the prose face)
        from tpurpc.obs import diagnose as _diagnose

        params = _query_params(query)
        doc = _diagnose.diagnose_doc(params)
        if params.get("text"):
            return (200, "text/plain",
                    _diagnose.render_text(doc).encode())
        return (200, "application/json",
                json.dumps(doc, indent=1).encode())
    if route in ("/channelz", "/channelz/"):
        from tpurpc.rpc import channelz

        return (200, "application/json",
                json.dumps(channelz.snapshot(), indent=1).encode())
    if route in ("/traces", "/traces/"):
        trace_id: Optional[str] = None
        for part in query.split("&"):
            k, _, v = part.partition("=")
            if k == "trace_id" and v:
                trace_id = v
        try:
            body = json.dumps(_tracing.chrome_trace(trace_id)).encode()
        except ValueError:
            return 400, "text/plain", b"bad trace_id\n"
        return 200, "application/json", body
    return (404, "text/plain",
            b"tpurpc-scope: /metrics /traces /channelz /healthz "
            b"/debug/flight /debug/stalls /debug/profile /debug/waterfall "
            b"/debug/history /debug/slo /debug/seq /debug/diagnose\n")


def _response(status: int, ctype: str, body: bytes,
              head_only: bool = False) -> List[bytes]:
    reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
              503: "Service Unavailable"}.get(status, "")
    head = (f"HTTP/1.0 {status} {reason}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(body)}\r\n"
            f"Connection: close\r\n\r\n").encode()
    return [head] if head_only else [head, body]


def handle_http(endpoint, first: bytes) -> None:
    """Serve one HTTP request on a freshly-sniffed Endpoint and close it.

    ``first`` is whatever the protocol sniff already consumed. Reads to the
    end of the request line only (headers are irrelevant), bounded at 8 KiB
    / 5 s so a stuck client can't pin the sniff thread."""
    buf = bytearray(first)
    try:
        scratch = bytearray(1024)
        mv = memoryview(scratch)
        while b"\r\n" not in buf and b"\n" not in buf and len(buf) < 8192:
            n = endpoint.read_into(mv, timeout=5)
            if n == 0:
                break
            buf += mv[:n]
        line = bytes(buf).split(b"\n", 1)[0].strip().decode("latin-1")
        parts = line.split()
        method = parts[0] if parts else "GET"
        path = parts[1] if len(parts) > 1 else "/metrics"
        status, ctype, body = _route(path)
        endpoint.write(_response(status, ctype, body,
                                 head_only=method == "HEAD"))
    except Exception:
        pass  # a scrape must never take anything down
    finally:
        try:
            endpoint.close()
        except Exception:
            pass


def start_http_server(host: str = "127.0.0.1", port: int = 0):
    """Standalone introspection endpoint (client-only processes): returns
    ``(server, bound_port)``; ``server.shutdown()`` stops it. Daemon
    threads — it never blocks interpreter exit."""
    import http.server
    import socketserver
    import threading

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 — http.server API
            status, ctype, body = _route(self.path)
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_HEAD(self):  # noqa: N802
            status, ctype, body = _route(self.path)
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()

        def log_message(self, *args):  # quiet: scrapes are periodic
            pass

    class Srv(socketserver.ThreadingMixIn, http.server.HTTPServer):
        daemon_threads = True
        allow_reuse_address = True

    srv = Srv((host, port), Handler)
    t = threading.Thread(target=srv.serve_forever, daemon=True,
                         name="tpurpc-obs-http")
    t.start()
    return srv, srv.server_address[1]
