"""tpurpc-argus fleet collector: one telemetry front door for N members.

RDMAvisor's lesson (arXiv:1802.01870) applied to observability: scarce
shared state — "what is the whole fleet doing" — belongs behind ONE
aggregating service, not duplicated into every member. The collector is a
standalone process (``python -m tpurpc.tools.collector``) that polls
every member's EXISTING introspection routes (``/metrics``,
``/debug/slo``, ``/debug/flight``, ``/traces`` — the same plain-HTTP
plane ``curl`` and the PR-7 shard fan-out already speak) and serves the
merged views:

* ``GET /fleet/metrics``  — every member's Prometheus series with a
  ``member="host:port"`` label injected first (exactly the shard merge's
  ``shard="k"`` move, lifted across processes/hosts), counters passed
  through a :class:`tpurpc.obs.tsdb.ResetClamp` so a restarted member
  cannot step a merged series backwards, plus
  ``tpurpc_member_up{member}`` / ``tpurpc_member_stale{member}``;
* ``GET /fleet/slo``      — every member's ``/debug/slo`` document plus a
  flat ``alerts`` list (each alert tagged with its member) — the fleet
  pager's one stop;
* ``GET /fleet/diagnose`` — every member's causal diagnosis report
  (tpurpc-oracle, ISSUE 20) merged: hypotheses re-combined by cause
  across members, a ``corroboration`` map naming which members cite
  each cause, and the ``degraded`` member list;
* ``GET /fleet/timeline`` — one Perfetto chrome-trace for the whole
  fleet, reusing :mod:`tpurpc.tools.timeline`'s clock-anchor rebase
  (members' monotonic clocks aligned on their exported anchors);
* ``GET /healthz``        — the collector's own liveness + member census.

Member death is tolerated by design: a member that stops answering is
marked STALE after ``stale_after`` missed polls (``member_stale=1``,
``member_up=0``) and its series VANISH from ``/fleet/metrics`` — the
PR-4 weakref-death contract ("a dead thing drops out, never freezes its
last values") lifted to the fleet. A member that answers again resumes
seamlessly; if its counters restarted from zero, the reset clamp
detects the step and continues the merged series from last-known.

Targets come from a static ``host:port`` list or any resolver scheme
:func:`tpurpc.rpc.resolver.resolve_target` understands (``dns:///...``,
registered custom schemes).
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from typing import Dict, List, Optional, Tuple

from tpurpc.obs.tsdb import ResetClamp

__all__ = ["FleetCollector", "resolve_targets"]


def resolve_targets(specs: List[str]) -> List[str]:
    """``host:port`` specs pass through; anything with a scheme goes to
    the resolver (``dns:///name:port`` fans out to every address)."""
    out: List[str] = []
    for spec in specs:
        if "://" in spec or spec.startswith("dns:"):
            try:
                from tpurpc.rpc.resolver import resolve_target

                for addr in resolve_target(spec):
                    host = getattr(addr, "host", None) or addr[0]
                    port = getattr(addr, "port", None) or addr[1]
                    out.append(f"{host}:{port}")
                continue
            except Exception:
                pass  # fall through: treat as literal
        out.append(spec)
    # stable de-dup
    seen = set()
    uniq = []
    for t in out:
        if t not in seen:
            seen.add(t)
            uniq.append(t)
    return uniq


class _Member:
    __slots__ = ("target", "metrics_text", "slo", "flight", "anchor",
                 "seq", "diagnose", "last_ok_mono", "polls", "misses",
                 "resets_seen")

    def __init__(self, target: str):
        self.target = target
        self.metrics_text = ""
        self.slo: Optional[dict] = None
        self.flight: Optional[dict] = None
        self.anchor: Optional[dict] = None
        self.seq: Optional[dict] = None
        self.diagnose: Optional[dict] = None
        self.last_ok_mono = 0.0
        self.polls = 0
        self.misses = 0
        self.resets_seen = 0


class FleetCollector:
    """Polls the members on ``poll_s`` and renders the merged views.
    Pure-ish core: :meth:`poll_once` + the renderers are driven directly
    by tests; :meth:`serve` adds the HTTP face."""

    def __init__(self, targets: List[str], poll_s: float = 1.0,
                 stale_after: int = 3, fetch_timeout_s: float = 2.0):
        self.targets = list(targets)
        self.poll_s = poll_s
        self.stale_after = max(1, int(stale_after))
        self.fetch_timeout_s = fetch_timeout_s
        self._members: Dict[str, _Member] = {
            t: _Member(t) for t in self.targets}
        self._clamp = ResetClamp()
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._httpd = None

    # -- polling --------------------------------------------------------------

    def _fetch(self, target: str, path: str) -> Optional[bytes]:
        try:
            with urllib.request.urlopen(
                    f"http://{target}{path}",
                    timeout=self.fetch_timeout_s) as resp:
                return resp.read()
        except Exception:
            return None

    def poll_once(self) -> None:
        for target in self.targets:
            m = self._members[target]
            m.polls += 1
            raw = self._fetch(target, "/metrics")
            if raw is None:
                m.misses += 1
                continue
            slo_raw = self._fetch(target, "/debug/slo")
            flight_raw = self._fetch(target, "/debug/flight")
            traces_raw = self._fetch(target, "/traces")
            seq_raw = self._fetch(target, "/debug/seq")
            diag_raw = self._fetch(target, "/debug/diagnose")
            with self._lock:
                m.misses = 0
                m.last_ok_mono = time.monotonic()
                m.metrics_text = raw.decode("utf-8", "replace")
                m.slo = _loads(slo_raw)
                m.flight = _loads(flight_raw)
                m.seq = _loads(seq_raw)
                m.diagnose = _loads(diag_raw)
                traces = _loads(traces_raw) or {}
                m.anchor = (traces.get("clock_anchor")
                            or _first_anchor(traces))

    def _loop(self) -> None:
        while not self._stop.wait(timeout=self.poll_s):
            try:
                self.poll_once()
            except Exception:
                pass  # a collector crash helps nobody

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop = threading.Event()
        t = threading.Thread(target=self._loop, daemon=True,
                             name="tpurpc-collector")
        self._thread = t
        t.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2)
        self._thread = None
        httpd = self._httpd
        if httpd is not None:
            try:
                httpd.shutdown()
            except Exception:
                pass
            self._httpd = None

    # -- member state ---------------------------------------------------------

    def member_state(self, m: _Member) -> str:
        if m.last_ok_mono == 0.0:
            return "never-seen"
        if m.misses >= self.stale_after:
            return "stale"
        return "up"

    def census(self) -> List[dict]:
        with self._lock:
            return [{"member": m.target, "state": self.member_state(m),
                     "polls": m.polls, "misses": m.misses,
                     "age_s": (round(time.monotonic() - m.last_ok_mono, 2)
                               if m.last_ok_mono else None)}
                    for m in self._members.values()]

    # -- /fleet/metrics -------------------------------------------------------

    @staticmethod
    def _member_label(line: str, member: str) -> str:
        brace = line.find("{")
        space = line.find(" ")
        if brace != -1 and (space == -1 or brace < space):
            return f'{line[:brace]}{{member="{member}",{line[brace + 1:]}'
        name, _, rest = line.partition(" ")
        return f'{name}{{member="{member}"}} {rest}'

    def merged_metrics(self) -> str:
        """The fleet Prometheus text. A stale member contributes NO data
        series (vanish, never freeze) but stays in the census gauges;
        counters ride the reset clamp so a member restart reads as a flat
        spot, not a cliff."""
        types: Dict[str, str] = {}
        series: List[str] = []
        census: List[Tuple[str, str]] = []
        with self._lock:
            members = list(self._members.values())
        for m in members:
            state = self.member_state(m)
            census.append((m.target, state))
            if state != "up":
                continue
            counter_names = set()
            for line in m.metrics_text.splitlines():
                if line.startswith("# TYPE "):
                    parts = line.split()
                    if len(parts) >= 4:
                        types.setdefault(parts[2], parts[3])
                        if parts[3] == "counter":
                            counter_names.add(parts[2])
                    continue
                if not line or line.startswith("#"):
                    continue
                name, _, value = line.rpartition(" ")
                if name in counter_names or name.split("{", 1)[0] \
                        in counter_names:
                    try:
                        v = float(value)
                    except ValueError:
                        series.append(self._member_label(line, m.target))
                        continue
                    clamped = self._clamp.clamp((m.target, name), v)
                    if clamped != v:
                        m.resets_seen = self._clamp.resets
                    line = f"{name} {_fmt(clamped)}"
                series.append(self._member_label(line, m.target))
        lines = [f"# TYPE {name} {t}" for name, t in sorted(types.items())]
        lines.append("# TYPE tpurpc_member_up gauge")
        lines.append("# TYPE tpurpc_member_stale gauge")
        for target, state in census:
            up = 1 if state == "up" else 0
            stale = 1 if state == "stale" else 0
            lines.append(f'tpurpc_member_up{{member="{target}"}} {up}')
            lines.append(
                f'tpurpc_member_stale{{member="{target}"}} {stale}')
        lines.append(
            f"tpurpc_collector_counter_resets {self._clamp.resets}")
        lines.extend(series)
        return "\n".join(lines) + "\n"

    # -- /fleet/slo -----------------------------------------------------------

    def merged_slo(self) -> dict:
        members: Dict[str, dict] = {}
        alerts: List[dict] = []
        with self._lock:
            snap = [(m.target, self.member_state(m), m.slo)
                    for m in self._members.values()]
        for target, state, doc in snap:
            members[target] = {"state": state,
                               "slo": doc if state == "up" else None}
            if state != "up" or not doc:
                continue
            for a in doc.get("firing", ()):
                alerts.append(dict(a, member=target))
            for obj in doc.get("objectives", ()):
                for track, st in (obj.get("tracks") or {}).items():
                    if st.get("state") == "pending":
                        alerts.append({
                            "objective": obj.get("name"), "track": track,
                            "state": "pending",
                            "burn_fast": st.get("burn_fast"),
                            "burn_slow": st.get("burn_slow"),
                            "member": target})
        alerts.sort(key=lambda a: (a.get("state", "firing") != "firing",
                                   str(a.get("member"))))
        return {"members": members, "alerts": alerts,
                "firing": sum(1 for a in alerts
                              if a.get("state", "firing") == "firing")}

    # -- /fleet/seq (tpurpc-odyssey, ISSUE 15) --------------------------------

    def merged_seq(self) -> dict:
        """The fleet-wide sequence/account view: every UP member's
        /debug/seq merged through the same pure merge the shard fan-out
        uses — rows tagged ``member``, account rollups summed across the
        fleet (a stale member's sequences VANISH, never freeze)."""
        from tpurpc.obs.odyssey import merge_seq_docs

        with self._lock:
            snap = [(m.target, self.member_state(m), m.seq)
                    for m in self._members.values()]
        docs = {t: doc for t, state, doc in snap
                if state == "up" and doc}
        out = merge_seq_docs(docs, label="member")
        out["members"] = {t: state for t, state, _d in snap}
        return out

    # -- /fleet/diagnose (tpurpc-oracle, ISSUE 20) ----------------------------

    def merged_diagnose(self) -> dict:
        """The fleet-wide causal view: every UP member's /debug/diagnose
        merged through the same pure merge the shard fan-out uses —
        hypotheses re-combined by cause, evidence member-tagged, and a
        ``corroboration`` map naming which members cite each cause ("3
        members degraded, all cite the same peer" is one dict lookup)."""
        from tpurpc.obs.diagnose import merge_diagnose_docs

        with self._lock:
            snap = [(m.target, self.member_state(m), m.diagnose)
                    for m in self._members.values()]
        docs = {t: doc for t, state, doc in snap
                if state == "up" and doc}
        out = merge_diagnose_docs(docs, label="member")
        out["members"] = {t: state for t, state, _d in snap}
        out["degraded"] = sorted(
            t for t, state, doc in snap
            if state == "up" and doc and doc.get("symptom"))
        return out

    # -- /fleet/timeline ------------------------------------------------------

    def timeline(self) -> dict:
        """One Perfetto doc for the fleet, via tools.timeline's pure merge
        (fresh member fetches — a timeline wants NOW, not the poll cache)."""
        from tpurpc.tools import timeline as _timeline

        collected = []
        for target in self.targets:
            col = _timeline.collect(target)
            if col["traces"] is None and col["flight"] is None:
                continue
            collected.append(col)
        return _timeline.build_timeline(collected)

    # -- HTTP face ------------------------------------------------------------

    def route(self, path: str) -> Tuple[int, str, bytes]:
        route, _, _query = path.partition("?")
        if route in ("/fleet/metrics", "/fleet/metrics/", "/metrics"):
            return (200, "text/plain; version=0.0.4",
                    self.merged_metrics().encode())
        if route in ("/fleet/slo", "/fleet/slo/"):
            return (200, "application/json",
                    json.dumps(self.merged_slo(), indent=1).encode())
        if route in ("/fleet/seq", "/fleet/seq/"):
            return (200, "application/json",
                    json.dumps(self.merged_seq(), indent=1).encode())
        if route in ("/fleet/diagnose", "/fleet/diagnose/"):
            return (200, "application/json",
                    json.dumps(self.merged_diagnose(), indent=1).encode())
        if route in ("/fleet/timeline", "/fleet/timeline/"):
            try:
                return (200, "application/json",
                        json.dumps(self.timeline()).encode())
            except Exception as exc:
                return (500, "text/plain",
                        f"timeline failed: {exc!r}\n".encode())
        if route in ("/healthz", "/health"):
            doc = {"status": "ok", "members": self.census(),
                   "poll_s": self.poll_s}
            return 200, "application/json", json.dumps(doc).encode()
        return (404, "text/plain",
                b"tpurpc-collector: /fleet/metrics /fleet/slo /fleet/seq "
                b"/fleet/diagnose /fleet/timeline /healthz\n")

    def serve(self, host: str = "127.0.0.1", port: int = 0) -> int:
        """Start polling + the HTTP face; returns the bound port."""
        import http.server
        import socketserver

        self.start()
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                status, ctype, body = outer.route(self.path)
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):
                pass

        class Srv(socketserver.ThreadingMixIn, http.server.HTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._httpd = Srv((host, port), Handler)
        t = threading.Thread(target=self._httpd.serve_forever, daemon=True,
                             name="tpurpc-collector-http")
        t.start()
        return self._httpd.server_address[1]


def _loads(raw: Optional[bytes]) -> Optional[dict]:
    if raw is None:
        return None
    try:
        doc = json.loads(raw)
        return doc if isinstance(doc, dict) else None
    except ValueError:
        return None


def _first_anchor(traces: dict) -> Optional[dict]:
    anchors = traces.get("clock_anchors")
    if isinstance(anchors, dict) and anchors:
        return anchors[sorted(anchors)[0]]
    return None


def _fmt(v: float) -> str:
    return str(int(v)) if float(v).is_integer() else repr(v)
