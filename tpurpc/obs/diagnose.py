"""tpurpc-oracle: the causal diagnosis engine — from seeing to explaining.

Five telemetry planes (tsdb, flight, watchdog, lens, seq ledgers — plus
the native C lane) can SEE every fault; this module correlates them into
a ranked answer to "why". A SYMPTOM (firing SLO, watchdog trip,
healthz-degraded, or an operator query) goes in; ranked ``Hypothesis``
objects come out, each carrying a cause slug, a combined confidence, the
cited evidence — ``(plane, ref, value)`` triples an operator can chase
by hand — and an ``actionable`` hint (the autopilot on-ramp: ROADMAP
item 5 consumes these, it does not re-derive them).

The engine is three layers, all pure reads:

* **onset** — :func:`detect_onset` fixes WHEN a series changed: a
  reset-aware window-delta transform (counters become positive deltas,
  the post-reset value IS the delta — same algebra as ``Tsdb.rate``)
  followed by an exhaustive mean-shift split (the CUSUM max-deviation
  point / one binary-segmentation step, O(n) via prefix sums). A shift
  scores ``|Δmean| · sqrt(nl·nr/n) / pooled_sd`` — a t-statistic — and
  only splits past ``min_score`` count, so a flat-but-noisy series never
  fabricates an onset.
* **rules** — a declarative registry of ``Rule(symptom_kinds,
  collect_fn, score_fn)`` entries. Collect functions may only READ the
  planes (the ``diag`` lint rule enforces it: no counter bumps, no
  flight emits from inside a diagnosis); score functions turn the
  collected facts into hypotheses. Per-cause combination is
  noisy-OR: ``1 - Π(1 - c_i)``, capped at 0.99 — independent planes
  agreeing beats any single plane shouting.
* **faces** — live ``GET /debug/diagnose`` (scrape plane, shard fan-out
  via :func:`merge_diagnose_docs`), fleet ``/fleet/diagnose`` on the
  collector (member-tagged + cross-member corroboration), and offline
  ``python -m tpurpc.tools.diagnose <bundle-dir>`` replaying a PR-14
  bundle through :class:`BundlePlanes` into the SAME ranked report —
  every auto-captured bundle also ships a ``diagnosis.json`` written at
  trip time.

``TPURPC_DIAGNOSE=0`` turns the whole plane off (the route answers
``{"enabled": false}``; the bundle hook writes nothing).
"""

from __future__ import annotations

import json
import math
import os
import sys
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Hypothesis", "Rule", "Planes", "LivePlanes", "BundlePlanes",
    "detect_onset", "series_shifts", "find_symptom", "diagnose",
    "diagnose_doc", "diagnose_bundle", "merge_diagnose_docs",
    "render_text", "enabled", "register", "rules", "ACTIONS",
]


def enabled() -> bool:
    from tpurpc.utils.config import _env

    return (_env("TPURPC_DIAGNOSE") or "1").lower() not in (
        "0", "off", "false")


# -- change-point detection ----------------------------------------------------

#: fewest points a series needs before an onset claim is admissible
MIN_POINTS = 8
#: t-like score floor — below it a split is noise, not an onset
MIN_SCORE = 4.0


def detect_onset(points: Sequence[Tuple[int, float]], kind: str = "gauge",
                 min_points: int = MIN_POINTS,
                 min_score: float = MIN_SCORE) -> Optional[dict]:
    """The single strongest mean shift in one series, or None.

    ``points`` are time-ordered ``(t_ns, value)``. Counter series are
    first reduced to reset-aware positive deltas (a negative delta is a
    restart; the post-reset value IS the missing delta — the exact
    algebra ``Tsdb.rate`` uses), so a restarting worker cannot fake a
    cliff. The returned onset names the FIRST point of the right-hand
    (post-shift) segment:

        {"t_ns", "index", "direction" (+1 rise / -1 fall),
         "magnitude" (right mean - left mean), "score"}
    """
    pts = list(points)
    if len(pts) < min_points:
        return None
    if kind == "counter":
        ts: List[int] = []
        vals: List[float] = []
        prev = pts[0][1]
        for t, v in pts[1:]:
            d = v - prev
            vals.append(d if d >= 0 else v)
            ts.append(t)
            prev = v
    else:
        ts = [t for t, _v in pts]
        vals = [v for _t, v in pts]
    n = len(vals)
    if n < min_points:
        return None
    # prefix sums: every candidate split scored in O(1), the scan in O(n)
    ps = [0.0] * (n + 1)
    pss = [0.0] * (n + 1)
    for i, v in enumerate(vals):
        ps[i + 1] = ps[i] + v
        pss[i + 1] = pss[i] + v * v
    best_score = 0.0
    best_i = -1
    best_mag = 0.0
    for i in range(2, n - 1):
        nl = i
        nr = n - i
        ml = ps[i] / nl
        mr = (ps[n] - ps[i]) / nr
        var_l = max(0.0, pss[i] / nl - ml * ml)
        var_r = max(0.0, (pss[n] - pss[i]) / nr - mr * mr)
        pooled = math.sqrt((var_l * nl + var_r * nr) / n)
        score = abs(mr - ml) * math.sqrt(nl * nr / n) / (pooled + 1e-9)
        if score > best_score:
            best_score, best_i, best_mag = score, i, mr - ml
    if best_i < 0 or best_score < min_score:
        return None
    return {
        "t_ns": ts[best_i],
        "index": best_i,
        "direction": 1 if best_mag > 0 else -1,
        "magnitude": round(best_mag, 6),
        "score": round(min(best_score, 1e6), 2),
    }


def series_shifts(windows: Dict[str, List[Tuple[int, float]]],
                  kinds: Dict[str, str]) -> Dict[str, dict]:
    """Onsets for every series that has one (the cross-plane scan the
    tsdb-shift rule and the report's ``onsets`` block are built from)."""
    out: Dict[str, dict] = {}
    for name, pts in windows.items():
        onset = detect_onset(pts, kind=kinds.get(name, "gauge"))
        if onset is not None:
            out[name] = onset
    return out


# -- planes: one read-only adapter per evidence source -------------------------


class Planes:
    """Read-only view over every telemetry plane. The rules below speak
    ONLY this interface, so the live route and the offline bundle replay
    run the identical engine — parity is structural, not aspirational.
    Every accessor is total: a missing/broken plane reads as empty."""

    def __init__(self):
        self._shifts: Optional[Dict[str, dict]] = None

    # per-source accessors (overridden)
    def now_ns(self) -> int:
        return 0

    def windows(self) -> Dict[str, List[Tuple[int, float]]]:
        return {}

    def kinds(self) -> Dict[str, str]:
        return {}

    def flight_events(self) -> List[dict]:
        return []

    def watchdog(self) -> dict:
        return {}

    def slo(self) -> Optional[dict]:
        return None

    def seq(self) -> Optional[dict]:
        return None

    def waterfall(self) -> Optional[dict]:
        return None

    def native(self) -> Dict[str, float]:
        return {}

    # shared derived view
    def shifts(self) -> Dict[str, dict]:
        if self._shifts is None:
            self._shifts = series_shifts(self.windows(), self.kinds())
        return self._shifts


class LivePlanes(Planes):
    """The in-process view: tsdb snapshot, merged flight timeline
    (Python + native lanes), watchdog snapshot, SLO/seq/lens docs, C
    metrics table. Each source is fetched once and cached — one
    diagnosis is one consistent read."""

    def __init__(self, now_ns: Optional[int] = None):
        super().__init__()
        self._now = now_ns if now_ns is not None else time.monotonic_ns()
        self._windows: Optional[Dict[str, List[Tuple[int, float]]]] = None
        self._kinds: Dict[str, str] = {}
        self._flight: Optional[List[dict]] = None
        self._watchdog: Optional[dict] = None

    def now_ns(self) -> int:
        return self._now

    def windows(self) -> Dict[str, List[Tuple[int, float]]]:
        if self._windows is None:
            try:
                from tpurpc.obs import tsdb as _tsdb

                if _tsdb.enabled():
                    db = _tsdb.get()
                    self._windows = db.snapshot_windows(now_ns=self._now)
                    self._kinds = db.series()
                else:
                    self._windows = {}
            except Exception:
                self._windows = {}
        return self._windows

    def kinds(self) -> Dict[str, str]:
        self.windows()
        return self._kinds

    def flight_events(self) -> List[dict]:
        if self._flight is None:
            try:
                from tpurpc.obs import flight as _flight

                self._flight = _flight.snapshot(
                    since_ns=self._now - 120_000_000_000, limit=1024)
            except Exception:
                self._flight = []
        return self._flight

    def watchdog(self) -> dict:
        if self._watchdog is None:
            try:
                from tpurpc.obs import watchdog as _watchdog

                self._watchdog = _watchdog.get().snapshot()
            except Exception:
                self._watchdog = {}
        return self._watchdog

    def slo(self) -> Optional[dict]:
        # sys.modules gate: a process without an SLO plane stays without
        mod = sys.modules.get("tpurpc.obs.slo")
        if mod is None:
            return None
        try:
            return mod.slo_doc()
        except Exception:
            return None

    def seq(self) -> Optional[dict]:
        mod = sys.modules.get("tpurpc.obs.odyssey")
        if mod is None:
            return None
        try:
            return mod.seq_doc()
        except Exception:
            return None

    def waterfall(self) -> Optional[dict]:
        try:
            from tpurpc.obs import lens as _lens

            if not _lens.enabled():
                return None
            return _lens.waterfall()
        except Exception:
            return None

    def native(self) -> Dict[str, float]:
        try:
            from tpurpc.obs import native_obs as _nobs

            return _nobs.counters() or {}
        except Exception:
            return {}


class BundlePlanes(Planes):
    """The offline view: a PR-14 postmortem bundle directory replayed
    through the same interface. ``history.json`` feeds the tsdb windows
    (with its ``kinds`` map when present — older bundles fall back to
    name-suffix inference), ``flight-*.json`` the event algebra,
    ``stalls.json``/``slo.json``/``waterfall.json`` the rest. ``now``
    is the capture stamp (``meta.json`` ``t_mono_ns``) so edge ages are
    computed against WHEN the evidence froze, not when a human reads it."""

    def __init__(self, root: str):
        super().__init__()
        self.root = root
        self._history = self._load("history.json") or {}
        self._stalls = self._load("stalls.json") or {}
        self._slo = self._load("slo.json")
        self._waterfall = self._load("waterfall.json")
        self._meta = self._load("meta.json") or {}
        self._flight: List[dict] = []
        try:
            for name in sorted(os.listdir(root)):
                if name.startswith("flight-") and name.endswith(".json"):
                    evs = self._load(name)
                    if isinstance(evs, list):
                        self._flight.extend(
                            e for e in evs if isinstance(e, dict))
        except OSError:
            pass
        self._flight.sort(key=lambda e: e.get("t_ns", 0))

    def _load(self, fname: str):
        try:
            with open(os.path.join(self.root, fname),
                      encoding="utf-8") as f:
                return json.load(f)
        except Exception:
            return None

    def now_ns(self) -> int:
        t = self._meta.get("t_mono_ns")
        if t:
            return int(t)
        best = 0
        for pts in (self._history.get("series") or {}).values():
            if pts:
                best = max(best, int(pts[-1][0]))
        if self._flight:
            best = max(best, int(self._flight[-1].get("t_ns", 0)))
        return best

    def windows(self) -> Dict[str, List[Tuple[int, float]]]:
        series = self._history.get("series") or {}
        return {name: [(int(t), float(v)) for t, v in pts]
                for name, pts in series.items()}

    def kinds(self) -> Dict[str, str]:
        kinds = self._history.get("kinds")
        if isinstance(kinds, dict) and kinds:
            return kinds
        # pre-oracle bundles carry no kinds map: quantile exports are
        # named ``:pNN``/``:count``; everything else scores safest as a
        # gauge (counters merely lose the delta transform)
        out = {}
        for name in (self._history.get("series") or {}):
            out[name] = "quantile" if ":" in name else "gauge"
        return out

    def flight_events(self) -> List[dict]:
        return self._flight

    def watchdog(self) -> dict:
        return self._stalls

    def slo(self) -> Optional[dict]:
        return self._slo

    def waterfall(self) -> Optional[dict]:
        return self._waterfall

    def meta(self) -> dict:
        return self._meta


# -- symptom ------------------------------------------------------------------


def find_symptom(planes: Planes, want: Optional[str] = None
                 ) -> Optional[dict]:
    """Resolve what we are diagnosing: ``{"kind", "detail", ...}``.

    ``want`` None/"auto" walks the precedence ladder — an ACTIVE
    watchdog stall beats a firing SLO beats recent watchdog history
    (the bundle replay case: the trip that caused the capture is
    history by the time the snapshot freezes). "slo"/"watchdog" pin one
    plane; "healthz" is an alias for auto (healthz degradation IS
    watchdog-or-slo); any other string is an operator query diagnosed
    against every rule."""
    wd = planes.watchdog() or {}
    slo = planes.slo() or {}
    firing = slo.get("firing") or []
    active = wd.get("active") or []
    history = wd.get("history") or []

    def _wd_symptom(d: dict, state: str) -> dict:
        return {"kind": "watchdog", "state": state,
                "stage": d.get("stage"), "method": d.get("method"),
                "detail": d.get("detail"), "t_ns": d.get("since_ns")}

    def _slo_symptom(a: dict) -> dict:
        return {"kind": "slo", "state": "firing",
                "detail": f"{a.get('objective')}/{a.get('track')}",
                "t_ns": a.get("since_ns")}

    if want in (None, "", "auto", "healthz"):
        if active:
            return _wd_symptom(active[0], "active")
        if firing:
            return _slo_symptom(firing[0])
        if history:
            return _wd_symptom(history[-1], "history")
        return None
    if want == "watchdog":
        if active:
            return _wd_symptom(active[0], "active")
        if history:
            return _wd_symptom(history[-1], "history")
        return None
    if want == "slo":
        return _slo_symptom(firing[0]) if firing else None
    return {"kind": "query", "detail": want, "t_ns": None}


# -- hypotheses ---------------------------------------------------------------


class Hypothesis:
    """One candidate cause with its cited evidence. ``evidence`` is a
    list of ``(plane, ref, value)`` triples — ``plane`` names the source
    ("watchdog", "flight", "tsdb", "lens", "seq", "native"), ``ref`` is
    a chaseable locator inside it, ``value`` the observed number."""

    __slots__ = ("cause", "confidence", "evidence", "rule")

    def __init__(self, cause: str, confidence: float,
                 evidence: Optional[List[tuple]] = None,
                 rule: str = ""):
        self.cause = cause
        self.confidence = max(0.0, min(1.0, confidence))
        self.evidence = list(evidence or [])
        self.rule = rule


#: cause-slug prefix -> the hint autopilot (ROADMAP item 5) will consume.
#: Keys match the part of a cause before the first ":".
ACTIONS: Dict[str, str] = {
    "credit-starvation": "grow ring credits or shed load from this pair "
                         "(TPURPC_RING_SLOTS / reroute)",
    "peer-not-reading": "restart or drain the wedged peer; reroute its "
                        "pairs until it reads again",
    "h2-flow-control": "raise the h2 window or move bulk tensors to the "
                       "rendezvous path",
    "ctrl-ring": "bounce the peer's ring consumer; grow "
                 "TPURPC_CTRL_RING_SLOTS if sized too small",
    "rendezvous": "inspect the peer's claim path; lower "
                  "TPURPC_RENDEZVOUS_CLAIM_TIMEOUT_S to fail fast to "
                  "the framed path",
    "kv-swap": "throttle admissions until the swap clears; check host "
               "arena pressure",
    "migration": "cancel or retry the migration; check the destination "
                 "peer's health",
    "decode-step": "the model step is the long pole — check device "
                   "health / batch size, not the transport",
    "batcher-wait": "raise batcher concurrency or lower the fan-in "
                    "window",
    "poller-wake": "check poller thread liveness; a lost kick needs a "
                   "transport bounce",
    "device-infer": "the peer's handler/device is the long pole — "
                    "diagnose THAT process (fleet view: /fleet/diagnose)",
    "slo": "walk the cited evidence; if none, the objective may be "
           "mis-sized for current load",
    "native-ctrl-frozen": "the peer's C drain loop froze — restart the "
                          "peer process; capture its stacks first",
    "native-pin-wait": "a claim waiter holds landing windows across a "
                       "close — check for leaked claims on the peer",
    "native-rdv-fallback": "bulk sends degrading to framed path — check "
                           "claim timeouts and window placement failures",
    "native-delivery": "the delivery shard is not draining — check "
                       "decode/materialization backpressure",
    "hot-account": "one account dominates step time — rebalance or "
                   "rate-limit it (autopilot: shed/reroute the account)",
    "slow-hop": "the named hop is the pipeline bottleneck — rebalance "
                "copy work or grow that stage",
    "metric-shift": "unattributed shift — correlate the named series "
                    "with deploys/load changes",
}


def _action_for(cause: str) -> Optional[str]:
    return ACTIONS.get(cause.partition(":")[0])


# -- rule registry ------------------------------------------------------------


class Rule:
    """One declarative evidence rule. ``collect`` pulls facts from the
    planes (READ-ONLY — the ``diag`` lint rule audits it), ``score``
    turns them into hypotheses. ``symptom_kinds`` gates which symptom
    kinds the rule runs for (empty = all)."""

    __slots__ = ("name", "symptom_kinds", "collect", "score")

    def __init__(self, name: str, symptom_kinds: Sequence[str],
                 collect: Callable, score: Callable):
        self.name = name
        self.symptom_kinds = frozenset(symptom_kinds)
        self.collect = collect
        self.score = score


_RULES: List[Rule] = []


def register(rule: Rule) -> None:
    _RULES.append(rule)


def rules() -> List[Rule]:
    return list(_RULES)


# -- rule: watchdog stage (the most specific single witness) -------------------


def _collect_watchdog_stage(planes: Planes, symptom: dict) -> List[tuple]:
    snap = planes.watchdog() or {}
    facts = [("active", d) for d in (snap.get("active") or [])]
    facts.extend(("history", d) for d in (snap.get("history") or [])[-8:])
    return facts


def _score_watchdog_stage(facts: List[tuple], planes: Planes,
                          symptom: dict) -> List[Hypothesis]:
    out = []
    seen = set()
    for state, d in facts:
        stage = d.get("stage")
        if not stage:
            continue
        key = (stage, d.get("since_ns"))
        if key in seen:
            continue
        seen.add(key)
        conf = 0.9 if state == "active" else 0.55
        ev = [("watchdog", f"{state}:{d.get('method')}",
               d.get("age_s"))]
        cause = d.get("cause") or {}
        if cause.get("entity"):
            ev.append(("watchdog", "entity", cause["entity"]))
        for item in (cause.get("evidence") or [])[:4]:
            ev.append(tuple(item))
        out.append(Hypothesis(stage, conf, ev, rule="watchdog-stage"))
    return out


register(Rule("watchdog-stage", (), _collect_watchdog_stage,
              _score_watchdog_stage))


# -- rule: flight edge algebra near onset --------------------------------------


def _collect_flight_edges(planes: Planes, symptom: dict) -> dict:
    """Open-bracket algebra over the merged flight tail (native lane
    included) — the same pairing the watchdog sweeps, recomputed here so
    a bundle replay (or a diagnosis with the watchdog off) still has
    first-class edge evidence."""
    from tpurpc.obs import flight as _flight

    now = planes.now_ns()
    open_lease = 0
    lease_ent = None
    open_rdv: Dict[tuple, int] = {}
    open_ctrl: Dict[str, int] = {}
    open_nctrl: Dict[str, int] = {}
    open_pin: Dict[str, int] = {}
    open_dlv: Dict[str, int] = {}
    open_stall: Dict[str, int] = {}
    fallbacks: List[int] = []
    last_h2 = 0
    h2_ent = None
    for e in planes.flight_events():
        code = e.get("code")
        ent = e.get("entity")
        t = e.get("t_ns", 0)
        if code == _flight.LEASE_RESERVE:
            open_lease += 1
            lease_ent = ent
        elif code in (_flight.LEASE_COMMIT, _flight.LEASE_ABORT):
            open_lease = max(0, open_lease - 1)
        elif code == _flight.CTRL_STALL_BEGIN:
            (open_nctrl if e.get("lane") == "native"
             else open_ctrl)[ent] = t
        elif code == _flight.CTRL_STALL_END:
            (open_nctrl if e.get("lane") == "native"
             else open_ctrl).pop(ent, None)
        elif code == _flight.WRITE_STALL_BEGIN:
            open_stall[ent] = t
        elif code == _flight.WRITE_STALL_END:
            open_stall.pop(ent, None)
        elif code == _flight.NATIVE_PIN_WAIT_BEGIN:
            open_pin[ent] = t
        elif code == _flight.NATIVE_PIN_WAIT_END:
            open_pin.pop(ent, None)
        elif code == _flight.NATIVE_DLV_STALL_BEGIN:
            open_dlv[ent] = t
        elif code == _flight.NATIVE_DLV_STALL_END:
            open_dlv.pop(ent, None)
        elif code == _flight.NATIVE_RDV_FALLBACK:
            fallbacks.append(t)
        elif code == _flight.RDV_OFFER:
            open_rdv[(ent, "o", e.get("a1"))] = t
        elif code == _flight.RDV_CLAIM:
            open_rdv.pop((ent, "o", e.get("a1")), None)
            open_rdv[(ent, "l", e.get("a2"))] = t
        elif code in (_flight.RDV_COMPLETE, _flight.RDV_RELEASE):
            open_rdv.pop((ent, "l", e.get("a1")), None)
            if code == _flight.RDV_RELEASE:
                open_rdv.pop((ent, "o", e.get("a2")), None)
        elif code == _flight.H2_WINDOW_EXHAUSTED:
            last_h2 = t
            h2_ent = ent
    return {"now": now, "open_lease": open_lease, "lease_ent": lease_ent,
            "open_rdv": open_rdv, "open_ctrl": open_ctrl,
            "open_nctrl": open_nctrl, "open_pin": open_pin,
            "open_dlv": open_dlv, "open_stall": open_stall,
            "fallbacks": fallbacks, "last_h2": last_h2, "h2_ent": h2_ent}


def _edge_hyp(cause: str, conf: float, table: Dict, now: int,
              ref_prefix: str) -> Optional[Hypothesis]:
    if not table:
        return None
    ev = []
    for key, t in sorted(table.items(), key=lambda kv: kv[1])[:3]:
        ent = key[0] if isinstance(key, tuple) else key
        ev.append(("flight", f"{ref_prefix}:{ent}@{t}",
                   round((now - t) / 1e9, 3)))
    return Hypothesis(cause, conf, ev, rule="flight-edges")


def _score_flight_edges(facts: dict, planes: Planes,
                        symptom: dict) -> List[Hypothesis]:
    now = facts["now"]
    out: List[Hypothesis] = []
    if facts["open_lease"] > 0:
        out.append(Hypothesis(
            "credit-starvation", 0.6,
            [("flight", f"lease-reserve-open:{facts['lease_ent']}",
              facts["open_lease"])], rule="flight-edges"))
    for cause, conf, table, pref in (
            ("native-ctrl-frozen", 0.7, facts["open_nctrl"], "ctrl-stall"),
            ("ctrl-ring", 0.6, facts["open_ctrl"], "ctrl-stall"),
            ("native-pin-wait", 0.6, facts["open_pin"], "pin-wait"),
            ("native-delivery", 0.55, facts["open_dlv"], "dlv-stall"),
            ("peer-not-reading", 0.5, facts["open_stall"], "write-stall"),
            ("rendezvous", 0.5, facts["open_rdv"], "rdv-open")):
        # a fresh edge is traffic, not a wedge: only brackets open for
        # at least a second count as evidence on their own
        aged = {k: t for k, t in table.items() if now - t >= 1_000_000_000}
        h = _edge_hyp(cause, conf, aged, now, pref)
        if h is not None:
            out.append(h)
    recent_fb = [t for t in facts["fallbacks"] if now - t < 10_000_000_000]
    if len(recent_fb) >= 3:
        out.append(Hypothesis(
            "native-rdv-fallback", 0.6,
            [("flight", f"rdv-fallback@{t}", 1) for t in recent_fb[-3:]],
            rule="flight-edges"))
    if facts["last_h2"] and now - facts["last_h2"] < 15_000_000_000:
        out.append(Hypothesis(
            "h2-flow-control", 0.45,
            [("flight", f"h2-exhausted:{facts['h2_ent']}@{facts['last_h2']}",
              round((now - facts["last_h2"]) / 1e9, 3))],
            rule="flight-edges"))
    return out


register(Rule("flight-edges", (), _collect_flight_edges,
              _score_flight_edges))


# -- rule: tsdb rate shifts near onset -----------------------------------------

#: series-name fragment -> cause slug (ordered; first match wins)
_SERIES_CAUSE: List[Tuple[str, str]] = [
    ("write_stalled", "peer-not-reading"),
    ("credit", "credit-starvation"),
    ("ctrl_ring", "ctrl-ring"),
    ("rdv_fallback", "native-rdv-fallback"),
    ("fallback", "native-rdv-fallback"),
    ("pin_wait", "native-pin-wait"),
    ("dlv_", "native-delivery"),
    ("kv_swap", "kv-swap"),
    ("swap", "kv-swap"),
    ("migration", "migration"),
    ("h2_", "h2-flow-control"),
    ("batcher", "batcher-wait"),
    ("decode", "decode-step"),
]


def _collect_tsdb_shifts(planes: Planes, symptom: dict) -> List[tuple]:
    shifts = planes.shifts()
    t_sym = symptom.get("t_ns") if symptom else None
    out = []
    for name, onset in shifts.items():
        # when the symptom has an onset stamp, only shifts within ±60s
        # of it correlate; an operator query takes the whole window
        if t_sym and abs(onset["t_ns"] - t_sym) > 60_000_000_000:
            continue
        out.append((name, onset))
    out.sort(key=lambda kv: kv[1]["score"], reverse=True)
    return out[:12]


def _score_tsdb_shifts(facts: List[tuple], planes: Planes,
                       symptom: dict) -> List[Hypothesis]:
    out = []
    for name, onset in facts:
        cause = None
        for frag, slug in _SERIES_CAUSE:
            if frag in name:
                cause = slug
                break
        ev = [("tsdb", f"{name}@{onset['t_ns']}",
               onset["magnitude"])]
        if cause is None:
            # watchdog_stalls{stage} shifting IS the stage's counter
            if name.startswith("watchdog_stalls{"):
                cause = name[len("watchdog_stalls{"):].rstrip("}")
                out.append(Hypothesis(cause, 0.4, ev, rule="tsdb-shift"))
            else:
                out.append(Hypothesis(
                    f"metric-shift:{name}", 0.2, ev, rule="tsdb-shift"))
            continue
        conf = 0.45 * min(1.0, onset["score"] / 8.0)
        out.append(Hypothesis(cause, conf, ev, rule="tsdb-shift"))
    return out


register(Rule("tsdb-shift", (), _collect_tsdb_shifts, _score_tsdb_shifts))


# -- rule: lens slowest hop (corroborative) ------------------------------------


def _collect_lens_hop(planes: Planes, symptom: dict) -> Optional[dict]:
    return planes.waterfall()


def _score_lens_hop(facts: Optional[dict], planes: Planes,
                    symptom: dict) -> List[Hypothesis]:
    if not facts:
        return []
    slowest = facts.get("slowest_hop")
    if not slowest:
        return []
    row = next((r for r in facts.get("hops", [])
                if r.get("hop") == slowest), {})
    if not row.get("busy_ms"):
        return []
    return [Hypothesis(
        f"slow-hop:{slowest}", 0.3,
        [("lens", f"hop:{slowest}", row.get("gbps"))], rule="lens-hop")]


register(Rule("lens-hop", (), _collect_lens_hop, _score_lens_hop))


# -- rule: seq-ledger costliest account ----------------------------------------


def _collect_seq_ledger(planes: Planes, symptom: dict) -> Optional[dict]:
    return planes.seq()


def _score_seq_ledger(facts: Optional[dict], planes: Planes,
                      symptom: dict) -> List[Hypothesis]:
    if not facts or not facts.get("enabled"):
        return []
    accounts = facts.get("accounts") or {}
    total = float(facts.get("step_us_total") or 0.0)
    if not accounts or total <= 0:
        return []
    name, row = max(accounts.items(),
                    key=lambda kv: kv[1].get("step_us", 0))
    share = (row.get("step_us") or 0) / total
    if share < 0.5:
        return []
    return [Hypothesis(
        f"hot-account:{name}", 0.35,
        [("seq", f"account:{name}", round(share, 3))],
        rule="seq-ledger")]


register(Rule("seq-ledger", (), _collect_seq_ledger, _score_seq_ledger))


# -- rule: native fallback/stall counters (corroborative) ----------------------


def _collect_native_counters(planes: Planes,
                             symptom: dict) -> Dict[str, float]:
    return planes.native()


def _score_native_counters(facts: Dict[str, float], planes: Planes,
                           symptom: dict) -> List[Hypothesis]:
    out = []
    for key, cause, conf in (("rdv_fallbacks", "native-rdv-fallback", 0.25),
                             ("dlv_stalls", "native-delivery", 0.2),
                             ("pin_waits", "native-pin-wait", 0.15)):
        v = facts.get(key) or 0
        if v > 0:
            out.append(Hypothesis(
                cause, conf, [("native", key, v)],
                rule="native-counters"))
    return out


register(Rule("native-counters", (), _collect_native_counters,
              _score_native_counters))


# -- combination + ranking -----------------------------------------------------


def _combine(hyps: List[Hypothesis]) -> List[dict]:
    """Noisy-OR per cause: independent planes agreeing compound, one
    plane repeating itself does not (evidence dedups on (plane, ref))."""
    by: Dict[str, dict] = {}
    for h in hyps:
        agg = by.setdefault(h.cause, {"cause": h.cause, "miss": 1.0,
                                      "evidence": [], "rules": [],
                                      "_seen": set()})
        agg["miss"] *= (1.0 - h.confidence)
        if h.rule and h.rule not in agg["rules"]:
            agg["rules"].append(h.rule)
        for plane, ref, value in h.evidence:
            k = (plane, ref)
            if k in agg["_seen"]:
                continue
            agg["_seen"].add(k)
            if len(agg["evidence"]) < 8:
                agg["evidence"].append([plane, ref, value])
    out = []
    for agg in by.values():
        conf = min(0.99, 1.0 - agg["miss"])
        out.append({"cause": agg["cause"],
                    "confidence": round(conf, 3),
                    "evidence": agg["evidence"],
                    "rules": agg["rules"],
                    "actionable": _action_for(agg["cause"])})
    out.sort(key=lambda d: (-d["confidence"], d["cause"]))
    return out


# -- the engine ----------------------------------------------------------------


def diagnose(planes: Planes, want: Optional[str] = None) -> dict:
    """Run every applicable rule and return the ranked report — the one
    document all three faces serve."""
    symptom = find_symptom(planes, want)
    hyps: List[Hypothesis] = []
    if symptom is not None:
        kind = symptom.get("kind")
        for rule in _RULES:
            if rule.symptom_kinds and kind not in rule.symptom_kinds:
                continue
            try:
                facts = rule.collect(planes, symptom)
                hyps.extend(rule.score(facts, planes, symptom) or [])
            except Exception:
                continue  # one broken rule must never break the report
    shifts = planes.shifts()
    top = sorted(shifts.items(), key=lambda kv: kv[1]["score"],
                 reverse=True)[:16]
    return {
        "enabled": True,
        "symptom": symptom,
        "hypotheses": _combine(hyps),
        "onsets": {name: onset for name, onset in top},
        "rules_run": [r.name for r in _RULES],
    }


def diagnose_doc(params: Optional[dict] = None) -> dict:
    """``GET /debug/diagnose`` body (the scrape-plane face)."""
    params = params or {}
    if not enabled():
        return {"enabled": False, "reason": "TPURPC_DIAGNOSE=0"}
    doc = diagnose(LivePlanes(), want=params.get("symptom") or None)
    from tpurpc.obs import shard as _shard

    if _shard.shard_id() >= 0:
        doc["shard"] = _shard.shard_id()
    return doc


def diagnose_bundle(root: str, want: Optional[str] = None) -> dict:
    """The offline face: replay a postmortem bundle directory through
    the identical engine (``python -m tpurpc.tools.diagnose <dir>``)."""
    planes = BundlePlanes(root)
    doc = diagnose(planes, want=want)
    doc["bundle"] = os.path.basename(os.path.abspath(root))
    meta = planes.meta()
    if meta:
        doc["trigger"] = meta.get("trigger")
    return doc


def merge_diagnose_docs(docs: Dict[str, dict], label: str = "shard"
                        ) -> dict:
    """The pure shard/fleet merge: per-source reports keyed by shard id
    or member target -> one report. Hypotheses re-combine by cause
    across sources (noisy-OR again), each evidence row tagged with its
    source; ``corroboration`` lists which sources cite each cause — the
    "3 members degraded, all cite the same peer" signal the fleet face
    exists for."""
    merged: Dict[str, dict] = {}
    symptoms: List[dict] = []
    enabled_any = False
    for src in sorted(docs):
        doc = docs[src] or {}
        if not doc.get("enabled"):
            continue
        enabled_any = True
        sym = doc.get("symptom")
        if sym:
            symptoms.append(dict(sym, **{label: src}))
        for h in doc.get("hypotheses", ()):
            agg = merged.setdefault(h["cause"], {
                "cause": h["cause"], "miss": 1.0, "evidence": [],
                "rules": [], "sources": [],
                "actionable": h.get("actionable")})
            agg["miss"] *= (1.0 - (h.get("confidence") or 0.0))
            agg["sources"].append(src)
            for r in h.get("rules", ()):
                if r not in agg["rules"]:
                    agg["rules"].append(r)
            for plane, ref, value in h.get("evidence", ()):
                if len(agg["evidence"]) < 12:
                    agg["evidence"].append(
                        [plane, f"{label}={src}:{ref}", value])
    hyps = []
    for agg in merged.values():
        hyps.append({"cause": agg["cause"],
                     "confidence": round(min(0.99, 1.0 - agg["miss"]), 3),
                     "evidence": agg["evidence"],
                     "rules": agg["rules"],
                     "sources": agg["sources"],
                     "actionable": agg["actionable"]})
    hyps.sort(key=lambda d: (-d["confidence"], d["cause"]))
    # watchdog symptoms outrank slo outrank query; active beats history
    order = {"watchdog": 0, "slo": 1, "healthz": 2, "query": 3}
    symptoms.sort(key=lambda s: (order.get(s.get("kind"), 9),
                                 s.get("state") != "active"))
    return {
        "enabled": enabled_any,
        "sources": sorted(docs),
        "symptom": symptoms[0] if symptoms else None,
        "symptoms": symptoms,
        "hypotheses": hyps,
        "corroboration": {c: a["sources"] for c, a in merged.items()
                          if len(a["sources"]) > 1},
    }


# -- text face ----------------------------------------------------------------


def render_text(doc: Optional[dict] = None) -> str:
    """The ``?text=1`` / CLI rendering of one report."""
    if doc is None:
        doc = diagnose_doc()
    if not doc.get("enabled"):
        return f"diagnose: disabled ({doc.get('reason')})\n"
    lines = []
    sym = doc.get("symptom")
    if sym is None:
        lines.append("diagnose: no active symptom")
    else:
        what = sym.get("stage") or sym.get("detail") or sym.get("kind")
        lines.append(f"symptom [{sym.get('kind')}] {what}"
                     + (f" method={sym['method']}"
                        if sym.get("method") else ""))
    hyps = doc.get("hypotheses") or []
    if not hyps:
        lines.append("  no hypotheses")
    for i, h in enumerate(hyps[:8], 1):
        lines.append(f"  #{i} {h['cause']:<24} "
                     f"confidence={h['confidence']:.2f} "
                     f"rules={','.join(h.get('rules', []))}")
        for plane, ref, value in h.get("evidence", [])[:4]:
            lines.append(f"       [{plane}] {ref} = {value}")
        if h.get("actionable"):
            lines.append(f"       -> {h['actionable']}")
    cor = doc.get("corroboration")
    if cor:
        for cause, srcs in sorted(cor.items()):
            lines.append(f"  corroborated: {cause} cited by "
                         f"{len(srcs)} sources ({', '.join(map(str, srcs))})")
    return "\n".join(lines) + "\n"
