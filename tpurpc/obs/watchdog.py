"""tpurpc-blackbox stall watchdog: find the wedged RPC and name the stage.

A serving fleet's worst page is "one call is stuck and nothing says where".
The watchdog is a background sweeper over an in-process registry of
in-flight RPCs (both server handlers and the pipelined client's windows
register): any call in flight past a multiple of its method's ROLLING p99
— or past a static floor when the method has no history yet — produces a
structured diagnosis naming the blocked *stage*, derived from the flight
recorder's tail plus the scrape plane's fleet gauges:

* ``credit-starvation`` — an open (unmatched) send-lease reserve, an
  unresolved ring credit-starvation edge, or a freshly write-stalled pair;
* ``peer-not-reading`` — a write stall/starvation that has persisted well
  past the stall bar (the peer is alive but not draining its ring);
* ``h2-flow-control`` — an h2 send window exhausted within the stall
  window (the peer stopped granting WINDOW_UPDATE credit);
* ``batcher-wait`` — requests parked in the fan-in batcher's queue;
* ``poller-wake`` — a pair with a complete message waiting that no waiter
  has drained (wake-latency / lost-kick territory);
* ``device-infer`` — the transport is quiet and the handler is simply
  still executing (the model/device is the long pole).

Diagnoses are served at ``GET /debug/stalls``, mirrored into the
``watchdog_trips`` / ``watchdog_stalls{stage}`` anomaly counters, flip
``/healthz`` to degraded (503) while active, flag the call's trace for
tail capture (:func:`tpurpc.obs.tracing.tail_flag` — so the postmortem has
the span tree), and log one flight-recorder replay per trip.

Cost: registration is a dict store + one monotonic stamp per RPC;
completion feeds a fixed-size rolling duration window per method (p99
computed lazily, cached 0.5 s). The sweeper is one daemon thread at
``TPURPC_WATCHDOG_SWEEP_S`` (default 0.25 s) that does nothing while no
call is over its bar. ``TPURPC_WATCHDOG=0`` disables everything.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from tpurpc.obs import flight as _flight
from tpurpc.obs import metrics as _metrics
from tpurpc.obs import profiler as _obs_profiler

__all__ = ["StallWatchdog", "get", "call_started", "call_finished",
           "STAGES"]

#: tpurpc-lens: the sweeper thread parked between sweeps is infrastructure
#: idle time, not unattributed serving work
_LENS_STAGES = {"_loop": "idle", "sweep_once": "idle"}
_obs_profiler.register_stages(__file__, _LENS_STAGES)

_log = logging.getLogger("tpurpc.watchdog")

STAGES = ("credit-starvation", "peer-not-reading", "h2-flow-control",
          "ctrl-ring", "rendezvous", "kv-swap", "migration", "decode-step",
          "batcher-wait", "poller-wake", "device-infer", "slo", "unknown",
          # tpurpc-xray (ISSUE 19): stages diagnosed from the C core's
          # shm flight ring + metrics table — evidence the Python plane
          # cannot see (append-only, like the event codes)
          "native-ctrl-frozen", "native-pin-wait", "native-rdv-fallback",
          "native-delivery")

# tpurpc-argus (ISSUE 14): trip hooks — automatic evidence capture
# (obs/bundle.py) registers here so every sweeper trip and every external
# trip (a firing SLO page routes through external_trip) can snapshot a
# postmortem bundle. Hooks run on the tripping thread (the sweeper or the
# SLO evaluator — never an RPC hot path) and must never raise outward.

_trip_hooks: List = []


def add_trip_hook(fn) -> None:
    """Register ``fn(diag_dict)`` to run once per NEW trip (sweeper or
    external). Duplicate registrations are ignored."""
    if fn not in _trip_hooks:
        _trip_hooks.append(fn)


def remove_trip_hook(fn) -> None:
    try:
        _trip_hooks.remove(fn)
    except ValueError:
        pass


def _run_trip_hooks(diag: dict) -> None:
    for fn in list(_trip_hooks):
        try:
            fn(diag)
        except Exception:
            _log.exception("watchdog trip hook failed")

#: anomaly counters (always-on registry): total trips + per-stage breakdown
_TRIPS = _metrics.counter("watchdog_trips")
_STALLS = _metrics.labeled_counter("watchdog_stalls", ("stage",))

_BEGIN_END = {
    _flight.WRITE_STALL_BEGIN: _flight.WRITE_STALL_END,
    _flight.CREDIT_STARVE_BEGIN: _flight.CREDIT_STARVE_END,
}


class _Roll:
    """Fixed-size rolling duration window per method; p99 cached 0.5 s."""

    __slots__ = ("buf", "n", "_p99", "_stamp")
    SIZE = 128

    def __init__(self):
        self.buf = [0] * self.SIZE
        self.n = 0
        self._p99 = None
        self._stamp = 0.0

    def record(self, dur_ns: int) -> None:
        self.buf[self.n % self.SIZE] = dur_ns
        self.n += 1

    def p99_ns(self) -> Optional[int]:
        if self.n < 8:
            return None  # too little history to call anything an outlier
        now = time.monotonic()
        if self._p99 is None or now - self._stamp > 0.5:
            window = sorted(self.buf[:min(self.n, self.SIZE)])
            self._p99 = window[max(0, int(len(window) * 0.99) - 1)]
            self._stamp = now
        return self._p99


class StallWatchdog:
    def __init__(self, sweep_s: Optional[float] = None,
                 mult: Optional[float] = None,
                 min_stall_s: Optional[float] = None):
        import os

        self.enabled = os.environ.get("TPURPC_WATCHDOG", "1").lower() not in (
            "0", "off", "false")
        self.sweep_s = sweep_s if sweep_s is not None else float(
            os.environ.get("TPURPC_WATCHDOG_SWEEP_S", "0.25"))
        self.mult = mult if mult is not None else float(
            os.environ.get("TPURPC_WATCHDOG_MULT", "8"))
        self.min_stall_s = min_stall_s if min_stall_s is not None else float(
            os.environ.get("TPURPC_WATCHDOG_MIN_S", "1.0"))
        #: token -> [method, t0_ns, trace_id, kind, tripped]
        self._inflight: Dict[int, list] = {}
        self._tokens = itertools.count(1)
        self._rolls: Dict[str, _Roll] = {}
        self._active: List[dict] = []
        self._history: deque = deque(maxlen=64)
        self._thread: Optional[threading.Thread] = None
        self._thread_lock = threading.Lock()
        self._wake = threading.Event()

    # -- per-RPC face (hot-ish: one dict store / delete) ----------------------

    def call_started(self, method: str, trace_id: int = 0,
                     kind: str = "server") -> Optional[int]:
        if not self.enabled:
            return None
        tok = next(self._tokens)
        # [method, t0, trace_id, kind, tripped-stages] — the last slot
        # records which stages already paged for THIS call (tpurpc-oracle:
        # a diagnosis that sharpens, e.g. rendezvous -> native-ctrl-frozen
        # once the C evidence lands, re-trips under the sharper stage so
        # the trip hooks capture the better story; each stage at most once)
        self._inflight[tok] = [method, time.monotonic_ns(), trace_id, kind,
                               set()]
        if self._thread is None:
            self._ensure_thread()
        return tok

    def call_finished(self, token: Optional[int],
                      error: bool = False) -> None:
        if token is None:
            return
        entry = self._inflight.pop(token, None)
        if entry is None or error:
            return  # failures don't tighten the p99 bar
        dur = time.monotonic_ns() - entry[1]
        method = entry[0]
        roll = self._rolls.get(method)
        if roll is None:
            if len(self._rolls) >= 256:
                return  # bounded method cardinality
            roll = self._rolls.setdefault(method, _Roll())
        roll.record(dur)

    def slow_threshold_ns(self, method: str) -> Optional[int]:
        """``mult × rolling-p99`` for tail capture's slow bar, or None
        without enough history."""
        roll = self._rolls.get(method)
        if roll is None:
            return None
        p99 = roll.p99_ns()
        return None if p99 is None else int(p99 * self.mult)

    def rolling_p99_ns(self) -> Optional[int]:
        """The WORST rolling p99 across methods with history, or None —
        tpurpc-fleet's admission gate and load reports read this as the
        server's latency signal (one method in trouble is the fleet
        signal; averaging would hide it)."""
        worst = None
        for roll in list(self._rolls.values()):
            p99 = roll.p99_ns()
            if p99 is not None and (worst is None or p99 > worst):
                worst = p99
        return worst

    def method_p99s(self) -> Dict[str, int]:
        """Per-method rolling p99s (ns) for methods with enough history —
        tpurpc-argus's tsdb samples these into ``watchdog_p99_us{method}``
        series: unlike the cumulative ``srv_call_us`` histogram, a rolling
        window RECOVERS after a degradation ends, which is what a burn-
        rate alert must see to resolve."""
        out: Dict[str, int] = {}
        for method, roll in list(self._rolls.items()):
            p99 = roll.p99_ns()
            if p99 is not None:
                out[method] = p99
        return out

    # -- the sweeper ----------------------------------------------------------

    def _ensure_thread(self) -> None:
        with self._thread_lock:
            if self._thread is not None:
                return
            t = threading.Thread(target=self._loop, daemon=True,
                                 name="tpurpc-watchdog")
            self._thread = t
            t.start()

    def _loop(self) -> None:
        while True:
            self._wake.wait(timeout=self.sweep_s)
            self._wake.clear()
            try:
                self.sweep_once()
            except Exception:  # the watchdog must never take anything down
                _log.exception("watchdog sweep failed")

    def _stall_bar_ns(self, method: str) -> int:
        bar = int(self.min_stall_s * 1e9)
        p99m = self.slow_threshold_ns(method)
        if p99m is not None:
            bar = max(bar, p99m)  # never page on a method's normal tail
        return bar

    def sweep_once(self, now_ns: Optional[int] = None) -> List[dict]:
        """One sweep: rebuild the active diagnosis list; fire trip actions
        for newly detected stalls. Exposed for tests (deterministic
        sweeps) — the daemon loop calls it on the configured cadence."""
        now = now_ns if now_ns is not None else time.monotonic_ns()
        active: List[dict] = []
        to_trip: List[tuple] = []
        evidence = None
        for tok, entry in list(self._inflight.items()):
            method, t0, trace_id, kind, tripped_stages = entry
            age = now - t0
            if age < self._stall_bar_ns(method):
                continue
            if evidence is None:
                evidence = self._gather_evidence(now)
            stage, detail = self._attribute(evidence, kind, age)
            diag = {
                "method": method,
                "kind": kind,
                "stage": stage,
                "detail": detail,
                # tpurpc-oracle: the same diagnosis as a structured
                # object (stage + entity + evidence refs) — the prose
                # above stays byte-identical for the text face
                "cause": self._cause_struct(evidence, stage),
                "age_s": round(age / 1e9, 3),
                "trace_id": f"{trace_id:016x}" if trace_id else None,
                "since_ns": t0,
            }
            active.append(diag)
            if stage not in tripped_stages:
                tripped_stages.add(stage)
                to_trip.append((diag, trace_id, age))
        self._active = active
        if active:
            for d in active:
                done = {"t": time.time()}  # tpr: allow(wallclock)
                done.update(d)
                if not self._history or self._history[-1].get(
                        "since_ns") != d["since_ns"] or \
                        self._history[-1].get("stage") != d["stage"]:
                    self._history.append(done)
        # trips fire AFTER the snapshot state is updated: a trip hook
        # (the bundle writer, tpurpc-oracle's diagnosis) that reads
        # ``snapshot()`` must see the diagnosis that tripped it
        for diag, trace_id, age in to_trip:
            self._trip(diag, trace_id, age)
        return active

    def _trip(self, diag: dict, trace_id: int, age_ns: int) -> None:
        _TRIPS.inc()
        _STALLS.labels(diag["stage"]).inc()
        _flight.emit(_flight.WATCHDOG_TRIP,
                     _flight.tag_for(diag["method"]), age_ns // 1_000_000)
        if trace_id:
            # postmortem spans: promote the wedged call's provisional trace
            # NOW, while it is still in flight — /traces has the tree even
            # if the call never completes
            from tpurpc.obs import tracing as _tracing

            _tracing.tail_flag(trace_id)
        # module-level dump_text, not the recorder's: the trip log must
        # replay the MERGED timeline — native-plane stages cite C evidence
        _log.warning(
            "stall: %s %s in flight %.2fs — stage %s (%s)\n%s",
            diag["kind"], diag["method"], diag["age_s"], diag["stage"],
            diag["detail"],
            _flight.dump_text(
                since_ns=diag["since_ns"] - 1_000_000_000))
        _run_trip_hooks(diag)

    def external_trip(self, stage: str, method: str, detail: str) -> None:
        """A trip raised by another verification subsystem rather than the
        sweeper — tpurpc-proof's live protocol verifier
        (``TPURPC_VERIFY_PROTOCOL=1``) calls this when a declared flight
        machine sees an illegal transition. Counts like a sweeper trip
        (``watchdog_trips`` / ``watchdog_stalls{stage}``), lands in the
        history served at ``/debug/stalls``, and logs one flight replay —
        but registers no in-flight call (there is nothing to age out)."""
        if not self.enabled:
            return
        _TRIPS.inc()
        _STALLS.labels(stage).inc()
        diag = {
            "method": method,
            "kind": "external",
            "stage": stage,
            "detail": detail,
            # an external verifier supplies no flight-edge evidence
            "cause": {"stage": stage, "entity": None, "evidence": []},
            "age_s": 0.0,
            "trace_id": None,
            "since_ns": time.monotonic_ns(),
        }
        done = {"t": time.time()}  # tpr: allow(wallclock)
        done.update(diag)
        self._history.append(done)
        _log.warning(
            "external trip: %s — stage %s (%s)\n%s",
            method, stage, detail,
            _flight.dump_text(
                since_ns=time.monotonic_ns() - 2_000_000_000))
        _run_trip_hooks(diag)

    # -- stage attribution ----------------------------------------------------

    def _gather_evidence(self, now_ns: int) -> dict:
        """One pass over the flight tail + fleet gauges, shared by every
        diagnosis in a sweep."""
        # the MERGED timeline (tpurpc-xray): the module-level snapshot
        # folds the C core's shm flight ring in, so native rdv/ctrl edges
        # and the native-only codes below are first-class evidence
        events = _flight.snapshot(
            since_ns=now_ns - 60_000_000_000, limit=512)
        open_lease = 0
        open_edges: Dict[tuple, int] = {}  # (begin_code, tag) -> t_ns
        # tpurpc-express: unmatched rendezvous edges — an OFFER the peer
        # never claimed ((tag, 'o', req)) or a claimed region never
        # completed/released ((tag, 'l', lease)) — are the evidence a call
        # is wedged INSIDE a bulk-tensor handoff, not in the ring/h2 path
        open_rdv: Dict[tuple, int] = {}
        # tpurpc-pulse: an open (unmatched) ring-full stall edge — the
        # producer sees the peer's descriptor ring full and the consumer
        # is not draining it; paired with a nonzero ctrl_ring_backlog
        # gauge this outranks the generic rendezvous story (the wedge is
        # the CONTROL plane, not the transfer)
        open_ctrl: Dict[int, int] = {}
        # tpurpc-cadence: per-scheduler step bracket — an open
        # GEN_STEP_BEGIN (no matching END) is a decode step IN the model
        # right now; its age says whether that is traffic or a wedge. The
        # last END stamp catches the other failure shape: sequences
        # waiting while the loop has stopped stepping entirely.
        open_step: Dict[int, int] = {}
        # tpurpc-keystone: open swap/migration brackets — a KV_SWAP_BEGIN
        # or MIG_BEGIN with no matching END is a sequence mid-move; aged
        # past the stall floor it is the wedge, and it outranks the
        # generic decode-step story (more specific evidence wins)
        open_swap: Dict[tuple, int] = {}
        open_mig: Dict[tuple, int] = {}
        # tpurpc-xray: native-plane evidence. A C-side tx-ring-full stall
        # (CTRL_STALL_BEGIN on an "nctrl:*" entity) is a FROZEN C CONSUMER
        # — the peer's native drain loop stopped; a pin-wait bracket is a
        # link close() wedged behind window pins; delivery-stall brackets
        # and recent fallbacks come straight off the C ring.
        open_nctrl: Dict[int, int] = {}
        open_pin: Dict[int, int] = {}
        open_dlv: Dict[int, int] = {}
        native_fallbacks: List[int] = []
        last_step_end = 0
        last_step_batch = 0
        last_h2 = 0
        for e in events:
            code = e["code"]
            if code == _flight.LEASE_RESERVE:
                open_lease += 1
            elif code in (_flight.LEASE_COMMIT, _flight.LEASE_ABORT):
                open_lease = max(0, open_lease - 1)
            elif code in _BEGIN_END:
                open_edges[(code, e["tag"])] = e["t_ns"]
            elif code in _BEGIN_END.values():
                for b, en in _BEGIN_END.items():
                    if en == code:
                        open_edges.pop((b, e["tag"]), None)
            elif code == _flight.H2_WINDOW_EXHAUSTED:
                last_h2 = e["t_ns"]
            elif code == _flight.CTRL_STALL_BEGIN:
                if e.get("lane") == "native":
                    open_nctrl[e["tag"]] = e["t_ns"]
                else:
                    open_ctrl[e["tag"]] = e["t_ns"]
            elif code == _flight.CTRL_STALL_END:
                if e.get("lane") == "native":
                    open_nctrl.pop(e["tag"], None)
                else:
                    open_ctrl.pop(e["tag"], None)
            elif code == _flight.NATIVE_PIN_WAIT_BEGIN:
                open_pin[e["tag"]] = e["t_ns"]
            elif code == _flight.NATIVE_PIN_WAIT_END:
                open_pin.pop(e["tag"], None)
            elif code == _flight.NATIVE_DLV_STALL_BEGIN:
                open_dlv[e["tag"]] = e["t_ns"]
            elif code == _flight.NATIVE_DLV_STALL_END:
                open_dlv.pop(e["tag"], None)
            elif code == _flight.NATIVE_RDV_FALLBACK:
                native_fallbacks.append(e["t_ns"])
            elif code == _flight.RDV_OFFER:
                open_rdv[(e["tag"], "o", e["a1"])] = e["t_ns"]
            elif code == _flight.RDV_CLAIM:
                open_rdv.pop((e["tag"], "o", e["a1"]), None)
                open_rdv[(e["tag"], "l", e["a2"])] = e["t_ns"]
            elif code == _flight.RDV_COMPLETE:
                open_rdv.pop((e["tag"], "l", e["a1"]), None)
            elif code == _flight.RDV_RELEASE:
                open_rdv.pop((e["tag"], "l", e["a1"]), None)
                open_rdv.pop((e["tag"], "o", e["a2"]), None)
            elif code == _flight.GEN_STEP_BEGIN:
                open_step[e["tag"]] = e["t_ns"]
                last_step_batch = e["a1"]
            elif code == _flight.GEN_STEP_END:
                open_step.pop(e["tag"], None)
                last_step_end = e["t_ns"]
            elif code == _flight.KV_SWAP_BEGIN:
                open_swap[(e["tag"], e["a1"])] = e["t_ns"]
            elif code == _flight.KV_SWAP_END:
                open_swap.pop((e["tag"], e["a1"]), None)
            elif code == _flight.MIG_BEGIN:
                open_mig[(e["tag"], e["a1"])] = e["t_ns"]
            elif code == _flight.MIG_END:
                open_mig.pop((e["tag"], e["a1"]), None)

        # tpurpc-xray: the C metrics table backs the flight-tail evidence
        # (depth gauge for the delivery story, fallback total for storms)
        try:
            from tpurpc.obs import native_obs as _nobs

            ntab = _nobs.counters()
        except Exception:
            ntab = {}

        def fleet_sum(name: str) -> float:
            m = _metrics.registry().metrics().get(name)
            if m is None or not isinstance(m, _metrics.FleetGauge):
                return 0.0
            return m.collect()[0]

        return {
            "now_ns": now_ns,
            "open_lease": open_lease,
            "open_edges": open_edges,
            "open_rdv": open_rdv,
            "open_ctrl": open_ctrl,
            "ctrl_ring_backlog": fleet_sum("ctrl_ring_backlog"),
            "open_nctrl": open_nctrl,
            "open_pin": open_pin,
            "open_dlv": open_dlv,
            "native_fallbacks": native_fallbacks,
            "native_dlv_depth": ntab.get("dlv_depth", 0),
            "native_fallback_total": ntab.get("rdv_fallbacks", 0),
            "open_swap": open_swap,
            "open_mig": open_mig,
            "open_step": open_step,
            "last_step_end_ns": last_step_end,
            "last_step_batch": last_step_batch,
            "last_h2_ns": last_h2,
            "pairs_write_stalled": fleet_sum("pairs_write_stalled"),
            "batcher_queue_depth": fleet_sum("batcher_queue_depth"),
            "pairs_msg_waiting": fleet_sum("pairs_msg_waiting"),
            "decode_waiting": fleet_sum("decode_waiting"),
            "decode_running": fleet_sum("decode_running"),
        }

    def _attribute(self, ev: dict, kind: str, age_ns: int) -> tuple:
        now = ev["now_ns"]
        starve_age = 0
        for (code, tag), t in ev["open_edges"].items():
            starve_age = max(starve_age, now - t)
        if ev["open_lease"] > 0:
            return ("credit-starvation",
                    "send-lease held: reserve without commit/abort in the "
                    "flight tail — the ring write lock is wedged")
        # tpurpc-xray: a C-side tx-ring-full stall bracket is the most
        # specific control-plane story there is — the peer's NATIVE drain
        # loop (poller/pump thread) froze, diagnosed purely from C
        # evidence (the Python plane never sees these posts at all)
        open_nctrl = ev.get("open_nctrl") or {}
        if open_nctrl:
            oldest = max(now - t for t in open_nctrl.values())
            if oldest >= self.min_stall_s * 1e9 / 2:
                return ("native-ctrl-frozen",
                        f"native ctrl ring full {oldest / 1e9:.2f}s on "
                        f"{len(open_nctrl)} link(s): the peer's C consumer "
                        "stopped draining its descriptor ring")
        # tpurpc-pulse: a stuck descriptor ring is MORE specific than the
        # rendezvous story it wedges — the control op (offer/claim/
        # complete) is sitting in a ring nobody drains.  Evidence: an aged
        # open ring-full stall bracket, or posted-but-unconsumed records
        # (the backlog gauge) behind an aged rendezvous edge.
        open_ctrl = ev.get("open_ctrl") or {}
        backlog = ev.get("ctrl_ring_backlog", 0)
        open_rdv = ev.get("open_rdv") or {}
        ctrl_age = 0
        if open_ctrl:
            ctrl_age = max(now - t for t in open_ctrl.values())
        elif backlog > 0 and open_rdv:
            ctrl_age = max(now - t for t in open_rdv.values())
        if ctrl_age >= self.min_stall_s * 1e9 / 2:
            return ("ctrl-ring",
                    f"descriptor-ring control plane stalled "
                    f"{ctrl_age / 1e9:.2f}s: {int(backlog)} posted "
                    f"record(s) undrained"
                    + (f", {len(open_ctrl)} link(s) ring-full"
                       if open_ctrl else "")
                    + " — the peer's ring consumer stopped draining")
        if open_rdv:
            oldest = max(now - t for t in open_rdv.values())
            # a fresh edge is a transfer IN PROGRESS (claim round trips are
            # µs-scale); only an edge aged past half the stall floor is
            # evidence of a wedge rather than of traffic
            if oldest >= self.min_stall_s * 1e9 / 2:
                offers = sum(1 for k in open_rdv if k[1] == "o")
                claims = len(open_rdv) - offers
                return ("rendezvous",
                        f"bulk-tensor rendezvous wedged {oldest / 1e9:.2f}s:"
                        f" {offers} offer(s) unanswered, {claims} claimed "
                        "region(s) without complete/release in the flight "
                        "tail")
        # tpurpc-xray: the remaining native-plane stories, all from C
        # evidence alone. A pin-wait bracket is a link close() wedged
        # behind window pins (a claim waiter or in-flight placement holds
        # the mapping); a delivery-stall bracket backed by the depth
        # gauge is the server's delivery shard not draining; a burst of
        # fallback edges is the rendezvous plane silently degrading every
        # bulk send to the framed path.
        open_pin = ev.get("open_pin") or {}
        if open_pin:
            oldest = max(now - t for t in open_pin.values())
            if oldest >= self.min_stall_s * 1e9 / 2:
                return ("native-pin-wait",
                        f"native link close() waiting {oldest / 1e9:.2f}s "
                        "on pinned landing windows — a claim waiter or "
                        "in-flight placement still holds the mapping")
        open_dlv = ev.get("open_dlv") or {}
        if open_dlv:
            oldest = max(now - t for t in open_dlv.values())
            if oldest >= self.min_stall_s * 1e9 / 2:
                return ("native-delivery",
                        f"native delivery shard backlogged "
                        f"{oldest / 1e9:.2f}s "
                        f"({int(ev.get('native_dlv_depth', 0))} item(s) "
                        "queued): decode/materialization is not keeping "
                        "up with the pollers")
        fallbacks = ev.get("native_fallbacks") or []
        recent_fb = [t for t in fallbacks if now - t < 10e9]
        if len(recent_fb) >= 3:
            return ("native-rdv-fallback",
                    f"{len(recent_fb)} native rendezvous fallback(s) in "
                    "10s (total "
                    f"{int(ev.get('native_fallback_total', 0))}): bulk "
                    "sends are degrading to the framed path — claims "
                    "refused, timing out, or placement failing")
        # tpurpc-keystone: an aged open swap/migration bracket is MORE
        # specific than the decode-step story — the loop (or a migration
        # thread) is inside a KV move, and every stream behind the
        # boundary waits on it
        open_swap = ev.get("open_swap") or {}
        if open_swap:
            oldest = max(now - t for t in open_swap.values())
            if oldest >= self.min_stall_s * 1e9 / 2:
                return ("kv-swap",
                        f"KV swap wedged {oldest / 1e9:.2f}s: a "
                        f"swap begin without its end in the flight tail "
                        f"({len(open_swap)} open) — the host copy or "
                        "arena re-admission is stuck")
        open_mig = ev.get("open_mig") or {}
        if open_mig:
            oldest = max(now - t for t in open_mig.values())
            if oldest >= self.min_stall_s * 1e9 / 2:
                return ("migration",
                        f"live migration wedged {oldest / 1e9:.2f}s: "
                        f"{len(open_mig)} sequence(s) detached with no "
                        "migration-end — the peer handoff "
                        "(offer/ship/complete) is stuck")
        open_step = ev.get("open_step") or {}
        if open_step:
            oldest = max(now - t for t in open_step.values())
            # a fresh step edge is a decode step in flight (ms-scale);
            # only one aged past half the stall floor is a wedge — the
            # model call itself is the long pole, and every stream in the
            # batch is stalled behind it
            if oldest >= self.min_stall_s * 1e9 / 2:
                return ("decode-step",
                        f"decode step wedged {oldest / 1e9:.2f}s in the "
                        f"model (batch of {int(ev.get('last_step_batch', 0))}"
                        "): every running stream waits on this step")
        if (ev.get("decode_waiting", 0) > 0
                and not open_step
                and (not ev.get("last_step_end_ns")
                     or now - ev["last_step_end_ns"]
                     >= self.min_stall_s * 1e9)):
            return ("decode-step",
                    f"{int(ev['decode_waiting'])} sequence(s) waiting but "
                    "the decode loop has not completed a step inside the "
                    "stall window — the scheduler thread is wedged or "
                    "starved")
        if starve_age or ev["pairs_write_stalled"] > 0:
            if starve_age > 2 * age_ns or (
                    starve_age > 3 * self.min_stall_s * 1e9):
                return ("peer-not-reading",
                        "write stall/credit starvation persisted "
                        f"{starve_age / 1e9:.2f}s: the peer is connected "
                        "but not draining its receive ring")
            return ("credit-starvation",
                    "ring writer out of credits "
                    f"({int(ev['pairs_write_stalled'])} pair(s) "
                    "write-stalled)")
        if ev["last_h2_ns"] and now - ev["last_h2_ns"] < age_ns + int(1e9):
            # an exhaustion event within the stalled call's lifetime
            # (plus a second of slack for sweep-phase skew)
            return ("h2-flow-control",
                    "h2 send window exhausted: the peer stopped granting "
                    "WINDOW_UPDATE credit")
        if ev["batcher_queue_depth"] > 0:
            return ("batcher-wait",
                    f"{int(ev['batcher_queue_depth'])} request(s) parked "
                    "in the fan-in batcher queue")
        if ev["pairs_msg_waiting"] > 0:
            return ("poller-wake",
                    "a complete message is sitting undrained in a pair's "
                    "receive ring — wake latency or a lost kick")
        if kind == "server":
            return ("device-infer",
                    "transport quiet, handler still executing: the "
                    "model/device call is the long pole")
        return ("device-infer",
                "no local transport anomaly: the call is in flight at the "
                "peer (its handler/device is the long pole)")

    def _cause_struct(self, ev: dict, stage: str) -> dict:
        """tpurpc-oracle: the machine-readable twin of ``_attribute`` —
        the stage, the entity (connection/link) the oldest witness names,
        and ``[plane, ref, value]`` evidence rows citing the exact flight
        edges / gauges the prose describes. ``diagnose.py`` consumes this
        directly; the prose face stays untouched. ``device-infer`` (and
        external trips) legitimately carry no local evidence."""
        now = ev["now_ns"]
        evidence: List[list] = []
        entity: Optional[str] = None

        def add_table(table, slug, tag_index=None):
            nonlocal entity
            for key, t in sorted(table.items(), key=lambda kv: kv[1])[:4]:
                tag = key[tag_index] if tag_index is not None else key
                name = _flight.tag_name(tag)
                if entity is None:
                    entity = name
                evidence.append(
                    ["flight", f"{slug}:{name}@{t}",
                     round((now - t) / 1e9, 3)])

        def add_gauge(name):
            v = ev.get(name, 0)
            if v:
                evidence.append(["metrics", name, v])

        if stage == "credit-starvation":
            if ev.get("open_lease"):
                evidence.append(
                    ["flight", "lease-reserve-open", ev["open_lease"]])
            add_table(ev.get("open_edges") or {}, "stall-edge", 1)
            add_gauge("pairs_write_stalled")
        elif stage == "peer-not-reading":
            add_table(ev.get("open_edges") or {}, "stall-edge", 1)
            add_gauge("pairs_write_stalled")
        elif stage == "native-ctrl-frozen":
            add_table(ev.get("open_nctrl") or {}, "nctrl-ring-full")
        elif stage == "ctrl-ring":
            add_table(ev.get("open_ctrl") or {}, "ctrl-ring-full")
            add_gauge("ctrl_ring_backlog")
            if not ev.get("open_ctrl"):
                add_table(ev.get("open_rdv") or {}, "rdv-open", 0)
        elif stage == "rendezvous":
            add_table(ev.get("open_rdv") or {}, "rdv-open", 0)
        elif stage == "native-pin-wait":
            add_table(ev.get("open_pin") or {}, "pin-wait")
        elif stage == "native-delivery":
            add_table(ev.get("open_dlv") or {}, "dlv-stall")
            add_gauge("native_dlv_depth")
        elif stage == "native-rdv-fallback":
            for t in (ev.get("native_fallbacks") or [])[-4:]:
                evidence.append(
                    ["flight", f"rdv-fallback@{t}",
                     round((now - t) / 1e9, 3)])
            add_gauge("native_fallback_total")
        elif stage == "kv-swap":
            add_table(ev.get("open_swap") or {}, "kv-swap-open", 0)
        elif stage == "migration":
            add_table(ev.get("open_mig") or {}, "mig-open", 0)
        elif stage == "decode-step":
            add_table(ev.get("open_step") or {}, "step-open")
            add_gauge("decode_waiting")
            if ev.get("last_step_end_ns"):
                evidence.append(
                    ["flight", f"last-step-end@{ev['last_step_end_ns']}",
                     round((now - ev["last_step_end_ns"]) / 1e9, 3)])
        elif stage == "h2-flow-control":
            if ev.get("last_h2_ns"):
                evidence.append(
                    ["flight", f"h2-exhausted@{ev['last_h2_ns']}",
                     round((now - ev["last_h2_ns"]) / 1e9, 3)])
        elif stage == "batcher-wait":
            add_gauge("batcher_queue_depth")
        elif stage == "poller-wake":
            add_gauge("pairs_msg_waiting")
        return {"stage": stage, "entity": entity, "evidence": evidence}

    # -- export ---------------------------------------------------------------

    def active(self) -> List[dict]:
        return list(self._active)

    def snapshot(self) -> dict:
        out = {
            "active": list(self._active),
            "history": list(self._history),
            "inflight": len(self._inflight),
            "sweep_s": self.sweep_s,
            "mult": self.mult,
            "min_stall_s": self.min_stall_s,
            "enabled": self.enabled,
        }
        # tpurpc-manycore: a shard worker's registry names its shard so the
        # aggregated /debug/stalls view attributes each diagnosis
        from tpurpc.obs import shard as _shard

        if _shard.shard_id() >= 0:
            out["shard"] = _shard.shard_id()
        return out

    def reset(self) -> None:
        """Test isolation: forget in-flight calls and diagnoses (the
        sweeper thread, if started, keeps running harmlessly)."""
        self._inflight.clear()
        self._rolls.clear()
        self._active = []
        self._history.clear()


_instance: Optional[StallWatchdog] = None
_instance_lock = threading.Lock()


def get() -> StallWatchdog:
    global _instance
    if _instance is None:
        with _instance_lock:
            if _instance is None:
                _instance = StallWatchdog()
    return _instance


def call_started(method: str, trace_id: int = 0,
                 kind: str = "server") -> Optional[int]:
    return get().call_started(method, trace_id, kind)


def call_finished(token: Optional[int], error: bool = False) -> None:
    if token is not None:
        get().call_finished(token, error=error)


def postfork_reset() -> None:
    """Fresh watchdog in a forked shard worker: the inherited instance's
    sweeper thread did not survive the fork (and ``call_started`` would
    never restart it — ``_thread`` is non-None but dead), and its in-flight
    registry describes the supervisor's calls, not this worker's."""
    global _instance, _instance_lock
    _instance_lock = threading.Lock()
    _instance = None
