"""tpurpc-manycore: shard identity + cross-worker scrape aggregation.

A sharded server (``tpurpc.rpc.shard.ShardedServer``) runs N worker
PROCESSES, each owning its poller, rings, batcher, and — crucially for this
module — its own metrics registry, flight ring, and watchdog. Telemetry
that only describes one worker is useless to an operator who scraped
"the server": this module makes ONE ``GET /metrics`` (or ``/traces``,
``/debug/flight``, ``/debug/stalls``, ``/debug/profile``,
``/debug/waterfall``, ``/healthz``) on the serving port tell the whole
truth, whichever worker the kernel's accept spread happened to hand the
scrape to.

Mechanics:

* every worker runs a loopback-only scrape listener
  (:func:`tpurpc.obs.scrape.start_http_server`) and the supervisor
  broadcasts the full ``{shard_id: scrape_port}`` map to every worker;
* a worker answering an aggregate route fetches each peer's LOCAL view
  (``?local=1`` — the recursion guard) over loopback, renders its own view
  in-process, and merges, tagging every series/event with ``shard="k"``;
* a shard that died is simply unreachable: its series VANISH from the next
  scrape (the PR 4 weakref-death contract extended across the process
  boundary — a dead worker must drop out, never freeze its last values),
  and ``tpurpc_shard_up`` enumerates who answered.

The per-request hot path pays nothing for any of this: shard identity is
two module ints, and all fan-out happens at scrape time on the sniff
thread that was already serving the HTTP request.
"""

from __future__ import annotations

import json
import socket
import threading
from typing import Dict, List, Optional, Tuple

__all__ = [
    "set_identity", "shard_id", "n_shards", "set_peers", "peers",
    "sharded", "route_aggregate", "aggregate_metrics", "aggregate_flight",
    "aggregate_stalls", "aggregate_healthz", "aggregate_traces",
    "aggregate_profile", "aggregate_waterfall", "aggregate_slo",
    "aggregate_history", "aggregate_seq", "aggregate_diagnose",
]

# tpurpc-argus (ISSUE 14): counter-reset hardening. A shard worker that
# died and was respawned restarts every counter at zero; summing or
# re-exporting its raw values silently steps the merged series BACKWARDS
# (a scrape-side cliff that poisons every rate() downstream). One
# process-wide ResetClamp — keyed (shard, series) — detects the monotonic
# break and continues each series from last-known + delta. It persists
# across scrapes by design: the clamp IS the memory of the restart.


def _reset_clamp():
    from tpurpc.obs.tsdb import ResetClamp

    global _CLAMP
    if _CLAMP is None:
        _CLAMP = ResetClamp()
    return _CLAMP


_CLAMP = None

_lock = threading.Lock()
_SHARD_ID = -1   # -1 = this process is not a shard worker
_N_SHARDS = 0
_PEERS: Dict[int, int] = {}  # shard_id -> loopback scrape port

#: how long one peer fetch may take; a SIGKILLed worker's port refuses
#: instantly, so this bound only matters for a wedged-but-alive worker
_FETCH_TIMEOUT_S = 0.6


def set_identity(shard: int, total: int) -> None:
    global _SHARD_ID, _N_SHARDS
    with _lock:
        _SHARD_ID = int(shard)
        _N_SHARDS = int(total)


def shard_id() -> int:
    return _SHARD_ID


def n_shards() -> int:
    return _N_SHARDS


def set_peers(mapping: Dict[int, int]) -> None:
    """Install the supervisor-broadcast ``{shard_id: scrape_port}`` map
    (including this worker's own entry)."""
    global _PEERS
    with _lock:
        _PEERS = {int(k): int(v) for k, v in mapping.items()}


def peers() -> Dict[int, int]:
    with _lock:
        return dict(_PEERS)


def sharded() -> bool:
    """True when this process should answer scrapes with the AGGREGATE
    view (it is a shard worker and knows its peers)."""
    return _SHARD_ID >= 0 and bool(_PEERS)


# -- peer fetch ---------------------------------------------------------------

def _fetch(port: int, path: str) -> Optional[Tuple[int, bytes]]:
    """One loopback HTTP/1.0 GET; None when the peer is gone/wedged —
    the caller drops that shard from the merged view."""
    try:
        with socket.create_connection(("127.0.0.1", port),
                                      timeout=_FETCH_TIMEOUT_S) as s:
            s.settimeout(_FETCH_TIMEOUT_S)
            s.sendall(f"GET {path} HTTP/1.0\r\n\r\n".encode())
            buf = bytearray()
            while True:
                chunk = s.recv(65536)
                if not chunk:
                    break
                buf += chunk
    except OSError:
        return None
    head, _, body = bytes(buf).partition(b"\r\n\r\n")
    parts = head.split(None, 2)
    if len(parts) < 2:
        return None
    try:
        return int(parts[1]), body
    except ValueError:
        return None


def _each_shard(path: str):
    """Yield ``(shard_id, status, body_bytes)`` for every REACHABLE shard;
    self is rendered in-process (never through its own HTTP listener)."""
    from tpurpc.obs import scrape as _scrape

    me = _SHARD_ID
    for k in sorted(peers()):
        if k == me:
            status, _ctype, body = _scrape.route_local(path)
            yield k, status, body
            continue
        got = _fetch(peers()[k], path if "?" in path else path + "?local=1")
        if got is None:
            continue  # dead/unreachable shard: drops out of the merge
        yield k, got[0], got[1]


# -- /metrics -----------------------------------------------------------------

def _shard_label(line: str, k: int) -> str:
    """Inject ``shard="k"`` as the first label of one exposition line."""
    brace = line.find("{")
    space = line.find(" ")
    if brace != -1 and (space == -1 or brace < space):
        return f'{line[:brace]}{{shard="{k}",{line[brace + 1:]}'
    name, _, rest = line.partition(" ")
    return f'{name}{{shard="{k}"}} {rest}'


def aggregate_metrics() -> str:
    """The merged Prometheus text: every reachable worker's series with a
    ``shard`` label, one ``# TYPE`` line per family, plus ``tpurpc_shard_up``
    per answering shard (a dead shard is ABSENT — presence is liveness)."""
    types: Dict[str, str] = {}
    series: List[str] = []
    up: List[int] = []
    clamp = _reset_clamp()
    for k, status, body in _each_shard("/metrics"):
        if status != 200:
            continue
        up.append(k)
        counters: set = set()
        for line in body.decode("utf-8", errors="replace").splitlines():
            if line.startswith("# TYPE "):
                parts = line.split()
                if len(parts) >= 4:
                    types.setdefault(parts[2], parts[3])
                    if parts[3] == "counter":
                        counters.add(parts[2])
                continue
            if not line or line.startswith("#"):
                continue
            name, _, value = line.rpartition(" ")
            if name and (name in counters
                         or name.split("{", 1)[0] in counters):
                # killed-and-restarted worker: clamp the monotonic break
                try:
                    v = float(value)
                except ValueError:
                    v = None
                if v is not None:
                    clamped = clamp.clamp((k, name), v)
                    if clamped != v:
                        line = (f"{name} {int(clamped)}"
                                if clamped.is_integer()
                                else f"{name} {clamped}")
            series.append(_shard_label(line, k))
    lines = [f"# TYPE {name} {t}" for name, t in sorted(types.items())]
    lines.append("# TYPE tpurpc_shard_up gauge")
    lines.extend(f'tpurpc_shard_up{{shard="{k}"}} 1' for k in up)
    lines.append(f"tpurpc_shards_configured {_N_SHARDS}")
    lines.extend(series)
    return "\n".join(lines) + "\n"


# -- /debug/flight ------------------------------------------------------------

def aggregate_flight(since_ns: int = 0) -> dict:
    """Every reachable shard's flight events in ONE time-ordered replay.
    CLOCK_MONOTONIC is system-wide on Linux, so cross-process ``t_ns``
    stamps order correctly — the whole point of merging: one timeline of
    what every worker's transport did."""
    events: List[dict] = []
    capacity = 0
    up: List[int] = []
    for k, status, body in _each_shard(
            f"/debug/flight?local=1&since_ns={since_ns}"):
        if status != 200:
            continue
        try:
            doc = json.loads(body)
        except ValueError:
            continue
        up.append(k)
        capacity = max(capacity, int(doc.get("capacity") or 0))
        for e in doc.get("events", ()):
            e["shard"] = k
            events.append(e)
    events.sort(key=lambda e: e.get("t_ns", 0))
    return {"events": events, "capacity": capacity, "shards": up}


def aggregate_flight_text(since_ns: int = 0) -> str:
    doc = aggregate_flight(since_ns=since_ns)
    events = doc["events"]
    if not events:
        return "flight recorder: no events (any shard)\n"
    t0 = events[0]["t_ns"]
    lines = [f"flight recorder: {len(events)} events across "
             f"{len(doc['shards'])} shard(s)"]
    for e in events:
        lines.append(
            f"  +{(e['t_ns'] - t0) / 1e6:10.3f}ms s{e.get('shard', '?')} "
            f"{e['event']:<22} {e.get('entity', '-'):<20} "
            f"a1={e['a1']} a2={e['a2']}")
    return "\n".join(lines) + "\n"


# -- /traces ------------------------------------------------------------------

def aggregate_traces(trace_id: str = "") -> dict:
    """Every reachable shard's span buffer in ONE chrome-trace document
    (tpurpc-lens, ISSUE 8 — before this, a trace born on shard 2 was
    invisible on the serving port). Each shard becomes its own process
    lane: its events are re-pid'd to the shard id, its ``process_name``
    metadata renamed, and its monotonic↔wall :func:`clock anchor
    <tpurpc.obs.tracing.clock_anchor>` preserved per shard under
    ``clock_anchors`` — timestamps stay in each worker's monotonic clock
    here (the timeline tool rebases; fork-inherited CLOCK_MONOTONIC is
    system-wide on Linux, so same-host lanes already line up)."""
    events: List[dict] = []
    anchors: Dict[str, dict] = {}
    up: List[int] = []
    q = f"&trace_id={trace_id}" if trace_id else ""
    for k, status, body in _each_shard(f"/traces?local=1{q}"):
        if status != 200:
            continue
        try:
            doc = json.loads(body)
        except ValueError:
            continue
        up.append(k)
        anchor = doc.get("clock_anchor")
        if anchor:
            anchors[str(k)] = anchor
        for e in doc.get("traceEvents", ()):
            e["pid"] = k
            if e.get("ph") == "M" and e.get("name") == "process_name":
                e.setdefault("args", {})["name"] = f"tpurpc shard {k}"
            events.append(e)
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "clock_anchors": anchors, "shards": up}


# -- /debug/profile -----------------------------------------------------------

def aggregate_profile(include_samples: bool = False) -> dict:
    """Per-shard profiler snapshots plus a merged per-stage sample count —
    the serving-port answer to "where do the cycles go, fleet-wide"."""
    shards: Dict[str, dict] = {}
    stages: Dict[str, int] = {}
    samples = 0
    q = "&samples=1" if include_samples else ""
    for k, status, body in _each_shard(f"/debug/profile?local=1{q}"):
        if status != 200:
            continue
        try:
            snap = json.loads(body)
        except ValueError:
            continue
        shards[str(k)] = snap
        samples += int(snap.get("samples") or 0)
        for stage, n in (snap.get("stages") or {}).items():
            stages[stage] = stages.get(stage, 0) + int(n)
    other = stages.get("other", 0)
    unatt = stages.get("unattributed", 0)
    denom = samples - other
    return {"shards": shards, "stages": stages, "samples": samples,
            "attributed_pct": (round((denom - unatt) / denom * 100, 1)
                               if denom else 0.0),
            "enabled": any(s.get("enabled") for s in shards.values())}


def aggregate_profile_collapsed() -> str:
    """Merged collapsed stacks, each line prefixed ``shard-k;`` so one
    flamegraph shows every worker side by side."""
    lines: List[str] = []
    for k, status, body in _each_shard("/debug/profile?local=1&collapsed=1"):
        if status != 200:
            continue
        for line in body.decode("utf-8", errors="replace").splitlines():
            if line:
                lines.append(f"shard-{k};{line}")
    return "\n".join(lines) + ("\n" if lines else "")


# -- /debug/waterfall ---------------------------------------------------------

def aggregate_waterfall() -> dict:
    """Per-shard waterfalls plus a merged hop table (bytes and busy_ns sum
    across workers; effective GB/s recomputed over the sums — N workers
    each moving b bytes in t ns aggregate to Nb/Nt, the same rate, not an
    inflated one)."""
    shards: Dict[str, dict] = {}
    merged: Dict[str, dict] = {}
    order: List[str] = []
    clamp = _reset_clamp()
    for k, status, body in _each_shard("/debug/waterfall?local=1"):
        if status != 200:
            continue
        try:
            doc = json.loads(body)
        except ValueError:
            continue
        shards[str(k)] = doc
        for row in doc.get("hops", ()):
            hop = row.get("hop")
            if hop not in merged:
                merged[hop] = {"hop": hop, "bytes": 0, "busy_ms": 0.0,
                               "copy_bytes": 0, "what": row.get("what", "")}
                order.append(hop)
            # tpurpc-argus: these SUM raw per-shard counters — exactly the
            # merge a worker restart would step backwards; clamp each
            # shard's contribution to its monotone view first
            merged[hop]["bytes"] += int(clamp.clamp(
                (k, hop, "bytes"), int(row.get("bytes") or 0)))
            merged[hop]["busy_ms"] += clamp.clamp(
                (k, hop, "busy_ms"), float(row.get("busy_ms") or 0.0))
            merged[hop]["copy_bytes"] += int(clamp.clamp(
                (k, hop, "copy_bytes"), int(row.get("copy_bytes") or 0)))
    rows = []
    for hop in order:
        r = merged[hop]
        ns = r["busy_ms"] * 1e6
        r["gbps"] = round(r["bytes"] / ns, 3) if ns else 0.0
        r["busy_ms"] = round(r["busy_ms"], 3)
        rows.append(r)
    live = [r for r in rows if r["bytes"] > 0 and r["busy_ms"] > 0]
    return {"hops": rows,
            "slowest_hop": (min(live, key=lambda r: r["gbps"])["hop"]
                            if live else None),
            "shards": shards}


# -- /debug/slo + /debug/history (tpurpc-argus, ISSUE 14) ---------------------

def aggregate_slo() -> dict:
    """Every reachable shard's SLO document plus one flat shard-tagged
    ``firing`` list — the serving-port answer to "is anything paging"."""
    shards: Dict[str, dict] = {}
    firing: List[dict] = []
    for k, status, body in _each_shard("/debug/slo?local=1"):
        if status != 200:
            continue
        try:
            doc = json.loads(body)
        except ValueError:
            continue
        shards[str(k)] = doc
        for a in doc.get("firing", ()):
            firing.append(dict(a, shard=k))
    return {"shards": shards, "firing": firing}


def aggregate_seq() -> dict:
    """tpurpc-odyssey (ISSUE 15): every reachable shard's /debug/seq
    merged — sequence rows tagged ``shard``, account rollups and the
    step-time attribution totals SUMMED (the pure merge lives in
    :func:`tpurpc.obs.odyssey.merge_seq_docs`, shared with the fleet
    collector's /fleet/seq)."""
    from tpurpc.obs import odyssey as _odyssey

    docs: Dict[str, dict] = {}
    for k, status, body in _each_shard("/debug/seq?local=1"):
        if status != 200:
            continue
        try:
            docs[str(k)] = json.loads(body)
        except ValueError:
            continue
    return _odyssey.merge_seq_docs(docs, label="shard")


def aggregate_diagnose(params: Optional[dict] = None) -> dict:
    """tpurpc-oracle (ISSUE 20): every reachable shard's /debug/diagnose
    merged — hypotheses re-combined by cause across workers, evidence
    rows shard-tagged, cross-shard corroboration surfaced (the pure
    merge lives in :func:`tpurpc.obs.diagnose.merge_diagnose_docs`,
    shared with the fleet collector's /fleet/diagnose)."""
    from tpurpc.obs import diagnose as _diagnose

    want = (params or {}).get("symptom")
    path = "/debug/diagnose?local=1"
    if want:
        path += f"&symptom={want}"
    docs: Dict[str, dict] = {}
    for k, status, body in _each_shard(path):
        if status != 200:
            continue
        try:
            docs[str(k)] = json.loads(body)
        except ValueError:
            continue
    return _diagnose.merge_diagnose_docs(docs, label="shard")


def aggregate_history() -> dict:
    """Per-shard tsdb inventories (each worker samples its OWN registry —
    series merge happens at query time via the shard key, like /traces)."""
    shards: Dict[str, dict] = {}
    for k, status, body in _each_shard("/debug/history?local=1"):
        if status != 200:
            continue
        try:
            shards[str(k)] = json.loads(body)
        except ValueError:
            continue
    return {"shards": shards}


# -- /debug/stalls ------------------------------------------------------------

def aggregate_stalls() -> dict:
    """Per-shard watchdog snapshots plus a merged active/history view (each
    diagnosis tagged with its shard) — the keys tools.top and the smoke
    scripts already read stay present and truthful."""
    shards: Dict[str, dict] = {}
    active: List[dict] = []
    history: List[dict] = []
    inflight = 0
    for k, status, body in _each_shard("/debug/stalls"):
        if status != 200:
            continue
        try:
            snap = json.loads(body)
        except ValueError:
            continue
        shards[str(k)] = snap
        for d in snap.get("active", ()):
            d = dict(d, shard=k)
            active.append(d)
        for d in snap.get("history", ()):
            history.append(dict(d, shard=k))
        inflight += int(snap.get("inflight") or 0)
    history.sort(key=lambda d: d.get("since_ns", 0))
    return {"shards": shards, "active": active, "history": history,
            "inflight": inflight,
            "enabled": any(s.get("enabled") for s in shards.values())}


# -- /healthz -----------------------------------------------------------------

def aggregate_healthz() -> Tuple[int, bytes]:
    """Worst-of health: any degraded shard degrades the whole server (one
    wedged worker IS an incident); all-draining reports draining. A dead
    shard is skipped — its connections are already gone, and liveness is
    ``tpurpc_shard_up``'s job, not the health probe's."""
    degraded: List[str] = []
    bodies: List[bytes] = []
    for k, status, body in _each_shard("/healthz"):
        if status == 503:
            degraded.append(f"shard {k}: {body.decode(errors='replace').strip()}")
        bodies.append(body.strip())
    if degraded:
        return 503, ("\n".join(degraded) + "\n").encode()
    if bodies and all(b == b"draining" for b in bodies):
        return 200, b"draining\n"
    return 200, b"ok\n"


# -- scrape-plane hook --------------------------------------------------------

def route_aggregate(route: str, params: dict
                    ) -> Optional[Tuple[int, str, bytes]]:
    """The scrape plane's shard hook: the merged ``(status, ctype, body)``
    for an aggregate-aware route, or None for routes served locally
    (/channelz stays per-worker — channelz entities are process-scoped by
    design; scrape it via ?local=1 on a worker's own scrape port when
    debugging one shard). tpurpc-lens (ISSUE 8) added /traces,
    /debug/profile and /debug/waterfall to the fan-out: a trace or a hot
    stage born on shard 2 must be visible on the serving port."""
    try:
        if route in ("/traces", "/traces/"):
            doc = aggregate_traces(trace_id=params.get("trace_id") or "")
            return 200, "application/json", json.dumps(doc).encode()
        if route in ("/debug/profile", "/debug/profile/"):
            if params.get("collapsed"):
                return (200, "text/plain",
                        aggregate_profile_collapsed().encode())
            doc = aggregate_profile(
                include_samples=bool(params.get("samples")))
            return 200, "application/json", json.dumps(doc).encode()
        if route in ("/debug/waterfall", "/debug/waterfall/"):
            doc = aggregate_waterfall()
            if params.get("text"):
                from tpurpc.obs import lens as _lens

                return 200, "text/plain", _lens.render_text(doc).encode()
            return 200, "application/json", json.dumps(doc).encode()
        if route in ("/metrics", "/metrics/"):
            return 200, "text/plain; version=0.0.4", aggregate_metrics().encode()
        if route in ("/debug/flight", "/debug/flight/"):
            try:
                since_ns = int(params.get("since_ns") or 0)
            except ValueError:
                return 400, "text/plain", b"bad since_ns\n"
            if params.get("text"):
                return (200, "text/plain",
                        aggregate_flight_text(since_ns=since_ns).encode())
            return (200, "application/json",
                    json.dumps(aggregate_flight(since_ns=since_ns)).encode())
        if route in ("/debug/slo", "/debug/slo/"):
            return (200, "application/json",
                    json.dumps(aggregate_slo(), indent=1).encode())
        if route in ("/debug/seq", "/debug/seq/"):
            return (200, "application/json",
                    json.dumps(aggregate_seq(), indent=1).encode())
        if route in ("/debug/history", "/debug/history/") \
                and not params.get("series"):
            # a series drill-down (?series=) stays per-worker — points
            # from different registries must not interleave silently
            return (200, "application/json",
                    json.dumps(aggregate_history()).encode())
        if route in ("/debug/stalls", "/debug/stalls/"):
            return (200, "application/json",
                    json.dumps(aggregate_stalls(), indent=1).encode())
        if route in ("/debug/diagnose", "/debug/diagnose/"):
            doc = aggregate_diagnose(params)
            if params.get("text"):
                from tpurpc.obs import diagnose as _diagnose

                return 200, "text/plain", _diagnose.render_text(doc).encode()
            return (200, "application/json",
                    json.dumps(doc, indent=1).encode())
        if route in ("/healthz", "/health"):
            status, body = aggregate_healthz()
            return status, "text/plain", body
    except Exception:
        # an aggregation bug must never take the scrape down: fall back to
        # the local view (the pre-manycore behavior)
        return None
    return None
