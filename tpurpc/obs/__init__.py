"""tpurpc-scope: the unified telemetry subsystem (ISSUE 4).

Three faces over one always-on core:

* :mod:`tpurpc.obs.metrics` — the process-wide metrics registry. Counters
  are plain-int, GIL-atomic bumps (branch-free on the hot path); batch/
  latency histograms amortize one lock per *batch*; state gauges are
  evaluated at SCRAPE time over weakly-referenced live objects (fleet
  gauges), so idle-state observability costs the hot path nothing.
* :mod:`tpurpc.obs.tracing` — per-RPC span timelines with a trace context
  (trace_id / span_id / sampled bit) carried in call metadata
  client→server→batcher→device on both the Python and native planes.
  Sampling defaults OFF; the whole plane is behind one module-global gate.
* :mod:`tpurpc.obs.scrape` — the introspection plane: a Prometheus-text
  endpoint served in-process on every :class:`tpurpc.rpc.server.Server`
  port (the protocol sniff answers plain ``GET /metrics``), feeding the
  registry, the copy ledger, and channelz; ``python -m tpurpc.tools.top``
  renders it live.

The reference fork's whole debugging story was trace flags plus a
shutdown-time profiler table (SURVEY.md §5, ``stats_time.cc``); tpurpc-scope
replaces post-hoc printf with always-on, near-free telemetry.
"""

from tpurpc.obs import metrics, tracing  # noqa: F401

__all__ = ["metrics", "tracing"]
