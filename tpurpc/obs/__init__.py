"""tpurpc-scope: the unified telemetry subsystem (ISSUE 4).

Three faces over one always-on core:

* :mod:`tpurpc.obs.metrics` — the process-wide metrics registry. Counters
  are plain-int, GIL-atomic bumps (branch-free on the hot path); batch/
  latency histograms amortize one lock per *batch*; state gauges are
  evaluated at SCRAPE time over weakly-referenced live objects (fleet
  gauges), so idle-state observability costs the hot path nothing.
* :mod:`tpurpc.obs.tracing` — per-RPC span timelines with a trace context
  (trace_id / span_id / sampled bit) carried in call metadata
  client→server→batcher→device on both the Python and native planes.
  Sampling defaults OFF; the whole plane is behind one module-global gate.
* :mod:`tpurpc.obs.scrape` — the introspection plane: a Prometheus-text
  endpoint served in-process on every :class:`tpurpc.rpc.server.Server`
  port (the protocol sniff answers plain ``GET /metrics``), feeding the
  registry, the copy ledger, and channelz; ``python -m tpurpc.tools.top``
  renders it live.

tpurpc-blackbox (ISSUE 5) adds the POSTMORTEM faces on top:

* :mod:`tpurpc.obs.flight` — an always-on, fixed-size binary ring of
  structured transport events (stall/starvation edges, lease lifecycle,
  poller mode flips, window exhaustion, deadline expiry, peer death) with
  a preallocated lock-free encoder; dump via ``GET /debug/flight``,
  ``SIGUSR2``, or automatically on watchdog trip.
* :mod:`tpurpc.obs.watchdog` — a stall sweeper over the in-flight-RPC
  registry that names the blocked STAGE (credit starvation / poller wake /
  h2 flow control / batcher wait / device infer / peer-not-reading) from
  the flight tail + fleet gauges; served at ``GET /debug/stalls`` and
  reflected in ``/healthz``.
* tail-based trace capture (in :mod:`tpurpc.obs.tracing`) — every RPC gets
  a provisional span buffer regardless of sample rate, committed iff the
  call was slow, errored, or watchdog-flagged: ``TPURPC_TRACE_SAMPLE=0``
  still yields a full span tree for every pathological call.

tpurpc-lens (ISSUE 8) adds the PERFORMANCE-ATTRIBUTION faces:

* :mod:`tpurpc.obs.profiler` — a continuous stage-tagged sampling
  profiler: thread stacks sampled at ~50 Hz and mapped to pipeline stages
  via a static frame-marker registry; per-stage shares + collapsed stacks
  at ``GET /debug/profile``.
* :mod:`tpurpc.obs.lens` — the byte-flow waterfall: per-hop (device →
  send ring → wire → peer ring → decode → hbm → jax.Array) bytes/busy-ns
  counters whose scrape-time ratio is each hop's effective GB/s; the
  argmin names the bottleneck. ``GET /debug/waterfall``.
* ``python -m tpurpc.tools.timeline`` — one Perfetto trace for a whole
  deployment: spans + flight edges + CPU samples from every shard/fleet
  member, aligned on per-process monotonic↔wall clock anchors.

tpurpc-argus (ISSUE 14) adds the TIME and FLEET dimensions:

* :mod:`tpurpc.obs.tsdb` — a bounded in-process ring time-series store:
  a background sampler snapshots the registry into preallocated
  two-tier rings (~1 s grain for minutes, ~15 s for the hour);
  ``rate()`` / ``quantile_over_time()`` / ``window()`` queries at
  ``GET /debug/history``.
* :mod:`tpurpc.obs.slo` — declared availability/latency objectives
  evaluated as multi-window multi-burn-rate alerts over the tsdb
  (pending→firing→resolved; admission sheds burn a separate budget);
  ``GET /debug/slo``, flight fire/resolve events, watchdog bridge,
  degraded ``/healthz``.
* :mod:`tpurpc.obs.collector` — a standalone fleet collector polling
  every member's existing routes and serving merged, member-labeled
  ``/fleet/metrics`` + ``/fleet/slo`` + ``/fleet/timeline`` (stale
  members' series vanish; counter resets clamped).
* :mod:`tpurpc.obs.bundle` — automatic evidence capture: a firing alert
  or watchdog trip writes a rate-limited, size-capped postmortem bundle
  (flight dump, tail traces, profile, waterfall, tsdb window) that
  ``python -m tpurpc.analysis protocol --flight`` replays unmodified.

The reference fork's whole debugging story was trace flags plus a
shutdown-time profiler table (SURVEY.md §5, ``stats_time.cc``); tpurpc-scope
replaces post-hoc printf with always-on, near-free telemetry, tpurpc-blackbox
makes the rare-event failures it samples away recoverable after the fact,
tpurpc-lens says where the cycles and bytes actually go, and tpurpc-argus
answers over time and across members — then writes the postmortem itself.
"""

from tpurpc.obs import flight, lens, metrics, profiler, tracing  # noqa: F401

__all__ = ["flight", "lens", "metrics", "profiler", "tracing"]
