"""tpurpc-xray: Python face of the native observability plane (ISSUE 19).

The C core (``native/src/tpr_obs.cc``) keeps a flight ring and a fixed-slot
metrics table in ONE shm region; this module attaches to that region and
decodes it — the read path is an mmap + struct walk, zero ctypes calls per
record. Three consumers sit on top:

* :func:`records` / :func:`tag_table` — raw flight tuples for
  :mod:`tpurpc.obs.flight`'s merged snapshot (lane ``"native"``);
* :func:`counters` — the metrics table as a name → value dict (names
  mirror ``MetricIdx`` in tpr_obs.h IN ORDER — the index is the ABI);
* :func:`sync_registry` — pushes the table into the PR 4 registry as
  ``native_*`` series and feeds the lens waterfall's native hops, called
  at scrape/sample time (/metrics, tsdb ticks, /debug/waterfall).

The decoder honors the writer's seqlock: per slot it reads the seq word,
copies the record, and re-reads the seq word — a wrap during the copy
changes the stamp and the slot is skipped (torn reads are detected, never
returned). Record order across slots comes from the stamps; the merged
flight view sorts on the shared CLOCK_MONOTONIC timeline.

``TPURPC_NATIVE_OBS=0`` (read by the C side at first use) leaves the
plane off: every entry point here degrades to empty/no-op and the PR 18
``tpr_rdv_counters`` ledger ABI is untouched either way.
"""

from __future__ import annotations

import mmap
import os
import struct
import threading
from typing import Dict, List, Optional, Tuple

__all__ = [
    "METRIC_NAMES", "GAUGE_METRICS", "available", "records", "tag_table",
    "counters", "sync_registry", "reset", "postfork_reset",
]

LAYOUT_VERSION = 1
RECORD_BYTES = 32
_MAGIC = 0x54505258  # 'TPRX'

#: the metrics-table ABI: index here == MetricIdx in native/src/tpr_obs.h.
#: Append-only, like the event codes.
METRIC_NAMES: Tuple[str, ...] = (
    "rdv_send_bytes",      # one-sided bytes placed by rdv_write
    "rdv_send_busy_ns",    # ns inside the placement memcpy
    "rdv_recv_bytes",      # region bytes delivered to the stream layer
    "rdv_recv_busy_ns",    # ns inside deliver()
    "rdv_wait_ns",         # ns senders spent waiting on solicited claims
    "rdv_waits",           # solicited claim waits begun
    "rdv_fallbacks",       # eligible sends that fell back framed
    "ctrl_drain_batches",  # non-empty ctrl_drain passes
    "ctrl_drain_records",  # records drained across those passes
    "ctrl_kicks",          # framed kicks sent to a parked consumer
    "ctrl_posts",          # records placed in the peer's ring
    "ctrl_frames",         # control ops that went framed (ring miss/cold)
    "pin_waits",           # close() paths that found window pins held
    "pin_wait_ns",         # ns close() spent waiting for pins to drain
    "dlv_enqueued",        # delivery-shard items enqueued
    "dlv_drained",         # delivery-shard items delivered
    "dlv_stalls",          # backlog high-water crossings
    "dlv_depth",           # gauge: current delivery backlog
    "conn_up",             # connections established (native plane)
    "conn_down",           # connections died
    "emitted",             # flight records emitted (wraps overwrite)
    "tag_overflow",        # tag interns refused (table full -> tag 0)
)

#: table slots that are instantaneous values, not monotonic totals
GAUGE_METRICS = frozenset({"dlv_depth"})

_REC = struct.Struct("<QHHIqq")
_U64 = struct.Struct("<Q")
_U32 = struct.Struct("<I")

_lock = threading.Lock()
_bound = False


class _Map:
    """One attached shm region: mmap + parsed header offsets."""

    __slots__ = ("name", "mm", "capacity", "tag_cap", "metrics_cap",
                 "metrics_off", "tags_off", "seq_off", "rec_off")

    def __init__(self, name: str, mm: mmap.mmap):
        self.name = name
        self.mm = mm
        (magic,) = _U32.unpack_from(mm, 0)
        (version,) = _U32.unpack_from(mm, 4)
        if magic != _MAGIC or version != LAYOUT_VERSION:
            raise ValueError(f"tpr_obs layout mismatch "
                             f"(magic={magic:#x} version={version})")
        (self.capacity,) = _U32.unpack_from(mm, 8)
        (self.tag_cap,) = _U32.unpack_from(mm, 12)
        (self.metrics_cap,) = _U32.unpack_from(mm, 16)
        (rb,) = _U32.unpack_from(mm, 20)
        if rb != RECORD_BYTES:
            raise ValueError(f"tpr_obs record size mismatch ({rb})")
        (self.metrics_off,) = _U32.unpack_from(mm, 32)
        (self.tags_off,) = _U32.unpack_from(mm, 36)
        (self.seq_off,) = _U32.unpack_from(mm, 40)
        (self.rec_off,) = _U32.unpack_from(mm, 44)

    def tag_count(self) -> int:
        (n,) = _U32.unpack_from(self.mm, 48)
        return min(n, self.tag_cap)

    def close(self) -> None:
        try:
            self.mm.close()
        except Exception:
            pass


#: None = not tried, False = unavailable this process, _Map = attached
_state: Optional[object] = None


def _lib():
    from tpurpc.core import _native

    lib = _native.load()
    if lib is None or not hasattr(lib, "tpr_obs_enabled"):
        return None
    global _bound
    if not _bound:
        import ctypes

        lib.tpr_obs_enabled.restype = ctypes.c_int
        lib.tpr_obs_enabled.argtypes = []
        lib.tpr_obs_shm_name.restype = ctypes.c_char_p
        lib.tpr_obs_shm_name.argtypes = []
        lib.tpr_obs_layout_version.restype = ctypes.c_uint32
        lib.tpr_obs_reset.restype = None
        lib.tpr_obs_reset.argtypes = []
        lib.tpr_obs_postfork.restype = None
        lib.tpr_obs_postfork.argtypes = []
        _bound = True
    return lib


def _attach_locked():
    """(Re)attach to the C side's current region. Called under _lock."""
    global _state
    lib = _lib()
    if lib is None or not lib.tpr_obs_enabled():
        _state = False
        return None
    raw = lib.tpr_obs_shm_name()
    name = raw.decode("ascii", "replace") if raw else ""
    if not name:
        _state = False
        return None
    if isinstance(_state, _Map):
        if _state.name == name:
            return _state
        _state.close()  # the C side rebuilt (postfork): remap
        _state = None
    try:
        fd = os.open("/dev/shm/" + name, os.O_RDONLY)
        try:
            size = os.fstat(fd).st_size
            mm = mmap.mmap(fd, size, prot=mmap.PROT_READ)
        finally:
            os.close(fd)
        _state = _Map(name, mm)
    except (OSError, ValueError):
        _state = False
        return None
    return _state


def _map() -> Optional[_Map]:
    st = _state
    if isinstance(st, _Map):
        # cheap staleness probe: the C side swaps regions only on postfork,
        # which also swaps the advertised name
        lib = _lib()
        if lib is not None:
            raw = lib.tpr_obs_shm_name()
            if raw and raw.decode("ascii", "replace") == st.name:
                return st
        with _lock:
            return _attach_locked()
    if st is False:
        return None
    with _lock:
        if _state is None:
            return _attach_locked()
        return _state if isinstance(_state, _Map) else None


def available() -> bool:
    """True when the C plane is on and its region is mapped here."""
    return _map() is not None


def records() -> List[Tuple[int, int, int, int, int, int]]:
    """Seqlock-consistent snapshot of the flight ring as raw
    ``(t_ns, code, tag, tid, a1, a2)`` tuples (slot order — callers sort
    on ``t_ns``). Torn and empty slots are skipped."""
    st = _map()
    if st is None:
        return []
    mm = st.mm
    out: List[Tuple[int, int, int, int, int, int]] = []
    seq_off, rec_off = st.seq_off, st.rec_off
    for slot in range(st.capacity):
        so = seq_off + slot * 8
        (s1,) = _U64.unpack_from(mm, so)
        if s1 == 0:
            continue
        rec = bytes(mm[rec_off + slot * RECORD_BYTES:
                       rec_off + (slot + 1) * RECORD_BYTES])
        (s2,) = _U64.unpack_from(mm, so)
        if s2 != s1:
            continue  # a writer wrapped onto this slot mid-copy
        out.append(_REC.unpack(rec))
    return out


def tag_table() -> List[str]:
    """Interned entity names, indexed by native tag (0 = anonymous)."""
    st = _map()
    if st is None:
        return ["-"]
    out = ["-"]
    mm, base = st.mm, st.tags_off
    for i in range(st.tag_count()):
        off = base + i * 48
        (ln,) = struct.unpack_from("<H", mm, off)
        ln = min(ln, 46)
        out.append(bytes(mm[off + 2:off + 2 + ln]).decode("utf-8", "replace"))
    return out


def counters() -> Dict[str, int]:
    """The metrics table as ``{name: value}`` (empty when the plane is
    off). One relaxed-read pass over the shm slots."""
    st = _map()
    if st is None:
        return {}
    mm, base = st.mm, st.metrics_off
    n = min(len(METRIC_NAMES), st.metrics_cap)
    return {METRIC_NAMES[i]: _U64.unpack_from(mm, base + i * 8)[0]
            for i in range(n)}


# -- registry / lens sync -----------------------------------------------------

# the lens hop triples, bound ONCE at import with literal hop names (the
# `stage` lint rule's cached-counter contract); the table keys each hop
# mirrors ride alongside
from tpurpc.obs import lens as _lens  # noqa: E402  (after the ABI tables)

_HOP_SYNC: Tuple[Tuple[Tuple, str, str], ...] = (
    (_lens.hop_counters("native_send"), "rdv_send_bytes",
     "rdv_send_busy_ns"),
    (_lens.hop_counters("native_recv"), "rdv_recv_bytes",
     "rdv_recv_busy_ns"),
    (_lens.hop_counters("native_rdv"), "rdv_send_bytes", "rdv_wait_ns"),
)


def sync_registry() -> bool:
    """Mirror the native table into the PR 4 registry (``native_<name>``
    series: counters get their externally-owned running total, gauges the
    instantaneous value) and feed the lens waterfall's native hops.
    Scrape-time only — /metrics, tsdb sampling, and /debug/waterfall call
    this; the C hot path never sees Python. Returns False when off."""
    vals = counters()
    if not vals:
        return False
    from tpurpc.obs import metrics as _metrics

    reg = _metrics.registry()
    for name, v in vals.items():
        if name in GAUGE_METRICS:
            reg.gauge("native_" + name).set(v)
        else:
            # value assignment, not inc(): the shm slot owns the total
            reg.counter("native_" + name).value = v
    for (b, ns, _cp), bkey, nkey in _HOP_SYNC:
        b.value = vals[bkey]
        ns.value = vals[nkey]
    return True


# -- test / lifecycle hooks ---------------------------------------------------

def reset() -> None:
    """Zero the ring + table (test isolation; callers quiesce emitters
    first, the same promise flight.FlightRecorder.reset makes)."""
    lib = _lib()
    if lib is not None:
        lib.tpr_obs_reset()


def postfork_reset() -> None:
    """Forked shard worker: tell the C side to drop the inherited mapping
    (without unlinking the parent's region) and build its own, then drop
    our cached map so the next read attaches to the child's region."""
    global _state
    lib = _lib()
    if lib is not None:
        lib.tpr_obs_postfork()
    with _lock:
        if isinstance(_state, _Map):
            _state.close()
        _state = None


def reset_for_tests() -> None:
    """Forget the cached mapping/decision (mirrors _native.reset_for_tests
    — tests that flip TPURPC_NATIVE_OBS in-process re-probe)."""
    global _state, _bound
    with _lock:
        if isinstance(_state, _Map):
            _state.close()
        _state = None
        _bound = False
