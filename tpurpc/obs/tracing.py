"""Per-RPC span timelines with cross-process trace-context propagation.

One RPC, one ``trace_id``; each recorded interval is a span
(``send-lease``, ``wire``, ``dispatch``, ``batch-wait``, ``infer``,
``respond``) carrying ``(trace_id, span_id, parent_id, name, t0, dur)``.
The context travels in ordinary call metadata under the text key
:data:`HEADER` (``"%016x-%08x-%d"``), so it crosses every wire tpurpc
speaks — the native framing, the gRPC h2 mapping, and the native (C) plane
via ``tpr_call_start``'s metadata array — without a new wire feature.

Near-free when disabled (the default): the ONE module global
:data:`ACTIVE` gates every entry point, so an untraced process pays a
single global load + branch per instrumented site. Sampling is enabled by
``TPURPC_TRACE_SAMPLE=<rate 0..1>`` or programmatically
(:func:`force` / :func:`configure`).

Finished spans land in a bounded in-process ring (default 4096, env
``TPURPC_TRACE_BUFFER``); export as a plain span list / nested tree for
tests (:func:`spans`, :func:`span_tree`) or as Chrome ``trace_event`` JSON
for perfetto/chrome://tracing (:func:`chrome_trace`, served at
``GET /traces`` by the introspection plane).

**Tail-based capture (tpurpc-blackbox, ISSUE 5).** Head sampling misses the
one wedged RPC in a million by construction — the pathological call is
exactly the one the sampler skipped. With tail capture on (the default;
``TPURPC_TRACE_TAIL=0`` opts out), every RPC whose sampler draw declined
still gets a PROVISIONAL trace context (header flag ``2``): its spans
accumulate in a bounded side buffer keyed by trace id, and on completion
:func:`tail_decide` COMMITS them to the main span ring iff the call was
slow (over ``TPURPC_TRACE_TAIL_MS`` or the method's rolling-p99 multiple,
fed by the stall watchdog), errored, or watchdog-flagged
(:func:`tail_flag`) — otherwise they age out untouched. So
``TPURPC_TRACE_SAMPLE=0`` still yields a full span tree for every
pathological call, at a bounded always-on cost (the provisional buffer is
a fixed-size dict of fixed-size lists; the per-call price is the same span
records a sampled call pays).

Two gates, one fast check: :data:`ACTIVE` stays "head sampling is live"
(back-compat), :data:`LIVE` is the union gate instrumented sites load —
``ACTIVE or tail-capture-on``.
"""

from __future__ import annotations

import itertools
import random
import threading
import time
from collections import deque
from typing import Dict, List, Optional

__all__ = [
    "HEADER", "ACTIVE", "LIVE", "TraceContext", "configure", "force",
    "tail", "maybe_sample", "current", "adopt", "use", "span", "begin",
    "finish", "record", "tail_decide", "tail_flag", "tail_pending",
    "spans", "span_tree", "chrome_trace", "clock_anchor", "reset",
]

#: metadata key the context rides in (text — works across the h2 plane's
#: ascii metadata and the native plane's char* arrays alike)
HEADER = "tpurpc-trace"

#: head-sampling gate (back-compat): True iff the sampler can fire
ACTIVE = False
#: the ONE fast gate instrumented sites load: sampling OR tail capture live
LIVE = False

_rate = 0.0
_forced: Optional[bool] = None
_tail: Optional[bool] = None  # None = env default (on)
_lock = threading.Lock()
#: cached per-call gates, recomputed by configure()/force()/tail() — the
#: env reads behind them cost microseconds and must never sit on the
#: per-RPC path (measured: _env twice per call ≈ 8 µs on a 60 µs RPC)
_TAIL_LIVE = False
_TAIL_STATIC_NS = 250_000_000


def _tail_default() -> bool:
    from tpurpc.utils.config import _env

    return (_env("TPURPC_TRACE_TAIL") or "1").lower() not in (
        "0", "off", "false")


def _tail_on() -> bool:
    return _tail if _tail is not None else _tail_default()


def _buffer_cap() -> int:
    from tpurpc.utils.config import _env

    raw = _env("TPURPC_TRACE_BUFFER") or ""
    try:
        return max(64, int(raw)) if raw else 4096
    except ValueError:
        return 4096


_spans: "deque" = deque(maxlen=_buffer_cap())
_tls = threading.local()
#: span-id allocator: sequential, not random — ids only need to be unique
#: within the bounded span buffer, and ``next()`` on a count is both
#: GIL-atomic and ~5x cheaper than getrandbits per span (the trace path
#: runs per sampled RPC; trace_ids stay random 64-bit).
_span_ids = itertools.count(1)


def _next_span_id() -> int:
    return next(_span_ids) & 0xFFFFFFFF


class TraceContext:
    """(trace_id, span_id, sampled, provisional) — what propagates.

    ``provisional`` marks a tail-capture context: spans route to the
    pending side buffer until :func:`tail_decide` commits or ages them out.
    On the wire the flag field carries ``2`` (old peers read it as
    "sampled", which merely over-records one call on a mixed fleet)."""

    __slots__ = ("trace_id", "span_id", "sampled", "provisional")

    def __init__(self, trace_id: int, span_id: int, sampled: bool = True,
                 provisional: bool = False):
        self.trace_id = trace_id & (1 << 64) - 1
        self.span_id = span_id & (1 << 32) - 1
        self.sampled = sampled
        self.provisional = provisional

    def encode(self) -> str:
        fl = 2 if self.provisional else int(self.sampled)
        return f"{self.trace_id:016x}-{self.span_id:08x}-{fl}"

    @staticmethod
    def decode(value) -> "Optional[TraceContext]":
        try:
            if isinstance(value, (bytes, bytearray, memoryview)):
                value = bytes(value).decode("ascii")
            t, s, fl = value.split("-")
            return TraceContext(int(t, 16), int(s, 16), fl != "0",
                                provisional=fl == "2")
        except (ValueError, AttributeError):
            return None  # malformed context: untraced, never an error

    def child(self) -> "TraceContext":
        return TraceContext(self.trace_id, _next_span_id(), self.sampled,
                            provisional=self.provisional)

    def __repr__(self) -> str:
        return f"<TraceContext {self.encode()}>"


def adopt(value) -> "Optional[TraceContext]":
    """Decode a wire context AND register tail-capture state: a provisional
    context arriving from a peer opens this process's pending buffer for
    the trace, so server-side spans join the same tail decision. The
    server planes use this instead of bare ``decode``."""
    ctx = TraceContext.decode(value)
    if ctx is not None and ctx.provisional:
        _tail_register(ctx.trace_id)
    return ctx


# -- sampling ----------------------------------------------------------------

def _recompute_gates() -> None:
    global ACTIVE, LIVE, _TAIL_LIVE, _TAIL_STATIC_NS
    ACTIVE = _forced if _forced is not None else _rate > 0.0
    _TAIL_LIVE = _forced is not False and _tail_on()
    LIVE = ACTIVE or _TAIL_LIVE
    from tpurpc.utils.config import _env

    raw = _env("TPURPC_TRACE_TAIL_MS") or ""
    try:
        _TAIL_STATIC_NS = int(float(raw) * 1e6) if raw else int(
            _TAIL_MS_DEFAULT * 1e6)
    except ValueError:
        _TAIL_STATIC_NS = int(_TAIL_MS_DEFAULT * 1e6)


def configure(rate: Optional[float] = None) -> None:
    """Set the sampling rate (None = re-read ``TPURPC_TRACE_SAMPLE``)."""
    global _rate
    if rate is None:
        from tpurpc.utils.config import _env

        raw = _env("TPURPC_TRACE_SAMPLE") or "0"
        try:
            rate = float(raw)
        except ValueError:
            rate = 0.0
    with _lock:
        _rate = min(1.0, max(0.0, rate))
        _recompute_gates()


def force(on: Optional[bool]) -> None:
    """Tests/bench: True samples every call, False disables everything
    (tail capture included — the bench's true-off leg), None returns
    control to the configured rate."""
    global _forced
    with _lock:
        _forced = on
        _recompute_gates()


def tail(on: Optional[bool]) -> None:
    """Enable/disable tail capture (None = re-read ``TPURPC_TRACE_TAIL``,
    whose default is ON — the blackbox contract)."""
    global _tail
    with _lock:
        _tail = on
        _recompute_gates()


def maybe_sample() -> Optional[TraceContext]:
    """Root decision for a new outgoing RPC: the ambient context if one is
    installed; a fresh COMMITTED root when the head sampler fires; a fresh
    PROVISIONAL root when tail capture is on (spans buffered, committed
    only if the call turns out pathological); else None."""
    if not LIVE:
        return None
    cur = getattr(_tls, "ctx", None)
    if cur is not None:
        return cur
    if ACTIVE and (_forced or random.random() < _rate):
        return TraceContext(random.getrandbits(64), _next_span_id())
    if _TAIL_LIVE:
        ctx = TraceContext(random.getrandbits(64), _next_span_id(),
                           provisional=True)
        _tail_register(ctx.trace_id)
        return ctx
    return None


# -- ambient context ---------------------------------------------------------

def current() -> Optional[TraceContext]:
    return getattr(_tls, "ctx", None) if LIVE else None


class use:
    """``with use(ctx):`` — install ``ctx`` as this thread's ambient trace
    context. A slotted class, not a generator contextmanager: this sits on
    the per-sampled-RPC path and the generator protocol costs ~3x."""

    __slots__ = ("ctx", "_prev")

    def __init__(self, ctx: Optional[TraceContext]):
        self.ctx = ctx

    def __enter__(self):
        self._prev = getattr(_tls, "ctx", None)
        _tls.ctx = self.ctx
        return self.ctx

    def __exit__(self, *exc):
        _tls.ctx = self._prev
        return False


# -- recording ---------------------------------------------------------------
#
# A finished span is a plain 8-tuple — one allocation, no attribute churn:
#   (trace_id, span_id, parent_id, name, t0_ns, dur_ns, tid, attrs|None)
# The tuple shape is private; export (:func:`spans`) rebuilds dicts.
#
# Routing: spans of a PROVISIONAL trace go to its bounded pending list;
# spans of a committed (or never-registered, i.e. head-sampled) trace go
# straight to the main ring. One dict.get per span decides.

#: tail-capture side buffer: trace_id -> list of span tuples, or
#: _COMMITTED once tail_decide/tail_flag promoted the trace (late spans
#: then land in the main ring directly). Uncommitted traces simply AGE OUT
#: by insertion-order eviction — a "drop" needs no bookkeeping and can
#: never race a peer's commit.
_COMMITTED: list = []  # sentinel (identity compare)
_pending: "Dict[int, list]" = {}
_plock = threading.Lock()
_PENDING_TRACES = 512
_PENDING_SPANS = 96


def _tail_register(trace_id: int) -> None:
    if trace_id in _pending:
        return
    with _plock:
        if trace_id in _pending:
            return
        while len(_pending) >= _PENDING_TRACES:
            _pending.pop(next(iter(_pending)), None)  # evict oldest
        _pending[trace_id] = []


def _route_append(trace_id: int, tup: tuple) -> None:
    lst = _pending.get(trace_id)
    if lst is None or lst is _COMMITTED:
        _spans.append(tup)
    elif len(lst) < _PENDING_SPANS:
        lst.append(tup)


def record(name: str, ctx: Optional[TraceContext], t0_ns: int, dur_ns: int,
           **attrs) -> None:
    """Store one externally-timed span (the batcher stamps its own
    enqueue/dispatch/retire times)."""
    if ctx is None or not ctx.sampled:
        return
    _route_append(ctx.trace_id,
                  (ctx.trace_id, _next_span_id(), ctx.span_id, name, t0_ns,
                   max(0, dur_ns), threading.get_ident() & 0xFFFF,
                   attrs or None))


def begin(name: str, ctx: Optional[TraceContext]) -> Optional[list]:
    """Open-ended span for intervals that end on ANOTHER thread (the
    pipelined client's wire span ends on the reader). Pair with
    :func:`finish`."""
    if ctx is None or not ctx.sampled:
        return None
    return [ctx.trace_id, _next_span_id(), ctx.span_id, name,
            time.monotonic_ns(), -1, threading.get_ident() & 0xFFFF, None]


def finish(sp: Optional[list], **attrs) -> None:
    if sp is None:
        return
    sp[5] = time.monotonic_ns() - sp[4]
    if attrs:
        sp[7] = attrs
    _route_append(sp[0], tuple(sp))


class _NullSpan:
    """Shared stateless no-op context manager for the untraced path."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()
#: shared reusable no-op context manager — instrumentation sites use it
#: instead of allocating a contextlib.nullcontext() per untraced call
NULL_CM = _NULL


class _SpanCtx:
    __slots__ = ("_name", "_ctx", "_attrs", "_t0")

    def __init__(self, name, ctx, attrs):
        self._name = name
        self._ctx = ctx
        self._attrs = attrs

    def __enter__(self):
        self._t0 = time.monotonic_ns()
        return self._ctx

    def __exit__(self, *exc):
        ctx = self._ctx
        _route_append(ctx.trace_id,
                      (ctx.trace_id, _next_span_id(), ctx.span_id,
                       self._name, self._t0,
                       time.monotonic_ns() - self._t0,
                       threading.get_ident() & 0xFFFF, self._attrs))
        return False


def span(name: str, ctx: Optional[TraceContext] = None, **attrs):
    """``with span("infer"):`` — records when the (ambient or given)
    context is sampled; a shared no-op otherwise. Spans parent to ``ctx``
    itself (no ambient reinstall: body code that captures
    :func:`current` sees the call's context, and the per-span TLS churn
    stays off the sampled hot path)."""
    if not LIVE:
        return _NULL
    ctx = ctx if ctx is not None else current()
    if ctx is None or not ctx.sampled:
        return _NULL
    return _SpanCtx(name, ctx, attrs or None)


# -- tail-capture decisions ---------------------------------------------------

_TAIL_MS_DEFAULT = 250.0


def _tail_threshold_ns(method: Optional[str]) -> int:
    """The slow bar: the static ``TPURPC_TRACE_TAIL_MS`` floor (cached —
    re-read on configure()/tail()), tightened by the method's rolling-p99
    multiple when the stall watchdog has one (so a 2 ms method's 50 ms
    outlier is captured even far under the static bar)."""
    static_ns = _TAIL_STATIC_NS
    if method is not None:
        try:
            from tpurpc.obs import watchdog as _wd

            p99_mult = _wd.get().slow_threshold_ns(method)
            if p99_mult is not None:
                return min(static_ns, p99_mult)
        except Exception:
            pass
    return static_ns


def tail_commit(trace_id: int) -> None:
    """Promote a provisional trace's buffered spans into the main ring;
    later spans for the trace land there directly."""
    with _plock:
        lst = _pending.get(trace_id)
        if lst is _COMMITTED:
            return
        if lst:
            _spans.extend(lst)
        if trace_id not in _pending:
            while len(_pending) >= _PENDING_TRACES:
                _pending.pop(next(iter(_pending)), None)
        _pending[trace_id] = _COMMITTED


#: watchdog face: flag a wedged call's trace for capture while it is STILL
#: in flight — the spans recorded so far surface immediately on /traces
tail_flag = tail_commit


def tail_decide(ctx: Optional[TraceContext], dur_ns: int,
                error: bool = False, method: Optional[str] = None) -> bool:
    """The tail-sampling decision, called where an RPC completes: commit
    the provisional trace iff the call errored or was slow (static
    threshold or method-p99 multiple). Returns True when the trace is
    committed (callers may then record post-hoc spans). No-op for
    non-provisional contexts — head-sampled spans are already in the
    ring."""
    if ctx is None or not getattr(ctx, "provisional", False):
        return False
    if _pending.get(ctx.trace_id) is _COMMITTED:
        return True
    if error or dur_ns >= _tail_threshold_ns(method):
        tail_commit(ctx.trace_id)
        return True
    return False


def tail_pending(trace_id: Optional[int] = None) -> int:
    """Observability of the buffer itself (tests, /debug): the number of
    pending (uncommitted) traces, or one trace's buffered span count."""
    if trace_id is None:
        return sum(1 for v in _pending.values() if v is not _COMMITTED)
    lst = _pending.get(trace_id)
    return len(lst) if isinstance(lst, list) and lst is not _COMMITTED else 0


# -- export ------------------------------------------------------------------

def spans(trace_id: "Optional[int | str]" = None) -> List[Dict]:
    """Finished spans (oldest first), optionally filtered by trace id
    (int or 16-hex-digit string)."""
    if isinstance(trace_id, str):
        trace_id = int(trace_id, 16)
    out = []
    for (tid64, sid, pid, name, t0, dur, tid, attrs) in list(_spans):
        if trace_id is not None and tid64 != trace_id:
            continue
        d = {"trace_id": f"{tid64:016x}", "span_id": sid, "parent_id": pid,
             "name": name, "t0_ns": t0, "dur_ns": dur, "tid": tid}
        if attrs:
            d["attrs"] = attrs
        out.append(d)
    out.sort(key=lambda d: d["t0_ns"])
    return out


def span_tree(trace_id: "int | str") -> Dict:
    """One trace as a nested tree: ``{"trace_id", "spans": [roots]}``,
    each node ``{"name", "t0_ns", "dur_ns", "children": [...]}`` —
    the plain-dict export the acceptance tests assert on."""
    flat = spans(trace_id)
    by_id = {}
    for d in flat:
        by_id[d["span_id"]] = dict(d, children=[])
    roots = []
    for d in flat:
        node = by_id[d["span_id"]]
        parent = by_id.get(d["parent_id"])
        if parent is not None and parent is not node:
            parent["children"].append(node)
        else:
            roots.append(node)
    tid = flat[0]["trace_id"] if flat else (
        f"{int(trace_id, 16):016x}" if isinstance(trace_id, str)
        else f"{trace_id:016x}")
    return {"trace_id": tid, "spans": roots}


def clock_anchor() -> Dict:
    """This process's monotonic↔wallclock anchor (tpurpc-lens, ISSUE 8).

    Span/flight timestamps are ``time.monotonic_ns`` — correct for
    durations, but each process has its OWN monotonic epoch, so traces
    exported by different processes (shard workers, fleet members) cannot
    be merged by raw ``ts``. The anchor is one simultaneous reading of both
    clocks: a collector rebases any monotonic stamp from this process onto
    the shared wall clock as ``wall = t_mono - mono_ns + wall_ns``. The
    wall read is bracketed by two monotonic reads and paired with their
    midpoint, bounding the skew to half the bracket width."""
    import os

    m0 = time.monotonic_ns()
    wall = time.time_ns()  # the anchor IS absolute (time_ns, not time())
    m1 = time.monotonic_ns()
    return {"pid": os.getpid(), "mono_ns": (m0 + m1) // 2, "wall_ns": wall,
            "uncertainty_ns": m1 - m0}


def chrome_trace(trace_id: "Optional[int | str]" = None) -> Dict:
    """Chrome ``trace_event`` JSON (perfetto / chrome://tracing): complete
    ("X") events with microsecond timestamps, one row per recording
    thread, plus the ``process_name``/``thread_name`` metadata ("M")
    events — without them perfetto renders bare pid/tid numbers instead of
    named lanes. Span attrs pass through as ``args``.

    The top-level ``clock_anchor`` (chrome-trace tolerates extra keys) is
    this process's monotonic↔wall pairing — the piece that lets
    ``python -m tpurpc.tools.timeline`` align traces exported by different
    processes onto one wall-clock axis (see :func:`clock_anchor`)."""
    events: List[Dict] = [{
        "ph": "M", "name": "process_name", "pid": 1, "tid": 0,
        "args": {"name": "tpurpc"},
    }]
    named_tids = set()
    for d in spans(trace_id):
        tid = d["tid"]
        if tid not in named_tids:
            named_tids.add(tid)
            events.append({
                "ph": "M", "name": "thread_name", "pid": 1, "tid": tid,
                "args": {"name": f"tpurpc-thread-{tid:#x}"},
            })
        events.append({
            "ph": "X",
            "name": d["name"],
            "cat": "tpurpc",
            "ts": d["t0_ns"] / 1e3,
            "dur": max(d["dur_ns"], 0) / 1e3,
            "pid": 1,
            "tid": tid,
            "args": dict(d.get("attrs") or {},
                         trace_id=d["trace_id"],
                         span_id=d["span_id"]),
        })
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "clock_anchor": clock_anchor()}


def reset() -> None:
    _spans.clear()
    with _plock:
        _pending.clear()


configure()
