"""Per-RPC span timelines with cross-process trace-context propagation.

One RPC, one ``trace_id``; each recorded interval is a span
(``send-lease``, ``wire``, ``dispatch``, ``batch-wait``, ``infer``,
``respond``) carrying ``(trace_id, span_id, parent_id, name, t0, dur)``.
The context travels in ordinary call metadata under the text key
:data:`HEADER` (``"%016x-%08x-%d"``), so it crosses every wire tpurpc
speaks — the native framing, the gRPC h2 mapping, and the native (C) plane
via ``tpr_call_start``'s metadata array — without a new wire feature.

Near-free when disabled (the default): the ONE module global
:data:`ACTIVE` gates every entry point, so an untraced process pays a
single global load + branch per instrumented site. Sampling is enabled by
``TPURPC_TRACE_SAMPLE=<rate 0..1>`` or programmatically
(:func:`force` / :func:`configure`).

Finished spans land in a bounded in-process ring (default 4096, env
``TPURPC_TRACE_BUFFER``); export as a plain span list / nested tree for
tests (:func:`spans`, :func:`span_tree`) or as Chrome ``trace_event`` JSON
for perfetto/chrome://tracing (:func:`chrome_trace`, served at
``GET /traces`` by the introspection plane).
"""

from __future__ import annotations

import itertools
import random
import threading
import time
from collections import deque
from typing import Dict, List, Optional

__all__ = [
    "HEADER", "ACTIVE", "TraceContext", "configure", "force",
    "maybe_sample", "current", "use", "span", "begin", "finish", "record",
    "spans", "span_tree", "chrome_trace", "reset",
]

#: metadata key the context rides in (text — works across the h2 plane's
#: ascii metadata and the native plane's char* arrays alike)
HEADER = "tpurpc-trace"

#: fast gate: False ⇒ every instrumented site is one global load + branch
ACTIVE = False

_rate = 0.0
_forced: Optional[bool] = None
_lock = threading.Lock()


def _buffer_cap() -> int:
    from tpurpc.utils.config import _env

    raw = _env("TPURPC_TRACE_BUFFER") or ""
    try:
        return max(64, int(raw)) if raw else 4096
    except ValueError:
        return 4096


_spans: "deque" = deque(maxlen=_buffer_cap())
_tls = threading.local()
#: span-id allocator: sequential, not random — ids only need to be unique
#: within the bounded span buffer, and ``next()`` on a count is both
#: GIL-atomic and ~5x cheaper than getrandbits per span (the trace path
#: runs per sampled RPC; trace_ids stay random 64-bit).
_span_ids = itertools.count(1)


def _next_span_id() -> int:
    return next(_span_ids) & 0xFFFFFFFF


class TraceContext:
    """(trace_id, span_id, sampled) — what propagates, nothing else."""

    __slots__ = ("trace_id", "span_id", "sampled")

    def __init__(self, trace_id: int, span_id: int, sampled: bool = True):
        self.trace_id = trace_id & (1 << 64) - 1
        self.span_id = span_id & (1 << 32) - 1
        self.sampled = sampled

    def encode(self) -> str:
        return f"{self.trace_id:016x}-{self.span_id:08x}-{int(self.sampled)}"

    @staticmethod
    def decode(value) -> "Optional[TraceContext]":
        try:
            if isinstance(value, (bytes, bytearray, memoryview)):
                value = bytes(value).decode("ascii")
            t, s, fl = value.split("-")
            return TraceContext(int(t, 16), int(s, 16), fl != "0")
        except (ValueError, AttributeError):
            return None  # malformed context: untraced, never an error

    def child(self) -> "TraceContext":
        return TraceContext(self.trace_id, _next_span_id(), self.sampled)

    def __repr__(self) -> str:
        return f"<TraceContext {self.encode()}>"


# -- sampling ----------------------------------------------------------------

def configure(rate: Optional[float] = None) -> None:
    """Set the sampling rate (None = re-read ``TPURPC_TRACE_SAMPLE``)."""
    global _rate, ACTIVE
    if rate is None:
        from tpurpc.utils.config import _env

        raw = _env("TPURPC_TRACE_SAMPLE") or "0"
        try:
            rate = float(raw)
        except ValueError:
            rate = 0.0
    with _lock:
        _rate = min(1.0, max(0.0, rate))
        ACTIVE = _forced if _forced is not None else _rate > 0.0


def force(on: Optional[bool]) -> None:
    """Tests/bench: True samples every call, False disables everything,
    None returns control to the configured rate."""
    global _forced, ACTIVE
    with _lock:
        _forced = on
        ACTIVE = bool(on) if on is not None else _rate > 0.0


def maybe_sample() -> Optional[TraceContext]:
    """Root-sampling decision for a new outgoing RPC: the ambient context
    if one is installed, else a fresh root context when the sampler fires,
    else None (the overwhelmingly common untraced path)."""
    if not ACTIVE:
        return None
    cur = getattr(_tls, "ctx", None)
    if cur is not None:
        return cur
    if _forced or random.random() < _rate:
        return TraceContext(random.getrandbits(64), _next_span_id())
    return None


# -- ambient context ---------------------------------------------------------

def current() -> Optional[TraceContext]:
    return getattr(_tls, "ctx", None) if ACTIVE else None


class use:
    """``with use(ctx):`` — install ``ctx`` as this thread's ambient trace
    context. A slotted class, not a generator contextmanager: this sits on
    the per-sampled-RPC path and the generator protocol costs ~3x."""

    __slots__ = ("ctx", "_prev")

    def __init__(self, ctx: Optional[TraceContext]):
        self.ctx = ctx

    def __enter__(self):
        self._prev = getattr(_tls, "ctx", None)
        _tls.ctx = self.ctx
        return self.ctx

    def __exit__(self, *exc):
        _tls.ctx = self._prev
        return False


# -- recording ---------------------------------------------------------------
#
# A finished span is a plain 8-tuple — one allocation, no attribute churn:
#   (trace_id, span_id, parent_id, name, t0_ns, dur_ns, tid, attrs|None)
# The tuple shape is private; export (:func:`spans`) rebuilds dicts.

def record(name: str, ctx: Optional[TraceContext], t0_ns: int, dur_ns: int,
           **attrs) -> None:
    """Store one externally-timed span (the batcher stamps its own
    enqueue/dispatch/retire times)."""
    if ctx is None or not ctx.sampled:
        return
    _spans.append((ctx.trace_id, _next_span_id(), ctx.span_id, name, t0_ns,
                   max(0, dur_ns), threading.get_ident() & 0xFFFF,
                   attrs or None))  # deque.append: GIL-atomic, maxlen-bounded


def begin(name: str, ctx: Optional[TraceContext]) -> Optional[list]:
    """Open-ended span for intervals that end on ANOTHER thread (the
    pipelined client's wire span ends on the reader). Pair with
    :func:`finish`."""
    if ctx is None or not ctx.sampled:
        return None
    return [ctx.trace_id, _next_span_id(), ctx.span_id, name,
            time.monotonic_ns(), -1, threading.get_ident() & 0xFFFF, None]


def finish(sp: Optional[list], **attrs) -> None:
    if sp is None:
        return
    sp[5] = time.monotonic_ns() - sp[4]
    if attrs:
        sp[7] = attrs
    _spans.append(tuple(sp))


class _NullSpan:
    """Shared stateless no-op context manager for the untraced path."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()
#: shared reusable no-op context manager — instrumentation sites use it
#: instead of allocating a contextlib.nullcontext() per untraced call
NULL_CM = _NULL


class _SpanCtx:
    __slots__ = ("_name", "_ctx", "_attrs", "_t0")

    def __init__(self, name, ctx, attrs):
        self._name = name
        self._ctx = ctx
        self._attrs = attrs

    def __enter__(self):
        self._t0 = time.monotonic_ns()
        return self._ctx

    def __exit__(self, *exc):
        ctx = self._ctx
        _spans.append((ctx.trace_id, _next_span_id(), ctx.span_id,
                       self._name, self._t0,
                       time.monotonic_ns() - self._t0,
                       threading.get_ident() & 0xFFFF, self._attrs))
        return False


def span(name: str, ctx: Optional[TraceContext] = None, **attrs):
    """``with span("infer"):`` — records when the (ambient or given)
    context is sampled; a shared no-op otherwise. Spans parent to ``ctx``
    itself (no ambient reinstall: body code that captures
    :func:`current` sees the call's context, and the per-span TLS churn
    stays off the sampled hot path)."""
    if not ACTIVE:
        return _NULL
    ctx = ctx if ctx is not None else current()
    if ctx is None or not ctx.sampled:
        return _NULL
    return _SpanCtx(name, ctx, attrs or None)


# -- export ------------------------------------------------------------------

def spans(trace_id: "Optional[int | str]" = None) -> List[Dict]:
    """Finished spans (oldest first), optionally filtered by trace id
    (int or 16-hex-digit string)."""
    if isinstance(trace_id, str):
        trace_id = int(trace_id, 16)
    out = []
    for (tid64, sid, pid, name, t0, dur, tid, attrs) in list(_spans):
        if trace_id is not None and tid64 != trace_id:
            continue
        d = {"trace_id": f"{tid64:016x}", "span_id": sid, "parent_id": pid,
             "name": name, "t0_ns": t0, "dur_ns": dur, "tid": tid}
        if attrs:
            d["attrs"] = attrs
        out.append(d)
    out.sort(key=lambda d: d["t0_ns"])
    return out


def span_tree(trace_id: "int | str") -> Dict:
    """One trace as a nested tree: ``{"trace_id", "spans": [roots]}``,
    each node ``{"name", "t0_ns", "dur_ns", "children": [...]}`` —
    the plain-dict export the acceptance tests assert on."""
    flat = spans(trace_id)
    by_id = {}
    for d in flat:
        by_id[d["span_id"]] = dict(d, children=[])
    roots = []
    for d in flat:
        node = by_id[d["span_id"]]
        parent = by_id.get(d["parent_id"])
        if parent is not None and parent is not node:
            parent["children"].append(node)
        else:
            roots.append(node)
    tid = flat[0]["trace_id"] if flat else (
        f"{int(trace_id, 16):016x}" if isinstance(trace_id, str)
        else f"{trace_id:016x}")
    return {"trace_id": tid, "spans": roots}


def chrome_trace(trace_id: "Optional[int | str]" = None) -> Dict:
    """Chrome ``trace_event`` JSON (perfetto / chrome://tracing): complete
    ("X") events, microsecond timestamps, one row per recording thread."""
    events = []
    for d in spans(trace_id):
        events.append({
            "ph": "X",
            "name": d["name"],
            "cat": "tpurpc",
            "ts": d["t0_ns"] / 1e3,
            "dur": max(d["dur_ns"], 0) / 1e3,
            "pid": 1,
            "tid": d["tid"],
            "args": dict(d.get("attrs") or {},
                         trace_id=d["trace_id"],
                         span_id=d["span_id"]),
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def reset() -> None:
    _spans.clear()


configure()
