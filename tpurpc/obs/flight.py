"""tpurpc-blackbox flight recorder: always-on binary ring of transport events.

The rare-event failures that matter in serving fleets — credit starvation,
wake-latency stalls, head-of-line blocking — are exactly what sampled
telemetry misses by construction (Biswas et al. 1804.01138 §5; Xue et al.
1805.08430 §3): by the time an operator looks, the evidence is gone. The
flight recorder is the postmortem answer: a fixed-size binary ring of
structured transport EVENTS (connect/disconnect, write-stall and
credit-starvation edges, send-lease reserve/commit/abort, poller BP↔EV
adoption, h2 window exhaustion, batcher flush decisions, deadline expiry,
peer death/reconnect) that is cheap enough to leave on in production and
replayable after the fact.

Cost model — why this can be ALWAYS ON:

* **Events are edges, not traffic.** Nothing on the per-message path emits;
  only state *transitions* do (a pair entering a write stall, a poller mode
  flip, a lease opening). A healthy serving loop emits near zero events.
* **Preallocated encoder, no per-event allocation.** ``emit`` is one
  ``struct.pack_into`` of five ints into a preallocated ``bytearray`` ring —
  no dicts, no f-strings, no bytes objects. The ``flight`` lint rule
  (``analysis/lint.py``) enforces this shape at every hot-module call site.
* **Lock-free.** Slot allocation is ``next()`` on an ``itertools.count``
  (GIL-atomic); concurrent emitters write distinct slots. A reader racing a
  wrap can observe one torn record, which the defensive decoder skips —
  the trade a crash recorder should make (a lock on the emit path is a
  probe effect; a torn record is a skipped line in a postmortem).

Record layout (32 bytes, little-endian)::

    <Q t_ns> <H code> <H tag> <I tid> <q a1> <q a2>

``tag`` is an interned small int naming the entity (pair, connection,
method) — intern once at connect time via :func:`tag_for`, emit plain ints
forever after. Dump via ``GET /debug/flight`` on the scrape plane, on
``SIGUSR2`` (stderr), or automatically when the stall watchdog trips.
"""

from __future__ import annotations

import itertools
import struct
import threading
import time
from typing import Dict, List, Optional

__all__ = [
    "RECORDER", "FlightRecorder", "emit", "tag_for", "tag_name",
    "snapshot", "dump_text", "install_sigusr2", "EVENT_NAMES",
]

# -- event codes --------------------------------------------------------------
# Stable small ints: they land in the binary ring and in dumps; append-only.

PAIR_CONNECT = 1          # a1 = peer ring size
PAIR_DISCONNECT = 2       # graceful close
WRITE_STALL_BEGIN = 3     # pair sender stalled (want_write edge up)
WRITE_STALL_END = 4       # stall resolved (want_write edge down)
CREDIT_STARVE_BEGIN = 5   # ring writer out of credits; a1 = in-flight bytes
CREDIT_STARVE_END = 6
LEASE_RESERVE = 7         # a1 = reserved bytes
LEASE_COMMIT = 8
LEASE_ABORT = 9
POLLER_BP = 10            # hybrid waiter (re)adopted the busy-poll window
POLLER_EV = 11            # hybrid waiter parked on fds (EWMA below floor)
H2_WINDOW_EXHAUSTED = 12  # a1 = stream id
BATCH_FLUSH = 13          # a1 = flush reason code, a2 = batch size
DEADLINE_EXPIRED = 14     # a1 = configured timeout (us)
PEER_DEATH = 15           # pair/connection died unexpectedly
RECONNECT = 16            # subchannel re-dialed after a death
CONN_CONNECT = 17         # client transport connection established
CONN_DEAD = 18            # client transport connection died; a1 = 1 if graceful
CALL_FIRST_OK = 19        # first OK call on a connection (reconnect proof)
WATCHDOG_TRIP = 20        # a1 = stalled-call age (ms)
# tpurpc-fleet (ISSUE 6): hedging / drain / admission / subchannel health
HEDGE_FIRED = 21          # a1 = attempt index (1 = first hedge)
HEDGE_WON = 22            # a1 = winning attempt index (0 = original)
HEDGE_CANCELLED = 23      # a1 = cancelled attempt index
DRAIN_BEGIN = 24          # a1 = connections at drain start
DRAIN_END = 25            # a1 = streams still open at budget expiry (0=clean)
ADMIT_REJECT = 26         # a1 = inflight at rejection, a2 = pushback (ms)
SUBCH_EJECT = 27          # a1 = subchannel index, a2 = reason (0=errors,1=slow)
SUBCH_REINSTATE = 28      # a1 = subchannel index
# tpurpc-manycore (ISSUE 7): shard lifecycle + connection handoff
SHARD_START = 29          # worker up; a1 = shard id, a2 = n_shards
SHARD_EXIT = 30           # worker exited gracefully; a1 = shard id
SHARD_DEATH = 31          # supervisor saw a worker die; a1 = shard id, a2 = wait status
CONN_HANDOFF = 32         # supervisor passed an accepted fd; a1 = shard id
# tpurpc-express (ISSUE 9): one-sided rendezvous bulk-tensor transfers.
# Edges pair per link tag: OFFER(a1=req) closed by CLAIM(a1=req) or
# RELEASE(a2=req); CLAIM opens a lease edge (a2=lease) closed by
# COMPLETE(a1=lease) or RELEASE(a1=lease) — the watchdog's rendezvous-stage
# evidence is an unmatched edge in this algebra.
RDV_OFFER = 33            # a1 = request id, a2 = payload bytes
RDV_CLAIM = 34            # a1 = request id (0 = cached grant), a2 = lease id
RDV_WRITE = 35            # one-sided payload write done; a1 = lease id, a2 = bytes
RDV_COMPLETE = 36         # a1 = lease id, a2 = bytes
RDV_RELEASE = 37          # lease/offer abandoned; a1 = lease id (0 = none), a2 = request id
# tpurpc-cadence (ISSUE 10): continuous-batching decode scheduler. One
# STEP pair per DEVICE STEP (amortized over every running stream, like
# BATCH_FLUSH) brackets the membership events: a JOIN/LEAVE/RETIRE names
# the sequence that entered/left between two steps — the acceptance
# evidence that batching is continuous. An open STEP edge is the
# watchdog's `decode-step` stage evidence.
GEN_STEP_BEGIN = 38       # a1 = running batch size, a2 = waiting depth
GEN_STEP_END = 39         # a1 = running batch size, a2 = tokens emitted
GEN_JOIN = 40             # a1 = sequence id, a2 = prompt tokens (0 = resume)
GEN_LEAVE = 41            # client left mid-stream; a1 = seq id, a2 = emitted
GEN_RETIRE = 42           # natural finish; a1 = seq id, a2 = tokens emitted
GEN_SHED = 43             # a1 = slo class (0=interactive,1=batch), a2 = pushback ms
GEN_PREEMPT = 44          # a1 = seq id, a2 = slo class of the preempted seq
# tpurpc-keystone (ISSUE 11): the paged KV-cache plane + disaggregated
# prefill/decode. Alloc/free/prefix-hit are sequence-lifetime edges;
# KV_SWAP_BEGIN/END bracket one swap (a2: 0 = out-to-host, 1 = in-from-
# host) and MIG_BEGIN/MIG_END bracket one live migration — an open
# bracket aged past the stall floor is the watchdog's `kv-swap` /
# `migration` stage evidence. KV_SHIP_* are the block-granular handoff's
# control edges (OFFER-KV answered by a grant, COMPLETE after the
# one-sided block writes); KV_QUARANTINE records blocks pulled from
# circulation on a death path (never an alloc/free pair — quarantined
# blocks do not come back).
KV_ALLOC = 45             # a1 = owner/seq key, a2 = blocks allocated
KV_FREE = 46              # a1 = owner/seq key (0 = raw), a2 = blocks freed
KV_SWAP_BEGIN = 47        # a1 = seq key, a2 = direction (0=out, 1=in)
KV_SWAP_END = 48          # a1 = seq key, a2 = direction
KV_PREFIX_HIT = 49        # a1 = seq key, a2 = entries reused (prefill skipped)
KV_SHIP_OFFER = 50        # a1 = handoff id, a2 = payload bytes offered
KV_SHIP_COMPLETE = 51     # a1 = handoff id, a2 = payload bytes landed
KV_QUARANTINE = 52        # a1 = handoff/seq key (0 = link), a2 = blocks
MIG_BEGIN = 53            # a1 = seq id, a2 = entries to move
MIG_END = 54              # a1 = seq id, a2 = 1 ok / 0 failed
# tpurpc-proof (ISSUE 12): the live protocol verifier's breadcrumb — a
# declared flight-event state machine (analysis/protocol.py) saw an
# illegal transition. a1 = machine index, a2 = the offending event code.
PROTO_VIOLATION = 55
# tpurpc-pulse (ISSUE 13): shared-memory descriptor rings for the
# rendezvous control plane. ADOPT fires once per link when the peer's ring
# descriptor verifies; SPIN/PARK are the consumer's hot↔cold flips (the
# POLLER_BP/EV discipline applied to ring polling); STALL_BEGIN/END
# bracket the producer's ring-full condition — an aged open stall edge is
# the watchdog's `ctrl-ring` evidence that the consumer stopped draining.
CTRL_ADOPT = 56           # a1 = ring slots, a2 = slot bytes
CTRL_SPIN = 57            # consumer hot-polling the ring; a1 = consumed so far
CTRL_PARK = 58            # consumer parked on the framed path; a1 = consumed
CTRL_STALL_BEGIN = 59     # producer saw the ring full; a1 = backlog
CTRL_STALL_END = 60       # space returned (consumer drained)
# tpurpc-argus (ISSUE 14): SLO burn-rate alerting + automatic evidence
# capture. FIRING/RESOLVED bracket one alert episode per (objective tag,
# track) — the slo protocol machine forbids a double-fire or an orphan
# resolve. BUNDLE_WRITTEN records one postmortem bundle landing on disk
# (a1 = trigger code: 0 slo / 1 watchdog / 2 manual, a2 = bundle ordinal).
SLO_FIRING = 61           # a1 = track (0=errors,1=sheds,2=latency), a2 = burn x100
SLO_RESOLVED = 62         # a1 = track, a2 = burn x100 at resolve
BUNDLE_WRITTEN = 63       # a1 = trigger code, a2 = bundle ordinal
# tpurpc-odyssey (ISSUE 15): sequence identity as a first-class flight
# key — the `seq-journey` protocol machine (analysis/protocol.py) runs
# over these plus the PR 10/11 GEN_JOIN/LEAVE/RETIRE/PREEMPT and MIG_*
# events, keyed (scheduler tag, seq id). SUBMIT opens the journey (before
# any JOIN can fire — emitted under the admission lock), FIRST_TOKEN is
# the one per-sequence token edge (TTFT; events are edges, not traffic —
# per-token emission stays banned), DETACH is the migration sender's
# hand-out (the journey continues on the peer under the same trace).
SEQ_SUBMIT = 64           # a1 = seq id, a2 = prompt tokens
SEQ_FIRST_TOKEN = 65      # a1 = seq id, a2 = TTFT (us)
SEQ_DETACH = 66           # a1 = seq id, a2 = KV entries handed out
# tpurpc-hive (ISSUE 16): the connection-scale plane. PARK/UNPARK bracket
# one parked episode per pair (the `park` protocol machine forbids a
# double-park or an unpark with no preceding park); ACCEPT_SHED is the
# listener's pre-handshake pushback under a reconnect storm.
PAIR_PARK = 67            # a1 = ring bytes returned to the pool
PAIR_UNPARK = 68          # a1 = ring bytes re-leased, a2 = 1 if remote wake
ACCEPT_SHED = 69          # a1 = inflight handshakes, a2 = pushback (ms)
# tpurpc-xray (ISSUE 19): native-only edges. The C plane (native/src/
# tpr_obs.cc) REUSES the shared codes above for every edge the Python
# plane also records (RDV_*, CTRL_*, CONN_*) so the protocol machines
# replay it unmodified; these five are edges only the C core can see.
# They arrive through the merged module-level snapshot() with lane
# "native" — the Python recorder never emits them.
NATIVE_PIN_WAIT_BEGIN = 70   # link close() waiting on window pins; a1 = pins
NATIVE_PIN_WAIT_END = 71     # a1 = ns waited
NATIVE_DLV_STALL_BEGIN = 72  # delivery-shard backlog over high water; a1 = depth
NATIVE_DLV_STALL_END = 73    # backlog drained below low water; a1 = depth
NATIVE_RDV_FALLBACK = 74     # eligible send fell back framed; a1 = bytes,
                             # a2 = reason (0 no claim, 1 write failed)

EVENT_NAMES: Dict[int, str] = {
    PAIR_CONNECT: "pair-connect",
    PAIR_DISCONNECT: "pair-disconnect",
    WRITE_STALL_BEGIN: "write-stall-begin",
    WRITE_STALL_END: "write-stall-end",
    CREDIT_STARVE_BEGIN: "credit-starve-begin",
    CREDIT_STARVE_END: "credit-starve-end",
    LEASE_RESERVE: "lease-reserve",
    LEASE_COMMIT: "lease-commit",
    LEASE_ABORT: "lease-abort",
    POLLER_BP: "poller-mode-bp",
    POLLER_EV: "poller-mode-ev",
    H2_WINDOW_EXHAUSTED: "h2-window-exhausted",
    BATCH_FLUSH: "batch-flush",
    DEADLINE_EXPIRED: "deadline-expired",
    PEER_DEATH: "peer-death",
    RECONNECT: "reconnect",
    CONN_CONNECT: "conn-connect",
    CONN_DEAD: "conn-dead",
    CALL_FIRST_OK: "call-first-ok",
    WATCHDOG_TRIP: "watchdog-trip",
    HEDGE_FIRED: "hedge-fired",
    HEDGE_WON: "hedge-won",
    HEDGE_CANCELLED: "hedge-cancelled",
    DRAIN_BEGIN: "drain-begin",
    DRAIN_END: "drain-end",
    ADMIT_REJECT: "admit-reject",
    SUBCH_EJECT: "subch-ejected",
    SUBCH_REINSTATE: "subch-reinstated",
    SHARD_START: "shard-start",
    SHARD_EXIT: "shard-exit",
    SHARD_DEATH: "shard-death",
    CONN_HANDOFF: "conn-handoff",
    RDV_OFFER: "rdv-offer",
    RDV_CLAIM: "rdv-claim",
    RDV_WRITE: "rdv-write",
    RDV_COMPLETE: "rdv-complete",
    RDV_RELEASE: "rdv-release",
    GEN_STEP_BEGIN: "gen-step-begin",
    GEN_STEP_END: "gen-step-end",
    GEN_JOIN: "gen-join",
    GEN_LEAVE: "gen-leave",
    GEN_RETIRE: "gen-retire",
    GEN_SHED: "gen-shed",
    GEN_PREEMPT: "gen-preempt",
    KV_ALLOC: "kv-alloc",
    KV_FREE: "kv-free",
    KV_SWAP_BEGIN: "kv-swap-begin",
    KV_SWAP_END: "kv-swap-end",
    KV_PREFIX_HIT: "kv-prefix-hit",
    KV_SHIP_OFFER: "kv-ship-offer",
    KV_SHIP_COMPLETE: "kv-ship-complete",
    KV_QUARANTINE: "kv-quarantine",
    MIG_BEGIN: "migration-begin",
    MIG_END: "migration-end",
    PROTO_VIOLATION: "proto-violation",
    CTRL_ADOPT: "ctrl-adopt",
    CTRL_SPIN: "ctrl-spin",
    CTRL_PARK: "ctrl-park",
    CTRL_STALL_BEGIN: "ctrl-stall-begin",
    CTRL_STALL_END: "ctrl-stall-end",
    SLO_FIRING: "slo-firing",
    SLO_RESOLVED: "slo-resolved",
    BUNDLE_WRITTEN: "bundle-written",
    SEQ_SUBMIT: "seq-submit",
    SEQ_FIRST_TOKEN: "seq-first-token",
    SEQ_DETACH: "seq-detach",
    PAIR_PARK: "pair-park",
    PAIR_UNPARK: "pair-unpark",
    ACCEPT_SHED: "accept-shed",
    NATIVE_PIN_WAIT_BEGIN: "native-pin-wait-begin",
    NATIVE_PIN_WAIT_END: "native-pin-wait-end",
    NATIVE_DLV_STALL_BEGIN: "native-dlv-stall-begin",
    NATIVE_DLV_STALL_END: "native-dlv-stall-end",
    NATIVE_RDV_FALLBACK: "native-rdv-fallback",
}

#: batch-flush reason codes (a1 of BATCH_FLUSH) — mirrors the jaxshim
#: flush-reason counters so one event names both the decision and the size
FLUSH_REASONS = ("size", "timer", "drained", "close")
FLUSH_REASON_CODE = {name: i for i, name in enumerate(FLUSH_REASONS)}

_REC = struct.Struct("<QHHIqq")
RECORD_BYTES = _REC.size  # 32
_I64_MAX = (1 << 63) - 1
_I64_MIN = -(1 << 63)


def _default_capacity() -> int:
    import os

    raw = os.environ.get("TPURPC_FLIGHT_BUFFER", "")
    try:
        return max(64, int(raw)) if raw else 4096
    except ValueError:
        return 4096


# -- tag interning ------------------------------------------------------------

_tag_lock = threading.Lock()
_tags: Dict[str, int] = {}
_tag_names: List[str] = ["-"]  # tag 0 = anonymous


def tag_for(name: str) -> int:
    """Intern ``name`` to a small int, once per entity lifetime (connect
    time) — the hot emit path then carries only ints. Bounded at 2^16-1
    tags; overflow degrades to the anonymous tag 0, never an error."""
    t = _tags.get(name)
    if t is not None:
        return t
    with _tag_lock:
        t = _tags.get(name)
        if t is None:
            if len(_tag_names) >= 0xFFFF:
                return 0
            t = len(_tag_names)
            _tag_names.append(name)
            _tags[name] = t
        return t


def tag_name(tag: int) -> str:
    try:
        return _tag_names[tag]
    except IndexError:
        return f"#{tag}"


# -- live protocol verification tap (tpurpc-proof, ISSUE 12) ------------------
#
# TPURPC_VERIFY_PROTOCOL=1 installs analysis/protocol.py's LiveVerifier
# here; emit() forwards every recorded event to it AFTER the pack. Cost
# when unset: one global load + None check per event — and events are
# EDGES, so a healthy loop pays nothing either way.

_verify = None


def set_verify_hook(hook) -> None:
    """Install (or clear, with ``None``) the per-event verification tap:
    ``hook(code, tag, a1, a2)`` is called for every recorded event."""
    global _verify
    _verify = hook


def verify_hook():
    return _verify


# -- the recorder -------------------------------------------------------------

class FlightRecorder:
    """Fixed-size binary event ring. See the module docstring for the cost
    argument; the public face is :meth:`emit` (hot) and :meth:`snapshot`
    (cold — decodes, validates, time-orders)."""

    def __init__(self, capacity: Optional[int] = None):
        self.capacity = capacity or _default_capacity()
        self._buf = bytearray(self.capacity * RECORD_BYTES)
        self._slots = itertools.count()
        self.enabled = True

    # -- hot path ------------------------------------------------------------

    def emit(self, code: int, tag: int = 0, a1: int = 0, a2: int = 0) -> None:
        """Record one event: one pack_into, zero allocation beyond the slot
        int. Never raises — a recorder failure must not take down the
        transport it is recording."""
        if not self.enabled:
            return
        try:
            _REC.pack_into(
                self._buf, (next(self._slots) % self.capacity) * RECORD_BYTES,
                time.monotonic_ns(), code, tag,
                threading.get_ident() & 0xFFFFFFFF,
                min(max(int(a1), _I64_MIN), _I64_MAX),
                min(max(int(a2), _I64_MIN), _I64_MAX))
        except (struct.error, ValueError):
            pass
        if _verify is not None:
            try:
                _verify(code, tag, a1, a2)
            except Exception:
                pass  # verification must never break the recorder contract

    # -- cold paths ----------------------------------------------------------

    def snapshot(self, since_ns: int = 0,
                 limit: Optional[int] = None) -> List[dict]:
        """Decode the ring into time-ordered event dicts (oldest first).

        Slot order is not arrival order after a wrap, so ordering comes from
        the monotonic stamps; zeroed slots and torn/unknown records (a
        reader racing a wrap) are skipped — defensive by design."""
        out: List[dict] = []
        # tpurpc-manycore: a worker's events carry its shard id so merged
        # replays (obs.shard.aggregate_flight) attribute every edge
        from tpurpc.obs import shard as _shard

        sid = _shard.shard_id()
        buf = bytes(self._buf)  # one copy: decode from a stable image
        for off in range(0, len(buf), RECORD_BYTES):
            t_ns, code, tag, tid, a1, a2 = _REC.unpack_from(buf, off)
            if t_ns == 0 or code not in EVENT_NAMES or t_ns < since_ns:
                continue
            rec = {"t_ns": t_ns, "code": code,
                   "event": EVENT_NAMES[code], "tag": tag,
                   "entity": tag_name(tag), "tid": tid,
                   "a1": a1, "a2": a2}
            if sid >= 0:
                rec["shard"] = sid
            out.append(rec)
        out.sort(key=lambda d: d["t_ns"])
        if limit is not None and len(out) > limit:
            out = out[-limit:]
        return out

    def dump_text(self, since_ns: int = 0) -> str:
        """Human-readable replay (the SIGUSR2 / watchdog-trip rendering)."""
        events = self.snapshot(since_ns=since_ns)
        if not events:
            return "flight recorder: no events\n"
        t0 = events[0]["t_ns"]
        lines = [f"flight recorder: {len(events)} events "
                 f"(capacity {self.capacity})"]
        for e in events:
            lines.append(
                f"  +{(e['t_ns'] - t0) / 1e6:10.3f}ms "
                f"{e['event']:<22} {e['entity']:<20} "
                f"a1={e['a1']} a2={e['a2']} tid={e['tid']:#x}")
        return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Zero every slot (test isolation). Not synchronized against
        concurrent emitters — callers quiesce first. Resetting the
        process-wide recorder also clears the native shm ring: snapshot()
        merges both lanes into one timeline, so a reset that left the C
        half standing would hand every later caller a seed of stale
        native brackets."""
        for i in range(len(self._buf)):
            self._buf[i] = 0
        if globals().get("RECORDER") is self:
            try:
                from tpurpc.obs import native_obs as _nobs
                _nobs.reset()
            except Exception:
                pass  # the native plane must never break the Python one


#: the process-wide recorder; hot modules cache ``flight.emit`` (below)
RECORDER = FlightRecorder()

#: module-level emit — the one name instrumented sites call
#: (``_flight.emit(CODE, tag, a1, a2)``; the `flight` lint rule keys on it)
emit = RECORDER.emit


def _native_events(since_ns: int = 0) -> List[dict]:
    """tpurpc-xray: decode the C core's shm flight ring into event dicts
    (lane ``"native"``). Native tags are re-interned through THIS module's
    table so entity names resolve uniformly downstream (watchdog evidence,
    protocol replay, dump rendering); both planes stamp CLOCK_MONOTONIC,
    so in-process merge order is a plain sort on ``t_ns``."""
    try:
        from tpurpc.obs import native_obs as _nobs

        recs = _nobs.records()
        if not recs:
            return []
        tags = _nobs.tag_table()
    except Exception:
        return []  # the native plane must never break the Python one
    from tpurpc.obs import shard as _shard

    sid = _shard.shard_id()
    out: List[dict] = []
    for t_ns, code, tag, tid, a1, a2 in recs:
        if t_ns == 0 or code not in EVENT_NAMES or t_ns < since_ns:
            continue
        entity = tags[tag] if 0 <= tag < len(tags) else f"#{tag}"
        rec = {"t_ns": t_ns, "code": code, "event": EVENT_NAMES[code],
               "tag": tag_for(entity) if entity != "-" else 0,
               "entity": entity, "tid": tid, "a1": a1, "a2": a2,
               "lane": "native"}
        if sid >= 0:
            rec["shard"] = sid
        out.append(rec)
    return out


def snapshot(since_ns: int = 0, limit: Optional[int] = None) -> List[dict]:
    """The merged flight view: Python recorder + native shm ring, one
    monotonic timeline. When the native plane is off (or absent) this is
    byte-identical to the recorder's own snapshot — lane tags appear only
    once there are two lanes to tell apart."""
    out = RECORDER.snapshot(since_ns=since_ns)
    native = _native_events(since_ns=since_ns)
    if native:
        for e in out:
            e["lane"] = "py"
        out.extend(native)
        out.sort(key=lambda d: d["t_ns"])
    if limit is not None and len(out) > limit:
        out = out[-limit:]
    return out


def dump_text(since_ns: int = 0) -> str:
    """Human-readable replay of the MERGED timeline (the /debug/flight
    ?text=1 and SIGUSR2 rendering; single-lane output matches the
    recorder's own dump format exactly)."""
    events = snapshot(since_ns=since_ns)
    if not events:
        return "flight recorder: no events\n"
    t0 = events[0]["t_ns"]
    lines = [f"flight recorder: {len(events)} events "
             f"(capacity {RECORDER.capacity})"]
    for e in events:
        lane = e.get("lane")
        lines.append(
            f"  +{(e['t_ns'] - t0) / 1e6:10.3f}ms "
            f"{e['event']:<22} {e['entity']:<20} "
            f"a1={e['a1']} a2={e['a2']} tid={e['tid']:#x}"
            + (f" [{lane}]" if lane else ""))
    return "\n".join(lines) + "\n"


def postfork_restart() -> None:
    """Fresh ring in a forked shard worker: the inherited buffer holds the
    supervisor's pre-fork events, which would replay as this worker's
    history. Zeroing + a fresh slot counter keeps the module-level ``emit``
    binding (hot modules reference ``_flight.emit``) intact."""
    # tpurpc-xray: swap the C plane's inherited shm mapping for a fresh
    # per-worker region BEFORE RECORDER.reset() — reset() also clears the
    # native ring, and doing that while still attached to the inherited
    # mapping would wipe the parent's evidence.
    try:
        from tpurpc.obs import native_obs as _nobs

        _nobs.postfork_reset()
    except Exception:
        pass
    RECORDER.reset()
    RECORDER._slots = itertools.count()


# -- SIGUSR2 dump -------------------------------------------------------------

_sig_installed = False


def install_sigusr2() -> bool:
    """Dump the flight ring to stderr on SIGUSR2 (``kill -USR2 <pid>``).

    Best-effort: signal handlers only install from the main thread, and not
    every platform has SIGUSR2 — failure leaves the recorder fully usable
    via ``/debug/flight``. The previous handler is chained."""
    global _sig_installed
    if _sig_installed:
        return True
    import signal
    import sys

    if not hasattr(signal, "SIGUSR2"):
        return False
    try:
        prev = signal.getsignal(signal.SIGUSR2)

        def _dump(signum, frame):
            try:
                sys.stderr.write(RECORDER.dump_text())
                sys.stderr.flush()
            except Exception:
                pass
            if callable(prev) and prev not in (signal.SIG_IGN,
                                               signal.SIG_DFL):
                prev(signum, frame)

        signal.signal(signal.SIGUSR2, _dump)
    except (ValueError, OSError):  # non-main thread / exotic platform
        return False
    _sig_installed = True
    return True


install_sigusr2()


# -- at-exit dump for offline conformance (tpurpc-proof, ISSUE 12) ------------
#
# TPURPC_FLIGHT_DUMP=<dir> makes every process (smokes spawn several)
# write its flight ring as <dir>/flight-<pid>.json at interpreter exit —
# the input `python -m tpurpc.analysis protocol --flight <dir>` replays
# against the declared protocol machines (tools/check.sh wires the two
# together).

def _install_exit_dump() -> None:
    import atexit
    import json
    import os

    target = os.environ.get("TPURPC_FLIGHT_DUMP", "")
    if not target:
        return

    def _dump_at_exit():
        try:
            # the clock anchor (pid + bracketed mono/wall pair) lets the
            # offline checker rebase several per-process dumps of ONE run
            # onto the shared wall clock and check them as a MERGED
            # stream (`protocol --flight A --flight B`, ISSUE 17)
            from tpurpc.obs import tracing as _tracing

            # the MERGED timeline (tpurpc-xray): C-plane rdv/ctrl/conn
            # edges ride the same dump and replay through the same
            # protocol machines as the Python lane's
            doc = {"events": snapshot(),
                   "clock_anchor": _tracing.clock_anchor()}
            os.makedirs(target, exist_ok=True)
            path = os.path.join(target, f"flight-{os.getpid()}.json")
            with open(path, "w", encoding="utf-8") as f:
                json.dump(doc, f)
        except Exception:
            pass  # a failed postmortem dump must not fail the exit

    atexit.register(_dump_at_exit)


_install_exit_dump()


def _install_env_verifier() -> None:
    import os

    if os.environ.get("TPURPC_VERIFY_PROTOCOL", "") != "1":
        return
    try:
        # flight's module object is already in sys.modules (constants all
        # defined above), so protocol's import of it resolves cleanly
        from tpurpc.analysis import protocol as _protocol

        _protocol.install_live()
    except Exception:
        # import-order cycle: something imported analysis.protocol first
        # and THAT import pulled us in — protocol's own module bottom
        # installs the verifier once it finishes initializing
        pass


_install_env_verifier()
