"""tpurpc-lens byte-flow waterfall: per-hop byte/nanosecond attribution.

ROADMAP item 2's question is "streaming runs at 1.72 GB/s against an
8.5 GB/s memcpy ceiling — WHICH hop eats the gap?", and nothing in the
telemetry stack could answer it: the registry counts bytes per subsystem
and the copy ledger counts bytes per mechanism, but neither says how much
*time* each hop of the streaming path spent moving those bytes. The
waterfall is that instrument: every hop of the data path carries a pair of
always-on registry counters — bytes moved and busy nanoseconds — and the
scrape-time division ``bytes / busy_ns`` is that hop's effective GB/s
(B/ns ≡ GB/s, no unit conversion). The hop with the lowest effective rate
under load is, by construction, the one to attack.

The hop chain, in data-flow order (the ISSUE 8 vocabulary)::

    device     serialize: tensor bytes gathered host-side into wire form
               (jaxshim/codec.py encode — the device→host leg)
    send_ring  RingWriter placement into the peer's receive ring
               (core/ring.py writev/write_many + the fused native send)
    wire       bytes crossing the transport boundary: the pair-plane
               one-sided send (core/pair.py Pair.send, credit machinery
               included) and TCP socket writes (core/endpoint.py)
    peer_ring  RingReader drain out of the local receive ring
               (core/ring.py read_into/drain_into/read_many)
    decode     codec parse of wire bytes back into tensors
               (jaxshim/codec.py decode_tree_at, tpu/endpoint.py
               decode_tree_to_ring)
    hbm        placement into the device-resident landing ring
               (tpu/hbm_ring.py place/place_many)
    jax_array  materialization as jax.Array — dlpack alias or the
               device_put staging copy (jaxshim/codec.py to_jax)

Cost model — why this is ALWAYS on, like the rest of the obs stack:

* accounting sites run once per **batched operation** (a drain, a gathered
  writev, a tree decode), never per byte: two ``time.monotonic_ns`` reads
  and two/three GIL-atomic Counter bumps per op;
* the counters are plain registry Counters, cached as module globals at
  import by every instrumented module (the ``stage`` lint rule enforces
  the pure-int plumbing contract at each site, exactly as the ``flight``
  rule does for the recorder);
* hops may NEST (``wire`` wraps ``send_ring`` on the pair plane;
  ``decode`` wraps ``jax_array``): the table is a waterfall of per-hop
  effective rates, not a disjoint partition of wall time. The invariant
  that matters holds regardless: every hop's effective GB/s is an upper
  bound on the end-to-end rate through it, so the MINIMUM names the
  bottleneck.

The copy ledger is folded in: each hop row carries ``copy_bytes`` (bytes
that hop moved via a host memcpy / staging copy) so the table shows copies
alongside throughput — a hop running fast *because* it aliases reads
differently from one running fast while copying.

Served at ``GET /debug/waterfall`` (``?text=1`` for the table rendering,
``?local=1`` per-shard), merged across shard workers by the PR 7 fan-out,
rendered live by ``python -m tpurpc.tools.top``, and recorded into the
bench artifact (``waterfall_gbps_by_hop`` + ``waterfall_slowest_hop``).

``TPURPC_LENS=0`` switches the lens plane off (the sampling profiler stops
and the scrape routes answer 404-style disabled docs); the hop counters
themselves are branch-free and stay live — they are the same class of
always-on accounting as ``ring_bytes_read``.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from tpurpc.obs import metrics as _metrics

__all__ = [
    "HOPS", "HOP_NAMES", "hop_counters", "enabled", "waterfall",
    "render_text", "slowest_hop",
]

#: the declared hop registry, in data-flow order: (name, accounting site /
#: what the hop means). Append-only — names land in scrape output and
#: bench artifacts.
HOPS: Tuple[Tuple[str, str], ...] = (
    ("device", "serialize: tensor bytes gathered into wire form "
               "(codec encode, the device→host leg)"),
    ("send_ring", "RingWriter placement into the peer's receive ring"),
    ("wire", "transport boundary: pair one-sided send / TCP socket write"),
    ("rendezvous", "one-sided bulk payload write into the peer-advertised "
                   "landing region (tpurpc-express)"),
    ("ctrl", "control-plane work: descriptor-ring posts/drains and framed "
             "rendezvous control sends (tpurpc-pulse)"),
    ("native_send", "native-plane rdv placement: the one-sided memcpy "
                    "into the peer-advertised landing region (tpr_rdv.cc)"),
    ("native_recv", "native-plane delivery: completed landing regions "
                    "handed to the stream layer (tpr_rdv.cc deliver)"),
    ("native_rdv", "native-plane claim wait: solicited offer -> claim "
                   "grant round trip (tpr_rdv.cc rdv_claim)"),
    ("peer_ring", "RingReader drain out of the local receive ring"),
    ("decode", "codec parse of wire bytes back into tensors"),
    ("hbm", "placement into the device-resident HBM landing ring"),
    ("jax_array", "materialization as jax.Array (dlpack alias or "
                  "device_put staging)"),
)

HOP_NAMES: Tuple[str, ...] = tuple(name for name, _ in HOPS)

_BYTES: Dict[str, _metrics.Counter] = {}
_NS: Dict[str, _metrics.Counter] = {}
_COPY: Dict[str, _metrics.Counter] = {}
for _name, _desc in HOPS:
    _BYTES[_name] = _metrics.counter(f"lens_{_name}_bytes")
    _NS[_name] = _metrics.counter(f"lens_{_name}_busy_ns")
    _COPY[_name] = _metrics.counter(f"lens_{_name}_copy_bytes")


def hop_counters(name: str) -> Tuple[_metrics.Counter, _metrics.Counter,
                                     _metrics.Counter]:
    """The ``(bytes, busy_ns, copy_bytes)`` counter triple for one declared
    hop. Instrumented modules call this ONCE at import (module-level, a
    string-constant hop name — the ``stage`` lint rule checks both) and
    cache the counters as globals; the per-op cost is then the bumps alone.
    """
    if name not in _BYTES:
        raise ValueError(f"unknown waterfall hop {name!r}; "
                         f"declared hops: {HOP_NAMES}")
    return _BYTES[name], _NS[name], _COPY[name]


def enabled() -> bool:
    """The lens master switch (``TPURPC_LENS=0`` off). Gates the sampling
    profiler and the scrape routes; the hop counters are branch-free
    always-on accounting and ignore it."""
    from tpurpc.utils.config import _env

    return (_env("TPURPC_LENS") or "1").lower() not in ("0", "off", "false")


# -- scrape-time export -------------------------------------------------------

def waterfall() -> dict:
    """The per-hop effective-throughput table, sampled from the counters at
    call time. ``gbps`` is ``bytes / busy_ns`` (identical units); a hop
    that has seen no traffic reports zeros and is excluded from the
    bottleneck argmin."""
    # tpurpc-xray: pull the C core's byte/busy_ns table into the native
    # hops first, so slowest_hop judges the PRODUCTION plane too
    try:
        from tpurpc.obs import native_obs as _nobs

        _nobs.sync_registry()
    except Exception:
        pass
    rows: List[dict] = []
    for name, desc in HOPS:
        b = _BYTES[name].snapshot()
        ns = _NS[name].snapshot()
        cp = _COPY[name].snapshot()
        rows.append({
            "hop": name,
            "bytes": b,
            "busy_ms": round(ns / 1e6, 3),
            "gbps": round(b / ns, 3) if ns else 0.0,
            "copy_bytes": cp,
            "what": desc,
        })
    out = {"hops": rows, "slowest_hop": slowest_hop(rows)}
    try:
        from tpurpc.tpu import ledger

        out["ledger"] = ledger.snapshot()
    except Exception:
        pass
    return out


def slowest_hop(rows: Optional[List[dict]] = None) -> Optional[str]:
    """The bottleneck hop: lowest effective GB/s among hops that actually
    moved bytes (and spent time doing it). None before any traffic.

    Hops that carried under 1% of the busiest hop's bytes are excluded:
    once the rendezvous plane carries the bulk payloads, the framed ``wire``
    hop sees only control frames — a few KB at small-message rates — and a
    control-only hop's low GB/s is not an upper bound on the BULK flow, so
    naming it the bottleneck would be the instrument lying."""
    if rows is None:
        rows = waterfall()["hops"]
    live = [r for r in rows if r["bytes"] > 0 and r["busy_ms"] > 0]
    if not live:
        return None
    bar = max(r["bytes"] for r in live) * 0.01
    bulk = [r for r in live if r["bytes"] >= bar]
    return min(bulk or live, key=lambda r: r["gbps"])["hop"]


def render_text(doc: Optional[dict] = None) -> str:
    """Human rendering of the waterfall (``?text=1`` / tools.top pane)."""
    doc = doc if doc is not None else waterfall()
    rows = doc["hops"]
    lines = [f"{'hop':<10} {'GB/s':>8} {'MiB':>10} {'busy_ms':>10} "
             f"{'copy_MiB':>9}  what"]
    lines.append("-" * len(lines[0]))
    for r in rows:
        mark = " <-- slowest" if r["hop"] == doc.get("slowest_hop") else ""
        lines.append(
            f"{r['hop']:<10} {r['gbps']:>8.3f} "
            f"{r['bytes'] / (1 << 20):>10.1f} {r['busy_ms']:>10.1f} "
            f"{r['copy_bytes'] / (1 << 20):>9.1f}  {r['what'][:46]}{mark}")
    if doc.get("slowest_hop") is None:
        lines.append("(no traffic yet: every hop idle)")
    return "\n".join(lines) + "\n"
