"""Device-path serialization: wire bytes ↔ jax.Array with ledger accounting.

The BASELINE north star names two helpers:

* ``SerializeFromDevice`` — tensor payloads leave device memory and enter the
  send ring without host *staging*: exactly one d2h movement (none on a host
  backend, where the array memory is already host-addressable and the wire
  segments alias it), then the ring/endpoint gather-write places the same
  buffer. No intermediate host buffer is ever allocated.
* ``DeserializeToDevice`` — received wire bytes become a ``jax.Array`` with
  exactly one h2d movement (none on a host backend: dlpack import aliases the
  assembly buffer).

Both report to :mod:`tpurpc.tpu.ledger`; tests assert the copy counts, which
is the honesty mechanism SURVEY.md §7 stage 6 demands of the emulated path.
"""

from __future__ import annotations

from typing import Any, List

import numpy as np

from tpurpc.jaxshim import codec
from tpurpc.tpu import ledger


def _on_host_backend(arr) -> bool:
    try:
        return all(d.platform == "cpu" for d in arr.devices())
    except Exception:
        return False


def serialize_from_device(x) -> List[bytes]:
    """Wire segments for a jax.Array/numpy without host staging.

    Returns the codec's gather list; the payload segment aliases the d2h
    landing buffer (or the array itself on host backends) — downstream gather
    writes (ring slice-send / sendmsg) consume it in place.
    """
    import jax

    if isinstance(x, jax.Array) and not _on_host_backend(x):
        ledger.dma_d2h(x.nbytes)       # the one unavoidable device→host DMA
        host = np.asarray(x)
        ledger.zero_copy(host.nbytes)  # segments alias the DMA landing buffer
        return codec.encode_tensor(host)
    host = np.asarray(x)
    ledger.zero_copy(host.nbytes)
    return codec.encode_tensor(host)


def deserialize_to_device(buf, offset: int = 0):
    """Wire record → jax.Array with ledger accounting; returns (array, end)."""
    import jax

    arr, end = codec.decode_tensor(buf, offset)  # zero-copy view of buf
    out = codec.to_jax(arr)
    if _on_host_backend(out):
        ledger.zero_copy(arr.nbytes)   # dlpack alias, no movement
    else:
        ledger.dma_h2d(arr.nbytes)     # one host→HBM DMA, no host memcpy
    return out, end


def tree_from_device(tree: Any) -> List[bytes]:
    """Pytree variant of :func:`serialize_from_device` (gather segments)."""
    import jax

    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array) and not _on_host_backend(leaf):
            ledger.dma_d2h(leaf.nbytes)
        else:
            ledger.zero_copy(getattr(leaf, "nbytes", 0))
    return codec.encode_tree(tree)


# ---------------------------------------------------------------------------
# SerializeFromDevice → rendezvous region / send ring (tpurpc-express, ISSUE 9)
# ---------------------------------------------------------------------------

def serialize_into(x, write, offset: int = 0) -> int:
    """``SerializeFromDevice`` finished end-to-end: gather-serialize one
    array STRAIGHT into a rendezvous landing window (or any one-sided
    write target) with zero host staging — each codec segment (header,
    payload view aliasing the d2h landing buffer or the array itself)
    lands via ``write(offset, segment)``; no intermediate host buffer is
    ever allocated or joined. ``write`` must be a one-sided placement
    (a :class:`~tpurpc.core.pair.Window` write / rendezvous region); the
    movement is billed as ``rdma_write``, and the copy ledger proves the
    zero-staging claim: exactly one ``dma_d2h`` on device backends (zero on
    host backends, where the segments alias the array) and zero
    ``host_copy``. Returns bytes written past ``offset``."""
    segs = serialize_from_device(x)
    return _write_segments(segs, write, offset)


def serialize_tree_into(tree: Any, write, offset: int = 0) -> int:
    """Pytree variant of :func:`serialize_into` — the outbound half the
    multi-host activation transport (ROADMAP item 5) consumes: device
    activations leave HBM and land in the peer's advertised region with
    no host staging buffer in between."""
    segs = tree_from_device(tree)
    return _write_segments(segs, write, offset)


def _write_segments(segs: List[bytes], write, offset: int) -> int:
    total = 0
    for seg in segs:
        view = memoryview(seg).cast("B")
        write(offset + total, view)
        total += len(view)
    ledger.rdma_write(total)
    return total
