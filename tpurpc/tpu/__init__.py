"""TPU-side data plane: copy ledger, device serialization, HBM receive ring.

The BASELINE.json north star re-homed: receive rings in device memory,
payloads surfaced as zero-copy ``jax.Array``s, outbound tensors serialized
from device without host staging — with every remaining copy measured by
:mod:`tpurpc.tpu.ledger` so the emulated path can't overclaim.

``serialize`` is loaded lazily: it pulls in the jaxshim codec, which itself
ships bytes through the rpc layer that reports into the ledger — eager
import here would close that cycle.
"""

from tpurpc.tpu import ledger
from tpurpc.tpu.hbm_ring import HbmLease, HbmRing

__all__ = ["ledger", "HbmLease", "HbmRing", "deserialize_to_device",
           "serialize_from_device", "tree_from_device", "TpuRingEndpoint",
           "DeviceMessage", "decode_tensor_to_ring", "decode_tree_to_ring"]

#: endpoint module exports, loaded lazily (they import the rpc/endpoint stack)
_ENDPOINT_NAMES = ("TpuRingEndpoint", "DeviceMessage", "decode_tensor_to_ring",
                   "decode_tree_to_ring")


def __getattr__(name):
    if name in ("deserialize_to_device", "serialize_from_device",
                "tree_from_device"):
        from tpurpc.tpu import serialize

        return getattr(serialize, name)
    if name in _ENDPOINT_NAMES:
        from tpurpc.tpu import endpoint

        return getattr(endpoint, name)
    raise AttributeError(f"module 'tpurpc.tpu' has no attribute {name!r}")
