"""Device-resident receive ring: tensor payloads live in TPU HBM, consumers
get device views — the emulated form of the BASELINE north star.

Real hardware path (not reachable in this environment): the NIC DMAs into a
dmabuf-exported HBM ring, head/footer words stay host-visible, and ``Recv``
returns device buffer handles. This module emulates the *architecture* with
XLA-visible pieces so the protocol, lease discipline, and copy ledger are
real even though the placement is a ``device_put``:

* ``place`` — one h2d movement per payload (ledger: dma_h2d) followed by the
  donated-buffer ``dynamic_update_slice`` that lands it in the ring. The
  in-ring landing write moves the payload a second time ON DEVICE, and the
  ledger records it as ``dma_d2d`` — two honest entries for two real
  movements (on NIC hardware the DMA writes the ring directly and both
  entries collapse into the NIC's single placement write).
* ``view`` — for aligned, unwrapped spans on the emulated (CPU-backed)
  platform: a **dlpack alias** of the ring bytes themselves — a
  ``jax.Array`` whose buffer pointer is ``ring_base + offset``, zero bytes
  moved, ledger ``zero_copy`` (round-4 chipcheck proved the seam:
  ``dlpack_ptr_same: true``; round 5 makes the receive path use it).
  Aliasing is **verified per view** by pointer comparison — an import the
  backend chose to copy (misaligned span, exotic dtype) is recorded as
  ``dma_d2d``, honestly. Wrapped spans and real-TPU backends use
  ``dynamic_slice`` (+ bitcast): a device copy, recorded as ``dma_d2d``
  (on real hardware the aliasing seam is the dmabuf export, out of this
  environment's reach). Payload bytes never touch the host either way.

  The alias relies on one invariant the real hardware has by construction
  (a pinned ring is never reallocated): XLA's donation must keep the ring
  allocation at the same address across ``place`` updates. ``place``
  asserts this after every rebind and refuses to continue (loud
  RuntimeError, not silent corruption) if the allocation ever moved while
  aliased leases were outstanding.
* lease/credit — a message's span stays pinned until every handle is
  released; only then does the head advance (SURVEY.md §7 hard-part #4: a
  ``jax.Array`` aliasing ring memory must gate credit return).

Thread model: ``self.buf`` is rebound by donating jits in ``place`` while
``view`` slices it — both run under ``self._lock`` for their whole device
op, because a donated buffer is DELETED the moment the update launches and a
concurrent slice of the old binding would fault (advisor r1 finding). The
lock spans an XLA dispatch, which is acceptable for the emulation: one ring
has one producer (the receive path) and its consumers.

Capacity is a power of two; offsets are monotonic 64-bit counters — the same
invariants as the host ring (tpurpc/core/ring.py), so the flow-control math
is shared by inspection.

Reference analog: the creation path ``rdma_bp_posix.cc:706-796`` (pool take →
init → bootstrap → poller) and the receive drain ``ring_buffer.cc:122-191``;
here the drain's landing target is device memory.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

from tpurpc.obs import lens as _lens
from tpurpc.obs import metrics as _metrics
from tpurpc.obs import profiler as _profiler
from tpurpc.tpu import ledger

# tpurpc-scope (ISSUE 4): device-ring placement totals + scrape-time
# occupancy over live HBM rings (one counter bump per placement BATCH; the
# per-byte movement accounting stays the copy ledger's job)
_HBM_PLACE_MSGS = _metrics.counter("hbm_place_msgs")
_HBM_PLACE_BYTES = _metrics.counter("hbm_place_bytes")
_HBM_RINGS = _metrics.fleet("hbm_ring_occupancy_bytes",
                            lambda r: r.tail - r.head)

# tpurpc-lens (ISSUE 8): the `hbm` waterfall hop — bytes landed in the
# device ring and the nanoseconds the placement dispatch took, one bump
# set per place/place_many call. The emulated placement stages host→device
# (dma_h2d), so every placed byte is also a copy byte here.
_LENS_HBM_BYTES, _LENS_HBM_NS, _LENS_HBM_COPY = _lens.hop_counters("hbm")

_LENS_STAGES = {
    "place": "hbm-place",
    "place_many": "hbm-place",
    "_pallas_place": "hbm-place",
    "view": "device-dispatch",
}
_profiler.register_stages(__file__, _LENS_STAGES)


class HbmRing:
    """Byte ring in device memory with host-tracked head/tail + leases."""

    def __init__(self, capacity: int, device=None):
        import jax
        import jax.numpy as jnp

        if capacity < 64 or capacity & (capacity - 1):
            raise ValueError("capacity must be a power of two >= 64")
        self.capacity = capacity
        self._mask = capacity - 1
        if device is None:
            device = jax.devices()[0]
        self.device = device
        self.buf = jax.device_put(jnp.zeros((capacity,), jnp.uint8), device)
        self.tail = 0   # absolute bytes ever placed
        self.head = 0   # absolute bytes ever freed
        self._lock = threading.Lock()
        #: signaled whenever the head advances (space became writable)
        self._space = threading.Condition(self._lock)
        #: span -> [outstanding leases, ever_released] — a span frees only
        #: after at least one lease was taken AND all were released, so a
        #: placed-but-unconsumed message can never be reclaimed under it
        self._live: Dict[Tuple[int, int], list] = {}
        #: outstanding leases whose array ALIASES ring memory (dlpack views):
        #: while > 0, the allocation-stability assert in place() is fatal
        self._aliased = 0
        _HBM_RINGS.track(self)
        #: ring base address (unsafe_buffer_pointer), or None where the
        #: backend doesn't expose one — the dlpack view path needs it both
        #: to build the alias and to verify stability across donations
        self._base_ptr = self._ptr_of(self.buf)

        def _update(buf, payload, start):
            import jax.lax as lax
            return lax.dynamic_update_slice(buf, payload, (start,))

        self._update = jax.jit(_update, donate_argnums=0)

        def _slice(buf, start, n):
            import jax.lax as lax
            return lax.dynamic_slice(buf, (start,), (n,))

        # n is static per shape; jit caches per payload size
        self._slice = jax.jit(_slice, static_argnums=2)

    @staticmethod
    def _ptr_of(arr) -> Optional[int]:
        """Device buffer address of a jax.Array, or None (backend-
        dependent introspection; every consumer tolerates None)."""
        try:
            return arr.addressable_shards[0].data.unsafe_buffer_pointer()
        except Exception:
            return None

    def _dlpack_view(self, p: int, n: int, dt, shape):
        """Aliasing ``jax.Array`` of ring bytes ``[p, p+n)`` — the round-5
        zero-copy receive path (VERDICT r4 next #3). Returns ``(array,
        is_alias)`` or None to use the slice chain.

        Builds a numpy view over the raw span (``ctypes.from_address`` on
        the ring base — free), applies dtype/shape numpy-side (views,
        free), and imports via dlpack. On the CPU-backed emulated platform
        XLA adopts the buffer in place; ``is_alias`` is PROVEN by pointer
        equality, never assumed — a copying import (misaligned offset) is
        still a correct result, just billed as ``dma_d2d``.

        Lifetime: the jax.Array holds the dlpack capsule → the numpy view
        → nothing (the raw span has no owner). The ring allocation is the
        owner, kept alive by ``self.buf`` (the lease holds the ring) and
        kept *in place* by the donation-stability assert in ``place``.
        Consumers must not donate a leased array into a jit — that would
        hand XLA a write alias into ring memory (same contract as the
        reference's borrowed ring slices, ``ring_buffer.cc:122-191``)."""
        import os

        if (getattr(self, "_dlpack_broken", False)
                or self.device.platform != "cpu"
                or self._base_ptr is None
                or os.environ.get("TPURPC_DLPACK_VIEW", "1") == "0"):
            return None
        import ctypes

        import jax.numpy as jnp

        # Order the alias read after every pending placement: the raw-pointer
        # view below has NO dataflow dependency on the donated
        # dynamic_update_slice that landed the span, and under JAX async
        # dispatch (default-on for CPU) a consumer could otherwise read the
        # span before place()'s update executed — stale tensor bytes on the
        # zero-copy path (ADVICE r5, medium). Real hardware gets this
        # ordering from the NIC's completion; the emulation must ask for it.
        self.buf.block_until_ready()
        try:
            raw = (ctypes.c_uint8 * n).from_address(self._base_ptr + p)
            npv = np.ctypeslib.as_array(raw)
            npdt = np.dtype(dt)
            if npdt != np.uint8:
                npv = npv.view(npdt)  # numpy view: free; bf16 et al raise
            npv = npv.reshape(shape if shape is not None else (-1,))
            arr = jnp.from_dlpack(npv)  # raises for dlpack-unsupported dt
        except Exception:
            return None  # per-span/per-dtype failure: slice chain is law
        if arr.devices() != {self.device}:
            # from_dlpack landed the alias on a different jax device than
            # the ring's (virtual multi-device mesh): consumers would trip
            # cross-device errors. Latch off — this is a property of the
            # ring's device, not of one span.
            self._dlpack_broken = True
            return None
        return arr, self._ptr_of(arr) == self._base_ptr + p

    def _pallas_ok(self, p: int, n: int, min_capacity: int,
                   broken_attr: str) -> bool:
        """Shared eligibility guard for the place/view kernels: first-failure
        latch, 4-byte alignment, capacity floor, validated platforms, env
        opt-out (``TPURPC_PALLAS=0``)."""
        import os

        return not (getattr(self, broken_attr, False)
                    or p % 4 or n % 4 or self.capacity < min_capacity
                    or self.device.platform not in ("cpu", "tpu")
                    or os.environ.get("TPURPC_PALLAS", "1") == "0")

    def _pallas_place(self, dev_payload, p: int, n: int) -> bool:
        """Land ``dev_payload`` at physical offset ``p`` via the aliased
        ring_scatter kernel (tpurpc.ops.ring_scatter) — ONE landing write
        per placement, wrapped or not (the kernel's wrap window is
        conditional, so the unwrapped span is the same single aliased
        dispatch; the reference's placement is always one RDMA WRITE,
        ``pair.cc:587-622``). Returns False to use the jax-op chain."""
        if not self._pallas_ok(p, n, 2 * 9 * 512, "_pallas_place_broken"):
            return False
        on_cpu = self.device.platform == "cpu"
        try:
            from tpurpc.ops.ring_scatter import ring_scatter

            self.buf = ring_scatter(self.buf, dev_payload, p,
                                    interpret=on_cpu)
            return True
        except Exception as exc:
            # ring_scatter DONATES the ring. A compile-time failure (the
            # usual Mosaic/tunnel mode) raises before launch, so the buffer
            # is intact and falling back is safe. A post-launch runtime
            # failure consumed the donation — the old contents are gone and
            # "fallback" would update a deleted array after tail/_live were
            # advanced: surface the corruption honestly instead.
            if getattr(self.buf, "is_deleted", lambda: False)():
                raise
            self._pallas_place_broken = True
            import warnings

            warnings.warn(f"pallas ring_scatter disabled after failure: {exc}")
            return False

    def _pallas_window(self, p: int, n: int):
        """Fused wrapped-window gather (tpurpc.ops.ring_window), or None to
        use the jax-op chain. The kernel is validated on real TPU hardware
        (v5e) and in interpret mode (CPU, where the suite runs it on every
        wrapped view) — on by default, ``TPURPC_PALLAS=0`` opts out."""
        if not self._pallas_ok(p, n, 9 * 512, "_pallas_broken"):
            return None  # ineligible, or failed once (don't re-pay per view)
        on_cpu = self.device.platform == "cpu"
        try:
            from tpurpc.ops import ring_window

            return ring_window(self.buf, p, n, interpret=on_cpu)
        except Exception as exc:
            # kernel trouble: the slice+concat chain is law. Remember and
            # warn ONCE — retracing a failing kernel on every wrapped view
            # (under self._lock, on the consume hot path) is not acceptable.
            self._pallas_broken = True
            import warnings

            warnings.warn(f"pallas ring_window disabled after failure: {exc}")
            return None

    # -- producer ------------------------------------------------------------

    def writable(self) -> int:
        return self.capacity - (self.tail - self.head)

    def place(self, payload, timeout: Optional[float] = None) -> Tuple[int, int]:
        """DMA one payload into the ring; returns its (offset, nbytes) span.

        Emulates the NIC's placement write: one h2d movement plus the in-ring
        landing write (dma_d2d); zero host memcpy (the payload view is
        consumed in place).

        Blocks up to ``timeout`` seconds for lease releases to free space
        (credit-based flow control, ``pair.cc:276-284`` analog); with
        ``timeout=None`` a full ring raises :class:`BufferError` immediately.
        A payload larger than the whole ring always raises.
        """
        import jax

        src = np.frombuffer(payload, np.uint8) if not isinstance(
            payload, np.ndarray) else payload.reshape(-1).view(np.uint8)
        n = src.nbytes
        if n == 0:
            # Zero-size spans never enter _live: they'd all share the key
            # (tail, 0) and corrupt each other's lease counts. An empty
            # payload needs no ring bytes and no credit.
            return self.tail, 0
        if n > self.capacity:
            raise BufferError(f"payload {n} exceeds ring capacity {self.capacity}")
        t0 = time.monotonic_ns()
        with self._lock:
            if n > self.writable() and timeout is not None:
                import time as _time
                deadline = _time.monotonic() + timeout
                while n > self.writable():
                    remain = deadline - _time.monotonic()
                    if remain <= 0 or not self._space.wait(timeout=remain):
                        break
            if n > self.writable():
                raise BufferError(f"HBM ring full: {n} > {self.writable()}")
            off = self.tail
            self.tail += n
            self._live[(off, n)] = [0, False]
            p = off & self._mask
            dev = jax.device_put(jax.numpy.asarray(src), self.device)
            ledger.dma_h2d(n)
            first = min(n, self.capacity - p)
            # Single-landing-write invariant (VERDICT r3 next#6, assertable
            # via the ledger's op counts): every placement is exactly ONE
            # in-ring write — the unwrapped case as one donated
            # dynamic_update_slice, the wrapped case through the aliased
            # ring_scatter kernel (two donated updates only when the kernel
            # is ineligible, and then the ledger says so honestly). The
            # h2d transfer stays a separate movement: XLA cannot land a
            # host transfer at an offset of an existing device buffer
            # (chipcheck's aliasing verdict) — a real NIC-DMA'd ring would
            # fuse them, which is exactly what the dlpack import seam is
            # reserved for.
            if first >= n:  # unwrapped: already a single landing write
                # Donating update: rebinding self.buf under the lock —
                # view() must never slice a just-donated (deleted) binding.
                self.buf = self._update(self.buf, dev, p)
                ledger.dma_d2d(n)
            elif self._pallas_place(dev, p, n):
                ledger.dma_d2d(n)  # one aliased kernel write across the wrap
            else:
                self.buf = self._update(self.buf, dev[:first], p)
                ledger.dma_d2d(first)
                self.buf = self._update(self.buf, dev[first:], 0)
                ledger.dma_d2d(n - first)
            self._assert_stable()
        dt = time.monotonic_ns() - t0
        _HBM_PLACE_MSGS.inc()
        _HBM_PLACE_BYTES.inc(n)
        _LENS_HBM_BYTES.inc(n)
        _LENS_HBM_NS.inc(dt)
        _LENS_HBM_COPY.inc(n)
        return off, n

    def place_many(self, payloads,
                   timeout: Optional[float] = None) -> "list[Tuple[int, int]]":
        """DMA a BATCH of payloads into the ring with ONE landing dispatch.

        The payloads pack host-side into one contiguous image (one pass), move
        with one h2d, and land with a single donated ``dynamic_update_slice``
        (or one aliased ring_scatter kernel across the wrap) — one XLA
        dispatch per *batch* instead of per tensor, the device half of the
        batched receive pipeline.  Returns the per-payload ``(offset,
        nbytes)`` spans, each leased/credited independently exactly as if
        placed by :meth:`place` back to back.

        Flow control matches :meth:`place` with the batch treated as one
        unit: blocks up to ``timeout`` for the TOTAL to fit; a batch larger
        than the whole ring raises."""
        import jax

        srcs = [np.frombuffer(p, np.uint8) if not isinstance(p, np.ndarray)
                else p.reshape(-1).view(np.uint8) for p in payloads]
        lens = [s.nbytes for s in srcs]
        total = sum(lens)
        if total == 0:
            return [(self.tail, 0) for _ in srcs]
        if total > self.capacity:
            raise BufferError(
                f"batch of {total} bytes exceeds ring capacity {self.capacity}")
        t0 = time.monotonic_ns()
        with self._lock:
            if total > self.writable() and timeout is not None:
                import time as _time
                deadline = _time.monotonic() + timeout
                while total > self.writable():
                    remain = deadline - _time.monotonic()
                    if remain <= 0 or not self._space.wait(timeout=remain):
                        break
            if total > self.writable():
                raise BufferError(
                    f"HBM ring full: {total} > {self.writable()}")
            off = self.tail
            self.tail += total
            spans = []
            for n in lens:
                if n:  # zero-size spans hold no credit (see place())
                    self._live[(off, n)] = [0, False]
                spans.append((off, n))
                off += n
            packed = np.concatenate(srcs) if len(srcs) > 1 else srcs[0]
            p = spans[0][0] & self._mask
            dev = jax.device_put(jax.numpy.asarray(packed), self.device)
            ledger.dma_h2d(total)
            first = min(total, self.capacity - p)
            if first >= total:  # unwrapped: one donated landing write
                self.buf = self._update(self.buf, dev, p)
                ledger.dma_d2d(total)
            elif self._pallas_place(dev, p, total):
                ledger.dma_d2d(total)  # one aliased kernel write at the wrap
            else:
                self.buf = self._update(self.buf, dev[:first], p)
                ledger.dma_d2d(first)
                self.buf = self._update(self.buf, dev[first:], 0)
                ledger.dma_d2d(total - first)
            self._assert_stable()
        dt = time.monotonic_ns() - t0
        _HBM_PLACE_MSGS.inc(len(spans))
        _HBM_PLACE_BYTES.inc(total)
        _LENS_HBM_BYTES.inc(total)
        _LENS_HBM_NS.inc(dt)
        _LENS_HBM_COPY.inc(total)
        return spans

    def _assert_stable(self) -> None:
        """Donation-stability invariant behind the dlpack aliases (called
        under the lock after every ``self.buf`` rebind): real hardware pins
        the ring for the NIC, so a moved allocation is an emulation-breaking
        event — fatal while aliased leases exist (their pointers now dangle),
        a silent re-base when none do."""
        if self._base_ptr is None:
            return
        now = self._ptr_of(self.buf)
        if now == self._base_ptr:
            return
        if self._aliased:
            raise RuntimeError(
                f"HBM ring allocation moved ({self._base_ptr:#x} -> "
                f"{now and hex(now)}) with {self._aliased} aliased lease(s) "
                "outstanding — XLA stopped reusing the donated ring buffer; "
                "set TPURPC_DLPACK_VIEW=0 on this backend")
        self._base_ptr = now

    # -- consumer ------------------------------------------------------------

    def view(self, off: int, n: int, dtype=np.uint8,
             shape: Optional[tuple] = None) -> "HbmLease":
        """Device view of a placed span; pins it until the lease is released.

        Unwrapped spans on the CPU-backed platform come back as dlpack
        ALIASES of ring memory (ledger: zero_copy, pointer-verified);
        everything else is a device-side materialization (dma_d2d). Payload
        bytes never return to the host either way, and the ledger records
        which of the two actually happened for every message.
        """
        import jax.numpy as jnp
        from jax import lax

        if n == 0:
            dt = jnp.dtype(dtype)
            empty = jnp.zeros((0,), dt).reshape(shape if shape is not None
                                                else (0,))
            return HbmLease(self, off, 0, empty)
        with self._lock:
            if (off, n) not in self._live:
                raise KeyError(f"span ({off}, {n}) not live")
            self._live[(off, n)][0] += 1
            # Everything between the count increment and the HbmLease
            # hand-off must UNDO the increment on failure, or a poison
            # view request (bad dtype/shape vs nbytes — wire-reachable
            # through decode_tensor_to_ring's header) pins the span's
            # credit forever with no lease anyone could release.
            try:
                p = off & self._mask
                first = min(n, self.capacity - p)
                if first >= n:  # unwrapped: the zero-copy aliasing path
                    got = self._dlpack_view(p, n, dtype, shape)
                    if got is not None:
                        seg, is_alias = got
                        if is_alias:
                            self._aliased += 1
                            ledger.zero_copy(n)
                        else:  # backend copied on import: correct + billed
                            ledger.dma_d2d(n)
                        return HbmLease(self, off, n, seg, aliased=is_alias)
                seg = None
                if first < n:  # wrapped span: try the fused Pallas gather —
                    # ONE kernel/d2d pass instead of slice+slice+concatenate
                    seg = self._pallas_window(p, n)
                if seg is None:
                    seg = self._slice(self.buf, p, first)
                    if first < n:
                        seg = jnp.concatenate(
                            [seg, self._slice(self.buf, 0, n - first)])
            except BaseException:
                self._live[(off, n)][0] -= 1
                self._advance_locked()  # cnt may now be 0 on a consumed span
                raise
        try:
            dt = jnp.dtype(dtype)
            if dt != jnp.uint8:
                seg = lax.bitcast_convert_type(
                    seg.reshape(-1, dt.itemsize), dt).reshape(-1)
            if shape is not None:
                seg = seg.reshape(shape)
        except BaseException:
            # failed shaping does NOT consume the span (another consumer may
            # still take a correct view of it)
            self._release(off, n, consumed=False)
            raise
        ledger.dma_d2d(n)  # slice materialization: a device copy, not an alias
        return HbmLease(self, off, n, seg)

    def _release(self, off: int, n: int, aliased: bool = False, *,
                 consumed: bool = True) -> None:
        """Return one lease's credit. ``consumed=False`` (internal, error
        unwinding) decrements without marking the span consumed — a failed
        view attempt must not let the head advance over bytes nobody read."""
        if n == 0:
            return  # zero-size spans hold no credit (never entered _live)
        with self._lock:
            if aliased:
                self._aliased -= 1
            entry = self._live[(off, n)]
            entry[0] -= 1
            if consumed:
                entry[1] = True
            if entry[0] > 0:
                return
            self._advance_locked()

    def _advance_locked(self) -> None:
        """Advance head over every consumed (leased-and-released) prefix.
        Caller holds ``self._lock``."""
        advanced = False
        while self._live:
            first_key = min(self._live)
            cnt, consumed = self._live[first_key]
            if first_key[0] != self.head or cnt > 0 or not consumed:
                break
            del self._live[first_key]
            self.head += first_key[1]
            advanced = True
        if advanced:
            self._space.notify_all()

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"capacity": self.capacity, "head": self.head,
                    "tail": self.tail, "live_spans": len(self._live),
                    "writable": self.writable()}

    # -- rendezvous landing leases (tpurpc-express, ISSUE 9) ------------------

    def lease_region(self, nbytes: int,
                     timeout: Optional[float] = None) -> "HbmRegionLease":
        """Reserve a ring span as a RENDEZVOUS LANDING REGION: the span is
        claimed (credit held, placement deferred) and advertised to a bulk
        sender; :meth:`HbmRegionLease.fill` later lands the payload with
        exactly one h2d DMA + one in-ring landing write — the accelerator-
        plane half of the peer-advertised landing region (the shm/verbs
        pools play this role on the host planes). Release without fill
        (peer death with the region claimed) returns the credit.

        Blocks up to ``timeout`` for credit like :meth:`place`; raises
        :class:`BufferError` when the ring cannot ever hold ``nbytes``."""
        if nbytes <= 0:
            raise ValueError("lease_region needs a positive size")
        if nbytes > self.capacity:
            raise BufferError(
                f"payload {nbytes} exceeds ring capacity {self.capacity}")
        with self._lock:
            if nbytes > self.writable() and timeout is not None:
                import time as _time
                deadline = _time.monotonic() + timeout
                while nbytes > self.writable():
                    remain = deadline - _time.monotonic()
                    if remain <= 0 or not self._space.wait(timeout=remain):
                        break
            if nbytes > self.writable():
                raise BufferError(
                    f"HBM ring full: {nbytes} > {self.writable()}")
            off = self.tail
            self.tail += nbytes
            self._live[(off, nbytes)] = [0, False]
        return HbmRegionLease(self, off, nbytes)

    def _fill_span(self, off: int, nbytes: int, payload) -> None:
        """Land ``payload`` into a reserved span (lease_region's deferred
        placement): ONE h2d transfer + the single landing write, same
        discipline and ledger accounting as :meth:`place`."""
        import jax

        src = np.frombuffer(payload, np.uint8) if not isinstance(
            payload, np.ndarray) else payload.reshape(-1).view(np.uint8)
        if src.nbytes != nbytes:
            raise ValueError(f"fill of {src.nbytes} bytes into a "
                             f"{nbytes}-byte lease")
        t0 = time.monotonic_ns()
        with self._lock:
            if (off, nbytes) not in self._live:
                raise KeyError(f"span ({off}, {nbytes}) not live")
            p = off & self._mask
            dev = jax.device_put(jax.numpy.asarray(src), self.device)
            ledger.dma_h2d(nbytes)
            first = min(nbytes, self.capacity - p)
            if first >= nbytes:
                self.buf = self._update(self.buf, dev, p)
                ledger.dma_d2d(nbytes)
            elif self._pallas_place(dev, p, nbytes):
                ledger.dma_d2d(nbytes)
            else:
                self.buf = self._update(self.buf, dev[:first], p)
                ledger.dma_d2d(first)
                self.buf = self._update(self.buf, dev[first:], 0)
                ledger.dma_d2d(nbytes - first)
            self._assert_stable()
        dt = time.monotonic_ns() - t0
        _HBM_PLACE_MSGS.inc()
        _HBM_PLACE_BYTES.inc(nbytes)
        _LENS_HBM_BYTES.inc(nbytes)
        _LENS_HBM_NS.inc(dt)
        _LENS_HBM_COPY.inc(nbytes)


class HbmLease:
    """A device view pinning its ring span; release returns the credit.

    ``release()`` is idempotent; dropping the lease without releasing leaks
    the span until process exit (deliberate: silent auto-free under GC
    pressure would make flow control nondeterministic — the reference's
    credits are explicit too, ``pair.cc:276-284``)."""

    __slots__ = ("_ring", "_off", "_n", "array", "_released", "aliased")

    def __init__(self, ring: HbmRing, off: int, n: int, array,
                 aliased: bool = False):
        self._ring = ring
        self._off = off
        self._n = n
        self.array = array
        #: True when ``array`` ALIASES ring memory (dlpack view, ledger
        #: zero_copy): valid only within the lease window — after release
        #: the span may be overwritten in place under it. Copied views
        #: (False) are snapshots and survive release.
        self.aliased = aliased
        self._released = False

    def release(self) -> None:
        if not self._released:
            self._released = True
            self._ring._release(self._off, self._n, self.aliased)

    def __enter__(self):
        return self.array

    def __exit__(self, *exc):
        self.release()
        return False


class HbmRegionLease:
    """A reserved-but-unfilled ring span advertised as a rendezvous landing
    region (see :meth:`HbmRing.lease_region`).

    Lifecycle mirrors the rendezvous protocol the ringcheck model proves:
    claim (this object) → :meth:`fill` (the one-sided placement) →
    :meth:`view` (zero-copy consumption) → :meth:`release`; release without
    fill is the peer-death path and simply returns the credit."""

    __slots__ = ("ring", "offset", "nbytes", "filled", "_released")

    def __init__(self, ring: HbmRing, offset: int, nbytes: int):
        self.ring = ring
        self.offset = offset
        self.nbytes = nbytes
        self.filled = False
        self._released = False

    def fill(self, payload) -> None:
        """Land the payload: one dma_h2d + one in-ring landing write (the
        ledger's op counts assert the single-movement claim)."""
        if self._released:
            raise RuntimeError("lease already released")
        self.ring._fill_span(self.offset, self.nbytes, payload)
        self.filled = True

    def view(self, dtype=np.uint8, shape: Optional[tuple] = None
             ) -> HbmLease:
        """Device view of the landed payload (dlpack alias on eligible
        backends, ledger-billed either way). Only valid after fill."""
        if not self.filled:
            raise RuntimeError("view before fill: the landing write has "
                               "not happened")
        return self.ring.view(self.offset, self.nbytes, dtype=dtype,
                              shape=shape)

    def release(self) -> None:
        """Return the span's credit (idempotent). An unfilled release is
        the peer-death path: the span is marked consumed so the head can
        advance over it."""
        if self._released:
            return
        self._released = True
        with self.ring._lock:
            entry = self.ring._live.get((self.offset, self.nbytes))
            if entry is None:
                return
            entry[1] = True  # consumed (possibly without any fill/view)
            if entry[0] == 0:
                self.ring._advance_locked()
