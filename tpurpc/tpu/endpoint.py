"""Platform.TPU endpoint: the ring byte-pipe whose received tensor payloads
land in a device(HBM)-resident ring and surface as lease-backed jax.Arrays.

This is the file ``create_endpoint`` dispatches to for
``GRPC_PLATFORM_TYPE=TPU`` / ``RDMA_TPU`` (``tpurpc/core/endpoint.py:452-456``)
— the framework's namesake transport, and round 1's headline gap.

Architecture (BASELINE.json north star: "receive ring in HBM, recv yields
device handles, host-memcpy = 0 after frame assembly"):

* The byte pipe itself is the same pooled shm Pair as the other ring
  platforms (creation path mirrors ``rdma_bp_posix.cc:706-796``: pool take →
  init → bootstrap over the connected socket → hybrid-discipline wakeups).
  Control structures — frame headers, metadata, trailers — are parsed
  host-side, exactly as the real-hardware design keeps head/footer words
  host-visible while payloads go to HBM.
* Each connection owns an :class:`~tpurpc.tpu.hbm_ring.HbmRing`
  (``device_ring``), created lazily on first tensor decode so pure-bytes
  RPCs never pay jax initialization.
* :func:`decode_tensor_to_ring` / :func:`decode_tree_to_ring` are the
  ``DeserializeToDevice`` of this platform (SURVEY §7 stage 6): they parse
  the codec's host-visible tensor header, place the payload span into the
  device ring straight from the wire-assembly buffer (zero host memcpy —
  the ledger proves it), and hand back device views whose leases gate the
  ring's credit return (hard-part #4: a jax.Array aliasing ring memory
  must pin its span).

The RPC layer reaches the device ring through ``ServerContext.device_ring``
(server) and ``Channel.device_ring()`` (client); the jaxshim tensor service
uses them when registered with ``device=True``.
"""

from __future__ import annotations

import socket
import struct
import time
from typing import Any, List, Optional, Tuple

import numpy as np

from tpurpc.core.endpoint import RingEndpoint
from tpurpc.jaxshim import codec
from tpurpc.obs import lens as _lens
from tpurpc.obs import profiler as _profiler
from tpurpc.tpu.hbm_ring import HbmLease, HbmRing
from tpurpc.utils.config import Platform, get_config
from tpurpc.utils.trace import trace_endpoint

# tpurpc-lens (ISSUE 8): the device-plane decode (wire record → placed
# device view) is the `decode` waterfall hop here; its HBM placement share
# is visible on the `hbm` row (hops may nest — see obs/lens.py).
_LENS_DEC_BYTES, _LENS_DEC_NS, _LENS_DEC_COPY = _lens.hop_counters("decode")

_LENS_STAGES = {
    "decode_tensor_to_ring": "codec",
    "decode_tree_to_ring": "codec",
    "_parse_tensor_record": "codec",
}
_profiler.register_stages(__file__, _LENS_STAGES)

#: Default wait for device-ring space before failing a decode: long enough to
#: ride out a burst of unreleased leases, short enough to surface a genuine
#: leak as an error instead of a hang.
PLACE_TIMEOUT_S = 30.0


class TpuRingEndpoint(RingEndpoint):
    """Ring endpoint + device-resident receive ring for tensor payloads.

    The byte-pipe contract is inherited unchanged — anything that speaks
    frames over a :class:`RingEndpoint` works here too. What's new is
    :attr:`device_ring`: the per-connection HBM ring that the tensor decode
    path places payloads into.
    """

    def __init__(self, sock: socket.socket, *, pool_key: str,
                 is_server: bool = False, preread: bytes = b""):
        super().__init__(sock, discipline=Platform.TPU.discipline,
                         pool_key=pool_key, preread=preread)
        self.is_server = is_server
        self._hbm: Optional[HbmRing] = None
        import threading

        self._hbm_lock = threading.Lock()

    @property
    def device_ring(self) -> HbmRing:
        """The connection's HBM receive ring; created on first use (jax
        backend init is expensive — pure-bytes traffic never pays it)."""
        if self._hbm is None:
            with self._hbm_lock:
                if self._hbm is None:
                    cap = get_config().hbm_ring_size
                    self._hbm = HbmRing(cap)
                    trace_endpoint.log(
                        "TPU endpoint %s: HBM ring up (%d bytes)",
                        self.peer, cap)
        return self._hbm

    def close(self) -> None:
        # The HbmRing needs no explicit teardown: leases pin spans, and the
        # device buffer dies with the last reference. Dropping the ring here
        # (not at pool putback) matches per-connection device resources.
        self._hbm = None
        super().close()


# ---------------------------------------------------------------------------
# DeserializeToDevice over the device ring.
# ---------------------------------------------------------------------------

def _parse_tensor_record(view: memoryview, offset: int):
    """Host-side parse of one codec tensor record: ``(dtype, shape,
    payload_view, next_offset)`` with the payload as a zero-copy numpy view
    over ``view`` — shared by the single and batched placement paths."""
    if len(view) - offset < codec._HDR.size:
        raise codec.CodecError("short tensor header")
    magic, code, ndim, _, nbytes = codec._HDR.unpack_from(view, offset)
    if magic != codec.MAGIC:
        raise codec.CodecError(f"bad tensor magic {magic!r}")
    try:
        dt = codec._CODE_TO_DTYPE[code]
    except KeyError:
        raise codec.CodecError(f"unknown dtype code {code}") from None
    pos = offset + codec._HDR.size
    if len(view) - pos < 8 * ndim:
        raise codec.CodecError("short tensor dims")
    shape = struct.unpack_from(f"<{ndim}q", view, pos) if ndim else ()
    pos += 8 * ndim
    pos += (-(pos - offset)) % codec._ALIGN
    if len(view) - pos < nbytes:
        raise codec.CodecError(
            f"short tensor payload: want {nbytes}, have {len(view) - pos}")
    payload = np.frombuffer(view, dtype=np.uint8, count=nbytes, offset=pos)
    return dt, shape, payload, pos + nbytes


def decode_tensor_to_ring(ring: HbmRing, buf, offset: int = 0,
                          timeout: Optional[float] = PLACE_TIMEOUT_S
                          ) -> Tuple[HbmLease, int]:
    """One wire tensor record → device-ring placement + lease-backed view.

    Parses the codec header host-side (control words), places ONLY the
    payload span into ``ring`` directly from ``buf`` (no intermediate host
    buffer — the ledger's host_copy stays 0 for this step), and returns
    ``(lease, next_offset)``. ``lease.array`` is the shaped/dtyped device
    view; releasing the lease returns the span's credit.
    """
    t0 = time.monotonic_ns()
    dt, shape, payload, next_pos = _parse_tensor_record(memoryview(buf), offset)
    off, n = ring.place(payload, timeout=timeout)
    lease = ring.view(off, n, dtype=dt, shape=shape)
    elapsed = time.monotonic_ns() - t0
    _LENS_DEC_NS.inc(elapsed)
    _LENS_DEC_BYTES.inc(n)
    return lease, next_pos


def decode_tree_to_ring(ring: HbmRing, buf,
                        timeout: Optional[float] = PLACE_TIMEOUT_S
                        ) -> Tuple[Any, List[HbmLease]]:
    """Pytree wire message → device-ring-backed tree + the leases pinning it.

    Mirrors :func:`tpurpc.jaxshim.codec.decode_tree`, but every leaf's
    payload is placed into the device ring instead of aliased host-side.
    Returns ``(tree, leases)``; release every lease (or use
    :class:`DeviceMessage`) to return the ring credit.
    """
    import json

    import jax

    t0 = time.monotonic_ns()
    view = memoryview(buf)
    magic, n_leaves, trailer_len = codec._TREE.unpack_from(view, 0)
    if magic != codec.TREE_MAGIC:
        raise codec.CodecError(f"bad tree magic {magic!r}")
    # A tree whose payloads can never fit the ring must fail fast: waiting on
    # lease releases is futile when the blocking leases are this same
    # message's earlier leaves (reviewer finding: every such request would
    # stall a worker the full place timeout before the inevitable error).
    total = _tree_payload_bytes(view, n_leaves)
    if total > ring.capacity:
        raise BufferError(
            f"tree payloads total {total} bytes > ring capacity "
            f"{ring.capacity}; raise TPURPC_HBM_RING_SIZE_KB")
    pos = codec._TREE.size + ((-codec._TREE.size) % codec._ALIGN)
    # Batched placement: parse EVERY leaf header first (host control words),
    # then land all payloads with ONE ring.place_many dispatch — one h2d +
    # one donated update per tree instead of per leaf (ISSUE 1 tentpole;
    # a transformer pytree has hundreds of leaves and paid a dispatch each).
    metas = []  # (dtype, shape)
    payloads = []
    for _ in range(n_leaves):
        dt, shape, payload, pos = _parse_tensor_record(view, pos)
        pos += (-pos) % codec._ALIGN
        metas.append((dt, shape))
        payloads.append(payload)
    if len(view) - pos < trailer_len:
        raise codec.CodecError("short tree trailer")
    spans = ring.place_many(payloads, timeout=timeout)
    leases: List[HbmLease] = []
    leaves = []
    try:
        for (dt, shape), (off, n) in zip(metas, spans):
            lease = ring.view(off, n, dtype=dt, shape=shape)
            leases.append(lease)
            leaves.append(lease.array)
        trailer = bytes(view[pos:pos + trailer_len])
        treedef = codec._treedef_from_json(json.loads(trailer.decode()))
        tree = jax.tree_util.tree_unflatten(treedef, leaves)
    except Exception:
        # Corrupt leaf, trailer, or treedef: every already-taken lease must
        # go back, or a poison message permanently pins ring credit — and
        # spans placed but never viewed must be consumed-and-released too,
        # or the batch's tail spans block the head forever.
        for lease in leases:
            lease.release()
        for off, n in spans[len(leases):]:
            try:
                ring.view(off, n).release()
            except Exception:
                pass  # span already torn down; nothing more to free
        raise
    elapsed = time.monotonic_ns() - t0
    _LENS_DEC_NS.inc(elapsed)
    _LENS_DEC_BYTES.inc(total)
    return tree, leases


def _tree_payload_bytes(view: memoryview, n_leaves: int) -> int:
    """Sum the payload sizes of a tree message by walking headers only."""
    pos = codec._TREE.size + ((-codec._TREE.size) % codec._ALIGN)
    total = 0
    for _ in range(n_leaves):
        if len(view) - pos < codec._HDR.size:
            raise codec.CodecError("short tensor header")
        magic, _, ndim, _, nbytes = codec._HDR.unpack_from(view, pos)
        if magic != codec.MAGIC:
            raise codec.CodecError(f"bad tensor magic {magic!r}")
        rec = pos
        pos += codec._HDR.size + 8 * ndim
        pos += (-(pos - rec)) % codec._ALIGN
        pos += nbytes
        pos += (-pos) % codec._ALIGN
        total += nbytes
    return total


class DeviceMessage:
    """A decoded device-resident message: the tree + its ring leases.

    Use as a context manager (or call :meth:`release`) — the ring spans under
    the arrays stay pinned until then, which IS the flow control: a slow
    consumer holding messages back-pressures the placement path.
    """

    __slots__ = ("tree", "_leases", "_released")

    def __init__(self, tree: Any, leases: List[HbmLease]):
        self.tree = tree
        self._leases = leases
        self._released = False

    def release(self) -> None:
        if not self._released:
            self._released = True
            for lease in self._leases:
                lease.release()

    def __enter__(self) -> Any:
        return self.tree

    def __exit__(self, *exc) -> bool:
        self.release()
        return False
