"""Copy ledger: byte-exact accounting of host memcpys and DMAs per path.

BASELINE.json's third headline metric is "host-memcpy bytes" on the receive
path — a number the reference cannot even measure (its copies are implicit in
``ring_buffer.cc:122-191`` Read and slice assembly). Every data-plane layer
reports its copies here, so "zero-copy" is a measured claim, not a slogan:

* ``host_copy``    — CPU memcpy between two host buffers (ring drain, frame
                     assembly, codec copy=True, staging)
* ``dma_h2d``      — host buffer → device memory (jax device_put of wire bytes)
* ``dma_d2h``      — device memory → host buffer (serialize-from-device)
* ``dma_d2d``      — device → device movement (ring in-place update, slice
                     materialization in ``HbmRing.view`` — XLA's dynamic_slice
                     produces a NEW buffer, which is a copy, and the ledger
                     says so; see VERDICT r1 "the copy ledger lies")
* ``zero_copy``    — payload bytes delivered by aliasing (dlpack import of a
                     wire buffer): no bytes moved anywhere
* ``rdma_write``   — one-sided rendezvous placement into a peer-advertised
                     registered landing region (tpurpc-express): the wire
                     movement itself — an RDMA WRITE on the verbs domain,
                     a single memoryview copy on the shm/local emulations.
                     Distinct from ``host_copy`` because it IS the transfer:
                     the receive side lands zero additional host copies
                     (decode aliases the landing region in place).

Counters are process-wide and monotonic; :func:`track` snapshots a window.
GIL-protected integer adds — the accounting itself must not cost a memcpy.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Dict

_lock = threading.Lock()
_counters: Dict[str, int] = {
    "host_copy": 0,
    "dma_h2d": 0,
    "dma_d2h": 0,
    "dma_d2d": 0,
    "zero_copy": 0,
    "rdma_write": 0,
    # op counts (one per reported movement) alongside the byte totals:
    # single-movement claims are assertable — "this placement was exactly
    # ONE device write" is a count, not a byte sum (VERDICT r3 next#6)
    "host_copy_ops": 0,
    "dma_h2d_ops": 0,
    "dma_d2h_ops": 0,
    "dma_d2d_ops": 0,
    "zero_copy_ops": 0,
    "rdma_write_ops": 0,
}


def add(kind: str, nbytes: int) -> None:
    if nbytes:
        with _lock:
            _counters[kind] += nbytes
            _counters[kind + "_ops"] += 1


def host_copy(nbytes: int) -> None:
    add("host_copy", nbytes)


def dma_h2d(nbytes: int) -> None:
    add("dma_h2d", nbytes)


def dma_d2h(nbytes: int) -> None:
    add("dma_d2h", nbytes)


def dma_d2d(nbytes: int) -> None:
    add("dma_d2d", nbytes)


def zero_copy(nbytes: int) -> None:
    add("zero_copy", nbytes)


def rdma_write(nbytes: int) -> None:
    add("rdma_write", nbytes)


def snapshot() -> Dict[str, int]:
    with _lock:
        return dict(_counters)


def reset() -> None:
    with _lock:
        for k in _counters:
            _counters[k] = 0


class Window:
    """Counter deltas over a tracked region."""

    def __init__(self, start: Dict[str, int]):
        self._start = start
        self.delta: Dict[str, int] = {}

    def close(self, end: Dict[str, int]) -> None:
        self.delta = {k: end[k] - self._start[k] for k in end}

    def __getitem__(self, k: str) -> int:
        return self.delta[k]


_active_windows = 0


def tracking() -> bool:
    """True while any ``track()`` window is open. Fast paths that bypass
    the instrumented Python data plane (the channel's native unary fast
    path) consult this and step aside — a copy-ledger measurement must
    measure the path whose copies the ledger counts."""
    return _active_windows > 0


@contextlib.contextmanager
def track():
    """``with ledger.track() as w: ...`` → ``w["host_copy"]`` etc."""
    global _active_windows
    w = Window(snapshot())
    with _lock:
        _active_windows += 1
    try:
        yield w
    finally:
        with _lock:
            _active_windows -= 1
        w.close(snapshot())
