"""Device-mesh construction and sharding helpers.

The reference's only parallelism is RPC-plane (many connections / pollers,
SURVEY.md §2.7); serving sharded models behind those connections is the TPU
side of the capability. One mesh, five logical axes:

=====  =====================================================================
axis   meaning
=====  =====================================================================
dp     data parallel — batch sharding, gradient psum
pp     pipeline parallel — layer stages, microbatch ppermute ring
sp     sequence parallel — long-context ring attention (K/V rotate over ICI)
tp     tensor parallel — Megatron-style column/row splits, activation psum
ep     expert parallel — MoE all_to_all dispatch/return
=====  =====================================================================

Axes the hardware can't fill get size 1 — the collectives still compile and
the same program scales when real chips arrive (pjit/XLA semantics: axis size
is a compile-time constant, not a code path).
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.8
    from jax import shard_map as _shard_map
    _CHECK_KW = "check_vma"
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map
    _CHECK_KW = "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs, check_rep: bool = False):
    """Version-stable shard_map (jax renamed check_rep → check_vma in 0.8)."""
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      **{_CHECK_KW: check_rep})

AXES = ("dp", "pp", "sp", "tp", "ep")


def factor_mesh(n_devices: int,
                priority: Sequence[str] = ("dp", "tp", "sp", "pp", "ep"),
                caps: Optional[Dict[str, int]] = None) -> Dict[str, int]:
    """Split ``n_devices`` over the five logical axes.

    Greedy: peel prime factors (largest first) onto axes in ``priority``
    round-robin, respecting per-axis ``caps``. Deterministic, total product
    == n_devices, unfilled axes get 1.
    """
    sizes = {a: 1 for a in AXES}
    caps = caps or {}
    rem = n_devices
    factors = []
    d = 2
    while d * d <= rem:
        while rem % d == 0:
            factors.append(d)
            rem //= d
        d += 1
    if rem > 1:
        factors.append(rem)
    factors.sort(reverse=True)
    i = 0
    for f in factors:
        for _ in range(len(priority)):
            a = priority[i % len(priority)]
            i += 1
            if sizes[a] * f <= caps.get(a, n_devices):
                sizes[a] *= f
                break
        else:  # no axis can take it (all capped) — dump on dp
            sizes["dp"] *= f
    return sizes


def build_mesh(n_devices: Optional[int] = None,
               sizes: Optional[Dict[str, int]] = None,
               devices: Optional[Sequence] = None) -> Mesh:
    """An ``AXES``-named mesh over the first ``n_devices`` jax devices."""
    devs = list(devices) if devices is not None else list(jax.devices())
    n = n_devices or len(devs)
    if len(devs) < n:
        raise ValueError(f"need {n} devices, have {len(devs)}")
    sizes = dict(sizes) if sizes else factor_mesh(n)
    for a in AXES:
        sizes.setdefault(a, 1)
    shape = tuple(sizes[a] for a in AXES)
    if math.prod(shape) != n:
        raise ValueError(f"mesh sizes {sizes} != {n} devices")
    arr = np.asarray(devs[:n]).reshape(shape)
    return Mesh(arr, AXES)


def shard(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, P(*spec))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def axis_size(mesh: Mesh, axis: str) -> int:
    return dict(zip(mesh.axis_names, mesh.devices.shape))[axis]
