"""Expert parallelism: switch-style top-1 MoE with all_to_all dispatch.

One expert FFN per 'ep' shard. Tokens are routed top-1, packed into
per-expert capacity slots host-free (cumsum position trick — no dynamic
shapes, XLA-friendly), exchanged with two ``lax.all_to_all``s over the 'ep'
axis (dispatch + return), and combined weighted by the router gate.

The all_to_all rides ICI exactly like the reference's RDMA WRITEs ride the
NIC: a one-sided bulk permutation of payload between peers with no
request/response round trip (SURVEY.md §2.8 → TPU mapping §5).

Everything is a per-device block function for use inside shard_map with axis
name 'ep' bound; see tpurpc/models/transformer.py for placement.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax


class MoEParams(NamedTuple):
    router: jax.Array   # [d_model, n_experts]
    w_in: jax.Array     # [1(local experts), d_model, d_ff]
    w_out: jax.Array    # [1, d_ff, d_model]


def init_moe(key, d_model: int, d_ff: int, n_experts: int,
             dtype=jnp.float32) -> MoEParams:
    """Global-view params; shard w_in/w_out leading axis over 'ep'."""
    kr, ki, ko = jax.random.split(key, 3)
    s = d_model ** -0.5
    return MoEParams(
        router=(jax.random.normal(kr, (d_model, n_experts)) * s).astype(dtype),
        w_in=(jax.random.normal(ki, (n_experts, d_model, d_ff)) * s).astype(dtype),
        w_out=(jax.random.normal(ko, (n_experts, d_ff, d_model))
               * d_ff ** -0.5).astype(dtype),
    )


def moe_block(params: MoEParams, x: jax.Array, axis_name: str = "ep",
              capacity_factor: float = 2.0) -> Tuple[jax.Array, jax.Array]:
    """Per-device body. x: [T, d] local tokens. Returns (y, aux_loss).

    ``params.w_in/w_out`` arrive as the local expert slice [E_loc, d, f].
    Router is replicated. aux_loss is the switch load-balance term
    (mean fraction·router-prob product, scaled by n_experts²).
    """
    ep = lax.psum(1, axis_name)
    T, d = x.shape
    e_loc = params.w_in.shape[0]
    E = ep * e_loc
    cap = max(1, int(capacity_factor * T / E))

    logits = jnp.einsum("td,de->te", x.astype(jnp.float32),
                        params.router.astype(jnp.float32))
    probs = jax.nn.softmax(logits, axis=-1)
    expert = jnp.argmax(probs, axis=-1)                       # [T]
    gate = jnp.max(probs, axis=-1)                            # [T]
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)     # [T, E]

    # load-balance aux (Switch Transformer eq. 4): fraction of tokens vs
    # mean router prob per expert, both local; psum makes it global-mean.
    frac = lax.pmean(jnp.mean(onehot, axis=0), axis_name)
    pmean = lax.pmean(jnp.mean(probs, axis=0), axis_name)
    aux = jnp.sum(frac * pmean) * E

    # position of each token within its expert's capacity
    pos = jnp.cumsum(onehot, axis=0) - 1.0                    # [T, E]
    keep = (pos < cap).astype(jnp.float32) * onehot
    pos_clamped = jnp.clip(pos, 0, cap - 1).astype(jnp.int32)
    slot = jax.nn.one_hot(pos_clamped, cap, dtype=jnp.float32)  # [T, E, C]
    dispatch = slot * keep[..., None]                         # [T, E, C]
    combine = dispatch * gate[:, None, None]

    # pack: [E, C, d]; all_to_all → [E, C, d] grouped by source shard
    packed = jnp.einsum("tec,td->ecd", dispatch, x.astype(jnp.float32))
    recv = lax.all_to_all(packed.reshape(ep, e_loc, cap, d), axis_name,
                          split_axis=0, concat_axis=0, tiled=True)
    # recv: [ep(src), e_loc, C, d] → local experts see all shards' tokens
    h = jnp.einsum("secd,edf->secf", recv, params.w_in.astype(jnp.float32))
    h = jax.nn.gelu(h)
    y = jnp.einsum("secf,efd->secd", h, params.w_out.astype(jnp.float32))
    back = lax.all_to_all(y, axis_name, split_axis=0, concat_axis=0,
                          tiled=True)                          # [ep, e_loc, C, d]
    out = jnp.einsum("tec,ecd->td", combine,
                     back.reshape(E, cap, d))
    return out.astype(x.dtype), aux.astype(jnp.float32)
