"""Ring attention: exact long-context attention with sequence sharded over 'sp'.

Each device holds one sequence block of Q, K, V. K/V blocks rotate around the
'sp' ring via ``lax.ppermute`` while a flash-style numerically stable
accumulator (running row-max, rescaled numerator/denominator) folds in one
block per step — after ``sp`` steps every Q block has attended to the full
sequence without any device ever materializing the (S, S) score matrix.

Communication pattern = the reference's credit ring inverted: instead of one
fixed buffer receiving remote writes (``ibverbs/ring_buffer.cc``), the payload
itself circulates over ICI. Compute/comm overlap is XLA's job (the ppermute
and the matmul of the *previous* block are independent in the dataflow graph).

Used inside ``shard_map`` bodies — operates on per-device blocks with axis
name 'sp' bound by the caller (see tpurpc/models/transformer.py).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _ring_perm(axis_size: int):
    # shift +1: device i sends to i+1, so at step s device i holds the block
    # originally owned by (i - s) mod axis_size.
    return [(i, (i + 1) % axis_size) for i in range(axis_size)]


def ring_attention_block(q: jax.Array, k: jax.Array, v: jax.Array,
                         axis_name: str = "sp", causal: bool = False,
                         scale: Optional[float] = None) -> jax.Array:
    """Per-device body: q,k,v are local blocks [B, H, S_blk, D].

    Returns the local output block [B, H, S_blk, D] in q.dtype; softmax
    statistics accumulate in float32 regardless of input dtype (bfloat16
    inputs keep the MXU fed, fp32 running stats keep softmax exact).
    """
    sp = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    B, H, S, D = q.shape
    if scale is None:
        scale = D ** -0.5
    qf = q.astype(jnp.float32) * scale

    perm = _ring_perm(sp)

    def step(carry, s):
        k_cur, v_cur, m, num, den = carry
        # source block index: who originally owned the K/V we now hold
        src = (idx - s) % sp
        scores = jnp.einsum("bhqd,bhkd->bhqk", qf, k_cur.astype(jnp.float32))
        if causal:
            q_pos = idx * S + jnp.arange(S)[:, None]        # [S,1] global q
            k_pos = src * S + jnp.arange(S)[None, :]        # [1,S] global k
            scores = jnp.where(k_pos > q_pos, -jnp.inf, scores)
        blk_max = jnp.max(scores, axis=-1)                  # [B,H,S]
        m_new = jnp.maximum(m, blk_max)
        # rescale old accumulators; exp(-inf - -inf) guarded by where
        alpha = jnp.exp(jnp.where(jnp.isneginf(m), -jnp.inf, m - m_new))
        p = jnp.exp(scores - m_new[..., None])              # [B,H,S,Sk]
        p = jnp.where(jnp.isneginf(scores), 0.0, p)
        num = num * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_cur.astype(jnp.float32))
        den = den * alpha + jnp.sum(p, axis=-1)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, m_new, num, den), None

    m0 = jnp.full((B, H, S), -jnp.inf, jnp.float32)
    num0 = jnp.zeros((B, H, S, D), jnp.float32)
    den0 = jnp.zeros((B, H, S), jnp.float32)
    (k, v, m, num, den), _ = lax.scan(
        step, (k, v, m0, num0, den0), jnp.arange(sp))
    # fully-masked rows (can't happen for causal with s>=1, but keep det.)
    out = num / jnp.maximum(den, 1e-30)[..., None]
    return out.astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, mesh,
                   causal: bool = False, axis_name: str = "sp") -> jax.Array:
    """Whole-array convenience wrapper: shard [B,H,S,D] over 'sp' and run.

    For use outside an existing shard_map (tests, serving). Model code should
    call :func:`ring_attention_block` inside its own shard_map instead.
    """
    from jax.sharding import PartitionSpec as P
    from tpurpc.parallel.mesh import shard_map

    spec = P(None, None, axis_name, None)
    fn = shard_map(
        functools.partial(ring_attention_block, axis_name=axis_name,
                          causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_rep=False)
    return fn(q, k, v)
