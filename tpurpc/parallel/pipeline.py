"""Pipeline parallelism: GPipe-style microbatch ring over the 'pp' axis.

Stage s holds its layer slice (params stacked on a leading axis sharded over
'pp'); activations hop stage→stage with ``lax.ppermute`` while a ``lax.scan``
walks M + P - 1 ticks (M microbatches through P stages, the classic bubble).
Every stage runs its compute every tick — bubbles burn FLOPs instead of
introducing data-dependent control flow, which is the XLA-friendly trade.

Autodiff: ``ppermute``'s transpose is the reverse permutation, so
``jax.grad`` through the whole schedule yields the textbook 1F1B-equivalent
backward ring with no custom VJP.

Per-device body for shard_map with axis name 'pp' bound by the caller.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   stage_params: Any, x_micro: jax.Array,
                   axis_name: str = "pp") -> jax.Array:
    """Run microbatches through the stage ring.

    stage_fn(params_local, h) -> h' — one stage's compute (same signature on
    every stage; param *values* differ per shard).
    stage_params: this device's stage slice (leading stage axis squeezed by
    the caller's in_spec).
    x_micro: [M, mb, ...] microbatched input, replicated over 'pp'.

    Returns [M, mb, ...] outputs, replicated over 'pp' (masked psum from the
    last stage).
    """
    P = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    M = x_micro.shape[0]
    perm = [(i, (i + 1) % P) for i in range(P)]

    probe = jax.eval_shape(lambda p, h: stage_fn(p, h), stage_params,
                           jax.ShapeDtypeStruct(x_micro.shape[1:],
                                                x_micro.dtype))
    if probe.shape != x_micro.shape[1:] or probe.dtype != x_micro.dtype:
        raise ValueError("pipeline stages must preserve activation shape/dtype "
                         f"({x_micro.shape[1:]}/{x_micro.dtype} -> "
                         f"{probe.shape}/{probe.dtype})")

    def tick(carry, t):
        recv, out_acc = carry
        xm = lax.dynamic_index_in_dim(x_micro, jnp.clip(t, 0, M - 1), 0,
                                      keepdims=False)
        h = jnp.where(stage == 0, xm, recv)
        y = stage_fn(stage_params, h)
        recv_next = lax.ppermute(y, axis_name, perm)
        # last stage commits microbatch (t - (P-1)) when it's in range
        m_idx = t - (P - 1)
        commit = jnp.logical_and(stage == P - 1,
                                 jnp.logical_and(m_idx >= 0, m_idx < M))
        safe = jnp.clip(m_idx, 0, M - 1)
        cur = lax.dynamic_index_in_dim(out_acc, safe, 0, keepdims=False)
        upd = jnp.where(commit, y, cur)
        out_acc = lax.dynamic_update_index_in_dim(out_acc, upd, safe, 0)
        return (recv_next, out_acc), None

    recv0 = jnp.zeros_like(x_micro[0])
    out0 = jnp.zeros_like(x_micro)
    (_, out), _ = lax.scan(tick, (recv0, out0), jnp.arange(M + P - 1))
    # replicate the last stage's buffer to every stage
    return lax.psum(jnp.where(stage == P - 1, out, jnp.zeros_like(out)),
                    axis_name)


def microbatch(x: jax.Array, n_micro: int) -> jax.Array:
    """[B, ...] -> [M, B/M, ...]; B must divide evenly (static shapes)."""
    B = x.shape[0]
    if B % n_micro:
        raise ValueError(f"batch {B} not divisible by {n_micro} microbatches")
    return x.reshape(n_micro, B // n_micro, *x.shape[1:])


def unmicrobatch(x: jax.Array) -> jax.Array:
    return x.reshape(x.shape[0] * x.shape[1], *x.shape[2:])
