"""Multi-host bring-up: the control-plane seam for scaling past one host.

The reference's multi-node story is MPI-launched processes whose data
plane rides verbs (SURVEY.md §2.8); tpurpc's TPU-native equivalent is
``jax.distributed`` — one process per host joins a coordinator, and after
that the SAME pjit/mesh programs used single-host (tpurpc/parallel/mesh.py,
models/transformer.py) run globally: XLA routes collectives over ICI
inside a slice and DCN between slices. The RPC plane (this package's
host-level transport) is unchanged — it is how requests REACH a host;
the mesh is how work spreads across chips once there.

Axis placement rule (the scaling-book recipe): put ``dp`` (and ``pp``)
outermost so their collectives are the ones that cross DCN — they move
gradients/activations once per step; keep ``tp``/``sp``/``ep`` inside a
slice where ICI bandwidth lives. ``factor_mesh`` already orders axes this
way; ``global_mesh`` just applies it to the multi-host device list.

Env UX (mirrors the reference's launcher-agnostic env family):
``TPURPC_COORDINATOR`` (host:port), ``TPURPC_NUM_PROCESSES``,
``TPURPC_PROCESS_ID``. With none of those set the call is a single-process
no-op (the same program runs on a lone host); set ``TPURPC_AUTODETECT=1``
to instead let jax's own cluster autodetection (GKE/Cloud TPU metadata)
do the join — opt-in because on a plain host it would block hunting for a
coordinator that doesn't exist.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

_initialized = False


def initialize_cluster(coordinator: Optional[str] = None,
                       num_processes: Optional[int] = None,
                       process_id: Optional[int] = None) -> int:
    """Join (or stand alone as) a jax.distributed cluster; returns the
    process index. Single-process (num_processes in (None on a lone host,
    1)) is a no-op so the same program runs anywhere. Idempotent."""
    global _initialized
    import jax

    coordinator = coordinator or os.environ.get("TPURPC_COORDINATOR")
    if num_processes is None:
        env = os.environ.get("TPURPC_NUM_PROCESSES")
        num_processes = int(env) if env else None
    if process_id is None:
        env = os.environ.get("TPURPC_PROCESS_ID")
        process_id = int(env) if env else None

    if _initialized:
        return jax.process_index()
    autodetect = os.environ.get("TPURPC_AUTODETECT") == "1"
    if (coordinator is None and not autodetect
            and (num_processes is None or num_processes == 1)):
        _initialized = True  # single-process: nothing to join
        return 0
    # Cross-process collectives on the CPU backend need an explicit
    # implementation (on TPU the ICI/DCN fabric is implicit) — and the
    # CPU backend is in play whenever JAX_PLATFORMS is unset (default
    # fallback), "cpu", or lists cpu, so set it for every multi-process
    # join: the knob only affects the CPU client and is harmless on TPU.
    # TPURPC_CPU_COLLECTIVES selects the implementation (gloo | mpi).
    # Must run before the first backend touch. (CI exercises this with
    # no TPU pod: tests/test_distributed.py.)
    impl = os.environ.get("TPURPC_CPU_COLLECTIVES", "gloo")
    try:
        jax.config.update("jax_cpu_collectives_implementation", impl)
    except AttributeError:
        pass  # older jax without the knob
    except ValueError:
        if "TPURPC_CPU_COLLECTIVES" in os.environ:
            raise  # an explicitly-set bad value must fail loudly
    if autodetect and coordinator is None:
        jax.distributed.initialize()  # cluster env (GKE/Cloud TPU) fills in
    else:
        jax.distributed.initialize(coordinator_address=coordinator,
                                   num_processes=num_processes,
                                   process_id=process_id)
    _initialized = True
    return jax.process_index()


def global_mesh(sizes: Optional[Dict[str, int]] = None):
    """A 5-axis mesh over every device in the cluster (all processes).

    With ``sizes`` omitted, ``factor_mesh`` factors the GLOBAL device
    count with dp outermost — so the axes most tolerant of DCN hops are
    the ones that cross hosts. Call after :func:`initialize_cluster`."""
    import jax

    from tpurpc.parallel.mesh import build_mesh, factor_mesh

    devs = jax.devices()  # global across processes after initialize
    sizes = sizes or factor_mesh(len(devs))
    return build_mesh(len(devs), sizes=sizes, devices=devs), sizes


def process_count() -> int:
    import jax

    return jax.process_count()
