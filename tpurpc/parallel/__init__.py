"""TPU scale-out: mesh construction, parallel forms, multi-host bring-up.

Single-host and multi-host run the SAME programs: build a 5-axis mesh
(``mesh.build_mesh``), shard with the provided specs, and XLA inserts the
collectives — ICI inside a slice, DCN across hosts once
``distributed.initialize_cluster`` has joined the processes.
"""

from tpurpc.parallel.distributed import (global_mesh, initialize_cluster,
                                         process_count)
from tpurpc.parallel.mesh import build_mesh, factor_mesh

__all__ = ["build_mesh", "factor_mesh", "global_mesh",
           "initialize_cluster", "process_count"]
