__version__ = "0.1.0"
