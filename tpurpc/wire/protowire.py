"""Minimal protobuf wire-format primitives shared by the hand-rolled
standard services (:mod:`tpurpc.rpc.health`, :mod:`tpurpc.rpc.reflection`).

These modules speak real protobuf on the wire without a protobuf dependency
— their messages are a handful of scalar fields. One copy of the varint /
tag / field-walk math lives here so a robustness fix reaches every user.
"""

from __future__ import annotations

from typing import Iterator, Tuple, Union


def encode_varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def decode_varint(data: bytes, pos: int) -> Tuple[int, int]:
    shift = val = 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated varint")
        b = data[pos]
        pos += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def ld(field_no: int, payload: bytes) -> bytes:
    """A length-delimited (wire type 2) field.

    The tag is a VARINT like any other (a raw ``bytes([tag])`` is invalid
    past field 15 — tag ≥ 128 sets the continuation bit and the decoder
    eats the length byte as tag continuation; latent until a field number
    ≥ 16 exists, caught by the xds_v3 fuzz test)."""
    return encode_varint((field_no << 3) | 2) + encode_varint(
        len(payload)) + payload


def vf(field_no: int, value: int) -> bytes:
    """A varint (wire type 0) field; proto3 default-0 is omitted."""
    if not value:
        return b""
    return encode_varint((field_no << 3) | 0) + encode_varint(int(value))


def fields(data: bytes) -> Iterator[Tuple[int, int, Union[int, bytes]]]:
    """Yield ``(field_no, wire_type, value)`` over a serialized message.

    Raises :class:`ValueError` on any truncation — a field whose declared
    length runs past the buffer is corruption, not a short message, and
    must not be silently answered as if valid.
    """
    pos = 0
    n = len(data)
    while pos < n:
        tag, pos = decode_varint(data, pos)
        field_no, wt = tag >> 3, tag & 0x07
        if wt == 0:
            val, pos = decode_varint(data, pos)
        elif wt == 2:
            ln, pos = decode_varint(data, pos)
            if pos + ln > n:
                raise ValueError(f"field {field_no} truncated "
                                 f"({ln} declared, {n - pos} left)")
            val = data[pos:pos + ln]
            pos += ln
        elif wt == 5:
            if pos + 4 > n:
                raise ValueError(f"field {field_no} truncated fixed32")
            val = data[pos:pos + 4]
            pos += 4
        elif wt == 1:
            if pos + 8 > n:
                raise ValueError(f"field {field_no} truncated fixed64")
            val = data[pos:pos + 8]
            pos += 8
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield field_no, wt, val
