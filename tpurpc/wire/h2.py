"""HTTP/2 framing layer (RFC 7540 subset sufficient for gRPC).

Covers exactly what a gRPC peer exercises: SETTINGS exchange, HEADERS(+
CONTINUATION), DATA with connection+stream flow control, WINDOW_UPDATE,
PING, RST_STREAM, GOAWAY. No push, no priority tree (PRIORITY frames are
parsed and ignored, like every modern implementation).

Reference: ``chttp2/transport/frame_*.cc`` + ``flow_control.cc``
(SURVEY.md §2.4) — re-derived from the RFC, not ported.
"""

from __future__ import annotations

import struct
import threading
from typing import Dict, List, Optional, Tuple

PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"

DATA = 0x0
HEADERS = 0x1
PRIORITY = 0x2
RST_STREAM = 0x3
SETTINGS = 0x4
PUSH_PROMISE = 0x5
PING = 0x6
GOAWAY = 0x7
WINDOW_UPDATE = 0x8
CONTINUATION = 0x9

FLAG_END_STREAM = 0x1   # DATA, HEADERS
FLAG_ACK = 0x1          # SETTINGS, PING
FLAG_END_HEADERS = 0x4
FLAG_PADDED = 0x8
FLAG_PRIORITY = 0x20

SETTINGS_HEADER_TABLE_SIZE = 0x1
SETTINGS_ENABLE_PUSH = 0x2
SETTINGS_MAX_CONCURRENT_STREAMS = 0x3
SETTINGS_INITIAL_WINDOW_SIZE = 0x4
SETTINGS_MAX_FRAME_SIZE = 0x5
SETTINGS_MAX_HEADER_LIST_SIZE = 0x6

#: tpurpc-express (ISSUE 9) over the gRPC wire: an EXTENSION frame type
#: carrying the rendezvous offer/claim/complete/release control messages
#: (the FLAGS byte is the op from tpurpc.core.rendezvous; the bulk payload
#: itself bypasses DATA/flow-control entirely via the one-sided landing
#: region), negotiated through a custom SETTINGS identifier. Both are safe
#: against stock peers by RFC 7540: implementations MUST ignore unknown
#: frame types (§4.1) and unknown settings (§6.5.2) — a vanilla grpcio
#: peer never advertises the setting, so it never sees the frame.
TPURPC_RDV = 0xF0
SETTINGS_TPURPC_RDV = 0xF0F0

DEFAULT_WINDOW = 65535
DEFAULT_MAX_FRAME = 16384

# gRPC error-ish codes we emit
NO_ERROR = 0x0
PROTOCOL_ERROR = 0x1
FLOW_CONTROL_ERROR = 0x3
CANCEL = 0x8

_HDR = struct.Struct("!I")  # we pack the 24-bit length by slicing


class H2Error(ConnectionError):
    pass


def pack_frame(ftype: int, flags: int, stream_id: int, payload: bytes = b"") -> List[bytes]:
    if len(payload) > (1 << 24) - 1:
        raise H2Error("frame too large")
    head = (len(payload).to_bytes(3, "big") + bytes([ftype, flags]) +
            (stream_id & 0x7FFFFFFF).to_bytes(4, "big"))
    return [head, payload] if payload else [head]


def pack_settings(settings: Dict[int, int], ack: bool = False) -> List[bytes]:
    payload = b"".join(struct.pack("!HI", k, v) for k, v in settings.items())
    return pack_frame(SETTINGS, FLAG_ACK if ack else 0, 0, payload)


def parse_settings(payload: bytes) -> Dict[int, int]:
    if len(payload) % 6:
        raise H2Error("malformed SETTINGS")
    out = {}
    for i in range(0, len(payload), 6):
        k, v = struct.unpack_from("!HI", payload, i)
        out[k] = v
    return out


def validate_settings(settings: Dict[int, int]) -> None:
    """RFC 7540 §6.5.2 range checks for the values we ACT on — a peer's
    MAX_FRAME_SIZE outside [16384, 2^24-1] or INITIAL_WINDOW_SIZE above
    2^31-1 is a connection error, not a loop-step size to adopt (a zero
    max-frame would spin the send loop forever; an unsigned-huge window
    delta would blow FlowWindow past its overflow guard later)."""
    if SETTINGS_MAX_FRAME_SIZE in settings:
        v = settings[SETTINGS_MAX_FRAME_SIZE]
        if not (16384 <= v <= (1 << 24) - 1):
            raise H2Error(f"SETTINGS_MAX_FRAME_SIZE {v} outside "
                          "[16384, 2^24-1] (PROTOCOL_ERROR)")
    if SETTINGS_INITIAL_WINDOW_SIZE in settings:
        v = settings[SETTINGS_INITIAL_WINDOW_SIZE]
        if v > 0x7FFFFFFF:
            raise H2Error(f"SETTINGS_INITIAL_WINDOW_SIZE {v} exceeds "
                          "2^31-1 (FLOW_CONTROL_ERROR)")


def pack_goaway(last_stream: int, code: int, debug: bytes = b"") -> List[bytes]:
    return pack_frame(GOAWAY, 0, 0,
                      struct.pack("!II", last_stream & 0x7FFFFFFF, code) + debug)


def pack_rst(stream_id: int, code: int) -> List[bytes]:
    return pack_frame(RST_STREAM, 0, stream_id, struct.pack("!I", code))


def pack_window_update(stream_id: int, increment: int) -> List[bytes]:
    return pack_frame(WINDOW_UPDATE, 0, stream_id,
                      struct.pack("!I", increment & 0x7FFFFFFF))


def strip_padding(flags: int, payload: bytes, has_priority: bool) -> bytes:
    """Remove PADDED/PRIORITY envelope from HEADERS/DATA payloads."""
    pos = 0
    pad = 0
    if flags & FLAG_PADDED:
        if not payload:
            raise H2Error("padded frame with empty payload")
        pad = payload[0]
        pos = 1
    if has_priority and flags & FLAG_PRIORITY:
        pos += 5
    if pad > len(payload) - pos:
        raise H2Error("padding exceeds payload")
    return payload[pos:len(payload) - pad]


class FlowWindow:
    """A send-direction flow-control window: block until credit arrives."""

    def __init__(self, initial: int):
        self._value = initial
        self._cv = threading.Condition()
        self._dead = False

    def take(self, want: int, timeout: Optional[float] = None) -> int:
        """Reserve up to ``want`` bytes; blocks while the window is empty."""
        with self._cv:
            while self._value <= 0 and not self._dead:
                if not self._cv.wait(timeout=timeout):
                    raise TimeoutError("flow-control window starved")
            if self._dead:
                raise H2Error("connection closed")
            got = min(want, self._value)
            self._value -= got
            return got

    def grant(self, n: int) -> None:
        with self._cv:
            self._value += n
            if self._value > 0x7FFFFFFF:
                raise H2Error("window overflow")
            self._cv.notify_all()

    def adjust(self, delta: int) -> None:
        """SETTINGS_INITIAL_WINDOW_SIZE change retro-adjusts stream windows."""
        with self._cv:
            self._value += delta
            self._cv.notify_all()

    def kill(self) -> None:
        with self._cv:
            self._dead = True
            self._cv.notify_all()

    def try_take(self, want: int) -> bool:
        """Reserve exactly ``want`` bytes iff fully available right now —
        the non-blocking probe the fused (single-write) response path uses;
        callers fall back to the blocking chunked path on False."""
        with self._cv:
            if self._dead or self._value < want:
                return False
            self._value -= want
            return True


class FrameScanner:
    """Incremental frame parser over a growing byte buffer."""

    def __init__(self):
        self.buf = bytearray()

    def feed(self, data) -> None:
        self.buf += data

    def next_frame(self) -> Optional[Tuple[int, int, int, bytes]]:
        if len(self.buf) < 9:
            return None
        length = int.from_bytes(self.buf[:3], "big")
        if len(self.buf) < 9 + length:
            return None
        ftype = self.buf[3]
        flags = self.buf[4]
        stream_id = int.from_bytes(self.buf[5:9], "big") & 0x7FFFFFFF
        payload = bytes(self.buf[9:9 + length])
        del self.buf[:9 + length]
        return ftype, flags, stream_id, payload

    def next_frames(self) -> List[Tuple[int, int, int, bytes]]:
        """Every complete frame currently buffered, in order (the burst the
        last transport read delivered). One endpoint read on the tensor path
        typically carries a run of DATA frames for one stream — returning
        the burst lets receivers coalesce them into a single dispatch
        instead of re-entering the parser per frame."""
        out: List[Tuple[int, int, int, bytes]] = []
        while True:
            f = self.next_frame()
            if f is None:
                return out
            out.append(f)
