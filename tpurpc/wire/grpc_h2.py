"""gRPC-over-HTTP/2 server connection: stock gRPC clients hit tpurpc servers.

This is the drop-in capability: the reference IS gRPC, so any grpcio /
grpc++ client must be able to call a tpurpc server unchanged. A connection
whose first bytes are the h2 preface (sniffed in ``Server.serve_endpoint``)
lands here instead of the native TPURPC framing; the same registered
``RpcMethodHandler``s serve both protocols.

Implements the gRPC HTTP/2 protocol mapping: POST /Service/Method,
``content-type: application/grpc``, 5-byte length-prefixed messages in DATA,
``grpc-timeout`` deadlines, trailers with ``grpc-status``/``grpc-message``
(percent-encoded), ``-bin`` metadata as unpadded base64, flow control both
directions. Reference: chttp2 + surface/call.cc (SURVEY.md §2.4/§3.3).
"""

from __future__ import annotations

import base64
import gzip
import logging
import queue
import struct
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

from tpurpc.core import rendezvous as _rdv
from tpurpc.core.endpoint import Endpoint, EndpointError
from tpurpc.obs import flight as _flight
from tpurpc.obs import metrics as _obs_metrics
from tpurpc.obs import profiler as _profiler
from tpurpc.obs import tracing as _tracing
from tpurpc.rpc.status import AbortError, Metadata, StatusCode
from tpurpc.utils import stats as _stats
from tpurpc.wire import h2
from tpurpc.wire.hpack import HpackDecoder, HpackEncoder, HpackError

# tpurpc-lens (ISSUE 8) sampling-profiler frame markers: message↔frame
# translation on the server h2 plane is the h2-framing stage
_LENS_STAGES = {
    "send_message": "h2-framing",
    "_send_unary_fused": "h2-framing",
    "_on_data": "h2-framing",
    "recv_message": "h2-framing",
    "_read_loop": "h2-framing",
}
_profiler.register_stages(__file__, _LENS_STAGES)

#: tpurpc-scope (ISSUE 4): live h2 server connections + their send-side
#: connection window, read at scrape time only (the DATA-coalescing batch
#: histogram h2_data_coalesce already rides _stats.batch_hist → registry)
_H2_SRV_CONNS = _obs_metrics.fleet("h2_server_connections")
_H2_SRV_WINDOW = _obs_metrics.fleet("h2_server_send_window_bytes",
                                    lambda c: c._conn_window._value)
#: tpurpc-blackbox (ISSUE 5): per-method per-status RED counters — shared
#: with the native-framing server plane (same family, same labels)
_SRV_CALLS = _obs_metrics.labeled_counter("srv_calls", ("method", "code"))

_log = logging.getLogger("tpurpc.grpc_h2")

_GRPC_MSG_HDR = struct.Struct("!BI")


def _frame_grpc_message(payload) -> bytearray:
    """gRPC length-prefix framing in ONE preallocated buffer.

    ``payload`` may be bytes-like or a serializer gather list; either way
    the 5-byte header + every segment lands with a single staging copy —
    the ``b"".join`` + header-concat idiom this replaces copied the whole
    message twice (and is banned by the hot-path no-copy lint)."""
    parts = payload if isinstance(payload, (list, tuple)) else (payload,)
    views = [memoryview(p).cast("B") for p in parts]
    total = sum(len(v) for v in views)
    data = bytearray(_GRPC_MSG_HDR.size + total)
    _GRPC_MSG_HDR.pack_into(data, 0, 0, total)
    pos = _GRPC_MSG_HDR.size
    for v in views:
        data[pos:pos + len(v)] = v
        pos += len(v)
    return data


def decode_grpc_message(msg: bytes, compressed: int, encoding: str):
    """Per-message decompression per the gRPC spec; shared by the h2 server
    and client. Returns ``(message, None)`` or ``(None, (status, details))``:
    compressed-flag with identity encoding is INTERNAL (spec/grpcio parity),
    unknown codecs are UNIMPLEMENTED, corrupt bodies are INTERNAL
    (gzip raises OSError/BadGzipFile on bad magic, EOFError on truncation,
    zlib.error on a corrupt deflate body — all three are wire corruption)."""
    if not compressed:
        return msg, None
    if encoding == "gzip":
        try:
            return gzip.decompress(msg), None
        except (OSError, EOFError, zlib.error):
            return None, (StatusCode.INTERNAL, "corrupt gzip message")
    if encoding == "deflate":
        # gRPC "deflate" is a raw zlib stream (RFC 1950), grpcio parity
        try:
            return zlib.decompress(msg), None
        except zlib.error:
            return None, (StatusCode.INTERNAL, "corrupt deflate message")
    if encoding == "identity":
        return None, (StatusCode.INTERNAL,
                      "compressed-flag set with identity grpc-encoding")
    return None, (StatusCode.UNIMPLEMENTED,
                  f"message encoding {encoding!r} not supported "
                  "(accept: identity, gzip, deflate)")

#: our receive windows (we grant aggressively; tensors are big)
RECV_WINDOW = 4 << 20


def _parse_timeout(value: str) -> Optional[float]:
    try:
        unit = value[-1]
        n = int(value[:-1])
    except (ValueError, IndexError):
        return None
    return n * {"H": 3600.0, "M": 60.0, "S": 1.0, "m": 1e-3, "u": 1e-6,
                "n": 1e-9}.get(unit, None) if unit in "HMSmun" else None


def _pct_encode(msg: str) -> str:
    out = []
    for b in msg.encode("utf-8"):
        if 0x20 <= b <= 0x7E and b != 0x25:
            out.append(chr(b))
        else:
            out.append(f"%{b:02X}")
    return "".join(out)


def _decode_metadata_value(key: str, value: bytes):
    if key.endswith("-bin"):
        pad = -len(value) % 4
        return base64.b64decode(value + b"=" * pad)
    return value.decode("utf-8", "replace")


def _encode_metadata_value(key: str, value) -> str:
    if key.endswith("-bin"):
        raw = value if isinstance(value, (bytes, bytearray)) else str(value).encode()
        return base64.b64encode(raw).decode().rstrip("=")
    return value.decode() if isinstance(value, (bytes, bytearray)) else str(value)


class _H2Stream:
    _END = object()

    def __init__(self, stream_id: int):
        self.stream_id = stream_id
        self.requests: "queue.Queue[object]" = queue.Queue()
        self.partial = bytearray()   # gRPC message assembly across DATA frames
        self.recv_encoding = "identity"  # request grpc-encoding
        self.half_closed = False
        self.cancelled = threading.Event()
        self.window: Optional[h2.FlowWindow] = None  # send window, set by conn
        self.headers_sent = False
        #: tpurpc-blackbox: the caller's trace context (tail capture rides
        #: the h2 plane too) + the status the RED counters record
        self.trace_ctx = None
        self.final_code: Optional[StatusCode] = None


class H2ServerContext:
    """grpcio-compatible context for handlers reached over the h2 path."""

    def __init__(self, conn: "GrpcH2Connection", stream: _H2Stream,
                 metadata: List[Tuple[str, object]],
                 deadline: Optional[float]):
        self._conn = conn
        self._stream = stream
        self._metadata = metadata
        self._deadline = deadline
        self._trailing: Metadata = ()
        self._code: Optional[StatusCode] = None
        self._details = ""

    def invocation_metadata(self):
        return list(self._metadata)

    def peer(self) -> str:
        return self._conn.endpoint.peer

    def deadline_remaining(self) -> Optional[float]:
        if self._deadline is None:
            return None
        return max(0.0, self._deadline - time.monotonic())

    time_remaining = deadline_remaining

    def is_active(self) -> bool:
        return not self._stream.cancelled.is_set()

    def cancel(self) -> None:
        self._stream.cancelled.set()

    def set_trailing_metadata(self, metadata: Metadata) -> None:
        self._trailing = metadata

    def set_code(self, code: StatusCode) -> None:
        self._code = code

    def set_details(self, details: str) -> None:
        self._details = details

    def abort(self, code: StatusCode, details: str = ""):
        if code is StatusCode.OK:
            raise ValueError("abort with OK is invalid")
        raise AbortError(code, details)

    def send_initial_metadata(self, metadata: Metadata) -> None:
        self._conn.send_response_headers(self._stream, metadata)

    def _deadline_exceeded(self) -> bool:
        return self._deadline is not None and time.monotonic() > self._deadline


class GrpcH2Connection:
    """One accepted h2 connection serving gRPC semantics."""

    def __init__(self, server, endpoint: Endpoint,
                 preface_consumed: int = 0):
        self.server = server
        self.endpoint = endpoint
        self._scanner = h2.FrameScanner()
        self._decoder = HpackDecoder()
        self._encoder = HpackEncoder()
        self._write_lock = threading.Lock()
        self._streams: Dict[int, _H2Stream] = {}
        self._lock = threading.Lock()
        self._peer_max_frame = h2.DEFAULT_MAX_FRAME
        self._peer_initial_window = h2.DEFAULT_WINDOW
        self._conn_window = h2.FlowWindow(h2.DEFAULT_WINDOW)  # our sends
        self._recv_conn_credit = 0
        self._preface_left = len(h2.PREFACE) - preface_consumed
        self._headers_frag: Optional[Tuple[int, int, bytearray]] = None
        self.alive = True
        self._ftag = _flight.tag_for("h2srv:" + getattr(endpoint, "peer",
                                                        "?"))
        _H2_SRV_CONNS.track(self)
        _H2_SRV_WINDOW.track(self)
        # tpurpc-express over the gRPC wire: arm the rendezvous link; the
        # custom SETTINGS id in _send_settings is the capability advert,
        # and only a peer that advertised it back ever sees an RDV frame
        self.rdv = _rdv.link_for_endpoint(
            endpoint, "h2srv:" + getattr(endpoint, "peer", "?"),
            self._rdv_send_op, self._rdv_deliver)
        self._send_settings()
        self._thread = threading.Thread(target=self._read_loop, daemon=True,
                                        name="tpurpc-h2-reader")
        self._thread.start()

    # -- writing -------------------------------------------------------------

    def _write(self, segs: List[bytes]) -> None:
        with self._write_lock:
            self.endpoint.write(segs)

    def _send_settings(self) -> None:
        settings = {
            h2.SETTINGS_MAX_CONCURRENT_STREAMS: 1024,
            h2.SETTINGS_INITIAL_WINDOW_SIZE: RECV_WINDOW,
            h2.SETTINGS_MAX_FRAME_SIZE: h2.DEFAULT_MAX_FRAME,
        }
        if self.rdv is not None:
            settings[h2.SETTINGS_TPURPC_RDV] = 1
        self._write(h2.pack_settings(settings))
        # lift the connection-level receive window too
        self._write(h2.pack_window_update(0, RECV_WINDOW - h2.DEFAULT_WINDOW))

    def _header_block_segs(self, sid: int, block: bytes,
                           end_stream: bool) -> List[bytes]:
        """One logical header block as HEADERS (+ CONTINUATIONs when the
        encoded block exceeds the peer's SETTINGS_MAX_FRAME_SIZE — e.g. a large
        trailing ``-bin`` metadata blob), returned as gather segments.
        END_HEADERS only on the last fragment; an oversized single frame is a
        FRAME_SIZE_ERROR that kills the whole connection on a compliant peer
        (RFC 7540 §4.2)."""
        limit = self._peer_max_frame
        es = h2.FLAG_END_STREAM if end_stream else 0
        frags = [block[i:i + limit] for i in range(0, len(block), limit)] or [b""]
        segs: List[bytes] = []
        for i, frag in enumerate(frags):
            ftype = h2.HEADERS if i == 0 else h2.CONTINUATION
            flags = es if ftype == h2.HEADERS else 0
            if i == len(frags) - 1:
                flags |= h2.FLAG_END_HEADERS
            segs.extend(h2.pack_frame(ftype, flags, sid, frag))
        return segs

    def _send_header_block(self, sid: int, block: bytes,
                           end_stream: bool) -> None:
        # one gather write: CONTINUATIONs must be contiguous on the wire
        self._write(self._header_block_segs(sid, block, end_stream))

    def _response_header_segs(self, st: _H2Stream,
                              metadata: Metadata = ()) -> List[bytes]:
        """Initial-metadata HEADERS segments (marks them sent), or [] when
        already sent — the building block send paths gather into one write."""
        if st.headers_sent:
            return []
        st.headers_sent = True
        hdrs = [(":status", "200"), ("content-type", "application/grpc"),
                ("grpc-accept-encoding", "identity,gzip,deflate")]
        for k, v in metadata:
            hdrs.append((k.lower(), _encode_metadata_value(k.lower(), v)))
        return self._header_block_segs(st.stream_id,
                                       self._encoder.encode(hdrs),
                                       end_stream=False)

    def send_response_headers(self, st: _H2Stream, metadata: Metadata = ()) -> None:
        segs = self._response_header_segs(st, metadata)
        if segs:
            self._write(segs)

    # -- rendezvous plumbing (tpurpc-express) ---------------------------------

    def _rdv_send_op(self, op: int, stream_id: int, payload: bytes) -> None:
        self._write(h2.pack_frame(h2.TPURPC_RDV, op, stream_id, payload))

    def _rdv_deliver(self, stream_id: int, flags: int, body) -> None:
        """A completed rendezvous request payload: the stream's next gRPC
        message, bypassing DATA reassembly and flow control entirely
        (flags bit 0 = the sender half-closed with this message)."""
        with self._lock:
            st = self._streams.get(stream_id)
        if st is None:
            return
        st.requests.put(body)
        if flags & 0x01:
            st.half_closed = True
            st.requests.put(_H2Stream._END)

    def send_message(self, st: _H2Stream, payload) -> None:
        rdv = self.rdv
        if rdv is not None:
            segs = ([memoryview(s).cast("B") for s in payload]
                    if isinstance(payload, (list, tuple)) else
                    [memoryview(payload).cast("B")])
            segs = [s for s in segs if len(s)]
            total = sum(len(s) for s in segs)
            if rdv.eligible(total) and rdv.send_message(
                    st.stream_id, 0, segs, total):
                return  # one-sided write done; COMPLETE frame already sent
        mv = memoryview(_frame_grpc_message(payload))
        pos = 0
        while pos < len(mv):
            want = min(len(mv) - pos, self._peer_max_frame)
            if st.window._value <= 0 or self._conn_window._value <= 0:
                # about to block on peer credit: the h2-flow-control stall
                # evidence the watchdog attributes from (edge-ish: once per
                # starved chunk, not per healthy frame)
                _flight.emit(_flight.H2_WINDOW_EXHAUSTED, self._ftag,
                             st.stream_id)
            got = st.window.take(want, timeout=120)
            try:
                conn_got = self._conn_window.take(got, timeout=120)
            except Exception:
                # conn-window take failed after the stream-window reservation:
                # grant the reserved bytes back or they leak forever, then
                # surface a status instead of dying trailers-less (a
                # TimeoutError here is a peer that stopped granting credit).
                st.window.grant(got)
                raise AbortError(StatusCode.UNAVAILABLE,
                                 "flow-control stalled: peer stopped granting "
                                 "window credit") from None
            if conn_got < got:  # return the stream window over-reservation
                st.window.grant(got - conn_got)
                got = conn_got
            # the chunk view passes through to the gather write unmaterialized
            self._write(h2.pack_frame(h2.DATA, 0, st.stream_id,
                                      mv[pos:pos + got]))
            pos += got

    def _trailer_segs(self, st: _H2Stream, code: StatusCode, details: str,
                      metadata: Metadata = ()) -> List[bytes]:
        hdrs = [("grpc-status", str(int(code)))]
        if details:
            hdrs.append(("grpc-message", _pct_encode(details)))
        for k, v in metadata:
            hdrs.append((k.lower(), _encode_metadata_value(k.lower(), v)))
        return self._header_block_segs(st.stream_id,
                                       self._encoder.encode(hdrs),
                                       end_stream=True)

    def send_trailers(self, st: _H2Stream, code: StatusCode, details: str,
                      metadata: Metadata = ()) -> None:
        # initial metadata (when still unsent) and trailers gather into ONE
        # endpoint write — trailers-only responses cost a single syscall
        st.final_code = code
        segs = self._response_header_segs(st)
        segs += self._trailer_segs(st, code, details, metadata)
        self._write(segs)

    def _send_unary_fused(self, st: _H2Stream, payload, code: StatusCode,
                          details: str, metadata: Metadata = ()) -> bool:
        """The unary fast path: initial metadata + the whole response message
        + trailers in ONE gather write, when the message fits a single DATA
        frame and both flow-control windows can reserve it without blocking.
        Returns False (nothing written) to use the chunked blocking path."""
        data = _frame_grpc_message(payload)
        if len(data) > self._peer_max_frame or st.window is None:
            return False
        if not st.window.try_take(len(data)):
            return False
        if not self._conn_window.try_take(len(data)):
            st.window.grant(len(data))
            return False
        st.final_code = code
        segs = self._response_header_segs(st)
        segs += h2.pack_frame(h2.DATA, 0, st.stream_id, data)
        segs += self._trailer_segs(st, code, details, metadata)
        self._write(segs)
        return True

    # -- reading -------------------------------------------------------------

    def _read_loop(self) -> None:
        if self.rdv is not None:
            # big responses from inline/reader-thread contexts must never
            # park here waiting for a CLAIM this thread would deliver
            self.rdv.disallowed_thread = threading.get_ident()
        scratch = bytearray(1 << 16)
        mv = memoryview(scratch)
        try:
            while True:
                if self._preface_left > 0:
                    n = self.endpoint.read_into(mv[:self._preface_left])
                    if n == 0:
                        return
                    self._preface_left -= n
                    continue
                frames = self._scanner.next_frames()
                if not frames:
                    n = self.endpoint.read_into(mv)
                    if n == 0:
                        return
                    self._scanner.feed(mv[:n])
                    continue
                self._dispatch_burst(frames)
        except (EndpointError, h2.H2Error, HpackError, OSError) as exc:
            _log.debug("h2 connection error: %s", exc)
        finally:
            self._shutdown()

    def _dispatch_burst(self, frames) -> None:
        """Dispatch one transport read's worth of frames, coalescing runs of
        consecutive DATA frames on the same stream into a single payload
        span (one ``_on_data`` — one window-update write and one gRPC
        reassembly pass — per run instead of per frame)."""
        i = 0
        n = len(frames)
        while i < n:
            ftype, flags, sid, payload = frames[i]
            if ftype != h2.DATA or self._headers_frag is not None:
                self._dispatch(ftype, flags, sid, payload)
                i += 1
                continue
            datas = [h2.strip_padding(flags, payload, has_priority=False)]
            consumed = len(payload)
            last_flags = flags
            j = i + 1
            while (j < n and not last_flags & h2.FLAG_END_STREAM):
                ft2, fl2, sid2, pl2 = frames[j]
                if ft2 != h2.DATA or sid2 != sid:
                    break
                datas.append(h2.strip_padding(fl2, pl2, has_priority=False))
                consumed += len(pl2)
                last_flags = fl2
                j += 1
            if j - i > 1:
                _stats.batch_hist("h2_data_coalesce").record(j - i)
            # the run's payloads pass through as a segment list — _on_data
            # appends each to the reassembly buffer (no join copy)
            self._on_data(sid, last_flags,
                          datas if len(datas) > 1 else datas[0],
                          consumed)
            i = j

    def _dispatch(self, ftype: int, flags: int, sid: int, payload: bytes) -> None:
        if self._headers_frag is not None and ftype != h2.CONTINUATION:
            raise h2.H2Error("expected CONTINUATION")
        if ftype == h2.SETTINGS:
            if flags & h2.FLAG_ACK:
                return
            settings = h2.parse_settings(payload)
            h2.validate_settings(settings)  # RFC 7540 §6.5.2 ranges
            with self._write_lock:
                # Process-all-then-ACK in ONE write-lock hold (the server
                # mirror of the h2_client SETTINGS-ACK race): a peer may
                # keep enforcing its pre-settings limits until our ACK
                # arrives, and every response write takes _write_lock, so
                # a handler thread that observed an enlarged max-frame /
                # window can only reach the socket behind the ACK queued
                # here.
                if h2.SETTINGS_MAX_FRAME_SIZE in settings:
                    self._peer_max_frame = settings[
                        h2.SETTINGS_MAX_FRAME_SIZE]
                if h2.SETTINGS_INITIAL_WINDOW_SIZE in settings:
                    new = settings[h2.SETTINGS_INITIAL_WINDOW_SIZE]
                    delta = new - self._peer_initial_window
                    self._peer_initial_window = new
                    with self._lock:
                        for st in self._streams.values():
                            st.window.adjust(delta)
                self.endpoint.write(h2.pack_settings({}, ack=True))
            if settings.get(h2.SETTINGS_TPURPC_RDV) and self.rdv is not None:
                self.rdv.on_peer_hello()
        elif ftype == h2.PING:
            if not flags & h2.FLAG_ACK:
                self._write(h2.pack_frame(h2.PING, h2.FLAG_ACK, 0, payload))
        elif ftype == h2.WINDOW_UPDATE:
            inc = int.from_bytes(payload[:4], "big") & 0x7FFFFFFF
            if sid == 0:
                self._conn_window.grant(inc)
            else:
                with self._lock:
                    st = self._streams.get(sid)
                if st is not None:
                    st.window.grant(inc)
        elif ftype == h2.HEADERS:
            block = h2.strip_padding(flags, payload, has_priority=True)
            if flags & h2.FLAG_END_HEADERS:
                self._on_headers(sid, block, bool(flags & h2.FLAG_END_STREAM))
            else:
                self._headers_frag = (sid, flags, bytearray(block))
        elif ftype == h2.CONTINUATION:
            if self._headers_frag is None or self._headers_frag[0] != sid:
                raise h2.H2Error("unexpected CONTINUATION")
            fsid, fflags, buf = self._headers_frag
            buf += payload
            if flags & h2.FLAG_END_HEADERS:
                self._headers_frag = None
                self._on_headers(fsid, bytes(buf),
                                 bool(fflags & h2.FLAG_END_STREAM))
        elif ftype == h2.DATA:
            self._on_data(sid, flags,
                          h2.strip_padding(flags, payload, has_priority=False),
                          len(payload))
        elif ftype == h2.RST_STREAM:
            with self._lock:
                st = self._streams.pop(sid, None)
            if st is not None:
                st.cancelled.set()
                st.requests.put(_H2Stream._END)
        elif ftype == h2.TPURPC_RDV:
            if self.rdv is not None:  # never sent un-negotiated
                self.rdv.on_op(flags, sid, payload)
        elif ftype == h2.GOAWAY:
            raise h2.H2Error("client sent GOAWAY")
        # PRIORITY / PUSH_PROMISE / unknown: ignore

    def _on_headers(self, sid: int, block: bytes, end_stream: bool) -> None:
        headers = self._decoder.decode(block)
        with self._lock:
            existing = self._streams.get(sid)
        if existing is not None:  # client trailers — treat as half-close
            existing.half_closed = True
            existing.requests.put(_H2Stream._END)
            return
        pseudo = {}
        metadata: List[Tuple[str, object]] = []
        timeout_s: Optional[float] = None
        encoding = "identity"
        trace_raw: Optional[bytes] = None
        for name_b, value_b in headers:
            name = name_b.decode("ascii", "replace")
            if name.startswith(":"):
                pseudo[name] = value_b.decode("ascii", "replace")
            elif name == "grpc-timeout":
                timeout_s = _parse_timeout(value_b.decode("ascii", "replace"))
            elif name == "grpc-encoding":
                encoding = value_b.decode("ascii", "replace")
            elif name == _tracing.HEADER:
                # transport-internal like te/content-type: consumed here,
                # never surfaced to handlers
                trace_raw = value_b
            elif name in ("te", "content-type", "user-agent",
                          "grpc-accept-encoding", "accept-encoding"):
                pass  # transport-level, not surfaced as metadata (grpcio parity)
            else:
                metadata.append((name, _decode_metadata_value(name, value_b)))
        path = pseudo.get(":path", "")
        st = _H2Stream(sid)
        st.recv_encoding = encoding
        if trace_raw is not None and _tracing.LIVE:
            st.trace_ctx = _tracing.adopt(trace_raw)
        st.window = h2.FlowWindow(self._peer_initial_window)
        with self._lock:
            self._streams[sid] = st
        if end_stream:
            st.half_closed = True
            st.requests.put(_H2Stream._END)
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        handler = self.server._lookup_intercepted(path, metadata)
        if handler is None:
            self.send_trailers(st, StatusCode.UNIMPLEMENTED,
                               f"unknown method {path}")
            self._finish(st)
            return
        ctx = H2ServerContext(self, st, metadata, deadline)
        try:
            self.server._pool.submit(self._run_handler, handler, st, ctx, path)
        except RuntimeError:  # pool shut down: server is stopping
            self.send_trailers(st, StatusCode.UNAVAILABLE,
                               "server shutting down")
            self._finish(st)
            # Same contract as the native framing path: a connection whose
            # server cannot run handlers kills itself so clients redial.
            self.close()

    def _on_data(self, sid: int, flags: int, data,
                 consumed: int) -> None:
        """``data`` is the padding-stripped payload — one bytes-like, or a
        LIST of them for a coalesced run of DATA frames; ``consumed`` the
        flow-control bytes the run occupied on the wire (RFC 7540 §6.9
        counts padding)."""
        with self._lock:
            st = self._streams.get(sid)
        # flow control: grant back what we consumed, always (even on unknown
        # streams — the bytes crossed the connection window regardless).
        # Both grants ride ONE endpoint write.
        if consumed:
            segs = h2.pack_window_update(0, consumed)
            if st is not None:
                segs = segs + h2.pack_window_update(sid, consumed)
            self._write(segs)
        if st is None:
            return
        if isinstance(data, list):
            for d in data:
                st.partial += d
        else:
            st.partial += data
        while True:
            if len(st.partial) < _GRPC_MSG_HDR.size:
                break
            compressed, length = _GRPC_MSG_HDR.unpack_from(st.partial)
            if len(st.partial) < _GRPC_MSG_HDR.size + length:
                break
            # one copy out of the reassembly buffer via a released view —
            # bytes(partial[a:b]) would slice-copy and then copy again
            mv = memoryview(st.partial)
            msg = mv[_GRPC_MSG_HDR.size:_GRPC_MSG_HDR.size + length].tobytes()
            mv.release()
            del st.partial[:_GRPC_MSG_HDR.size + length]
            msg, err = decode_grpc_message(msg, compressed, st.recv_encoding)
            if err is not None:
                self.send_trailers(st, err[0], err[1])
                self._finish(st)
                return
            st.requests.put(msg)
        if flags & h2.FLAG_END_STREAM:
            st.half_closed = True
            st.requests.put(_H2Stream._END)

    # -- handler execution ----------------------------------------------------

    def _request_iterator(self, st: _H2Stream, deserializer, ctx):
        while True:
            # Deadline applies while awaiting the next client message too: a
            # client that stalls without half-closing must not pin a worker
            # past grpc-timeout (grpcio cancels the call at deadline).
            try:
                item = st.requests.get(timeout=ctx.deadline_remaining())
            except queue.Empty:
                ctx.cancel()
                raise AbortError(StatusCode.DEADLINE_EXCEEDED,
                                 "deadline exceeded awaiting request") from None
            if item is _H2Stream._END:
                return
            if not ctx.is_active():
                return
            yield deserializer(item)

    def _run_handler(self, handler, st: _H2Stream, ctx: H2ServerContext,
                     path: str) -> None:
        from tpurpc.obs import watchdog as _watchdog

        counters = self.server.call_counters
        counters.on_start()
        ok = False
        tctx = st.trace_ctx
        wd_tok = _watchdog.call_started(
            path, tctx.trace_id if tctx is not None else 0)
        t0 = time.monotonic_ns()
        try:
            with _tracing.use(tctx) if tctx is not None \
                    else _tracing.NULL_CM:
                with (_tracing.span("dispatch", tctx, method=path)
                      if tctx is not None else _tracing.NULL_CM):
                    ok = bool(self._run_handler_inner(handler, st, ctx, path))
        finally:
            counters.on_finish(ok)
            code = st.final_code if st.final_code is not None \
                else StatusCode.CANCELLED
            _SRV_CALLS.labels(path, int(code)).inc()
            _watchdog.call_finished(wd_tok, error=not ok)
            _tracing.tail_decide(tctx, time.monotonic_ns() - t0,
                                 error=not ok, method=path)

    def _run_handler_inner(self, handler, st: _H2Stream,
                           ctx: H2ServerContext, path: str):
        try:
            if handler.request_streaming:
                request_in = self._request_iterator(
                    st, handler.request_deserializer, ctx)
            else:
                try:
                    item = st.requests.get(timeout=ctx.deadline_remaining())
                except queue.Empty:
                    self.send_trailers(st, StatusCode.DEADLINE_EXCEEDED,
                                       "deadline exceeded awaiting request")
                    return
                if item is _H2Stream._END or not ctx.is_active():
                    if ctx.is_active():
                        self.send_trailers(
                            st, StatusCode.INVALID_ARGUMENT,
                            "client half-closed before sending a request")
                    return
                request_in = handler.request_deserializer(item)

            result = handler.behavior(request_in, ctx)

            if handler.response_streaming:
                self.send_response_headers(st)
                for response in result:
                    if not ctx.is_active():
                        return
                    if ctx._deadline_exceeded():
                        self.send_trailers(st, StatusCode.DEADLINE_EXCEEDED,
                                           "deadline exceeded", ctx._trailing)
                        return
                    self.send_message(st, handler.response_serializer(response))
            elif ctx.is_active():
                # unary: headers + message + trailers fuse into one endpoint
                # write when windows allow (the h2 mirror of the native
                # framing's send_many fast path); else the chunked path below
                code = ctx._code if ctx._code is not None else StatusCode.OK
                payload = handler.response_serializer(result)
                if self._send_unary_fused(st, payload, code, ctx._details,
                                          ctx._trailing):
                    return code is StatusCode.OK
                self.send_response_headers(st)
                self.send_message(st, payload)
            else:
                self.send_response_headers(st)
            if ctx.is_active():
                code = ctx._code if ctx._code is not None else StatusCode.OK
                self.send_trailers(st, code, ctx._details, ctx._trailing)
                return code is StatusCode.OK
        except AbortError as exc:
            self.send_trailers(st, exc.code, exc.details, ctx._trailing)
        except (EndpointError, h2.H2Error, OSError):
            pass  # connection gone
        except Exception as exc:  # handler bug → UNKNOWN, like grpcio
            _log.exception("h2 handler for %s raised", path)
            self.send_trailers(st, StatusCode.UNKNOWN,
                               f"Exception calling application: {exc}")
        finally:
            self._finish(st)
        return False

    def _finish(self, st: _H2Stream) -> None:
        with self._lock:
            self._streams.pop(st.stream_id, None)

    def _shutdown(self) -> None:
        with self._lock:
            if not self.alive:
                return
            self.alive = False
            streams = list(self._streams.values())
            self._streams.clear()
        if self.rdv is not None:
            self.rdv.close()  # peer gone: claimed landing regions release
        self._conn_window.kill()
        for st in streams:
            st.cancelled.set()
            st.window.kill()
            st.requests.put(_H2Stream._END)
        try:
            self.endpoint.close()
        except Exception:
            pass
        self.server._forget(self)

    def close(self) -> None:
        try:
            self.endpoint.close()
        except Exception:
            pass
