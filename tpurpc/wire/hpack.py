"""HPACK (RFC 7541) — header compression for the gRPC wire-compat path.

The reference carries this in ``chttp2/transport/hpack_{parser,encoder,
table}.cc`` (SURVEY.md §2.4); this is a from-scratch implementation of the
spec, not a port: the decoder handles every field representation (indexed,
literal ±indexing, never-indexed, table-size update), huffman-coded strings,
and the dynamic table with eviction; the encoder is the minimal legal one —
literal-without-indexing with raw strings for unknown headers, indexed
fields for static-table hits — stateless by design so a lost frame can never
desynchronize two ends' dynamic tables.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, List, Optional, Tuple

from tpurpc.wire.rfc7541_tables import HUFFMAN_CODES, STATIC_TABLE

Header = Tuple[bytes, bytes]


class HpackError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Huffman coding (Appendix B)
# ---------------------------------------------------------------------------

def _build_tree():
    # binary trie: internal node = [zero_branch, one_branch]; leaf = symbol int
    root: list = [None, None]
    for sym, (code, nbits) in enumerate(HUFFMAN_CODES):
        node = root
        for i in range(nbits - 1, -1, -1):
            bit = (code >> i) & 1
            if i == 0:
                node[bit] = sym
            else:
                if node[bit] is None:
                    node[bit] = [None, None]
                node = node[bit]
    return root


_TREE = _build_tree()
_EOS = 256


def huffman_decode(data: bytes) -> bytes:
    out = bytearray()
    node = _TREE
    depth = 0  # bits consumed since last symbol (for padding validation)
    ones = True  # padding must be a prefix of EOS == all ones
    for byte in data:
        for i in range(7, -1, -1):
            bit = (byte >> i) & 1
            nxt = node[bit]
            depth += 1
            ones = ones and bit == 1
            if nxt is None:
                raise HpackError("invalid huffman code")
            if isinstance(nxt, int):
                if nxt == _EOS:
                    raise HpackError("EOS in huffman string")
                out.append(nxt)
                node = _TREE
                depth = 0
                ones = True
            else:
                node = nxt
    if depth > 7 or not ones:
        raise HpackError("bad huffman padding")
    return bytes(out)


def huffman_encode(data: bytes) -> bytes:
    acc = 0
    nbits = 0
    out = bytearray()
    for b in data:
        code, n = HUFFMAN_CODES[b]
        acc = (acc << n) | code
        nbits += n
        while nbits >= 8:
            nbits -= 8
            out.append((acc >> nbits) & 0xFF)
    if nbits:
        pad = 8 - nbits
        out.append(((acc << pad) | ((1 << pad) - 1)) & 0xFF)
    return bytes(out)


# ---------------------------------------------------------------------------
# Primitive codecs (§5)
# ---------------------------------------------------------------------------

def encode_int(value: int, prefix_bits: int, first_byte_flags: int = 0) -> bytes:
    limit = (1 << prefix_bits) - 1
    if value < limit:
        return bytes([first_byte_flags | value])
    out = bytearray([first_byte_flags | limit])
    value -= limit
    while value >= 0x80:
        out.append(0x80 | (value & 0x7F))
        value >>= 7
    out.append(value)
    return bytes(out)


def decode_int(data, pos: int, prefix_bits: int) -> Tuple[int, int]:
    limit = (1 << prefix_bits) - 1
    if pos >= len(data):
        raise HpackError("truncated integer")
    value = data[pos] & limit
    pos += 1
    if value < limit:
        return value, pos
    shift = 0
    while True:
        if pos >= len(data):
            raise HpackError("truncated integer continuation")
        b = data[pos]
        pos += 1
        value += (b & 0x7F) << shift
        shift += 7
        if shift > 35:
            raise HpackError("integer overflow")
        if not b & 0x80:
            return value, pos


def decode_string(data, pos: int) -> Tuple[bytes, int]:
    if pos >= len(data):
        raise HpackError("truncated string")
    huff = bool(data[pos] & 0x80)
    length, pos = decode_int(data, pos, 7)
    if pos + length > len(data):
        raise HpackError("truncated string payload")
    raw = bytes(data[pos:pos + length])
    return (huffman_decode(raw) if huff else raw), pos + length


def encode_string(data: bytes) -> bytes:
    return encode_int(len(data), 7, 0x00) + data


# ---------------------------------------------------------------------------
# Tables (§2.3)
# ---------------------------------------------------------------------------

_STATIC: List[Header] = [
    (n.encode() if n else None, v.encode() if v is not None else b"")
    for n, v in STATIC_TABLE
]
_STATIC_LOOKUP = {}
for _i in range(1, len(_STATIC)):
    _n, _v = _STATIC[_i]
    _STATIC_LOOKUP.setdefault((_n, _v), _i)

_ENTRY_OVERHEAD = 32


class _DynamicTable:
    def __init__(self, max_size: int = 4096, lookup: bool = False):
        self.entries: Deque[Header] = deque()  # most recent first
        self.size = 0
        self.max_size = max_size
        self.cap = max_size  # protocol ceiling (SETTINGS_HEADER_TABLE_SIZE)
        #: encoder-side O(1) reverse lookups: (n, v)/name → absolute add id.
        #: Positions shift on every add, so we store a monotone id instead
        #: and convert at lookup time; evicted ids resolve out of range.
        self._lookup = lookup
        self._abs = 0
        self._by_pair: dict = {}
        self._by_name: dict = {}

    def add(self, name: bytes, value: bytes) -> None:
        need = len(name) + len(value) + _ENTRY_OVERHEAD
        while self.entries and self.size + need > self.max_size:
            evicted_abs = self._abs - len(self.entries)  # oldest entry's id
            n, v = self.entries.pop()
            self.size -= len(n) + len(v) + _ENTRY_OVERHEAD
            if self._lookup:
                # Purge exactly-matching ids so the reverse maps can't grow
                # unboundedly on never-repeated header values (a newer add
                # of the same pair/name keeps its newer id).
                if self._by_pair.get((n, v)) == evicted_abs:
                    del self._by_pair[(n, v)]
                if self._by_name.get(n) == evicted_abs:
                    del self._by_name[n]
        if need <= self.max_size:
            self.entries.appendleft((name, value))
            self.size += need
            if self._lookup:
                self._by_pair[(name, value)] = self._abs
                self._by_name[name] = self._abs
        # else: entry larger than table — spec says result is an empty table
        self._abs += 1  # ids advance even for too-large adds (position math)

    def _abs_to_index(self, abs_id: int) -> Optional[int]:
        """Wire index (1-based) for an absolute add id, or None if evicted."""
        pos = self._abs - 1 - abs_id
        if 0 <= pos < len(self.entries):
            return len(_STATIC) + pos
        return None

    def find(self, name: bytes, value: bytes) -> Optional[int]:
        abs_id = self._by_pair.get((name, value))
        if abs_id is None:
            return None
        idx = self._abs_to_index(abs_id)
        if idx is None:
            del self._by_pair[(name, value)]
        return idx

    def find_name(self, name: bytes) -> Optional[int]:
        abs_id = self._by_name.get(name)
        if abs_id is None:
            return None
        idx = self._abs_to_index(abs_id)
        if idx is None:
            del self._by_name[name]
        return idx

    def resize(self, new_max: int) -> None:
        if new_max > self.cap:
            raise HpackError(f"table size {new_max} above ceiling {self.cap}")
        self.max_size = new_max
        while self.entries and self.size > self.max_size:
            n, v = self.entries.pop()
            self.size -= len(n) + len(v) + _ENTRY_OVERHEAD

    def get(self, index: int) -> Header:
        # index is 1-based; 1..61 static, 62.. dynamic
        if 1 <= index < len(_STATIC):
            return _STATIC[index]
        didx = index - len(_STATIC)
        if 0 <= didx < len(self.entries):
            return self.entries[didx]
        raise HpackError(f"index {index} out of range")


# ---------------------------------------------------------------------------
# Decoder / Encoder
# ---------------------------------------------------------------------------

class HpackDecoder:
    def __init__(self, max_table_size: int = 4096):
        self._table = _DynamicTable(max_table_size)

    def decode(self, block) -> List[Header]:
        data = bytes(block)
        pos = 0
        out: List[Header] = []
        while pos < len(data):
            b = data[pos]
            if b & 0x80:  # indexed field
                idx, pos = decode_int(data, pos, 7)
                if idx == 0:
                    raise HpackError("indexed field with index 0")
                out.append(self._table.get(idx))
            elif b & 0x40:  # literal with incremental indexing
                idx, pos = decode_int(data, pos, 6)
                name = (self._table.get(idx)[0] if idx
                        else None)
                if name is None:
                    name, pos = decode_string(data, pos)
                value, pos = decode_string(data, pos)
                self._table.add(name, value)
                out.append((name, value))
            elif b & 0x20:  # dynamic table size update
                new_max, pos = decode_int(data, pos, 5)
                self._table.resize(new_max)
            else:  # literal without indexing (0x00) / never indexed (0x10)
                idx, pos = decode_int(data, pos, 4)
                name = self._table.get(idx)[0] if idx else None
                if name is None:
                    name, pos = decode_string(data, pos)
                value, pos = decode_string(data, pos)
                out.append((name, value))
        return out


#: static name → first index with that name (name-only reference)
_STATIC_NAME_LOOKUP: dict = {}
for _i in range(len(_STATIC) - 1, 0, -1):
    _STATIC_NAME_LOOKUP[_STATIC[_i][0]] = _i


class HpackEncoder:
    """HPACK encoder with an optional dynamic table (RFC 7541 §2.3.2).

    ``dynamic=False`` (the server's response path) stays stateless: static
    hits as indexed fields, everything else literal-without-indexing.

    ``dynamic=True`` (the client path, where :path/:authority/user metadata
    repeat on every call) inserts repeatable headers with incremental
    indexing and emits 1-2 byte indexed fields on subsequent calls. The
    encoder's table mirrors exactly what its own emissions tell the peer's
    decoder to do, so it can never desynchronize.

    Indexing starts DISABLED even with ``dynamic=True``: until the peer's
    SETTINGS arrive the peer's actual table ceiling is unknown (it need not
    be the 4096 default — a 0-size decoder would silently drop our inserts
    and desync on the first indexed reference). Call
    :meth:`apply_peer_table_size` when SETTINGS are processed: it sizes the
    table to ``min(4096, peer)``, queues the RFC 7541 §4.2 dynamic-table
    size update for the front of the next header block when shrinking, and
    enables indexing."""

    #: headers that change per-call and would churn the table
    _NEVER_INDEX = {b"grpc-timeout", b"content-length", b"date"}

    def __init__(self, dynamic: bool = False, max_table_size: int = 4096):
        self._dynamic = dynamic
        self._table = (_DynamicTable(max_table_size, lookup=True)
                       if dynamic else None)
        self._index_enabled = False
        self._pending_size_update: Optional[int] = None

    def apply_peer_table_size(self, peer_max: int) -> None:
        """Peer's SETTINGS_HEADER_TABLE_SIZE processed: enable indexing at
        ``min(default, peer_max)``, emitting the mandated size update at the
        start of the next block when that shrinks our declared size."""
        if self._table is None:
            return
        new = min(4096, peer_max)
        if new < self._table.max_size:
            self._table.cap = new
            self._table.resize(new)
            self._pending_size_update = new
        self._index_enabled = new > 0

    def encode(self, headers) -> bytes:
        out = bytearray()
        if self._pending_size_update is not None:
            out += encode_int(self._pending_size_update, 5, 0x20)
            self._pending_size_update = None
        table = self._table
        for name, value in headers:
            n = name.encode() if isinstance(name, str) else bytes(name)
            v = value.encode() if isinstance(value, str) else bytes(value)
            idx = _STATIC_LOOKUP.get((n, v))
            if idx is not None:
                out += encode_int(idx, 7, 0x80)
                continue
            if table is not None:
                idx = table.find(n, v)
                if idx is not None:
                    out += encode_int(idx, 7, 0x80)
                    continue
            name_idx = _STATIC_NAME_LOOKUP.get(n)
            if name_idx is None and table is not None:
                name_idx = table.find_name(n)
            if self._index_enabled and n not in self._NEVER_INDEX:
                # literal WITH incremental indexing: the peer's decoder adds
                # it; we mirror the add so future lookups hit
                out += encode_int(name_idx or 0, 6, 0x40)
                if name_idx is None:
                    out += encode_string(n)
                out += encode_string(v)
                table.add(n, v)
            else:
                out += encode_int(name_idx or 0, 4, 0x00)
                if name_idx is None:
                    out += encode_string(n)
                out += encode_string(v)
        return bytes(out)
