"""HPACK (RFC 7541) — header compression for the gRPC wire-compat path.

The reference carries this in ``chttp2/transport/hpack_{parser,encoder,
table}.cc`` (SURVEY.md §2.4); this is a from-scratch implementation of the
spec, not a port: the decoder handles every field representation (indexed,
literal ±indexing, never-indexed, table-size update), huffman-coded strings,
and the dynamic table with eviction; the encoder is the minimal legal one —
literal-without-indexing with raw strings for unknown headers, indexed
fields for static-table hits — stateless by design so a lost frame can never
desynchronize two ends' dynamic tables.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, List, Tuple

from tpurpc.wire.rfc7541_tables import HUFFMAN_CODES, STATIC_TABLE

Header = Tuple[bytes, bytes]


class HpackError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Huffman coding (Appendix B)
# ---------------------------------------------------------------------------

def _build_tree():
    # binary trie: internal node = [zero_branch, one_branch]; leaf = symbol int
    root: list = [None, None]
    for sym, (code, nbits) in enumerate(HUFFMAN_CODES):
        node = root
        for i in range(nbits - 1, -1, -1):
            bit = (code >> i) & 1
            if i == 0:
                node[bit] = sym
            else:
                if node[bit] is None:
                    node[bit] = [None, None]
                node = node[bit]
    return root


_TREE = _build_tree()
_EOS = 256


def huffman_decode(data: bytes) -> bytes:
    out = bytearray()
    node = _TREE
    depth = 0  # bits consumed since last symbol (for padding validation)
    ones = True  # padding must be a prefix of EOS == all ones
    for byte in data:
        for i in range(7, -1, -1):
            bit = (byte >> i) & 1
            nxt = node[bit]
            depth += 1
            ones = ones and bit == 1
            if nxt is None:
                raise HpackError("invalid huffman code")
            if isinstance(nxt, int):
                if nxt == _EOS:
                    raise HpackError("EOS in huffman string")
                out.append(nxt)
                node = _TREE
                depth = 0
                ones = True
            else:
                node = nxt
    if depth > 7 or not ones:
        raise HpackError("bad huffman padding")
    return bytes(out)


def huffman_encode(data: bytes) -> bytes:
    acc = 0
    nbits = 0
    out = bytearray()
    for b in data:
        code, n = HUFFMAN_CODES[b]
        acc = (acc << n) | code
        nbits += n
        while nbits >= 8:
            nbits -= 8
            out.append((acc >> nbits) & 0xFF)
    if nbits:
        pad = 8 - nbits
        out.append(((acc << pad) | ((1 << pad) - 1)) & 0xFF)
    return bytes(out)


# ---------------------------------------------------------------------------
# Primitive codecs (§5)
# ---------------------------------------------------------------------------

def encode_int(value: int, prefix_bits: int, first_byte_flags: int = 0) -> bytes:
    limit = (1 << prefix_bits) - 1
    if value < limit:
        return bytes([first_byte_flags | value])
    out = bytearray([first_byte_flags | limit])
    value -= limit
    while value >= 0x80:
        out.append(0x80 | (value & 0x7F))
        value >>= 7
    out.append(value)
    return bytes(out)


def decode_int(data, pos: int, prefix_bits: int) -> Tuple[int, int]:
    limit = (1 << prefix_bits) - 1
    if pos >= len(data):
        raise HpackError("truncated integer")
    value = data[pos] & limit
    pos += 1
    if value < limit:
        return value, pos
    shift = 0
    while True:
        if pos >= len(data):
            raise HpackError("truncated integer continuation")
        b = data[pos]
        pos += 1
        value += (b & 0x7F) << shift
        shift += 7
        if shift > 35:
            raise HpackError("integer overflow")
        if not b & 0x80:
            return value, pos


def decode_string(data, pos: int) -> Tuple[bytes, int]:
    if pos >= len(data):
        raise HpackError("truncated string")
    huff = bool(data[pos] & 0x80)
    length, pos = decode_int(data, pos, 7)
    if pos + length > len(data):
        raise HpackError("truncated string payload")
    raw = bytes(data[pos:pos + length])
    return (huffman_decode(raw) if huff else raw), pos + length


def encode_string(data: bytes) -> bytes:
    return encode_int(len(data), 7, 0x00) + data


# ---------------------------------------------------------------------------
# Tables (§2.3)
# ---------------------------------------------------------------------------

_STATIC: List[Header] = [
    (n.encode() if n else None, v.encode() if v is not None else b"")
    for n, v in STATIC_TABLE
]
_STATIC_LOOKUP = {}
for _i in range(1, len(_STATIC)):
    _n, _v = _STATIC[_i]
    _STATIC_LOOKUP.setdefault((_n, _v), _i)

_ENTRY_OVERHEAD = 32


class _DynamicTable:
    def __init__(self, max_size: int = 4096):
        self.entries: Deque[Header] = deque()  # most recent first
        self.size = 0
        self.max_size = max_size
        self.cap = max_size  # protocol ceiling (SETTINGS_HEADER_TABLE_SIZE)

    def add(self, name: bytes, value: bytes) -> None:
        need = len(name) + len(value) + _ENTRY_OVERHEAD
        while self.entries and self.size + need > self.max_size:
            n, v = self.entries.pop()
            self.size -= len(n) + len(v) + _ENTRY_OVERHEAD
        if need <= self.max_size:
            self.entries.appendleft((name, value))
            self.size += need
        # else: entry larger than table — spec says result is an empty table

    def resize(self, new_max: int) -> None:
        if new_max > self.cap:
            raise HpackError(f"table size {new_max} above ceiling {self.cap}")
        self.max_size = new_max
        while self.entries and self.size > self.max_size:
            n, v = self.entries.pop()
            self.size -= len(n) + len(v) + _ENTRY_OVERHEAD

    def get(self, index: int) -> Header:
        # index is 1-based; 1..61 static, 62.. dynamic
        if 1 <= index < len(_STATIC):
            return _STATIC[index]
        didx = index - len(_STATIC)
        if 0 <= didx < len(self.entries):
            return self.entries[didx]
        raise HpackError(f"index {index} out of range")


# ---------------------------------------------------------------------------
# Decoder / Encoder
# ---------------------------------------------------------------------------

class HpackDecoder:
    def __init__(self, max_table_size: int = 4096):
        self._table = _DynamicTable(max_table_size)

    def decode(self, block) -> List[Header]:
        data = bytes(block)
        pos = 0
        out: List[Header] = []
        while pos < len(data):
            b = data[pos]
            if b & 0x80:  # indexed field
                idx, pos = decode_int(data, pos, 7)
                if idx == 0:
                    raise HpackError("indexed field with index 0")
                out.append(self._table.get(idx))
            elif b & 0x40:  # literal with incremental indexing
                idx, pos = decode_int(data, pos, 6)
                name = (self._table.get(idx)[0] if idx
                        else None)
                if name is None:
                    name, pos = decode_string(data, pos)
                value, pos = decode_string(data, pos)
                self._table.add(name, value)
                out.append((name, value))
            elif b & 0x20:  # dynamic table size update
                new_max, pos = decode_int(data, pos, 5)
                self._table.resize(new_max)
            else:  # literal without indexing (0x00) / never indexed (0x10)
                idx, pos = decode_int(data, pos, 4)
                name = self._table.get(idx)[0] if idx else None
                if name is None:
                    name, pos = decode_string(data, pos)
                value, pos = decode_string(data, pos)
                out.append((name, value))
        return out


class HpackEncoder:
    """Minimal legal encoder: static-table hits as indexed fields, everything
    else literal-without-indexing with raw strings. Deliberately stateless
    (no dynamic table) — nothing to desynchronize."""

    def encode(self, headers) -> bytes:
        out = bytearray()
        for name, value in headers:
            n = name.encode() if isinstance(name, str) else bytes(name)
            v = value.encode() if isinstance(value, str) else bytes(value)
            idx = _STATIC_LOOKUP.get((n, v))
            if idx is not None:
                out += encode_int(idx, 7, 0x80)
                continue
            name_idx = _STATIC_LOOKUP.get((n, b""))
            if name_idx is None:
                # find any static entry with this name for name-only reference
                for i in range(1, len(_STATIC)):
                    if _STATIC[i][0] == n:
                        name_idx = i
                        break
            if name_idx is not None:
                out += encode_int(name_idx, 4, 0x00)
            else:
                out += b"\x00" + encode_string(n)
            out += encode_string(v)
        return bytes(out)
