"""gRPC-over-HTTP/2 client: tpurpc calls stock gRPC servers unchanged.

The other half of the drop-in capability (the server half is
``tpurpc/wire/grpc_h2.py``): :class:`H2Channel` dials any grpc-compliant
server — grpcio, grpc++, a tpurpc server's sniffed h2 path — and exposes the
same four grpcio-shaped multicallables as :class:`tpurpc.rpc.channel.Channel`.

Protocol mapping (gRPC PROTOCOL-HTTP2 spec; reference: the chttp2 client
stack — chttp2_connector + ``ext/transport/chttp2/`` + ``surface/call.cc``,
SURVEY.md §3.2-3.3 — re-derived from the spec, not ported):

* connection preface + SETTINGS exchange, HEADERS with ``:method: POST``,
  ``:path: /Service/Method``, ``te: trailers``,
  ``content-type: application/grpc``, ``grpc-timeout``, ``-bin`` metadata as
  unpadded base64;
* requests as 5-byte length-prefixed messages in DATA frames, chunked to the
  peer's SETTINGS_MAX_FRAME_SIZE under both connection and stream send
  windows;
* responses: initial-metadata HEADERS, DATA → message reassembly,
  trailers (HEADERS+END_STREAM) carrying ``grpc-status``/``grpc-message``
  (percent-decoded), including the trailers-only form;
* HPACK with a DYNAMIC encoder table (``:path``/user metadata repeat per
  call → 1-2 byte fields after the first), sized down to the peer's
  SETTINGS_HEADER_TABLE_SIZE;
* PING ack, GOAWAY → UNAVAILABLE on open calls, RST_STREAM → status,
  aggressive receive-window grants (tensors are big).
"""

from __future__ import annotations

import base64
import logging
import queue
import socket
import struct
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

from tpurpc.core import rendezvous as _rdv
from tpurpc.core.endpoint import Endpoint, EndpointError, TcpEndpoint
from tpurpc.obs import profiler as _profiler

# tpurpc-lens (ISSUE 8): client-side h2 framing frame markers
_LENS_STAGES = {
    "_send_message": "h2-framing",
    "_on_data": "h2-framing",
    "_read_loop": "h2-framing",
    "_pump": "h2-framing",
}
_profiler.register_stages(__file__, _LENS_STAGES)
from tpurpc.obs import flight as _flight
from tpurpc.obs import metrics as _obs_metrics
from tpurpc.rpc.status import Metadata, RpcError, StatusCode
from tpurpc.utils import stats as _stats
from tpurpc.wire import h2
from tpurpc.wire.grpc_h2 import (RECV_WINDOW, _decode_metadata_value,
                                 _encode_metadata_value, decode_grpc_message)
from tpurpc.wire.hpack import HpackDecoder, HpackEncoder, HpackError

#: tpurpc-scope (ISSUE 4): live h2 client channels + their send-side
#: connection window — scrape-time reads only
_H2_CLI_CONNS = _obs_metrics.fleet("h2_client_connections")
_H2_CLI_WINDOW = _obs_metrics.fleet("h2_client_send_window_bytes",
                                    lambda c: c._conn_window._value)

_log = logging.getLogger("tpurpc.h2_client")

_GRPC_MSG_HDR = struct.Struct("!BI")


def _pct_decode(raw: str) -> str:
    out = bytearray()
    i = 0
    while i < len(raw):
        c = raw[i]
        if c == "%" and i + 2 < len(raw):
            try:
                out.append(int(raw[i + 1:i + 3], 16))
                i += 3
                continue
            except ValueError:
                pass
        out.extend(c.encode("utf-8"))
        i += 1
    return out.decode("utf-8", "replace")


def _grpc_timeout(seconds: float) -> str:
    """Largest-unit encoding that fits the spec's 8-digit cap."""
    for unit, scale in (("n", 1e9), ("u", 1e6), ("m", 1e3)):
        v = int(seconds * scale)
        if v < 1e8:
            return f"{max(v, 1)}{unit}"
    return f"{min(int(seconds), 99999999)}S"


class _H2Call:
    """Client-side per-stream state, fed by the reader thread."""

    def __init__(self, stream_id: int, deadline: Optional[float]):
        self.stream_id = stream_id
        self.deadline = deadline
        self.events: "queue.Queue[tuple]" = queue.Queue()
        self.partial = bytearray()   # gRPC message assembly across DATA
        self.recv_encoding = "identity"  # response grpc-encoding
        self.initial_md: Optional[List[Tuple[str, object]]] = None
        self.window: Optional[h2.FlowWindow] = None  # send window
        self.trailing_md: Optional[List[Tuple[str, object]]] = None
        self.code: Optional[StatusCode] = None
        self.details = ""

    # reader-thread side -----------------------------------------------------

    def feed_data(self, chunk: bytes) -> int:
        """Append DATA payload; emit completed gRPC messages. Returns the
        number of flow-control bytes consumed (== len(chunk))."""
        self.partial += chunk
        while len(self.partial) >= 5:
            compressed, length = _GRPC_MSG_HDR.unpack_from(self.partial)
            if len(self.partial) - 5 < length:
                break
            msg = bytes(self.partial[5:5 + length])
            del self.partial[:5 + length]
            msg, err = decode_grpc_message(msg, compressed,
                                           self.recv_encoding)
            if err is not None:
                self.deliver_status(err[0], err[1], [])
                return len(chunk)
            self.events.put(("message", msg))
        return len(chunk)

    def deliver_initial(self, md: List[Tuple[str, object]]) -> None:
        self.initial_md = md
        self.events.put(("initial_metadata", md))

    def deliver_status(self, code: StatusCode, details: str,
                       md: List[Tuple[str, object]]) -> None:
        # Record on the call BEFORE queueing: a sender blocked in the flow
        # window needs a non-consuming way to learn the outcome (consuming
        # the queued event would starve the response consumer).
        self.code = code
        self.details = details
        self.trailing_md = md
        self.events.put(("status", code, details, md))

    # caller side ------------------------------------------------------------

    def _remaining(self) -> Optional[float]:
        if self.deadline is None:
            return None
        return self.deadline - time.monotonic()

    def next_event(self) -> tuple:
        remain = self._remaining()
        if remain is not None and remain <= 0:
            raise RpcError(StatusCode.DEADLINE_EXCEEDED, "deadline exceeded")
        try:
            return self.events.get(timeout=remain)
        except queue.Empty:
            raise RpcError(StatusCode.DEADLINE_EXCEEDED,
                           "deadline exceeded awaiting response") from None


class H2Channel:
    """A gRPC-over-HTTP/2 client channel (one connection, multiplexed calls).

    grpcio-shaped surface: ``unary_unary`` / ``unary_stream`` /
    ``stream_unary`` / ``stream_stream`` return multicallables accepting
    ``(request, timeout=None, metadata=None)``.
    """

    def __init__(self, target: str, connect_timeout: float = 30.0,
                 authority: Optional[str] = None, credentials=None):
        host, _, port = target.rpartition(":")
        sock = socket.create_connection((host or "127.0.0.1", int(port)),
                                        timeout=connect_timeout)
        ssl_ctx = getattr(credentials, "_context", None)
        if ssl_ctx is not None:
            from tpurpc.core.endpoint import tls_client_handshake

            hostname = (getattr(credentials, "_override_hostname", None)
                        or host or "127.0.0.1")
            sock = tls_client_handshake(sock, ssl_ctx, hostname)
        sock.settimeout(None)
        self._ep: Endpoint = TcpEndpoint(sock)
        self._authority = authority or target
        self._lock = threading.Lock()
        self._wlock = threading.Lock()   # serializes writes + HPACK encoder
        self._calls: Dict[int, _H2Call] = {}
        self._next_stream = 1
        self._dead: Optional[str] = None

        self._enc = HpackEncoder(dynamic=True)
        self._dec = HpackDecoder()
        self._peer_max_frame = h2.DEFAULT_MAX_FRAME
        self._peer_initial_window = h2.DEFAULT_WINDOW
        self._conn_window = h2.FlowWindow(h2.DEFAULT_WINDOW)  # our sends
        self._settings_acked = threading.Event()
        self._ftag = _flight.tag_for("h2cli:" + str(target))
        _H2_CLI_CONNS.track(self)
        _H2_CLI_WINDOW.track(self)

        # tpurpc-express over the gRPC wire: arm the rendezvous link and
        # advertise the capability in our SETTINGS; it activates only when
        # the server's SETTINGS carry the id back (stock servers never do)
        self.rdv = _rdv.link_for_endpoint(
            self._ep, "h2cli:" + str(target),
            self._rdv_send_op, self._rdv_deliver)
        settings = {h2.SETTINGS_INITIAL_WINDOW_SIZE: RECV_WINDOW,
                    h2.SETTINGS_MAX_FRAME_SIZE: 1 << 20}
        if self.rdv is not None:
            settings[h2.SETTINGS_TPURPC_RDV] = 1
        with self._wlock:
            self._ep.write([h2.PREFACE]
                           + h2.pack_settings(settings)
                           + h2.pack_window_update(0, RECV_WINDOW))
        self._reader = threading.Thread(target=self._read_loop, daemon=True,
                                        name="tpurpc-h2c-reader")
        self._reader.start()

    # -- connection lifecycle -------------------------------------------------

    def close(self) -> None:
        self._die("channel closed", notify_peer=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _die(self, why: str, notify_peer: bool = False) -> None:
        with self._lock:
            if self._dead is not None:
                return
            self._dead = why
            calls = list(self._calls.values())
            self._calls.clear()
        if notify_peer:
            try:
                with self._wlock:
                    self._ep.write(h2.pack_goaway(0, h2.NO_ERROR))
            except (EndpointError, OSError):
                pass
        if self.rdv is not None:
            self.rdv.close()  # claimed landing regions release on death
        for call in calls:
            if call.window is not None:
                call.window.kill()
            call.deliver_status(StatusCode.UNAVAILABLE, f"connection: {why}", [])
        self._conn_window.kill()
        try:
            self._ep.close()
        except (EndpointError, OSError):
            pass

    # -- reader thread --------------------------------------------------------

    def _read_loop(self) -> None:
        if self.rdv is not None:
            self.rdv.disallowed_thread = threading.get_ident()
        scanner = h2.FrameScanner()
        hdr_accum: Optional[Tuple[int, int, bytearray]] = None  # sid, flags, block
        pending: List[Tuple[int, int, int, bytes]] = []  # burst being walked
        try:
            while True:
                if not pending:
                    pending = scanner.next_frames()
                if not pending:
                    data = self._ep.read(1 << 20)
                    if not data:
                        self._die("server closed connection")
                        return
                    scanner.feed(data)
                    continue
                ftype, flags, sid, payload = pending[0]
                if hdr_accum is not None and ftype != h2.CONTINUATION:
                    raise h2.H2Error("expected CONTINUATION")
                if ftype == h2.DATA:
                    # Coalesce the burst's run of DATA frames for this stream
                    # into ONE reassembly pass + ONE window-update write —
                    # a 4 MiB tensor response arrives as ≥256 DATA frames
                    # and per-frame dispatch was a measured hot spot.
                    datas = [h2.strip_padding(flags, payload,
                                              has_priority=False)]
                    consumed = len(payload)
                    taken = 1
                    while (taken < len(pending)
                           and not flags & h2.FLAG_END_STREAM):
                        ft2, fl2, sid2, pl2 = pending[taken]
                        if ft2 != h2.DATA or sid2 != sid:
                            break
                        datas.append(h2.strip_padding(fl2, pl2,
                                                      has_priority=False))
                        consumed += len(pl2)
                        flags = fl2
                        taken += 1
                    del pending[:taken]
                    if taken > 1:
                        _stats.batch_hist("h2_data_coalesce").record(taken)
                    self._on_data(sid, flags,
                                  b"".join(datas) if len(datas) > 1
                                  else datas[0], consumed)
                    continue
                del pending[:1]
                if ftype == h2.HEADERS:
                    block = bytearray(
                        h2.strip_padding(flags, payload, has_priority=True))
                    if flags & h2.FLAG_END_HEADERS:
                        self._on_headers(sid, flags, block)
                    else:
                        hdr_accum = (sid, flags, block)
                elif ftype == h2.CONTINUATION:
                    if hdr_accum is None or hdr_accum[0] != sid:
                        raise h2.H2Error("unexpected CONTINUATION")
                    hdr_accum[2].extend(payload)
                    if flags & h2.FLAG_END_HEADERS:
                        sid0, flags0, block = hdr_accum
                        hdr_accum = None
                        self._on_headers(sid0, flags0, block)
                elif ftype == h2.SETTINGS:
                    self._on_settings(flags, payload)
                elif ftype == h2.WINDOW_UPDATE:
                    self._on_window_update(sid, payload)
                elif ftype == h2.PING:
                    if not flags & h2.FLAG_ACK:
                        with self._wlock:
                            self._ep.write(
                                h2.pack_frame(h2.PING, h2.FLAG_ACK, 0, payload))
                elif ftype == h2.RST_STREAM:
                    (code,) = struct.unpack("!I", payload)
                    call = self._pop_call(sid)
                    if call is not None:
                        call.deliver_status(
                            StatusCode.CANCELLED if code == h2.CANCEL
                            else StatusCode.UNAVAILABLE,
                            f"stream reset by server (h2 error {code})", [])
                elif ftype == h2.TPURPC_RDV:
                    if self.rdv is not None:  # never sent un-negotiated
                        self.rdv.on_op(flags, sid, payload)
                elif ftype == h2.GOAWAY:
                    last, code = struct.unpack_from("!II", payload)
                    self._goaway_last = last
                    self._die(f"server sent GOAWAY (error {code})")
                    return
                # PRIORITY / PUSH_PROMISE / unknown: ignored
        except (EndpointError, h2.H2Error, HpackError, struct.error, OSError) as exc:
            self._die(f"h2 read loop failed: {exc}")

    def _get_call(self, sid: int) -> Optional[_H2Call]:
        with self._lock:
            return self._calls.get(sid)

    def _pop_call(self, sid: int) -> Optional[_H2Call]:
        with self._lock:
            call = self._calls.pop(sid, None)
        if call is not None and call.window is not None:
            # The stream is over (trailers/RST/cancel): release any sender
            # blocked in FlowWindow.take — no grant can ever arrive for a
            # dead stream, so without the kill it waits forever.
            call.window.kill()
        return call

    def _on_headers(self, sid: int, flags: int, block: bytes) -> None:
        headers = self._dec.decode(block)
        call = self._get_call(sid)
        if call is None:
            return
        md: List[Tuple[str, object]] = []
        grpc_status: Optional[bytes] = None
        grpc_message = b""
        http_status = None
        for k, v in headers:
            key = k.decode("ascii", "replace")
            if key == "grpc-status":
                grpc_status = v
            elif key == "grpc-message":
                grpc_message = v
            elif key == ":status":
                http_status = v
            elif key == "grpc-encoding":
                call.recv_encoding = (v.decode("ascii", "replace")
                                      if isinstance(v, (bytes, bytearray))
                                      else str(v))
            elif (key.startswith(":")
                  or key in ("content-type", "grpc-accept-encoding")):
                continue
            else:
                md.append((key, _decode_metadata_value(key, v)))
        end = bool(flags & h2.FLAG_END_STREAM)
        if grpc_status is not None or end:
            # trailers (or trailers-only response)
            if grpc_status is None:
                code = (StatusCode.UNKNOWN if http_status == b"200"
                        else StatusCode.UNAVAILABLE)
                details = f"stream ended without grpc-status (:status {http_status})"
            else:
                try:
                    code = StatusCode(int(grpc_status))
                except ValueError:
                    code = StatusCode.UNKNOWN
                details = _pct_decode(grpc_message.decode("ascii", "replace"))
            self._pop_call(sid)
            call.trailing_md = md
            call.deliver_status(code, details, md)
        else:
            call.deliver_initial(md)

    def _on_data(self, sid: int, flags: int, data: bytes,
                 consumed: int) -> None:
        """``data`` is padding-stripped (possibly a whole coalesced run of
        DATA frames); ``consumed`` the wire-level flow-control bytes.
        RFC 7540 §6.9: flow control covers the ENTIRE DATA payload including
        padding, so the grant uses ``consumed``, not ``len(data)`` —
        stripping-before-granting leaks the pad bytes until the sender's
        view of our window runs dry."""
        call = self._get_call(sid)
        if call is not None and data:
            call.feed_data(data)
        # Replenish both windows aggressively (we sized RECV_WINDOW for
        # tensors).
        if consumed:
            segs = h2.pack_window_update(0, consumed)
            if call is not None:
                segs = segs + h2.pack_window_update(sid, consumed)
            with self._wlock:
                self._ep.write(segs)
        if flags & h2.FLAG_END_STREAM:
            call2 = self._pop_call(sid)
            if call2 is not None and call2.code is None:
                # DATA+END_STREAM without trailers is a protocol violation in
                # gRPC; surface it rather than hang the caller.
                call2.deliver_status(
                    StatusCode.INTERNAL, "stream ended without trailers", [])

    def _on_settings(self, flags: int, payload: bytes) -> None:
        if flags & h2.FLAG_ACK:
            self._settings_acked.set()
            return
        settings = h2.parse_settings(payload)
        h2.validate_settings(settings)  # RFC 7540 §6.5.2 ranges
        with self._wlock:
            # Process EVERY setting, then ACK, in ONE write-lock hold
            # (RFC 7540 §6.5.3's process-all-then-ACK). The hold is what
            # makes enlargements safe: a peer may keep enforcing its
            # PRE-settings limits until it receives our ACK (grpc-core
            # does exactly that for max frame size — the round-3 sporadic
            # 'Failed parsing HTTP/2'), and since every DATA/HEADERS write
            # takes _wlock, a sender that observed an enlarged value can
            # only reach the socket after the ACK already queued ahead of
            # it in this critical section.
            if h2.SETTINGS_MAX_FRAME_SIZE in settings:
                self._peer_max_frame = settings[h2.SETTINGS_MAX_FRAME_SIZE]
            if h2.SETTINGS_INITIAL_WINDOW_SIZE in settings:
                new = settings[h2.SETTINGS_INITIAL_WINDOW_SIZE]
                # The write to _peer_initial_window and the snapshot of
                # calls to retro-adjust must be ONE critical section with
                # _start_call's window creation (which nests _lock inside
                # _wlock in this same order): a call created in between
                # would otherwise get the new initial AND the adjust
                # (double-applied delta → overrunning the server's window
                # → FLOW_CONTROL_ERROR).
                with self._lock:
                    delta = new - self._peer_initial_window
                    self._peer_initial_window = new
                    calls = list(self._calls.values())
                for call in calls:
                    if call.window is not None:
                        call.window.adjust(delta)
            # Indexing stays off until this first SETTINGS is processed (the
            # peer's table ceiling is unknown before); applied under the
            # write lock so no HEADERS block interleaves the transition.
            self._enc.apply_peer_table_size(
                settings.get(h2.SETTINGS_HEADER_TABLE_SIZE, 4096))
            self._ep.write(h2.pack_settings({}, ack=True))
        if settings.get(h2.SETTINGS_TPURPC_RDV) and self.rdv is not None:
            self.rdv.on_peer_hello()

    def _on_window_update(self, sid: int, payload: bytes) -> None:
        (inc,) = struct.unpack("!I", payload)
        inc &= 0x7FFFFFFF
        if sid == 0:
            self._conn_window.grant(inc)
        else:
            call = self._get_call(sid)
            if call is not None and call.window is not None:
                call.window.grant(inc)

    # -- call machinery -------------------------------------------------------

    def _check_alive(self) -> None:
        with self._lock:
            if self._dead is not None:
                raise RpcError(StatusCode.UNAVAILABLE,
                               f"channel dead: {self._dead}")

    def _start_call(self, method: str, timeout: Optional[float],
                    metadata: Optional[Metadata]) -> _H2Call:
        self._check_alive()
        deadline = None if timeout is None else time.monotonic() + timeout
        headers: List[Tuple[str, str]] = [
            (":method", "POST"),
            (":scheme", "http"),
            (":path", method),
            (":authority", self._authority),
            ("te", "trailers"),
            ("content-type", "application/grpc"),
            ("grpc-accept-encoding", "identity,gzip,deflate"),
            ("user-agent", "tpurpc-h2/0.1"),
        ]
        if timeout is not None:
            headers.append(("grpc-timeout", _grpc_timeout(timeout)))
        for key, value in metadata or ():
            headers.append((key, _encode_metadata_value(key, value)))
        # sid allocation and the HEADERS write share one critical section:
        # h2 requires new stream ids to appear on the wire in increasing
        # order — a racing call writing its (higher) sid first makes the
        # server treat the lower sid as implicitly closed and drop it.
        with self._wlock:
            with self._lock:
                sid = self._next_stream
                self._next_stream += 2
                call = _H2Call(sid, deadline)
                call.window = h2.FlowWindow(self._peer_initial_window)
                self._calls[sid] = call
            block = self._enc.encode(headers)
            frames: List[bytes] = []
            first = True
            while first or block:
                chunk, block = (block[:self._peer_max_frame],
                                block[self._peer_max_frame:])
                flags = (h2.FLAG_END_HEADERS if not block else 0)
                frames.extend(h2.pack_frame(
                    h2.HEADERS if first else h2.CONTINUATION,
                    flags, sid, bytes(chunk)))
                first = False
            self._ep.write(frames)
        return call

    # -- rendezvous plumbing (tpurpc-express) ---------------------------------

    def _rdv_send_op(self, op: int, stream_id: int, payload: bytes) -> None:
        with self._wlock:
            self._ep.write(h2.pack_frame(h2.TPURPC_RDV, op, stream_id,
                                         payload))

    def _rdv_deliver(self, stream_id: int, flags: int, body) -> None:
        """A completed rendezvous response payload: the call's next gRPC
        message, bypassing DATA reassembly and flow control (end-of-stream
        rides trailers on the response direction)."""
        call = self._get_call(stream_id)
        if call is not None:
            call.events.put(("message", body))

    def _send_message(self, call: _H2Call, payload, end: bool) -> None:
        rdv = self.rdv
        if rdv is not None:
            segs = ([memoryview(s).cast("B") for s in payload]
                    if isinstance(payload, (list, tuple)) else
                    [memoryview(payload).cast("B")])
            segs = [s for s in segs if len(s)]
            total = sum(len(s) for s in segs)
            # COMPLETE's flags bit 0 carries the half-close, so the whole
            # message+end costs one one-sided write + one control frame
            if rdv.eligible(total) and rdv.send_message(
                    call.stream_id, 1 if end else 0, segs, total):
                return
        data = (b"".join(bytes(s) for s in payload)
                if isinstance(payload, (list, tuple)) else bytes(payload))
        buf = _GRPC_MSG_HDR.pack(0, len(data)) + data
        view = memoryview(buf)
        while view:
            want = min(len(view), self._peer_max_frame)
            if call.window._value <= 0 or self._conn_window._value <= 0:
                # tpurpc-blackbox: about to block on peer WINDOW_UPDATE
                # credit — the watchdog's h2-flow-control stall evidence
                _flight.emit(_flight.H2_WINDOW_EXHAUSTED, self._ftag,
                             call.stream_id)
            try:
                got = call.window.take(want, timeout=call._remaining())
                conn_got = self._conn_window.take(got,
                                                  timeout=call._remaining())
            except TimeoutError:
                # Deadline passed while flow-control starved: this is a
                # DEADLINE, not a transport failure (grpcio semantics; the
                # receive path reports the identical condition the same way).
                raise RpcError(StatusCode.DEADLINE_EXCEEDED,
                               "deadline exceeded while sending "
                               "(flow-control starved)") from None
            except h2.H2Error:
                # The stream's window was killed: terminated under us. If a
                # real status arrived (trailers-only reject, RST), surface
                # THAT; stop sending quietly on OK (server finished early
                # without draining the request, which h2 permits).
                if call.code is StatusCode.OK:
                    return
                if call.code is not None:
                    raise RpcError(call.code, call.details,
                                   call.trailing_md) from None
                raise RpcError(StatusCode.UNAVAILABLE,
                               "stream closed while sending") from None
            if conn_got < got:
                # Another stream drained the shared connection window under
                # us: return the stream credit we reserved but can't send,
                # or it leaks and the call eventually wedges at window 0.
                call.window.grant(got - conn_got)
                got = conn_got
            chunk = view[:got]
            view = view[got:]
            last = end and not view
            with self._wlock:
                self._ep.write(h2.pack_frame(
                    h2.DATA, h2.FLAG_END_STREAM if last else 0,
                    call.stream_id, bytes(chunk)))

    def _half_close(self, call: _H2Call) -> None:
        with self._wlock:
            self._ep.write(h2.pack_frame(h2.DATA, h2.FLAG_END_STREAM,
                                         call.stream_id, b""))

    def _cancel(self, call: _H2Call) -> None:
        self._pop_call(call.stream_id)
        try:
            with self._wlock:
                self._ep.write(h2.pack_rst(call.stream_id, h2.CANCEL))
        except (EndpointError, OSError):
            pass

    def _messages(self, call: _H2Call):
        """Yield response messages until status; raise on non-OK."""
        while True:
            ev = call.next_event()
            if ev[0] == "initial_metadata":
                continue
            if ev[0] == "message":
                yield ev[1]
                continue
            _, code, details, md = ev
            call.code, call.details = code, details
            if code is not StatusCode.OK:
                raise RpcError(code, details, md)
            return

    # -- grpcio-shaped surface ------------------------------------------------

    def unary_unary(self, method: str, request_serializer=None,
                    response_deserializer=None):
        ser = request_serializer or (lambda x: x)
        deser = response_deserializer or (lambda x: x)

        def call_fn(request, timeout: Optional[float] = None,
                    metadata: Optional[Metadata] = None):
            call = self._start_call(method, timeout, metadata)
            try:
                self._send_message(call, ser(request), end=True)
                msgs = list(self._messages(call))
            except (h2.H2Error, EndpointError, TimeoutError) as exc:
                self._cancel(call)
                raise RpcError(StatusCode.UNAVAILABLE, str(exc)) from exc
            except RpcError:
                self._cancel(call)
                raise
            except Exception:
                # user code (serializer / request iterator) blew up: free the
                # server-side stream before propagating
                self._cancel(call)
                raise
            if len(msgs) != 1:
                raise RpcError(StatusCode.INTERNAL,
                               f"expected 1 response message, got {len(msgs)}")
            return deser(msgs[0])

        return call_fn

    def unary_stream(self, method: str, request_serializer=None,
                     response_deserializer=None):
        ser = request_serializer or (lambda x: x)
        deser = response_deserializer or (lambda x: x)

        def call_fn(request, timeout: Optional[float] = None,
                    metadata: Optional[Metadata] = None):
            call = self._start_call(method, timeout, metadata)
            try:
                self._send_message(call, ser(request), end=True)
                for msg in self._messages(call):
                    yield deser(msg)
            except (h2.H2Error, EndpointError, TimeoutError) as exc:
                self._cancel(call)
                raise RpcError(StatusCode.UNAVAILABLE, str(exc)) from exc
            except RpcError:
                # locally raised (deadline, protocol): tell the server to
                # stop streaming into a consumer that is gone
                self._cancel(call)
                raise
            except Exception:
                self._cancel(call)
                raise
            except GeneratorExit:
                self._cancel(call)
                raise

        return call_fn

    def stream_unary(self, method: str, request_serializer=None,
                     response_deserializer=None):
        ser = request_serializer or (lambda x: x)
        deser = response_deserializer or (lambda x: x)

        def call_fn(request_iterator: Iterable,
                    timeout: Optional[float] = None,
                    metadata: Optional[Metadata] = None):
            call = self._start_call(method, timeout, metadata)
            try:
                for req in request_iterator:
                    self._send_message(call, ser(req), end=False)
                self._half_close(call)
                msgs = list(self._messages(call))
            except (h2.H2Error, EndpointError, TimeoutError) as exc:
                self._cancel(call)
                raise RpcError(StatusCode.UNAVAILABLE, str(exc)) from exc
            except RpcError:
                self._cancel(call)
                raise
            except Exception:
                # user code (serializer / request iterator) blew up: free the
                # server-side stream before propagating
                self._cancel(call)
                raise
            if len(msgs) != 1:
                raise RpcError(StatusCode.INTERNAL,
                               f"expected 1 response message, got {len(msgs)}")
            return deser(msgs[0])

        return call_fn

    def stream_stream(self, method: str, request_serializer=None,
                      response_deserializer=None):
        ser = request_serializer or (lambda x: x)
        deser = response_deserializer or (lambda x: x)

        def call_fn(request_iterator: Iterable,
                    timeout: Optional[float] = None,
                    metadata: Optional[Metadata] = None):
            call = self._start_call(method, timeout, metadata)

            def _pump():
                try:
                    for req in request_iterator:
                        self._send_message(call, ser(req), end=False)
                    self._half_close(call)
                except (h2.H2Error, EndpointError, TimeoutError, RpcError):
                    self._cancel(call)
                except Exception as exc:
                    # user code (request iterator / serializer) blew up in
                    # the sender thread: cancel AND deliver a status, or the
                    # response consumer blocks forever on an empty queue
                    self._cancel(call)
                    call.deliver_status(
                        StatusCode.INTERNAL,
                        f"request iterator/serializer failed: {exc!r}", [])

            sender = threading.Thread(target=_pump, daemon=True,
                                      name="tpurpc-h2c-sender")
            sender.start()
            try:
                for msg in self._messages(call):
                    yield deser(msg)
            except (RpcError, GeneratorExit):
                self._cancel(call)
                raise
            finally:
                sender.join(timeout=5)

        return call_fn
