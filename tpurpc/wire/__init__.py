"""gRPC wire-compat layer: HTTP/2 + HPACK so stock gRPC clients interoperate
(SURVEY.md §7 stage 3's compatibility path; reference: chttp2, §2.4)."""
