"""Pallas device kernels for tpurpc's hot device-side ops.

The host data plane's hot loops live in C++ (``native/src``); the DEVICE
side's hot op is the HBM ring-window consume — materializing a possibly
wrapped span of the device-resident receive ring as one contiguous array
(``tpurpc/tpu/hbm_ring.py``). :mod:`tpurpc.ops.ring_window` fuses that
into a single Pallas kernel (one d2d pass) instead of the
slice + slice + concatenate chain XLA would otherwise launch.
"""

from tpurpc.ops.ring_window import ring_window  # noqa: F401

__all__ = ["ring_window"]
