"""Pallas kernel: contiguous window of a wrapped device ring, in one pass.

The consume path of the HBM receive ring (``tpurpc/tpu/hbm_ring.py``,
reference analog ``ring_buffer.cc:122-191`` — whose ``Read`` memcpys out of
the host ring) needs ``out[i] = ring[(head + i) mod capacity]`` for a span
that may cross the wrap point. Expressed in jax ops that is
``dynamic_slice + dynamic_slice + concatenate``; this module does it as ONE
Pallas kernel, blocked over the output.

TPU-compatible formulation (validated on a real v5e chip AND in interpret
mode against a numpy oracle): the ring lives in ``ANY`` (HBM) as a
``(rows, 128)`` uint32 matrix; each program async-DMAs two 9-row windows
into VMEM scratch — the (row-clamped) source window at the block's start
and the wrap window at row 0 — then combines them with *flat rolls*
decomposed into supported 2-D ops:

    flat_roll(X, s)[r, c] = X[r + s//C + (c + s%C >= C), (c + s%C) % C]
                          = where(lane < C - s%C,
                                  roll(roll(X, -s//C, 0), -s%C, 1),
                                  roll(roll(X, -s//C - 1, 0), -s%C, 1))

Out-of-window rows rolled in are garbage but only land on lanes the final
pre/post-wrap select discards (proved in the per-case comments below).

Alignment contract: offsets/lengths multiple of 4 bytes (uint32 lanes),
ring capacity ≥ 9·512 bytes. The caller falls back to the jax-op chain
otherwise.
"""

from __future__ import annotations

import functools

import numpy as np

import jax

#: lanes per row (TPU vector lane width)
_C = 128
#: output rows per program: (8, 128) is the minimal uint32 tile
_R = 8
#: scratch rows: 9 valid rows (8 + 1 for sub-row shifts) padded so row
#: rolls up to 16 never wrap back into valid rows
_SCRATCH_ROWS = 32


def _flat_roll_neg(x, s, lanes):
    """first _R rows of flat_roll(x, -s): out[i] = x_flat[i + s], for
    lanes where i + s stays inside x's valid leading rows."""
    import jax.numpy as jnp
    from jax.experimental.pallas import tpu as pltpu

    rr = s // _C
    t = s % _C
    y1 = pltpu.roll(x, -rr, axis=0)
    y2 = pltpu.roll(x, -(rr + 1), axis=0)
    a1 = pltpu.roll(y1, -t, axis=1)
    a2 = pltpu.roll(y2, -t, axis=1)
    return jnp.where(lanes < _C - t, a1, a2)


def _flat_roll_pos(x, s, lanes):
    """first _R rows of flat_roll(x, +s): out[i] = x_flat[i - s], valid on
    lanes with i >= s (the rest roll in discarded garbage)."""
    import jax.numpy as jnp
    from jax.experimental.pallas import tpu as pltpu

    rr = s // _C
    t = s % _C
    y1 = pltpu.roll(x, rr, axis=0)
    y2 = pltpu.roll(x, rr + 1, axis=0)
    b1 = pltpu.roll(y1, t, axis=1)
    b2 = pltpu.roll(y2, t, axis=1)
    return jnp.where(lanes >= t, b1, b2)


def _kernel(head_ref, buf_ref, out_ref, scr_a, scr_b, sem_a, sem_b,
            *, rows: int):
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    capacity_words = rows * _C
    block = _R * _C
    pid = pl.program_id(0)
    p1 = jax.lax.rem(head_ref[0] + pid * block, capacity_words)
    row1 = p1 // _C
    row1c = jnp.minimum(row1, rows - (_R + 1))   # clamp: 9 rows must fit
    d_rows = row1 - row1c
    # pre-wrap length for this block; only the (at most one) block whose
    # window crosses the wrap ever selects from window B
    pre = capacity_words - p1
    # window A: 9 rows from the (clamped) source start; covers the
    # pre-wrap part of the block at flat offset s = d_rows*C + p1%C < 9C
    cp_a = pltpu.make_async_copy(
        buf_ref.at[pl.dslice(row1c, _R + 1), :],
        scr_a.at[pl.dslice(0, _R + 1), :], sem_a)
    cp_a.start()

    # window B: 9 rows from ring start; covers the post-wrap part. Skipped
    # for non-crossing blocks (the common case) — its lanes would be fully
    # discarded, so the DMA would be pure wasted bandwidth.
    @pl.when(pre < block)
    def _copy_wrap_window():
        cp_b = pltpu.make_async_copy(
            buf_ref.at[pl.dslice(0, _R + 1), :],
            scr_b.at[pl.dslice(0, _R + 1), :], sem_b)
        cp_b.start()
        cp_b.wait()

    cp_a.wait()

    lanes = jax.lax.broadcasted_iota(jnp.int32, (_SCRATCH_ROWS, _C), 1)
    flat = (jax.lax.broadcasted_iota(jnp.int32, (_SCRATCH_ROWS, _C), 0) * _C
            + lanes)
    s_a = d_rows * _C + p1 % _C
    a = _flat_roll_neg(scr_a[...], s_a, lanes)
    # when pre >= block, B is never selected and its (stale-scratch,
    # garbage-rolled) lanes are discarded by the select below
    b = _flat_roll_pos(scr_b[...], jax.lax.rem(pre, capacity_words), lanes)
    out_ref[...] = jnp.where(flat < pre, a, b)[:_R]


@functools.partial(jax.jit, static_argnames=("n_words", "interpret"))
def _ring_window_impl(buf_u8, head_word, *, n_words: int, interpret: bool):
    """One compiled dispatch: uint8→uint32 bitcast, the pallas gather, and
    the uint32→uint8 bitcast all fuse under this jit."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    buf_words = jax.lax.bitcast_convert_type(
        buf_u8.reshape(-1, 4), jnp.uint32).reshape(-1, _C)
    rows = buf_words.shape[0]
    block = _R * _C
    padded = ((n_words + block - 1) // block) * block
    grid = (padded // block,)
    out = pl.pallas_call(
        functools.partial(_kernel, rows=rows),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),  # head word index
            pl.BlockSpec(memory_space=pl.ANY),      # ring stays in HBM
        ],
        out_specs=pl.BlockSpec((_R, _C), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((padded // _C, _C), jnp.uint32),
        scratch_shapes=[pltpu.VMEM((_SCRATCH_ROWS, _C), jnp.uint32),
                        pltpu.VMEM((_SCRATCH_ROWS, _C), jnp.uint32),
                        pltpu.SemaphoreType.DMA,
                        pltpu.SemaphoreType.DMA],
        interpret=interpret,
    )(head_word, buf_words)
    return jax.lax.bitcast_convert_type(
        out.reshape(-1)[:n_words].reshape(-1, 1), jnp.uint8).reshape(-1)


def ring_window(buf, head: int, n: int, *, interpret: bool = False):
    """``out[i] = buf[(head + i) mod capacity]`` as one fused kernel.

    ``buf``: 1-D device uint8 array, power-of-two length ≥ 4608 bytes.
    ``head``/``n`` must be multiples of 4 (uint32 lanes). Returns a uint8
    array of length ``n``. Raises ValueError on shapes the kernel can't
    take — callers fall back to the jax-op chain.
    """
    import jax.numpy as jnp

    capacity = buf.shape[0]
    if n == 0:
        return jnp.zeros((0,), jnp.uint8)
    if capacity % 4 or head % 4 or n % 4:
        raise ValueError("ring_window needs 4-byte alignment")
    if capacity // 4 < (_R + 1) * _C:
        raise ValueError("ring smaller than the kernel's 9-row DMA window")
    if n > capacity:
        raise ValueError(f"window {n} exceeds capacity {capacity}")
    head_word = jnp.asarray([(head // 4) % (capacity // 4)], jnp.int32)
    return _ring_window_impl(buf, head_word, n_words=n // 4,
                             interpret=interpret)


def ring_window_reference(buf: np.ndarray, head: int, n: int) -> np.ndarray:
    """Numpy oracle for the kernel's contract."""
    capacity = buf.shape[0]
    idx = (head + np.arange(n)) % capacity
    return np.asarray(buf)[idx]
