"""Pallas kernel: contiguous window of a wrapped device ring, in one pass.

The consume path of the HBM receive ring (``tpurpc/tpu/hbm_ring.py``,
reference analog ``ring_buffer.cc:122-191`` — whose ``Read`` memcpys out of
the host ring) needs ``out[i] = ring[(head + i) mod capacity]`` for a span
that may cross the wrap point. Expressed in jax ops that is
``dynamic_slice + dynamic_slice + concatenate`` — three kernels and an
intermediate. This module does it as ONE Pallas kernel, blocked over the
output, each block combining (at most) the two source segments with
dynamic rolls:

    for output block at offset o (size B, B | capacity):
        p1 = (head + o) mod capacity          # block's source start
        d  = p1 - min(p1, capacity - B)       # overrun past the wrap, 0..B
        A  = ring[p1 - d : p1 - d + B]        # static-size, dynamic-start
        Bw = ring[0 : B]
        out = where(lane < B - d, roll(A, -d), roll(Bw, B - d))

    roll(A, -d)[i]    = ring[p1 + i]            for i <  B - d   (pre-wrap)
    roll(Bw, B - d)[i] = ring[i - (B - d)]      for i >= B - d   (post-wrap)

Works on ``uint32`` lanes (TPU-friendly), so offsets/lengths must be
4-byte aligned; the caller falls back to the jax-op chain otherwise.
Validated against a numpy oracle across wrap phases in interpret mode
(the CPU test mesh); on real TPU hardware the kernel is opt-in via
``TPURPC_PALLAS=1`` until it has been profiled there.
"""

from __future__ import annotations

import functools

import numpy as np

#: output block, in uint32 lanes (4 KiB of ring per block — far under VMEM)
_BLOCK = 1024


def _kernel(head_ref, buf_ref, out_ref, *, block: int, capacity_words: int):
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    pid = pl.program_id(0)
    o = pid * block
    p1 = (head_ref[0] + o) % capacity_words
    p1c = jnp.minimum(p1, capacity_words - block)
    d = p1 - p1c                      # 0 unless this block crosses the wrap
    seg_a = buf_ref[pl.dslice(p1c, block)]
    seg_b = buf_ref[pl.dslice(0, block)]
    lanes = jax.lax.iota(jnp.int32, block)
    rolled_a = jnp.roll(seg_a, -d)
    rolled_b = jnp.roll(seg_b, block - d)
    out_ref[...] = jnp.where(lanes < block - d, rolled_a, rolled_b)


import jax  # noqa: E402  (after the docstring; kernel body uses jax.lax)


@functools.partial(jax.jit, static_argnames=("n_words", "interpret"))
def _ring_window_impl(buf_u8, head_word, *, n_words: int, interpret: bool):
    """One compiled dispatch: uint8→uint32 bitcast, the pallas gather, and
    the uint32→uint8 bitcast all fuse under this jit (an eager prologue
    would re-touch O(capacity) bytes per call)."""
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    buf_words = jax.lax.bitcast_convert_type(
        buf_u8.reshape(-1, 4), jnp.uint32).reshape(-1)
    capacity_words = buf_words.shape[0]
    block = min(_BLOCK, n_words)
    # pad the requested length up to a whole number of blocks; caller trims
    padded = ((n_words + block - 1) // block) * block
    grid = (padded // block,)
    out = pl.pallas_call(
        functools.partial(_kernel, block=block,
                          capacity_words=capacity_words),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),   # head index, scalar-ish
            pl.BlockSpec(memory_space=pl.ANY),   # whole ring stays in HBM/ANY
        ],
        out_specs=pl.BlockSpec((block,), lambda i: (i,)),
        out_shape=jax.ShapeDtypeStruct((padded,), jnp.uint32),
        interpret=interpret,
    )(head_word, buf_words)
    return jax.lax.bitcast_convert_type(
        out[:n_words].reshape(-1, 1), jnp.uint8).reshape(-1)


def ring_window(buf, head: int, n: int, *, interpret: bool = False):
    """``out[i] = buf[(head + i) mod capacity]`` as one fused kernel.

    ``buf``: 1-D device uint8 array, power-of-two length. ``head``/``n``
    must be multiples of 4 (uint32 lanes). Returns a uint8 array of
    length ``n``. Raises ValueError on alignment the kernel can't take —
    callers fall back to the jax-op chain.
    """
    import jax.numpy as jnp

    capacity = buf.shape[0]
    if n == 0:
        return jnp.zeros((0,), jnp.uint8)
    if capacity % 4 or head % 4 or n % 4:
        raise ValueError("ring_window needs 4-byte alignment")
    if n > capacity:
        raise ValueError(f"window {n} exceeds capacity {capacity}")
    head_word = jnp.asarray([(head // 4) % (capacity // 4)], jnp.int32)
    return _ring_window_impl(buf, head_word, n_words=n // 4,
                             interpret=interpret)


def ring_window_reference(buf: np.ndarray, head: int, n: int) -> np.ndarray:
    """Numpy oracle for the kernel's contract."""
    capacity = buf.shape[0]
    idx = (head + np.arange(n)) % capacity
    return np.asarray(buf)[idx]
