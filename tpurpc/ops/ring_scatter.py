"""Pallas kernel: land a payload into a wrapped device ring, in one pass.

The write twin of :mod:`tpurpc.ops.ring_window` (VERDICT r2 next#6): the
place path of the HBM receive ring needs

    ring[(start + i) mod capacity] = payload[i]        for i < n

which in jax ops is a donated ``dynamic_update_slice`` — TWO dispatches when
the span wraps (``hbm_ring.py place``), and the wrap case rebinds the
donated buffer twice. This kernel does the whole landing as ONE aliased
pallas_call: the NIC-placement-write of the north star
(``ring_buffer.cc:261-330`` GetWriteRequests' wrap-split is the host-side
analog this replaces).

Formulation (same validated machinery as ring_window — 2-D row-granular
DMAs with dynamic row offsets + flat rolls decomposed into ``pltpu.roll``):
the ring is a ``(rows, 128)`` uint32 matrix in ``ANY`` (HBM); each program
owns one (8,128) payload block and read-modify-writes the ≤2 nine-row ring
windows its bytes land in:

  window A (dest span start):  in-DMA 9 rows -> merge
      ``where(s <= flat < s + lim_pre, payload_flat[flat - s], old)``
      with ``s = dest offset within the window`` -> out-DMA 9 rows back
  window B (ring rows 0..9, wrap only): merge
      ``where(flat < lim_post, payload_flat[flat + pre], old)`` -> out-DMA

Rows the payload doesn't touch are preserved by the RMW; masks are exact,
so garbage lanes rolled in from the zero-padded payload tile are always
discarded (same proof shape as ring_window's selects).

Correctness depends on the TPU grid executing sequentially (it does: grid
iterations are a loop on a core; interpret mode likewise) — adjacent
programs' windows share boundary rows, and program i+1's in-DMA must see
program i's out-DMA. Both DMAs are awaited inside each program.

Alignment contract: start/length multiples of 4 bytes; capacity a power of
two ≥ 2·9·512 bytes (windows A and B must never overlap). Callers fall
back to the dynamic_update_slice chain otherwise.
"""

from __future__ import annotations

import functools

import numpy as np

import jax

from tpurpc.ops.ring_window import (_C, _R, _SCRATCH_ROWS, _flat_roll_neg,
                                    _flat_roll_pos)


def _kernel(start_ref, payload_ref, buf_ref, out_ref, scr, sem_in, sem_out,
            *, rows: int, n_words: int):
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    del buf_ref  # aliased with out_ref: out_ref starts as the ring's
    # contents (input_output_aliases) and is both RMW source and target
    capacity_words = rows * _C
    block = _R * _C
    pid = pl.program_id(0)
    base = pid * block                        # payload flat offset of block
    q = jax.lax.rem(start_ref[0] + base, capacity_words)
    row1 = q // _C
    row1c = jnp.minimum(row1, rows - (_R + 1))  # clamp: 9 rows must fit
    d_rows = row1 - row1c
    s = d_rows * _C + q % _C                  # dest offset inside window A
    pre = capacity_words - q                  # words before the wrap point
    valid = jnp.minimum(block, n_words - base)  # real payload words here
    lanes = jax.lax.broadcasted_iota(jnp.int32, (_SCRATCH_ROWS, _C), 1)
    flat = (jax.lax.broadcasted_iota(jnp.int32, (_SCRATCH_ROWS, _C), 0) * _C
            + lanes)
    # zero-padded payload tile: rolled-in rows beyond the 8 real ones are
    # zeros, and the exact masks below discard them anyway
    pad = jnp.zeros((_SCRATCH_ROWS - _R, _C), jnp.uint32)
    ptile = jnp.concatenate([payload_ref[...], pad], axis=0)

    # -- window A: the destination span's start ------------------------------
    cp_in = pltpu.make_async_copy(
        out_ref.at[pl.dslice(row1c, _R + 1), :],
        scr.at[pl.dslice(0, _R + 1), :], sem_in)
    cp_in.start()
    cp_in.wait()
    shifted = _flat_roll_pos(ptile, s, lanes)   # shifted[f] = payload[f - s]
    lim_pre = jnp.minimum(valid, pre)
    merged = jnp.where((flat >= s) & (flat < s + lim_pre), shifted, scr[...])
    scr[...] = merged
    cp_out = pltpu.make_async_copy(
        scr.at[pl.dslice(0, _R + 1), :],
        out_ref.at[pl.dslice(row1c, _R + 1), :], sem_out)
    cp_out.start()
    cp_out.wait()

    # -- window B: ring start (only when this block crosses the wrap) --------
    @pl.when(pre < valid)
    def _wrap_window():
        cp2_in = pltpu.make_async_copy(
            out_ref.at[pl.dslice(0, _R + 1), :],
            scr.at[pl.dslice(0, _R + 1), :], sem_in)
        cp2_in.start()
        cp2_in.wait()
        back = _flat_roll_neg(ptile, pre, lanes)  # back[f] = payload[f + pre]
        merged_b = jnp.where(flat < valid - pre, back, scr[...])
        scr[...] = merged_b
        cp2_out = pltpu.make_async_copy(
            scr.at[pl.dslice(0, _R + 1), :],
            out_ref.at[pl.dslice(0, _R + 1), :], sem_out)
        cp2_out.start()
        cp2_out.wait()


@functools.partial(jax.jit, static_argnames=("n_words", "interpret"),
                   donate_argnums=0)
def _ring_scatter_impl(buf_u8, payload_u8, start_word, *, n_words: int,
                       interpret: bool):
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    buf_words = jax.lax.bitcast_convert_type(
        buf_u8.reshape(-1, 4), jnp.uint32).reshape(-1, _C)
    rows = buf_words.shape[0]
    block = _R * _C
    padded = ((n_words + block - 1) // block) * block
    pay_words = jax.lax.bitcast_convert_type(
        payload_u8.reshape(-1, 4), jnp.uint32).reshape(-1)
    pay_words = jnp.concatenate(
        [pay_words, jnp.zeros((padded - n_words,), jnp.uint32)]
    ).reshape(-1, _C)
    grid = (padded // block,)
    out = pl.pallas_call(
        functools.partial(_kernel, rows=rows, n_words=n_words),
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),       # start word index
            pl.BlockSpec((_R, _C), lambda i: (i, 0)),    # payload block
            pl.BlockSpec(memory_space=pl.ANY),           # ring stays in HBM
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        out_shape=jax.ShapeDtypeStruct((rows, _C), jnp.uint32),
        scratch_shapes=[pltpu.VMEM((_SCRATCH_ROWS, _C), jnp.uint32),
                        pltpu.SemaphoreType.DMA,
                        pltpu.SemaphoreType.DMA],
        input_output_aliases={2: 0},  # the ring updates in place
        interpret=interpret,
    )(start_word, pay_words, buf_words)
    return jax.lax.bitcast_convert_type(
        out.reshape(-1, 1), jnp.uint8).reshape(-1)


def ring_scatter(buf, payload, start: int, *, interpret: bool = False):
    """``buf[(start + i) mod capacity] = payload[i]`` as one aliased kernel.

    ``buf``: 1-D device uint8 ring (donated; use the RETURNED array).
    ``payload``: 1-D device uint8 array. ``start``/len(payload) must be
    multiples of 4; capacity ≥ 2·9·512 bytes so the two RMW windows can
    never overlap. Raises ValueError on shapes the kernel can't take —
    callers fall back to the dynamic_update_slice chain.
    """
    import jax.numpy as jnp

    capacity = buf.shape[0]
    n = payload.shape[0]
    if n == 0:
        return buf
    if capacity % 4 or start % 4 or n % 4:
        raise ValueError("ring_scatter needs 4-byte alignment")
    if capacity // 4 < 2 * (_R + 1) * _C:
        raise ValueError("ring smaller than two 9-row RMW windows")
    if n > capacity:
        raise ValueError(f"payload {n} exceeds capacity {capacity}")
    start_word = jnp.asarray([(start // 4) % (capacity // 4)], jnp.int32)
    return _ring_scatter_impl(buf, payload, start_word, n_words=n // 4,
                              interpret=interpret)


def ring_scatter_reference(buf: np.ndarray, payload: np.ndarray,
                           start: int) -> np.ndarray:
    """Numpy oracle for the kernel's contract."""
    out = np.array(buf, copy=True)
    idx = (start + np.arange(payload.shape[0])) % buf.shape[0]
    out[idx] = payload
    return out
