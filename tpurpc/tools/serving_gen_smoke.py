"""~5s tpurpc-cadence smoke for the verification gate (tools/check.sh).

The ISSUE 10 acceptance story in miniature, jax-free (the toy decode
model is pure numpy):

* an interactive AND a batch-class client stream generations
  concurrently off one continuous-batching server — every token arrives
  IN ORDER (indices 0..n-1) with the exact values the reference
  recomputation predicts (any cross-stream mixup or dropped step changes
  the values, not just the count), and the second client's ``gen-join``
  sits between two step events (it joined MID-DECODE);
* an offered-load burst past the batch-class bar sheds AT LEAST ONE
  request — UNAVAILABLE with the pushback trailer, a ``gen-shed`` flight
  event, ``/healthz`` saying ``state=shedding`` with the queue numbers —
  while the admitted remainder COMPLETES once capacity frees (shed, not
  strand);
* an induced SLOW STEP (the model wedges mid-step) is attributed by the
  stall watchdog to the new ``decode-step`` stage within two sweeps,
  ``/healthz`` degrades while it lasts, and the stream completes once
  unwedged.

Exit 0 on success; any assertion/exception exits 1 with the reason.

    python -m tpurpc.tools.serving_gen_smoke
"""

from __future__ import annotations

import sys
import threading
import time


def _wait_for(pred, timeout: float, what: str):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = pred()
        if got:
            return got
        time.sleep(0.01)
    raise AssertionError(f"timed out waiting for {what}")


def run() -> int:
    from tpurpc.jaxshim.generate import ToyDecodeModel, reference_decode
    from tpurpc.obs import flight, scrape, watchdog
    from tpurpc.rpc.channel import Channel
    from tpurpc.rpc.server import PUSHBACK_KEY
    from tpurpc.rpc.status import RpcError, StatusCode
    from tpurpc.serving import GenerationClient, serve_generation

    flight.RECORDER.reset()
    wd = watchdog.get()
    wd.reset()
    wd.enabled = False           # quiet until the induced-stall phase:
    #                              healthy multi-second token streams are
    #                              not stalls

    wedge = threading.Event()
    wedge.set()                  # open = steps run normally

    class SmokeModel(ToyDecodeModel):
        def step(self, states, tokens):
            wedge.wait(10)
            return super().step(states, tokens)

    model = SmokeModel(step_delay_s=0.002)
    srv, port, sched = serve_generation(model, max_batch=4, max_waiting=6,
                                        batch_shed_depth=2)
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            gen = GenerationClient(ch)

            # -- per-token order + values, interactive + batch together --
            out: dict = {}

            def client(key, prompt, slo, n):
                out[key] = list(gen.generate_with_meta(
                    prompt, max_tokens=n, slo=slo, timeout=30))

            t1 = threading.Thread(target=client,
                                  args=("inter", [1, 2], "interactive", 24))
            t1.start()
            time.sleep(0.02)     # let the first stream start decoding
            t2 = threading.Thread(target=client,
                                  args=("batch", [3], "batch", 24))
            t2.start()
            t1.join(20)
            t2.join(20)
            for key, prompt in (("inter", [1, 2]), ("batch", [3])):
                pairs = out.get(key)
                assert pairs, f"{key} client produced nothing"
                idxs = [i for i, _ in pairs]
                assert idxs == list(range(24)), (
                    f"{key} stream out of order: {idxs}")
                vals = [t for _, t in pairs]
                want = reference_decode(prompt, 24)
                assert vals == want, (
                    f"{key} stream values wrong: {vals[:4]}... "
                    f"vs {want[:4]}...")
            # continuous batching, not serial: the device stepped merged
            # batches (48 tokens from well under 48 steps)
            assert sched.steps < 40, (
                f"{sched.steps} steps for 48 tokens: batches never merged")
            ev = flight.snapshot()
            joins = [e for e in ev if e["event"] == "gen-join"]
            steps = [e for e in ev
                     if e["event"] in ("gen-step-begin", "gen-step-end")]
            assert len(joins) >= 2 and steps, "flight missing join/step"
            t_join2 = joins[1]["t_ns"]
            assert any(e["t_ns"] < t_join2 for e in steps) and \
                any(e["t_ns"] > t_join2 for e in steps), (
                    "second join not between step events: not mid-decode")
            print(f"gen smoke: 2 classes x 24 tokens in order, "
                  f"{sched.steps} merged steps, join mid-decode OK")

            # -- offered-load burst: sheds trip, admitted work completes --
            hold = [gen.call([9], max_tokens=400, timeout=60)
                    for _ in range(4)]
            hold_iters = [iter(c) for c in hold]
            for it in hold_iters:
                next(it)          # 4 running: the batch is full
            burst: dict = {}

            def burst_client(i):
                try:
                    got = list(gen.generate([i], max_tokens=2, slo="batch",
                                            timeout=30))
                    burst[i] = ("ok", got)
                except RpcError as exc:
                    burst[i] = ("err", exc)

            threads = [threading.Thread(target=burst_client, args=(i,))
                       for i in range(6)]
            for t in threads:
                t.start()
                time.sleep(0.01)  # ordered offered load: queue then shed
            _wait_for(lambda: sched.shed_total >= 1, 10.0,
                      "the burst to shed")
            status, _ctype, body = scrape._route("/healthz")
            assert status == 200 and b"state=shedding" in body, (
                status, body)
            assert b"waiting=" in body and b"running=" in body, body
            for c in hold:        # free capacity: queued burst work lands
                c.cancel()
            for t in threads:
                t.join(20)
            sheds = 0
            for i, (kind, payload) in sorted(burst.items()):
                if kind == "ok":
                    assert payload == reference_decode([i], 2), (i, payload)
                    continue
                assert payload.code() is StatusCode.UNAVAILABLE, payload
                md = dict(payload.trailing_metadata() or ())
                assert PUSHBACK_KEY in md and int(md[PUSHBACK_KEY]) > 0, md
                sheds += 1
            assert sheds >= 1, f"burst outcomes: {burst}"
            assert any(e["event"] == "gen-shed"
                       for e in flight.snapshot()), "no gen-shed event"
            _wait_for(lambda: sched.running_depth() + sched.queue_depth()
                      == 0, 10.0, "the burst to drain")
            print(f"gen smoke: burst shed {sheds}/6 with pushback, "
                  f"{6 - sheds} completed after capacity freed, healthz "
                  "showed shedding + queue state")

            # -- induced slow step -> decode-step watchdog stage -----------
            wd.reset()
            wd.enabled = True
            wd.min_stall_s = 0.3  # fast smoke knobs (prod: 1s/0.25s)
            wd.sweep_s = 0.1
            wd.mult = 2
            slow_out: dict = {}

            def slow_client():
                try:
                    slow_out["v"] = list(gen.generate([7], max_tokens=6,
                                                      timeout=30))
                except Exception as exc:
                    slow_out["e"] = exc

            wedge.clear()         # the next decode step wedges mid-model
            t = threading.Thread(target=slow_client)
            t.start()
            _wait_for(lambda: sched.running_depth() >= 1, 5.0,
                      "the slow stream to join")
            diags = _wait_for(
                lambda: [d for d in wd.active()
                         if d["stage"] == "decode-step"],
                wd.min_stall_s + 6 * wd.sweep_s + 3.0,
                "decode-step watchdog attribution")
            assert "wedged" in diags[0]["detail"], diags
            status, _ctype, body = scrape._route("/healthz")
            assert status == 503 and b"decode-step" in body, (status, body)
            wedge.set()
            t.join(20)
            assert slow_out.get("v") == reference_decode([7], 6), slow_out
            _wait_for(lambda: not wd.active(), 5.0, "the stall to clear")
            print(f"gen smoke: induced slow step attributed to "
                  f"decode-step, healthz degraded while active, stream "
                  f"completed after unwedge")
    finally:
        srv.stop(grace=0)
        sched.close()
        wd.reset()
        wd.enabled = True
    return 0


def main() -> int:
    try:
        return run()
    except BaseException as exc:  # the gate wants a reasoned nonzero exit
        print(f"serving gen smoke FAILED: {exc!r}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
