"""~1s metrics smoke for the verification gate (tools/check.sh, ISSUE 4).

Stands up a loopback server, runs a handful of RPCs (pool + pipelined),
scrapes the SAME serving port over plain HTTP twice, and asserts:

* the core series are present (srv_call_us, channelz calls, resp_coalesce,
  pipeline_call_us, ledger bytes);
* the call counters are MONOTONIC between the two scrapes and account for
  the traffic we just generated;
* a forced-sampled traced call produces a span tree whose client-send /
  wire / dispatch / respond spans share one trace_id, and the /traces
  endpoint serves it as chrome trace JSON;
* `tools.top --once` parses the scrape (the dashboard's parser is the
  same code path).

Exit 0 on success; any assertion/exception exits 1 with the reason.

    python -m tpurpc.tools.obs_smoke
"""

from __future__ import annotations

import json
import sys
import urllib.request


def run() -> int:
    from tpurpc.obs import tracing
    from tpurpc.rpc.channel import Channel
    from tpurpc.rpc.server import Server, unary_unary_rpc_method_handler
    from tpurpc.tools.top import parse_prometheus

    srv = Server(max_workers=4)
    srv.add_method("/obs/Echo",
                   unary_unary_rpc_method_handler(
                       lambda req, ctx: b"ok:" + bytes(req)))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    tracing.force(True)
    try:
        def scrape(path="/metrics"):
            return urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10).read()

        with Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.unary_unary("/obs/Echo")
            assert mc(b"a", timeout=10) == b"ok:a"
            m1 = parse_prometheus(scrape().decode())
            pl = mc.pipeline(depth=4)
            futs = [pl.call_async(b"r%d" % i, timeout=10) for i in range(8)]
            for i, f in enumerate(futs):
                assert f.result(10) == b"ok:r%d" % i
            m2 = parse_prometheus(scrape().decode())

        # core series present
        for name in ("tpurpc_srv_call_us_count", "tpurpc_pipeline_call_us_count",
                     "tpurpc_resp_coalesce_count"):
            assert (name, "") in m2, f"series {name} missing from scrape"
        assert any(n == "tpurpc_channelz_calls" for n, _l in m2), \
            "channelz call series missing"
        assert any(n == "tpurpc_ledger_bytes" for n, _l in m2), \
            "copy-ledger series missing"

        # monotonic + accounts for the traffic between the scrapes
        def calls(m):
            return sum(v for (n, lab), v in m.items()
                       if n == "tpurpc_channelz_calls"
                       and 'kind="started"' in lab)

        c1, c2 = calls(m1), calls(m2)
        assert c2 >= c1 + 8, f"call counter not monotonic/complete: {c1}->{c2}"
        s1 = m1.get(("tpurpc_srv_call_us_count", ""), 0)
        s2 = m2.get(("tpurpc_srv_call_us_count", ""), 0)
        assert s2 >= s1 + 8, f"srv latency histogram stalled: {s1}->{s2}"

        # traced spans: one trace_id across client-send/wire/dispatch/respond
        spans = tracing.spans()
        byname = {}
        for s in spans:
            byname.setdefault(s["name"], s)
        for need in ("client-send", "wire", "dispatch", "respond"):
            assert need in byname, f"span {need} missing ({sorted(byname)})"
        one = [s for s in spans
               if s["trace_id"] == byname["respond"]["trace_id"]]
        assert {"client-send", "wire", "dispatch", "respond"} <= {
            s["name"] for s in one}, "trace_id does not unify the call's spans"

        # /traces serves chrome trace JSON; /healthz answers
        tr = json.loads(scrape("/traces"))
        assert tr["traceEvents"], "trace export empty"
        assert scrape("/healthz").strip() == b"ok"
        print(f"obs smoke OK: {len(m2)} series, {len(spans)} spans, "
              f"calls {int(c1)}->{int(c2)}")
        return 0
    finally:
        tracing.force(None)
        srv.stop(grace=0)


def main() -> int:
    try:
        return run()
    except Exception as exc:
        print(f"obs smoke FAILED: {exc!r}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    raise SystemExit(main())
