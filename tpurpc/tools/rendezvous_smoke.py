"""tpurpc-express smoke (ISSUE 9): one 8 MiB tensor over shm rings AND
loopback TCP, rendezvous'd.

Per platform (RDMA_BPEV = shm ring plane, TCP = loopback TCP framing):

* stream one 8 MiB float32 tensor through a Sink handler that decodes it
  zero-copy and materializes it as a jax.Array;
* the copy ledger must show the one-sided write (``rdma_write`` ≥ payload)
  and ZERO host landing copies of the payload (< 64 KiB of small control/
  reply frames on the instrumented framed path);
* the flight recorder must carry the ordered offer → claim → write →
  complete evidence for the solicited transfer;
* then a claim-starved transfer (the ``drop_offers`` chaos seam) must be
  diagnosed by the stall watchdog as stuck in the ``rendezvous`` stage —
  and still COMPLETE via the framed fallback once the claim times out.

Runs each platform in a subprocess (GRPC_PLATFORM_TYPE is read at import).
Exit 0 = both planes passed.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time

PAYLOAD_SHAPE = (2048, 1024)  # 8 MiB float32


def run_phase() -> None:
    import numpy as np

    import tpurpc.core.rendezvous as rdv
    from tpurpc.jaxshim import TensorClient, add_tensor_method, to_jax
    from tpurpc.obs import flight, watchdog
    from tpurpc.rpc.channel import Channel
    from tpurpc.rpc.server import Server
    from tpurpc.tpu import ledger

    platform = os.environ.get("GRPC_PLATFORM_TYPE", "?")
    flight.RECORDER.reset()
    srv = Server(max_workers=4, native_dataplane=False)
    seen = {}

    def consume(req_iter):
        total = 0
        for tree in req_iter:
            arr = to_jax(tree["x"])  # zero-copy on 64B-aligned landings
            total += arr.nbytes
            seen["corner"] = float(np.asarray(arr)[-1, -1])
        yield {"bytes": np.int64(total)}

    add_tensor_method(srv, "Sink", consume, kind="stream_stream")
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    payload = np.random.default_rng(9).standard_normal(
        PAYLOAD_SHAPE).astype(np.float32)
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            cli = TensorClient(ch)

            def gen(k):
                for _ in range(k):
                    yield {"x": payload}

            # warm: settles the capability hello, jits the decode
            list(cli.duplex("Sink", gen(1), native=False, timeout=60))
            with ledger.track() as w:
                replies = list(cli.duplex("Sink", gen(1), native=False,
                                          timeout=60))
            total = int(np.asarray(replies[-1]["bytes"]).ravel()[0])
            assert total == payload.nbytes, (total, payload.nbytes)
            assert abs(seen["corner"] - float(payload[-1, -1])) < 1e-6
            assert w["rdma_write"] >= payload.nbytes, w.delta
            assert w["host_copy"] < 64 * 1024, (
                "host landing copies on the rendezvous path", w.delta)
            evs = [e["event"] for e in flight.snapshot()
                   if e["event"].startswith("rdv-")]
            for name in ("rdv-offer", "rdv-claim", "rdv-write",
                         "rdv-complete"):
                assert name in evs, evs
            print(f"  [{platform}] 8 MiB tensor rendezvous'd: "
                  f"rdma_write={w['rdma_write']} host_copy={w['host_copy']}"
                  f" (zero landing copies)")

            # induced stall: starve the claims; the watchdog must name the
            # rendezvous stage, then the framed fallback completes the call
            wd = watchdog.get()
            wd.reset()
            prev = (wd.min_stall_s, wd.sweep_s)
            wd.min_stall_s, wd.sweep_s = 0.3, 0.1
            rdv.TEST_HOOKS["drop_offers"] = True
            os.environ["TPURPC_RENDEZVOUS_CLAIM_TIMEOUT_S"] = "3"
            result = {}
            # a DIFFERENT size class than the 8 MiB stream: the standing
            # grants it left behind must not short-circuit the starvation
            stall_payload = np.ones((1024, 512), np.float32)  # 2 MiB

            def stalled():
                result["replies"] = list(
                    cli.duplex("Sink", iter([{"x": stall_payload}]),
                               native=False, timeout=60))

            t = threading.Thread(target=stalled)
            t.start()
            diag = None
            deadline = time.monotonic() + 10
            try:
                while diag is None and time.monotonic() < deadline:
                    time.sleep(0.15)
                    for d in wd.sweep_once():
                        if d["stage"] == "rendezvous":
                            diag = d
                            break
                assert diag is not None, (
                    "watchdog never named the rendezvous stage",
                    wd.active())
                t.join(timeout=60)
                assert not t.is_alive(), "stalled call never completed"
                total = int(np.asarray(
                    result["replies"][-1]["bytes"]).ravel()[0])
                assert total == stall_payload.nbytes
            finally:
                rdv.TEST_HOOKS.pop("drop_offers", None)
                os.environ.pop("TPURPC_RENDEZVOUS_CLAIM_TIMEOUT_S", None)
                wd.min_stall_s, wd.sweep_s = prev
                wd.reset()
            print(f"  [{platform}] induced stall diagnosed as "
                  f"'{diag['stage']}' ({diag['detail'][:60]}...); framed "
                  "fallback completed the call")
    finally:
        srv.stop(grace=1)


def main() -> int:
    if "--phase" in sys.argv:
        run_phase()
        return 0
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    for platform in ("RDMA_BPEV", "TCP"):
        env = dict(os.environ)
        env["GRPC_PLATFORM_TYPE"] = platform
        env.setdefault("JAX_PLATFORMS", "cpu")
        env["PYTHONPATH"] = here + os.pathsep + env.get("PYTHONPATH", "")
        rc = subprocess.run(
            [sys.executable, "-m", "tpurpc.tools.rendezvous_smoke",
             "--phase"], env=env, timeout=300).returncode
        if rc != 0:
            print(f"rendezvous smoke FAILED on {platform}")
            return 1
    print("rendezvous smoke: PASS (shm ring + loopback TCP, zero host "
          "landing copies, watchdog names the stage)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
