"""tpurpc-argus bundle renderer: read a postmortem off disk.

    python -m tpurpc.tools.bundle <bundle-dir | bundles-root>

Renders one evidence bundle (see :mod:`tpurpc.obs.bundle`): the trigger
and detail from ``meta.json``, the SLO alert states, the watchdog
diagnoses, the flight replay tail, the tsdb history summary, and the
waterfall — the whole detect→localize story in one terminal page.
Pointed at a root directory of bundles it lists them and renders the
newest. The bundle's flight dump is protocol-checkable as-is::

    python -m tpurpc.analysis protocol --flight <bundle-dir>
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Optional


def _load(path: str, fname: str):
    try:
        with open(os.path.join(path, fname), "r", encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _flight_dump(path: str) -> Optional[list]:
    for fn in sorted(os.listdir(path)):
        if fn.startswith("flight-") and fn.endswith(".json"):
            doc = _load(path, fn)
            if isinstance(doc, list):
                return doc
    return None


def render(path: str, flight_tail: int = 40) -> str:
    lines = [f"bundle: {path}", "=" * 64]
    meta = _load(path, "meta.json") or {}
    lines.append(f"trigger  {meta.get('trigger', '?')} "
                 f"(pid {meta.get('pid', '?')}, seq {meta.get('seq', '?')})")
    if meta.get("detail"):
        lines.append(f"detail   {meta['detail']}")

    slo = _load(path, "slo.json") or {}
    for obj in slo.get("objectives", ()):
        for track, st in (obj.get("tracks") or {}).items():
            if st.get("state") != "ok" or st.get("fired"):
                lines.append(
                    f"slo      {obj.get('name')}/{track}: "
                    f"state={st.get('state')} "
                    f"burn={st.get('burn_fast')}x/{st.get('burn_slow')}x "
                    f"fired={st.get('fired')}")
    stalls = _load(path, "stalls.json") or {}
    for d in (stalls.get("active") or [])[:5]:
        lines.append(f"stall    {d.get('method')}: stage={d.get('stage')} "
                     f"age={d.get('age_s')}s")
    for d in (stalls.get("history") or [])[-3:]:
        lines.append(f"stall(h) {d.get('method')}: stage={d.get('stage')}")

    hist = _load(path, "history.json") or {}
    n_series = len(hist.get("series") or {})
    if n_series:
        lines.append(f"history  {n_series} series over "
                     f"{hist.get('window_s')}s @ {hist.get('grain_s')}s "
                     f"grain (history.json)")
    wf = _load(path, "waterfall.json") or {}
    slow = wf.get("slowest_hop")
    if slow:
        lines.append(f"flow     slowest hop: {slow}")

    events = _flight_dump(path)
    if events:
        lines.append(f"flight   {len(events)} events; last {flight_tail}:")
        t0 = events[0].get("t_ns", 0)
        for e in events[-flight_tail:]:
            lines.append(
                f"  +{(e.get('t_ns', 0) - t0) / 1e6:10.3f}ms "
                f"{e.get('event', '?'):<22} {e.get('entity', '-'):<18} "
                f"a1={e.get('a1')} a2={e.get('a2')}")
        lines.append("verify   python -m tpurpc.analysis protocol "
                     f"--flight {path}")
    else:
        lines.append("flight   (no flight dump in bundle)")
    return "\n".join(lines) + "\n"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpurpc.tools.bundle",
        description="Render a tpurpc-argus evidence bundle.")
    ap.add_argument("path", help="a bundle directory, or a root of them")
    ap.add_argument("--tail", type=int, default=40,
                    help="flight events to show")
    args = ap.parse_args(argv)

    path = args.path
    if not os.path.isdir(path):
        print(f"bundle: {path} is not a directory", file=sys.stderr)
        return 1
    if not any(fn.startswith("flight-") or fn == "meta.json"
               for fn in os.listdir(path)):
        from tpurpc.obs.bundle import list_bundles

        names = list_bundles(path)
        if not names:
            print(f"bundle: no bundles under {path}", file=sys.stderr)
            return 1
        print(f"{len(names)} bundle(s) under {path}; rendering newest:")
        for n in names:
            print(f"  {n}")
        path = os.path.join(path, names[-1])
    sys.stdout.write(render(path, flight_tail=args.tail))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
