"""tpurpc-oracle smoke (ISSUE 20): induced fault -> correct rank-1
diagnosis, live AND via offline bundle replay.

Three distinct fault classes, each injected for real:

* **credit-starvation** — an open send-lease (reserve without commit)
  in the flight tail behind an in-flight call;
* **device-infer** — a slow peer: a call in flight with a quiet
  transport (no local anomaly to blame);
* **native-ctrl-frozen** — TPURPC_TEST_FREEZE_NCTRL freezes the C drain
  loop while a native client posts into an 8-slot ring (the real PR-19
  freeze; on rigs without the native plane a rendezvous wedge — an aged
  unanswered RDV_OFFER — substitutes as the third class).

For each fault the smoke asserts: (1) the LIVE ``/debug/diagnose``
route (through ``scrape._route``, the real dispatch) ranks the injected
cause #1; (2) the watchdog trip auto-captured a bundle whose
``diagnosis.json`` ranks it #1; (3) replaying that bundle offline
through ``tpurpc.tools.diagnose`` machinery agrees — live and offline
verdicts identical. Runs in one subprocess with
GRPC_PLATFORM_TYPE=RDMA_BPEV (read at import) so the native freeze is
real. Exit 0 = all faults diagnosed correctly both ways.

    python -m tpurpc.tools.diagnose_smoke
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import threading
import time


def _top_cause(doc: dict):
    hyps = doc.get("hypotheses") or []
    return hyps[0]["cause"] if hyps else None


def _live_doc():
    from tpurpc.obs import scrape

    status, _ctype, body = scrape._route("/debug/diagnose")
    assert status == 200, status
    return json.loads(body)


def _bundle_order(name: str):
    """Chronological sort key for bundle names: the trailing capture
    sequence number is unpadded, so plain lexical order puts -9 after
    -10 within one second."""
    head, _, seq = name.rpartition("-")
    try:
        return (head, int(seq))
    except ValueError:
        return (name, 0)


def _pick_bundle(expect: str, root: str, before: set,
                 deadline_s: float = 10.0):
    """Newest complete new bundle whose diagnosis names *expect* #1.

    The watchdog's background sweeper keeps tripping (once per distinct
    stage, on client AND server entries) while we read: a listed dir may
    be mid-write (diagnosis.json is written late in capture), and
    _enforce_caps may prune the very dir we just chose.  Earlier trips
    in the SAME phase legitimately diagnose the coarser stage they saw
    (the verdict sharpens as evidence ages), so the contract is: the
    trip fired at *expect* ships a bundle that ranks it #1 — wait out
    the write race for that newest bundle rather than trusting one
    listing."""
    from tpurpc.obs import bundle as obs_bundle

    deadline = time.monotonic() + deadline_s
    last_seen = None
    while True:
        new = sorted(
            (n for n in obs_bundle.list_bundles(root) if n not in before),
            key=_bundle_order)
        for name in reversed(new):
            path = os.path.join(root, name)
            try:
                with open(os.path.join(path, "diagnosis.json"),
                          encoding="utf-8") as f:
                    shipped = json.load(f)
            except (OSError, ValueError):
                continue  # mid-write or pruned underneath us
            last_seen = (name, _top_cause(shipped))
            if last_seen[1] == expect:
                return path, shipped
            break  # newest complete bundle predates the expect trip
        if time.monotonic() > deadline:
            raise AssertionError(
                f"no complete bundle ranks {expect} #1 "
                f"(newest complete: {last_seen}, new bundles: {new})")
        time.sleep(0.1)


def _check_fault(expect: str, root: str, before: set) -> None:
    """Live rank-1 correct, trip bundle written, offline replay agrees."""
    from tpurpc.obs import diagnose as obs_diagnose

    live = _live_doc()
    assert live.get("enabled"), live
    sym = live.get("symptom") or {}
    assert sym.get("stage") == expect, (expect, sym)
    live_top = _top_cause(live)
    assert live_top == expect, (
        f"live rank-1 was {live_top}, wanted {expect}",
        live.get("hypotheses"))
    # the trip auto-captured a bundle carrying diagnosis.json
    path, shipped = _pick_bundle(expect, root, before)
    assert _top_cause(shipped) == expect, (
        "diagnosis.json disagrees", _top_cause(shipped))
    # offline replay through the same engine: identical verdict
    offline = obs_diagnose.diagnose_bundle(path)
    off_top = _top_cause(offline)
    assert off_top == live_top == expect, (
        f"offline rank-1 {off_top} != live {live_top}")
    print(f"  [{expect}] live rank-1 OK, bundle "
          f"{os.path.basename(path)} agrees offline "
          f"(confidence {live['hypotheses'][0]['confidence']})")


def fault_credit_starvation(root: str) -> None:
    from tpurpc.obs import bundle as obs_bundle
    from tpurpc.obs import flight, watchdog

    wd = watchdog.get()
    flight.RECORDER.reset()
    wd.reset()
    before = set(obs_bundle.list_bundles(root))
    tag = flight.tag_for("pair:oracle-smoke")
    flight.emit(flight.LEASE_RESERVE, tag, 4096)
    tok = wd.call_started("/oracle/WedgedSend")
    try:
        time.sleep(3 * wd.min_stall_s)
        wd.sweep_once()
        _check_fault("credit-starvation", root, before)
    finally:
        wd.call_finished(tok)
        flight.emit(flight.LEASE_COMMIT, tag, 4096)
        wd.reset()


def fault_device_infer(root: str) -> None:
    from tpurpc.obs import bundle as obs_bundle
    from tpurpc.obs import flight, watchdog

    wd = watchdog.get()
    flight.RECORDER.reset()
    wd.reset()
    before = set(obs_bundle.list_bundles(root))
    tok = wd.call_started("/oracle/SlowPeer", kind="client")
    try:
        time.sleep(3 * wd.min_stall_s)
        wd.sweep_once()
        _check_fault("device-infer", root, before)
    finally:
        wd.call_finished(tok)
        wd.reset()


def fault_frozen_nctrl(root: str) -> None:
    """The real PR-19 freeze: TPURPC_TEST_FREEZE_NCTRL is read LIVE by
    the C drain loop; ring knobs are read at ring creation, so they are
    set before the server/channel exist (by run_phases)."""
    from tpurpc.obs import bundle as obs_bundle
    from tpurpc.obs import flight, native_obs, watchdog
    from tpurpc.rpc.channel import Channel
    from tpurpc.rpc.server import Server, stream_stream_rpc_method_handler

    wd = watchdog.get()
    flight.RECORDER.reset()
    native_obs.reset()
    wd.reset()
    before = set(obs_bundle.list_bundles(root))

    srv = Server(max_workers=4)

    def total(req_iter, ctx):
        n = 0
        for m in req_iter:
            n += len(m)
        yield str(n).encode()

    srv.add_method("/oraclesmoke.S/Total",
                   stream_stream_rpc_method_handler(total))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    payload = bytes(512) * 4096  # 2 MiB: no standing grant covers it
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.stream_stream("/oraclesmoke.S/Total")
            list(mc(iter([b"warm"]), timeout=30))  # hello + ring adoption
            os.environ["TPURPC_TEST_FREEZE_NCTRL"] = "1"
            tok = wd.call_started("/oraclesmoke.S/Total", kind="client")
            result: dict = {}

            def stalled():
                try:
                    result["out"] = list(
                        mc(iter([payload] * 8), timeout=120))
                finally:
                    wd.call_finished(tok)

            t = threading.Thread(target=stalled)
            t.start()
            found = False
            deadline = time.monotonic() + 30
            while not found and time.monotonic() < deadline:
                time.sleep(0.15)
                found = any(d["stage"] == "native-ctrl-frozen"
                            for d in wd.sweep_once())
            assert found, ("watchdog never named native-ctrl-frozen",
                           wd.active())
            _check_fault("native-ctrl-frozen", root, before)
            os.environ.pop("TPURPC_TEST_FREEZE_NCTRL", None)  # thaw
            t.join(timeout=120)
            assert not t.is_alive(), "frozen calls never completed"
    finally:
        os.environ.pop("TPURPC_TEST_FREEZE_NCTRL", None)
        wd.reset()
        srv.stop(grace=1)


def fault_rendezvous_substitute(root: str) -> None:
    """Third class on rigs without the native plane: an unanswered
    rendezvous offer aged behind an in-flight call."""
    from tpurpc.obs import bundle as obs_bundle
    from tpurpc.obs import flight, watchdog

    wd = watchdog.get()
    flight.RECORDER.reset()
    wd.reset()
    before = set(obs_bundle.list_bundles(root))
    tag = flight.tag_for("rdv:oracle-smoke")
    flight.emit(flight.RDV_OFFER, tag, 7)
    tok = wd.call_started("/oracle/BulkSend", kind="client")
    try:
        time.sleep(3 * wd.min_stall_s)
        wd.sweep_once()
        _check_fault("rendezvous", root, before)
    finally:
        wd.call_finished(tok)
        flight.emit(flight.RDV_RELEASE, tag, 0, 7)
        wd.reset()


def run_phases() -> int:
    from tpurpc.obs import bundle as obs_bundle
    from tpurpc.obs import native_obs, tracing, watchdog

    assert (os.environ.get("TPURPC_DIAGNOSE", "1") != "0"), \
        "smoke needs the diagnosis plane on"
    tracing.configure(0.0)
    wd = watchdog.get()
    wd.enabled = True
    wd.min_stall_s = 0.25
    wd.sweep_s = 0.1
    root = tempfile.mkdtemp(prefix="tpurpc-diagnose-smoke-")
    obs_bundle.enable(root, min_interval_s=0.0)
    try:
        fault_credit_starvation(root)
        fault_device_infer(root)
        if native_obs.available():
            fault_frozen_nctrl(root)
        else:
            print("  (native plane unavailable: rendezvous wedge "
                  "substitutes for the frozen-nctrl class)")
            fault_rendezvous_substitute(root)
    finally:
        obs_bundle.disable()
        wd.reset()
    print("diagnose smoke: PASS (3 fault classes rank-1 correct, "
          "live == bundle replay)")
    return 0


def main() -> int:
    if "--phase" in sys.argv:
        try:
            return run_phases()
        except Exception as exc:
            print(f"diagnose smoke FAILED: {exc!r}", file=sys.stderr)
            return 1
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ)
    # ring knobs are read at ring creation; the freeze env is read live
    env["GRPC_PLATFORM_TYPE"] = "RDMA_BPEV"
    env["TPURPC_CTRL_RING_SLOTS"] = "8"
    env["TPURPC_RENDEZVOUS_CLAIM_TIMEOUT_S"] = "0.5"
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = here + os.pathsep + env.get("PYTHONPATH", "")
    rc = subprocess.run(
        [sys.executable, "-m", "tpurpc.tools.diagnose_smoke", "--phase"],
        env=env, timeout=300).returncode
    if rc != 0:
        print("diagnose smoke FAILED")
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
