"""tpurpc-manycore smoke for the verification gate (tools/check.sh).

Stands up a 2-worker sharded server (fork + SO_REUSEPORT accept spread),
drives pipelined depth-4 traffic over enough distinct connections to land
on both shards, and asserts the manycore contract in ~2s with no jax:

* both shards actually served calls (per-shard ``srv_calls`` on the
  MERGED ``/metrics``, fetched through the serving port — whichever worker
  answers must aggregate its peers);
* the merged ``/debug/flight`` replay carries per-shard series: both
  workers' ``shard-start`` events, every event shard-tagged;
* ``tpurpc_shard_up`` enumerates exactly the running shards.

Exit 0 on success; any assertion/exception exits 1 with the reason.

    python -m tpurpc.tools.shard_smoke
"""

from __future__ import annotations

import json
import socket
import sys

WORKERS = 2
DEPTH = 4
CONNECTIONS = 8
PER_CONNECTION = 8


def _http_get(port: int, path: str, timeout: float = 10.0):
    with socket.create_connection(("127.0.0.1", port), timeout=timeout) as s:
        s.settimeout(timeout)
        s.sendall(f"GET {path} HTTP/1.0\r\n\r\n".encode())
        buf = bytearray()
        while True:
            chunk = s.recv(65536)
            if not chunk:
                break
            buf += chunk
    head, _, body = bytes(buf).partition(b"\r\n\r\n")
    return int(head.split(None, 2)[1]), body


def run() -> int:
    from tpurpc.rpc.channel import Channel
    from tpurpc.rpc.server import Server, unary_unary_rpc_method_handler
    from tpurpc.rpc.shard import ShardedServer

    def build(shard_id: int) -> Server:
        srv = Server(max_workers=8)
        srv.add_method("/smoke.S/Echo", unary_unary_rpc_method_handler(
            lambda req, ctx: bytes(req) + b"|" + str(shard_id).encode()))
        return srv

    sup = ShardedServer(build, workers=WORKERS,
                        listener="reuseport").start()
    try:
        served_by = set()
        total = 0
        for c in range(CONNECTIONS):
            with Channel(f"127.0.0.1:{sup.port}") as ch:
                pl = ch.unary_unary("/smoke.S/Echo",
                                    tpurpc_native=False).pipeline(DEPTH)
                futs = [pl.call_async(f"c{c}r{i}".encode(), timeout=20)
                        for i in range(PER_CONNECTION)]
                for i, f in enumerate(futs):
                    body, _, shard = bytes(f.result(timeout=25)).partition(
                        b"|")
                    assert body == f"c{c}r{i}".encode(), (
                        f"demux mix-up: {body!r} for c{c}r{i}")
                    served_by.add(int(shard))
                    total += 1
        assert total == CONNECTIONS * PER_CONNECTION
        assert served_by == set(range(WORKERS)), (
            f"accept spread left shards idle: only {sorted(served_by)} "
            f"served calls")

        # merged /metrics through the SERVING port: per-shard series + the
        # liveness roster, whichever worker answered the scrape
        status, body = _http_get(sup.port, "/metrics")
        assert status == 200, status
        text = body.decode()
        calls = {}
        for line in text.splitlines():
            if line.startswith("tpurpc_srv_calls{") and '/smoke.S/Echo' in line:
                shard = int(line.split('shard="', 1)[1].split('"', 1)[0])
                calls[shard] = calls.get(shard, 0) + int(float(
                    line.rsplit(" ", 1)[1]))
        assert set(calls) == set(range(WORKERS)), (
            f"/metrics missing per-shard srv_calls series: {calls}; "
            f"head: {text[:400]!r}")
        assert sum(calls.values()) == total, (calls, total)
        for k in range(WORKERS):
            assert f'tpurpc_shard_up{{shard="{k}"}} 1' in text

        # merged /debug/flight: both shards' lifecycles, every event tagged
        status, body = _http_get(sup.port, "/debug/flight")
        assert status == 200, status
        doc = json.loads(body)
        assert sorted(doc["shards"]) == list(range(WORKERS)), doc["shards"]
        starts = {(e["a1"], e.get("shard")) for e in doc["events"]
                  if e["event"] == "shard-start"}
        assert starts == {(k, k) for k in range(WORKERS)}, starts
        untagged = [e for e in doc["events"] if "shard" not in e]
        assert not untagged, f"untagged flight events: {untagged[:3]}"

        print(f"shard smoke: {WORKERS} workers, depth={DEPTH}, {total} "
              f"pipelined requests spread as {dict(sorted(calls.items()))}; "
              "merged /metrics + /debug/flight carry per-shard series")
        return 0
    finally:
        sup.stop()


def main() -> int:
    try:
        return run()
    except BaseException as exc:  # the gate wants a reasoned nonzero exit
        print(f"shard smoke FAILED: {exc!r}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
