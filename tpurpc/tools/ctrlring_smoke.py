"""tpurpc-pulse smoke (ISSUE 13): descriptor-ring control plane, two
processes over shm.

Phase 1 (cross-process, the deployment shape): a server SUBPROCESS and
this client stream 1 MiB tensors over the rendezvous plane with the
descriptor-ring control plane on (the default):

* both sides must ADOPT the ring (ctrl-adopt in the client's flight ring;
  the server reports its counters per stream);
* the steady-state stream must carry ZERO control frames after warmup —
  ``rdv_ctrl_frames`` flat on BOTH sides while ``ctrl_ring_posts`` carries
  every OFFER/CLAIM/COMPLETE;
* payload integrity end to end (byte totals + corner values).

Phase 2 (in-process): an induced STUCK RING — the ``freeze_drain`` test
hook stops every consumer, so a bulk send's OFFER ages in the ring — must
be attributed by the stall watchdog to the new ``ctrl-ring`` stage, and
the call must still COMPLETE via the framed fallback once the claim times
out (the zero-failed-RPC degradation ladder).

Exit 0 = both phases passed.  Runs under TPURPC_FLIGHT_DUMP in
tools/check.sh, so the protocol-conformance stage replays the ctrl-ring
machines over everything this smoke emitted.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

N_MSGS = 16
SHAPE = (512, 512)  # 1 MiB float32

_SERVER = r"""
import json, os, sys
import numpy as np

from tpurpc.jaxshim import add_tensor_method
from tpurpc.rpc.server import Server

srv = Server(max_workers=4, native_dataplane=False)
port = srv.add_insecure_port("127.0.0.1:0")
print("PORT", port, flush=True)

def consume(req_iter):
    total = 0
    corner = 0.0
    for tree in req_iter:
        arr = tree["x"]
        total += arr.nbytes
        corner = float(arr[-1, -1])
    from tpurpc.obs import metrics
    reg = metrics.registry().metrics()
    snap = {name: reg[name].snapshot() for name in
            ("rdv_ctrl_frames", "ctrl_ring_posts", "ctrl_ring_records",
             "rdv_transfers_received") if name in reg}
    print("CTRLSTATS", json.dumps(snap), flush=True)
    yield {"bytes": np.int64(total), "corner": np.float64(corner)}

add_tensor_method(srv, "Sink", consume, kind="stream_stream")
srv.start()
print("READY", flush=True)
srv.wait_for_termination(timeout=180)
"""


def phase_cross_process() -> None:
    import numpy as np

    from tpurpc.jaxshim import TensorClient
    from tpurpc.obs import flight, metrics
    from tpurpc.rpc.channel import Channel

    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = here + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen([sys.executable, "-c", _SERVER],
                            stdout=subprocess.PIPE, text=True, env=env)
    lines: list = []
    ready = threading.Event()

    def pump():
        for line in proc.stdout:
            lines.append(line)
            if line.startswith("READY"):
                ready.set()

    threading.Thread(target=pump, daemon=True).start()
    try:
        assert ready.wait(60), "server subprocess never came up"
        port = int([ln for ln in lines if ln.startswith("PORT")][0]
                   .split()[1])
        payload = np.arange(SHAPE[0] * SHAPE[1], dtype=np.float32).reshape(
            SHAPE)
        with Channel(f"127.0.0.1:{port}") as ch:
            cli = TensorClient(ch)

            def gen(k):
                for _ in range(k):
                    yield {"x": payload}

            # warmup: hello + ring adoption + standing grants settle
            list(cli.duplex("Sink", gen(2), native=False, timeout=60))
            stats_seen = len([ln for ln in lines
                              if ln.startswith("CTRLSTATS")])
            reg = metrics.registry().metrics()
            frames0 = reg["rdv_ctrl_frames"].snapshot()
            posts0 = reg["ctrl_ring_posts"].snapshot()
            deadline = time.monotonic() + 20
            while (len([ln for ln in lines if ln.startswith("CTRLSTATS")])
                   < stats_seen and time.monotonic() < deadline):
                time.sleep(0.05)
            warm_lines = [ln for ln in lines if ln.startswith("CTRLSTATS")]
            srv_warm = json.loads(warm_lines[-1].split(" ", 1)[1])

            # the steady-state stream the zero-frames claim is about
            replies = list(cli.duplex("Sink", gen(N_MSGS), native=False,
                                      timeout=120))
            total = int(np.asarray(replies[-1]["bytes"]).ravel()[0])
            assert total == N_MSGS * payload.nbytes, (total, N_MSGS)
            corner = float(np.asarray(replies[-1]["corner"]).ravel()[0])
            assert abs(corner - float(payload[-1, -1])) < 1e-3, corner

            frames = reg["rdv_ctrl_frames"].snapshot() - frames0
            posts = reg["ctrl_ring_posts"].snapshot() - posts0
            assert frames == 0, (
                f"steady-state stream sent {frames} framed control ops "
                "(want 0: every OFFER/CLAIM/COMPLETE on the ring)")
            assert posts >= N_MSGS, (
                f"only {posts} ring posts for {N_MSGS} bulk messages")
            evs = [e["event"] for e in flight.snapshot()]
            assert "ctrl-adopt" in evs, (
                "client never adopted the peer's descriptor ring", evs)

            deadline = time.monotonic() + 20
            while (len([ln for ln in lines if ln.startswith("CTRLSTATS")])
                   <= len(warm_lines) and time.monotonic() < deadline):
                time.sleep(0.05)
            srv_end = json.loads(
                [ln for ln in lines if ln.startswith("CTRLSTATS")][-1]
                .split(" ", 1)[1])
            srv_frames = (srv_end.get("rdv_ctrl_frames", 0)
                          - srv_warm.get("rdv_ctrl_frames", 0))
            assert srv_frames == 0, (
                f"server sent {srv_frames} framed control ops during the "
                "steady stream (want 0)")
            got = (srv_end.get("rdv_transfers_received", 0)
                   - srv_warm.get("rdv_transfers_received", 0))
            assert got == N_MSGS, (got, N_MSGS)
        print(f"  [shm x 2 processes] {N_MSGS} x 1 MiB rendezvous'd: "
              f"{posts} ring posts, 0 control frames either side, "
              "ring adoption in flight")
    finally:
        proc.kill()


def phase_stuck_ring() -> None:
    """In-process: freeze every ring consumer, wedge a bulk send, and the
    watchdog must name the ``ctrl-ring`` stage; the framed fallback (claim
    timeout) must still complete the call."""
    import numpy as np

    import tpurpc.core.ctrlring as ctrlring
    from tpurpc.jaxshim import TensorClient, add_tensor_method
    from tpurpc.obs import watchdog
    from tpurpc.rpc.channel import Channel
    from tpurpc.rpc.server import Server

    srv = Server(max_workers=4, native_dataplane=False)

    def consume(req_iter):
        total = 0
        for tree in req_iter:
            total += np.asarray(tree["x"]).nbytes
        yield {"bytes": np.int64(total)}

    add_tensor_method(srv, "Sink", consume, kind="stream_stream")
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    payload = np.ones((512, 1024), np.float32)  # 2 MiB
    wd = watchdog.get()
    wd.reset()
    prev = (wd.min_stall_s, wd.sweep_s)
    wd.min_stall_s, wd.sweep_s = 0.3, 0.1
    os.environ["TPURPC_RENDEZVOUS_CLAIM_TIMEOUT_S"] = "3"
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            cli = TensorClient(ch)
            # warm on a DIFFERENT size class so no standing grant
            # short-circuits the frozen ring
            list(cli.duplex("Sink", iter([{"x": np.ones((128, 128),
                                                        np.float32)}]),
                            native=False, timeout=60))
            ctrlring.TEST_HOOKS["freeze_drain"] = True
            result: dict = {}

            def stalled():
                result["replies"] = list(
                    cli.duplex("Sink", iter([{"x": payload}]),
                               native=False, timeout=60))

            t = threading.Thread(target=stalled)
            t.start()
            diag = None
            deadline = time.monotonic() + 10
            while diag is None and time.monotonic() < deadline:
                time.sleep(0.15)
                for d in wd.sweep_once():
                    if d["stage"] == "ctrl-ring":
                        diag = d
                        break
            assert diag is not None, (
                "watchdog never named the ctrl-ring stage", wd.active())
            ctrlring.TEST_HOOKS.pop("freeze_drain", None)
            t.join(timeout=60)
            assert not t.is_alive(), "stalled call never completed"
            total = int(np.asarray(
                result["replies"][-1]["bytes"]).ravel()[0])
            assert total == payload.nbytes
        print(f"  [stuck ring] watchdog named '{diag['stage']}' "
              f"({diag['detail'][:58]}...); framed fallback completed "
              "the call")
    finally:
        ctrlring.TEST_HOOKS.pop("freeze_drain", None)
        os.environ.pop("TPURPC_RENDEZVOUS_CLAIM_TIMEOUT_S", None)
        wd.min_stall_s, wd.sweep_s = prev
        wd.reset()
        srv.stop(grace=1)


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("GRPC_PLATFORM_TYPE", "RDMA_BPEV")
    phase_cross_process()
    phase_stuck_ring()
    print("ctrlring smoke: PASS (2-process shm rings, zero steady-state "
          "control frames, ctrl-ring stall attributed)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
