"""tpurpc command-line tool — the grpcurl-shaped workflow over tpurpc.

The reference ecosystem's debugging loop is `grpcurl list/describe/call`
against the reflection service (``src/cpp/ext/proto_server_reflection.cc``);
this is that loop as a first-party tool over tpurpc's native framing:

    python -m tpurpc.tools.cli list host:port
    python -m tpurpc.tools.cli health host:port [service]
    python -m tpurpc.tools.cli call host:port /pkg.Svc/Method [payload]
    python -m tpurpc.tools.cli ping host:port

``call`` sends the payload bytes verbatim (or stdin when omitted; prefix
with @file to read a file) and prints the raw response — codecs live in
generated stubs, not here. Exit code 0 on OK, the gRPC status code
otherwise (grpcurl convention).
"""

from __future__ import annotations

import argparse
import sys

from tpurpc.rpc.channel import Channel
from tpurpc.rpc.status import RpcError


def _channel(target: str) -> Channel:
    return Channel(target)


def cmd_list(args) -> int:
    from tpurpc.rpc.reflection import V1ALPHA_SERVICE
    from tpurpc.wire.protowire import fields, ld

    with _channel(args.target) as ch:
        mc = ch.stream_stream(f"/{V1ALPHA_SERVICE}/ServerReflectionInfo")
        reply = next(iter(mc(iter([ld(7, b"")]), timeout=args.timeout)))
    names = []
    for f, _w, v in fields(bytes(reply)):
        if f == 6:
            for f2, _w2, v2 in fields(bytes(v)):
                if f2 == 1:
                    for f3, _w3, v3 in fields(bytes(v2)):
                        if f3 == 1:
                            names.append(bytes(v3).decode())
    for n in sorted(names):
        print(n)
    return 0


def cmd_describe(args) -> int:
    """Resolve a symbol via reflection and print its descriptor (grpcurl
    describe). Uses the real protobuf runtime for parsing — a tools-only
    dependency; the services themselves stay protobuf-free."""
    from google.protobuf import descriptor_pb2

    from tpurpc.rpc.reflection import V1ALPHA_SERVICE
    from tpurpc.wire.protowire import fields, ld

    with _channel(args.target) as ch:
        mc = ch.stream_stream(f"/{V1ALPHA_SERVICE}/ServerReflectionInfo")
        reply = next(iter(mc(iter([ld(4, args.symbol.encode())]),
                             timeout=args.timeout)))
    fdp_blobs = []
    err = None
    for f, _w, v in fields(bytes(reply)):
        if f == 4:  # file_descriptor_response
            for f2, _w2, v2 in fields(bytes(v)):
                if f2 == 1:
                    fdp_blobs.append(bytes(v2))
        elif f == 7:  # error_response
            msg = b""
            for f2, _w2, v2 in fields(bytes(v)):
                if f2 == 2:
                    msg = bytes(v2)
            err = msg.decode("utf-8", "replace")
    if err is not None:
        print(f"error: {err}", file=sys.stderr)
        return 5  # NOT_FOUND
    for raw in fdp_blobs:
        fdp = descriptor_pb2.FileDescriptorProto.FromString(raw)
        print(f"file: {fdp.name}  package: {fdp.package}")
        for svc in fdp.service:
            print(f"service {fdp.package + '.' if fdp.package else ''}"
                  f"{svc.name} {{")
            for m in svc.method:
                cs = "stream " if m.client_streaming else ""
                ss = "stream " if m.server_streaming else ""
                print(f"  rpc {m.name}({cs}{m.input_type}) returns "
                      f"({ss}{m.output_type});")
            print("}")
        for msg in fdp.message_type:
            fields_s = ", ".join(f"{fld.name}={fld.number}"
                                 for fld in msg.field)
            print(f"message {msg.name} {{ {fields_s} }}")
    return 0


def cmd_health(args) -> int:
    from tpurpc.rpc import health

    with _channel(args.target) as ch:
        mc = ch.unary_unary("/grpc.health.v1.Health/Check")
        try:
            raw = mc(health.encode_request(args.service or ""),
                     timeout=args.timeout)
        except RpcError as exc:
            print(f"error: {exc.code().name}: {exc.details()}",
                  file=sys.stderr)
            return exc.code().value
    status = health.decode_response(raw)
    print(status.name)
    return 0 if status is health.ServingStatus.SERVING else 1


def cmd_call(args) -> int:
    if args.payload is None:
        payload = sys.stdin.buffer.read()
    elif args.payload.startswith("@"):
        try:
            with open(args.payload[1:], "rb") as f:
                payload = f.read()
        except OSError as exc:
            # local usage error, NOT a network failure: exit 2 (argparse's
            # usage-error code), never a gRPC status a script would retry
            print(f"error: cannot read payload file: {exc}", file=sys.stderr)
            return 2
    else:
        payload = args.payload.encode()
    with _channel(args.target) as ch:
        mc = ch.unary_unary(args.method)
        try:
            resp, call = mc.with_call(payload, timeout=args.timeout)
        except RpcError as exc:
            print(f"error: {exc.code().name}: {exc.details()}",
                  file=sys.stderr)
            return exc.code().value
        sys.stdout.buffer.write(bytes(resp))
        sys.stdout.buffer.flush()
        for k, v in call.trailing_metadata() or ():
            print(f"\n{k}: {v}", file=sys.stderr)
    return 0


def cmd_ping(args) -> int:
    with _channel(args.target) as ch:
        try:
            rtt = ch.ping(timeout=args.timeout)
        except RpcError as exc:
            print(f"error: {exc.code().name}: {exc.details()}",
                  file=sys.stderr)
            return exc.code().value
    print(f"{rtt * 1e6:.0f} us")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m tpurpc.tools.cli",
                                 description=__doc__.splitlines()[0])
    ap.add_argument("--timeout", type=float, default=20.0)
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("list", help="reflection: list services")
    p.add_argument("target")
    p.set_defaults(fn=cmd_list)
    p = sub.add_parser("describe", help="reflection: describe a symbol")
    p.add_argument("target")
    p.add_argument("symbol")
    p.set_defaults(fn=cmd_describe)
    p = sub.add_parser("health", help="grpc.health.v1 check")
    p.add_argument("target")
    p.add_argument("service", nargs="?", default="")
    p.set_defaults(fn=cmd_health)
    p = sub.add_parser("call", help="unary call with raw bytes")
    p.add_argument("target")
    p.add_argument("method")
    p.add_argument("payload", nargs="?", default=None)
    p.set_defaults(fn=cmd_call)
    p = sub.add_parser("ping", help="transport-level PING round trip")
    p.add_argument("target")
    p.set_defaults(fn=cmd_ping)
    args = ap.parse_args(argv)
    try:
        return args.fn(args)
    except RpcError as exc:
        print(f"error: {exc.code().name}: {exc.details()}", file=sys.stderr)
        return exc.code().value
    except (ConnectionError, OSError) as exc:
        print(f"error: {exc}", file=sys.stderr)
        return 14  # UNAVAILABLE


if __name__ == "__main__":
    raise SystemExit(main())
