"""tpurpc-lens smoke for the verification gate (tools/check.sh, ISSUE 8).

Runs a short burst of streaming (ring-plane tensor duplex) + serving
(unary echo) traffic in-process, plus one tiny SUBPROCESS member, and
asserts the three lens faces work end to end:

* the stage-tagged sampling profiler attributes samples to >=3 known
  stages (and the unattributed share stays under the 20% bar);
* ``/debug/waterfall`` reports EVERY declared hop with nonzero bytes and
  names a slowest hop;
* ``python -m tpurpc.tools.timeline`` against this process + the
  subprocess emits a Perfetto-loadable chrome-trace JSON with >=2 named
  process lanes, rebased on per-process clock anchors.

~15s (jax on cpu pays the import). Exit 0 on success; any assertion or
exception exits 1 with the reason.

    python -m tpurpc.tools.lens_smoke
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time
import urllib.request

os.environ.setdefault("GRPC_PLATFORM_TYPE", "RDMA_BPEV")
os.environ.setdefault("TPURPC_LENS_HZ", "200")  # smoke: sample fast
os.environ.setdefault("JAX_PLATFORMS", "cpu")

_PEER_CODE = r"""
import sys, time
from tpurpc.obs import tracing
from tpurpc.rpc.server import Server, unary_unary_rpc_method_handler

tracing.force(True)
srv = Server(max_workers=2)
srv.add_method("/lens/Echo",
               unary_unary_rpc_method_handler(lambda req, ctx: bytes(req)))
port = srv.add_insecure_port("127.0.0.1:0")
srv.start()
print("PORT", port, flush=True)
time.sleep(float(sys.argv[1]))
"""


def run() -> int:
    import numpy as np

    from tpurpc.jaxshim import TensorClient
    from tpurpc.obs import lens, profiler, tracing
    from tpurpc.rpc.channel import Channel
    from tpurpc.rpc.server import Server, unary_unary_rpc_method_handler
    from tpurpc.tpu.hbm_ring import HbmRing

    # -- local member: streaming + serving on the instrumented plane ------
    from tpurpc.jaxshim.service import add_tensor_method

    srv = Server(max_workers=8, native_dataplane=False)
    add_tensor_method(srv, "Sink", _sink, kind="stream_stream")
    srv.add_method("/lens/Echo",
                   unary_unary_rpc_method_handler(
                       lambda req, ctx: bytes(req)))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    assert profiler.get().running(), "Server.start did not start the sampler"
    tracing.force(True)

    peer = subprocess.Popen([sys.executable, "-u", "-c", _PEER_CODE, "60"],
                            stdout=subprocess.PIPE, text=True)
    try:
        peer_port = int(peer.stdout.readline().split()[1])

        payload = np.ones((512, 512), np.float32)  # 1 MiB

        def gen(k):
            for _ in range(k):
                yield {"x": payload}

        with Channel(f"127.0.0.1:{port}") as ch:
            cli = TensorClient(ch)
            deadline = time.monotonic() + 10.0
            rounds = 0
            while True:
                replies = list(cli.duplex("Sink", gen(24), native=False,
                                          timeout=60))
                total = int(np.asarray(replies[-1]["bytes"]).ravel()[0])
                assert total == 24 * payload.nbytes, (total, rounds)
                rounds += 1
                snap = profiler.snapshot()
                named = [s for s in snap["stages"]
                         if s in profiler.STAGES and snap["stages"][s] > 0]
                if len(named) >= 3 and rounds >= 2:
                    break
                if time.monotonic() > deadline:
                    break
            mc = ch.unary_unary("/lens/Echo")
            for i in range(32):
                assert mc(b"e%d" % i, timeout=10) == b"e%d" % i
        with Channel(f"127.0.0.1:{peer_port}") as ch2:
            mc2 = ch2.unary_unary("/lens/Echo")
            for i in range(8):
                assert mc2(b"p%d" % i, timeout=10) == b"p%d" % i

        # the hbm + jax_array device hops: a real HbmRing placement and a
        # lease-backed view (emulated device plane, same accounting)
        ring = HbmRing(1 << 16)
        off, n = ring.place(np.arange(4096, dtype=np.uint8))
        lease = ring.view(off, n)
        assert lease.array.shape == (4096,)
        lease.release()

        # -- face 1: profiler names >=3 known stages ----------------------
        snap = profiler.snapshot()
        named = sorted(s for s in snap["stages"]
                       if s in profiler.STAGES and snap["stages"][s] > 0)
        assert len(named) >= 3, \
            f"profiler named only {named} over {snap['samples']} samples"
        assert snap["attributed_pct"] >= 80.0, \
            f"unattributed share too high: {snap}"
        assert snap["top_stacks"], "no collapsed stacks collected"

        # -- face 2: waterfall reports every declared hop -----------------
        wf = _get_json(port, "/debug/waterfall")
        by_hop = {r["hop"]: r for r in wf["hops"]}
        assert tuple(by_hop) == lens.HOP_NAMES, by_hop.keys()
        idle = [h for h, r in by_hop.items() if r["bytes"] == 0]
        assert not idle, f"hops with zero bytes after traffic: {idle}"
        assert wf["slowest_hop"] in by_hop, wf["slowest_hop"]
        assert "ledger" in wf, "copy ledger not folded into the waterfall"
        text = _get_text(port, "/debug/waterfall?text=1")
        assert "slowest" in text, text

        # profile served on the serving port too (+collapsed)
        prof = _get_json(port, "/debug/profile")
        assert prof["samples"] > 0 and prof["stage_pct"], prof
        assert _get_text(port, "/debug/profile?collapsed=1").strip(), \
            "empty collapsed-stack export"

        # -- face 3: timeline tool over both members ----------------------
        out = os.path.join(tempfile.mkdtemp(prefix="tpurpc-lens-"),
                           "timeline.json")
        from tpurpc.tools import timeline as tl

        rc = tl.main([f"127.0.0.1:{port}", f"127.0.0.1:{peer_port}",
                      "-o", out])
        assert rc == 0, f"timeline tool exit {rc}"
        with open(out, encoding="utf-8") as f:
            doc = json.load(f)  # valid JSON is the Perfetto bar
        lanes = [e for e in doc["traceEvents"]
                 if e.get("ph") == "M" and e.get("name") == "process_name"]
        assert len(lanes) >= 2, f"{len(lanes)} process lane(s)"
        spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        assert spans, "timeline carries no span/sample events"
        assert not doc["otherData"]["unanchored"], \
            f"members exported no clock anchor: {doc['otherData']}"
        # rebased timestamps must be non-negative and sane (< 1 day span)
        ts = [e["ts"] for e in doc["traceEvents"] if "ts" in e]
        assert min(ts) >= 0 and max(ts) - min(ts) < 86_400e6, \
            (min(ts), max(ts))

        print(f"lens smoke OK: stages {named}, "
              f"attributed {snap['attributed_pct']}%, "
              f"slowest hop {wf['slowest_hop']}, "
              f"timeline {len(lanes)} lanes / {len(spans)} events")
        return 0
    finally:
        tracing.force(None)
        peer.kill()
        srv.stop(0)


def _sink(req_iter):
    import numpy as np

    from tpurpc.jaxshim import to_jax

    total = 0
    for tree in req_iter:
        arr = to_jax(tree["x"])
        total += arr.nbytes
    yield {"bytes": np.int64(total)}


def _get_json(port: int, path: str) -> dict:
    return json.loads(_get_text(port, path))


def _get_text(port: int, path: str) -> str:
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=10) as resp:
        return resp.read().decode("utf-8", "replace")


def main() -> int:
    try:
        return run()
    except AssertionError as exc:
        print(f"lens smoke FAILED: {exc}", file=sys.stderr)
        return 1
    except Exception as exc:  # noqa: BLE001 — smoke: any failure is a fail
        import traceback

        traceback.print_exc()
        print(f"lens smoke FAILED: {exc!r}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    raise SystemExit(main())
