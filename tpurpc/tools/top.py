"""tpurpc-top: a terminal dashboard over the introspection plane.

Polls a tpurpc process's Prometheus endpoint (any serving port answers
``GET /metrics`` — see tpurpc/obs/scrape.py) and renders live QPS, handler
latency percentiles, ring occupancy/credits, pipelined-window depth, the
fan-in batcher's batch-size/flush-reason profile, and — tpurpc-blackbox
(ISSUE 5) — a stalls/anomalies pane fed by ``/debug/stalls`` (active
watchdog diagnoses with their attributed stage, plus the trip counters),
and — tpurpc-odyssey (ISSUE 15) — a ``seq`` pane fed by ``/debug/seq``
(top sequences by device step-ms and KV byte-seconds, per-account cost
rollup), and — tpurpc-xray (ISSUE 19) — a ``natv`` pane from the
``native_*`` series the scrape mirrors out of the C core's shm metrics
table (rdv ledger, ctrl drain cadence, fallbacks, pin/delivery pressure),
and — tpurpc-oracle (ISSUE 20) — a ``diag`` pane fed by
``/debug/diagnose``: when a symptom is active, the top ranked cause with
confidence and the suggested action.

    python -m tpurpc.tools.top HOST:PORT [--interval 1.0] [--once]

``--once`` prints a single snapshot (no screen clearing) — what the CI
metrics smoke and scripts use. When stdout is not a TTY (CI logs, pipes),
one-shot mode is the automatic default: no ANSI clears in captured logs.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
import time
import urllib.request
from typing import Dict, Optional, Tuple

_LINE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>[-+0-9.eE]+|NaN)$")


def parse_prometheus(text: str) -> Dict[Tuple[str, str], float]:
    """{(name, labels): value} for every sample line (types ignored)."""
    out: Dict[Tuple[str, str], float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _LINE.match(line)
        if m is None:
            continue
        try:
            out[(m.group("name"), m.group("labels") or "")] = float(
                m.group("value"))
        except ValueError:
            continue
    return out


def fetch(target: str, timeout: float = 5.0) -> Dict[Tuple[str, str], float]:
    url = f"http://{target}/metrics"
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return parse_prometheus(resp.read().decode("utf-8", "replace"))


def fetch_stalls(target: str, timeout: float = 5.0) -> Optional[dict]:
    """The watchdog's /debug/stalls snapshot, or None when unreachable /
    pre-blackbox server (the dashboard degrades to 'n/a', never dies)."""
    try:
        with urllib.request.urlopen(f"http://{target}/debug/stalls",
                                    timeout=timeout) as resp:
            return json.loads(resp.read().decode("utf-8", "replace"))
    except Exception:
        return None


def fetch_waterfall(target: str, timeout: float = 5.0) -> Optional[dict]:
    """tpurpc-lens /debug/waterfall (per-hop effective GB/s), or None when
    unreachable / pre-lens server."""
    try:
        with urllib.request.urlopen(f"http://{target}/debug/waterfall",
                                    timeout=timeout) as resp:
            return json.loads(resp.read().decode("utf-8", "replace"))
    except Exception:
        return None


def fetch_slo(target: str, timeout: float = 5.0) -> Optional[dict]:
    """tpurpc-argus /debug/slo (objectives + burn-rate alert states), or
    None when unreachable / pre-argus server."""
    try:
        with urllib.request.urlopen(f"http://{target}/debug/slo",
                                    timeout=timeout) as resp:
            return json.loads(resp.read().decode("utf-8", "replace"))
    except Exception:
        return None


def fetch_seq(target: str, timeout: float = 5.0) -> Optional[dict]:
    """tpurpc-odyssey /debug/seq (per-sequence cost ledgers + account
    rollup), or None when unreachable / pre-odyssey server."""
    try:
        with urllib.request.urlopen(f"http://{target}/debug/seq",
                                    timeout=timeout) as resp:
            return json.loads(resp.read().decode("utf-8", "replace"))
    except Exception:
        return None


def fetch_diagnose(target: str, timeout: float = 5.0) -> Optional[dict]:
    """tpurpc-oracle /debug/diagnose (ranked causal hypotheses for the
    active symptom), or None when unreachable / pre-oracle server."""
    try:
        with urllib.request.urlopen(f"http://{target}/debug/diagnose",
                                    timeout=timeout) as resp:
            return json.loads(resp.read().decode("utf-8", "replace"))
    except Exception:
        return None


def _val(m: Dict, name: str, labels: str = "") -> float:
    return m.get((name, labels), 0.0)


def _sum_label(m: Dict, name: str, needle: str = "") -> float:
    return sum(v for (n, lab), v in m.items()
               if n == name and needle in lab)


def _fmt_us(us: float) -> str:
    if us >= 1e6:
        return f"{us / 1e6:.2f}s"
    if us >= 1e3:
        return f"{us / 1e3:.1f}ms"
    return f"{us:.0f}us"


def render(cur: Dict, prev: Optional[Dict], dt: float,
           target: str, stalls: Optional[dict] = None,
           waterfall: Optional[dict] = None,
           slo: Optional[dict] = None,
           seq: Optional[dict] = None,
           diagnose: Optional[dict] = None) -> str:
    P = "tpurpc_"
    Q50 = 'quantile="0.5"'
    Q99 = 'quantile="0.99"'
    lines = []
    lines.append(f"tpurpc-top — {target} — {time.strftime('%H:%M:%S')}")
    lines.append("=" * 64)

    def rate(name: str, labels: str = "") -> float:
        if prev is None or dt <= 0:
            return 0.0
        return max(0.0, (_val(cur, name, labels)
                         - _val(prev, name, labels))) / dt

    # QPS from channelz call counters (sum across entities)
    def crate(kind: str) -> float:
        if prev is None or dt <= 0:
            return 0.0
        name = P + "channelz_calls"
        now = sum(v for (n, lab), v in cur.items()
                  if n == name and f'kind="{kind}"' in lab)
        was = sum(v for (n, lab), v in (prev or {}).items()
                  if n == name and f'kind="{kind}"' in lab)
        return max(0.0, now - was) / dt

    lines.append(f"rpc   qps {crate('started'):8.1f}   "
                 f"ok/s {crate('succeeded'):8.1f}   "
                 f"fail/s {crate('failed'):6.1f}   "
                 f"streams {int(_sum_label(cur, P + 'channelz_streams')):4d}")
    lines.append(
        f"lat   srv p50 {_fmt_us(_val(cur, P + 'srv_call_us', Q50)):>8}  "
        f"p99 {_fmt_us(_val(cur, P + 'srv_call_us', Q99)):>8}   "
        f"pipe p50 {_fmt_us(_val(cur, P + 'pipeline_call_us', Q50)):>8}  "
        f"p99 {_fmt_us(_val(cur, P + 'pipeline_call_us', Q99)):>8}")
    lines.append(
        f"ring  in-flight {int(_val(cur, P + 'ring_in_flight_bytes')):>10}B  "
        f"unpub-credit {int(_val(cur, P + 'ring_credit_unpublished_bytes')):>8}B  "
        f"msgs/s in {rate(P + 'ring_msgs_read'):8.0f} "
        f"out {rate(P + 'ring_msgs_written'):8.0f}")
    lines.append(
        f"pipe  in-flight {int(_val(cur, P + 'pipeline_inflight')):>4} over "
        f"{int(_val(cur, P + 'pipeline_inflight_objects')):>3} windows   "
        f"pairs {int(_val(cur, P + 'pairs_connected')):>3} "
        f"(stalled {int(_val(cur, P + 'pairs_write_stalled'))})")
    lines.append(
        f"wake  spin-hit/s {rate(P + 'wait_spin_hit'):7.0f}  "
        f"spin-miss/s {rate(P + 'wait_spin_miss'):7.0f}  "
        f"sleep/s {rate(P + 'wait_sleep'):7.0f}")
    lines.append(
        f"batch fanin p50 {int(_val(cur, P + 'fanin_batch', Q50)):>3}  "
        f"p99 {int(_val(cur, P + 'fanin_batch', Q99)):>3}  "
        f"rows/s {rate(P + 'batcher_rows'):8.0f}  "
        "flush size/timer/drained "
        f"{int(_val(cur, P + 'batcher_flush_size'))}/"
        f"{int(_val(cur, P + 'batcher_flush_timer'))}/"
        f"{int(_val(cur, P + 'batcher_flush_drained'))}")
    lines.append(
        f"coal  resp p50 {int(_val(cur, P + 'resp_coalesce', Q50)):>3}  "
        f"h2-data p50 {int(_val(cur, P + 'h2_data_coalesce', Q50)):>3}   "
        f"drain p50 {int(_val(cur, P + 'ring_drain', Q50)):>3} "
        f"msgs/wakeup")
    led = {k[1]: v for k, v in cur.items() if k[0] == P + "ledger_bytes"}
    if led:
        hc = led.get('kind="host_copy"', 0)
        zc = led.get('kind="zero_copy"', 0)
        lines.append(f"copy  host {int(hc):>12}B   zero-copy {int(zc):>12}B")
    # tpurpc-xray native-plane pane (ISSUE 19): the native_* series the
    # scrape mirrors out of the C core's shm metrics table — rdv ledger,
    # ctrl-ring drain cadence, fallbacks, pin/delivery pressure. Absent
    # (emitted == 0) on python-plane-only processes.
    if _val(cur, P + "native_emitted") > 0:
        lines.append(
            f"natv  rdv sent "
            f"{int(_val(cur, P + 'native_rdv_send_bytes')):>12}B  recv "
            f"{int(_val(cur, P + 'native_rdv_recv_bytes')):>12}B  "
            f"waits {int(_val(cur, P + 'native_rdv_waits'))}  "
            f"fallbacks {int(_val(cur, P + 'native_rdv_fallbacks'))}")
        lines.append(
            f"      ctrl drains/s {rate(P + 'native_ctrl_drain_batches'):7.0f} "
            f"({rate(P + 'native_ctrl_drain_records'):8.0f} rec/s)  "
            f"posts/s {rate(P + 'native_ctrl_posts'):7.0f}  "
            f"kicks/s {rate(P + 'native_ctrl_kicks'):5.0f}  "
            f"frames {int(_val(cur, P + 'native_ctrl_frames'))}")
        lines.append(
            f"      pin-waits {int(_val(cur, P + 'native_pin_waits'))} "
            f"({_fmt_us(_val(cur, P + 'native_pin_wait_ns') / 1e3):>7})  "
            f"dlv depth {int(_val(cur, P + 'native_dlv_depth')):>4} "
            f"stalls {int(_val(cur, P + 'native_dlv_stalls'))}  conns "
            f"{int(_val(cur, P + 'native_conn_up') - _val(cur, P + 'native_conn_down'))}")
    # tpurpc-blackbox stalls/anomalies pane (/debug/stalls + trip counters)
    trips = int(_val(cur, P + "watchdog_trips"))
    errs = int(_sum_label(cur, P + "deadline_exceeded"))
    if stalls is None:
        lines.append(f"stall n/a (no /debug/stalls)   trips {trips}   "
                     f"deadline-exceeded {errs}")
    else:
        active = stalls.get("active", [])
        lines.append(
            f"stall active {len(active)}   in-flight "
            f"{stalls.get('inflight', 0)}   trips {trips}   "
            f"deadline-exceeded {errs}")
        for d in active[:3]:
            lines.append(
                f"  !! {d.get('kind', '?'):>6} {d.get('method', '?'):<28} "
                f"{d.get('age_s', 0):>7.2f}s  {d.get('stage', '?')}")
    # tpurpc-lens byte-flow waterfall pane (/debug/waterfall): per-hop
    # effective GB/s, slowest hop flagged — the streaming-gap instrument
    if waterfall is not None:
        hops = [r for r in waterfall.get("hops", ()) if r.get("bytes")]
        slow = waterfall.get("slowest_hop")
        if hops:
            cells = "  ".join(
                f"{r['hop']} {r['gbps']:.2f}" + ("*" if r["hop"] == slow
                                                 else "")
                for r in hops)
            lines.append(f"flow  GB/s by hop: {cells}")
            if slow:
                lines.append(f"      slowest hop: {slow} "
                             "(* = the hop to attack)")
    # tpurpc-argus SLO alerts pane (/debug/slo): objective/track states
    # with burn rates — the page an operator would get, rendered live
    if slo is not None:
        objs = slo.get("objectives", ())
        if objs:
            n_fire = len(slo.get("firing", ()))
            lines.append(f"slo   objectives {len(objs)}   firing {n_fire}")
            for obj in objs:
                for track, st in sorted((obj.get("tracks") or {}).items()):
                    state = st.get("state", "ok")
                    if state == "ok" and not st.get("fired"):
                        continue
                    mark = "!!" if state == "firing" else \
                        " !" if state == "pending" else "  "
                    lines.append(
                        f"  {mark} {obj.get('name', '?'):<20} "
                        f"{track:<8} {state:<8} "
                        f"burn {st.get('burn_fast', 0):>6.1f}x fast "
                        f"{st.get('burn_slow', 0):>6.1f}x slow  "
                        f"fired {st.get('fired', 0)}")
    # tpurpc-odyssey sequence pane (/debug/seq): top sequences by device
    # step-ms and KV byte-seconds, plus the per-account cost rollup — the
    # "whose sequences own the device" view
    if seq is not None and seq.get("enabled"):
        live = seq.get("live", ())
        att = seq.get("attributed_pct")
        lines.append(
            f"seq   live {seq.get('live_total', len(live))}   "
            f"step-time attributed "
            f"{att if att is not None else 'n/a'}%")
        rows = sorted(list(live) + list(seq.get("recent", ()))[:8],
                      key=lambda r: r.get("step_us", 0), reverse=True)
        for r in rows[:4]:
            lines.append(
                f"   #{r.get('sid', '?'):<5} {r.get('account', '?'):<14} "
                f"{r.get('state', '?'):<9} tok {r.get('tokens', 0):>4}  "
                f"step {r.get('step_us', 0) / 1e3:>8.1f}ms  "
                f"kv {r.get('kv_byte_s', 0):>8.1f}B·s  "
                f"swap {r.get('swap_byte_s', 0):>6.1f}B·s")
        accounts = seq.get("accounts") or {}
        for name in sorted(accounts,
                           key=lambda a: -accounts[a].get("step_us", 0))[:4]:
            b = accounts[name]
            lines.append(
                f"   @{name:<14} seqs {int(b.get('seqs', 0)):>4}  "
                f"tok {int(b.get('tokens', 0)):>6}  "
                f"step {b.get('step_us', 0) / 1e3:>8.1f}ms  "
                f"kv {b.get('kv_byte_s', 0):>8.1f}B·s  "
                f"preempt {int(b.get('preempts', 0))}  "
                f"mig {int(b.get('migrations', 0))}")
    # tpurpc-oracle diagnosis pane (/debug/diagnose): when any symptom is
    # active, the top ranked cause with its confidence and the action
    # hint — the "why", one line under all the "what" panes above
    if diagnose is not None and diagnose.get("enabled"):
        sym = diagnose.get("symptom") or {}
        hyps = diagnose.get("hypotheses") or []
        if sym.get("stage") and hyps:
            top = hyps[0]
            lines.append(
                f"diag  symptom {sym.get('stage', '?'):<22} "
                f"-> {top.get('cause', '?'):<22} "
                f"conf {top.get('confidence', 0):.2f}  "
                f"({len(top.get('evidence', ()))} evidence, "
                f"{len(hyps)} hypotheses)")
            act = top.get("actionable")
            if act:
                lines.append(f"      action: {act}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tpurpc.tools.top")
    ap.add_argument("target", help="HOST:PORT of any tpurpc serving port")
    ap.add_argument("--interval", type=float, default=1.0)
    ap.add_argument("--once", action="store_true",
                    help="print one snapshot and exit (automatic when "
                         "stdout is not a TTY — CI/pipe safe)")
    args = ap.parse_args(argv)
    if not args.once and not sys.stdout.isatty():
        args.once = True  # non-TTY: never emit ANSI clears into a log

    prev: Optional[Dict] = None
    t_prev = time.monotonic()
    while True:
        try:
            cur = fetch(args.target)
        except OSError as exc:
            print(f"tpurpc-top: {args.target} unreachable: {exc}",
                  file=sys.stderr)
            return 1
        stalls = fetch_stalls(args.target)
        wf = fetch_waterfall(args.target)
        slo = fetch_slo(args.target)
        seq = fetch_seq(args.target)
        diag = fetch_diagnose(args.target)
        now = time.monotonic()
        out = render(cur, prev, now - t_prev, args.target, stalls=stalls,
                     waterfall=wf, slo=slo, seq=seq, diagnose=diag)
        if args.once:
            print(out)
            return 0
        sys.stdout.write("\x1b[2J\x1b[H" + out + "\n")
        sys.stdout.flush()
        prev, t_prev = cur, now
        time.sleep(args.interval)


if __name__ == "__main__":
    raise SystemExit(main())
