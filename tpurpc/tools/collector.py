"""tpurpc-argus fleet collector CLI.

    python -m tpurpc.tools.collector HOST:PORT [HOST:PORT ...] \
        [--port 9123] [--poll 1.0] [--stale-after 3] [--once]

Polls every member's introspection routes and serves the merged fleet
views — ``/fleet/metrics`` (member-labeled Prometheus text with counter
resets clamped), ``/fleet/slo`` (every member's objectives + a flat
alert list), ``/fleet/timeline`` (one clock-anchored Perfetto doc) — on
its own HTTP port. See :mod:`tpurpc.obs.collector` for the semantics.

``--once`` polls once, prints the merged SLO document, and exits (what
scripts and the smoke use). Targets may also be resolver specs
(``dns:///name:port``).
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpurpc.tools.collector",
        description="Aggregate N tpurpc members' telemetry behind one "
                    "/fleet/* endpoint.")
    ap.add_argument("targets", nargs="+",
                    help="HOST:PORT (or resolver spec) of each member")
    ap.add_argument("--port", type=int, default=0,
                    help="HTTP port to serve /fleet/* on (0 = ephemeral)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--poll", type=float, default=1.0,
                    help="poll interval, seconds")
    ap.add_argument("--stale-after", type=int, default=3,
                    help="missed polls before a member is marked stale")
    ap.add_argument("--once", action="store_true",
                    help="poll once, print merged /fleet/slo, exit")
    args = ap.parse_args(argv)

    from tpurpc.obs.collector import FleetCollector, resolve_targets

    targets = resolve_targets(args.targets)
    if not targets:
        print("collector: no targets", file=sys.stderr)
        return 1
    col = FleetCollector(targets, poll_s=args.poll,
                         stale_after=args.stale_after)
    if args.once:
        col.poll_once()
        print(json.dumps(col.merged_slo(), indent=1))
        return 0
    port = col.serve(host=args.host, port=args.port)
    print(f"collector: {len(targets)} member(s), serving "
          f"http://{args.host}:{port}/fleet/{{metrics,slo,timeline}}",
          flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        col.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
