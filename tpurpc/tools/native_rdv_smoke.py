"""tpurpc-ironclad smoke (ISSUE 18): the NATIVE-plane rendezvous + ctrl
rings, ledger-proven.

Phase 1 (native <-> native): a default Server (ring adoption onto the C
loop) and a default Channel (C client plane) move one 8 MiB tensor — the
process-global C ledger must show the one-sided write
(``rdv_bytes_sent`` >= payload) with < 64 KiB of framed host-copy bytes,
zero fallbacks, and ZERO framed control ops (every OFFER/CLAIM/COMPLETE
rode the descriptor ring).

Phase 2 (python client -> native server, the cross-plane bar): the Python
sender's copy ledger must show ``rdma_write`` >= payload with < 64 KiB
host landing copies, and its flight ring the ORDERED
offer -> claim -> write -> complete evidence; the C server's receiver
counters must move in step.

Phase 3 (induced stall): TPURPC_TEST_FREEZE_NCTRL freezes the C
consumer's drain — the python sender's OFFER ages in a ring nobody
drains, the stall watchdog must name the ``ctrl-ring`` stage, and the
call must still COMPLETE via the framed fallback once the claim times
out (the zero-failed-RPC degradation ladder).

Runs everything in one subprocess (GRPC_PLATFORM_TYPE is read at import);
under TPURPC_FLIGHT_DUMP the flight dump feeds tools/check.sh's protocol
conformance stage. Exit 0 = all three phases passed.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time

PAYLOAD_BYTES = 8 << 20  # the 8 MiB tensor


def _native_counters():
    from tpurpc.rpc import native_client

    return native_client.rdv_counters()


def _totaling_server(**kw):
    from tpurpc.rpc.server import Server, stream_stream_rpc_method_handler

    srv = Server(max_workers=4, **kw)

    def total(req_iter, ctx):
        n = 0
        for m in req_iter:
            n += len(m)
        yield str(n).encode()

    srv.add_method("/natsmoke.S/Total",
                   stream_stream_rpc_method_handler(total))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    return srv, port


def phase_native_ledger() -> None:
    """Native client -> native server: one 8 MiB message, C ledger proof."""
    from tpurpc.rpc.channel import Channel

    srv, port = _totaling_server()  # ring platform: adopts onto the C loop
    payload = bytes(range(256)) * (PAYLOAD_BYTES // 256)
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.stream_stream("/natsmoke.S/Total")
            # warmup settles the capability hello + standing grants; the
            # first big send legitimately races the hello and frames
            list(mc(iter([b"warm"]), timeout=30))
            c0 = _native_counters()
            assert c0 is not None, "native plane unavailable on this rig"
            out = list(mc(iter([payload]), timeout=60))
            assert out[-1] == str(len(payload)).encode(), out
            c1 = _native_counters()
        sent = c1["rdv_sent"] - c0["rdv_sent"]
        wrote = c1["rdv_bytes_sent"] - c0["rdv_bytes_sent"]
        host = c1["host_copy_bytes"] - c0["host_copy_bytes"]
        frames = c1["ctrl_frames"] - c0["ctrl_frames"]
        assert sent >= 1 and c1["rdv_fallback"] == c0["rdv_fallback"], c1
        assert wrote >= len(payload), (wrote, len(payload))
        assert host < 64 * 1024, (
            "host landing copies on the native rendezvous path", host)
        assert frames == 0, (
            f"{frames} framed control ops (want 0: ring-borne steady state)")
        print(f"  [native<->native] 8 MiB one-sided write: "
              f"rdv_bytes_sent={wrote} host_copy={host} ctrl_frames=0")
    finally:
        srv.stop(grace=1)


_NATIVE_SERVER = r"""
import json, sys
from tpurpc.rpc import native_client
from tpurpc.rpc.server import Server, stream_stream_rpc_method_handler

srv = Server(max_workers=4)  # ring platform: adopts onto the C loop
def total(req_iter, ctx):
    n = 0
    for m in req_iter:
        n += len(m)
    c = native_client.rdv_counters() or {}
    print("NATCOUNTS", json.dumps(c), flush=True)
    yield str(n).encode()
srv.add_method("/natsmoke.S/Total", stream_stream_rpc_method_handler(total))
port = srv.add_insecure_port("127.0.0.1:0")
print("PORT", port, flush=True)
srv.start()
print("READY", flush=True)
srv.wait_for_termination(timeout=180)
"""


def phase_cross_plane_flight() -> None:
    """Python sender -> native server SUBPROCESS (the deployment shape):
    ordered rdv flight + a clean python copy ledger — the in-process
    trampoline's handler materialization must not pollute the proof."""
    from tpurpc.obs import flight
    from tpurpc.rpc.channel import Channel
    from tpurpc.tpu import ledger

    flight.RECORDER.reset()
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = here + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen([sys.executable, "-c", _NATIVE_SERVER],
                            stdout=subprocess.PIPE, text=True, env=env)
    lines: list = []
    ready = threading.Event()

    def pump():
        for line in proc.stdout:
            lines.append(line)
            if line.startswith("READY"):
                ready.set()

    threading.Thread(target=pump, daemon=True).start()
    payload = bytes(range(256)) * (PAYLOAD_BYTES // 256)
    try:
        assert ready.wait(60), "native server subprocess never came up"
        port = int([ln for ln in lines if ln.startswith("PORT")][0]
                   .split()[1])
        import json

        def natcounts():
            got = [ln for ln in lines if ln.startswith("NATCOUNTS")]
            return [json.loads(ln.split(" ", 1)[1]) for ln in got]

        with Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.stream_stream("/natsmoke.S/Total", tpurpc_native=False)
            list(mc(iter([b"warm"]), timeout=30))
            with ledger.track() as w:
                out = list(mc(iter([payload]), timeout=60))
            assert out[-1] == str(len(payload)).encode(), out
        deadline = time.monotonic() + 20
        while len(natcounts()) < 2 and time.monotonic() < deadline:
            time.sleep(0.05)
        counts = natcounts()
        assert len(counts) >= 2 and counts[-1] and (
            counts[-1]["rdv_recv"] - counts[0]["rdv_recv"] >= 1), (
            "the C receiver never saw the python sender's transfer", counts)
        assert w["rdma_write"] >= len(payload), w.delta
        assert w["host_copy"] < 64 * 1024, (
            "host landing copies on the cross-plane path", w.delta)
        evs = [e["event"] for e in flight.snapshot()
               if e["event"].startswith("rdv-")]
        order = ("rdv-offer", "rdv-claim", "rdv-write", "rdv-complete")
        idx = [evs.index(name) for name in order if name in evs]
        assert len(idx) == len(order), (order, evs)
        assert idx == sorted(idx), ("rdv flight out of order", evs)
        print(f"  [python->native x 2 processes] ordered offer/claim/write/"
              f"complete; rdma_write={w['rdma_write']} "
              f"host_copy={w['host_copy']}")
    finally:
        proc.kill()


def phase_frozen_consumer() -> None:
    """Freeze the C drain: watchdog names ctrl-ring, framed fallback
    completes the call anyway."""
    from tpurpc.obs import watchdog
    from tpurpc.rpc.channel import Channel

    srv, port = _totaling_server()
    payload = bytes(512) * 4096  # 2 MiB: a class with no standing grant
    wd = watchdog.get()
    wd.reset()
    prev = (wd.min_stall_s, wd.sweep_s)
    wd.min_stall_s, wd.sweep_s = 0.3, 0.1
    os.environ["TPURPC_RENDEZVOUS_CLAIM_TIMEOUT_S"] = "3"
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.stream_stream("/natsmoke.S/Total", tpurpc_native=False)
            list(mc(iter([b"warm"]), timeout=30))  # hello + ring adoption
            # the C lib reads this env LIVE in ctrl_drain: every native
            # consumer goes quiet, posted records age in the ring
            os.environ["TPURPC_TEST_FREEZE_NCTRL"] = "1"
            result: dict = {}

            def stalled():
                result["out"] = list(mc(iter([payload]), timeout=60))

            t = threading.Thread(target=stalled)
            t.start()
            diag = None
            deadline = time.monotonic() + 10
            while diag is None and time.monotonic() < deadline:
                time.sleep(0.15)
                for d in wd.sweep_once():
                    if d["stage"] == "ctrl-ring":
                        diag = d
                        break
            assert diag is not None, (
                "watchdog never named the ctrl-ring stage", wd.active())
            t.join(timeout=60)
            assert not t.is_alive(), "stalled call never completed"
            assert result["out"][-1] == str(len(payload)).encode()
        print(f"  [frozen C consumer] watchdog named '{diag['stage']}' "
              f"({diag['detail'][:56]}...); framed fallback completed "
              "the call")
    finally:
        os.environ.pop("TPURPC_TEST_FREEZE_NCTRL", None)
        os.environ.pop("TPURPC_RENDEZVOUS_CLAIM_TIMEOUT_S", None)
        wd.min_stall_s, wd.sweep_s = prev
        wd.reset()
        srv.stop(grace=1)


def run_phases() -> None:
    phase_native_ledger()
    phase_cross_plane_flight()
    phase_frozen_consumer()


def main() -> int:
    if "--phase" in sys.argv:
        run_phases()
        return 0
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ)
    env["GRPC_PLATFORM_TYPE"] = "RDMA_BPEV"
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = here + os.pathsep + env.get("PYTHONPATH", "")
    rc = subprocess.run(
        [sys.executable, "-m", "tpurpc.tools.native_rdv_smoke", "--phase"],
        env=env, timeout=300).returncode
    if rc != 0:
        print("native rdv smoke FAILED")
        return 1
    print("native rdv smoke: PASS (C-plane one-sided 8 MiB, cross-plane "
          "ordered flight, ctrl-ring stall attributed)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
