"""~8s tpurpc-argus smoke for the verification gate (tools/check.sh).

The ISSUE 14 acceptance loop in miniature — detect → localize → capture,
with the burn-rate windows scaled to fractions of a second:

* one SERVER (slow-able handler) + one CLIENT + one COLLECTOR PROCESS
  (``python -m tpurpc.tools.collector`` polling the server's serving
  port at 4 Hz);
* a latency SLO declared on the probe method; the handler degrades on
  command → the alert must pass PENDING and reach FIRING within two fast
  windows (plus evaluator cadence slack);
* ``/fleet/slo`` on the collector must show the firing alert under the
  right ``member`` label, and ``/fleet/metrics`` must carry
  member-labeled series with ``tpurpc_member_up 1``;
* ``/healthz`` goes 503 with the structured ``slo-firing`` reason;
* exactly ONE evidence bundle lands on disk (rate-limited against the
  continuing degradation) and its flight dump passes
  ``python -m tpurpc.analysis protocol --flight <bundle>`` UNMODIFIED.

Exit 0 on success; any assertion/exception exits 1 with the reason.

    python -m tpurpc.tools.argus_smoke
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

FAST_S = 0.8
SLOW_S = 1.6


def _get_json(url: str, timeout: float = 5.0) -> dict:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read().decode("utf-8", "replace"))
    except urllib.error.HTTPError as exc:
        # a degraded /healthz answers 503 WITH the structured body
        return json.loads(exc.read().decode("utf-8", "replace"))


def run() -> int:
    os.environ["TPURPC_TSDB_FINE_S"] = "0.05"
    from tpurpc.analysis import protocol
    from tpurpc.obs import bundle as obs_bundle
    from tpurpc.obs import flight
    from tpurpc.obs import slo as obs_slo
    from tpurpc.obs import tsdb as obs_tsdb
    from tpurpc.rpc.channel import Channel
    from tpurpc.rpc.server import Server, unary_unary_rpc_method_handler

    flight.RECORDER.reset()
    obs_tsdb.postfork_reset()
    obs_slo.reset()

    bundle_dir = tempfile.mkdtemp(prefix="tpurpc-argus-smoke-")
    slow = threading.Event()

    def handler(req, ctx):
        if slow.is_set():
            time.sleep(0.05)
        return b"ok"

    srv = Server(max_workers=4)
    srv.add_method("/argus/Probe", unary_unary_rpc_method_handler(handler))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    member = f"127.0.0.1:{port}"

    obs_bundle.enable(bundle_dir, min_interval_s=30.0)
    ev = obs_slo.get()
    ev.eval_s = 0.1
    obj = obs_slo.declare(
        "probe-p99", method="/argus/Probe", latency_ms=10.0,
        latency_target_pct=50.0, windows=[(FAST_S, SLOW_S, 1.2)])
    st = obj.tracks["latency"]

    # the collector PROCESS, polling the member at 4 Hz
    col = subprocess.Popen(
        [sys.executable, "-m", "tpurpc.tools.collector", member,
         "--poll", "0.25", "--port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        line = col.stdout.readline()
        assert "serving http://" in line, f"collector failed: {line!r}"
        col_base = line.split("serving ")[1].split("/fleet")[0].strip()

        with Channel(member) as ch:
            call = ch.unary_unary("/argus/Probe")
            for _ in range(16):     # healthy rolling-p99 history
                call(b"x", timeout=5)
            t_degrade = time.monotonic()
            slow.set()              # induce the p99 degradation
            states = set()
            deadline = t_degrade + 2 * FAST_S + 8.0  # 2 fast windows + slack
            while time.monotonic() < deadline:
                call(b"x", timeout=5)
                states.add(st.state)
                if st.state == "firing":
                    break
            t_fired = time.monotonic() - t_degrade
            assert st.state == "firing", \
                f"alert never fired (states seen: {states})"
            assert "pending" in states, "firing without an observed pending"

            # healthz degraded with the structured reason
            doc = _get_json(f"http://{member}/healthz?json=1")
            reasons = [r["reason"] for r in doc["degraded_reasons"]]
            assert "slo-firing" in reasons, doc

            # the collector's fleet views show it, member-labeled
            fleet = None
            for _ in range(20):  # within a few 0.25s polls
                fleet = _get_json(f"{col_base}/fleet/slo")
                if any(a.get("member") == member
                       and a.get("state", "firing") == "firing"
                       for a in fleet.get("alerts", ())):
                    break
                time.sleep(0.25)
            else:
                raise AssertionError(f"/fleet/slo never showed the alert: "
                                     f"{fleet}")
            raw = urllib.request.urlopen(f"{col_base}/fleet/metrics",
                                         timeout=5).read().decode()
            assert f'tpurpc_member_up{{member="{member}"}} 1' in raw
            assert f'member="{member}"' in raw

        # exactly one rate-limited bundle, protocol-clean flight dump
        time.sleep(0.5)
        bundles = obs_bundle.list_bundles(bundle_dir)
        assert len(bundles) == 1, f"want exactly 1 bundle, got {bundles}"
        bpath = os.path.join(bundle_dir, bundles[0])
        total, violations = protocol.check_dump(bpath)
        assert not violations, violations
        assert total > 0
        with open(os.path.join(bpath, f"flight-{os.getpid()}.json")) as f:
            events = json.load(f)
        assert any(e["event"] == "slo-firing" for e in events)
    finally:
        col.terminate()
        col.wait(timeout=5)
        ev.stop()
        srv.stop(grace=0)
        obs_slo.reset()
        obs_bundle.disable()
        obs_tsdb.get().stop()
        obs_tsdb.postfork_reset()

    print(f"argus smoke OK: pending->firing in {t_fired:.2f}s "
          f"(fast window {FAST_S}s), fleet view member-labeled, healthz "
          f"slo-firing, 1 bundle, protocol-clean ({total} events)")
    return 0


def main() -> int:
    try:
        return run()
    except Exception as exc:
        print(f"argus smoke FAILED: {exc!r}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    raise SystemExit(main())
