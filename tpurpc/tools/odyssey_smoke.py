"""tpurpc-odyssey smoke (ISSUE 15): one sequence's whole journey, proven.

A disaggregated pair over shm block grants — prefill in a CHILD process,
two decode servers in this one — serves a single account's generation
stream which is live-MIGRATED mid-decode from decode A to decode B.
Asserted:

* **token exactness across three hops**: prefill process -> decode A ->
  migration -> decode B, values equal ``reference_decode`` bit-exactly
  and indices 0..n-1 (the PR 11 contract, still holding under odyssey);
* **one trace_id spans the split**: the client opens ONE trace context;
  the journey doc built from ``/traces?trace_id=`` of the decode process
  AND the prefill process parses as Perfetto JSON with >=2 clock-anchored
  process lanes, and carries prefill-side spans plus the decode-side
  ``seq-ship``/``seq-decode``/``seq-migrate`` journey spans;
* **the cost plane attributes**: ``/debug/seq`` rolls the account up with
  tokens, >=1 migration, shipped bytes, and >=95% of measured device-step
  time attributed to named sequences;
* **protocol conformance**: the in-process flight stream (SEQ_SUBMIT ->
  GEN_JOIN -> SEQ_FIRST_TOKEN -> ... -> SEQ_DETACH / MIG brackets)
  checks clean against the declared machines, and the
  ``TPURPC_FLIGHT_DUMP`` dump rides the check.sh conformance stage.

Exit 0 on success. ~5 s, numpy only (no jax).

    python -m tpurpc.tools.odyssey_smoke
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

PROMPT_LEN = 192
MAX_TOKENS = 48
ACCOUNT = "smoke-tenant"


def run_prefill_child() -> int:
    decode_addr = sys.argv[sys.argv.index("--prefill") + 1]

    from tpurpc.jaxshim.generate import ToyDecodeModel
    from tpurpc.rpc.channel import Channel
    from tpurpc.serving import serve_prefill

    ch = Channel(decode_addr)
    srv, port, state = serve_prefill(ToyDecodeModel(), ch, decode_addr)
    print(f"PORT {port}", flush=True)
    try:
        sys.stdin.read()
    finally:
        srv.stop(grace=0)
        state.close()
        ch.close()
    return 0


def run() -> int:
    import numpy as np

    from tpurpc.analysis import protocol
    from tpurpc.jaxshim.generate import ToyDecodeModel, reference_decode
    from tpurpc.obs import flight, odyssey, scrape, tracing
    from tpurpc.rpc.channel import Channel
    from tpurpc.serving import DisaggClient, migrate, serve_decode

    tracing.force(True)  # every span commits: the journey must be whole
    t0_flight = __import__("time").monotonic_ns()

    # decode A (the handoff target) and decode B (the migration target),
    # both paged over shm arenas; a slow-ish step keeps the stream alive
    # long enough to migrate it mid-decode
    a_srv, a_port, a_sched, a_state = serve_decode(
        ToyDecodeModel(step_delay_s=0.01), kv_blocks=96, block_bytes=512,
        kv_kind="shm", name="odyA")
    b_srv, b_port, b_sched, b_state = serve_decode(
        ToyDecodeModel(step_delay_s=0.01), kv_blocks=96, block_bytes=512,
        kv_kind="shm", name="odyB")
    b_ch = Channel(f"127.0.0.1:{b_port}")

    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ)
    env["PYTHONPATH"] = here + os.pathsep + env.get("PYTHONPATH", "")
    env["TPURPC_TRACE_SAMPLE"] = "1"  # the child commits its spans too
    child = subprocess.Popen(
        [sys.executable, "-m", "tpurpc.tools.odyssey_smoke", "--prefill",
         f"127.0.0.1:{a_port}"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env, text=True)
    try:
        line = child.stdout.readline().strip()
        assert line.startswith("PORT "), f"child said {line!r}"
        p_port = int(line.split()[1])
        p_ch = Channel(f"127.0.0.1:{p_port}")
        cli = DisaggClient(p_ch, f"127.0.0.1:{a_port}", account=ACCOUNT)
        prompt = np.arange(PROMPT_LEN, dtype=np.int32) % 97
        want = reference_decode(prompt, MAX_TOKENS)

        # ONE trace context for the whole journey: it rides the Prefill
        # RPC into the child, the OfferKv into decode A, the migration
        # offer into decode B — every process's spans share its trace_id.
        ctx = tracing.TraceContext(
            int.from_bytes(os.urandom(8), "big"), 1)
        pairs = []
        with tracing.use(ctx):
            it = cli.generate_with_meta(prompt, max_tokens=MAX_TOKENS,
                                        timeout=30)
            for _ in range(6):
                pairs.append(next(it))
            # mid-stream: move every live sequence A -> B; the client
            # follows the `migrated` record transparently
            moved, failed = migrate(a_state, b_ch, f"127.0.0.1:{b_port}")
            assert moved >= 1 and failed == 0, (moved, failed)
            pairs.extend(it)
        idxs = [i for i, _ in pairs]
        vals = [t for _, t in pairs]
        assert idxs == list(range(MAX_TOKENS)), idxs
        assert vals == want, (vals[:8], want[:8])
        print(f"  odyssey smoke: {MAX_TOKENS} tokens exact across "
              f"prefill-child -> decode A -> migrate -> decode B "
              f"(moved={moved})")

        # -- the journey: one trace_id, >=2 anchored process lanes ------
        doc = odyssey.journey([f"127.0.0.1:{a_port}",
                               f"127.0.0.1:{p_port}"], ctx.trace_id)
        doc = json.loads(json.dumps(doc))  # must be pure JSON
        meta = doc["otherData"]
        assert meta["lanes"] >= 2, meta
        assert not meta["unanchored"], meta
        names = {e.get("name") for e in doc["traceEvents"]
                 if e.get("ph") == "X"}
        for needed in ("seq-ship", "seq-decode", "seq-migrate"):
            assert needed in names, (needed, sorted(names))
        # the prefill process contributed spans of the SAME trace
        lane_pids = {e.get("pid") for e in doc["traceEvents"]
                     if e.get("ph") == "X"}
        assert len(lane_pids) >= 2, sorted(names)
        print(f"  odyssey smoke: journey doc has {meta['lanes']} anchored "
              f"lanes, spans {sorted(names)}")

        # -- the cost plane: account rollup + attribution ---------------
        status, _ctype, body = scrape.route_local("/debug/seq")
        assert status == 200
        seq = json.loads(body)
        assert seq["enabled"], seq
        accounts = seq["accounts"]
        assert ACCOUNT in accounts, sorted(accounts)
        acct = accounts[ACCOUNT]
        assert acct["tokens"] >= MAX_TOKENS - 1, acct
        assert acct["migrations"] >= 1, acct
        assert acct["shipped_bytes"] > 0, acct
        assert seq["attributed_pct"] is not None \
            and seq["attributed_pct"] >= 95.0, seq["attributed_pct"]
        print(f"  odyssey smoke: /debug/seq attributes "
              f"{seq['attributed_pct']}% of step time; account "
              f"'{ACCOUNT}': tokens={int(acct['tokens'])} "
              f"migrations={int(acct['migrations'])} "
              f"shipped={int(acct['shipped_bytes'])}B")

        # -- flight conformance (the dump also rides check.sh) ----------
        events = flight.snapshot(since_ns=t0_flight)
        bad = protocol.check_events(events, strict=False)
        assert not bad, bad[:3]
        protocol.assert_ordered(events, [
            ("seq-submit", {"a2": PROMPT_LEN}),
            "gen-join", "seq-first-token", "seq-detach",
            "migration-begin", ("migration-end", {"a2": 1}),
        ], since_ns=t0_flight)
        print("  odyssey smoke: flight journey protocol-conformant "
              "(submit -> join -> first-token -> detach -> migration)")
        cli.close()
        p_ch.close()
    finally:
        try:
            child.stdin.close()
            child.wait(timeout=10)
        except Exception:
            child.kill()
        tracing.force(None)
        for srv, _port, sched, state in ((a_srv, a_port, a_sched, a_state),
                                         (b_srv, b_port, b_sched,
                                          b_state)):
            srv.stop(grace=0)
            sched.close()
            state.close()
            state.mgr.close()
        b_ch.close()
    print("odyssey smoke: PASS (2 processes, one trace_id end-to-end, "
          "account rollup + >=95% step attribution, conformant flight)")
    return 0


def main() -> int:
    if "--prefill" in sys.argv:
        return run_prefill_child()
    try:
        return run()
    except BaseException as exc:
        print(f"odyssey smoke FAILED: {exc!r}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
