"""tpurpc-keystone smoke (ISSUE 11): one PREFILL process ships KV into
one DECODE process's arena over shm block grants — the verification
gate's proof that disaggregated serving really is zero-host-copy.

The decode server (this process) and the prefill server (a subprocess)
talk control frames over loopback TCP; the KV payload moves as one-sided
writes into the decode arena's shm region. Asserted:

* **copy-ledger proof**: during a 4096-token prompt's handoff the decode
  process's ledger shows host copies bounded by the CONTROL traffic (the
  16 KiB prompt rides the framed path twice: client→prefill, then the
  descriptor-only OfferKv) while the 64 KiB of KV entries land with NO
  host-copy counterpart — ``host_copy < 2×prompt + 8 KiB < kv_bytes``,
  i.e. no KV-sized landing copy exists. The prefill side's
  ``rdma_write`` (≥ the shipped KV bytes) is fetched over its stats RPC
  and asserted too.
* **token-value exactness**: the disaggregated stream's tokens equal
  ``reference_decode`` — prefill on one process, decode on another,
  values bit-identical.
* **prefix-cache hit**: the SAME prompt again scores ``kv_prefix_hits``
  ≥ 1 on the decode arena and the prefill tier ships exactly ONE entry
  (the first token) the second time — prefill skipped for the shared
  span.

Exit 0 on success. ~10 s, numpy only (no jax).

    python -m tpurpc.tools.disagg_smoke
"""

from __future__ import annotations

import os
import subprocess
import sys

PROMPT_LEN = 4096
MAX_TOKENS = 8


def run_prefill_child() -> int:
    """Child: a prefill server shipping into the decode address given on
    argv; prints its port, serves until stdin closes."""
    decode_addr = sys.argv[sys.argv.index("--prefill") + 1]

    from tpurpc.jaxshim.generate import ToyDecodeModel
    from tpurpc.rpc.channel import Channel
    from tpurpc.serving import serve_prefill

    ch = Channel(decode_addr)
    srv, port, state = serve_prefill(ToyDecodeModel(), ch, decode_addr)
    print(f"PORT {port}", flush=True)
    try:
        sys.stdin.read()  # parent closes stdin to stop us
    finally:
        srv.stop(grace=0)
        state.close()
        ch.close()
    return 0


def run() -> int:
    import numpy as np

    from tpurpc.jaxshim import codec
    from tpurpc.jaxshim.generate import ToyDecodeModel, reference_decode
    from tpurpc.rpc.channel import Channel
    from tpurpc.serving import DisaggClient, serve_decode
    from tpurpc.tpu import ledger

    d_srv, d_port, sched, state = serve_decode(
        ToyDecodeModel(), kv_blocks=64, block_bytes=4096, kv_kind="shm",
        name="smoke")
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ)
    env["PYTHONPATH"] = here + os.pathsep + env.get("PYTHONPATH", "")
    child = subprocess.Popen(
        [sys.executable, "-m", "tpurpc.tools.disagg_smoke", "--prefill",
         f"127.0.0.1:{d_port}"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env, text=True)
    try:
        line = child.stdout.readline().strip()
        assert line.startswith("PORT "), f"child said {line!r}"
        p_port = int(line.split()[1])
        p_ch = Channel(f"127.0.0.1:{p_port}")
        cli = DisaggClient(p_ch, f"127.0.0.1:{d_port}")
        prompt = np.arange(PROMPT_LEN, dtype=np.int32) % 251
        kv_bytes = (PROMPT_LEN + 1) * 16

        # -- cold handoff: zero host landing copies + exact values -----
        with ledger.track() as w:
            pairs = list(cli.generate_with_meta(prompt,
                                                max_tokens=MAX_TOKENS,
                                                timeout=30))
        idxs = [i for i, _ in pairs]
        vals = [t for _, t in pairs]
        assert idxs == list(range(MAX_TOKENS)), idxs
        want = reference_decode(prompt, MAX_TOKENS)
        assert vals == want, (vals, want)
        # the prompt (4 B/token) legitimately rides the framed control
        # path twice; the KV (16 B/entry) must NOT — so host copies stay
        # under 2×prompt + slack, well below prompt + kv
        control_bar = 2 * PROMPT_LEN * 4 + 8 * 1024
        assert control_bar < kv_bytes, "smoke misconfigured"
        assert w["host_copy"] < control_bar, (
            "a KV-sized host landing copy appeared on the decode side",
            w.delta)
        print(f"  disagg smoke: {PROMPT_LEN}-token prompt handed off, "
              f"{MAX_TOKENS} tokens exact; decode-side host_copy="
              f"{w['host_copy']}B (control only) for {kv_bytes}B of KV "
              "(zero landing copies)")

        # prefill side moved the KV as one-sided writes (its ledger)
        stats = p_ch.unary_unary("/tpurpc.Kv/PrefillStats",
                                 codec.tree_serializer,
                                 codec.tree_deserializer)
        s1 = stats({}, timeout=10)
        rdma = int(np.asarray(s1["rdma_write"]).ravel()[0])
        shipped1 = int(np.asarray(s1["shipped_bytes"]).ravel()[0])
        assert rdma >= kv_bytes, (rdma, kv_bytes)
        assert shipped1 >= kv_bytes
        print(f"  disagg smoke: prefill side rdma_write={rdma}B "
              f"(one-sided block writes)")

        # -- repeated prompt: prefix hit, prefill skipped --------------
        pairs2 = list(cli.generate_with_meta(prompt,
                                             max_tokens=MAX_TOKENS,
                                             timeout=30))
        assert [t for _, t in pairs2] == want
        assert state.mgr.prefix_hits >= 1, state.mgr.stats()
        s2 = stats({}, timeout=10)
        shipped2 = int(np.asarray(s2["shipped_bytes"]).ravel()[0]) \
            - shipped1
        skipped = int(np.asarray(
            s2["prefix_skipped_entries"]).ravel()[0])
        assert skipped >= PROMPT_LEN, skipped
        assert shipped2 == 16, (
            f"warm handoff shipped {shipped2}B, wanted exactly one "
            "16B entry")
        print(f"  disagg smoke: repeated prompt scored a prefix hit — "
              f"{skipped} entries skipped, warm ship {shipped2}B vs "
              f"cold {shipped1}B")
        cli.close()
        p_ch.close()
    finally:
        try:
            child.stdin.close()
            child.wait(timeout=10)
        except Exception:
            child.kill()
        d_srv.stop(grace=0)
        sched.close()
        state.close()
        state.mgr.close()
    print("disagg smoke: PASS (2 processes, shm block grants, "
          "ledger-proven zero landing copies, prefix-cache hit)")
    return 0


def main() -> int:
    if "--prefill" in sys.argv:
        return run_prefill_child()
    try:
        return run()
    except BaseException as exc:  # the gate wants a reasoned nonzero exit
        print(f"disagg smoke FAILED: {exc!r}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
