"""~1s tpurpc-blackbox smoke for the verification gate (tools/check.sh).

The ISSUE 5 acceptance criterion in miniature, with TPURPC_TRACE_SAMPLE=0
(head sampling OFF — everything below must come from the always-on
blackbox machinery):

* wedge a RING SENDER on purpose (fill a loopback pair's ring with no
  reader draining it) with an RPC registered in flight → the stall
  watchdog diagnoses it within two sweep periods and names the stage
  ``credit-starvation``;
* wedge a HANDLER on purpose (server behavior parks on an event) → the
  watchdog names ``device-infer`` (transport quiet, handler executing);
* the wedged call's span tree exists via TAIL CAPTURE (no sampling), on
  the real client→server path;
* ``/debug/flight`` replays the ordered event sequence (credit-starve
  begin → watchdog trip) and ``/healthz`` is degraded while the stall is
  active, healthy after it clears.

Exit 0 on success; any assertion/exception exits 1 with the reason.

    python -m tpurpc.tools.watchdog_smoke
"""

from __future__ import annotations

import json
import sys
import threading
import time
import urllib.request


def _wait_for(pred, timeout: float, what: str):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = pred()
        if got:
            return got
    raise AssertionError(f"timed out waiting for {what}")


def run() -> int:
    from tpurpc.core.pair import create_loopback_pair
    from tpurpc.obs import flight, tracing, watchdog
    from tpurpc.rpc.channel import Channel
    from tpurpc.rpc.server import Server, unary_unary_rpc_method_handler

    tracing.force(None)
    tracing.configure(0.0)       # head sampling OFF — the blackbox premise
    assert not tracing.ACTIVE and tracing.LIVE, "tail capture must be live"
    flight.RECORDER.reset()
    wd = watchdog.get()
    wd.reset()
    wd.enabled = True
    wd.min_stall_s = 0.2         # fast smoke knobs (prod defaults: 1s/0.25s)
    wd.sweep_s = 0.1

    # -- scenario A: wedged ring sender → credit-starvation -------------------
    a, b = create_loopback_pair(ring_size=4096)
    tok = wd.call_started("/smoke/WedgedSend")
    t_wedge = time.monotonic_ns()
    sent = a.send([b"x" * 16384])     # > ring: sender stalls for credits
    assert sent < 16384, "ring unexpectedly swallowed the whole payload"
    assert a.want_write, "sender should be credit-stalled"

    diags = _wait_for(wd.active, wd.min_stall_s + 2 * wd.sweep_s + 1.0,
                      "watchdog diagnosis (two sweep periods)")
    d = next((x for x in diags if x["method"] == "/smoke/WedgedSend"), None)
    assert d is not None, f"wedged send not diagnosed: {diags}"
    assert d["stage"] == "credit-starvation", \
        f"wrong stage for a credit-wedged sender: {d}"
    latency_sweeps = (time.monotonic_ns() - t_wedge) / 1e9 / wd.sweep_s

    # healthz reflects the active stall
    from tpurpc.obs import scrape

    status, _ctype, body = scrape._route("/healthz")
    assert status == 503 and b"degraded" in body, (status, body)

    # /debug/flight replays the ordered sequence: starve begin -> trip
    status, _ctype, body = scrape._route("/debug/flight")
    assert status == 200
    events = [e["event"] for e in json.loads(body)["events"]]
    assert "credit-starve-begin" in events and "watchdog-trip" in events
    assert (events.index("credit-starve-begin")
            < events.index("watchdog-trip")), events

    # unwedge: drain the peer ring; the sender's stall resolves and the
    # watchdog clears on the next sweep
    b.recv(1 << 20)
    a.send([b""])  # no-op send folds credits; stall state re-evaluates
    wd.call_finished(tok)
    _wait_for(lambda: not wd.active(), 2.0, "diagnosis to clear")
    status, _ctype, body = scrape._route("/healthz")
    assert status == 200 and body.strip() == b"ok", (status, body)
    a.destroy()
    b.destroy()

    # -- scenario B: wedged handler → device-infer + tail-captured spans ------
    hold = threading.Event()
    srv = Server(max_workers=4)
    srv.add_method("/smoke/Hold",
                   unary_unary_rpc_method_handler(
                       lambda req, ctx: (hold.wait(5), b"done")[1]))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            pl = ch.unary_unary("/smoke/Hold").pipeline(depth=2)
            fut = pl.call_async(b"wedge", timeout=30)

            diags = _wait_for(
                lambda: [x for x in wd.active()
                         if x["method"] == "/smoke/Hold"
                         and x["kind"] == "server"],
                wd.min_stall_s + 2 * wd.sweep_s + 2.0,
                "handler-wedge diagnosis (server side)")
            assert diags[0]["stage"] == "device-infer", diags
            hold.set()
            assert fut.result(10) == b"done"

        # tail capture (sampling is 0): the slow call's FULL span tree was
        # committed — client-send/wire on the client half plus the server
        # half's spans: dispatch/respond on the Python plane, or the
        # native trampoline's single `handler` span when the ring
        # connection was adopted (GRPC_PLATFORM_TYPE=RDMA_*)
        def tree_complete():
            by_trace = {}
            for s in tracing.spans():
                by_trace.setdefault(s["trace_id"], set()).add(s["name"])
            return any(
                {"client-send", "wire"} <= names
                and ({"dispatch", "respond"} <= names
                     or "handler" in names)
                for names in by_trace.values())

        _wait_for(tree_complete, 2.0, "tail-captured span tree")
        _wait_for(lambda: not wd.active(), 2.0, "handler diagnosis to clear")
    finally:
        srv.stop(grace=0)

    # the scrape plane serves the same data over real HTTP
    srv2 = Server(max_workers=2)
    srv2.add_method("/smoke/Echo",
                    unary_unary_rpc_method_handler(lambda r, c: bytes(r)))
    port2 = srv2.add_insecure_port("127.0.0.1:0")
    srv2.start()
    try:
        raw = urllib.request.urlopen(
            f"http://127.0.0.1:{port2}/debug/stalls", timeout=10).read()
        snap = json.loads(raw)
        assert "active" in snap and "history" in snap, snap
        assert any(h.get("stage") == "credit-starvation"
                   for h in snap["history"]), snap["history"]
    finally:
        srv2.stop(grace=0)

    print(f"watchdog smoke OK: credit-starvation diagnosed in "
          f"~{latency_sweeps:.1f} sweep periods past the bar; "
          f"device-infer attributed; tail tree captured at sample=0; "
          f"flight replay ordered")
    return 0


def main() -> int:
    try:
        return run()
    except Exception as exc:
        print(f"watchdog smoke FAILED: {exc!r}", file=sys.stderr)
        return 1
    finally:
        try:
            from tpurpc.obs import tracing, watchdog

            watchdog.get().reset()
            tracing.reset()
        except Exception:
            pass


if __name__ == "__main__":
    raise SystemExit(main())
