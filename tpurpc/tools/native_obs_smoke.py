"""tpurpc-xray smoke (ISSUE 19): the C observability plane, end to end.

Phase 1 (native <-> native merged flight): a default Server (ring
adoption onto the C loop) and a default Channel (C client plane) move one
4 MiB tensor — ``GET /debug/flight`` must return a MERGED timeline where
the C plane's rendezvous evidence (offer -> claim -> complete, emitted by
tpr_rdv.cc into the shm ring) appears in order on the same monotonic
clock as the Python recorder's events, lane-tagged so the two planes stay
distinguishable; the native metrics table must show the one-sided bytes
(``native_rdv_send_bytes`` >= payload) and the waterfall must carry the
native hops so ``slowest_hop`` can name the production plane.

Phase 2 (frozen C consumer, attributed from C evidence alone):
TPURPC_TEST_FREEZE_NCTRL freezes every native drain while a native client
keeps posting control ops into an 8-slot ring — the C plane's own
tx-ring-full stall bracket (CTRL_STALL_BEGIN on an ``nctrl:*`` entity,
lane ``native``) is the ONLY stall evidence in the merged flight, and the
stall watchdog must name the ``native-ctrl-frozen`` stage from it. The
calls must still COMPLETE via the framed fallback (the zero-failed-RPC
degradation ladder holds while the instrument points at the freeze).

Runs everything in one subprocess (GRPC_PLATFORM_TYPE is read at import);
under TPURPC_FLIGHT_DUMP the MERGED flight dump feeds tools/check.sh's
protocol conformance stage — the C plane's offer/claim/complete/release
replay through the rdv-lease/rdv-offer/ctrl-ring machines unmodified.
Exit 0 = both phases passed.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

PAYLOAD_BYTES = 4 << 20  # the 4 MiB tensor


def _totaling_server(**kw):
    from tpurpc.rpc.server import Server, stream_stream_rpc_method_handler

    srv = Server(max_workers=4, **kw)

    def total(req_iter, ctx):
        n = 0
        for m in req_iter:
            n += len(m)
        yield str(n).encode()

    srv.add_method("/xraysmoke.S/Total",
                   stream_stream_rpc_method_handler(total))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    return srv, port


def phase_merged_flight() -> None:
    """Native client -> native server: one 4 MiB message, merged
    /debug/flight + native table + waterfall proof."""
    from tpurpc.obs import flight, lens, native_obs, scrape
    from tpurpc.rpc.channel import Channel

    flight.RECORDER.reset()
    assert native_obs.available(), "native obs plane unavailable on this rig"
    native_obs.reset()
    srv, port = _totaling_server()
    payload = bytes(range(256)) * (PAYLOAD_BYTES // 256)
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.stream_stream("/xraysmoke.S/Total")
            list(mc(iter([b"warm"]), timeout=30))  # hello + standing grants
            out = list(mc(iter([payload]), timeout=60))
            assert out[-1] == str(len(payload)).encode(), out
            # one PYTHON-plane leg on the same wire: a native<->native
            # exchange rides the C loop end to end and the py recorder
            # stays silent — the merge claim needs both lanes live
            mc_py = ch.stream_stream("/xraysmoke.S/Total",
                                     tpurpc_native=False)
            py_payload = bytes(512) * 4096  # 2 MiB: over the rdv floor
            out = list(mc_py(iter([py_payload]), timeout=60))
            assert out[-1] == str(len(py_payload)).encode(), out
        # the REAL route, not a side door: /debug/flight must serve the
        # merged timeline
        status, _ctype, body = scrape._route("/debug/flight")
        assert status == 200, status
        events = json.loads(body)["events"]
        stamps = [e["t_ns"] for e in events]
        assert stamps == sorted(stamps), "merged timeline out of order"
        native = [e for e in events if e.get("lane") == "native"]
        assert native, ("the C plane contributed nothing to the merged "
                        "flight", [e["event"] for e in events][:20])
        assert any(e.get("lane") == "py" for e in events), (
            "python lane lost its tag in the merge")
        evs = [e["event"] for e in native]
        order = ("rdv-offer", "rdv-claim", "rdv-complete")
        idx = [evs.index(name) for name in order if name in evs]
        assert len(idx) == len(order), (order, evs)
        assert idx == sorted(idx), ("C rdv flight out of order", evs)
        assert all(e["entity"].startswith("n") for e in native), (
            "native entities must carry the n* tag vocabulary", native[:5])
        # the metrics table saw the one-sided write, and the scrape +
        # waterfall surfaces carry it
        tab = native_obs.counters()
        assert tab["rdv_send_bytes"] >= len(payload), tab
        assert tab["emitted"] >= len(native), tab
        assert "tpurpc_native_rdv_send_bytes" in scrape.render_prometheus()
        rows = lens.waterfall()["hops"]
        live = {r["hop"] for r in rows if r["bytes"] > 0}
        assert "native_send" in live, (
            "waterfall never grew the native hop", sorted(live))
        print(f"  [native<->native] merged /debug/flight: "
              f"{len(native)} C-plane events in order with "
              f"{len(events) - len(native)} py events; "
              f"native_rdv_send_bytes={tab['rdv_send_bytes']}")
    finally:
        srv.stop(grace=1)


def phase_frozen_native_consumer() -> None:
    """Freeze every native drain: the C plane's tx-ring-full bracket is
    the only stall evidence, and the watchdog names native-ctrl-frozen
    from it; framed fallback completes the calls anyway."""
    from tpurpc.obs import flight, native_obs, watchdog
    from tpurpc.rpc.channel import Channel

    # an 8-slot ring fills after a handful of undrained control posts —
    # read at ring creation, so set BEFORE the server/channel exist
    os.environ["TPURPC_CTRL_RING_SLOTS"] = "8"
    os.environ["TPURPC_RENDEZVOUS_CLAIM_TIMEOUT_S"] = "0.5"
    flight.RECORDER.reset()
    native_obs.reset()
    srv, port = _totaling_server()
    payload = bytes(512) * 4096  # 2 MiB: a class with no standing grant
    wd = watchdog.get()
    wd.reset()
    prev = (wd.min_stall_s, wd.sweep_s)
    wd.min_stall_s, wd.sweep_s = 0.3, 0.1
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            mc = ch.stream_stream("/xraysmoke.S/Total")
            list(mc(iter([b"warm"]), timeout=30))  # hello + ring adoption
            # the C lib reads this env LIVE in ctrl_drain: every native
            # consumer goes quiet, posted records age in the ring
            os.environ["TPURPC_TEST_FREEZE_NCTRL"] = "1"
            result: dict = {}

            # the native client self-pumps (no thread parked on it), so
            # the smoke registers the in-flight marker the way the server
            # plane does — the ATTRIBUTION below still rests on C
            # evidence alone
            tok = wd.call_started("/xraysmoke.S/Total", kind="client")

            def stalled():
                try:
                    result["out"] = list(
                        mc(iter([payload] * 8), timeout=120))
                finally:
                    wd.call_finished(tok)

            t = threading.Thread(target=stalled)
            t.start()
            diag = None
            deadline = time.monotonic() + 30
            while diag is None and time.monotonic() < deadline:
                time.sleep(0.15)
                for d in wd.sweep_once():
                    if d["stage"] == "native-ctrl-frozen":
                        diag = d
                        break
            assert diag is not None, (
                "watchdog never named native-ctrl-frozen", wd.active())
            # C evidence ALONE: every open stall bracket in the merged
            # flight is native-lane (the python plane never saw a post)
            stalls = [e for e in flight.snapshot()
                      if e["event"] == "ctrl-stall-begin"]
            assert stalls and all(
                e.get("lane") == "native" for e in stalls), stalls
            assert all(e["entity"].startswith("nctrl:") for e in stalls)
            os.environ.pop("TPURPC_TEST_FREEZE_NCTRL", None)  # thaw
            t.join(timeout=120)
            assert not t.is_alive(), "stalled calls never completed"
            assert result["out"][-1] == str(len(payload) * 8).encode()
        print(f"  [frozen C consumer] watchdog named '{diag['stage']}' "
              f"({diag['detail'][:56]}...) from {len(stalls)} native-lane "
              "bracket(s); framed fallback completed the calls")
    finally:
        os.environ.pop("TPURPC_TEST_FREEZE_NCTRL", None)
        os.environ.pop("TPURPC_RENDEZVOUS_CLAIM_TIMEOUT_S", None)
        os.environ.pop("TPURPC_CTRL_RING_SLOTS", None)
        wd.min_stall_s, wd.sweep_s = prev
        wd.reset()
        srv.stop(grace=1)


def run_phases() -> None:
    phase_merged_flight()
    phase_frozen_native_consumer()


def main() -> int:
    if "--phase" in sys.argv:
        run_phases()
        return 0
    here = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env = dict(os.environ)
    env["GRPC_PLATFORM_TYPE"] = "RDMA_BPEV"
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = here + os.pathsep + env.get("PYTHONPATH", "")
    rc = subprocess.run(
        [sys.executable, "-m", "tpurpc.tools.native_obs_smoke", "--phase"],
        env=env, timeout=300).returncode
    if rc != 0:
        print("native obs smoke FAILED")
        return 1
    print("native obs smoke: PASS (merged C+py /debug/flight ordered, "
          "native table scraped, frozen C consumer attributed from "
          "C evidence alone)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
