"""Operator tooling over the standard services (reflection, health)."""
