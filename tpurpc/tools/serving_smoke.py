"""Fast serving-pipeline smoke for the verification gate (tools/check.sh).

Exercises the ISSUE 3 serving path end to end in about a second, with no
jax dependency: a loopback server whose unary echo handler runs on the
INLINE dispatch path, one connection, and a depth-4 pipelined client
issuing 32 tagged requests. Asserts:

* every future completes (no window wedge, no lost completion);
* every response demuxes to the stream that asked — the payload must echo
  its own request's tag, so a stream-id mix-up in the reader (or a
  coalescing corruption on the server's gathered writev) fails loudly;
* out-of-order completion works: one deliberately parked request must not
  block its siblings' futures.

Exit 0 on success; any assertion/exception exits 1 with the reason. This
is the gate's cheap stand-in for the full bench's depth sweep.

    python -m tpurpc.tools.serving_smoke
"""

from __future__ import annotations

import sys
import threading

DEPTH = 4
REQUESTS = 32


def run() -> int:
    from tpurpc.rpc.channel import Channel
    from tpurpc.rpc.server import Server, unary_unary_rpc_method_handler

    park = threading.Event()

    def echo(req, ctx):
        # tag 0 parks until every other response has been demanded —
        # proves siblings complete out of order past a slow stream
        if bytes(req) == b"req:0":
            park.wait(10)
        return b"ok:" + bytes(req)

    srv = Server(max_workers=8)
    srv.add_method("/smoke/Echo",
                   unary_unary_rpc_method_handler(echo, inline=False))
    # the parked handler above must NOT be inline (it blocks); a second,
    # genuinely inline method covers the reactor path
    srv.add_method("/smoke/EchoInline",
                   unary_unary_rpc_method_handler(
                       lambda req, ctx: b"ok:" + bytes(req), inline=True))
    port = srv.add_insecure_port("127.0.0.1:0")
    srv.start()
    try:
        with Channel(f"127.0.0.1:{port}") as ch:
            pl = ch.unary_unary("/smoke/Echo").pipeline(depth=DEPTH)
            slow = pl.call_async(b"req:0", timeout=30)
            futs = [(i, pl.call_async(b"req:%d" % i, timeout=30))
                    for i in range(1, REQUESTS)]
            for i, fut in futs:  # completes while req:0 is parked
                got = fut.result(timeout=10)
                assert got == b"ok:req:%d" % i, (
                    f"demux mix-up: stream {i} got {got!r}")
            assert not slow.done(), "parked request completed early?"
            park.set()
            assert slow.result(timeout=10) == b"ok:req:0"

            ipl = ch.unary_unary("/smoke/EchoInline").pipeline(depth=DEPTH)
            ifuts = [(i, ipl.call_async(b"inl:%d" % i, timeout=30))
                     for i in range(REQUESTS)]
            for i, fut in ifuts:
                got = fut.result(timeout=10)
                assert got == b"ok:inl:%d" % i, (
                    f"inline demux mix-up: stream {i} got {got!r}")
    finally:
        srv.stop(grace=0)
    print(f"serving smoke: depth={DEPTH}, {REQUESTS}+{REQUESTS} pipelined "
          "requests demuxed correctly (pool + inline dispatch)")
    return 0


def main() -> int:
    try:
        return run()
    except BaseException as exc:  # the gate wants a reasoned nonzero exit
        print(f"serving smoke FAILED: {exc!r}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
