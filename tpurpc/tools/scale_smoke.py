"""tpurpc-hive connection-scale smoke (ISSUE 16).

One process, thousands of parked pairs: build a loopback fleet sized to the
fd budget (target 5000 pairs = 2500 connections, 10 fds each), park BOTH
sides of every connection, then wake a slice of it under live pipelined
traffic.  Asserts the things the C100K plane promises:

  * a parked pair holds no ring — RingPool accounting balances exactly
    (free bytes == parked pairs x (ring + status class)), and every parked
    pair's resident estimate is <= 4KiB;
  * park/unpark is invisible to traffic — payloads pipelined into parked
    connections arrive intact after the automatic wake;
  * pool accounting is conserved across unpark (leased + free bytes is
    constant) and drains to zero leased regions at quiesce;
  * the ``pairs_parked`` / ``pair_resident_bytes_est`` fleet gauges and the
    ``ring_pool_{leased,free}_bytes`` gauges agree with ground truth, and
    PAIR_PARK / PAIR_UNPARK flight events exist for the protocol replay;
  * the Poller's idle sweep (TPURPC_PAIR_PARK_S) parks a registered pair
    end-to-end and its parked-stub watcher completes a remote wake with no
    owner thread blocked on the pair.

Runs in ~5s with no jax and no network.  Wired into tools/check.sh.
"""

import dataclasses
import os
import resource
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

RING = 4096
TARGET_CONNS = 2500          # 5000 pairs, the ISSUE 16 floor
FDS_PER_CONN = 10            # 2 socketpair ends + 8 wake-pipe ends (measured)
WAKE_CONNS = 64              # slice woken under pipelined traffic
PAYLOADS = [b"hive-%02d!" % i * 23 for i in range(4)]  # pipelined per conn


def _pump(a, b) -> bool:
    hot = False
    for p in (a, b):
        try:
            if p.drain_notifications():
                p.kick()
                hot = True
        except Exception:
            pass
    return hot


def _pump_until(pairs, pred, deadline_s=10.0) -> bool:
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        if pred():
            return True
        hot = False
        for a, b in pairs:
            hot |= _pump(a, b)
        if not hot:
            time.sleep(0.001)
    return pred()


def _build_fleet():
    import tpurpc.core.pair as P

    soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
    if soft < hard:
        try:
            resource.setrlimit(resource.RLIMIT_NOFILE, (hard, hard))
            soft = hard
        except (ValueError, OSError):
            pass
    cap = max(8, (soft - 100) // FDS_PER_CONN)
    conns = min(TARGET_CONNS, cap)
    if conns < TARGET_CONNS:
        print(f"  [fleet] NOTE: fd limit {soft} caps the fleet at "
              f"{conns} connections ({2 * conns} pairs) — the 5000-pair "
              f"target needs RLIMIT_NOFILE >= {TARGET_CONNS * FDS_PER_CONN + 100}")
    t0 = time.monotonic()
    fleet = [P.create_loopback_pair(ring_size=RING) for _ in range(conns)]
    print(f"  [fleet] {conns} loopback connections ({2 * conns} pairs) "
          f"in {time.monotonic() - t0:.2f}s")
    return fleet


def _park_fleet(fleet) -> None:
    import tpurpc.core.pair as P

    t0 = time.monotonic()
    now = time.monotonic()
    for a, b in fleet:
        a.maybe_park(now, 0.0)
        b.maybe_park(now, 0.0)
    def all_parked():
        return all(a._parked and b._parked for a, b in fleet)
    # a re-initiating sweep: an ack can race the first round's drain order
    deadline = time.monotonic() + 15.0
    while not all_parked() and time.monotonic() < deadline:
        if not _pump_until(fleet, all_parked, deadline_s=1.0):
            now = time.monotonic()
            for a, b in fleet:
                if not a._parked:
                    a.maybe_park(now, 0.0)
                if not b._parked:
                    b.maybe_park(now, 0.0)
    parked = sum(int(a._parked) + int(b._parked) for a, b in fleet)
    assert parked == 2 * len(fleet), \
        f"park sweep incomplete: {parked}/{2 * len(fleet)} pairs parked"
    print(f"  [park] {parked} pairs parked in {time.monotonic() - t0:.2f}s")

    stats = P.RingPool.get().stats()
    per_pair = RING + P.STATUS_BYTES
    want_free = parked * per_pair
    assert stats["free_bytes"] == want_free, \
        f"pool free {stats['free_bytes']} != parked rings {want_free}"
    assert stats["leased_regions"] == 0, stats
    for a, b in fleet:
        for p in (a, b):
            est = p.resident_bytes_est()
            assert est <= 4096, f"parked pair resident estimate {est} > 4KiB"
    print(f"  [park] pool holds {stats['free_bytes']} free bytes "
          f"({stats['free_regions']} regions), 0 leased; "
          f"resident estimate <= 4KiB per parked pair")


def _wake_slice(fleet) -> None:
    import tpurpc.core.pair as P

    subset = fleet[:WAKE_CONNS]
    total_stats = P.RingPool.get().stats()
    conserved = total_stats["free_bytes"] + total_stats["leased_bytes"]

    t0 = time.monotonic()
    want = b"".join(PAYLOADS)
    got = {id(a): bytearray() for a, _ in subset}
    sent = {id(a): 0 for a, _ in subset}
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        done = True
        for a, b in subset:
            k = id(a)
            if sent[k] < len(want):
                # b.send unparks b and wakes parked a in-band; a retryable
                # 0 while the park episode resolves is the contract
                sent[k] += b.send([want[sent[k]:]])
            if len(got[k]) < len(want):
                chunk = a.recv()
                if chunk:
                    got[k] += chunk
            if sent[k] < len(want) or len(got[k]) < len(want):
                done = False
            _pump(a, b)
        if done:
            break
    bad = [k for k, v in got.items() if bytes(v) != want]
    assert not bad, \
        f"{len(bad)}/{len(subset)} woken connections corrupted or incomplete"
    print(f"  [wake] {len(subset)} connections woken under pipelined traffic "
          f"in {time.monotonic() - t0:.2f}s; "
          f"{len(subset)} x {len(want)}B payloads intact")

    stats = P.RingPool.get().stats()
    assert stats["free_bytes"] + stats["leased_bytes"] == conserved, \
        (stats, conserved)
    per_pair = RING + P.STATUS_BYTES
    assert stats["leased_bytes"] == 2 * len(subset) * per_pair, stats
    print(f"  [wake] pool conserved: {stats['leased_bytes']}B re-leased to "
          f"{2 * len(subset)} unparked pairs, "
          f"{stats['free_bytes']}B still pooled")


def _check_observability(fleet) -> None:
    from tpurpc.obs import flight, metrics

    snap = metrics.snapshot()
    parked_truth = sum(int(a._parked) + int(b._parked) for a, b in fleet)
    fleet_gauge = snap["fleet"].get("pairs_parked", {})
    assert fleet_gauge.get("sum") == float(parked_truth), \
        (fleet_gauge, parked_truth)
    import tpurpc.core.pair as P
    stats = P.RingPool.get().stats()
    gauges = snap["gauges"]
    assert gauges.get("ring_pool_free_bytes") == float(stats["free_bytes"]), \
        (gauges.get("ring_pool_free_bytes"), stats)
    assert gauges.get("ring_pool_leased_bytes") == float(
        stats["leased_bytes"]), (gauges.get("ring_pool_leased_bytes"), stats)
    counters = snap["counters"]
    assert counters.get("pair_park", 0) >= parked_truth, counters
    assert counters.get("pair_unpark", 0) >= 2 * WAKE_CONNS, counters
    events = {e["event"] for e in flight.snapshot()}
    assert "pair-park" in events and "pair-unpark" in events, sorted(events)
    print(f"  [obs] pairs_parked={int(fleet_gauge['sum'])} "
          f"pair_park={counters['pair_park']} "
          f"pair_unpark={counters['pair_unpark']}; "
          f"flight has pair-park/pair-unpark events")


def _poller_sweep_roundtrip() -> None:
    """End-to-end: an idle pair registered on the Poller is parked by the
    background sweep, and the parked-stub watcher completes a remote wake
    with no owner thread involved."""
    import tpurpc.core.pair as P
    from tpurpc.core.poller import Poller
    from tpurpc.utils.config import get_config, set_config

    cfg = get_config()
    set_config(dataclasses.replace(cfg, pair_park_s=0.05))
    try:
        Poller.reset()
        poller = Poller.get()
        a, b = P.create_loopback_pair(ring_size=RING)
        poller.add_pollable(a)
        deadline = time.monotonic() + 5.0
        while not a._parked and time.monotonic() < deadline:
            if b.drain_notifications():  # b acks the sweep's park request
                b.kick()
            time.sleep(0.002)
        assert a._parked, "poller sweep never parked the idle pair"
        print("  [sweep] background sweep parked the registered pair "
              "(TPURPC_PAIR_PARK_S=0.05)")
        payload = b"sweep-wake!"
        sent = 0
        deadline = time.monotonic() + 5.0
        while sent < len(payload) and time.monotonic() < deadline:
            sent += b.send([payload[sent:]])
            if b.drain_notifications():
                b.kick()
            time.sleep(0.002)
        # a has NO owner thread: only the poller's parked-stub watcher can
        # see the wake frame and run the unpark
        deadline = time.monotonic() + 5.0
        got = bytearray()
        while len(got) < len(payload) and time.monotonic() < deadline:
            if a._parked:
                time.sleep(0.002)
                continue
            chunk = a.recv()
            if chunk:
                got += chunk
            else:
                time.sleep(0.002)
        assert bytes(got) == payload, \
            f"ownerless wake lost data: {bytes(got)!r}"
        print("  [sweep] parked-stub watcher completed the ownerless wake; "
              "payload intact")
        a.destroy()
        b.destroy()
    finally:
        set_config(cfg)
        Poller.reset()


def _teardown(fleet) -> None:
    import tpurpc.core.pair as P

    for a, b in fleet:
        try:
            a.destroy()
            b.destroy()
        except Exception:
            pass
    stats = P.RingPool.get().stats()
    assert stats["leased_regions"] == 0, \
        f"destroy leaked pool leases: {stats}"
    print(f"  [teardown] fleet destroyed; pool leases drained to zero "
          f"({stats['free_regions']} regions retained for reuse)")


def main() -> int:
    t0 = time.monotonic()
    import tpurpc.core.pair as P

    P.RingPool.reset()
    fleet = _build_fleet()
    try:
        _park_fleet(fleet)
        _wake_slice(fleet)
        _check_observability(fleet)
        _poller_sweep_roundtrip()
    finally:
        _teardown(fleet)
        P.RingPool.reset()
    print(f"scale smoke: PASS ({2 * len(fleet)} pairs, "
          f"{time.monotonic() - t0:.1f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
