"""tpurpc-oracle offline diagnosis: replay a postmortem bundle into the
same ranked causal report the live ``/debug/diagnose`` route serves.

    python -m tpurpc.tools.diagnose <bundle-dir | bundles-root> [--json]
                                    [--symptom KIND]

The bundle's frozen planes (``history.json`` tsdb windows,
``flight-*.json`` event algebra, ``stalls.json`` watchdog state,
``slo.json``, ``waterfall.json``) run through the IDENTICAL rule engine
(:mod:`tpurpc.obs.diagnose` — :class:`BundlePlanes` is just another
``Planes``), so a postmortem read days later ranks the same cause the
live route ranked at trip time. Pointed at a root of bundles it picks
the newest. ``--json`` prints the machine document (what
``diagnosis.json`` inside the bundle holds); the default is the prose
report."""

from __future__ import annotations

import argparse
import json
import os
import sys

from tpurpc.obs import diagnose as _diagnose


def _resolve(path: str) -> str:
    """A bundle dir as-is, or the newest bundle under a root."""
    if os.path.isfile(os.path.join(path, "meta.json")):
        return path
    try:
        names = sorted(n for n in os.listdir(path)
                       if n.startswith("bundle-")
                       and os.path.isdir(os.path.join(path, n)))
    except OSError:
        names = []
    if names:
        return os.path.join(path, names[-1])
    return path


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tpurpc.tools.diagnose",
        description="replay a postmortem bundle through the causal "
                    "diagnosis engine")
    ap.add_argument("path", help="bundle directory (or a root of bundles "
                                 "— the newest is diagnosed)")
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable report")
    ap.add_argument("--symptom", default=None,
                    help="pin the symptom (auto|watchdog|slo|<query>)")
    args = ap.parse_args(argv)

    path = _resolve(args.path)
    if not os.path.isdir(path):
        print(f"no such bundle: {args.path}", file=sys.stderr)
        return 2
    doc = _diagnose.diagnose_bundle(path, want=args.symptom)
    if args.json:
        print(json.dumps(doc, indent=1))
    else:
        print(f"bundle: {path}")
        if doc.get("trigger"):
            print(f"trigger: {doc['trigger']}")
        sys.stdout.write(_diagnose.render_text(doc))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
