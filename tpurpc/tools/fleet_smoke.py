"""Fleet front-door smoke for the verification gate (tools/check.sh).

The ISSUE 6 story end to end in a few seconds, no jax: 3 echo servers
behind a ``round_robin`` channel, steady traffic from hedged unary
callers, then — mid-traffic — one server is KILLED (stop grace=0) and
another is DRAINED (``Server.drain``). Asserts:

* **zero failed RPCs**: every call completes OK. Kill coverage comes from
  hedging (the attempt on the dead server fails UNAVAILABLE, the hedge on
  a live one wins); drain coverage from the refused-stream migration
  (FLAG_REFUSED replays exclude the drainer).
* the drain completes within its linger budget, and the drained server
  receives no traffic afterwards;
* the flight recorder holds the hedge (``hedge-fired``/``hedge-won``) and
  drain (``drain-begin``→``drain-end``, ordered) evidence the chaos
  postmortem story depends on.

Exit 0 on success; any assertion/exception exits 1 with the reason.

    python -m tpurpc.tools.fleet_smoke
"""

from __future__ import annotations

import sys
import threading
import time

CLIENTS = 4
SERVERS = 3


def run() -> int:
    from tpurpc.obs import flight
    from tpurpc.rpc.channel import Channel, HedgingPolicy
    from tpurpc.rpc.server import Server, unary_unary_rpc_method_handler

    #: set → server 0 turns into the SLOW replica (the degraded-backend
    #: phase: in-flight calls on it must hedge to a healthy sibling)
    slow_mode = threading.Event()
    rigs = []
    for i in range(SERVERS):
        srv = Server(max_workers=8, native_dataplane=False)
        calls = [0]

        def handler(req, ctx, _c=calls, _slow=(i == 0)):
            _c[0] += 1
            time.sleep(0.25 if _slow and slow_mode.is_set() else 0.001)
            return req

        srv.add_method("/fleet/Echo", unary_unary_rpc_method_handler(handler))
        port = srv.add_insecure_port("127.0.0.1:0")
        srv.start()
        rigs.append((srv, port, calls))
    addrs = ",".join(f"127.0.0.1:{p}" for _, p, _ in rigs)
    flight.RECORDER.reset()
    stop = threading.Event()
    errors: list = []
    done = [0] * CLIENTS
    try:
        with Channel(f"ipv4:{addrs}", lb_policy="round_robin",
                     hedging_policy=HedgingPolicy(max_attempts=3,
                                                  hedging_delay=0.05)) as ch:
            mc = ch.unary_unary("/fleet/Echo")

            def worker(idx: int):
                while not stop.is_set():
                    payload = b"c%d-%d" % (idx, done[idx])
                    try:
                        got = bytes(mc(payload, timeout=30))
                        assert got == payload, (got, payload)
                        done[idx] += 1
                    except Exception as exc:
                        errors.append(exc)
                        return

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(CLIENTS)]
            [t.start() for t in threads]
            time.sleep(0.4)  # steady state
            slow_mode.set()  # server 0 degrades: its calls must hedge out
            time.sleep(0.5)
            rigs[0][0].stop(grace=0)          # ...then KILL it outright...
            time.sleep(0.4)
            clean = rigs[1][0].drain(linger=10.0)  # ...DRAIN another
            drained_at = rigs[1][2][0]
            time.sleep(0.8)  # traffic continues on the last healthy server
            stop.set()
            [t.join(timeout=30) for t in threads]
        assert not errors, f"failed RPCs: {errors[:3]}"
        assert all(n > 10 for n in done), f"a client stalled: {done}"
        assert clean, "drain missed its linger budget"
        assert rigs[1][2][0] == drained_at, \
            "drained server saw traffic after drain"
        assert rigs[2][2][0] > 0, "surviving server took no traffic"
        events = [(e["event"], e["t_ns"]) for e in flight.snapshot()]
        names = [ev for ev, _t in events]
        assert "hedge-fired" in names, \
            "no hedge fired across the slow/kill phase"
        assert "hedge-won" in names, "no hedge won"
        t_begin = next(t for ev, t in events if ev == "drain-begin")
        t_end = next(t for ev, t in events if ev == "drain-end")
        assert t_begin <= t_end, "drain flight events out of order"
    finally:
        stop.set()
        for srv, _, _ in rigs:
            try:
                srv.stop(grace=0)
            except Exception:
                pass
    print(f"fleet smoke: {sum(done)} RPCs across {CLIENTS} hedged clients, "
          f"1 server killed + 1 drained mid-traffic, zero failures; "
          "hedge + drain flight events present and ordered")
    return 0


def main() -> int:
    try:
        return run()
    except BaseException as exc:  # the gate wants a reasoned nonzero exit
        print(f"fleet smoke FAILED: {exc!r}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
