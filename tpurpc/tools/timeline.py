"""tpurpc-lens unified timeline: one Perfetto file for a whole deployment.

    python -m tpurpc.tools.timeline HOST:PORT [HOST:PORT ...] -o trace.json

Collects, from EVERY named shard/fleet member over the existing
introspection plane (the same plain-HTTP routes ``curl`` reaches):

* ``/traces``          — the per-RPC span trees (chrome-trace export; the
                         PR 7 fan-out merges shard workers, so one serving
                         port yields every worker's spans);
* ``/debug/flight``    — the flight recorder's transport edges;
* ``/debug/profile``   — the sampling profiler's recent raw samples
                         (``?samples=1``): what each thread's CPU was doing;
* ``/metrics``         — a handful of load gauges as counter tracks.

and emits ONE Perfetto-loadable chrome-trace JSON with named process/thread
lanes: a slow RPC's span tree, the transport edges under it, and the CPU
stages alongside — on a single shared time axis.

**Clock alignment (the satellite fix).** Every tpurpc timestamp is
``time.monotonic_ns``, and every process has its OWN monotonic epoch —
merging raw stamps from two processes misaligns by their boot-time delta.
Each exporter therefore publishes a monotonic↔wall *clock anchor*
(:func:`tpurpc.obs.tracing.clock_anchor` — one bracketed simultaneous
reading of both clocks) in its trace metadata, and this collector rebases
every event onto the wall clock::

    wall_ns = t_mono_ns - anchor.mono_ns + anchor.wall_ns

then subtracts the earliest anchor's wall time so ``ts`` stays small. A
process exporting no anchor (a pre-lens build) is rebased with zero offset
and flagged in the summary — visible, never silently wrong.

The merge itself is pure (:func:`rebase_events`, :func:`build_timeline`),
so the pinned two-fake-processes-with-known-skew test needs no sockets.
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.request
from typing import Dict, List, Optional, Tuple

#: synthetic tid lanes inside each process row
TID_FLIGHT = 0xF11D
TID_GAUGES = 0xF22E

#: gauges worth a counter track (present on any post-PR4 build)
GAUGE_TRACKS = (
    "tpurpc_ring_in_flight_bytes",
    "tpurpc_pipeline_inflight",
    "tpurpc_batcher_queue_depth",
    "tpurpc_pairs_connected",
)


def _get(target: str, path: str, timeout: float = 10.0) -> Optional[bytes]:
    try:
        with urllib.request.urlopen(f"http://{target}{path}",
                                    timeout=timeout) as resp:
            return resp.read()
    except Exception:
        return None


def _get_json(target: str, path: str) -> Optional[dict]:
    raw = _get(target, path)
    if raw is None:
        return None
    try:
        return json.loads(raw)
    except ValueError:
        return None


# -- clock rebasing (pure: the pinned skew test drives these directly) --------

def rebase_ns(t_mono_ns: int, anchor: Optional[dict],
              epoch_wall_ns: int) -> float:
    """One monotonic stamp → microseconds since ``epoch_wall_ns`` on the
    shared wall clock, via the exporting process's anchor."""
    if anchor:
        wall = t_mono_ns - int(anchor["mono_ns"]) + int(anchor["wall_ns"])
    else:
        wall = t_mono_ns  # no anchor (pre-lens exporter): raw, flagged
    return (wall - epoch_wall_ns) / 1e3


def rebase_events(events: List[dict], anchor: Optional[dict],
                  epoch_wall_ns: int, pid: int) -> List[dict]:
    """Rebase one process's chrome-trace events onto the shared axis and
    re-pid them into their assigned lane. ``ts`` arrives in µs of the
    process-local monotonic clock (chrome_trace's export unit)."""
    out = []
    for e in events:
        e = dict(e)
        e["pid"] = pid
        if e.get("ph") != "M":  # metadata rows carry no timestamp
            ts_us = float(e.get("ts", 0.0))
            e["ts"] = rebase_ns(int(ts_us * 1e3), anchor, epoch_wall_ns)
        out.append(e)
    return out


# -- collection ---------------------------------------------------------------

def collect(target: str) -> dict:
    """Everything one member exports, raw (monotonic clocks intact)."""
    return {
        "target": target,
        "traces": _get_json(target, "/traces"),
        "flight": _get_json(target, "/debug/flight"),
        "profile": _get_json(target, "/debug/profile?samples=1"),
        "metrics": (_get(target, "/metrics") or b"").decode(
            "utf-8", "replace"),
    }


def _processes(col: dict) -> List[Tuple[str, Optional[int], Optional[dict],
                                        List[dict]]]:
    """Split one member's /traces doc into per-process lanes:
    ``(label, shard_id|None, anchor|None, traceEvents)``. A sharded member
    (the PR 7 fan-out doc: per-shard pids + ``clock_anchors``) yields one
    lane per worker; a plain member yields one lane."""
    doc = col.get("traces") or {}
    target = col["target"]
    anchors = doc.get("clock_anchors")
    if anchors is not None:  # merged multi-shard document
        by_shard: Dict[int, List[dict]] = {}
        for e in doc.get("traceEvents", ()):
            by_shard.setdefault(int(e.get("pid", 0)), []).append(e)
        shards = sorted(set(by_shard) | {int(k) for k in anchors})
        return [(f"{target} shard {k}", k, anchors.get(str(k)),
                 by_shard.get(k, [])) for k in shards]
    return [(target, None, doc.get("clock_anchor"),
             list(doc.get("traceEvents", ())))]


def build_timeline(collected: List[dict]) -> dict:
    """The pure merge: N members' raw collections → one chrome-trace doc
    with named per-process lanes, everything rebased onto the earliest
    anchor's wall clock."""
    lanes = []  # (label, shard, anchor, trace_events, member)
    for col in collected:
        for label, shard, anchor, events in _processes(col):
            lanes.append((label, shard, anchor, events, col))
    anchors = [a for _l, _s, a, _e, _c in lanes if a]
    epoch = min(int(a["wall_ns"]) for a in anchors) if anchors else 0
    out_events: List[dict] = []
    unanchored: List[str] = []
    for pid, (label, shard, anchor, events, col) in enumerate(lanes, 1):
        if not anchor:
            unanchored.append(label)
        out_events.append({"ph": "M", "name": "process_name", "pid": pid,
                           "tid": 0, "args": {"name": label}})
        out_events.extend(
            e for e in rebase_events(events, anchor, epoch, pid)
            if not (e.get("ph") == "M" and e.get("name") == "process_name"))

        # flight edges as instant events under the same lane
        fdoc = col.get("flight") or {}
        out_events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": TID_FLIGHT,
                           "args": {"name": "flight-recorder"}})
        for ev in fdoc.get("events", ()):
            if shard is not None and ev.get("shard") not in (None, shard):
                continue
            out_events.append({
                "ph": "i", "s": "t", "cat": "flight",
                "name": ev.get("event", "?"),
                "ts": rebase_ns(int(ev.get("t_ns", 0)), anchor, epoch),
                "pid": pid, "tid": TID_FLIGHT,
                "args": {"entity": ev.get("entity"), "a1": ev.get("a1"),
                         "a2": ev.get("a2")},
            })

        # profiler samples as fixed-width slices per sampled thread
        pdoc = col.get("profile") or {}
        if shard is not None and "shards" in pdoc:
            pdoc = (pdoc.get("shards") or {}).get(str(shard)) or {}
        hz = float(pdoc.get("hz") or 50.0)
        width_us = 1e6 / hz
        named = set()
        for s in pdoc.get("recent", ()):
            tid = int(s.get("tid", 0)) & 0xFFFF
            if tid not in named:
                named.add(tid)
                out_events.append({
                    "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                    "args": {"name": f"cpu {s.get('thread') or hex(tid)}"}})
            out_events.append({
                "ph": "X", "cat": "lens-profile",
                "name": s.get("stage", "?"),
                "ts": rebase_ns(int(s.get("t_ns", 0)), anchor, epoch),
                "dur": width_us, "pid": pid, "tid": tid,
            })

        # gauge snapshot as counter events at collection time (one point —
        # live dashboards are tools.top's job; the timeline wants context)
        if shard is None and col.get("metrics") and anchor:
            from tpurpc.tools.top import parse_prometheus

            m = parse_prometheus(col["metrics"])
            ts = rebase_ns(int(anchor["mono_ns"]), anchor, epoch)
            for gname in GAUGE_TRACKS:
                val = m.get((gname, ""))
                if val is None:
                    continue
                out_events.append({
                    "ph": "C", "name": gname, "ts": ts, "pid": pid,
                    "tid": TID_GAUGES, "args": {"value": val}})
    # normalize: anchors are captured at EXPORT time, so events recorded
    # before the earliest export rebase negative — shift the whole doc so
    # the earliest event is t=0 (the epoch records the absolute origin)
    stamps = [e["ts"] for e in out_events if "ts" in e]
    t_min = min(stamps) if stamps else 0.0
    for e in out_events:
        if "ts" in e:
            e["ts"] = round(e["ts"] - t_min, 3)
    return {
        "traceEvents": out_events,
        "displayTimeUnit": "ms",
        "otherData": {
            "tool": "tpurpc.tools.timeline",
            "members": [c["target"] for c in collected],
            "lanes": len(lanes),
            "epoch_wall_ns": epoch + int(t_min * 1e3),
            "unanchored": unanchored,
        },
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpurpc.tools.timeline",
        description="Collect spans + flight edges + profile samples from "
                    "every shard/fleet member and emit one Perfetto-"
                    "loadable trace on a shared wall-clock axis.")
    ap.add_argument("targets", nargs="+",
                    help="HOST:PORT of each member's serving port")
    ap.add_argument("-o", "--out", default="tpurpc-timeline.json")
    args = ap.parse_args(argv)

    collected = []
    for t in args.targets:
        col = collect(t)
        if col["traces"] is None and col["flight"] is None:
            print(f"timeline: {t} unreachable (no /traces, no /debug/flight)",
                  file=sys.stderr)
            continue
        collected.append(col)
    if not collected:
        print("timeline: no reachable members", file=sys.stderr)
        return 1
    doc = build_timeline(collected)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    meta = doc["otherData"]
    n_span = sum(1 for e in doc["traceEvents"] if e.get("ph") == "X"
                 and e.get("cat") != "lens-profile")
    n_prof = sum(1 for e in doc["traceEvents"] if e.get("cat")
                 == "lens-profile")
    n_flight = sum(1 for e in doc["traceEvents"] if e.get("ph") == "i")
    print(f"timeline: {args.out} — {meta['lanes']} process lane(s), "
          f"{n_span} spans, {n_flight} flight edges, {n_prof} cpu samples"
          + (f"; UNANCHORED (raw clock): {meta['unanchored']}"
             if meta["unanchored"] else ""))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
