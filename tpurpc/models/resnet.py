"""ResNet-50 in flax.linen — the serving flagship (BASELINE.json config #5:
"JAX ResNet-50 inference server: image request tensors zero-copy RDMA→HBM").

Standard bottleneck-v1.5 architecture (stride-2 on the 3x3), NHWC layout —
the TPU-native choice: XLA's conv tiling prefers channels-last, and bfloat16
activations keep the MXU at full rate. The reference has no models at all
(SURVEY.md §2.7); this exists to put a real MXU-bound workload behind the RPC
plane, per BASELINE.

Inference entry: :func:`resnet50`, then ``model.apply({'params': p}, x)``.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

ModuleDef = Any


class BottleneckBlock(nn.Module):
    filters: int
    strides: Tuple[int, int]
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1),
                                 self.strides, name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype)
        norm = functools.partial(nn.BatchNorm, use_running_average=not train,
                                 momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2),
                 padding=[(3, 3), (3, 3)], name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = BottleneckBlock(self.num_filters * 2 ** i, strides,
                                    conv, norm)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


def resnet50(num_classes: int = 1000, dtype=jnp.float32) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), num_classes=num_classes,
                  dtype=dtype)


def resnet18_thin(num_classes: int = 1000, dtype=jnp.float32) -> ResNet:
    """Small stand-in with the same code path for fast tests/compile checks."""
    return ResNet(stage_sizes=(1, 1), num_classes=num_classes,
                  num_filters=8, dtype=dtype)


def init_resnet(key, model: ResNet, image_size: int = 224,
                batch: int = 1):
    x = jnp.zeros((batch, image_size, image_size, 3), jnp.float32)
    variables = model.init(key, x, train=False)
    return variables


def make_infer_fn(model: ResNet) -> Callable:
    """Jittable (variables, images) → logits, inference mode."""
    def infer(variables, images):
        return model.apply(variables, images, train=False)
    return infer
