"""Flagship sharded model: decoder-only MoE transformer over the 5-axis mesh.

Every parallelism family the TPU build owes (task brief; the reference itself
has none — SURVEY.md §2.7) lands here, in one ``shard_map`` program:

* **dp**  batch sharding; gradient reduction falls out of shard_map transpose
* **pp**  layers stacked on a leading axis sharded over 'pp';
          :func:`tpurpc.parallel.pipeline.pipeline_apply` rings microbatches
* **sp**  sequence sharded; :func:`ring_attention_block` rotates K/V
* **tp**  attention heads + expert FFN column-split; one psum per block
* **ep**  experts sharded; two all_to_alls per MoE layer
          (batch is sharded over ('dp','ep') jointly so expert dispatch moves
          distinct tokens — ep doubles as data parallelism outside MoE layers,
          the standard Switch/GShard layout)

Weights stay in the param dtype (bfloat16 on TPU keeps the MXU at full rate);
softmax/router/loss statistics accumulate in float32.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from tpurpc.parallel.mesh import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpurpc.parallel.moe import moe_block, MoEParams
from tpurpc.parallel.pipeline import microbatch, pipeline_apply, unmicrobatch
from tpurpc.parallel.ring_attention import ring_attention_block


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 256
    d_model: int = 128
    n_heads: int = 8
    head_dim: int = 16
    d_ff: int = 256
    n_layers: int = 4
    n_experts: int = 2
    capacity_factor: float = 2.0
    n_micro: int = 2          # pipeline microbatches (must divide local batch)
    dtype: Any = jnp.float32  # bfloat16 on real TPU

    def validate(self, mesh: Mesh) -> None:
        ax = dict(zip(mesh.axis_names, mesh.devices.shape))
        assert self.n_heads % ax.get("tp", 1) == 0, "heads % tp != 0"
        assert self.n_experts % ax.get("ep", 1) == 0, "experts % ep != 0"
        assert self.n_layers % ax.get("pp", 1) == 0, "layers % pp != 0"


def init_params(key, cfg: TransformerConfig) -> Dict[str, jax.Array]:
    L, d, H, Dh = cfg.n_layers, cfg.d_model, cfg.n_heads, cfg.head_dim
    E, f, V = cfg.n_experts, cfg.d_ff, cfg.vocab
    ks = jax.random.split(key, 8)
    s = d ** -0.5
    dt = cfg.dtype
    return {
        "embed": (jax.random.normal(ks[0], (V, d)) * s).astype(dt),
        "ln_f": jnp.ones((d,), dt),
        "ln1": jnp.ones((L, d), dt),
        "ln2": jnp.ones((L, d), dt),
        "wq": (jax.random.normal(ks[1], (L, d, H, Dh)) * s).astype(dt),
        "wk": (jax.random.normal(ks[2], (L, d, H, Dh)) * s).astype(dt),
        "wv": (jax.random.normal(ks[3], (L, d, H, Dh)) * s).astype(dt),
        "wo": (jax.random.normal(ks[4], (L, H, Dh, d))
               * (H * Dh) ** -0.5).astype(dt),
        "router": (jax.random.normal(ks[5], (L, d, E)) * s).astype(dt),
        "w_in": (jax.random.normal(ks[6], (L, E, d, f)) * s).astype(dt),
        "w_out": (jax.random.normal(ks[7], (L, E, f, d))
                  * f ** -0.5).astype(dt),
    }


def param_specs(cfg: TransformerConfig) -> Dict[str, P]:
    return {
        "embed": P(None, None),
        "ln_f": P(None),
        "ln1": P("pp", None),
        "ln2": P("pp", None),
        "wq": P("pp", None, "tp", None),
        "wk": P("pp", None, "tp", None),
        "wv": P("pp", None, "tp", None),
        "wo": P("pp", "tp", None, None),
        "router": P("pp", None, None),
        "w_in": P("pp", "ep", None, None),
        "w_out": P("pp", "ep", None, None),
    }


DATA_SPEC = P(("dp", "ep"), "sp")  # [B, S] tokens


def _layer_norm(x, scale):
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    return ((xf - mu) * lax.rsqrt(var + 1e-6) * scale.astype(jnp.float32)
            ).astype(x.dtype)


def _block(lp: Dict[str, jax.Array], h: jax.Array,
           cfg: TransformerConfig) -> jax.Array:
    """One transformer block on local shards. h: [b, s_loc, d]."""
    # -- attention: tp over heads, sp ring over sequence --
    x = _layer_norm(h, lp["ln1"])
    q = jnp.einsum("bsd,dhk->bhsk", x, lp["wq"])
    k = jnp.einsum("bsd,dhk->bhsk", x, lp["wk"])
    v = jnp.einsum("bsd,dhk->bhsk", x, lp["wv"])
    o = ring_attention_block(q, k, v, axis_name="sp", causal=True)
    attn = jnp.einsum("bhsk,hkd->bsd", o, lp["wo"])
    attn = lax.psum(attn, "tp")
    h = h + attn
    # -- MoE FFN: ep all_to_all --
    x = _layer_norm(h, lp["ln2"])
    b, s, d = x.shape
    flat = x.reshape(b * s, d)
    moe = MoEParams(router=lp["router"], w_in=lp["w_in"], w_out=lp["w_out"])
    y, _aux = moe_block(moe, flat, axis_name="ep",
                        capacity_factor=cfg.capacity_factor)
    return h + y.reshape(b, s, d)


_LAYER_KEYS = ("ln1", "ln2", "wq", "wk", "wv", "wo", "router", "w_in", "w_out")


def _forward_local(params: Dict[str, jax.Array], tokens: jax.Array,
                   cfg: TransformerConfig) -> jax.Array:
    """shard_map body: local tokens [b_loc, s_loc] → local logits."""
    h = jnp.take(params["embed"], tokens, axis=0)          # [b, s, d]

    stage_params = {k: params[k] for k in _LAYER_KEYS}     # [L_loc, ...]

    def stage_fn(sp_params, hm):
        def one_layer(carry, lp):
            return _block(lp, carry, cfg), None
        out, _ = lax.scan(one_layer, hm, sp_params)
        return out

    hm = microbatch(h, cfg.n_micro)
    hm = pipeline_apply(stage_fn, stage_params, hm, axis_name="pp")
    h = unmicrobatch(hm)

    h = _layer_norm(h, params["ln_f"])
    logits = jnp.einsum("bsd,vd->bsv", h.astype(jnp.float32),
                        params["embed"].astype(jnp.float32))
    return logits


def _loss_local(params, tokens, targets, cfg) -> jax.Array:
    logits = _forward_local(params, tokens, cfg)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    loss = jnp.mean(nll)
    return lax.pmean(loss, ("dp", "ep", "sp"))


def _in_specs(cfg: TransformerConfig):
    return (param_specs(cfg), DATA_SPEC, DATA_SPEC)


def build_loss_fn(cfg: TransformerConfig, mesh: Mesh):
    cfg.validate(mesh)
    body = functools.partial(_loss_local, cfg=cfg)
    return shard_map(body, mesh=mesh,
                     in_specs=_in_specs(cfg), out_specs=P(),
                     check_rep=False)


def build_forward(cfg: TransformerConfig, mesh: Mesh):
    """jit-ready sharded forward: (params, tokens[B,S]) → logits."""
    cfg.validate(mesh)
    body = functools.partial(_forward_local, cfg=cfg)
    fwd = shard_map(body, mesh=mesh,
                    in_specs=(param_specs(cfg), DATA_SPEC),
                    out_specs=P(("dp", "ep"), "sp", None),
                    check_rep=False)
    return jax.jit(fwd)


def build_train_step(cfg: TransformerConfig, mesh: Mesh, lr: float = 1e-3):
    """Full sharded training step: (params, opt_state, tokens, targets) →
    (params, opt_state, loss). Adam moments inherit param shardings."""
    import optax

    cfg.validate(mesh)
    opt = optax.adamw(lr)
    loss_fn = build_loss_fn(cfg, mesh)

    def step(params, opt_state, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return jax.jit(step), opt


def shard_params(params, cfg: TransformerConfig, mesh: Mesh):
    specs = param_specs(cfg)
    return {k: jax.device_put(v, NamedSharding(mesh, specs[k]))
            for k, v in params.items()}
