"""Step-function decode models: the contract tpurpc-cadence schedules.

One-shot serving (``serve_jax``) wraps a callable ``fn(tree) -> tree`` whose
leaves carry a leading batch axis; the :class:`~tpurpc.jaxshim.service.
FanInBatcher` stacks requests along that axis and dispatches once.
Autoregressive generation needs the SAME discipline applied *per decode
step*: the model is two batched callables instead of one, and the batch
membership CHANGES between calls — that re-batching is the scheduler's job
(:mod:`tpurpc.serving.scheduler`), not the model's.

The **step-model contract** (serve_jax's signature discipline, iterated):

* ``prefill(prompts) -> (states, first_tokens)`` — ``prompts`` is a list of
  1-D ``int32`` token arrays (ragged lengths are the model's problem: pad,
  bucket, or loop — the scheduler only promises a per-step *token budget*
  bound on ``sum(len(p))``). Returns ``states`` with a leading batch axis
  (row ``i`` is prompt ``i``'s decode state) and ``first_tokens``, the
  ``int32[B]`` first sampled token per row.
* ``step(states, tokens) -> (states, tokens)`` — one decode step for the
  whole batch: row-aligned state and last-token arrays in, advanced state
  and next-token arrays out. Shape-polymorphic ONLY in the leading axis, so
  a jitted implementation compiles once per batch bucket exactly like the
  one-shot path.
* ``eos`` — the stop token id, or ``None`` for never-stop models.

Rows must be independent: the scheduler concatenates, slices, and re-orders
rows across calls (join/leave/preempt at step boundaries), and retries a
failed batched call row-by-row so a poisoned sequence fails ALONE — both
moves are only sound when row ``i``'s outputs depend on row ``i``'s inputs.

The **explicit-KV contract** (tpurpc-keystone, ISSUE 11) is the same
discipline with the state made addressable: instead of an opaque
``states`` array the model reads and writes per-sequence KV through a
block table (:class:`~tpurpc.serving.kv.SeqKv` / ``HostKv`` — anything
with ``entry``/``last``/``append``/``truncate`` over 16-byte
``(hash, token, flags)`` records):

* ``prefill_paged(prompts, kvs) -> first_tokens`` — for each row,
  entries ``[0, kvs[i].length)`` are ALREADY PRESENT (a prefix-cache hit
  or a resumed handoff: prefill is SKIPPED for that span) and the model
  appends one entry per remaining prompt token plus the first sampled
  token's entry. Entry ``p`` must depend only on tokens ``0..p`` — the
  invariant that makes prefix sharing, swap, and migration sound.
* ``step_paged(kvs, tokens) -> tokens`` — one decode step reading each
  row's LAST entry and appending the next. Rows independent, same
  poison/batch-failure semantics as ``step``.

The two contracts are value-equivalent by construction (the regression
tests assert exact token equality between the opaque-state and paged
paths for the same prompts).

:class:`ToyDecodeModel` is the reference implementation of BOTH contracts:
a deterministic affine-hash generator, pure numpy (the smoke tools and
scheduler tests stay jax-free), with knobs to induce the failure modes the
scheduler must contain (``poison_token``, ``step_delay_s``).
:func:`reference_decode` recomputes any prompt's exact token stream
out-of-band, so transport tests can assert per-token VALUES, not just
counts.
"""

from __future__ import annotations

import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

__all__ = ["ToyDecodeModel", "reference_decode"]

#: state vector layout of the toy model: [hash, last_token, poisoned]
_STATE_DIM = 3
#: multiplier/increment of the toy model's affine hash (any odd pair works;
#: these keep short prompts from colliding within a few steps)
_MULT = 1103515245
_INC = 12345


class ToyDecodeModel:
    """Deterministic autoregressive stand-in implementing the step-model
    contract in pure numpy.

    The "model" is an affine hash: prefill folds the prompt tokens into a
    64-bit state, and each step advances ``h = h * MULT + INC`` emitting
    ``(h >> 16) % vocab``. Deterministic, row-independent, and trivially
    recomputable (:func:`reference_decode`) — which is exactly what a
    scheduler test needs: any reordering, cross-row mixup, or dropped step
    changes the emitted values, not just their count.

    Failure knobs:

    * ``poison_token`` — a prompt containing it marks its ROW poisoned:
      prefill succeeds (the poison is latent, like a NaN that hasn't hit a
      check yet), and any ``step`` whose batch contains a poisoned row
      raises — the whole-batch failure a bad input causes a real jitted
      call. Single-row steps on clean rows succeed: the scheduler's
      row-by-row isolation retry can prove poison fails alone.
    * ``step_delay_s`` — sleeps inside every ``step`` call: an induced slow
      decode step for watchdog-attribution and saturation tests.
    """

    def __init__(self, vocab: int = 251, eos: Optional[int] = None,
                 poison_token: Optional[int] = None,
                 step_delay_s: float = 0.0):
        if vocab < 2:
            raise ValueError("vocab must be >= 2")
        self.vocab = int(vocab)
        self.eos = eos
        self.poison_token = poison_token
        self.step_delay_s = float(step_delay_s)
        self.prefills = 0
        self.steps = 0

    # -- the step-model contract ----------------------------------------------

    def prefill(self, prompts: Sequence[np.ndarray]
                ) -> Tuple[np.ndarray, np.ndarray]:
        self.prefills += 1
        states = np.zeros((len(prompts), _STATE_DIM), dtype=np.uint64)
        for i, p in enumerate(prompts):
            p = np.asarray(p, dtype=np.int64).reshape(-1)
            if p.size == 0:
                raise ValueError("empty prompt")
            h = np.uint64(0)
            for t in p.tolist():
                h = np.uint64((int(h) * _MULT + _INC + int(t))
                              & 0xFFFFFFFFFFFFFFFF)
            bad = (self.poison_token is not None
                   and bool(np.any(p == self.poison_token)))
            states[i, 0] = h
            states[i, 2] = np.uint64(1 if bad else 0)
        states, tokens = self._advance(states)
        return states, tokens

    def step(self, states: np.ndarray, tokens: np.ndarray
             ) -> Tuple[np.ndarray, np.ndarray]:
        self.steps += 1
        states = np.asarray(states, dtype=np.uint64)
        if states.ndim != 2 or states.shape[1] != _STATE_DIM:
            raise ValueError(f"bad state shape {states.shape}")
        if self.step_delay_s:
            time.sleep(self.step_delay_s)
        if np.any(states[:, 2] != 0):
            raise ValueError("poisoned row in decode batch")
        return self._advance(states)

    # -- the explicit-KV contract (tpurpc-keystone) ---------------------------

    def prefill_paged(self, prompts: Sequence[np.ndarray], kvs: Sequence
                      ) -> np.ndarray:
        """Paged prefill: fold each prompt's UNCACHED tail into its block
        table. Row ``i`` starts from ``kvs[i].length`` entries already
        present (0 for a cold prompt; the shared span on a prefix-cache
        hit, whose last entry seeds the hash — prefill skipped for it),
        appends one entry per remaining prompt token, then samples and
        appends the first generated token. Returns ``int32[B]`` first
        tokens. Value-identical to :meth:`prefill`."""
        self.prefills += 1
        out = np.zeros(len(prompts), dtype=np.int32)
        for i, (p, kv) in enumerate(zip(prompts, kvs)):
            p = np.asarray(p, dtype=np.int64).reshape(-1)
            if p.size == 0:
                raise ValueError("empty prompt")
            start = kv.length
            if start > p.size:
                raise ValueError(f"table holds {start} entries for a "
                                 f"{p.size}-token prompt")
            if start:
                h, _tok, flags = kv.entry(start - 1)
            else:
                h, flags = 0, 0
            for t in p[start:].tolist():
                h = (int(h) * _MULT + _INC + int(t)) & 0xFFFFFFFFFFFFFFFF
                if self.poison_token is not None \
                        and t == self.poison_token:
                    flags |= 1  # FLAG_POISONED: latent, trips at step
                kv.append(h, int(t), flags)
            h = (int(h) * _MULT + _INC) & 0xFFFFFFFFFFFFFFFF
            tok = int((h >> 16) % self.vocab)
            kv.append(h, tok, flags)
            out[i] = tok
        return out

    def step_paged(self, kvs: Sequence, tokens: np.ndarray) -> np.ndarray:
        """One paged decode step for the whole batch: read each row's
        last entry, advance, append. Batched-failure semantics match
        :meth:`step`: any poisoned row fails the WHOLE batched call (the
        scheduler's row-by-row isolation retry then proves poison fails
        alone), and a partial append is undone by the scheduler via
        ``truncate`` before the retry."""
        self.steps += 1
        if self.step_delay_s:
            time.sleep(self.step_delay_s)
        lasts = [kv.last() for kv in kvs]
        if any(flags & 1 for _h, _t, flags in lasts):
            raise ValueError("poisoned row in decode batch")
        out = np.zeros(len(kvs), dtype=np.int32)
        for i, (kv, (h, _t, flags)) in enumerate(zip(kvs, lasts)):
            h = (int(h) * _MULT + _INC) & 0xFFFFFFFFFFFFFFFF
            tok = int((h >> 16) % self.vocab)
            kv.append(h, tok, flags)
            out[i] = tok
        return out

    # -- internals ------------------------------------------------------------

    def _advance(self, states: np.ndarray
                 ) -> Tuple[np.ndarray, np.ndarray]:
        out = states.copy()
        h = out[:, 0].astype(np.uint64)
        h = (h * np.uint64(_MULT) + np.uint64(_INC))  # wraps mod 2^64
        out[:, 0] = h
        tokens = ((h >> np.uint64(16)) % np.uint64(self.vocab)).astype(
            np.int32)
        out[:, 1] = tokens.astype(np.uint64)
        return out, tokens


def reference_decode(prompt, n_tokens: int, vocab: int = 251,
                     eos: Optional[int] = None) -> List[int]:
    """The exact token stream :class:`ToyDecodeModel` emits for ``prompt``
    (including the prefill's first token), computed without a model
    instance — the out-of-band truth transport tests compare against.
    Stops early at ``eos`` (inclusive) when given."""
    h = 0
    for t in np.asarray(prompt, dtype=np.int64).reshape(-1).tolist():
        h = (h * _MULT + _INC + int(t)) & 0xFFFFFFFFFFFFFFFF
    out: List[int] = []
    for _ in range(n_tokens):
        h = (h * _MULT + _INC) & 0xFFFFFFFFFFFFFFFF
        tok = (h >> 16) % vocab
        out.append(int(tok))
        if eos is not None and tok == eos:
            break
    return out
