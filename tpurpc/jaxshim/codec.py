"""Tensor wire codec: jax.Array / numpy ↔ framed bytes, zero-copy on decode.

This is the serialization half of the ``grpcio-jax`` shim called for by
BASELINE.json: the reference ships tensors as opaque protobuf ``bytes`` fields
(every byte is copied at least twice — protobuf serialize + ``grpc_slice``
assembly, reference ``src/core/lib/surface/byte_buffer.cc``); we define a raw
layout a receiver can alias in place:

    [4B magic 'TPT1'][1B dtype][1B ndim][2B reserved][8B payload nbytes]
    [ndim x 8B little-endian dims][row-major payload, 64B-aligned start]

The 64-byte alignment of the payload start lets the decoded view satisfy
dlpack/XLA alignment so ``decode → jax.Array`` needs no repack; the copy ledger
(:mod:`tpurpc.tpu.ledger`) records whether a given decode aliased or copied.

Pytrees are carried as a count-prefixed concatenation of tensor records plus a
JSON treedef trailer, so arbitrary ``(params, batch)`` structures ship in one
message.
"""

from __future__ import annotations

import json
import struct
import time
from typing import Any, List, Optional, Tuple

import numpy as np

from tpurpc.obs import lens as _lens
from tpurpc.obs import profiler as _profiler

# tpurpc-lens (ISSUE 8) waterfall hops on the codec boundary: `device` is
# the serialize leg (device/host tensor bytes gathered into wire form),
# `decode` the parse back, `jax_array` the final materialization. One bump
# set per tensor record / tree record — never per byte.
_LENS_DEV_BYTES, _LENS_DEV_NS, _LENS_DEV_COPY = _lens.hop_counters("device")
_LENS_DEC_BYTES, _LENS_DEC_NS, _LENS_DEC_COPY = _lens.hop_counters("decode")
_LENS_JAX_BYTES, _LENS_JAX_NS, _LENS_JAX_COPY = _lens.hop_counters(
    "jax_array")

_LENS_STAGES = {
    "encode_tensor": "codec",
    "encode_tree": "codec",
    "decode_tensor": "codec",
    "decode_tree_at": "codec",
    "decode_tree_many": "codec",
    "to_jax": "device-dispatch",
}
_profiler.register_stages(__file__, _LENS_STAGES)

try:  # bfloat16 et al. — baked into the image alongside jax
    import ml_dtypes

    _BFLOAT16 = np.dtype(ml_dtypes.bfloat16)
    _FP8_E4M3 = np.dtype(ml_dtypes.float8_e4m3fn)
    _FP8_E5M2 = np.dtype(ml_dtypes.float8_e5m2)
except ImportError:  # pragma: no cover - ml_dtypes ships with jax
    _BFLOAT16 = _FP8_E4M3 = _FP8_E5M2 = None

MAGIC = b"TPT1"
_ALIGN = 64

# dtype code table. Codes are wire ABI — append only, never renumber.
_DTYPES: List[Tuple[int, "np.dtype | None"]] = [
    (0, np.dtype(np.float32)),
    (1, np.dtype(np.float64)),
    (2, np.dtype(np.int8)),
    (3, np.dtype(np.int16)),
    (4, np.dtype(np.int32)),
    (5, np.dtype(np.int64)),
    (6, np.dtype(np.uint8)),
    (7, np.dtype(np.uint16)),
    (8, np.dtype(np.uint32)),
    (9, np.dtype(np.uint64)),
    (10, np.dtype(np.float16)),
    (11, _BFLOAT16),
    (12, np.dtype(np.bool_)),
    (13, np.dtype(np.complex64)),
    (14, np.dtype(np.complex128)),
    (15, _FP8_E4M3),
    (16, _FP8_E5M2),
]
_CODE_TO_DTYPE = {c: d for c, d in _DTYPES if d is not None}
_DTYPE_TO_CODE = {d: c for c, d in _DTYPES if d is not None}

_HDR = struct.Struct("<4sBBHQ")  # magic, dtype code, ndim, reserved, nbytes


class CodecError(ValueError):
    pass


def dtype_code(dt) -> int:
    dt = np.dtype(dt)
    try:
        return _DTYPE_TO_CODE[dt]
    except KeyError:
        raise CodecError(f"unsupported wire dtype {dt}") from None


def _as_numpy(x) -> np.ndarray:
    """Materialize x host-side without gratuitous copies.

    jax.Array → np.asarray uses the dlpack/buffer protocol: zero-copy when the
    array is already in host memory (CPU backend), one device→host DMA when on
    TPU (unavoidable until the HBM send ring lands, tpurpc/tpu/).
    """
    if isinstance(x, np.ndarray):
        return np.ascontiguousarray(x)
    return np.ascontiguousarray(np.asarray(x))


def encode_tensor(x) -> List[bytes]:
    """Encode one array as a gather list: [header+dims+pad, payload_view].

    Returns buffer segments rather than one joined blob so the endpoint layer
    can scatter-gather them into the ring without an intermediate copy
    (reference: ``PairPollable::Send`` builds one doorbell from a grpc_slice*
    gather list, ``ibverbs/pair.cc:645-734``).
    """
    t0 = time.monotonic_ns()
    arr = _as_numpy(x)
    # contiguity copies are provable for ndarray inputs (ascontiguousarray
    # returns the same object when it aliased); a jax input's d2h gather is
    # the ledger's jurisdiction, not double-counted here
    materialized = isinstance(x, np.ndarray) and arr is not x
    code = dtype_code(arr.dtype)
    dims = struct.pack(f"<{arr.ndim}q", *arr.shape) if arr.ndim else b""
    head = _HDR.pack(MAGIC, code, arr.ndim, 0, arr.nbytes) + dims
    pad = (-len(head)) % _ALIGN
    head += b"\x00" * pad
    payload = arr.reshape(-1).view(np.uint8).data  # memoryview, no copy
    dt = time.monotonic_ns() - t0
    nbytes = arr.nbytes
    _LENS_DEV_NS.inc(dt)
    _LENS_DEV_BYTES.inc(nbytes)
    if materialized:
        _LENS_DEV_COPY.inc(nbytes)
    return [head, payload]


def encode_tensor_descriptor(x) -> Tuple[bytes, memoryview]:
    """Descriptor-only encode for rendezvous'd tensors (tpurpc-express,
    ISSUE 9): returns ``(descriptor, payload_view)`` where the descriptor
    is the header+dims+pad bytes the framed control path carries, and the
    payload view ALIASES the array's memory (or its d2h landing buffer) —
    the bytes the one-sided rendezvous write places directly into the
    peer's landing region. :func:`decode_tensor_external` is the inverse,
    grafting the externally-landed payload back under the descriptor with
    zero copies."""
    head, payload = encode_tensor(x)
    return bytes(head), memoryview(payload).cast("B")


def decode_tensor_external(desc, payload) -> np.ndarray:
    """Rebuild a tensor from a descriptor (control path) and its
    externally-delivered payload (the rendezvous landing region). The
    returned array is a zero-copy view over ``payload`` — with a
    64B-aligned landing region (the pool guarantees it), ``to_jax``
    dlpack-aliases it onward with no movement."""
    view = memoryview(desc)
    if len(view) < _HDR.size:
        raise CodecError("short tensor descriptor")
    magic, code, ndim, _, nbytes = _HDR.unpack_from(view, 0)
    if magic != MAGIC:
        raise CodecError(f"bad tensor magic {magic!r}")
    try:
        dt = _CODE_TO_DTYPE[code]
    except KeyError:
        raise CodecError(f"unknown dtype code {code}") from None
    if len(view) < _HDR.size + 8 * ndim:
        raise CodecError("short tensor descriptor dims")
    shape = struct.unpack_from(f"<{ndim}q", view, _HDR.size) if ndim else ()
    pv = memoryview(payload).cast("B")
    if len(pv) < nbytes:
        raise CodecError(f"external payload short: want {nbytes}, "
                         f"have {len(pv)}")
    expect = (int(np.prod(shape, dtype=np.int64)) * dt.itemsize
              if ndim else dt.itemsize)
    if expect != nbytes:
        raise CodecError(f"shape/nbytes mismatch: {shape} x {dt} "
                         f"!= {nbytes}")
    flat = np.frombuffer(pv, dtype=np.uint8, count=nbytes)
    return flat.view(dt).reshape(shape)


def encode_tensor_bytes(x) -> bytes:
    # materializing convenience API (tests/interop): accumulate, don't join
    out = bytearray()
    for s in encode_tensor(x):
        out += s
    return bytes(out)


def decode_tensor(buf, offset: int = 0, copy: bool = False) -> Tuple[np.ndarray, int]:
    """Decode one tensor record from ``buf`` at ``offset``.

    Returns ``(array, next_offset)``. With ``copy=False`` the array is a
    zero-copy view aliasing ``buf`` (the ledger's "host-memcpy bytes = 0"
    receive path); the caller owns keeping ``buf`` alive.
    """
    view = memoryview(buf)
    if len(view) - offset < _HDR.size:
        raise CodecError("short tensor header")
    magic, code, ndim, _, nbytes = _HDR.unpack_from(view, offset)
    if magic != MAGIC:
        raise CodecError(f"bad tensor magic {magic!r}")
    try:
        dt = _CODE_TO_DTYPE[code]
    except KeyError:
        raise CodecError(f"unknown dtype code {code}") from None
    pos = offset + _HDR.size
    if len(view) - pos < 8 * ndim:
        raise CodecError("short tensor dims")
    shape = struct.unpack_from(f"<{ndim}q", view, pos) if ndim else ()
    pos += 8 * ndim
    pos += (-(pos - offset)) % _ALIGN
    if len(view) - pos < nbytes:
        raise CodecError(f"short tensor payload: want {nbytes}, have {len(view) - pos}")
    expect = int(np.prod(shape, dtype=np.int64)) * dt.itemsize if ndim else dt.itemsize
    if expect != nbytes:
        raise CodecError(f"shape/nbytes mismatch: {shape} x {dt} != {nbytes}")
    flat = np.frombuffer(view, dtype=np.uint8, count=nbytes, offset=pos)
    arr = flat.view(dt).reshape(shape)
    if copy:
        arr = arr.copy()
    return arr, pos + nbytes


def to_jax(arr: np.ndarray):
    """Host view → jax.Array.

    On the CPU backend dlpack import aliases the numpy buffer (zero copy); on
    TPU this is the one host→HBM DMA of the receive path. The HBM-resident
    ring (tpurpc/tpu/hbm_ring.py) removes even that for the north-star path.
    """
    import jax

    from tpurpc.tpu import ledger

    t0 = time.monotonic_ns()
    nbytes = arr.nbytes
    try:
        if not arr.flags.writeable:
            # jax dlpack import refuses read-only buffers; device_put
            # instead (still a single copy onto device / into the arena).
            ledger.dma_h2d(nbytes)
            _LENS_JAX_COPY.inc(nbytes)
            return jax.device_put(arr)
        try:
            out = jax.dlpack.from_dlpack(arr)
            ledger.zero_copy(nbytes)
            return out
        except (TypeError, RuntimeError, ValueError):
            ledger.dma_h2d(nbytes)
            _LENS_JAX_COPY.inc(nbytes)
            return jax.device_put(arr)
    finally:
        dt = time.monotonic_ns() - t0
        _LENS_JAX_NS.inc(dt)
        _LENS_JAX_BYTES.inc(nbytes)


# ---------------------------------------------------------------------------
# Pytrees: N tensor records + JSON treedef trailer
# ---------------------------------------------------------------------------

_TREE = struct.Struct("<4sIQ")  # magic 'TPTR', n_leaves, trailer nbytes
TREE_MAGIC = b"TPTR"


def encode_tree(tree: Any) -> List[bytes]:
    """Encode an arbitrary pytree of arrays as a gather list."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    trailer = json.dumps(_treedef_to_json(treedef)).encode()
    segs: List[bytes] = [_TREE.pack(TREE_MAGIC, len(leaves), len(trailer))]
    pad = (-_TREE.size) % _ALIGN
    if pad:
        segs.append(b"\x00" * pad)
    for leaf in leaves:
        segs.extend(encode_tensor(leaf))
        tail = segs[-1]
        rem = (-len(tail)) % _ALIGN
        if rem:
            segs.append(b"\x00" * rem)
    segs.append(trailer)
    return segs


def encode_tree_bytes(tree: Any) -> bytes:
    # materializing convenience API (tests/interop): accumulate, don't join
    out = bytearray()
    for s in encode_tree(tree):
        out += s
    return bytes(out)


def decode_tree(buf, copy: bool = False, as_jax: bool = False,
                offset: int = 0) -> Any:
    tree, _ = decode_tree_at(buf, offset, copy=copy, as_jax=as_jax)
    return tree


def decode_tree_at(buf, offset: int = 0, copy: bool = False,
                   as_jax: bool = False) -> Tuple[Any, int]:
    """Decode one tree record at ``offset``; returns ``(tree, next_offset)``.

    All alignment arithmetic is RELATIVE to the record start, so records
    decode identically at any position — the walk primitive behind
    :func:`decode_tree_many`'s batched fast path over a contiguous drained
    buffer (memoryview offsets all the way down, no intermediate ``bytes``
    slices of the payload).
    """
    import jax

    t0 = time.monotonic_ns()
    view = memoryview(buf)
    if len(view) - offset < _TREE.size:
        raise CodecError("short tree header")
    magic, n, trailer_len = _TREE.unpack_from(view, offset)
    if magic != TREE_MAGIC:
        raise CodecError(f"bad tree magic {magic!r}")
    pos = offset + _TREE.size + ((-_TREE.size) % _ALIGN)
    leaves = []
    payload = 0
    for _ in range(n):
        arr, pos = decode_tensor(view, pos, copy=copy)
        pos += (-(pos - offset)) % _ALIGN
        payload += arr.nbytes
        leaves.append(to_jax(arr) if as_jax else arr)
    # Trailer sits at the decode cursor — never measure from the buffer end;
    # zero-copy receive windows may carry ring-alignment slack behind it.
    if len(view) - pos < trailer_len:
        raise CodecError("short tree trailer")
    trailer = view[pos:pos + trailer_len].tobytes()
    treedef = _treedef_from_json(json.loads(trailer.decode()))
    out = jax.tree_util.tree_unflatten(treedef, leaves), pos + trailer_len
    # tpurpc-lens `decode` hop: one bump set per tree record (to_jax's
    # share is also visible on its own jax_array row — hops may nest)
    dt = time.monotonic_ns() - t0
    _LENS_DEC_NS.inc(dt)
    _LENS_DEC_BYTES.inc(payload)
    if copy:
        _LENS_DEC_COPY.inc(payload)
    return out


def decode_tree_many(buf, count: Optional[int] = None, copy: bool = False,
                     as_jax: bool = False) -> List[Any]:
    """Batched decode: walk a contiguous buffer of back-to-back tree records
    (e.g. one ring drain's worth of messages) and return every tree.

    With ``count=None`` the walk stops cleanly at the buffer end or at the
    first position that does not start a record (zero-copy receive windows
    may carry ring-alignment slack behind the last record); a ``count``
    makes truncation an error instead. The buffer is sliced by memoryview
    offsets only — one decode pass, no per-record ``bytes`` copies.
    """
    view = memoryview(buf)
    out: List[Any] = []
    pos = 0
    while count is None or len(out) < count:
        if len(view) - pos < _TREE.size:
            if count is not None:
                raise CodecError(
                    f"short batch: {len(out)} of {count} tree records")
            break
        if view[pos:pos + 4].tobytes() != TREE_MAGIC:  # 4-byte peek
            if count is not None:
                raise CodecError(f"bad tree magic at batch offset {pos}")
            break
        tree, pos = decode_tree_at(view, pos, copy=copy, as_jax=as_jax)
        out.append(tree)
    return out


class _LeafSentinel:
    """Marks leaf positions in the treedef skeleton; distinct from a literal
    ``None`` node so trees carrying optional/None entries round-trip."""


_SENTINEL = _LeafSentinel()


def _treedef_to_json(treedef) -> Any:
    import jax

    skeleton = jax.tree_util.tree_unflatten(
        treedef, [_SENTINEL] * treedef.num_leaves)
    return _skel_to_json(skeleton)


_LEAF = {"__leaf__": 1}
_NONE = {"__none__": 1}


def _key_to_json(k) -> Any:
    if isinstance(k, str):
        return {"t": "s", "v": k}
    if isinstance(k, bool):  # before int: bool is an int subclass
        return {"t": "b", "v": k}
    if isinstance(k, int):
        return {"t": "i", "v": k}
    raise CodecError(f"unsupported dict key {k!r} (str/int/bool only)")


def _key_from_json(j) -> Any:
    return {"s": str, "b": bool, "i": int}[j["t"]](j["v"])


def _skel_to_json(s) -> Any:
    if s is _SENTINEL:
        return _LEAF
    if s is None:
        return _NONE
    if isinstance(s, (list, tuple)):
        return {"__seq__": "list" if isinstance(s, list) else "tuple",
                "items": [_skel_to_json(v) for v in s]}
    if isinstance(s, dict):
        return {"__dict__": [[_key_to_json(k), _skel_to_json(v)]
                             for k, v in s.items()]}
    raise CodecError(f"unsupported pytree node {type(s)!r}")


def _json_to_skel(j) -> Any:
    if j == _LEAF:
        return _SENTINEL
    if j == _NONE:
        return None
    if "__seq__" in j:
        items = [_json_to_skel(v) for v in j["items"]]
        return items if j["__seq__"] == "list" else tuple(items)
    if "__dict__" in j:
        return {_key_from_json(k): _json_to_skel(v) for k, v in j["__dict__"]}
    raise CodecError(f"bad treedef json {j!r}")


def _treedef_from_json(j) -> Any:
    import jax

    skeleton = _json_to_skel(j)
    return jax.tree_util.tree_structure(
        skeleton, is_leaf=lambda x: x is _SENTINEL)


# Serializer/Deserializer adapters for the rpc layer.
# Serializers return GATHER LISTS — the frame writer scatter-writes the
# segments (ring slice-gather / sendmsg) so the tensor payload is never
# joined into an intermediate host buffer.

def tensor_serializer(x) -> List[bytes]:
    return encode_tensor(x)


def tensor_deserializer(buf) -> np.ndarray:
    t0 = time.monotonic_ns()
    arr, _ = decode_tensor(buf)
    dt = time.monotonic_ns() - t0
    nbytes = arr.nbytes
    _LENS_DEC_NS.inc(dt)
    _LENS_DEC_BYTES.inc(nbytes)
    return arr


def tree_serializer(tree) -> List[bytes]:
    return encode_tree(tree)


def tree_deserializer(buf) -> Any:
    return decode_tree(buf)


def raw_view(buf):
    """Identity deserializer that opts INTO receiving the assembly view
    (``alias_ok``): device-mode tensor handlers decode it themselves."""
    return buf


# These decode zero-copy over the received assembly view; the rpc layer hands
# them the memoryview as-is instead of materializing grpcio-style bytes
# (tpurpc.rpc.status.deserialize).
tensor_deserializer.alias_ok = True
tree_deserializer.alias_ok = True
raw_view.alias_ok = True
