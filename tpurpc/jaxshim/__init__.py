"""grpcio-jax shim: jax.Array in/out over tpurpc (BASELINE.json north star).

* :mod:`tpurpc.jaxshim.codec` — tensor/pytree wire format, zero-copy decode.
* :mod:`tpurpc.jaxshim.service` — tensor services, fan-in batching, serve_jax.
* :mod:`tpurpc.jaxshim.generate` — the step-model contract tpurpc-cadence
  schedules (prefill/step with a leading batch axis), plus the toy
  reference model.
"""

from tpurpc.jaxshim.codec import (decode_tensor, decode_tree, encode_tensor,
                                  encode_tensor_bytes, encode_tree,
                                  encode_tree_bytes, tensor_deserializer,
                                  tensor_serializer, to_jax,
                                  tree_deserializer, tree_serializer)
from tpurpc.jaxshim.generate import ToyDecodeModel, reference_decode
from tpurpc.jaxshim.service import (DeviceMerger, FanInBatcher, ShardedFanIn,
                                    TensorClient, add_tensor_method,
                                    serve_jax, serve_jax_sharded)

__all__ = [
    "decode_tensor", "decode_tree", "encode_tensor", "encode_tensor_bytes",
    "encode_tree", "encode_tree_bytes", "tensor_deserializer",
    "tensor_serializer", "to_jax", "tree_deserializer", "tree_serializer",
    "FanInBatcher", "ShardedFanIn", "DeviceMerger", "TensorClient",
    "add_tensor_method", "serve_jax", "serve_jax_sharded",
    "ToyDecodeModel", "reference_decode",
]
