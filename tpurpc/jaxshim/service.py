"""Tensor services: serve jitted JAX callables over tpurpc.

The ``grpcio-jax`` surface from BASELINE.json:

* :func:`add_tensor_method` / :class:`TensorClient` — unary and
  server-streaming tensor RPCs (config #3: server-streaming
  ``float32[1024,1024]`` → ``jax.Array``).
* :class:`FanInBatcher` — cross-connection request batching (config #4:
  8-client fan-in → 1 TPU server): requests landing on independent
  connections are stacked into one leading batch axis and dispatched as a
  single jitted call, amortizing kernel launch + keeping the MXU fed.

The reference has no equivalent — its apps are byte-oriented greeters
(``examples/cpp/helloworld.benchmark``); batching here is the TPU-first
replacement for "more pollers": one big matmul beats eight small ones.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Iterator, List, Optional, Sequence

import numpy as np

from tpurpc.jaxshim import codec
from tpurpc.obs import flight as _flight
from tpurpc.obs import metrics as _metrics
from tpurpc.obs import profiler as _profiler
from tpurpc.obs import tracing as _tracing
from tpurpc.rpc.server import (Server, stream_stream_rpc_method_handler,
                               unary_stream_rpc_method_handler,
                               unary_unary_rpc_method_handler)
from tpurpc.utils.trace import TraceFlag

trace_jax = TraceFlag("jaxshim")

# tpurpc-lens (ISSUE 8) sampling-profiler frame markers: batching control
# flow is `batcher`, running a gathered batch on the model/device (and the
# cross-shard merged dispatch) is `device-dispatch`
_LENS_STAGES = {
    "_loop": "batcher",
    "_split_compatible": "batcher",
    "_concat_pad": "batcher",
    "_complete_loop": "batcher",
    "_run": "device-dispatch",
    "_merge_loop": "batcher",
    "_dispatch_group": "device-dispatch",
    "_run_one": "device-dispatch",
}
_profiler.register_stages(__file__, _LENS_STAGES)

# tpurpc-scope (ISSUE 4): fan-in batching observability. One histogram
# record + one counter bump per DISPATCHED BATCH (amortized by design);
# the flush-reason counters say WHY batches went out — a serving stack
# stuck on "timer" is leaving latency on the table, one stuck on
# "drained" with tiny batches is the batch-of-one fixed point ISSUE 3
# fought (see FanInBatcher._drained_inflight).
_FANIN_BATCH = _metrics.histogram("fanin_batch")
_BATCHER_BATCHES = _metrics.counter("batcher_batches")
_BATCHER_ROWS = _metrics.counter("batcher_rows")
_FLUSH_REASONS = {
    reason: _metrics.counter(f"batcher_flush_{reason}")
    for reason in ("size", "timer", "drained", "close")
}
#: tpurpc-blackbox (ISSUE 5): live batcher queue depth at sweep/scrape
#: time — the watchdog's "batcher-wait" stage evidence
_BATCHER_DEPTH = _metrics.fleet("batcher_queue_depth",
                                lambda b: len(b._queue))

TENSOR_SERVICE = "tpurpc.Tensor"


def _method_path(name: str) -> str:
    return f"/{TENSOR_SERVICE}/{name}"


def _device_decoder(ctx):
    """Per-call request decoder: device-ring placement when the transport is
    the TPU platform, host-aliasing decode otherwise.

    Returns ``(decode(buf) -> tree, finish())``. Credit discipline: each
    ``decode`` releases the PREVIOUS message's leases (the handler advancing
    the request iterator means it is done with that message — the rolling
    analog of the host ring's drain-then-credit, ``pair.cc:276-284``), and
    ``finish`` releases the last message's when the handler returns
    (SURVEY §7 hard-part #4: leases gate the ring's credit return)."""
    ring = getattr(ctx, "device_ring", None)
    if ring is None:
        return codec.tree_deserializer, lambda: None
    from tpurpc.tpu.endpoint import decode_tree_to_ring

    held = []

    def decode(buf):
        for lease in held:
            lease.release()
        held.clear()
        tree, leases = decode_tree_to_ring(ring, buf)
        held.extend(leases)
        return tree

    def finish():
        for lease in held:
            lease.release()
        held.clear()

    return decode, finish


def add_tensor_method(server: Server, name: str,
                      fn: Callable[..., Any],
                      kind: str = "unary_unary",
                      device: bool = False) -> None:
    """Register ``fn(tree) -> tree`` as a tensor-typed method.

    ``fn`` receives the decoded request pytree (numpy views over the receive
    buffer; pass through :func:`tpurpc.jaxshim.codec.to_jax` or let jit trace
    them — jax treats numpy zero-copy on CPU backends). Its return pytree is
    encoded the same way.

    With ``device=True`` and the TPU platform
    (``GRPC_PLATFORM_TYPE=TPU``), request payloads are placed into the
    connection's HBM receive ring and ``fn`` gets lease-backed device arrays;
    the leases (ring credit) are released when ``fn`` returns. On other
    platforms ``device=True`` degrades to the host-aliasing decode.
    """
    if not device:
        if kind == "unary_unary":
            def behavior(req, ctx):
                return fn(req)
            handler = unary_unary_rpc_method_handler(
                behavior, codec.tree_deserializer, codec.tree_serializer)
        elif kind == "unary_stream":
            def behavior(req, ctx):
                yield from fn(req)
            handler = unary_stream_rpc_method_handler(
                behavior, codec.tree_deserializer, codec.tree_serializer)
        elif kind == "stream_stream":
            def behavior(req_iter, ctx):
                yield from fn(req_iter)
            handler = stream_stream_rpc_method_handler(
                behavior, codec.tree_deserializer, codec.tree_serializer)
        else:
            raise ValueError(f"unsupported tensor method kind {kind}")
        server.add_method(_method_path(name), handler)
        return

    # device mode: identity deserializer (raw message bytes reach the
    # behavior), decode inside where ctx exposes the connection's ring.
    # Responses are serialized INSIDE the behavior, before finish():
    # round-5 ring views ALIAS ring memory (HbmRing._dlpack_view), so a
    # passthrough response (``return {"y": tree["a"]}``) read by the RPC
    # layer's serializer AFTER the lease release could see the span
    # overwritten in place by a concurrent RPC on the same connection.
    # Serialize-then-release makes the alias's whole read window sit
    # inside the lease window; the handler's serializer is identity.
    _ident = lambda b: b  # noqa: E731 — already-encoded bytes pass through
    if kind == "unary_unary":
        def behavior(raw, ctx):
            decode, finish = _device_decoder(ctx)
            try:
                return codec.tree_serializer(fn(decode(raw)))
            finally:
                finish()
        handler = unary_unary_rpc_method_handler(
            behavior, codec.raw_view, _ident)
    elif kind == "unary_stream":
        def behavior(raw, ctx):
            decode, finish = _device_decoder(ctx)
            try:
                for item in fn(decode(raw)):
                    yield codec.tree_serializer(item)
            finally:
                finish()
        handler = unary_stream_rpc_method_handler(
            behavior, codec.raw_view, _ident)
    elif kind == "stream_stream":
        def behavior(raw_iter, ctx):
            decode, finish = _device_decoder(ctx)
            try:
                for item in fn(decode(raw) for raw in raw_iter):
                    yield codec.tree_serializer(item)
            finally:
                finish()
        handler = stream_stream_rpc_method_handler(
            behavior, codec.raw_view, _ident)
    else:
        raise ValueError(f"unsupported tensor method kind {kind}")
    server.add_method(_method_path(name), handler)


class TensorClient:
    """Client for tensor methods; wraps a :class:`tpurpc.rpc.channel.Channel`
    (or a :class:`tpurpc.rpc.native_client.NativeChannel` for ``call`` /
    ``call_async``).

    ``depth`` bounds the per-method in-flight window ``call_async`` uses —
    the serving pipeline's client half (ISSUE 3): one connection sustains
    ``depth`` outstanding unary calls, demuxed by stream id, which is what
    lets the server's :class:`FanInBatcher` see real batches instead of a
    lockstep of ones."""

    def __init__(self, channel, depth: int = 16):
        self._channel = channel
        self.depth = max(1, depth)
        self._pipelines: dict = {}
        self._pl_lock = threading.Lock()

    def call(self, name: str, tree: Any, timeout: Optional[float] = None) -> Any:
        mc = self._channel.unary_unary(
            _method_path(name), codec.tree_serializer, codec.tree_deserializer)
        return mc(tree, timeout=timeout)

    def pipeline(self, name: str, depth: Optional[int] = None):
        """A bounded multi-in-flight caller for ``name``: an object with
        ``call_async(tree, timeout=None) -> Future``. Works on both the
        Python channel (``Channel.unary_unary(...).pipeline()``) and the
        native channel (CQ futures / inline window)."""
        depth = self.depth if depth is None else max(1, depth)
        mc = self._channel.unary_unary(
            _method_path(name), codec.tree_serializer, codec.tree_deserializer)
        pl = getattr(mc, "pipeline", None)
        if pl is not None:  # Python channel: stream-id-demuxed window
            return pl(depth)
        # NativeChannel: its .future() is already pipelined (CQ on
        # reader-thread channels, bounded worker window on inline-read);
        # wrap it behind the same bounded-window surface.
        return _NativePipeline(mc.future, depth)

    def call_async(self, name: str, tree: Any,
                   timeout: Optional[float] = None):
        """Pipelined unary call: returns a Future of the response tree.
        At most ``depth`` calls per method are in flight; the next
        ``call_async`` blocks until a slot frees (window backpressure)."""
        with self._pl_lock:
            pl = self._pipelines.get(name)
            if pl is None:
                pl = self._pipelines[name] = self.pipeline(name)
        return pl.call_async(tree, timeout=timeout)

    def call_device(self, name: str, tree: Any,
                    timeout: Optional[float] = None):
        """Unary call whose RESPONSE decodes into the channel's device ring.

        Returns a :class:`tpurpc.tpu.endpoint.DeviceMessage` — use it as a
        context manager (or call ``.release()``) so the ring credit returns.
        Falls back to a plain host decode (still wrapped in DeviceMessage,
        with no leases) when the channel's transport isn't the TPU platform.
        """
        from tpurpc.tpu.endpoint import DeviceMessage, decode_tree_to_ring

        mc = self._channel.unary_unary(
            _method_path(name), codec.tree_serializer, codec.raw_view)
        raw, call = mc.with_call(tree, timeout=timeout)
        # The call's OWN connection: an LB re-pick here could land the
        # response in a different connection's ring (or fail a finished call).
        ring = call.device_ring()
        if ring is None:
            return DeviceMessage(codec.decode_tree(raw), [])
        out, leases = decode_tree_to_ring(ring, raw)
        return DeviceMessage(out, leases)

    def stream(self, name: str, tree: Any,
               timeout: Optional[float] = None) -> Iterator[Any]:
        mc = self._channel.unary_stream(
            _method_path(name), codec.tree_serializer, codec.tree_deserializer)
        return mc(tree, timeout=timeout)

    def duplex(self, name: str, trees: Iterator[Any],
               timeout: Optional[float] = None,
               native: bool = True) -> Iterator[Any]:
        """Bidi tensor stream. ``native=True`` (default) rides the
        libtpurpc loop on eligible channels — round 5's same-weather A/B
        measured it ~40% faster on 4 MiB tensor streams (1.20 vs 0.86
        GB/s vs the Python plane; earlier rounds measured the opposite,
        which turned out to be the since-fixed notify-token-stealing bug,
        ring_transport.h wait_event). Ineligible channels (TPU device-ring
        platform, TLS, compression, multi-address) degrade to the Python
        transport automatically; pass ``native=False`` to force the
        instrumented Python plane (copy-ledger measurement runs)."""
        mc = self._channel.stream_stream(
            _method_path(name), codec.tree_serializer,
            codec.tree_deserializer, tpurpc_native=native)
        return mc(trees, timeout=timeout)


class _NativePipeline:
    """Window-bounded wrapper over a native ``.future`` — the native side
    already pipelines (CQ or inline worker window); this adds the same
    caller-facing backpressure contract PipelinedUnary has, so bench and
    serving code can treat the two planes identically."""

    def __init__(self, future_fn, depth: int):
        self._future_fn = future_fn
        self._window = threading.BoundedSemaphore(max(1, depth))

    def call_async(self, tree: Any, timeout: Optional[float] = None):
        self._window.acquire()
        try:
            fut = self._future_fn(tree, timeout=timeout)
        except BaseException:
            self._window.release()
            raise
        fut.add_done_callback(lambda _f: self._window.release())
        return fut

    def close(self) -> None:
        pass


# ---------------------------------------------------------------------------
# Fan-in batching (BASELINE config #4)
# ---------------------------------------------------------------------------

class _Pending:
    __slots__ = ("tree", "event", "result", "error", "tctx", "t_enq")

    def __init__(self, tree):
        self.tree = tree
        self.event = threading.Event()
        self.result = None
        self.error: Optional[Exception] = None
        #: tpurpc-scope: the calling RPC's trace context (captured from the
        #: handler thread's ambient) + enqueue stamp — the batcher thread
        #: turns them into "batch-wait"/"infer" spans per request
        self.tctx = _tracing.current() if _tracing.LIVE else None
        self.t_enq = time.monotonic_ns() if self.tctx is not None else 0


class FanInBatcher:
    """Stack concurrent requests from many connections into one jitted call.

    ``fn`` must accept arrays with a leading batch axis and be
    shape-polymorphic only in that axis (pad-to-bucket keeps XLA's compile
    cache small: batch is padded up to the next power of two ≤ max_batch).
    Each request contributes leading-axis rows; replies are split back out.

    Dispatch fires when ``max_batch`` rows are waiting or ``max_delay_s``
    elapsed since the first queued request — the same latency/throughput dial
    as the reference's busy-poll timeout (``GRPC_RDMA_BUSY_POLLING_TIMEOUT_US``,
    README.md:17-25), applied at the request level instead of the byte level.

    Reply delivery is a two-stage pipeline: the batcher thread only
    *dispatches* the jitted call (XLA dispatch is async — it returns as soon
    as the computation is enqueued on the device) and hands the in-flight
    batch to a completion thread, which materializes the result to host in
    ONE transfer per output leaf (``jax.device_get`` of the whole batch) and
    splits replies as numpy views. Two properties matter on real TPU hosts
    where device⇄host hops carry tens of ms of latency (the axon tunnel
    measures ~70 ms per round trip):

    * one d2h per batch, not one per request — splitting device arrays
      per-request would pay max_batch round trips;
    * batch N+1's host-side stacking and device dispatch overlap batch N's
      d2h (bounded depth, so backpressure still reaches callers);
    * ``d2h_workers`` completion threads materialize different batches
      concurrently — device→host round trips overlap almost perfectly
      (measured on the axon tunnel: 4 threads retire small transfers ~8×
      faster than 1), so a latency-bound link stops bounding batch rate.
    """

    #: lock map (lint rule `lock`) + shard contract (lint rule `shard`,
    #: tpurpc-manycore): the request queue and close flag are SHARD-LOCAL —
    #: only this batcher's own threads mutate them; cross-shard access is
    #: confined to the device merger's declared ``_MERGE_BOUNDARY``
    _GUARDED_BY = {"_queue": "_lock", "_closed": "_lock"}

    def __init__(self, fn: Callable[[Any], Any], max_batch: int = 8,
                 max_delay_s: float = 0.002, pad_to_bucket: bool = True,
                 fixed_bucket: bool = False, d2h_workers: int = 4,
                 transfer_dtype=None,
                 inflight_fn: Optional[Callable[[], int]] = None):
        #: depth-aware flush (ISSUE 3): a callable reporting how many
        #: requests are currently in flight at the transport (arrived or
        #: being read, response not yet finished — Server.inflight_requests).
        #: When every in-flight request is already queued here, no further
        #: arrival can happen until responses go out, so waiting out
        #: max_delay_s is pure latency: flush now. None = timer/size only.
        self._inflight_fn = inflight_fn
        from collections import deque

        #: recent dispatched batch sizes — the depth-aware flush's
        #: hysteresis floor is their max, so one small ramp-up batch can't
        #: drag the floor down while the occupancy the server recently
        #: proved it can fill keeps premature flushes suppressed
        self._recent_batches: "deque[int]" = deque(maxlen=8)
        #: cast host-side batches to this dtype before the h2d (e.g.
        #: ``jnp.bfloat16`` when the model computes in bf16 anyway): the
        #: transfer is usually the serving bottleneck and this halves it.
        #: None = ship requests in their wire dtype.
        self.transfer_dtype = transfer_dtype
        self._fn = fn
        self.max_batch = max_batch
        self.max_delay_s = max_delay_s
        self.pad_to_bucket = pad_to_bucket
        #: always pad to max_batch: ONE compiled shape for single-row
        #: requests, the right trade on accelerators where each new batch
        #: shape recompiles (XLA static shapes) — wasted pad rows cost far
        #: less than a mid-serving compile stall. NOTE: a dispatch whose
        #: requests total MORE than max_batch rows (multi-row requests) still
        #: pads to that larger total and compiles its shape; the one-shape
        #: guarantee assumes ≤1 row per request or callers sizing max_batch
        #: to the true row bound.
        self.fixed_bucket = fixed_bucket
        self._lock = threading.Lock()
        self._queue: List[_Pending] = []
        self._kick = threading.Condition(self._lock)
        self._closed = False
        self.batches_run = 0
        self.rows_run = 0
        import queue as _queue

        #: in-flight (dispatched, not yet materialized) batches; the bound is
        #: the pipeline depth — blocking put() backpressures the batcher
        #: thread, and through it the callers, when the device falls behind
        self._inflight: "_queue.Queue" = _queue.Queue(maxsize=max(2, d2h_workers))
        self._reaped = False  # set by close() after the workers are gone
        _BATCHER_DEPTH.track(self)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="tpurpc-batcher")
        self._completers = [
            threading.Thread(target=self._complete_loop, daemon=True,
                             name=f"tpurpc-batcher-d2h-{i}")
            for i in range(max(1, d2h_workers))]
        self._thread.start()
        for c in self._completers:
            c.start()

    def queue_depth(self) -> int:
        """Requests parked behind the transport (queued here + dispatched
        batches not yet materialized) — the tpurpc-fleet load report's
        queue-depth field (Server.set_load_provider wiring in serve_jax):
        on a model server THIS is where overload actually accumulates."""
        with self._lock:
            queued = len(self._queue)
        return queued + self._inflight.qsize()

    def close(self) -> None:
        import queue as _queue

        with self._lock:
            self._closed = True
            self._kick.notify_all()
        self._thread.join(timeout=5)
        for _ in self._completers:   # one sentinel per completion worker,
            try:                      # after the last dispatched batch.
                # Generous timeout: a merely-backlogged (healthy) queue
                # drains and takes the sentinel; only a truly wedged
                # consumer set makes us give up so close() stays bounded.
                self._inflight.put(None, timeout=10)
            except _queue.Full:
                break
        for c in self._completers:
            c.join(timeout=5)
        if any(c.is_alive() for c in self._completers):
            # Workers are wedged in device work (unrecoverable device
            # stall) but still hold the queue's consumer role — if they
            # ever unwedge they will drain remaining batches, so failing
            # those batches now would be both premature and racy. Leave
            # the daemon threads to their fate.
            return
        self._reaped = True  # a still-blocked dispatch put now fails its batch
        # Shutdown race sweep: if the batcher thread outlived its join
        # timeout its final batch can land after the workers exited on
        # sentinels — fail those callers instead of stranding them on
        # p.event forever. (A put racing this sweep is covered by the
        # _reaped check in the dispatch loop: either the sweep sees the
        # item, or the put times out and fails the batch itself.)
        while True:
            try:
                item = self._inflight.get_nowait()
            except _queue.Empty:
                break
            if item is None:
                continue
            batch = item[0]
            for p in batch:
                p.error = RuntimeError("batcher closed")
                p.event.set()

    def __call__(self, tree: Any) -> Any:
        p = _Pending(tree)
        with self._lock:
            if self._closed:
                raise RuntimeError("batcher closed")
            self._queue.append(p)
            self._kick.notify_all()
        p.event.wait()
        if p.error is not None:
            raise p.error
        return p.result

    # -- batcher thread ------------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._lock:
                while not self._queue and not self._closed:
                    self._kick.wait()
                if self._closed and not self._queue:
                    return
                deadline = time.monotonic() + self.max_delay_s
                reason = None
                while (len(self._queue) < self.max_batch and not self._closed):
                    if self._drained_inflight():
                        reason = "drained"  # nobody else is coming
                        break
                    left = deadline - time.monotonic()
                    if left <= 0:
                        reason = "timer"
                        break
                    self._kick.wait(timeout=left)
                if reason is None:
                    reason = ("size" if len(self._queue) >= self.max_batch
                              else "close")
                batch, self._queue = (self._queue[:self.max_batch],
                                      self._queue[self.max_batch:])
                if batch:
                    self._recent_batches.append(len(batch))
            if batch:
                _FLUSH_REASONS[reason].inc()
                _FANIN_BATCH.record(len(batch))
                # flight: one event per DISPATCHED batch — the flush
                # decision (reason + size) a latency postmortem replays
                _flight.emit(_flight.BATCH_FLUSH, 0,
                             _flight.FLUSH_REASON_CODE[reason], len(batch))
                self._run(batch)

    def _drained_inflight(self) -> bool:
        """True when the transport says every arrived-and-unanswered
        request is already in our queue — the depth-aware flush signal
        (runs under self._lock via the _loop wait).

        Hysteresis: the early flush also requires the queue to have
        reached the max RECENT batch size. "Every in-flight request is
        queued" is trivially true in the stagger gap of a closed-loop
        client set (responses written, next requests still on the wire) —
        flushing there degenerates to batches of one (measured: 5× QPS
        collapse under fixed_bucket, which pads every dispatch to
        max_batch). Demanding recently-proven occupancy first keeps
        steady-state batching intact; the max over a sliding window (not
        just the last batch) means one small ramp-up batch can't drag the
        floor into the sticky batch-of-one fixed point, while a genuinely
        quiet batcher decays to immediate flushes within a window."""
        if self._inflight_fn is None or not self._queue:
            return False
        try:
            pending = self._inflight_fn()
        except Exception:
            return False  # a broken probe degrades to the timer, never hangs
        q = len(self._queue)
        floor = min(self.max_batch, max(self._recent_batches, default=1))
        return q >= max(1, pending) and q >= floor

    def _split_compatible(self, batch: List[_Pending]) -> List[_Pending]:
        """Fail (individually) requests whose pytree structure or leaf
        row-shape/dtype can't stack with the batch's first valid row —
        one bad request must not poison its siblings' futures."""
        import jax

        good: List[_Pending] = []
        ref = None
        for p in batch:
            err: Optional[Exception] = None
            sig = None
            try:
                leaves, td = jax.tree_util.tree_flatten(p.tree)
                if not leaves:
                    raise ValueError("empty request tree")
                for x in leaves:
                    if np.ndim(x) < 1:
                        raise ValueError(
                            "batched request leaves need a leading batch axis")
                sig = (td, tuple((np.shape(x)[1:], np.dtype(
                    getattr(x, "dtype", None) or np.asarray(x).dtype))
                    for x in leaves))
            except Exception as exc:
                err = exc
            if err is None:
                if ref is None or sig == ref:
                    ref = ref or sig
                    good.append(p)
                    continue
                err = ValueError(
                    "request incompatible with batch: leaf shapes/dtypes "
                    f"{sig[1]} vs {ref[1]} (or differing tree structure)")
            p.error = err
            p.event.set()
        return good

    def _bucket(self, n: int) -> int:
        if self.fixed_bucket:
            return self.max_batch
        if not self.pad_to_bucket:
            return n
        b = 1
        while b < n:
            b <<= 1
        return min(b, self.max_batch)

    def _run(self, batch: List[_Pending]) -> None:
        """Stage 1 (batcher thread): stack, pad, dispatch, enqueue in-flight.

        Does NOT wait for the device: ``self._fn`` on a jitted function
        returns after async dispatch, and materialization happens on the
        completion thread so the next batch's stacking overlaps this batch's
        device time + d2h."""
        import jax

        batch = self._split_compatible(batch)
        if not batch:
            return
        t_disp = time.monotonic_ns()
        for p in batch:
            if p.tctx is not None:
                # enqueue → dispatch: the per-request "batch-wait" span
                _tracing.record("batch-wait", p.tctx, p.t_enq,
                                t_disp - p.t_enq)
        try:
            rows = [p.tree for p in batch]
            sizes = [jax.tree_util.tree_leaves(t)[0].shape[0] for t in rows]
            total = sum(sizes)
            bucket = max(self._bucket(total), total)
            stacked = jax.tree_util.tree_map(
                lambda *xs: self._concat_pad(xs, bucket), *rows)
            out = self._fn(stacked)
            # Start the d2h NOW (enqueued behind the compute, overlapping
            # everything after it): on links with high readback latency
            # (axon tunnel: np.asarray ~170 ms vs ~16 ms when the async
            # host copy was issued ahead) this is the difference between a
            # latency-bound and a compute-bound serving loop.
            for leaf in jax.tree_util.tree_leaves(out):
                hint = getattr(leaf, "copy_to_host_async", None)
                if hint is not None:
                    hint()
        except Exception as e:  # deliver failure to every caller in the batch
            for p in batch:
                p.error = e
                p.event.set()
            return
        # Bounded-backpressure put that stays shutdown-safe: once close()
        # has reaped the completion workers (_reaped), nobody will ever
        # drain the queue — fail this batch's callers instead of parking
        # them behind a put that can no longer complete.
        import queue as _queue

        def fail_batch(b):
            for p in b:
                p.error = RuntimeError("batcher closed")
                p.event.set()

        while True:
            if self._reaped:
                fail_batch(batch)
                return
            try:
                self._inflight.put((batch, sizes, total, out, t_disp),
                                   timeout=0.25)
                break
            except _queue.Full:
                continue
        if self._reaped:
            # Reaping raced our successful put and close()'s sweep may have
            # already drained: self-sweep so no batch is ever stranded.
            while True:
                try:
                    item = self._inflight.get_nowait()
                except _queue.Empty:
                    return
                if item is not None:
                    fail_batch(item[0])

    def _complete_loop(self) -> None:
        """Stage 2: one whole-batch device→host transfer, numpy reply split."""
        import jax

        while True:
            item = self._inflight.get()
            if item is None:
                return
            batch, sizes, total, out, t_disp = item
            try:
                # ONE d2h per output leaf for the whole batch; per-request
                # splits below are host views, free of device round trips
                host = jax.device_get(out)
                t_done = time.monotonic_ns()
                for p in batch:
                    if p.tctx is not None:
                        # dispatch → materialized: the "infer" span (jitted
                        # call + whole-batch d2h, shared by the batch)
                        _tracing.record("infer", p.tctx, t_disp,
                                        t_done - t_disp, rows=total)
                _BATCHER_BATCHES.inc()
                _BATCHER_ROWS.inc(total)
                with self._lock:
                    self.batches_run += 1
                    self.rows_run += total
                off = 0
                for p, n in zip(batch, sizes):
                    s = slice(off, off + n)
                    p.result = jax.tree_util.tree_map(lambda x: x[s], host)
                    off += n
                    p.event.set()
            except Exception as e:
                for p in batch:
                    p.error = e
                    p.event.set()

    def _concat_pad(self, xs: Sequence, bucket: int):
        import jax
        import jax.numpy as jnp
        import numpy as np

        # Requests arrive from the wire as HOST arrays: concat+pad in numpy
        # and ship the batch in ONE h2d. An N-array device-side concatenate
        # is catastrophically slower on high-latency device links (measured
        # on the axon tunnel: jnp.concatenate of 8 rows 514 ms vs host
        # concat + single device_put 6 ms) and never better — it turns one
        # bulk transfer into N small ones plus an extra device launch.
        if all(not isinstance(x, jax.Array) for x in xs):
            cat = np.concatenate([np.asarray(x) for x in xs], axis=0)
            if (self.transfer_dtype is not None
                    and np.issubdtype(cat.dtype, np.floating)):
                cat = cat.astype(self.transfer_dtype)  # halve h2d bytes
            deficit = bucket - cat.shape[0]
            if deficit > 0:
                pad = [(0, deficit)] + [(0, 0)] * (cat.ndim - 1)
                cat = np.pad(cat, pad)
            return jax.device_put(cat)
        # device-resident inputs (in-process callers): keep them on device
        cat = jnp.concatenate([jnp.asarray(x) for x in xs], axis=0)
        deficit = bucket - cat.shape[0]
        if deficit > 0:
            pad = [(0, deficit)] + [(0, 0)] * (cat.ndim - 1)
            cat = jnp.pad(cat, pad)
        return cat


# ---------------------------------------------------------------------------
# Device-boundary merge (tpurpc-manycore, ISSUE 7)
# ---------------------------------------------------------------------------

#: tpurpc-manycore: device-merger observability — how many sub-batches each
#: merged dispatch gathered (1 = nothing to merge), and how often a merged
#: dispatch had to fall back to per-sub isolation
_MERGE_SUBS = _metrics.histogram("merge_subbatches")
_MERGE_DISPATCH = _metrics.counter("merge_dispatches")
_MERGE_ISOLATED = _metrics.counter("merge_isolated_failures")


class _SubBatch:
    """One shard's stacked-and-padded batch, in flight across the merge
    boundary. The shard's batcher thread parks on ``done`` while the merger
    dispatches; ``result``/``error`` come back resolved."""

    __slots__ = ("stacked", "rows", "done", "out", "err")

    #: shard contract (lint rule `shard`): a sub-batch belongs to ITS shard;
    #: its out/err may only be written across the shard boundary inside
    #: the merger's declared ``_MERGE_BOUNDARY`` functions
    _GUARDED_BY = {"out": "done", "err": "done"}

    def __init__(self, stacked, rows: int):
        self.stacked = stacked
        self.rows = rows
        self.done = threading.Event()
        self.out = None
        self.err: Optional[Exception] = None


class DeviceMerger:
    """Gather compatible sub-batches from per-shard batchers into ONE
    device dispatch (tpurpc-manycore tentpole part 3).

    Shards batch independently — each :class:`FanInBatcher` keeps its own
    lock, queue, and flush policy — and meet the single accelerator only
    here: sub-batches are published through a lock-free
    :class:`~tpurpc.core.handoff.HandoffRing` (no cross-shard mutex on the
    hot path), and the one merger thread gathers whatever the other shards
    already committed, concatenates shape-compatible sub-batches along the
    batch axis, and dispatches once. The device stays saturated without the
    transport serializing on a shared batcher lock.

    Failure isolation extends PR 3's poison semantics across the boundary:
    a merged dispatch that fails is retried per sub-batch, so a mis-shaped
    (or poisoned) sub-batch fails ALONE — its siblings' requests complete.
    Incompatible signatures never co-dispatch in the first place (grouped
    by pytree structure + row shape/dtype).

    Note the merge trades one compiled shape for throughput: merging two
    bucket-B sub-batches dispatches 2B rows, a new XLA shape. Callers who
    need the strict one-shape guarantee keep ``n_shards=1`` (plain
    FanInBatcher) or size buckets for the merged total.
    """

    #: the ONLY functions allowed to mutate another shard's `_GUARDED_BY`
    #: state (lint rule `shard`): the merge loop and its resolve/fail arms
    _MERGE_BOUNDARY = ("_merge_loop", "_dispatch_group", "_resolve_sub",
                       "_fail_sub")

    def __init__(self, fn: Callable[[Any], Any], capacity: int = 64,
                 max_merge_subs: int = 8, gather_window_s: float = 0.0005):
        from tpurpc.core.handoff import HandoffRing

        self._fn = fn
        self.max_merge_subs = max(1, max_merge_subs)
        self.gather_window_s = gather_window_s
        self._ring = HandoffRing(capacity)
        self._closed = False
        self.dispatches = 0
        self.subs_merged = 0
        self._thread = threading.Thread(target=self._merge_loop, daemon=True,
                                        name="tpurpc-merge")
        self._thread.start()

    # -- shard-facing ---------------------------------------------------------

    def entry(self) -> Callable[[Any], Any]:
        """An ``fn``-shaped callable for one shard's FanInBatcher: publishes
        the stacked sub-batch across the boundary and parks until the
        merger resolves it. Returns HOST-side results (the merger owns the
        d2h), so the shard's completion stage degrades to a no-op split."""

        def dispatch(stacked):
            import jax

            rows = jax.tree_util.tree_leaves(stacked)[0].shape[0]
            sub = _SubBatch(stacked, rows)
            if not self._ring.publish(sub):
                raise RuntimeError("device merger closed")
            sub.done.wait()
            if sub.err is not None:
                raise sub.err
            return sub.out

        return dispatch

    def close(self) -> None:
        self._closed = True
        self._ring.close()
        self._thread.join(timeout=5)

    # -- the merge boundary (single consumer thread) --------------------------

    def _merge_loop(self) -> None:
        import time as _time

        while True:
            first = self._ring.take(timeout=0.25)
            if first is None:
                if self._closed:
                    return
                continue
            group = [first]
            # gather pass: drain what the other shards ALREADY committed,
            # then one brief window for shards mid-publish — bounded so a
            # lone sub-batch never waits on shards with nothing to say
            deadline = _time.monotonic() + self.gather_window_s
            while len(group) < self.max_merge_subs:
                nxt = self._ring.take_ready()
                if nxt is not None:
                    group.append(nxt)
                    continue
                if _time.monotonic() >= deadline:
                    break
                _time.sleep(self.gather_window_s / 4)
            for sig_group in self._partition(group):
                self._dispatch_group(sig_group)

    @staticmethod
    def _signature(sub: _SubBatch):
        import jax
        import numpy as _np

        leaves, td = jax.tree_util.tree_flatten(sub.stacked)
        return (td, tuple((tuple(_np.shape(x)[1:]),
                           str(getattr(x, "dtype", type(x))))
                          for x in leaves))

    def _partition(self, group: List[_SubBatch]) -> List[List[_SubBatch]]:
        """Group sub-batches that can legally concatenate (same pytree
        structure, row shape, dtype); order-preserving within a group."""
        buckets: dict = {}
        order: List[List[_SubBatch]] = []
        for sub in group:
            try:
                sig = self._signature(sub)
            except Exception:
                sig = ("bad", id(sub))
            lst = buckets.get(sig)
            if lst is None:
                lst = buckets[sig] = []
                order.append(lst)
            lst.append(sub)
        return order

    def _dispatch_group(self, group: List[_SubBatch]) -> None:
        import jax

        _MERGE_DISPATCH.inc()
        _MERGE_SUBS.record(len(group))
        if len(group) == 1:
            sub = group[0]
            try:
                self._resolve_sub(sub, self._run_one(sub.stacked))
            except Exception as exc:
                self._fail_sub(sub, exc)
            return
        try:
            merged = jax.tree_util.tree_map(
                lambda *xs: self._concat(xs), *[s.stacked for s in group])
            host = self._run_one(merged)
            self.subs_merged += len(group)
            off = 0
            for sub in group:
                sl = slice(off, off + sub.rows)
                self._resolve_sub(
                    sub, jax.tree_util.tree_map(lambda x: x[sl], host))
                off += sub.rows
        except Exception:
            # merged dispatch failed: isolate — each sub-batch dispatches
            # alone so a poisoned shard cannot fail its siblings (PR 3's
            # poison-isolation contract, lifted across the merge boundary)
            _MERGE_ISOLATED.inc()
            for sub in group:
                try:
                    self._resolve_sub(sub, self._run_one(sub.stacked))
                except Exception as exc:
                    self._fail_sub(sub, exc)

    def _run_one(self, stacked):
        """Dispatch + materialize to host: ONE d2h for the merged batch;
        the shards' split stages see numpy and pay nothing further."""
        import jax

        return jax.device_get(self._fn(stacked))

    @staticmethod
    def _resolve_sub(sub: _SubBatch, result) -> None:
        sub.out = result
        sub.done.set()

    @staticmethod
    def _fail_sub(sub: _SubBatch, exc: Exception) -> None:
        sub.err = exc
        sub.done.set()

    @staticmethod
    def _concat(xs):
        import numpy as _np

        return _np.concatenate([_np.asarray(x) for x in xs], axis=0)


class ShardedFanIn:
    """N independent FanInBatcher shards merging at the device boundary.

    Callers are striped round-robin across shards (one GIL-atomic
    ``next()`` — no shared lock on the request path); each shard batches
    under its OWN lock and publishes through the merger's handoff ring.
    Drop-in for FanInBatcher where serve_jax wires one (``__call__``,
    ``queue_depth``, ``batches_run``, ``close``)."""

    def __init__(self, fn: Callable[[Any], Any], n_shards: int = 2,
                 max_batch: int = 8, max_delay_s: float = 0.002,
                 inflight_fn: Optional[Callable[[], int]] = None, **kw):
        self.merger = DeviceMerger(fn, capacity=max(8, 4 * n_shards))
        self.shards = [
            FanInBatcher(self.merger.entry(), max_batch=max_batch,
                         max_delay_s=max_delay_s, inflight_fn=inflight_fn,
                         **kw)
            for _ in range(max(1, n_shards))]
        import itertools as _it

        self._rr = _it.count()

    def __call__(self, tree: Any) -> Any:
        return self.shards[next(self._rr) % len(self.shards)](tree)

    def queue_depth(self) -> int:
        return sum(s.queue_depth() for s in self.shards)

    @property
    def batches_run(self) -> int:
        return sum(s.batches_run for s in self.shards)

    @property
    def rows_run(self) -> int:
        return sum(s.rows_run for s in self.shards)

    def close(self) -> None:
        for s in self.shards:
            s.close()
        self.merger.close()


def serve_jax(fn: Callable[[Any], Any], address: str = "127.0.0.1:0", *,
              name: str = "Call", batching: bool = False, max_batch: int = 8,
              max_delay_s: float = 0.002, max_workers: int = 32,
              batch_shards: int = 1):
    """One-liner: stand up a tensor server around a (jitted) callable.

    Returns ``(server, port, batcher_or_None)``; the caller stops the server.

    With ``batching`` the FanInBatcher is wired to the server's in-flight
    request count (depth-aware flush): when every request the transport has
    admitted is already queued, the batch dispatches immediately instead of
    waiting out ``max_delay_s`` — pipelined clients (``TensorClient.
    call_async``) fill batches, lockstep clients stop paying the delay.

    ``batch_shards > 1`` (tpurpc-manycore) splits the batcher into that many
    independent shards merging only at the device boundary
    (:class:`ShardedFanIn`): callers stop contending on one batcher lock,
    the accelerator still sees merged dispatches.
    """
    srv = Server(max_workers=max_workers)
    batcher = None
    if batching:
        if batch_shards > 1:
            batcher = ShardedFanIn(fn, n_shards=batch_shards,
                                   max_batch=max_batch,
                                   max_delay_s=max_delay_s,
                                   inflight_fn=srv.inflight_requests)
        else:
            batcher = FanInBatcher(fn, max_batch=max_batch,
                                   max_delay_s=max_delay_s,
                                   inflight_fn=srv.inflight_requests)
        add_tensor_method(srv, name, batcher)
        # tpurpc-fleet: the batcher's queue depth rides the per-response
        # load report, so a least_loaded client sees model-side queueing
        # the transport-level inflight count alone would miss
        srv.set_load_provider(batcher.queue_depth)
    else:
        add_tensor_method(srv, name, fn)
    srv.start()
    port = srv.add_insecure_port(address)  # after start: returns the bound port
    return srv, port, batcher


def serve_jax_sharded(build_fn: Callable[[], Callable[[Any], Any]],
                      address: str = "127.0.0.1:0", *,
                      workers: int = 2, name: str = "Call",
                      batching: bool = True, max_batch: int = 8,
                      max_delay_s: float = 0.002, max_workers: int = 32,
                      batch_shards: int = 1, listener: str = "reuseport",
                      handoff_policy: str = "round_robin"):
    """tpurpc-manycore serving: N per-core worker processes on ONE port.

    ``build_fn`` constructs the model callable and runs IN EACH WORKER
    (post-fork) — model/XLA state must never cross a fork, so each shard
    owns a replica built in its own process. Each worker is a full
    :func:`serve_jax` stack: its own poller, rings, thread pool, and
    (per-shard, merged-at-the-device-boundary when ``batch_shards > 1``)
    batcher. Returns the started
    :class:`tpurpc.rpc.shard.ShardedServer`; ``.port`` is the serving
    port, ``.stop()`` tears the fleet down.
    """
    from tpurpc.rpc.shard import ShardedServer

    def build(shard_id: int):
        fn = build_fn()
        srv = Server(max_workers=max_workers)
        if batching:
            if batch_shards > 1:
                batcher = ShardedFanIn(fn, n_shards=batch_shards,
                                       max_batch=max_batch,
                                       max_delay_s=max_delay_s,
                                       inflight_fn=srv.inflight_requests)
            else:
                batcher = FanInBatcher(fn, max_batch=max_batch,
                                       max_delay_s=max_delay_s,
                                       inflight_fn=srv.inflight_requests)
            add_tensor_method(srv, name, batcher)
            srv.set_load_provider(batcher.queue_depth)
        else:
            add_tensor_method(srv, name, fn)
        return srv

    return ShardedServer(build, workers=workers, address=address,
                         listener=listener,
                         handoff_policy=handoff_policy).start()
