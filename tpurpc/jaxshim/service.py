"""Tensor services: serve jitted JAX callables over tpurpc.

The ``grpcio-jax`` surface from BASELINE.json:

* :func:`add_tensor_method` / :class:`TensorClient` — unary and
  server-streaming tensor RPCs (config #3: server-streaming
  ``float32[1024,1024]`` → ``jax.Array``).
* :class:`FanInBatcher` — cross-connection request batching (config #4:
  8-client fan-in → 1 TPU server): requests landing on independent
  connections are stacked into one leading batch axis and dispatched as a
  single jitted call, amortizing kernel launch + keeping the MXU fed.

The reference has no equivalent — its apps are byte-oriented greeters
(``examples/cpp/helloworld.benchmark``); batching here is the TPU-first
replacement for "more pollers": one big matmul beats eight small ones.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Iterator, List, Optional, Sequence

import numpy as np

from tpurpc.jaxshim import codec
from tpurpc.rpc.server import (Server, stream_stream_rpc_method_handler,
                               unary_stream_rpc_method_handler,
                               unary_unary_rpc_method_handler)
from tpurpc.rpc.status import StatusCode
from tpurpc.utils.trace import TraceFlag

trace_jax = TraceFlag("jaxshim")

TENSOR_SERVICE = "tpurpc.Tensor"


def _method_path(name: str) -> str:
    return f"/{TENSOR_SERVICE}/{name}"


def _device_decoder(ctx):
    """Per-call request decoder: device-ring placement when the transport is
    the TPU platform, host-aliasing decode otherwise.

    Returns ``(decode(buf) -> tree, finish())``. Credit discipline: each
    ``decode`` releases the PREVIOUS message's leases (the handler advancing
    the request iterator means it is done with that message — the rolling
    analog of the host ring's drain-then-credit, ``pair.cc:276-284``), and
    ``finish`` releases the last message's when the handler returns
    (SURVEY §7 hard-part #4: leases gate the ring's credit return)."""
    ring = getattr(ctx, "device_ring", None)
    if ring is None:
        return codec.tree_deserializer, lambda: None
    from tpurpc.tpu.endpoint import decode_tree_to_ring

    held = []

    def decode(buf):
        for lease in held:
            lease.release()
        held.clear()
        tree, leases = decode_tree_to_ring(ring, buf)
        held.extend(leases)
        return tree

    def finish():
        for lease in held:
            lease.release()
        held.clear()

    return decode, finish


def add_tensor_method(server: Server, name: str,
                      fn: Callable[..., Any],
                      kind: str = "unary_unary",
                      device: bool = False) -> None:
    """Register ``fn(tree) -> tree`` as a tensor-typed method.

    ``fn`` receives the decoded request pytree (numpy views over the receive
    buffer; pass through :func:`tpurpc.jaxshim.codec.to_jax` or let jit trace
    them — jax treats numpy zero-copy on CPU backends). Its return pytree is
    encoded the same way.

    With ``device=True`` and the TPU platform
    (``GRPC_PLATFORM_TYPE=TPU``), request payloads are placed into the
    connection's HBM receive ring and ``fn`` gets lease-backed device arrays;
    the leases (ring credit) are released when ``fn`` returns. On other
    platforms ``device=True`` degrades to the host-aliasing decode.
    """
    if not device:
        if kind == "unary_unary":
            def behavior(req, ctx):
                return fn(req)
            handler = unary_unary_rpc_method_handler(
                behavior, codec.tree_deserializer, codec.tree_serializer)
        elif kind == "unary_stream":
            def behavior(req, ctx):
                yield from fn(req)
            handler = unary_stream_rpc_method_handler(
                behavior, codec.tree_deserializer, codec.tree_serializer)
        elif kind == "stream_stream":
            def behavior(req_iter, ctx):
                yield from fn(req_iter)
            handler = stream_stream_rpc_method_handler(
                behavior, codec.tree_deserializer, codec.tree_serializer)
        else:
            raise ValueError(f"unsupported tensor method kind {kind}")
        server.add_method(_method_path(name), handler)
        return

    # device mode: identity deserializer (raw message bytes reach the
    # behavior), decode inside where ctx exposes the connection's ring.
    if kind == "unary_unary":
        def behavior(raw, ctx):
            decode, finish = _device_decoder(ctx)
            try:
                return fn(decode(raw))
            finally:
                finish()
        handler = unary_unary_rpc_method_handler(
            behavior, codec.raw_view, codec.tree_serializer)
    elif kind == "unary_stream":
        def behavior(raw, ctx):
            decode, finish = _device_decoder(ctx)
            try:
                yield from fn(decode(raw))
            finally:
                finish()
        handler = unary_stream_rpc_method_handler(
            behavior, codec.raw_view, codec.tree_serializer)
    elif kind == "stream_stream":
        def behavior(raw_iter, ctx):
            decode, finish = _device_decoder(ctx)
            try:
                yield from fn(decode(raw) for raw in raw_iter)
            finally:
                finish()
        handler = stream_stream_rpc_method_handler(
            behavior, codec.raw_view, codec.tree_serializer)
    else:
        raise ValueError(f"unsupported tensor method kind {kind}")
    server.add_method(_method_path(name), handler)


class TensorClient:
    """Client for tensor methods; wraps a :class:`tpurpc.rpc.channel.Channel`."""

    def __init__(self, channel):
        self._channel = channel

    def call(self, name: str, tree: Any, timeout: Optional[float] = None) -> Any:
        mc = self._channel.unary_unary(
            _method_path(name), codec.tree_serializer, codec.tree_deserializer)
        return mc(tree, timeout=timeout)

    def call_device(self, name: str, tree: Any,
                    timeout: Optional[float] = None):
        """Unary call whose RESPONSE decodes into the channel's device ring.

        Returns a :class:`tpurpc.tpu.endpoint.DeviceMessage` — use it as a
        context manager (or call ``.release()``) so the ring credit returns.
        Falls back to a plain host decode (still wrapped in DeviceMessage,
        with no leases) when the channel's transport isn't the TPU platform.
        """
        from tpurpc.tpu.endpoint import DeviceMessage, decode_tree_to_ring

        mc = self._channel.unary_unary(
            _method_path(name), codec.tree_serializer, codec.raw_view)
        raw, call = mc.with_call(tree, timeout=timeout)
        # The call's OWN connection: an LB re-pick here could land the
        # response in a different connection's ring (or fail a finished call).
        ring = call.device_ring()
        if ring is None:
            return DeviceMessage(codec.decode_tree(raw), [])
        out, leases = decode_tree_to_ring(ring, raw)
        return DeviceMessage(out, leases)

    def stream(self, name: str, tree: Any,
               timeout: Optional[float] = None) -> Iterator[Any]:
        mc = self._channel.unary_stream(
            _method_path(name), codec.tree_serializer, codec.tree_deserializer)
        return mc(tree, timeout=timeout)

    def duplex(self, name: str, trees: Iterator[Any],
               timeout: Optional[float] = None) -> Iterator[Any]:
        mc = self._channel.stream_stream(
            _method_path(name), codec.tree_serializer, codec.tree_deserializer)
        return mc(trees, timeout=timeout)


# ---------------------------------------------------------------------------
# Fan-in batching (BASELINE config #4)
# ---------------------------------------------------------------------------

class _Pending:
    __slots__ = ("tree", "event", "result", "error")

    def __init__(self, tree):
        self.tree = tree
        self.event = threading.Event()
        self.result = None
        self.error: Optional[Exception] = None


class FanInBatcher:
    """Stack concurrent requests from many connections into one jitted call.

    ``fn`` must accept arrays with a leading batch axis and be
    shape-polymorphic only in that axis (pad-to-bucket keeps XLA's compile
    cache small: batch is padded up to the next power of two ≤ max_batch).
    Each request contributes leading-axis rows; replies are split back out.

    Dispatch fires when ``max_batch`` rows are waiting or ``max_delay_s``
    elapsed since the first queued request — the same latency/throughput dial
    as the reference's busy-poll timeout (``GRPC_RDMA_BUSY_POLLING_TIMEOUT_US``,
    README.md:17-25), applied at the request level instead of the byte level.
    """

    def __init__(self, fn: Callable[[Any], Any], max_batch: int = 8,
                 max_delay_s: float = 0.002, pad_to_bucket: bool = True,
                 fixed_bucket: bool = False):
        self._fn = fn
        self.max_batch = max_batch
        self.max_delay_s = max_delay_s
        self.pad_to_bucket = pad_to_bucket
        #: always pad to max_batch: ONE compiled shape for single-row
        #: requests, the right trade on accelerators where each new batch
        #: shape recompiles (XLA static shapes) — wasted pad rows cost far
        #: less than a mid-serving compile stall. NOTE: a dispatch whose
        #: requests total MORE than max_batch rows (multi-row requests) still
        #: pads to that larger total and compiles its shape; the one-shape
        #: guarantee assumes ≤1 row per request or callers sizing max_batch
        #: to the true row bound.
        self.fixed_bucket = fixed_bucket
        self._lock = threading.Lock()
        self._queue: List[_Pending] = []
        self._kick = threading.Condition(self._lock)
        self._closed = False
        self.batches_run = 0
        self.rows_run = 0
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="tpurpc-batcher")
        self._thread.start()

    def close(self) -> None:
        with self._lock:
            self._closed = True
            self._kick.notify_all()
        self._thread.join(timeout=5)

    def __call__(self, tree: Any) -> Any:
        p = _Pending(tree)
        with self._lock:
            if self._closed:
                raise RuntimeError("batcher closed")
            self._queue.append(p)
            self._kick.notify_all()
        p.event.wait()
        if p.error is not None:
            raise p.error
        return p.result

    # -- batcher thread ------------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._lock:
                while not self._queue and not self._closed:
                    self._kick.wait()
                if self._closed and not self._queue:
                    return
                deadline = time.monotonic() + self.max_delay_s
                while (len(self._queue) < self.max_batch and not self._closed):
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    self._kick.wait(timeout=left)
                batch, self._queue = (self._queue[:self.max_batch],
                                      self._queue[self.max_batch:])
            if batch:
                self._run(batch)

    def _bucket(self, n: int) -> int:
        if self.fixed_bucket:
            return self.max_batch
        if not self.pad_to_bucket:
            return n
        b = 1
        while b < n:
            b <<= 1
        return min(b, self.max_batch)

    def _run(self, batch: List[_Pending]) -> None:
        import jax

        try:
            rows = [p.tree for p in batch]
            sizes = [jax.tree_util.tree_leaves(t)[0].shape[0] for t in rows]
            total = sum(sizes)
            bucket = max(self._bucket(total), total)
            stacked = jax.tree_util.tree_map(
                lambda *xs: self._concat_pad(xs, bucket), *rows)
            out = self._fn(stacked)
            self.batches_run += 1
            self.rows_run += total
            # split replies back along the leading axis, dropping padding
            off = 0
            for p, n in zip(batch, sizes):
                s = slice(off, off + n)
                p.result = jax.tree_util.tree_map(lambda x: x[s], out)
                off += n
                p.event.set()
        except Exception as e:  # deliver failure to every caller in the batch
            for p in batch:
                p.error = e
                p.event.set()

    @staticmethod
    def _concat_pad(xs: Sequence, bucket: int):
        import jax.numpy as jnp

        cat = jnp.concatenate([jnp.asarray(x) for x in xs], axis=0)
        deficit = bucket - cat.shape[0]
        if deficit > 0:
            pad = [(0, deficit)] + [(0, 0)] * (cat.ndim - 1)
            cat = jnp.pad(cat, pad)
        return cat


def serve_jax(fn: Callable[[Any], Any], address: str = "127.0.0.1:0", *,
              name: str = "Call", batching: bool = False, max_batch: int = 8,
              max_delay_s: float = 0.002, max_workers: int = 32):
    """One-liner: stand up a tensor server around a (jitted) callable.

    Returns ``(server, port, batcher_or_None)``; the caller stops the server.
    """
    srv = Server(max_workers=max_workers)
    batcher = None
    if batching:
        batcher = FanInBatcher(fn, max_batch=max_batch, max_delay_s=max_delay_s)
        add_tensor_method(srv, name, batcher)
    else:
        add_tensor_method(srv, name, fn)
    srv.start()
    port = srv.add_insecure_port(address)  # after start: returns the bound port
    return srv, port, batcher
