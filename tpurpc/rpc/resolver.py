"""Name resolution + load-balancing policies for the client channel.

The reference inherits these from gRPC's client_channel filter
(``ext/filters/client_channel/resolver/{dns,sockaddr,fake}`` and
``lb_policy/{pick_first,round_robin}`` — SURVEY.md §2.4). Same target UX:

* ``"host:port"`` / ``"dns:///host:port"`` → DNS resolution (getaddrinfo)
* ``"ipv4:1.2.3.4:5,6.7.8.9:10"``          → static address list
* ``register_resolver("scheme", fn)``       → the fake-resolver test seam

Policies: ``pick_first`` (dial addresses in order, stick with the winner —
gRPC's default) and ``round_robin`` (rotate READY subchannels per call).
"""

from __future__ import annotations

import itertools
import socket
import threading
from typing import Callable, List, Sequence, Tuple

Address = Tuple[str, int]
ResolveFn = Callable[[str], List[Address]]

_RESOLVERS: dict = {}


def register_resolver(scheme: str, fn: ResolveFn) -> None:
    """Register a scheme (the reference's fake resolver seam,
    ``resolver/fake/fake_resolver.cc``)."""
    _RESOLVERS[scheme] = fn


def _parse_hostport(hp: str) -> Address:
    host, _, port_s = hp.rpartition(":")
    if not host or not port_s.isdigit():
        raise ValueError(f"bad address {hp!r} (want host:port)")
    return host, int(port_s)


def _dns_resolve(hostport: str) -> List[Address]:
    host, port = _parse_hostport(hostport)
    try:
        infos = socket.getaddrinfo(host, port, type=socket.SOCK_STREAM)
    except socket.gaierror as exc:
        raise ValueError(f"resolution of {host!r} failed: {exc}") from exc
    seen = []
    for _family, _type, _proto, _canon, sockaddr in infos:
        addr = (sockaddr[0], sockaddr[1])
        if addr not in seen:
            seen.append(addr)
    return seen or [(host, port)]


def resolve_target(target: str) -> List[Address]:
    """gRPC-style target URI → ordered address list."""
    scheme, sep, rest = target.partition(":")
    if sep and scheme in _RESOLVERS:
        return _RESOLVERS[scheme](rest.lstrip("/"))
    if target.startswith("dns:"):
        return _dns_resolve(target[4:].lstrip("/"))
    if target.startswith("ipv4:") or target.startswith("ipv6:"):
        rest = target.split(":", 1)[1]
        return [_parse_hostport(a) for a in rest.split(",") if a]
    if target.startswith("static:"):
        return [_parse_hostport(a) for a in target[7:].split(",") if a]
    return _dns_resolve(target)


class PickFirst:
    """Try addresses in order; stick with the first that connects."""

    name = "pick_first"

    def __init__(self, n: int):
        self._n = n
        self._current = 0
        self._lock = threading.Lock()

    def order(self) -> Sequence[int]:
        with self._lock:
            cur = self._current
        return [(cur + i) % self._n for i in range(self._n)]

    def connected(self, idx: int) -> None:
        with self._lock:
            self._current = idx

    def failed(self, idx: int) -> None:
        with self._lock:
            if self._current == idx:
                self._current = (idx + 1) % self._n


class RoundRobin:
    """Rotate across subchannels per call."""

    name = "round_robin"

    def __init__(self, n: int):
        self._n = n
        self._counter = itertools.count()
        self._lock = threading.Lock()

    def order(self) -> Sequence[int]:
        with self._lock:
            start = next(self._counter) % self._n
        return [(start + i) % self._n for i in range(self._n)]

    def connected(self, idx: int) -> None:
        pass

    def failed(self, idx: int) -> None:
        pass


POLICIES = {"pick_first": PickFirst, "round_robin": RoundRobin}


def make_policy(name: str, n: int):
    try:
        return POLICIES[name](n)
    except KeyError:
        raise ValueError(f"unknown lb policy {name!r} "
                         f"(have {sorted(POLICIES)})") from None
