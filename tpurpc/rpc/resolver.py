"""Name resolution + load-balancing policies for the client channel.

The reference inherits these from gRPC's client_channel filter
(``ext/filters/client_channel/resolver/{dns,sockaddr,fake}`` and
``lb_policy/{pick_first,round_robin}`` — SURVEY.md §2.4). Same target UX:

* ``"host:port"`` / ``"dns:///host:port"`` → DNS resolution (getaddrinfo)
* ``"ipv4:1.2.3.4:5,6.7.8.9:10"``          → static address list
* ``register_resolver("scheme", fn)``       → the fake-resolver test seam

Policies: ``pick_first`` (dial addresses in order, stick with the winner —
gRPC's default), ``round_robin`` (rotate READY subchannels per call), and
``ring_hash`` (consistent hashing — the reference inherits
``lb_policy/ring_hash/ring_hash.cc``; same calls land on the same backend,
and a dead backend's keys spill to its ring successor only).
"""

from __future__ import annotations

import hashlib
import itertools
import socket
import threading
from typing import Callable, List, Optional, Sequence, Tuple

Address = Tuple[str, int]
ResolveFn = Callable[[str], List[Address]]

_RESOLVERS: dict = {}


def register_resolver(scheme: str, fn: ResolveFn) -> None:
    """Register a scheme (the reference's fake resolver seam,
    ``resolver/fake/fake_resolver.cc``)."""
    _RESOLVERS[scheme] = fn


def _parse_hostport(hp: str) -> Address:
    host, _, port_s = hp.rpartition(":")
    if not host or not port_s.isdigit():
        raise ValueError(f"bad address {hp!r} (want host:port)")
    return host, int(port_s)


def _dns_resolve(hostport: str) -> List[Address]:
    host, port = _parse_hostport(hostport)
    try:
        infos = socket.getaddrinfo(host, port, type=socket.SOCK_STREAM)
    except socket.gaierror as exc:
        raise ValueError(f"resolution of {host!r} failed: {exc}") from exc
    seen = []
    for _family, _type, _proto, _canon, sockaddr in infos:
        addr = (sockaddr[0], sockaddr[1])
        if addr not in seen:
            seen.append(addr)
    return seen or [(host, port)]


def resolve_target(target: str) -> List[Address]:
    """gRPC-style target URI → ordered address list."""
    scheme, sep, rest = target.partition(":")
    if sep and scheme in _RESOLVERS:
        return _RESOLVERS[scheme](rest.lstrip("/"))
    if target.startswith("dns:"):
        return _dns_resolve(target[4:].lstrip("/"))
    if target.startswith("ipv4:") or target.startswith("ipv6:"):
        rest = target.split(":", 1)[1]
        return [_parse_hostport(a) for a in rest.split(",") if a]
    if target.startswith("static:"):
        return [_parse_hostport(a) for a in target[7:].split(",") if a]
    return _dns_resolve(target)


class PickFirst:
    """Try addresses in order; stick with the first that connects."""

    name = "pick_first"

    def __init__(self, n: int):
        self._n = n
        self._current = 0
        self._lock = threading.Lock()

    def order(self) -> Sequence[int]:
        with self._lock:
            cur = self._current
        return [(cur + i) % self._n for i in range(self._n)]

    def connected(self, idx: int) -> None:
        with self._lock:
            self._current = idx

    def failed(self, idx: int) -> None:
        with self._lock:
            if self._current == idx:
                self._current = (idx + 1) % self._n


class RoundRobin:
    """Rotate across subchannels per call."""

    name = "round_robin"

    def __init__(self, n: int):
        self._n = n
        self._counter = itertools.count()
        self._lock = threading.Lock()

    def order(self) -> Sequence[int]:
        with self._lock:
            start = next(self._counter) % self._n
        return [(start + i) % self._n for i in range(self._n)]

    def connected(self, idx: int) -> None:
        pass

    def failed(self, idx: int) -> None:
        pass


_call_key = threading.local()


class ring_hash_key:
    """Route calls made inside this context by a consistent-hash key:

    >>> with ring_hash_key("user-42"):
    ...     stub.Get(req)        # always lands on the same backend

    The reference's ring_hash policy hashes a per-RPC attribute (the xds
    hash policy); tpurpc's channel API has no per-call LB metadata plumbing,
    so the key rides a thread-local that :class:`RingHash` reads at pick
    time. Without an active key, picks rotate (round-robin degenerate)."""

    def __init__(self, key: str):
        self._key = key

    def __enter__(self):
        self._prev = getattr(_call_key, "key", None)
        _call_key.key = self._key
        return self

    def __exit__(self, *exc):
        _call_key.key = self._prev
        return False


class RingHash:
    """Consistent hashing over subchannel indices.

    Each backend index is placed on a 2^32 ring at ``replicas`` points
    (md5 of ``"{idx}:{r}"``); a call's key hashes to a ring point and the
    preference order is the distinct backends encountered walking clockwise
    — so losing a backend moves only its arc to its successor, the property
    the reference's policy exists for."""

    name = "ring_hash"
    replicas = 64

    def __init__(self, n: int):
        self._n = n
        self._counter = itertools.count()
        self._lock = threading.Lock()
        points: List[Tuple[int, int]] = []
        for idx in range(n):
            for r in range(self.replicas):
                h = hashlib.md5(f"{idx}:{r}".encode()).digest()
                points.append((int.from_bytes(h[:4], "big"), idx))
        points.sort()
        self._points = points

    def _walk(self, start_hash: int) -> Sequence[int]:
        """Distinct backend indices in clockwise ring order from a point."""
        import bisect

        i = bisect.bisect_left(self._points, (start_hash, -1))
        order: List[int] = []
        seen = set()
        for k in range(len(self._points)):
            _, idx = self._points[(i + k) % len(self._points)]
            if idx not in seen:
                seen.add(idx)
                order.append(idx)
                if len(order) == self._n:
                    break
        return order

    def order(self) -> Sequence[int]:
        key: Optional[str] = getattr(_call_key, "key", None)
        if key is None:
            with self._lock:
                start = next(self._counter) % self._n
            return [(start + i) % self._n for i in range(self._n)]
        h = hashlib.md5(key.encode()).digest()
        return self._walk(int.from_bytes(h[:4], "big"))

    def connected(self, idx: int) -> None:
        pass

    def failed(self, idx: int) -> None:
        pass


POLICIES = {"pick_first": PickFirst, "round_robin": RoundRobin,
            "ring_hash": RingHash}


def make_policy(name: str, n: int):
    try:
        return POLICIES[name](n)
    except KeyError:
        raise ValueError(f"unknown lb policy {name!r} "
                         f"(have {sorted(POLICIES)})") from None
