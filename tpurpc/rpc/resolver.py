"""Name resolution + load-balancing policies for the client channel.

The reference inherits these from gRPC's client_channel filter
(``ext/filters/client_channel/resolver/{dns,sockaddr,fake}`` and
``lb_policy/{pick_first,round_robin}`` — SURVEY.md §2.4). Same target UX:

* ``"host:port"`` / ``"dns:///host:port"`` → DNS resolution (getaddrinfo)
* ``"ipv4:1.2.3.4:5,6.7.8.9:10"``          → static address list
* ``register_resolver("scheme", fn)``       → the fake-resolver test seam

Policies: ``pick_first`` (dial addresses in order, stick with the winner —
gRPC's default), ``round_robin`` (rotate READY subchannels per call),
``ring_hash`` (consistent hashing — the reference inherits
``lb_policy/ring_hash/ring_hash.cc``; same calls land on the same backend,
and a dead backend's keys spill to its ring successor only), and
``least_loaded`` (tpurpc-fleet: ORCA-style load reports piggybacked in
trailing metadata drive an EWMA pick order with outlier ejection of
slow/erroring backends).
"""

from __future__ import annotations

import hashlib
import itertools
import socket
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

Address = Tuple[str, int]
ResolveFn = Callable[[str], List[Address]]

_RESOLVERS: dict = {}


class Resolution:
    """One resolver result: addresses plus (optionally) the service config
    the resolver delivers with them — gRPC's resolver-result shape
    (``resolver.h`` Result carries addresses + service_config; the
    client_channel consumes per-method timeout/retry from it,
    ``service_config.cc``). ``service_config`` is the raw JSON dict; the
    channel parses it via :class:`tpurpc.rpc.service_config.ServiceConfig`."""

    __slots__ = ("addresses", "service_config")

    def __init__(self, addresses: List[Address],
                 service_config: "Optional[dict]" = None):
        self.addresses = list(addresses)
        self.service_config = service_config


def register_resolver(scheme: str, fn: ResolveFn) -> None:
    """Register a scheme (the reference's fake resolver seam,
    ``resolver/fake/fake_resolver.cc``). The fn may return a plain address
    list, an ``(addresses, service_config_dict)`` tuple, or a
    :class:`Resolution` — the latter two deliver per-method config with
    the membership, the way gRPC resolvers do."""
    _RESOLVERS[scheme] = fn


def _as_resolution(result) -> Resolution:
    if isinstance(result, Resolution):
        return result
    if (isinstance(result, tuple) and len(result) == 2
            and isinstance(result[1], (dict, type(None)))):
        return Resolution(list(result[0]), result[1])
    return Resolution(list(result), None)


def _parse_hostport(hp: str) -> Address:
    host, _, port_s = hp.rpartition(":")
    if not host or not port_s.isdigit():
        raise ValueError(f"bad address {hp!r} (want host:port)")
    return host, int(port_s)


def _dns_resolve(hostport: str) -> List[Address]:
    host, port = _parse_hostport(hostport)
    try:
        infos = socket.getaddrinfo(host, port, type=socket.SOCK_STREAM)
    except socket.gaierror as exc:
        raise ValueError(f"resolution of {host!r} failed: {exc}") from exc
    seen = []
    for _family, _type, _proto, _canon, sockaddr in infos:
        addr = (sockaddr[0], sockaddr[1])
        if addr not in seen:
            seen.append(addr)
    return seen or [(host, port)]


def resolve_target(target: str) -> List[Address]:
    """gRPC-style target URI → ordered address list."""
    return resolve_target_full(target).addresses


def resolve_target_full(target: str) -> Resolution:
    """gRPC-style target URI → :class:`Resolution` (addresses + any
    service config the scheme's resolver attached)."""
    scheme, sep, rest = target.partition(":")
    if sep and scheme == "xds" and scheme not in _RESOLVERS:
        # lazy: importing the xds module registers its resolver (bootstrap
        # + ADS-lite snapshot; tpurpc/rpc/xds.py — the reference's
        # resolver/xds analog)
        import tpurpc.rpc.xds  # noqa: F401
    if sep and scheme in _RESOLVERS:
        return _as_resolution(_RESOLVERS[scheme](rest.lstrip("/")))
    if target.startswith("dns:"):
        return Resolution(_dns_resolve(target[4:].lstrip("/")))
    if target.startswith("ipv4:") or target.startswith("ipv6:"):
        rest = target.split(":", 1)[1]
        return Resolution([_parse_hostport(a) for a in rest.split(",") if a])
    if target.startswith("static:"):
        return Resolution([_parse_hostport(a)
                           for a in target[7:].split(",") if a])
    return Resolution(_dns_resolve(target))


class PickFirst:
    """Try addresses in order; stick with the first that connects."""

    name = "pick_first"

    def __init__(self, n: int):
        self._n = n
        self._current = 0
        self._lock = threading.Lock()

    def order(self) -> Sequence[int]:
        with self._lock:
            cur = self._current
        return [(cur + i) % self._n for i in range(self._n)]

    def connected(self, idx: int) -> None:
        with self._lock:
            self._current = idx

    def failed(self, idx: int) -> None:
        with self._lock:
            if self._current == idx:
                self._current = (idx + 1) % self._n


class RoundRobin:
    """Rotate across subchannels per call."""

    name = "round_robin"

    def __init__(self, n: int):
        self._n = n
        self._counter = itertools.count()
        self._lock = threading.Lock()

    def order(self) -> Sequence[int]:
        with self._lock:
            start = next(self._counter) % self._n
        return [(start + i) % self._n for i in range(self._n)]

    def connected(self, idx: int) -> None:
        pass

    def failed(self, idx: int) -> None:
        pass


class LeastLoaded:
    """Load-aware picking from ORCA-style per-response load reports
    (tpurpc-fleet, ISSUE 6 — the reference's analog is the xds
    ``orca_load_report`` consumed by custom LB policies).

    Servers piggyback ``tpurpc-load: "<inflight>,<queue_depth>,<p99_ms>"``
    in trailing metadata (see :func:`tpurpc.rpc.server.Server._load_md`);
    the channel strips it off every response and feeds
    :meth:`load_report`. Pick order sorts subchannels by an EWMA of the
    reported utilization (inflight + queue depth), with a rotating
    tiebreak so equally-loaded backends still round-robin.

    Outlier ejection covers the two degradation modes load alone misses:

    * **erroring** — ``ejection_failures`` consecutive dial/call failures
      eject the subchannel for ``ejection_s`` seconds (flight event
      ``subch-ejected``, reason 0); any success resets the streak.
    * **slow** — a backend whose reported p99 EWMA exceeds
      ``slow_mult`` × the fleet median (above a 1 ms floor) is ejected
      the same way (reason 1) — a replica in GC hell or on a sick host
      reports modest queue depth while serving garbage latency.

    Ejection expiry reinstates the backend (``subch-reinstated``) so a
    recovered replica is re-probed; ejected backends still appear LAST in
    the pick order — a fleet with every member ejected degrades to
    round-robin rather than failing picks.
    """

    name = "least_loaded"
    ewma_alpha = 0.3

    def __init__(self, n: int, *, ejection_failures: int = 3,
                 ejection_s: float = 5.0, slow_mult: float = 4.0):
        self._n = n
        self.ejection_failures = ejection_failures
        self.ejection_s = ejection_s
        self.slow_mult = slow_mult
        self._counter = itertools.count()
        self._lock = threading.Lock()
        self._load = [0.0] * n          # EWMA of inflight + queue depth
        self._p99 = [0.0] * n           # EWMA of reported p99 (ms)
        self._reported = [False] * n
        self._fail_streak = [0] * n
        self._ejected_until = [0.0] * n
        # interned once; emits below are pure-int (flight lint discipline)
        from tpurpc.obs import flight as _flight

        self._flight = _flight
        self._ftag = _flight.tag_for("lb:least_loaded")

    @staticmethod
    def parse_report(raw) -> "Optional[Tuple[float, float]]":
        """``b"3,5,12.5"`` → ``(utilization, p99_ms)`` or None on junk."""
        try:
            if isinstance(raw, (bytes, bytearray, memoryview)):
                raw = bytes(raw).decode("ascii")
            parts = str(raw).split(",")
            inflight = float(parts[0])
            qdepth = float(parts[1]) if len(parts) > 1 else 0.0
            p99_ms = float(parts[2]) if len(parts) > 2 else 0.0
            return max(0.0, inflight) + max(0.0, qdepth), max(0.0, p99_ms)
        except (ValueError, IndexError):
            return None

    def load_report(self, idx: int, raw) -> None:
        """One server-piggybacked report for subchannel ``idx`` (called by
        the channel on every response carrying one)."""
        parsed = self.parse_report(raw)
        if parsed is None or not 0 <= idx < self._n:
            return
        util, p99_ms = parsed
        a = self.ewma_alpha
        with self._lock:
            if self._reported[idx]:
                self._load[idx] += a * (util - self._load[idx])
                self._p99[idx] += a * (p99_ms - self._p99[idx])
            else:
                self._reported[idx] = True
                self._load[idx] = util
                self._p99[idx] = p99_ms
            self._maybe_eject_slow_locked(idx)

    def _maybe_eject_slow_locked(self, idx: int) -> None:
        now = time.monotonic()
        if now < self._ejected_until[idx]:
            return
        peers = [self._p99[i] for i in range(self._n)
                 if i != idx and self._reported[i]]
        if not peers:
            return
        peers.sort()
        median = peers[len(peers) // 2]
        if self._p99[idx] > max(1.0, median * self.slow_mult):
            self._ejected_until[idx] = now + self.ejection_s
            self._flight.emit(self._flight.SUBCH_EJECT, self._ftag, idx, 1)

    def order(self) -> Sequence[int]:
        now = time.monotonic()
        with self._lock:
            rr = next(self._counter) % self._n
            expired = [i for i in range(self._n)
                       if self._ejected_until[i]
                       and now >= self._ejected_until[i]]
            for i in expired:
                self._ejected_until[i] = 0.0
                self._fail_streak[i] = 0
                self._flight.emit(self._flight.SUBCH_REINSTATE,
                                  self._ftag, i)
            ranked = sorted(
                range(self._n),
                key=lambda i: (1 if now < self._ejected_until[i] else 0,
                               self._load[i], (i - rr) % self._n))
        return ranked

    def connected(self, idx: int) -> None:
        with self._lock:
            if 0 <= idx < self._n:
                self._fail_streak[idx] = 0

    def failed(self, idx: int) -> None:
        if not 0 <= idx < self._n:
            return
        with self._lock:
            self._fail_streak[idx] += 1
            if (self._fail_streak[idx] >= self.ejection_failures
                    and time.monotonic() >= self._ejected_until[idx]):
                self._ejected_until[idx] = (time.monotonic()
                                            + self.ejection_s)
                self._flight.emit(self._flight.SUBCH_EJECT,
                                  self._ftag, idx, 0)

    def snapshot(self) -> dict:
        """Introspection/test seam: current EWMAs + ejection state."""
        now = time.monotonic()
        with self._lock:
            return {
                "load": list(self._load),
                "p99_ms": list(self._p99),
                "reported": list(self._reported),
                "ejected": [now < t for t in self._ejected_until],
                "fail_streak": list(self._fail_streak),
            }


_call_key = threading.local()


class ring_hash_key:
    """Route calls made inside this context by a consistent-hash key:

    >>> with ring_hash_key("user-42"):
    ...     stub.Get(req)        # always lands on the same backend

    The reference's ring_hash policy hashes a per-RPC attribute (the xds
    hash policy); tpurpc's channel API has no per-call LB metadata plumbing,
    so the key rides a thread-local that :class:`RingHash` reads at pick
    time. Without an active key, picks rotate (round-robin degenerate)."""

    def __init__(self, key: str):
        self._key = key

    def __enter__(self):
        self._prev = getattr(_call_key, "key", None)
        _call_key.key = self._key
        return self

    def __exit__(self, *exc):
        _call_key.key = self._prev
        return False


class RingHash:
    """Consistent hashing over subchannel indices.

    Each backend index is placed on a 2^32 ring at ``replicas`` points
    (md5 of ``"{idx}:{r}"``); a call's key hashes to a ring point and the
    preference order is the distinct backends encountered walking clockwise
    — so losing a backend moves only its arc to its successor, the property
    the reference's policy exists for."""

    name = "ring_hash"
    replicas = 64

    def __init__(self, n: int):
        self._n = n
        self._counter = itertools.count()
        self._lock = threading.Lock()
        points: List[Tuple[int, int]] = []
        for idx in range(n):
            for r in range(self.replicas):
                h = hashlib.md5(f"{idx}:{r}".encode()).digest()
                points.append((int.from_bytes(h[:4], "big"), idx))
        points.sort()
        self._points = points

    def _walk(self, start_hash: int) -> Sequence[int]:
        """Distinct backend indices in clockwise ring order from a point."""
        import bisect

        i = bisect.bisect_left(self._points, (start_hash, -1))
        order: List[int] = []
        seen = set()
        for k in range(len(self._points)):
            _, idx = self._points[(i + k) % len(self._points)]
            if idx not in seen:
                seen.add(idx)
                order.append(idx)
                if len(order) == self._n:
                    break
        return order

    def order(self) -> Sequence[int]:
        key: Optional[str] = getattr(_call_key, "key", None)
        if key is None:
            with self._lock:
                start = next(self._counter) % self._n
            return [(start + i) % self._n for i in range(self._n)]
        h = hashlib.md5(key.encode()).digest()
        return self._walk(int.from_bytes(h[:4], "big"))

    def connected(self, idx: int) -> None:
        pass

    def failed(self, idx: int) -> None:
        pass


class _IndexMapped:
    """Adapter running a child policy over a subset of the channel's
    subchannel indices: the child sees local indices ``0..k-1``; the adapter
    translates to/from the global ones. This is what lets ``priority`` and
    ``weighted_target`` compose arbitrary leaf policies (the reference builds
    the same shape as a tree of LB policies handing each child its own
    address sublist — ``lb_policy/priority/priority.cc``,
    ``weighted_target/weighted_target.cc``)."""

    def __init__(self, child, indices: Sequence[int]):
        self.child = child
        self.indices = list(indices)
        self._rev = {g: l for l, g in enumerate(self.indices)}

    def order(self) -> Sequence[int]:
        return [self.indices[i] for i in self.child.order()]

    def connected(self, gidx: int) -> None:
        if gidx in self._rev:
            self.child.connected(self._rev[gidx])

    def failed(self, gidx: int) -> None:
        if gidx in self._rev:
            self.child.failed(self._rev[gidx])


class Priority:
    """Ordered failover across child policies (ref
    ``lb_policy/priority/priority.cc``): all traffic goes to the
    highest-priority child with a usable backend; when every backend of the
    active child is marked failed, traffic fails over to the next child.
    Failed marks expire after ``failover_timeout_s`` so a recovered
    higher-priority child gets re-probed and traffic **fails back** (the
    reference drives this with its failover timer + child re-activation).

    The emitted order always appends the lower-priority children after the
    active child's backends — a single call can thus ride the channel's
    walk-the-order dial loop through a mid-call failover without waiting for
    the mark bookkeeping to settle."""

    name = "priority"

    def __init__(self, children: Sequence[_IndexMapped],
                 failover_timeout_s: float = 10.0):
        if not children:
            raise ValueError("priority needs at least one child")
        self._children = list(children)
        self.failover_timeout_s = failover_timeout_s
        self._failed_at: dict = {}          # global idx -> monotonic mark
        self._lock = threading.Lock()

    def _usable(self, child: _IndexMapped, now: float) -> bool:
        for g in child.indices:
            t = self._failed_at.get(g)
            if t is None or now - t >= self.failover_timeout_s:
                return True  # healthy, or failed mark expired: re-probe
        return False

    def order(self) -> Sequence[int]:
        import time as _time

        now = _time.monotonic()
        with self._lock:
            ranked = sorted(
                range(len(self._children)),
                key=lambda i: 0 if self._usable(self._children[i], now) else 1)
        out: List[int] = []
        seen = set()
        for ci in ranked:
            for g in self._children[ci].order():
                if g not in seen:
                    seen.add(g)
                    out.append(g)
        return out

    def connected(self, gidx: int) -> None:
        with self._lock:
            self._failed_at.pop(gidx, None)
        for c in self._children:
            c.connected(gidx)

    def failed(self, gidx: int) -> None:
        import time as _time

        with self._lock:
            self._failed_at[gidx] = _time.monotonic()
        for c in self._children:
            c.failed(gidx)


class WeightedTarget:
    """Weight-proportional traffic split across named targets, each with its
    own child policy (ref ``lb_policy/weighted_target/weighted_target.cc``).
    Pick uses smooth weighted round-robin (deterministic: a weight-3 target
    gets exactly 3 of every ``total`` picks, maximally interleaved), then
    the remaining targets are appended so dial failures spill over."""

    name = "weighted_target"

    def __init__(self, targets: Sequence[Tuple[float, _IndexMapped]]):
        if not targets:
            raise ValueError("weighted_target needs at least one target")
        self._targets = [(float(w), c) for w, c in targets]
        if any(w <= 0 for w, _ in self._targets):
            raise ValueError("weights must be positive")
        self._current = [0.0] * len(self._targets)
        self._lock = threading.Lock()

    def order(self) -> Sequence[int]:
        with self._lock:
            total = sum(w for w, _ in self._targets)
            for i, (w, _) in enumerate(self._targets):
                self._current[i] += w
            ranked = sorted(range(len(self._targets)),
                            key=lambda i: -self._current[i])
            self._current[ranked[0]] -= total
        out: List[int] = []
        seen = set()
        for ti in ranked:
            for g in self._targets[ti][1].order():
                if g not in seen:
                    seen.add(g)
                    out.append(g)
        return out

    def connected(self, gidx: int) -> None:
        for _, c in self._targets:
            c.connected(gidx)

    def failed(self, gidx: int) -> None:
        for _, c in self._targets:
            c.failed(gidx)


POLICIES = {"pick_first": PickFirst, "round_robin": RoundRobin,
            "ring_hash": RingHash, "least_loaded": LeastLoaded}


def make_policy(spec, n: int):
    """Build an LB policy.

    ``spec`` is either a policy name (``"pick_first"``, ``"round_robin"``,
    ``"ring_hash"``) over all ``n`` subchannels, or a composition tree à la
    gRPC service config (ref priority/weighted_target policies):

    >>> make_policy({"priority": {
    ...     "children": [
    ...         {"policy": "round_robin", "indices": [0, 1]},
    ...         {"policy": "pick_first", "indices": [2]},
    ...     ], "failover_timeout_s": 5}}, 3)
    >>> make_policy({"weighted_target": {"targets": [
    ...     {"weight": 3, "policy": "pick_first", "indices": [0]},
    ...     {"weight": 1, "policy": "pick_first", "indices": [1]},
    ... ]}}, 2)

    Children nest: a ``policy`` value may itself be a dict spec (e.g. a
    weighted_target of priority lists), in which case its ``indices`` are
    the universe its nested spec's indices refer into.
    """
    if isinstance(spec, str):
        try:
            return POLICIES[spec](n)
        except KeyError:
            raise ValueError(f"unknown lb policy {spec!r} "
                             f"(have {sorted(POLICIES)})") from None
    if not isinstance(spec, dict) or len(spec) != 1:
        raise ValueError(f"lb policy spec must be a name or one-key dict, "
                         f"got {spec!r}")
    kind, body = next(iter(spec.items()))

    def build_child(entry) -> _IndexMapped:
        indices = entry.get("indices")
        if not indices:
            raise ValueError(f"child {entry!r} needs non-empty 'indices'")
        bad = [i for i in indices if not 0 <= i < n]
        if bad:
            raise ValueError(f"child indices {bad} out of range 0..{n - 1}")
        child = make_policy(entry.get("policy", "pick_first"), len(indices))
        return _IndexMapped(child, indices)

    if kind == "priority":
        if isinstance(body, list):
            body = {"children": body}
        if not isinstance(body, dict) or "children" not in body:
            raise ValueError(f"priority spec needs 'children': {body!r}")
        children = [build_child(e) for e in body["children"]]
        return Priority(children,
                        failover_timeout_s=body.get("failover_timeout_s",
                                                    10.0))
    if kind == "weighted_target":
        if isinstance(body, list):
            body = {"targets": body}
        if not isinstance(body, dict) or "targets" not in body:
            raise ValueError(f"weighted_target spec needs 'targets': {body!r}")
        targets = [(e.get("weight", 1), build_child(e))
                   for e in body["targets"]]
        return WeightedTarget(targets)
    raise ValueError(f"unknown composite lb policy {kind!r} "
                     f"(have: priority, weighted_target)")
