"""xDS-lite: an xds resolver + EDS-style endpoint discovery shim.

The reference carries the xDS client_channel family — the ``xds:`` resolver
(``ext/filters/client_channel/resolver/xds/xds_resolver.cc``), the xds LB
policies (``lb_policy/xds/{cds,eds}.cc``) and the google-c2p variant — as
inherited inventory (SURVEY.md §2.4). This module is tpurpc's lite analog
of that capability, scoped the way VERDICT r3 #9 scoped it: the gRPC xDS
UX (bootstrap file + ``xds:///service`` targets + dynamic endpoint
updates) over tpurpc's OWN control-plane wire and existing composition
tree, NOT the Envoy ADS protobuf surface (that protocol family is
Envoy-ecosystem infrastructure the way ALTS is Google infrastructure —
out of scope; the seam where a full ADS client would plug in is exactly
this module).

Pieces (mirroring how gRPC's pieces fit):

* **Bootstrap** — ``GRPC_XDS_BOOTSTRAP`` (a JSON file path) or
  ``GRPC_XDS_BOOTSTRAP_CONFIG`` (inline JSON), the real gRPC knobs:
  ``{"xds_servers": [{"server_uri": "host:port"}], "node": {"id": ...}}``.
* **``xds:`` resolver** — registered into the channel's resolver registry
  (``register_resolver``, the fake-resolver seam): ``xds:///service``
  dials the bootstrap server and returns the service's CURRENT endpoint
  list — so a plain ``Channel("xds:///service")`` works with a static
  snapshot, grpcio-style.
* **:class:`XdsServicer`** — the control plane: per-service endpoint
  sets pushed to subscribers (``set_endpoints`` = the EDS
  ClusterLoadAssignment update). Attach to any tpurpc server.
* **:class:`XdsWatcher`** — the dynamic half: subscribes on the ADS-lite
  stream and feeds every update into ``Channel.update_addresses`` (the
  eds policy's job in the reference).
* **:func:`xds_channel`** — the one-call UX: bootstrap + first snapshot +
  watcher, returning a channel whose membership tracks the control plane.

Wire (ADS-lite): bidi stream ``/tpurpc.xds.v1.Ads/Stream``; the client
opens with ``{"node": {...}, "resource": "<service>"}`` (JSON) and
receives ``{"version": N, "endpoints": ["host:port", ...]}`` — the
current assignment immediately, then one message per change.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence

METHOD = "/tpurpc.xds.v1.Ads/Stream"


# -- bootstrap ---------------------------------------------------------------

def load_bootstrap() -> dict:
    """The gRPC bootstrap contract: file via GRPC_XDS_BOOTSTRAP, inline
    via GRPC_XDS_BOOTSTRAP_CONFIG (file wins, like gRPC)."""
    path = os.environ.get("GRPC_XDS_BOOTSTRAP")
    raw: Optional[str] = None
    if path:
        with open(path, "r", encoding="utf-8") as f:
            raw = f.read()
    else:
        raw = os.environ.get("GRPC_XDS_BOOTSTRAP_CONFIG")
    if not raw:
        raise RuntimeError(
            "xds: target needs a bootstrap: set GRPC_XDS_BOOTSTRAP to a "
            "JSON file or GRPC_XDS_BOOTSTRAP_CONFIG to inline JSON")
    cfg = json.loads(raw)
    servers = cfg.get("xds_servers") or []
    if not servers or "server_uri" not in servers[0]:
        raise RuntimeError("xds bootstrap needs xds_servers[0].server_uri")
    return cfg


def _server_uri(cfg: dict) -> str:
    return cfg["xds_servers"][0]["server_uri"]


# -- control plane -----------------------------------------------------------

class XdsServicer:
    """ADS-lite control plane: per-service endpoint assignments, pushed.

    ``set_endpoints(service, ["h:p", ...])`` is the EDS update; every
    subscriber of that service receives the new assignment immediately,
    and a fresh subscriber gets the current one on subscribe."""

    def __init__(self):
        self._lock = threading.Condition()
        self._assignments: Dict[str, List[str]] = {}
        self._version = 0

    def set_endpoints(self, service: str, endpoints: Sequence[str]) -> None:
        with self._lock:
            self._assignments[service] = list(endpoints)
            self._version += 1
            self._lock.notify_all()

    def get_endpoints(self, service: str) -> List[str]:
        with self._lock:
            return list(self._assignments.get(service, []))

    def _stream(self, request_iterator, ctx):
        first = next(iter(request_iterator), None)
        if first is None:
            return
        try:
            sub = json.loads(bytes(first).decode())
            resource = sub["resource"]
        except (ValueError, KeyError):
            from tpurpc.rpc.status import AbortError, StatusCode

            raise AbortError(StatusCode.INVALID_ARGUMENT,
                             "ADS stream must open with "
                             '{"resource": "<service>"}') from None
        last_sent: Optional[List[str]] = None
        while ctx.is_active():
            with self._lock:
                current = list(self._assignments.get(resource, []))
                version = self._version
                if current == last_sent:
                    self._lock.wait_for(lambda: self._version != version,
                                        timeout=1.0)
                    continue
            last_sent = current
            yield json.dumps({"version": version,
                              "endpoints": current}).encode()

    def attach(self, server) -> None:
        from tpurpc.rpc.server import stream_stream_rpc_method_handler

        server.add_method(METHOD,
                          stream_stream_rpc_method_handler(self._stream))


# -- client side -------------------------------------------------------------

def _fetch_snapshot(server_uri: str, service: str, node: dict,
                    timeout: float = 10.0) -> List[str]:
    """One subscribe → first assignment → done (the resolver's job)."""
    from tpurpc.rpc.channel import Channel
    from tpurpc.rpc.status import RpcError

    with Channel(server_uri, connect_timeout=timeout) as ch:
        stream = ch.stream_stream(METHOD)
        sub = json.dumps({"node": node, "resource": service}).encode()
        # ACTUALLY hold the request side open until the response lands (or
        # the fetch gives up): a generator that returns right after the
        # subscribe half-closes immediately, and a strict control plane may
        # treat client half-close as end-of-stream before its first push
        # (ADVICE r4 #5). The sender thread parks on this event; cancel()
        # below releases it on every exit path.
        done = threading.Event()

        def reqs():
            yield sub
            done.wait(timeout)

        call = stream(reqs(), timeout=timeout)
        try:
            first = next(iter(call), None)
        finally:
            done.set()
            try:
                call.cancel()
            except Exception:
                pass
        if first is None:
            raise RuntimeError(
                f"xds server {server_uri} closed the ADS stream without "
                f"an assignment for {service!r}")
        try:
            return list(json.loads(bytes(first).decode())["endpoints"])
        except (ValueError, KeyError) as exc:
            raise RuntimeError(
                f"malformed ADS response from {server_uri}") from exc


def _normalize(endpoints: Sequence[str]) -> list:
    """Endpoint strings → resolved (host, port) tuples, through the SAME
    normalization ``Channel.update_addresses`` applies — hostname
    endpoints must produce identical keys at construction and on every
    update, or the keep-live matching misses and a no-op update tears
    down live connections (channel.py's own warning)."""
    from tpurpc.rpc.resolver import resolve_target

    out = []
    for e in endpoints:
        out.extend(resolve_target(e))
    return out


def _resolve_xds(rest: str):
    """Resolver for ``xds:///service`` (registered below)."""
    service = rest.lstrip("/")
    cfg = load_bootstrap()
    endpoints = _fetch_snapshot(_server_uri(cfg), service,
                                cfg.get("node", {}))
    if not endpoints:
        raise ValueError(f"xds assignment for {service!r} is empty")
    return _normalize(endpoints)


def _install_resolver() -> None:
    from tpurpc.rpc.resolver import register_resolver

    register_resolver("xds", _resolve_xds)


_install_resolver()


class XdsWatcher:
    """Dynamic membership: ADS-lite subscription → update_addresses.

    The eds-policy role (``lb_policy/xds/eds.cc``): every assignment
    change the control plane pushes lands in the channel's composition
    tree via :meth:`Channel.update_addresses` (kept subchannels keep
    their connections). Reconnects with backoff when the control plane
    drops; the channel keeps its LAST applied assignment meanwhile
    (gRPC's xds behavior: no assignment churn on control-plane loss).

    Structurally a sibling of :class:`~tpurpc.rpc.lookaside.
    LookasideWatcher` (same subscribe/stream/apply/backoff skeleton) —
    kept separate because the wires diverge (grpclb speaks
    initial_response + ClientStats load reporting; ADS-lite is
    subscribe→assignments), but fixes to either loop's lifecycle
    handling likely apply to both."""

    def __init__(self, channel, service: str,
                 bootstrap: Optional[dict] = None):
        if getattr(channel, "_addrs", None) is None:
            raise ValueError(
                "xds watching needs a target-built channel "
                "(endpoint_factory channels have fixed membership)")
        self._channel = channel
        self._service = service
        self._cfg = bootstrap or load_bootstrap()
        self._stop = threading.Event()
        #: last NORMALIZED assignment applied (seeded from the channel's
        #: current membership): identical pushes — including the control
        #: plane's initial resend of the snapshot the resolver already
        #: fetched — are skipped, so a static assignment never churns the
        #: LB policy or disqualifies the channel's native fast path
        self._last_applied = list(channel._addrs)
        self.applied_versions: List[int] = []  # observability/test seam
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tpurpc-xds")
        self._thread.start()

    def _run(self) -> None:
        from tpurpc.rpc.channel import Channel

        uri = _server_uri(self._cfg)
        node = self._cfg.get("node", {})
        backoff = 0.2
        while not self._stop.is_set():
            try:
                with Channel(uri, connect_timeout=10.0) as bch:
                    self._bch = bch  # stop() closes it to unblock the recv
                    sub = json.dumps({"node": node,
                                      "resource": self._service}).encode()

                    def reqs():
                        yield sub
                        while not self._stop.wait(0.2):
                            pass

                    for msg in bch.stream_stream(METHOD)(reqs(),
                                                         timeout=None):
                        if self._stop.is_set():
                            return
                        try:
                            upd = json.loads(bytes(msg).decode())
                            # normalization may raise too (bad host:port
                            # strings): the whole parse is one
                            # keep-the-last-good unit, NOT a stream
                            # teardown — a control plane resending one
                            # malformed assignment must not put the
                            # watcher in a reconnect loop
                            addrs = _normalize(list(upd["endpoints"]))
                        except (ValueError, KeyError):
                            continue  # malformed push: keep the last good
                        if addrs and addrs != self._last_applied:
                            self._channel.update_addresses(addrs)
                            self._last_applied = addrs
                            self.applied_versions.append(
                                int(upd.get("version", -1)))
                        backoff = 0.2
            except Exception:
                if self._stop.is_set():
                    return
            if self._stop.wait(backoff):
                return
            backoff = min(backoff * 2, 5.0)

    def stop(self) -> None:
        self._stop.set()
        bch = getattr(self, "_bch", None)
        if bch is not None:
            try:
                bch.close()
            except Exception:
                pass
        self._thread.join(timeout=5)


def xds_channel(target: str, bootstrap: Optional[dict] = None, **channel_kw):
    """``xds:///service`` → a channel whose membership tracks the control
    plane. Returns ``(channel, watcher)``; stop the watcher before (or
    with) closing the channel."""
    if not target.startswith("xds:"):
        raise ValueError(f"not an xds target: {target!r}")
    from tpurpc.rpc.channel import Channel

    service = target[4:].lstrip("/")
    cfg = bootstrap or load_bootstrap()
    endpoints = _fetch_snapshot(_server_uri(cfg), service,
                                cfg.get("node", {}))
    if not endpoints:
        raise ValueError(f"xds assignment for {service!r} is empty")
    addrs = _normalize(endpoints)  # same keys update_addresses will produce
    ch = Channel("ipv4:" + ",".join(f"{h}:{p}" for h, p in addrs),
                 lb_policy=channel_kw.pop("lb_policy", "round_robin"),
                 **channel_kw)
    watcher = XdsWatcher(ch, service, bootstrap=cfg)
    return ch, watcher
